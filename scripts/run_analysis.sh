#!/usr/bin/env bash
# Invariant-analyzer sweep (sparkrdma_tpu/analysis/ — see docs/ANALYSIS.md).
#
#   scripts/run_analysis.sh               static passes + analyzer tests
#   scripts/run_analysis.sh --sanitize    ... + ASan/UBSan native harness
#                                         (builds instrumented .so's)
#   scripts/run_analysis.sh --lockgraph   ... + the WHOLE tier-1 suite under
#                                         the lock-order shim (exit 3 on any
#                                         lock-order cycle)
#   scripts/run_analysis.sh --all         everything above
#
# The fast subset (static passes + tests/test_analysis.py) is what tier-1
# already runs; this script exists for the gated extras and for running
# the sweep standalone in CI.
set -euo pipefail
cd "$(dirname "$0")/.."

SANITIZE=0; LOCKGRAPH=0
for arg in "$@"; do
  case "$arg" in
    --sanitize) SANITIZE=1 ;;
    --lockgraph) LOCKGRAPH=1 ;;
    --all) SANITIZE=1; LOCKGRAPH=1 ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done
[[ "${RUN_SANITIZERS:-0}" == "1" ]] && SANITIZE=1

echo "== static passes: wire / concurrency / drift =="
JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis

echo "== analyzer self-tests (fixtures + lockgraph e2e) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q \
  -p no:cacheprovider

if [[ "$SANITIZE" == "1" ]]; then
  echo "== native sanitizer harness (ASan, then UBSan) =="
  make -C csrc asan ubsan
  ASAN_OPTIONS=detect_leaks=0 \
    LD_PRELOAD="$(${CXX:-g++} -print-file-name=libasan.so)" \
    JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis.native_harness \
    sparkrdma_tpu/runtime/libtpushuffle_asan.so
  JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis.native_harness \
    sparkrdma_tpu/runtime/libtpushuffle_ubsan.so
fi

if [[ "$LOCKGRAPH" == "1" ]]; then
  echo "== tier-1 under the lockgraph shim =="
  ANALYSIS_LOCKGRAPH=1 JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
fi

echo "analysis sweep: done"
