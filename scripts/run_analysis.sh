#!/usr/bin/env bash
# Invariant-analyzer sweep (sparkrdma_tpu/analysis/ — see docs/ANALYSIS.md).
#
#   scripts/run_analysis.sh               static passes + analyzer tests
#   scripts/run_analysis.sh --model-check ... with the distributed-invariant
#                                         model checker (schedule enumeration;
#                                         violating traces dump under
#                                         .analysis_traces/ for --replay).
#                                         Budget knobs: MODELCHECK_SCHEDULES
#                                         (DFS cap per scenario, default 256),
#                                         MODELCHECK_DEPTH, MODELCHECK_WALKS —
#                                         the defaults fit the tier-1 time box;
#                                         raise MODELCHECK_SCHEDULES for an
#                                         exhaustive overnight sweep.
#   scripts/run_analysis.sh --replay <trace.json>
#                                         re-run one dumped violating schedule
#                                         byte-identically (exit 1 = violation
#                                         reproduced, 2 = trace diverged)
#   scripts/run_analysis.sh --sanitize    ... + ASan/UBSan native harness
#                                         (builds instrumented .so's)
#   scripts/run_analysis.sh --lockgraph   ... + the WHOLE tier-1 suite under
#                                         the lock-order shim (exit 3 on any
#                                         lock-order cycle)
#   scripts/run_analysis.sh --all         everything above (incl. model check)
#
# The fast subset (static passes + tests/test_analysis.py, which runs the
# model-check catalog too) is what tier-1 already runs; this script exists
# for the gated extras and for running the sweep standalone in CI.
set -euo pipefail
cd "$(dirname "$0")/.."

SANITIZE=0; LOCKGRAPH=0; MODELCHECK=0
args=("$@")
for i in "${!args[@]}"; do
  case "${args[$i]}" in
    --sanitize) SANITIZE=1 ;;
    --lockgraph) LOCKGRAPH=1 ;;
    --model-check) MODELCHECK=1 ;;
    --replay)
      trace="${args[$((i+1))]:?--replay needs a trace file}"
      exec env JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis \
        --replay "$trace" ;;
    --all) SANITIZE=1; LOCKGRAPH=1; MODELCHECK=1 ;;
    *) echo "unknown arg: ${args[$i]}" >&2; exit 2 ;;
  esac
done
[[ "${RUN_SANITIZERS:-0}" == "1" ]] && SANITIZE=1

if [[ "$MODELCHECK" == "1" ]]; then
  echo "== static passes + model checker =="
  JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis --model-check
else
  echo "== static passes: wire / concurrency / drift / resources =="
  JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis
fi

echo "== analyzer self-tests (fixtures + lockgraph e2e) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q \
  -p no:cacheprovider

if [[ "$SANITIZE" == "1" ]]; then
  echo "== native sanitizer harness (ASan, then UBSan) =="
  make -C csrc asan ubsan
  ASAN_OPTIONS=detect_leaks=0 \
    LD_PRELOAD="$(${CXX:-g++} -print-file-name=libasan.so)" \
    JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis.native_harness \
    sparkrdma_tpu/runtime/libtpushuffle_asan.so
  JAX_PLATFORMS=cpu python -m sparkrdma_tpu.analysis.native_harness \
    sparkrdma_tpu/runtime/libtpushuffle_ubsan.so
fi

if [[ "$LOCKGRAPH" == "1" ]]; then
  echo "== tier-1 under the lockgraph shim =="
  ANALYSIS_LOCKGRAPH=1 JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
fi

echo "analysis sweep: done"
