#!/usr/bin/env bash
# Client-fetch sweep: the native fetch engine's test matrix
# (tests/test_native_fetch.py — native-vs-Python byte identity across
# dataplane combos, read_to_device parity, doorbell batching, lease
# free-race hardening, the client CPU-per-GB acceptance gate) across a
# set of extra seeds, then the client microbench at full size with its
# acceptance gates: >= 2x lower CLIENT CPU per GB than the pure-Python
# receive path, per-request digests byte-identical with CRC trailers on
# and off, wire-to-device latency no worse than the staged upload. A
# red seed replays exactly:
#
#     NATIVE_FETCH_SEED=<seed> python -m pytest tests/test_native_fetch.py
#
# Usage: scripts/run_client_bench.sh [seed ...]
#   NATIVE_FETCH_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${NATIVE_FETCH_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== client fetch sweep: seed ${seed} ==="
  if ! NATIVE_FETCH_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_native_fetch.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    NATIVE_FETCH_SEED=${seed} python -m pytest tests/test_native_fetch.py"
    failed+=("${seed}")
  fi
done

echo "=== client microbench (CPU-per-GB acceptance) ==="
if ! JAX_PLATFORMS=cpu python - <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.client_bench import run_client_microbench

ok = True
for checksum in (False, True):
    with tempfile.TemporaryDirectory(prefix="clientbench_") as td:
        res = run_client_microbench(td, total_mb=512, checksum=checksum)
    print(json.dumps(res))
    w2d = res["wire_to_device_ms"]
    db = res["doorbell"]
    ok = (ok and res["identical"]
          and res["cpu_speedup"] >= 2.0
          and 0 < db["writevs"] < db["frames"]
          and w2d["native"] <= 1.25 * w2d["python"])
sys.exit(0 if ok else 1)
EOF
then
  echo "!!! client microbench FAILED its acceptance gates"
  failed+=("microbench")
fi

if [ ${#failed[@]} -gt 0 ]; then
  echo "client fetch sweep: FAILURES: ${failed[*]}"
  exit 1
fi
echo "client fetch sweep: all green"
