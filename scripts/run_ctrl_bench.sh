#!/usr/bin/env bash
# Control-plane scale-out sweep (ROADMAP item 3): the partitioned
# metadata ownership battery — the shard_plane unit/endpoint tests
# (owner fence CAS, seal-then-replay handoff, standby streams, the
# kill-the-owner zero-re-execution acceptance), the model-checked
# handoff scenarios, then the ctrl_bench microbench across a set of
# seeds (repeat rounds; sleep-based op cost is noisy under load) with
# its acceptance gates: >= 1.5x publish throughput at 4 write owners vs
# the driver-serialized baseline AND byte-identical resulting driver
# state (table bytes, fence floors, merged directory, fenced-zombie
# parity) on EVERY round. ``publishes_per_s_sharded`` and
# ``registrations_per_s`` are the headline numbers; a divergent round
# exits non-zero immediately.
#
# Usage: scripts/run_ctrl_bench.sh [rounds]
#   CTRL_BENCH_ROUNDS=5     alternative way to set the repeat count
#   CTRL_BENCH_SHARDS=4     owner count for the scale-out mode
set -uo pipefail
cd "$(dirname "$0")/.."

ROUNDS=${1:-${CTRL_BENCH_ROUNDS:-5}}
SHARDS=${CTRL_BENCH_SHARDS:-4}
failed=()

echo "=== shard ownership battery (unit + endpoints + handoff) ==="
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_shard_ownership.py -q \
     -p no:cacheprovider -p no:randomly; then
  failed+=("test_shard_ownership")
fi
echo "=== kill-a-shard-owner chaos acceptance ==="
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
     -k shard_owner_kill -p no:cacheprovider -p no:randomly; then
  failed+=("shard_owner_kill")
fi
echo "=== model-checked handoff scenarios ==="
if ! JAX_PLATFORMS=cpu python - <<'EOF'
import sys
from sparkrdma_tpu.analysis import modelcheck
bad = 0
for scn in modelcheck.catalog():
    if scn.name not in ("handoff_vs_publish", "handoff_vs_driver_failover"):
        continue
    runs, stats = modelcheck.run_scenario(scn)
    viols = [r for r in runs if r.violation]
    print(f"{scn.name}: {len(runs)} schedules, {len(viols)} violations")
    bad += len(viols)
sys.exit(1 if bad else 0)
EOF
then
  failed+=("modelcheck-handoff")
fi

echo "=== control-plane scale-out microbench (${ROUNDS} rounds," \
     "${SHARDS} owners) ==="
if ! JAX_PLATFORMS=cpu python -m sparkrdma_tpu.shuffle.ctrl_bench \
     --shards "${SHARDS}" --seeds "${ROUNDS}"; then
  failed+=("ctrl_bench")
fi

if [ "${#failed[@]}" -gt 0 ]; then
  echo "ctrl-plane sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "ctrl-plane sweep: green — sharded write path byte-identical to" \
     "the driver-serialized baseline at >= 1.5x throughput"
