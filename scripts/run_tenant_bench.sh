#!/usr/bin/env bash
# Tenancy sweep: the multi-tenant service's test matrix
# (tests/test_tenancy.py — quota ledgers, DRR fairness, admission
# queue-or-reject, TTL/GC + orphan reap, cross-tenant-eviction
# regression, fair-share byte parity on both serve paths) across a set
# of extra seeds, then the isolation microbench with its acceptance
# gates: >= 1.5x lower victim-tenant p99 under fair share vs FIFO with
# an antagonist saturating the serve path, byte-identical to solo
# runs, zero cross-tenant cache evictions — plus the sustained-traffic
# driver's clean-shedding accounting. A red seed replays exactly:
#
#     TENANT_SEED=<seed> python -m pytest tests/test_tenancy.py
#
# Usage: scripts/run_tenant_bench.sh [seed ...]
#   TENANT_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${TENANT_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== tenancy sweep: seed ${seed} ==="
  if ! TENANT_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_tenancy.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    TENANT_SEED=${seed} python -m pytest tests/test_tenancy.py"
    failed+=("${seed}")
  fi
done

echo "=== tenant isolation microbench ==="
if ! JAX_PLATFORMS=cpu python - <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.tenant_bench import (
    run_isolation_microbench, run_sustained_bench)

with tempfile.TemporaryDirectory(prefix="tenantbench_") as td:
    res = run_isolation_microbench(td)
print(json.dumps(res))
ok = (res["identical"] and res["cross_tenant_evictions"] == 0
      and res["speedup"] >= 1.5)
with tempfile.TemporaryDirectory(prefix="tenantsust_") as td:
    sus = run_sustained_bench(td)
print(json.dumps(sus, default=str))
jobs = sus["jobs"]
ok = (ok and sus["identical"] and sus["cross_tenant_evictions"] == 0
      and jobs["completed"] > 0
      and jobs["completed"] + jobs["shed"] == jobs["submitted"])
sys.exit(0 if ok else 1)
EOF
then
  failed+=("microbench")
fi

if [ "${#failed[@]}" -gt 0 ]; then
  echo "tenancy sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "tenancy sweep: all seeds green, isolation gates met"
