"""Micro-benchmarks for the TeraSort local-sort bottleneck on hardware.

Times the two phases of sort_rows_by_key separately across row widths:
the (key, iota) sort and the row gather — plus narrow-payload multisort
scaling, so layout/strategy decisions are measured, not guessed.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def _sync(out):
    """Force real completion: on the remote (axon) backend
    block_until_ready can return before the step finishes, so fetch a few
    result bytes — the transfer cannot start until the value exists."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf[:1])


def timeit(fn, *args, reps=5):
    fn_j = jax.jit(fn)
    for _ in range(2):
        _sync(fn_j(*args))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(fn_j(*args))
        times.append(time.perf_counter() - t0)
    return min(times)


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def main():
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 10_700_000
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 2**32, n_rows, dtype=np.uint32))
    order_np = rng.permutation(n_rows).astype(np.int32)
    order = jnp.asarray(order_np)
    log(f"n={n_rows} on {jax.devices()[0].device_kind}")

    # dispatch+fetch round-trip floor (subtract from small timings)
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(keys[:1])
    log(f"sync RTT floor: {(time.perf_counter()-t0)/5*1e3:.1f} ms")

    dt = timeit(lambda k: jax.lax.sort(
        (k, jnp.arange(k.shape[0], dtype=jnp.int32)), num_keys=1), keys)
    log(f"sort(key,iota): {dt*1e3:.1f} ms ({dt/n_rows*1e9:.2f} ns/row)")

    for width in (8, 16, 25, 32):
        rows = jnp.asarray(
            rng.integers(0, 2**32, (n_rows, width), dtype=np.uint32))
        dt = timeit(lambda r, o: jnp.take(r, o, axis=0), rows, order)
        bw = rows.nbytes * 2 / dt / 1e9
        log(f"gather width={width:3d}: {dt*1e3:7.1f} ms "
            f"({dt/n_rows*1e9:6.2f} ns/row, {bw:5.1f} GB/s r+w)")
        del rows

    # multisort scaling in payload operand count (compile can explode at
    # high operand counts: bound each with an alarm)
    for width in (2, 4, 8):
        rows = jnp.asarray(
            rng.integers(0, 2**32, (n_rows, width), dtype=np.uint32))

        def ms(k, r):
            cols = tuple(r[:, j] for j in range(r.shape[1]))
            out = jax.lax.sort((k,) + cols, num_keys=1)
            return jnp.stack(out[1:], axis=1)

        t0 = time.perf_counter()
        try:
            dt = timeit(ms, keys, rows)
            log(f"multisort width={width}: {dt*1e3:.1f} ms "
                f"(compile+warm {time.perf_counter()-t0:.0f}s)")
        except Exception as e:  # noqa: BLE001
            log(f"multisort width={width}: failed {e}")
        del rows


if __name__ == "__main__":
    main()
