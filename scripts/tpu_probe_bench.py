"""Incremental hardware probe for the TeraSort step: times each stage
(device_put, compile, steps) separately per size/mode so a tunnel stall
or a pathological compile is attributable, unlike the all-or-nothing
bench watchdog. Usage:

    python scripts/tpu_probe_bench.py [size_mb] [mode] [reps]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def main():
    size_mb = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    mode = sys.argv[2] if len(sys.argv) > 2 else "gather"
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3

    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from sparkrdma_tpu.models.terasort import (
        TeraSortConfig, generate_rows, make_terasort_step)

    devs = jax.devices()
    log(f"devices={devs} ({time.perf_counter() - t0:.1f}s)")
    n = len(devs)
    mesh = Mesh(np.array(devs), ("shuffle",))
    rows_per_device = (size_mb << 20) // 100 // n
    cfg = TeraSortConfig(rows_per_device=rows_per_device, payload_words=24,
                         out_factor=1 if n == 1 else 2, sort_mode=mode)

    t0 = time.perf_counter()
    rows = generate_rows(cfg, n, seed=0)
    log(f"generated {rows.nbytes >> 20} MiB ({time.perf_counter() - t0:.1f}s)")

    t0 = time.perf_counter()
    rows_d = jax.device_put(rows, NamedSharding(mesh, P("shuffle")))
    jax.block_until_ready(rows_d)
    dt = time.perf_counter() - t0
    log(f"device_put done ({dt:.1f}s, {rows.nbytes / dt / 1e6:.0f} MB/s)")

    step = make_terasort_step(mesh, "shuffle", cfg)
    t0 = time.perf_counter()
    lowered = jax.jit(step).lower(rows_d) if not hasattr(step, "lower") \
        else step.lower(rows_d)
    log(f"lowered ({time.perf_counter() - t0:.1f}s)")
    t0 = time.perf_counter()
    compiled = lowered.compile()
    log(f"compiled mode={mode} ({time.perf_counter() - t0:.1f}s)")

    for i in range(2):
        t0 = time.perf_counter()
        out = compiled(rows_d)
        np.asarray(out[1])
        log(f"warmup {i}: {time.perf_counter() - t0:.2f}s")
    times = []
    for i in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(compiled(rows_d))
        times.append(time.perf_counter() - t0)
        log(f"step {i}: {times[-1]:.3f}s")
    best = min(times)
    gbps = rows.nbytes / best / 1e9 / n
    log(f"RESULT size_mb={size_mb} mode={mode} best={best:.3f}s "
        f"-> {gbps:.3f} GB/s/chip")


if __name__ == "__main__":
    main()
