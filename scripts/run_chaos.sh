#!/usr/bin/env bash
# Seeded chaos sweep: run the fault-injection scenario matrix
# (tests/test_chaos.py, `chaos` marker — including the `slow` wide
# matrix) across a set of injector seeds. Each scenario asserts
# byte-identical reduce output under its faults and embeds the seed in
# any failure message, so a red sweep replays exactly:
#
#     CHAOS_SEED=<seed> python -m pytest tests/test_chaos.py -m chaos
#
# Usage: scripts/run_chaos.sh [seed ...]
#   CHAOS_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${CHAOS_SEEDS:-"0 1 2 3 4 5 6 7"}}
failed=()
for seed in $SEEDS; do
  echo "=== chaos sweep: seed ${seed} ==="
  if ! CHAOS_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_chaos.py -q -m chaos \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    CHAOS_SEED=${seed} python -m pytest tests/test_chaos.py -m chaos"
    failed+=("${seed}")
  fi
done

if [ "${#failed[@]}" -gt 0 ]; then
  echo "chaos sweep: FAILED seeds: ${failed[*]}"
  exit 1
fi
echo "chaos sweep: all seeds green"
