#!/usr/bin/env bash
# Seeded chaos sweep: run the fault-injection scenario matrix
# (tests/test_chaos.py, `chaos` marker — including the `slow` wide
# matrix) across a set of injector seeds, on BOTH fetch dataplanes
# (coalesced vectored reads and the per-map fallback — the failure paths
# differ, so the matrix covers each), and with the STORAGE-fault matrix
# (CHAOS_DISK=1, the default: seeded ENOSPC/EIO/torn-write/slow-disk/
# corrupt-at-rest scenarios over the spill/merge/commit/serve path with
# at-rest checksums on). Every scenario asserts byte-identical reduce
# output under its faults — via refetch, spill retry, fallback dir, or
# map re-execution — and embeds the seed in any failure message, so a
# red sweep replays exactly:
#
#     CHAOS_SEED=<seed> CHAOS_COALESCE=<0|1> CHAOS_DISK=<0|1> \
#         python -m pytest tests/test_chaos.py -m chaos
#
# Usage: scripts/run_chaos.sh [seed ...]
#   CHAOS_SEEDS="0 1 2"   alternative way to pass the seed list
#   CHAOS_COALESCE_MODES="0 1"  dataplanes to sweep (default both)
#   CHAOS_WARM_MODES="1 0"      metadata planes to sweep (default both:
#                               epoch-validated warm caches and the cold
#                               pre-plane path — stale-cache scenarios
#                               only run warm)
#   CHAOS_SKEW_MODES="0 1"      reduce-planning modes to sweep (default
#                               both: static plans, and adaptive_plan=1
#                               so size-carrying publishes, driver
#                               histograms, and plan pushes see every
#                               injected fault; the mid-stage re-plan
#                               scenario forces adaptive regardless)
#   CHAOS_MERGE_MODES="0 1"     push-merge modes to sweep (default both:
#                               off, and push_merge=1 so background
#                               pushes, merge targets, and
#                               merged-segment-first reads — partial
#                               finalize mid-reduce included — run under
#                               the whole fault matrix, byte-identical;
#                               the dedicated merge scenarios force it
#                               on regardless)
#   CHAOS_ELASTIC_MODES="0 1"   elastic-membership modes to sweep
#                               (default both: off, and CHAOS_ELASTIC=1
#                               so the wide byte-identity matrices run
#                               with a mid-reduce JOIN + graceful DRAIN
#                               churning in the background — announce,
#                               membership bump, health-watch, and
#                               decommission cross every injected
#                               fault; the dedicated 4->8->4 and
#                               drainee-death scenarios run regardless)
#   CHAOS_PUSHPLAN_MODES="0 1"  planned-push modes to sweep (default
#                               both: off, and CHAOS_PUSHPLAN=1 so the
#                               byte-identity matrices run with
#                               sender-driven planned pushes racing the
#                               faulted reduce in the background —
#                               plan publish, push fences, staged-first
#                               resolution, and hole fallback cross
#                               every injected fault; the dedicated
#                               kill-the-planned-reducer scenario runs
#                               regardless)
#   CHAOS_TENANT_MODES="0 1"    tenancy modes to sweep (default both:
#                               off, and CHAOS_TENANT=1 so every
#                               shuffle registers under a real tenant
#                               id — TenantMapMsg pushes, serve-path
#                               DRR queues, ledger charging, a live
#                               TTL sweeper — under the whole fault
#                               matrix; the cross-tenant isolation
#                               scenarios assert blast radius
#                               regardless)
#   CHAOS_DRIVER_MODES="0 1"    driver-HA modes to sweep (default both:
#                               off, and CHAOS_DRIVER=1 so the wide
#                               byte-identity matrices run with a
#                               lease-armed primary, a warm standby
#                               shadowing its op log, and a primary
#                               CRASH at a seeded random point inside
#                               the reduce window — lease takeover,
#                               op-log replay, TakeoverMsg re-pointing,
#                               and the DriverClient retry envelope all
#                               cross every injected fault; the
#                               dedicated kill -9 acceptance scenario
#                               runs regardless)
#   CHAOS_NATIVE_FETCH_MODES="0 1"  client dataplane modes to sweep
#                               (default both: the pure-Python fetcher,
#                               and CHAOS_NATIVE_FETCH=1 so the matrix
#                               runs on the NATIVE dataplane — C++ block
#                               server serving, the C client engine
#                               fetching into pool leases — and every
#                               control-plane/disk/membership fault
#                               crosses the engine's fallback-to-Python
#                               envelope; degrades to Python where the
#                               .so isn't built)
#   CHAOS_COLD_MODES="0 1"      cold-tier modes to sweep (default both:
#                               off, and CHAOS_COLD=1 so the whole
#                               matrix runs with the disaggregated
#                               cold tier active — push_merge forced
#                               on, finalized segments tiering to a
#                               blob store in the background, so
#                               uploads, one-sided publishes, and
#                               tombstone reaps cross every injected
#                               fault — plus the dedicated
#                               full-fleet-loss-restore and
#                               store-outage-degrade scenarios)
#   CHAOS_SHARD_MODES="0 1"     partitioned-ownership modes to sweep
#                               (default both: off, and CHAOS_SHARD=1
#                               so the whole matrix runs with
#                               metadata_shards=2 + shard_ownership=1 —
#                               publishes land at per-shard write
#                               owners, batch-converge into the driver,
#                               and stream to per-shard standbys, so
#                               every injected fault crosses the
#                               sharded control-plane write path and
#                               its driver-direct fallback; the
#                               dedicated kill-a-shard-owner scenario
#                               runs regardless)
#   CHAOS_DISK=0          drop the storage-fault matrix from the sweep
#   CHAOS_LOCKGRAPH=1     run every scenario under the lock-order shim
#                         (sparkrdma_tpu/analysis/lockgraph.py): the
#                         sweep then doubles as race detection — any
#                         lock-order cycle observed across a module's
#                         scenarios fails that module
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${CHAOS_SEEDS:-"0 1 2 3 4 5 6 7"}}
MODES=${CHAOS_COALESCE_MODES:-"1 0"}
WARM_MODES=${CHAOS_WARM_MODES:-"1 0"}
SKEW_MODES=${CHAOS_SKEW_MODES:-"0 1"}
MERGE_MODES=${CHAOS_MERGE_MODES:-"0 1"}
PUSHPLAN_MODES=${CHAOS_PUSHPLAN_MODES:-"0 1"}
TENANT_MODES=${CHAOS_TENANT_MODES:-"0 1"}
ELASTIC_MODES=${CHAOS_ELASTIC_MODES:-"0 1"}
DRIVER_MODES=${CHAOS_DRIVER_MODES:-"0 1"}
NATIVE_FETCH_MODES=${CHAOS_NATIVE_FETCH_MODES:-"0 1"}
SHARD_MODES=${CHAOS_SHARD_MODES:-"0 1"}
COLD_MODES=${CHAOS_COLD_MODES:-"0 1"}
DISK=${CHAOS_DISK:-1}
failed=()
for cold in $COLD_MODES; do
for shard in $SHARD_MODES; do
for nfetch in $NATIVE_FETCH_MODES; do
for driver in $DRIVER_MODES; do
for elastic in $ELASTIC_MODES; do
for tenant in $TENANT_MODES; do
for pushplan in $PUSHPLAN_MODES; do
for merge in $MERGE_MODES; do
for skew in $SKEW_MODES; do
for warm in $WARM_MODES; do
for coalesce in $MODES; do
  for seed in $SEEDS; do
    echo "=== chaos sweep: seed ${seed} coalesce=${coalesce}" \
         "warm=${warm} skew=${skew} merge=${merge}" \
         "pushplan=${pushplan} tenant=${tenant} elastic=${elastic}" \
         "driver=${driver} nfetch=${nfetch} shard=${shard}" \
         "cold=${cold} disk=${DISK} ==="
    if ! CHAOS_SEED="${seed}" CHAOS_COALESCE="${coalesce}" \
         CHAOS_WARM="${warm}" CHAOS_SKEW="${skew}" \
         CHAOS_MERGE="${merge}" CHAOS_PUSHPLAN="${pushplan}" \
         CHAOS_TENANT="${tenant}" \
         CHAOS_ELASTIC="${elastic}" CHAOS_DRIVER="${driver}" \
         CHAOS_NATIVE_FETCH="${nfetch}" \
         CHAOS_SHARD="${shard}" \
         CHAOS_COLD="${cold}" \
         CHAOS_DISK="${DISK}" \
         JAX_PLATFORMS=cpu \
         python -m pytest tests/test_chaos.py -q -m chaos \
           -p no:cacheprovider -p no:randomly; then
      echo "!!! seed ${seed} coalesce=${coalesce} warm=${warm}" \
           "skew=${skew} merge=${merge} pushplan=${pushplan}" \
           "tenant=${tenant} elastic=${elastic} driver=${driver}" \
           "nfetch=${nfetch} shard=${shard} cold=${cold} FAILED" \
           "— replay with:"
      echo "    CHAOS_SEED=${seed} CHAOS_COALESCE=${coalesce}" \
           "CHAOS_WARM=${warm} CHAOS_SKEW=${skew}" \
         "CHAOS_MERGE=${merge} CHAOS_PUSHPLAN=${pushplan}" \
           "CHAOS_TENANT=${tenant}" \
           "CHAOS_ELASTIC=${elastic} CHAOS_DRIVER=${driver}" \
           "CHAOS_NATIVE_FETCH=${nfetch}" \
           "CHAOS_SHARD=${shard}" \
           "CHAOS_COLD=${cold}" \
           "CHAOS_DISK=${DISK}" \
           "python -m pytest tests/test_chaos.py -m chaos"
      failed+=("${seed}/c${coalesce}w${warm}s${skew}m${merge}p${pushplan}t${tenant}e${elastic}d${driver}n${nfetch}h${shard}b${cold}")
    fi
  done
done
done
done
done
done
done
done
done
done
done
done

if [ "${#failed[@]}" -gt 0 ]; then
  echo "chaos sweep: FAILED (seed/dataplane): ${failed[*]}"
  exit 1
fi
echo "chaos sweep: all seeds green on both dataplanes, both metadata" \
     "planes, both reduce-planning modes, both push-merge modes," \
     "both planned-push modes, both tenancy modes, both" \
     "elastic-membership modes, both driver-HA modes, both client" \
     "fetch engines, both metadata-ownership modes, both cold-tier" \
     "modes (disk=${DISK})"
