#!/usr/bin/env bash
# Topology sweep: the hierarchical-exchange test matrix
# (tests/test_topology.py — two-level cost model, hierarchical vs
# flat-device vs host byte parity across uniform/zipfian/affine inputs,
# empty slices, per-slice degrade, link-cost layout) across a set of
# extra seeds, then the topo microbench with its acceptance gates:
# >= 1.5x vs the flat plan on a 2-slice virtual cluster under a 10:1
# ICI:DCN cost shim, byte-identical per-partition output, and STRICTLY
# fewer cross-slice bytes. A red seed replays exactly:
#
#     TOPO_SEED=<seed> python -m pytest tests/test_topology.py
#
# Usage: scripts/run_topo_bench.sh [seed ...]
#   TOPO_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${TOPO_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== topology sweep: seed ${seed} ==="
  if ! TOPO_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_topology.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    TOPO_SEED=${seed} python -m pytest tests/test_topology.py"
    failed+=("${seed}")
  fi
done

echo "=== hierarchical-exchange microbench ==="
if ! JAX_PLATFORMS=cpu \
     XLA_FLAGS="--xla_force_host_platform_device_count=8" \
     python - <<'EOF'
import json, sys
from sparkrdma_tpu.shuffle.topo_bench import run_topo_microbench

res = run_topo_microbench()
print(json.dumps(res))
cross = res["cross_slice_bytes"]
sys.exit(0 if res["identical"] and res["speedup"] >= 1.5
         and cross["hier"] < cross["flat"] else 1)
EOF
then
  failed+=("microbench")
fi

if [ "${#failed[@]}" -gt 0 ]; then
  echo "topology sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "topology sweep: all seeds green, microbench gates met"
