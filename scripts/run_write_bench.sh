#!/usr/bin/env bash
# Write-dataplane sweep: the streaming-writer test matrix
# (tests/test_writer_streaming.py — randomized byte-parity vs the
# monolithic baseline, spill boundaries, abort cleanliness, native/numpy
# scatter lockstep) across a set of extra parity seeds, then the
# shuffle-write microbench with its acceptance gates (>=2 spills, >=2x
# vs monolithic, byte-identical files, bounded peak memory). A red seed
# replays exactly:
#
#     WRITE_SEED=<seed> python -m pytest tests/test_writer_streaming.py
#
# Usage: scripts/run_write_bench.sh [seed ...]
#   WRITE_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${WRITE_SEEDS:-"11 23 42 1337"}}
failed=()
for seed in $SEEDS; do
  echo "=== write sweep: seed ${seed} ==="
  if ! WRITE_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_writer_streaming.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    WRITE_SEED=${seed} python -m pytest tests/test_writer_streaming.py"
    failed+=("${seed}")
  fi
done

echo "=== write microbench ==="
if ! JAX_PLATFORMS=cpu python - <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.write_bench import run_write_microbench

with tempfile.TemporaryDirectory(prefix="writebench_") as td:
    res = run_write_microbench(td, reps=2, map_compute_s=0.004)
print(json.dumps({k: v for k, v in res.items() if k != "write_metrics"}))
ok = (res["identical"] and res["spills"] >= 2 and res["speedup"] >= 2.0
      and res["peak_buffered_bytes"]
      <= res["spill_threshold"] + res["batch_bytes"])
sys.exit(0 if ok else 1)
EOF
then
  failed+=("microbench")
fi

if [ "${#failed[@]}" -gt 0 ]; then
  echo "write sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "write sweep: all seeds green, microbench gates met"
