#!/usr/bin/env bash
# Elastic-membership sweep: the membership test matrix
# (tests/test_membership.py — membership plane state machine, admission
# fleet scaling, autoscaler policy, mid-job join + health watch,
# graceful drain with zero re-executions, drainee-death fallback,
# mixed-version degrade) across a set of seeds, then the drain-vs-kill
# microbench with its acceptance gates: byte-identical both arms,
# ZERO re-executions on the planned drain, a real re-execution bill on
# the unplanned kill of the same slot. A red seed replays exactly:
#
#     ELASTIC_SEED=<seed> python -m pytest tests/test_membership.py
#
# Usage: scripts/run_elastic_bench.sh [seed ...]
#   ELASTIC_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${ELASTIC_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== elastic sweep: seed ${seed} ==="
  if ! ELASTIC_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_membership.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    ELASTIC_SEED=${seed} python -m pytest tests/test_membership.py"
    failed+=("${seed}")
  fi
done

echo "=== drain-vs-kill microbench ==="
for seed in $SEEDS; do
  if ! JAX_PLATFORMS=cpu python - "$seed" <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.elastic_bench import run_elastic_microbench

seed = int(sys.argv[1])
with tempfile.TemporaryDirectory(prefix="elasticbench_") as td:
    res = run_elastic_microbench(td, seed=seed)
print(json.dumps(res))
ok = (res["identical"] and res["drain_status"] == "drained"
      and res["reexec_drain"] == 0
      and res["reexec_kill"] == res["victim_owned_maps"] > 0)
sys.exit(0 if ok else 1)
EOF
  then
    failed+=("microbench-${seed}")
  fi
done

if [ "${#failed[@]}" -gt 0 ]; then
  echo "elastic sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "elastic sweep: all seeds green, drain-vs-kill gates met" \
     "(re-executions 0 vs N, byte-identical)"
