#!/bin/bash
# Watch for TPU tunnel recovery; on the first successful probe, run the
# full bench and save the record. The axon tunnel wedges after a device
# OOM (every jax.devices() call then hangs forever) and recovers on its
# own schedule — this loop turns "try again later" into evidence.
# Usage: scripts/bench_recovery_watch.sh [out_json] [max_hours]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-BENCH_SELF_r03.json}"
MAX_HOURS="${2:-9}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if timeout 70 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp, numpy as np
assert jax.devices()[0].platform == "tpu"
np.asarray(jax.jit(lambda x: x + 1)(jnp.zeros(8)))
EOF
  then
    echo "[$(date +%H:%M:%S)] tunnel live; running bench" >&2
    # the big multisort budget funds its ONE cold compile; once cached
    # (persistent XLA cache) later runs replay it in seconds
    BENCH_TIMEOUT_S="${BENCH_TIMEOUT_S:-700}" \
    BENCH_TIMEOUT_MULTISORT_S="${BENCH_TIMEOUT_MULTISORT_S:-2400}" \
      python bench.py > "$OUT.tmp" 2>/dev/null
    if [ -s "$OUT.tmp" ] && grep -qE '"platform": "tpu"[,}]' "$OUT.tmp"; then
      mv "$OUT.tmp" "$OUT"
      echo "[$(date +%H:%M:%S)] hardware bench recorded in $OUT" >&2
      exit 0
    fi
    echo "[$(date +%H:%M:%S)] bench ran but no tpu record; retrying later" >&2
    rm -f "$OUT.tmp"
  else
    echo "[$(date +%H:%M:%S)] tunnel still wedged" >&2
  fi
  sleep 480
done
echo "gave up after ${MAX_HOURS}h" >&2
exit 1
