#!/usr/bin/env bash
# Device-dataplane sweep: the fused-exchange test matrix
# (tests/test_device_plane.py — fused-step vs host-dataplane byte
# parity across every exchange transport, cost-model selection, the
# overflow -> host degrade, quota bucketing parity, overlap traces)
# across a set of extra seeds, then the fused-exchange microbench with
# its acceptance gates: >= 1.5x vs the host-staged path (same-process
# A/B, delay shim standing in for wire RTT) and byte-identical output.
# A red seed replays exactly:
#
#     DEVICE_SEED=<seed> python -m pytest tests/test_device_plane.py
#
# Usage: scripts/run_device_bench.sh [seed ...]
#   DEVICE_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${DEVICE_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== device-plane sweep: seed ${seed} ==="
  if ! DEVICE_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_device_plane.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    DEVICE_SEED=${seed} python -m pytest tests/test_device_plane.py"
    failed+=("${seed}")
  fi
done

echo "=== fused-exchange microbench ==="
if ! JAX_PLATFORMS=cpu \
     XLA_FLAGS="--xla_force_host_platform_device_count=8" \
     python - <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.device_bench import run_device_microbench

with tempfile.TemporaryDirectory(prefix="devbench_") as td:
    res = run_device_microbench(td)
print(json.dumps(res))
sys.exit(0 if res["identical"] and res["speedup"] >= 1.5 else 1)
EOF
then
  failed+=("microbench")
fi

if [ "${#failed[@]}" -gt 0 ]; then
  echo "device-plane sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "device-plane sweep: all seeds green, microbench gates met"
