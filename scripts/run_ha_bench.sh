#!/usr/bin/env bash
# Driver-HA sweep: the HA test battery (tests/test_ha.py — lease CAS on
# both backends, single-winner races, op-log compaction and replay
# idempotency over the driver-bound wire frames, DriverClient failover
# re-pointing, the in-process lease failover with live executors, and
# the zombie-primary fence) including the slow end-to-end scenarios,
# then the failover microbench across a set of seeds with its
# acceptance gates: byte-identical post-failover reduce, ZERO map
# re-executions, and a promoted incarnation. ``failover_downtime_ms``
# (crash to first successful publish against the promoted standby) and
# ``replay_ops`` are the numbers one crash costs. A red seed replays
# exactly:
#
#     python -m pytest tests/test_ha.py
#
# Usage: scripts/run_ha_bench.sh [seed ...]
#   HA_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${HA_SEEDS:-"0 7 42"}}
failed=()
echo "=== HA test battery (slow scenarios included) ==="
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_ha.py -q -m '' \
     -p no:cacheprovider -p no:randomly; then
  failed+=("test_ha")
fi
echo "=== chaos kill -9 acceptance ==="
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
     -k sigkill -p no:cacheprovider -p no:randomly; then
  failed+=("sigkill")
fi

echo "=== failover microbench ==="
for seed in $SEEDS; do
  if ! JAX_PLATFORMS=cpu python - "$seed" <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.ha_bench import run_ha_microbench

seed = int(sys.argv[1])
with tempfile.TemporaryDirectory(prefix="habench_") as td:
    res = run_ha_microbench(td, seed=seed)
print(json.dumps(res))
ok = (res["identical"] and res["reexec"] == 0
      and res["incarnation"] >= 1
      and res["failover_downtime_ms"] > 0)
sys.exit(0 if ok else 1)
EOF
  then
    failed+=("microbench-${seed}")
  fi
done

if [ "${#failed[@]}" -gt 0 ]; then
  echo "HA sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "HA sweep: all seeds green, failover gates met (byte-identical," \
     "zero re-executions, promoted incarnation)"
