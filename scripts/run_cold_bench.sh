#!/usr/bin/env bash
# Cold-tier sweep: the cold-tier test battery (tests/test_cold_tier.py —
# the BlobStore round trip and CRC verify, the TieringService upload
# discipline and ledger repay, the reducer's tiered-LAST resolve ladder,
# recovery re-pointing cold coverage, drain-to-cold, tombstone reaping,
# and the blob-fault matrix), the full-fleet-loss chaos scenarios, then
# the cold-restore microbench across a set of seeds with its acceptance
# gates: BOTH phases byte-identical, the cold phase's post-restart map
# re-executions exactly ZERO, the baseline's exactly NUM_MAPS, and
# ``cold_restore_speedup`` (re-execution baseline makespan over cold
# restore makespan on the fresh fleet) >= 1.5x. A red seed replays
# exactly:
#
#     python -m pytest tests/test_cold_tier.py
#
# Usage: scripts/run_cold_bench.sh [seed ...]
#   COLD_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${COLD_SEEDS:-"0 7 42"}}
failed=()
echo "=== cold-tier test battery ==="
if ! JAX_PLATFORMS=cpu python -m pytest tests/test_cold_tier.py -q -m '' \
     -p no:cacheprovider -p no:randomly; then
  failed+=("test_cold_tier")
fi
echo "=== full-fleet-loss chaos acceptance ==="
if ! JAX_PLATFORMS=cpu CHAOS_COLD=1 python -m pytest tests/test_chaos.py \
     -q -k cold -p no:cacheprovider -p no:randomly; then
  failed+=("chaos-cold")
fi

echo "=== cold-restore microbench ==="
for seed in $SEEDS; do
  if ! JAX_PLATFORMS=cpu python - "$seed" <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.cold_bench import run_cold_microbench
from sparkrdma_tpu.utils.benchgate import gated_best_of

seed = int(sys.argv[1])
with tempfile.TemporaryDirectory(prefix="coldbench_") as td:
    res = gated_best_of(lambda: run_cold_microbench(td, seed=seed))
print(json.dumps(res))
ok = (res["identical"] and res["reexec"]["cold"] == 0
      and res["reexec"]["baseline"] == res["maps"]
      and res["speedup"] >= 1.5)
sys.exit(0 if ok else 1)
EOF
  then
    failed+=("microbench-${seed}")
  fi
done

if [ "${#failed[@]}" -gt 0 ]; then
  echo "cold sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "cold sweep: all seeds green, restore gates met (byte-identical," \
     "zero re-executions on restore, full re-execution in the baseline)"
