#!/usr/bin/env bash
# Push-merge sweep: the push-merge dataplane's test matrix
# (tests/test_push_merge.py — target assignment, ledger fencing,
# directory round-trips, merged-vs-scattered byte parity, ENOSPC
# overflow, corrupt-segment degrade) across a set of extra seeds, then
# the merged-read microbench with its acceptance gates: >= 2x
# per-partition fetch vs the scattered per-map fan-in under the
# seek-cost shim, requests_per_reduce ~ 1 per partition, byte-identical
# output. A red seed replays exactly:
#
#     MERGE_SEED=<seed> python -m pytest tests/test_push_merge.py
#
# Usage: scripts/run_merge_bench.sh [seed ...]
#   MERGE_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${MERGE_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== merge sweep: seed ${seed} ==="
  if ! MERGE_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_push_merge.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    MERGE_SEED=${seed} python -m pytest tests/test_push_merge.py"
    failed+=("${seed}")
  fi
done

echo "=== merged-read microbench ==="
if ! JAX_PLATFORMS=cpu python - <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.merge_bench import run_merge_microbench

with tempfile.TemporaryDirectory(prefix="mergebench_") as td:
    res = run_merge_microbench(td)
print(json.dumps(res))
ok = (res["identical"] and res["coverage_complete"]
      and res["speedup"] >= 2.0
      and res["merged_reads"] == res["partitions"]
      and res["requests"]["merged"] <= res["partitions"] + 2)
sys.exit(0 if ok else 1)
EOF
then
  failed+=("microbench")
fi

if [ "${#failed[@]}" -gt 0 ]; then
  echo "merge sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "merge sweep: all seeds green, microbench gates met"
