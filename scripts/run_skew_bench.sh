#!/usr/bin/env bash
# Skew-plan sweep: the adaptive reduce planner's test matrix
# (tests/test_planner.py — plan determinism, coalesce/split boundaries,
# byte-parity vs the static plan on every dataplane combo, mid-stage
# re-plan) across a set of extra seeds, then the skew microbench with
# its acceptance gates on BOTH generators (zipfian terasort and the
# hot-key join): >=1.5x reduce-stage speedup vs the static plan,
# byte-identical output, identity plan on uniform input. A red seed
# replays exactly:
#
#     SKEW_SEED=<seed> python -m pytest tests/test_planner.py
#
# Usage: scripts/run_skew_bench.sh [seed ...]
#   SKEW_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${SKEW_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== skew sweep: seed ${seed} ==="
  if ! SKEW_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_planner.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    SKEW_SEED=${seed} python -m pytest tests/test_planner.py"
    failed+=("${seed}")
  fi
done

echo "=== skew microbench ==="
if ! JAX_PLATFORMS=cpu python - <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.plan_bench import run_skew_microbench

ok = True
for workload in ("terasort", "join"):
    with tempfile.TemporaryDirectory(prefix="skewbench_") as td:
        res = run_skew_microbench(td, workload=workload)
    print(workload, json.dumps(res))
    ok = ok and res["identical"] and res["skew_speedup"] >= 1.5
with tempfile.TemporaryDirectory(prefix="skewuni_") as td:
    uni = run_skew_microbench(td, uniform=True)
print("uniform", json.dumps(uni))
ok = ok and uni["identical"] and uni["is_identity"]
sys.exit(0 if ok else 1)
EOF
then
  failed+=("microbench")
fi

if [ "${#failed[@]}" -gt 0 ]; then
  echo "skew sweep: FAILED: ${failed[*]}"
  exit 1
fi
echo "skew sweep: all seeds green, microbench gates met (both workloads)"
