#!/usr/bin/env bash
# Serve-path sweep: the zero-copy serve path's test matrix
# (tests/test_serve_path.py — native-vs-Python byte identity on both
# coalesce dataplanes, CRC-reuse parity, LRU remap under budget,
# unregister-during-serve safety, the CPU-per-GB acceptance gate)
# across a set of extra seeds, then the serve microbench with its
# acceptance gates: >= 2x lower serve-side CPU per GB than the memcpy
# path at equal-or-better throughput, byte-identical responses with CRC
# on and off. A red seed replays exactly:
#
#     SERVE_SEED=<seed> python -m pytest tests/test_serve_path.py
#
# Usage: scripts/run_serve_bench.sh [seed ...]
#   SERVE_SEEDS="0 1 2"   alternative way to pass the seed list
set -uo pipefail
cd "$(dirname "$0")/.."

SEEDS=${*:-${SERVE_SEEDS:-"0 7 42"}}
failed=()
for seed in $SEEDS; do
  echo "=== serve sweep: seed ${seed} ==="
  if ! SERVE_SEED="${seed}" JAX_PLATFORMS=cpu \
       python -m pytest tests/test_serve_path.py -q \
         -p no:cacheprovider -p no:randomly; then
    echo "!!! seed ${seed} FAILED — replay with:"
    echo "    SERVE_SEED=${seed} python -m pytest tests/test_serve_path.py"
    failed+=("${seed}")
  fi
done

echo "=== serve microbench (CPU-per-GB acceptance) ==="
if ! JAX_PLATFORMS=cpu python - <<'EOF'
import json, sys, tempfile
from sparkrdma_tpu.shuffle.serve_bench import run_serve_microbench

ok = True
for checksum in (False, True):
    with tempfile.TemporaryDirectory(prefix="servebench_") as td:
        res = run_serve_microbench(td, total_mb=512, checksum=checksum)
    print(json.dumps(res))
    thr = res["throughput_gb_s"]
    ok = (ok and res["identical"] and res["trailer_ok"]
          and res["cpu_speedup"] >= 2.0
          and thr["zero_copy"] >= 0.95 * thr["memcpy"])
sys.exit(0 if ok else 1)
EOF
then
  echo "!!! serve microbench FAILED its acceptance gates"
  failed+=("microbench")
fi

if [ ${#failed[@]} -gt 0 ]; then
  echo "serve sweep: FAILURES: ${failed[*]}"
  exit 1
fi
echo "serve sweep: all green"
