// Native block server: the executor's data-serving path in C++.
//
// In the reference the serving executor's CPU is NOT in the data path — the
// NIC serves registered memory directly (one-sided READ,
// scala/RdmaShuffleFetcherIterator.scala:171-180 against mmap'd files
// registered in java/RdmaMappedFile.java). On the DCN fallback path this
// framework serves blocks over TCP; this server removes Python from that
// path AND keeps the per-request CPU constant-time in the bytes served
// (the Tiara property): connections are sharded round-robin across N epoll
// worker threads (the reference round-robins channels across its cpuList,
// java/RdmaNode.java:222-279 + java/RdmaThread.java:46-48), and the serve
// fast path never copies payload bytes — a response is framed as a small
// owned header plus iovec windows straight into the registered mapping,
// flushed with sendmsg() (writev with MSG_NOSIGNAL). The out-buffer copy
// survives only as the CRC-trailer fallback for ranges no precomputed CRC
// attests.
//
// Registered regions are a LEASE-ACCOUNTED POOL, not an eager mmap set
// (the NP-RDMA registration-on-demand argument): bs_register_file records
// (token -> fd, size), RETAINING the validation open's fd so the token
// stays bound to the registered inode (a speculative re-commit renames
// over the same path before unregistering the old token); the mapping
// happens on first serve, LRU-unmaps under bs_set_region_budget pressure,
// and remaps on demand from the retained fd (counted — the Python control
// plane traces these as serve.remap). Every in-flight serve holds a refcount PIN on its
// regions, so bs_unregister_file never unmaps under a live gather: the
// token disappears immediately (new requests answer kStatusUnknown), the
// munmap defers to the last unpin.
//
// Wire protocol: byte-compatible with sparkrdma_tpu.parallel.rpc_msg /
// messages — frames of [total:4][type:4][payload], request type 9
// (FetchBlocksReq: req_id q, shuffle_id i, count I, blocks (I,Q,I)*),
// response type 10 (FetchBlocksResp: req_id q, status i, flags i, data).
// Requests are VECTORED: the block list may span any mix of registered
// tokens (different maps' spill files), gathered in request order into one
// response. With bs_set_checksum(1) responses carry the same per-block
// CRC32 trailer as the Python server (FLAG_CRC32=4, one little-endian u32
// per requested block appended after the data) so a client can isolate a
// corrupt sub-range to one block — and therefore one map — instead of
// refetching the whole vectored response; otherwise flags=0. Trailer CRCs
// come from the per-file table bs_set_file_crcs installs (the at-rest
// sidecar / merge-ledger CRCs, combined with the zlib crc32_combine
// matrix math when a request spans several attested ranges) whenever the
// requested range aligns with attested ranges end-to-end; only unaligned
// ranges pay the copy-and-recompute fallback.
//
// Exposed as a C ABI for ctypes.

#include <atomic>
#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <sched.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

constexpr uint32_t kReqType = 9;
constexpr uint32_t kRespType = 10;
constexpr int32_t kStatusOk = 0;
constexpr int32_t kStatusUnknown = 1;
constexpr int32_t kStatusBadRange = 3;
// Transient serve failure (messages.STATUS_ERROR): the registered file
// could not be (re)mapped at serve time — the client's retry envelope
// owns it, exactly like a Python-path serve-time disk error.
constexpr int32_t kStatusError = 4;
// Request frames on this port are tiny ([16 fixed + 16/block]); anything
// larger than 1 MiB (~65k blocks) is a protocol violation, and capping the
// inbound frame well below kInHighWater guarantees a parked connection can
// always finish buffering the frame it is mid-way through.
constexpr size_t kMaxReqFrame = 1u << 20;
// Hard cap on one response's payload: far above the client's grouped-fetch
// ceiling (shuffle_read_block_size), far below uint32 frame-length wrap and
// the client Reassembler's 1 GiB max_frame. Oversized requests get
// kStatusBadRange instead of a frame the client can't parse (or, past
// 4 GiB, a wrapped out_total that would heap-overflow the out buffer).
constexpr uint64_t kMaxRespPayload = 256ull << 20;
// Backpressure high-water marks: while the unwritten response backlog (or
// unparsed input) exceeds these, the connection stops parsing AND stops
// recv()ing (EPOLLIN interest is dropped), bounding per-connection memory
// under pipelined clients instead of buffering toward kMaxFrame. Zero-copy
// region windows count at their logical size: they hold region pins, and
// fairness across connections is byte-denominated either way.
constexpr size_t kOutHighWater = 256u << 20;
constexpr size_t kInHighWater = 4u << 20;
// iovec batch per sendmsg() flush: plenty for a coalesced response's
// header + data windows + trailer, comfortably under IOV_MAX.
constexpr int kMaxIov = 64;
// Fair-share mode: parsed requests a connection may hold in its worker's
// tenant queues before the connection stops being read (the queue-depth
// analogue of kInHighWater — bounds deferred-request memory per conn).
constexpr uint32_t kMaxPendingPerConn = 4096;

// CRC-32 (IEEE 802.3, the zlib polynomial) — slice-by-8 tables, computed
// inline so the shared library needs no zlib link. Must match Python's
// zlib.crc32: init 0xFFFFFFFF, reflected 0xEDB88320, final complement.
// Slice-by-8 folds eight bytes per step (eight parallel table lookups
// instead of a serial byte chain), which keeps the checksum from being
// the bottleneck when a whole payload is verified in one pass — the
// byte-at-a-time loop runs ~400 MB/s, an order below the dataplane.
struct Crc32Table {
  uint32_t t[8][256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int j = 1; j < 8; ++j)
        t[j][i] = t[0][t[j - 1][i] & 0xFF] ^ (t[j - 1][i] >> 8);
  }
};
const Crc32Table kCrc32;

uint32_t crc32_ieee(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo, hi;  // memcpy: alignment-safe (UBSan) and little-endian
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= c;
    c = kCrc32.t[7][lo & 0xFF] ^ kCrc32.t[6][(lo >> 8) & 0xFF] ^
        kCrc32.t[5][(lo >> 16) & 0xFF] ^ kCrc32.t[4][lo >> 24] ^
        kCrc32.t[3][hi & 0xFF] ^ kCrc32.t[2][(hi >> 8) & 0xFF] ^
        kCrc32.t[1][(hi >> 16) & 0xFF] ^ kCrc32.t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  for (size_t i = 0; i < n; ++i) c = kCrc32.t[0][(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// crc32(A || B) from crc32(A), crc32(B), len(B) — zlib's crc32_combine
// (GF(2) operator matrices for appending len(B) zero bytes). What lets a
// request spanning several attested ranges reuse their CRCs without
// touching a byte: O(log len) 32x32 bit-matrix ops per range, constant in
// the bytes served. Parity with Python's utils/integrity.crc32_combine
// (and therefore zlib) is sanitizer-harness-tested.
uint32_t gf2_times(const uint32_t* mat, uint32_t vec) {
  uint32_t sum = 0;
  int i = 0;
  while (vec) {
    if (vec & 1) sum ^= mat[i];
    vec >>= 1;
    ++i;
  }
  return sum;
}

void gf2_square(uint32_t* square, const uint32_t* mat) {
  for (int n = 0; n < 32; ++n) square[n] = gf2_times(mat, mat[n]);
}

uint32_t crc32_combine(uint32_t crc1, uint32_t crc2, uint64_t len2) {
  if (len2 == 0) return crc1 ^ crc2;
  uint32_t even[32], odd[32];
  odd[0] = 0xEDB88320u;  // one zero BIT operator
  uint32_t row = 1;
  for (int n = 1; n < 32; ++n) {
    odd[n] = row;
    row <<= 1;
  }
  gf2_square(even, odd);  // two zero bits
  gf2_square(odd, even);  // four zero bits
  do {
    gf2_square(even, odd);  // eight, thirty-two, ... zero bits
    if (len2 & 1) crc1 = gf2_times(even, crc1);
    len2 >>= 1;
    if (!len2) break;
    gf2_square(odd, even);
    if (len2 & 1) crc1 = gf2_times(odd, crc1);
    len2 >>= 1;
  } while (len2);
  return crc1 ^ crc2;
}

constexpr uint32_t kFlagCrc32 = 4;  // messages.FLAG_CRC32

// One attested byte range of a registered file (at-rest sidecar partition
// or merge-ledger row), sorted by offset, zero-length ranges dropped.
struct CrcRange {
  uint64_t off;
  uint32_t len;
  uint32_t crc;
};

// One registered file. Lifetime is refcounted under Server::files_mu:
// `refs` counts the registration itself (1) plus every in-flight pin —
// a request validating against the region, or a zero-copy out-segment
// whose bytes are still draining to a socket. The mapping exists only
// while serving demands it (registration-on-demand) and is torn down by
// the LAST unpin after an unregister, never underneath a serve.
struct Region {
  std::string path;
  uint64_t size = 0;
  // The registration-time fd pins the INODE for the region's lifetime:
  // a re-commit os.replace()s the same path before unregistering the old
  // token (resolver.commit relies on snapshot-at-registration), so an
  // evicted or never-mapped region must NOT reopen by path — it would
  // serve the new attempt's bytes under the old token's offsets and CRC
  // table. One fd per registered file, the same resource profile as the
  // old eager per-file mmap.
  int fd = -1;
  void* base = nullptr;  // nullptr = registered but not currently mapped
  int refs = 1;          // registration + in-flight pins (files_mu)
  bool evicted = false;  // unmapped by LRU pressure; next map is a remap
  uint64_t last_use = 0; // LRU tick of the last serve touching it
  uint32_t tenant = 0;   // owning tenant (fair-share queueing + eviction)
  std::vector<CrcRange> crcs;  // sorted, disjoint; empty = no attestation
};

// One pending out-segment: either owned bytes (header, trailer, copied
// payload) or a zero-copy window into a pinned region's mapping.
struct OutSeg {
  std::vector<uint8_t> buf;      // owned bytes (region == nullptr)
  Region* region = nullptr;      // zero-copy: pinned source region
  const uint8_t* ptr = nullptr;  // window base within the mapping
  size_t len = 0;                // window length (owned segs: buf.size())
  size_t off = 0;                // bytes of this segment already sent

  size_t total() const { return region ? len : buf.size(); }
  const uint8_t* data() const { return region ? ptr : buf.data(); }
};

struct Conn {
  int fd;
  std::vector<uint8_t> in;  // accumulated unparsed bytes
  std::deque<OutSeg> out;   // pending response segments, in send order
  size_t out_bytes = 0;     // total unsent bytes across `out`
  uint32_t queued = 0;      // fair-mode requests parked in tenant queues
};

struct Server;

// One parsed-but-deferred request (fair-share mode): the block list is
// COPIED out of the connection's input buffer so the buffer can compact
// while the request waits its DRR turn.
struct PendingReq {
  Conn* c = nullptr;
  int64_t req_id = 0;
  std::vector<uint8_t> blocks;  // count * 16 bytes
  uint32_t count = 0;
  size_t plen = 0;
  uint64_t cost = 0;  // requested payload bytes (the DRR currency)
};

// One tenant's FIFO of deferred requests + its DRR deficit counter.
struct TenantQ {
  std::deque<PendingReq> q;
  uint64_t deficit = 0;
};

// One epoll loop; owns the connections assigned to it. Never touched by
// other threads except through (pending_mu, pending, wake_fd). The
// fair-share tenant queues are worker-local: requests defer and dispatch
// on the SAME thread that parsed them, so the DRR needs no locking.
struct Worker {
  Server* server = nullptr;
  int epoll_fd = -1;
  int wake_fd = -1;
  std::thread th;
  std::unordered_map<int, Conn*> conns;
  std::mutex pending_mu;
  std::vector<int> pending;  // accepted fds awaiting registration here
  std::map<uint32_t, TenantQ> tq;  // tenant -> deferred requests (DRR)
  size_t pending_reqs = 0;         // total deferred across tq
};

struct Server {
  int listen_fd = -1;
  int accept_epoll_fd = -1;
  int accept_wake_fd = -1;
  uint16_t port = 0;
  std::thread accept_th;
  std::deque<Worker> workers;
  std::atomic<uint32_t> next_worker{0};
  std::atomic<bool> stop{false};
  std::atomic<bool> checksum{false};   // append per-block CRC32 trailers
  std::atomic<bool> zero_copy{true};   // serve from the mapping when legal
  // Fair-share mode (tenancy): requests queue per owning tenant of the
  // requested token and dispatch by byte-cost deficit round robin
  // instead of parse order. Off = exact legacy inline serving.
  std::atomic<bool> fair{false};
  std::atomic<uint64_t> fair_quantum{256u << 10};
  std::atomic<uint64_t> fair_queued{0};  // requests ever deferred (audit)
  // files_mu guards ONLY token lookup + region refcount/mapping/LRU
  // bookkeeping — O(blocks) pointer work per request. No payload byte is
  // ever touched under it, so a 256 MiB response can't serialize the
  // other workers or block register/unregister.
  std::mutex files_mu;
  std::unordered_map<uint32_t, Region*> files;
  uint64_t region_budget = 0;  // mapped-bytes budget; 0 = unbounded
  uint64_t mapped_bytes = 0;
  uint64_t peak_mapped_bytes = 0;
  uint64_t lru_tick = 0;
  std::atomic<uint64_t> bytes_served{0};
  std::atomic<uint64_t> requests_served{0};
  std::atomic<uint64_t> remaps{0};            // evicted-then-mapped again
  std::atomic<uint64_t> zero_copy_blocks{0};  // blocks sent without a copy
  std::atomic<uint64_t> crc_reused{0};        // trailer CRCs from the table
  std::atomic<uint64_t> pin_events{0};        // request-level region pins
};

void set_nonblock(int fd) {
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

// -- region refcounting (all under files_mu) -------------------------------

void region_unmap_locked(Server* s, Region* r) {
  if (r->base) {
    munmap(r->base, (size_t)r->size);
    r->base = nullptr;
    s->mapped_bytes -= r->size;
  }
}

void region_unpin_locked(Server* s, Region* r) {
  if (--r->refs == 0) {
    region_unmap_locked(s, r);
    if (r->fd >= 0) close(r->fd);
    delete r;
  }
}

void enforce_budget_locked(Server* s);

// Unpin from the flush path (zero-copy windows fully drained or their
// connection died) — the whole batch under ONE files_mu hold, so a wide
// vectored response doesn't take the lock once per drained window. Pins
// blocked eviction while the serve was in flight, so their release is a
// budget edge: trim here, not only at map time, or a burst of wide
// vectored serves would leave the pool over budget until the NEXT serve
// happens to map something.
void region_unpin_batch(Server* s, std::vector<Region*>& regions) {
  if (regions.empty()) return;
  std::lock_guard<std::mutex> lk(s->files_mu);
  for (Region* r : regions) region_unpin_locked(s, r);
  enforce_budget_locked(s);
  regions.clear();
}

// LRU-unmap unpinned regions until mapped bytes fit the budget, one pass:
// collect the unpinned mapped regions, oldest-serve first, and unmap down
// the list until the pool fits. Pinned regions (refs > 1) are in-flight
// and never evicted; an empty candidate set simply leaves the pool over
// budget until pins drain.
void enforce_budget_locked(Server* s) {
  if (!s->region_budget || s->mapped_bytes <= s->region_budget) return;
  std::vector<Region*> victims;
  for (auto& [tok, r] : s->files) {
    (void)tok;
    if (r->base && r->refs == 1) victims.push_back(r);
  }
  std::sort(victims.begin(), victims.end(),
            [](const Region* a, const Region* b) {
              return a->last_use < b->last_use;
            });
  if (s->fair.load(std::memory_order_relaxed)) {
    // Tenancy-aware first pass: evict (LRU) only regions of tenants
    // holding MORE than their even share of the budget — the dynamic
    // per-tenant sizing of the registered set (NP-RDMA's
    // registration-on-demand argument, per tenant). The plain LRU pass
    // below mops up whatever imbalance this pass couldn't express.
    std::map<uint32_t, uint64_t> mapped_by;  // tenant -> mapped bytes
    for (auto& [tok, r] : s->files) {
      (void)tok;
      if (r->base) mapped_by[r->tenant] += r->size;
    }
    if (mapped_by.size() > 1) {
      uint64_t share = s->region_budget / mapped_by.size();
      for (Region* r : victims) {
        if (s->mapped_bytes <= s->region_budget) return;
        if (!r->base || mapped_by[r->tenant] <= share) continue;
        mapped_by[r->tenant] -= r->size;
        r->evicted = true;
        region_unmap_locked(s, r);
      }
    }
  }
  for (Region* r : victims) {
    if (s->mapped_bytes <= s->region_budget) break;
    if (!r->base) continue;  // already evicted by the tenant pass
    r->evicted = true;
    region_unmap_locked(s, r);
  }
}

// Map a pinned region WITHOUT the lock: mmap can touch a slow or
// degraded disk, and a stall under files_mu would serialize every worker
// and all register/unregister calls — the exact disease this serve path
// exists to cure. Maps from the registration-time fd (never by path: the
// path may have been renamed over by a re-commit; the fd pins the
// registered inode). The caller's pin keeps the region alive and
// un-evictable while unlocked; installation (under the lock) resolves
// the race of two serves mapping the same region concurrently, the
// loser's mapping discarded. Returns MAP_FAILED on any error.
void* map_region_file(const Region* r) {
  if (r->fd < 0) return MAP_FAILED;
  return mmap(nullptr, (size_t)r->size, PROT_READ, MAP_PRIVATE, r->fd, 0);
}

// CRC of [off, off+len) from the region's attested ranges, when they tile
// the request exactly (both endpoints aligned, no holes). Zero-length
// blocks are always 0 (zlib.crc32(b"")).
bool crc_from_table(const Region* r, uint64_t off, uint32_t len,
                    uint32_t* out) {
  if (len == 0) {
    *out = 0;
    return true;
  }
  const auto& v = r->crcs;
  if (v.empty()) return false;
  auto it = std::lower_bound(
      v.begin(), v.end(), off,
      [](const CrcRange& a, uint64_t o) { return a.off < o; });
  if (it == v.end() || it->off != off) return false;
  uint64_t end = off + len;
  uint64_t cur = off;
  uint32_t crc = 0;
  for (; it != v.end() && it->off == cur && cur + it->len <= end; ++it) {
    crc = cur == off ? it->crc : crc32_combine(crc, it->crc, it->len);
    cur += it->len;
    if (cur == end) {
      *out = crc;
      return true;
    }
  }
  return false;
}

// -- response assembly -----------------------------------------------------

// Bytes to write into the connection's owned out-stream: extend the last
// owned segment when it is at the tail (a partially-sent tail is fine —
// `off` tracks the sent prefix), else start a new one.
uint8_t* extend_owned(Conn* c, size_t n) {
  if (c->out.empty() || c->out.back().region != nullptr)
    c->out.emplace_back();
  OutSeg& seg = c->out.back();
  size_t base = seg.buf.size();
  seg.buf.resize(base + n);
  c->out_bytes += n;
  return seg.buf.data() + base;
}

// A zero-copy window into `region`'s mapping. The segment owns one pin,
// released when its bytes fully drain (or the connection dies).
void append_window(Conn* c, Region* region, const uint8_t* ptr, size_t len) {
  c->out.emplace_back();
  OutSeg& seg = c->out.back();
  seg.region = region;
  seg.ptr = ptr;
  seg.len = len;
  c->out_bytes += len;
}

void close_conn(Worker* w, Conn* c) {
  epoll_ctl(w->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  w->conns.erase(c->fd);
  // purge this connection's deferred fair-mode requests: they hold a
  // Conn* that is about to dangle (worker-local, so no lock needed)
  if (c->queued) {
    for (auto it = w->tq.begin(); it != w->tq.end();) {
      std::deque<PendingReq>& q = it->second.q;
      for (auto rit = q.begin(); rit != q.end();) {
        if (rit->c == c) {
          rit = q.erase(rit);
          --w->pending_reqs;
        } else {
          ++rit;
        }
      }
      it = q.empty() ? w->tq.erase(it) : std::next(it);
    }
  }
  // release the pins of undelivered zero-copy windows (one lock hold)
  std::vector<Region*> drained;
  for (OutSeg& seg : c->out)
    if (seg.region) drained.push_back(seg.region);
  region_unpin_batch(w->server, drained);
  delete c;
}

void arm(Worker* w, Conn* c) {
  bool want_in = c->in.size() < kInHighWater &&
                 c->out_bytes < kOutHighWater &&
                 c->queued < kMaxPendingPerConn;
  epoll_event ev{};
  ev.events = (want_in ? EPOLLIN : 0u) | (c->out_bytes ? EPOLLOUT : 0u);
  ev.data.ptr = c;
  epoll_ctl(w->epoll_fd, EPOLL_CTL_MOD, c->fd, &ev);
}

// Serve ONE validated request. `blocks` points at `count` 16-byte
// (token, offset, length) ranges. Appends the response to c->out.
void serve_request(Server* s, Conn* c, int64_t req_id, const uint8_t* blocks,
                   uint32_t count, size_t plen) {
  int32_t status = kStatusOk;
  uint64_t resp_len = 0;
  if (plen != 16 + (size_t)count * 16) {
    status = kStatusBadRange;
    count = 0;
  }
  // Pin + validate under ONE files_mu hold: token lookup, range checks
  // against the registered size, LRU accounting (mapping, when needed,
  // happens after — its disk syscalls never run under the lock).
  // O(count) pointer work — payload bytes are copied (when at all)
  // OUTSIDE the lock, so concurrent workers and register/unregister never
  // serialize behind a large response. Each block's resolved Region* is
  // recorded here: a concurrent unregister/re-register of the token
  // cannot redirect the build phase to a different file mid-request.
  std::vector<Region*> pinned;  // unique regions, one request-level pin each
  std::vector<Region*> block_regions(count, nullptr);
  std::vector<Region*> to_map;  // pinned, but unmapped at validate time
  pinned.reserve(8);
  // attested-CRC lookups resolve in the validate phase too: the per-file
  // table is replaced wholesale by bs_set_file_crcs under files_mu, so
  // reading it outside the lock would race the install. O(log ranges)
  // pointer work per block — still no payload byte under the lock.
  bool crc_mode = s->checksum.load(std::memory_order_relaxed);
  std::vector<uint32_t> table_crcs(crc_mode ? count : 0, 0);
  std::vector<uint8_t> crc_hit(crc_mode ? count : 0, 0);
  {
    std::lock_guard<std::mutex> lk(s->files_mu);
    uint64_t tick = ++s->lru_tick;
    for (uint32_t i = 0; i < count && status == kStatusOk; ++i) {
      uint32_t token, length;
      uint64_t offset;
      memcpy(&token, blocks + i * 16, 4);
      memcpy(&offset, blocks + i * 16 + 4, 8);
      memcpy(&length, blocks + i * 16 + 12, 4);
      auto it = s->files.find(token);
      if (it == s->files.end()) {
        status = kStatusUnknown;
      } else if (offset > it->second->size ||
                 length > it->second->size - offset) {
        status = kStatusBadRange;
      } else {
        resp_len += length;
        Region* r = it->second;
        block_regions[i] = r;
        if (crc_mode)
          crc_hit[i] = crc_from_table(r, offset, length, &table_crcs[i]);
        if (r->last_use != tick) {  // first touch by this request
          r->last_use = tick;
          ++r->refs;
          pinned.push_back(r);
          s->pin_events.fetch_add(1, std::memory_order_relaxed);
        }
      }
    }
    if (resp_len > kMaxRespPayload && status == kStatusOk)
      status = kStatusBadRange;
    if (status != kStatusOk) {
      for (Region* r : pinned) region_unpin_locked(s, r);
      pinned.clear();
      resp_len = 0;
    }
    if (status == kStatusOk) {
      for (Region* r : pinned)
        if (!r->base && r->size) to_map.push_back(r);
    }
  }
  // Registration-on-demand: (re)map pinned regions whose mapping was
  // evicted or never materialized — syscalls OUTSIDE the lock (see
  // map_region_file; only the immutable path/size are touched unlocked),
  // installation under it.
  if (status == kStatusOk) {
    std::vector<std::pair<Region*, void*>> fresh;
    bool map_failed = false;
    for (Region* r : to_map) {
      void* base = map_region_file(r);
      if (base == MAP_FAILED) {
        map_failed = true;
        break;
      }
      fresh.emplace_back(r, base);
    }
    if (map_failed || !fresh.empty()) {
      std::lock_guard<std::mutex> lk(s->files_mu);
      for (auto& [r, base] : fresh) {
        if (r->base) {  // a concurrent serve won the install race
          munmap(base, (size_t)r->size);
          continue;
        }
        r->base = base;
        s->mapped_bytes += r->size;
        if (s->mapped_bytes > s->peak_mapped_bytes)
          s->peak_mapped_bytes = s->mapped_bytes;
        if (r->evicted) {
          r->evicted = false;
          s->remaps.fetch_add(1, std::memory_order_relaxed);
        }
      }
      if (map_failed) {
        status = kStatusError;  // transient: the client retries
        for (Region* r : pinned) region_unpin_locked(s, r);
        pinned.clear();
        resp_len = 0;
      }
      enforce_budget_locked(s);
    }
  }
  // frame: [total][type][req_id q][status i][flags i][data][crc32*]
  bool crc = crc_mode && status == kStatusOk && count > 0;
  bool zc = s->zero_copy.load(std::memory_order_relaxed) &&
            status == kStatusOk;
  size_t trailer = crc ? (size_t)count * 4 : 0;
  uint32_t out_total = (uint32_t)(8 + 16 + resp_len + trailer);
  uint8_t* o = extend_owned(c, 24);
  memcpy(o, &out_total, 4);
  memcpy(o + 4, &kRespType, 4);
  memcpy(o + 8, &req_id, 8);
  memcpy(o + 16, &status, 4);
  uint32_t flags = crc ? kFlagCrc32 : 0;
  memcpy(o + 20, &flags, 4);
  if (status != kStatusOk) return;
  std::vector<uint32_t> crcs(crc ? count : 0);
  std::vector<std::pair<Region*, int>> window_pins;  // extra refs to take
  window_pins.reserve(8);
  uint64_t zc_blocks = 0;
  for (uint32_t i = 0; i < count; ++i) {
    uint32_t token, length;
    uint64_t offset;
    memcpy(&token, blocks + i * 16, 4);
    memcpy(&offset, blocks + i * 16 + 4, 8);
    memcpy(&length, blocks + i * 16 + 12, 4);
    (void)token;
    if (length == 0) {
      if (crc) crcs[i] = 0;
      continue;
    }
    // the pinned snapshot from the validate phase: stable without the
    // lock (base can't be unmapped while refs > 1), and immune to a
    // concurrent unregister/re-register of the token; CRC-table answers
    // were resolved there too (the table itself isn't lock-free)
    Region* src = block_regions[i];
    const uint8_t* base = (const uint8_t*)src->base + offset;
    bool have_crc = crc && crc_hit[i];
    if (zc && (!crc || have_crc)) {
      append_window(c, src, base, length);
      window_pins.emplace_back(src, 1);
      zc_blocks += 1;
      if (crc) {
        crcs[i] = table_crcs[i];
        s->crc_reused.fetch_add(1, std::memory_order_relaxed);
      }
    } else {
      // CRC-trailer fallback (or zero-copy disabled): one copy into the
      // owned stream; the checksum covers this server's own read+copy
      uint8_t* dst = extend_owned(c, length);
      memcpy(dst, base, length);
      if (crc) {
        if (have_crc) {
          crcs[i] = table_crcs[i];
          s->crc_reused.fetch_add(1, std::memory_order_relaxed);
        } else {
          crcs[i] = crc32_ieee(dst, length);
        }
      }
    }
  }
  if (crc) {
    uint8_t* t = extend_owned(c, trailer);
    memcpy(t, crcs.data(), trailer);
  }
  // transfer pins: each zero-copy window takes its own reference, the
  // request-level pins release — one files_mu acquisition for the batch.
  // Releasing pins is a budget edge (evictions they blocked can go now).
  {
    std::lock_guard<std::mutex> lk(s->files_mu);
    for (auto& [r, n] : window_pins) r->refs += n;
    for (Region* r : pinned) region_unpin_locked(s, r);
    enforce_budget_locked(s);
  }
  s->bytes_served += resp_len;
  s->requests_served += 1;
  s->zero_copy_blocks.fetch_add(zc_blocks, std::memory_order_relaxed);
}

// Parse every complete frame in c->in. Legacy (FIFO) mode serves each
// request inline, appending responses to c->out; fair-share mode DEFERS
// each request into the worker's per-tenant DRR queues (tenant = owner
// of the first block's token), dispatched by drain_pending.
bool process_frames(Server* s, Worker* w, Conn* c) {
  bool fair = s->fair.load(std::memory_order_relaxed);
  size_t pos = 0;
  while (c->in.size() - pos >= 8) {
    if (c->out_bytes > kOutHighWater) break;  // backpressure
    if (fair && c->queued >= kMaxPendingPerConn) break;
    uint32_t total, type;
    memcpy(&total, c->in.data() + pos, 4);
    memcpy(&type, c->in.data() + pos + 4, 4);
    if (total < 8 || total > kMaxReqFrame) return false;  // protocol error
    if (c->in.size() - pos < total) break;                // incomplete
    const uint8_t* p = c->in.data() + pos + 8;
    size_t plen = total - 8;
    // this port speaks exactly one request type; anything else is a
    // protocol violation — drop the connection so the client fails fast
    // (a TransportError) instead of timing out on a silently-ignored frame
    if (type != kReqType || plen < 16) return false;
    int64_t req_id;
    uint32_t count;
    memcpy(&req_id, p, 8);
    // p+8..12: shuffle_id (unused server-side: tokens are global)
    memcpy(&count, p + 12, 4);
    if (!fair) {
      serve_request(s, c, req_id, p + 16, count, plen);
    } else {
      PendingReq r;
      r.c = c;
      r.req_id = req_id;
      r.count = count;
      r.plen = plen;
      size_t blen = plen >= 16 ? plen - 16 : 0;
      r.blocks.assign(p + 16, p + 16 + blen);
      uint32_t tenant = 0;
      if (count > 0 && blen >= (size_t)count * 16) {
        for (uint32_t i = 0; i < count; ++i) {
          uint32_t length;
          memcpy(&length, r.blocks.data() + i * 16 + 12, 4);
          r.cost += length;
        }
        uint32_t token;
        memcpy(&token, r.blocks.data(), 4);
        std::lock_guard<std::mutex> lk(s->files_mu);
        auto it = s->files.find(token);
        if (it != s->files.end()) tenant = it->second->tenant;
      }
      w->tq[tenant].q.push_back(std::move(r));
      ++w->pending_reqs;
      ++c->queued;
      s->fair_queued.fetch_add(1, std::memory_order_relaxed);
    }
    pos += total;
  }
  if (pos) c->in.erase(c->in.begin(), c->in.begin() + pos);
  return true;
}

// Dispatch deferred requests by deficit round robin: each pass grants
// every queued tenant one quantum of byte credit and serves head-of-line
// requests that fit it (per-tenant FIFO preserved). A connection past
// its out high-water mark parks its tenant's head until the socket
// drains — other tenants keep dispatching around it. Returns the set of
// connections that gained output (the caller flushes + re-arms them).
void drain_pending(Worker* w, std::unordered_set<Conn*>& touched) {
  Server* s = w->server;
  if (w->pending_reqs == 0) return;
  uint64_t quantum = s->fair_quantum.load(std::memory_order_relaxed);
  // Loop passes until every queue is empty or every head is parked on
  // its connection's out high-water mark. A head merely short on
  // deficit keeps the loop going (`starved`): its deficit grows by one
  // quantum per pass, so a request costing K quanta dispatches after K
  // passes of THIS call — never parked until the next epoll tick.
  bool again = true;
  while (w->pending_reqs > 0 && again) {
    again = false;
    for (auto it = w->tq.begin(); it != w->tq.end();) {
      TenantQ& tq = it->second;
      if (tq.q.empty()) {
        it = w->tq.erase(it);
        continue;
      }
      if (tq.q.front().c->out_bytes > kOutHighWater) {
        // head parked on its socket: no quantum grant while blocked (a
        // long-blocked tenant must not bank credit and later burst)
        ++it;
        continue;
      }
      tq.deficit += quantum;
      while (!tq.q.empty()) {
        PendingReq& r = tq.q.front();
        if (r.c->out_bytes > kOutHighWater) break;  // socket-blocked
        if (r.cost > tq.deficit) {
          again = true;  // starved, not blocked: grow and retry
          break;
        }
        tq.deficit -= r.cost;
        serve_request(s, r.c, r.req_id, r.blocks.data(), r.count, r.plen);
        touched.insert(r.c);
        --r.c->queued;
        tq.q.pop_front();
        --w->pending_reqs;
        again = true;
      }
      if (tq.q.empty()) {
        it = w->tq.erase(it);  // drained: leftover deficit forfeits
      } else {
        ++it;
      }
    }
  }
}

// Flush pending segments with one gathered sendmsg per syscall (writev
// with MSG_NOSIGNAL): owned headers/trailers and mapped-region windows
// interleave in a single iovec batch. Returns false on a dead socket.
bool flush_out(Server* s, Conn* c) {
  std::vector<Region*> drained;  // window pins released in one batch below
  bool alive = true;
  while (c->out_bytes) {
    iovec iov[kMaxIov];
    int n = 0;
    for (const OutSeg& seg : c->out) {
      if (n == kMaxIov) break;
      size_t rem = seg.total() - seg.off;
      if (rem == 0) continue;
      iov[n].iov_base = (void*)(seg.data() + seg.off);
      iov[n].iov_len = rem;
      ++n;
    }
    msghdr mh{};
    mh.msg_iov = iov;
    mh.msg_iovlen = (size_t)n;
    ssize_t sent = sendmsg(c->fd, &mh, MSG_NOSIGNAL);
    if (sent < 0) {
      alive = errno == EAGAIN || errno == EWOULDBLOCK;
      break;
    }
    c->out_bytes -= (size_t)sent;
    size_t left = (size_t)sent;
    while (left && !c->out.empty()) {
      OutSeg& seg = c->out.front();
      size_t rem = seg.total() - seg.off;
      size_t take = rem < left ? rem : left;
      seg.off += take;
      left -= take;
      if (seg.off == seg.total()) {
        if (seg.region) drained.push_back(seg.region);
        c->out.pop_front();
      }
    }
  }
  region_unpin_batch(s, drained);
  return alive;
}

void worker_loop(Worker* w) {
  Server* s = w->server;
  epoll_event events[64];
  while (!s->stop.load()) {
    int n = epoll_wait(w->epoll_fd, events, 64, 200);
    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {  // wake eventfd
        uint64_t tmp;
        (void)!read(w->wake_fd, &tmp, 8);
        std::vector<int> fds;
        {
          std::lock_guard<std::mutex> lk(w->pending_mu);
          fds.swap(w->pending);
        }
        for (int fd : fds) {
          Conn* c = new Conn{fd, {}, {}, 0, 0};
          w->conns[fd] = c;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = c;
          epoll_ctl(w->epoll_fd, EPOLL_CTL_ADD, fd, &ev);
        }
        continue;
      }
      Conn* c = (Conn*)events[i].data.ptr;
      bool dead = false;
      if (events[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
      if (!dead && (events[i].events & EPOLLIN)) {
        char buf[1 << 16];
        while (c->in.size() < kInHighWater) {
          ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
          if (r > 0) {
            c->in.insert(c->in.end(), buf, buf + r);
          } else if (r == 0) {
            dead = true;
            break;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) dead = true;
            break;
          }
        }
        if (!dead && !process_frames(s, w, c)) dead = true;
      }
      if (!dead && c->out_bytes) {
        if (!flush_out(s, c)) dead = true;
        if (!dead && c->out_bytes == 0) {
          // backlog drained: serve any requests parked by the high-water
          // mark while we were blocked on the socket
          if (!c->in.empty() && !process_frames(s, w, c)) dead = true;
          if (!dead && c->out_bytes && !flush_out(s, c)) dead = true;
        }
      }
      if (dead) {
        close_conn(w, c);
      } else {
        arm(w, c);
      }
    }
    // fair-share dispatch: requests deferred into the tenant queues by
    // this pass's parses (or parked earlier behind a blocked socket)
    // dispatch by DRR now, then their connections flush + re-arm. Runs
    // every loop iteration, so a parked backlog retries at least every
    // epoll timeout even with no new events. Loops until no progress:
    // a connection parked at kMaxPendingPerConn still holds complete
    // unparsed frames in c->in that no future epoll event may ever
    // announce (the kernel rx buffer can be empty and the out side
    // fully flushed) — once dispatch frees its queue slots, those
    // frames must re-parse HERE or the client hangs.
    while (w->pending_reqs > 0) {
      // every Conn* in `touched` is live: a closed connection's
      // deferred requests were purged by close_conn, so drain_pending
      // can never have served it, and nothing in this loop closes a
      // connection other than the one being flushed
      std::unordered_set<Conn*> touched;
      drain_pending(w, touched);
      if (touched.empty()) break;  // every head socket-blocked: retry
                                   // on EPOLLOUT / next epoll tick
      bool parsed_more = false;
      for (Conn* c : touched) {
        if (!flush_out(s, c)) {
          close_conn(w, c);
          continue;
        }
        if (c->out_bytes == 0 && !c->in.empty()) {
          uint32_t before = c->queued;
          if (!process_frames(s, w, c)) {
            close_conn(w, c);
            continue;
          }
          if (c->queued > before) parsed_more = true;
          if (c->out_bytes && !flush_out(s, c)) {
            close_conn(w, c);
            continue;
          }
        }
        arm(w, c);
      }
      if (!parsed_more) break;  // nothing newly deferred; what's left
                                // is parked behind blocked sockets
    }
  }
}

void accept_loop(Server* s) {
  epoll_event events[8];
  while (!s->stop.load()) {
    int n = epoll_wait(s->accept_epoll_fd, events, 8, 200);
    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {  // wake eventfd
        uint64_t tmp;
        (void)!read(s->accept_wake_fd, &tmp, 8);
        continue;
      }
      while (true) {
        int fd = accept(s->listen_fd, nullptr, nullptr);
        if (fd < 0) break;
        set_nonblock(fd);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // round-robin the connection onto a worker (the reference assigns
        // each channel the next cpu vector, java/RdmaNode.java:222-279)
        Worker& w = s->workers[s->next_worker++ % s->workers.size()];
        {
          std::lock_guard<std::mutex> lk(w.pending_mu);
          w.pending.push_back(fd);
        }
        uint64_t one64 = 1;
        (void)!write(w.wake_fd, &one64, 8);
      }
    }
  }
}

void destroy(Server* s) {
  for (Worker& w : s->workers) {
    if (w.epoll_fd >= 0) close(w.epoll_fd);
    if (w.wake_fd >= 0) close(w.wake_fd);
  }
  if (s->accept_epoll_fd >= 0) close(s->accept_epoll_fd);
  if (s->accept_wake_fd >= 0) close(s->accept_wake_fd);
  if (s->listen_fd >= 0) close(s->listen_fd);
  delete s;
}

}  // namespace

extern "C" {

// host: dotted-quad bind address; empty/null binds loopback. The data port
// serves registered spill bytes unauthenticated, so it binds exactly as
// wide as asked — multi-host deployments pass the control-plane host and
// must firewall the port, same trust model as the reference's verbs
// listener (java/RdmaNode.java:74-88).
// num_threads: epoll worker count (>=1).
// cpus/num_cpus: optional CPU pin list; worker i pins to cpus[i % num_cpus]
// (the reference pins completion threads, java/RdmaThread.java:46-48).
void* bs_create(const char* host, uint16_t port, int num_threads,
                const int* cpus, int num_cpus) {
  Server* s = new Server();
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (host && host[0] &&
      inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  addr.sin_port = htons(port);
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(s->listen_fd, 128) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  set_nonblock(s->listen_fd);

  s->accept_epoll_fd = epoll_create1(0);
  s->accept_wake_fd = eventfd(0, EFD_NONBLOCK);
  if (s->accept_epoll_fd < 0 || s->accept_wake_fd < 0) {
    destroy(s);
    return nullptr;
  }
  epoll_event lev{};
  lev.events = EPOLLIN;
  lev.data.ptr = (void*)s;
  epoll_ctl(s->accept_epoll_fd, EPOLL_CTL_ADD, s->listen_fd, &lev);
  epoll_event wev{};
  wev.events = EPOLLIN;
  wev.data.ptr = nullptr;
  epoll_ctl(s->accept_epoll_fd, EPOLL_CTL_ADD, s->accept_wake_fd, &wev);

  if (num_threads < 1) num_threads = 1;
  s->workers.resize((size_t)num_threads);
  for (Worker& w : s->workers) {
    w.server = s;
    w.epoll_fd = epoll_create1(0);
    w.wake_fd = eventfd(0, EFD_NONBLOCK);
    if (w.epoll_fd < 0 || w.wake_fd < 0) {
      destroy(s);
      return nullptr;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;
    epoll_ctl(w.epoll_fd, EPOLL_CTL_ADD, w.wake_fd, &ev);
  }
  for (size_t i = 0; i < s->workers.size(); ++i) {
    Worker& w = s->workers[i];
    w.th = std::thread(worker_loop, &w);
    if (cpus && num_cpus > 0) {
      int cpu = cpus[i % (size_t)num_cpus];
      if (cpu >= 0 && cpu < CPU_SETSIZE) {  // reject garbage ids: CPU_SET
        cpu_set_t set;                      // with a bad index is UB
        CPU_ZERO(&set);
        CPU_SET(cpu, &set);
        pthread_setaffinity_np(w.th.native_handle(), sizeof(set), &set);
      }
    }
  }
  s->accept_th = std::thread(accept_loop, s);
  return s;
}

uint16_t bs_port(void* handle) { return ((Server*)handle)->port; }

// Toggle per-block CRC32 response trailers (FLAG_CRC32). Plumbed from the
// fetch_checksum config key so both serving paths speak one contract.
void bs_set_checksum(void* handle, int enabled) {
  ((Server*)handle)->checksum.store(enabled != 0);
}

// Toggle the zero-copy serve path (serve_zero_copy config key). Off =
// every block pays the copy-and-recompute fallback — the regression
// escape hatch and the A/B baseline the serve bench measures against.
void bs_set_zero_copy(void* handle, int enabled) {
  ((Server*)handle)->zero_copy.store(enabled != 0);
}

// Mapped-bytes budget for the registered-region pool (the
// registered_region_budget config key). 0 = unbounded. Past it, the
// least-recently-served unpinned mappings unmap; a later serve remaps on
// demand (counted by bs_remaps).
void bs_set_region_budget(void* handle, uint64_t budget) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  s->region_budget = budget;
  enforce_budget_locked(s);
}

// Register `path` for serving under `token` for `tenant` —
// registration-on-demand: the file is validated (open/fstat) but NOT
// mapped; the first serve maps it. The tenant tag keys fair-share
// request queueing and the budget eviction's per-tenant share. Returns
// 0 on success.
int bs_register_file2(void* handle, uint32_t token, const char* path,
                      uint32_t tenant) {
  Server* s = (Server*)handle;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -1;
  }
  Region* r = new Region();
  r->path = path;
  r->size = (uint64_t)st.st_size;
  r->fd = fd;  // retained: pins the inode against rename-over re-commits
  r->tenant = tenant;
  std::lock_guard<std::mutex> lk(s->files_mu);
  auto it = s->files.find(token);
  if (it != s->files.end())
    region_unpin_locked(s, it->second);  // replace: old region drains out
  s->files[token] = r;
  return 0;
}

// Legacy single-tenant registration (kept for older control planes and
// the sanitizer harness): everything lands under tenant 0.
int bs_register_file(void* handle, uint32_t token, const char* path) {
  return bs_register_file2(handle, token, path, 0);
}

// Deficit-round-robin fair-share serving (the fair_share_serving /
// fair_share_quantum_bytes config keys): on, requests defer into
// per-tenant worker-local queues and dispatch by byte-cost DRR; off
// (the default) preserves the legacy inline FIFO serve exactly.
void bs_set_fair(void* handle, int enabled, uint64_t quantum_bytes) {
  Server* s = (Server*)handle;
  if (quantum_bytes > 0) s->fair_quantum.store(quantum_bytes);
  s->fair.store(enabled != 0);
}

// Requests ever deferred through the fair-share queues (audit gauge).
uint64_t bs_fair_queued(void* handle) {
  return ((Server*)handle)->fair_queued.load();
}

// Attach attested CRC ranges (at-rest sidecar partitions / merge-ledger
// rows) to a registered token: ranges[i] = (offsets[i], lengths[i]) with
// CRC32 crcs[i]. Serves whose blocks tile these ranges exactly reuse the
// CRCs instead of recomputing — and may therefore stay zero-copy with
// trailers on. Returns 0 on success, -1 for an unknown token.
int bs_set_file_crcs(void* handle, uint32_t token, const uint64_t* offsets,
                     const uint32_t* lengths, const uint32_t* crcs,
                     uint32_t n) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  auto it = s->files.find(token);
  if (it == s->files.end()) return -1;
  std::vector<CrcRange> v;
  v.reserve(n);
  for (uint32_t i = 0; i < n; ++i)
    if (lengths[i] > 0) v.push_back({offsets[i], lengths[i], crcs[i]});
  std::sort(v.begin(), v.end(),
            [](const CrcRange& a, const CrcRange& b) { return a.off < b.off; });
  it->second->crcs = std::move(v);
  return 0;
}

// Unregister: the token disappears immediately (new requests answer
// kStatusUnknown); the mapping itself lives until the last in-flight pin
// (a serving request or a draining zero-copy window) releases — an
// unregister during an in-flight serve is safe by construction.
int bs_unregister_file(void* handle, uint32_t token) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  auto it = s->files.find(token);
  if (it == s->files.end()) return -1;
  Region* r = it->second;
  s->files.erase(it);
  region_unpin_locked(s, r);
  return 0;
}

uint64_t bs_bytes_served(void* handle) {
  return ((Server*)handle)->bytes_served.load();
}

uint64_t bs_requests_served(void* handle) {
  return ((Server*)handle)->requests_served.load();
}

// -- registered-region pool gauges (the leased_bytes-style accounting the
// Python control plane surfaces and traces) ------------------------------

uint64_t bs_mapped_bytes(void* handle) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  return s->mapped_bytes;
}

uint64_t bs_peak_mapped_bytes(void* handle) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  return s->peak_mapped_bytes;
}

uint64_t bs_registered_bytes(void* handle) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  uint64_t total = 0;
  for (auto& [tok, r] : s->files) {
    (void)tok;
    total += r->size;
  }
  return total;
}

uint64_t bs_remaps(void* handle) {
  return ((Server*)handle)->remaps.load();
}

uint64_t bs_zero_copy_blocks(void* handle) {
  return ((Server*)handle)->zero_copy_blocks.load();
}

uint64_t bs_crc_reused(void* handle) {
  return ((Server*)handle)->crc_reused.load();
}

uint64_t bs_pin_events(void* handle) {
  return ((Server*)handle)->pin_events.load();
}

void bs_stop(void* handle) {
  Server* s = (Server*)handle;
  s->stop.store(true);
  uint64_t one = 1;
  (void)!write(s->accept_wake_fd, &one, 8);
  for (Worker& w : s->workers) (void)!write(w.wake_fd, &one, 8);
  if (s->accept_th.joinable()) s->accept_th.join();
  for (Worker& w : s->workers) {
    if (w.th.joinable()) w.th.join();
    for (auto& [fd, c] : w.conns) {
      close(c->fd);
      std::vector<Region*> drained;
      for (OutSeg& seg : c->out)
        if (seg.region) drained.push_back(seg.region);
      region_unpin_batch(s, drained);
      delete c;
    }
    w.conns.clear();
    // accepted but never registered (stop raced the wake)
    std::lock_guard<std::mutex> lk(w.pending_mu);
    for (int fd : w.pending) close(fd);
    w.pending.clear();
  }
  {
    std::lock_guard<std::mutex> lk(s->files_mu);
    for (auto& [tok, r] : s->files) {
      (void)tok;
      region_unmap_locked(s, r);
      if (r->fd >= 0) close(r->fd);
      delete r;
    }
    s->files.clear();
  }
  destroy(s);
}

}  // extern "C"
