// Native block server: the executor's data-serving path in C++.
//
// In the reference the serving executor's CPU is NOT in the data path — the
// NIC serves registered memory directly (one-sided READ,
// scala/RdmaShuffleFetcherIterator.scala:171-180 against mmap'd files
// registered in java/RdmaMappedFile.java). On the DCN fallback path this
// framework serves blocks over TCP; this server removes Python from that
// path: connections are sharded round-robin across N epoll worker threads
// (the reference round-robins channels across its cpuList and pins the
// completion thread, java/RdmaNode.java:222-279 + java/RdmaThread.java:46-48)
// serving FetchBlocks requests straight out of mmap'd spill files
// (page cache -> socket), with the Python control plane only registering
// (token -> file) mappings.
//
// Wire protocol: byte-compatible with sparkrdma_tpu.parallel.rpc_msg /
// messages — frames of [total:4][type:4][payload], request type 9
// (FetchBlocksReq: req_id q, shuffle_id i, count I, blocks (I,Q,I)*),
// response type 10 (FetchBlocksResp: req_id q, status i, flags i, data).
// Requests are VECTORED: the block list may span any mix of registered
// tokens (different maps' spill files), gathered in request order into one
// response. With bs_set_checksum(1) responses carry the same per-block
// CRC32 trailer as the Python server (FLAG_CRC32=4, one little-endian u32
// per requested block appended after the data) so a client can isolate a
// corrupt sub-range to one block — and therefore one map — instead of
// refetching the whole vectored response; otherwise flags=0.
//
// Exposed as a C ABI for ctypes.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <sched.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kReqType = 9;
constexpr uint32_t kRespType = 10;
constexpr int32_t kStatusOk = 0;
constexpr int32_t kStatusUnknown = 1;
constexpr int32_t kStatusBadRange = 3;
// Request frames on this port are tiny ([16 fixed + 16/block]); anything
// larger than 1 MiB (~65k blocks) is a protocol violation, and capping the
// inbound frame well below kInHighWater guarantees a parked connection can
// always finish buffering the frame it is mid-way through.
constexpr size_t kMaxReqFrame = 1u << 20;
// Hard cap on one response's payload: far above the client's grouped-fetch
// ceiling (shuffle_read_block_size), far below uint32 frame-length wrap and
// the client Reassembler's 1 GiB max_frame. Oversized requests get
// kStatusBadRange instead of a frame the client can't parse (or, past
// 4 GiB, a wrapped out_total that would heap-overflow the out buffer).
constexpr uint64_t kMaxRespPayload = 256ull << 20;
// Backpressure high-water marks: while the unwritten response backlog (or
// unparsed input) exceeds these, the connection stops parsing AND stops
// recv()ing (EPOLLIN interest is dropped), bounding per-connection memory
// under pipelined clients instead of buffering toward kMaxFrame.
constexpr size_t kOutHighWater = 256u << 20;
constexpr size_t kInHighWater = 4u << 20;

struct MappedFile {
  void* base;
  uint64_t size;
};

// CRC-32 (IEEE 802.3, the zlib polynomial) — table-driven, computed inline
// so the shared library needs no zlib link. Must match Python's
// zlib.crc32: init 0xFFFFFFFF, reflected 0xEDB88320, final complement.
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const Crc32Table kCrc32;

uint32_t crc32_ieee(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = kCrc32.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

constexpr uint32_t kFlagCrc32 = 4;  // messages.FLAG_CRC32

struct Conn {
  int fd;
  std::vector<uint8_t> in;   // accumulated unparsed bytes
  std::vector<uint8_t> out;  // pending unwritten response bytes
  size_t out_off = 0;
};

struct Server;

// One epoll loop; owns the connections assigned to it. Never touched by
// other threads except through (pending_mu, pending, wake_fd).
struct Worker {
  Server* server = nullptr;
  int epoll_fd = -1;
  int wake_fd = -1;
  std::thread th;
  std::unordered_map<int, Conn*> conns;
  std::mutex pending_mu;
  std::vector<int> pending;  // accepted fds awaiting registration here
};

struct Server {
  int listen_fd = -1;
  int accept_epoll_fd = -1;
  int accept_wake_fd = -1;
  uint16_t port = 0;
  std::thread accept_th;
  std::deque<Worker> workers;
  std::atomic<uint32_t> next_worker{0};
  std::atomic<bool> stop{false};
  std::atomic<bool> checksum{false};  // append per-block CRC32 trailers
  std::mutex files_mu;
  std::unordered_map<uint32_t, MappedFile> files;
  std::atomic<uint64_t> bytes_served{0};
  std::atomic<uint64_t> requests_served{0};
};

void set_nonblock(int fd) {
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

void close_conn(Worker* w, Conn* c) {
  epoll_ctl(w->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  w->conns.erase(c->fd);
  delete c;
}

void arm(Worker* w, Conn* c) {
  size_t backlog = c->out.size() - c->out_off;
  bool want_in = c->in.size() < kInHighWater && backlog < kOutHighWater;
  epoll_event ev{};
  ev.events = (want_in ? EPOLLIN : 0u) | (backlog ? EPOLLOUT : 0u);
  ev.data.ptr = c;
  epoll_ctl(w->epoll_fd, EPOLL_CTL_MOD, c->fd, &ev);
}

// Parse + serve every complete frame in c->in; append responses to c->out.
bool process_frames(Server* s, Conn* c) {
  size_t pos = 0;
  while (c->in.size() - pos >= 8) {
    if (c->out.size() - c->out_off > kOutHighWater) break;  // backpressure
    uint32_t total, type;
    memcpy(&total, c->in.data() + pos, 4);
    memcpy(&type, c->in.data() + pos + 4, 4);
    if (total < 8 || total > kMaxReqFrame) return false;  // protocol error
    if (c->in.size() - pos < total) break;                // incomplete
    const uint8_t* p = c->in.data() + pos + 8;
    size_t plen = total - 8;
    // this port speaks exactly one request type; anything else is a
    // protocol violation — drop the connection so the client fails fast
    // (a TransportError) instead of timing out on a silently-ignored frame
    if (type != kReqType || plen < 16) return false;
    {
      int64_t req_id;
      uint32_t count;
      memcpy(&req_id, p, 8);
      // p+8..12: shuffle_id (unused server-side: tokens are global)
      memcpy(&count, p + 12, 4);
      const uint8_t* blocks = p + 16;
      int32_t status = kStatusOk;
      uint64_t resp_len = 0;
      if (plen != 16 + (size_t)count * 16) {
        status = kStatusBadRange;
        count = 0;
      }
      std::lock_guard<std::mutex> lk(s->files_mu);
      // validate + size pass
      for (uint32_t i = 0; i < count && status == kStatusOk; ++i) {
        uint32_t token, length;
        uint64_t offset;
        memcpy(&token, blocks + i * 16, 4);
        memcpy(&offset, blocks + i * 16 + 4, 8);
        memcpy(&length, blocks + i * 16 + 12, 4);
        auto it = s->files.find(token);
        if (it == s->files.end()) {
          status = kStatusUnknown;
        } else if (offset > it->second.size ||
                   length > it->second.size - offset) {
          status = kStatusBadRange;
        } else {
          resp_len += length;
        }
      }
      if (resp_len > kMaxRespPayload && status == kStatusOk)
        status = kStatusBadRange;
      if (status != kStatusOk) resp_len = 0;
      // frame: [total][type][req_id q][status i][flags i][data][crc32*]
      bool crc = s->checksum.load(std::memory_order_relaxed) &&
                 status == kStatusOk && count > 0;
      size_t trailer = crc ? (size_t)count * 4 : 0;
      uint32_t out_total = (uint32_t)(8 + 16 + resp_len + trailer);
      size_t base = c->out.size();
      c->out.resize(base + out_total);
      uint8_t* o = c->out.data() + base;
      memcpy(o, &out_total, 4);
      memcpy(o + 4, &kRespType, 4);
      memcpy(o + 8, &req_id, 8);
      memcpy(o + 16, &status, 4);
      uint32_t flags = crc ? kFlagCrc32 : 0;
      memcpy(o + 20, &flags, 4);
      uint8_t* data = o + 24;
      uint8_t* crcs = o + 24 + resp_len;
      if (status == kStatusOk) {
        for (uint32_t i = 0; i < count; ++i) {
          uint32_t token, length;
          uint64_t offset;
          memcpy(&token, blocks + i * 16, 4);
          memcpy(&offset, blocks + i * 16 + 4, 8);
          memcpy(&length, blocks + i * 16 + 12, 4);
          const MappedFile& f = s->files.at(token);
          memcpy(data, (const char*)f.base + offset, length);
          if (crc) {
            // checksum the RESPONSE copy, not the mapped file: the check
            // must cover this server's own read+copy, end to end
            uint32_t sum = crc32_ieee(data, length);
            memcpy(crcs + (size_t)i * 4, &sum, 4);
          }
          data += length;
        }
        s->bytes_served += resp_len;
        s->requests_served += 1;
      }
    }
    pos += total;
  }
  if (pos) c->in.erase(c->in.begin(), c->in.begin() + pos);
  return true;
}

void worker_loop(Worker* w) {
  Server* s = w->server;
  epoll_event events[64];
  while (!s->stop.load()) {
    int n = epoll_wait(w->epoll_fd, events, 64, 200);
    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {  // wake eventfd
        uint64_t tmp;
        (void)!read(w->wake_fd, &tmp, 8);
        std::vector<int> fds;
        {
          std::lock_guard<std::mutex> lk(w->pending_mu);
          fds.swap(w->pending);
        }
        for (int fd : fds) {
          Conn* c = new Conn{fd, {}, {}, 0};
          w->conns[fd] = c;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = c;
          epoll_ctl(w->epoll_fd, EPOLL_CTL_ADD, fd, &ev);
        }
        continue;
      }
      Conn* c = (Conn*)events[i].data.ptr;
      bool dead = false;
      if (events[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
      if (!dead && (events[i].events & EPOLLIN)) {
        char buf[1 << 16];
        while (c->in.size() < kInHighWater) {
          ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
          if (r > 0) {
            c->in.insert(c->in.end(), buf, buf + r);
          } else if (r == 0) {
            dead = true;
            break;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) dead = true;
            break;
          }
        }
        if (!dead && !process_frames(s, c)) dead = true;
      }
      if (!dead && c->out.size() > c->out_off) {
        while (c->out.size() > c->out_off) {
          ssize_t w2 = send(c->fd, c->out.data() + c->out_off,
                            c->out.size() - c->out_off, MSG_NOSIGNAL);
          if (w2 > 0) {
            c->out_off += (size_t)w2;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) dead = true;
            break;
          }
        }
        if (c->out_off == c->out.size()) {
          c->out.clear();
          c->out_off = 0;
          // backlog drained: serve any requests parked by the high-water
          // mark while we were blocked on the socket
          if (!c->in.empty() && !process_frames(s, c)) dead = true;
        }
      }
      if (dead) {
        close_conn(w, c);
      } else {
        arm(w, c);
      }
    }
  }
}

void accept_loop(Server* s) {
  epoll_event events[8];
  while (!s->stop.load()) {
    int n = epoll_wait(s->accept_epoll_fd, events, 8, 200);
    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {  // wake eventfd
        uint64_t tmp;
        (void)!read(s->accept_wake_fd, &tmp, 8);
        continue;
      }
      while (true) {
        int fd = accept(s->listen_fd, nullptr, nullptr);
        if (fd < 0) break;
        set_nonblock(fd);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // round-robin the connection onto a worker (the reference assigns
        // each channel the next cpu vector, java/RdmaNode.java:222-279)
        Worker& w = s->workers[s->next_worker++ % s->workers.size()];
        {
          std::lock_guard<std::mutex> lk(w.pending_mu);
          w.pending.push_back(fd);
        }
        uint64_t one64 = 1;
        (void)!write(w.wake_fd, &one64, 8);
      }
    }
  }
}

void destroy(Server* s) {
  for (Worker& w : s->workers) {
    if (w.epoll_fd >= 0) close(w.epoll_fd);
    if (w.wake_fd >= 0) close(w.wake_fd);
  }
  if (s->accept_epoll_fd >= 0) close(s->accept_epoll_fd);
  if (s->accept_wake_fd >= 0) close(s->accept_wake_fd);
  if (s->listen_fd >= 0) close(s->listen_fd);
  delete s;
}

}  // namespace

extern "C" {

// host: dotted-quad bind address; empty/null binds loopback. The data port
// serves registered spill bytes unauthenticated, so it binds exactly as
// wide as asked — multi-host deployments pass the control-plane host and
// must firewall the port, same trust model as the reference's verbs
// listener (java/RdmaNode.java:74-88).
// num_threads: epoll worker count (>=1).
// cpus/num_cpus: optional CPU pin list; worker i pins to cpus[i % num_cpus]
// (the reference pins completion threads, java/RdmaThread.java:46-48).
void* bs_create(const char* host, uint16_t port, int num_threads,
                const int* cpus, int num_cpus) {
  Server* s = new Server();
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (host && host[0] &&
      inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  addr.sin_port = htons(port);
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(s->listen_fd, 128) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  set_nonblock(s->listen_fd);

  s->accept_epoll_fd = epoll_create1(0);
  s->accept_wake_fd = eventfd(0, EFD_NONBLOCK);
  if (s->accept_epoll_fd < 0 || s->accept_wake_fd < 0) {
    destroy(s);
    return nullptr;
  }
  epoll_event lev{};
  lev.events = EPOLLIN;
  lev.data.ptr = (void*)s;
  epoll_ctl(s->accept_epoll_fd, EPOLL_CTL_ADD, s->listen_fd, &lev);
  epoll_event wev{};
  wev.events = EPOLLIN;
  wev.data.ptr = nullptr;
  epoll_ctl(s->accept_epoll_fd, EPOLL_CTL_ADD, s->accept_wake_fd, &wev);

  if (num_threads < 1) num_threads = 1;
  s->workers.resize((size_t)num_threads);
  for (Worker& w : s->workers) {
    w.server = s;
    w.epoll_fd = epoll_create1(0);
    w.wake_fd = eventfd(0, EFD_NONBLOCK);
    if (w.epoll_fd < 0 || w.wake_fd < 0) {
      destroy(s);
      return nullptr;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;
    epoll_ctl(w.epoll_fd, EPOLL_CTL_ADD, w.wake_fd, &ev);
  }
  for (size_t i = 0; i < s->workers.size(); ++i) {
    Worker& w = s->workers[i];
    w.th = std::thread(worker_loop, &w);
    if (cpus && num_cpus > 0) {
      int cpu = cpus[i % (size_t)num_cpus];
      if (cpu >= 0 && cpu < CPU_SETSIZE) {  // reject garbage ids: CPU_SET
        cpu_set_t set;                      // with a bad index is UB
        CPU_ZERO(&set);
        CPU_SET(cpu, &set);
        pthread_setaffinity_np(w.th.native_handle(), sizeof(set), &set);
      }
    }
  }
  s->accept_th = std::thread(accept_loop, s);
  return s;
}

uint16_t bs_port(void* handle) { return ((Server*)handle)->port; }

// Toggle per-block CRC32 response trailers (FLAG_CRC32). Plumbed from the
// fetch_checksum config key so both serving paths speak one contract.
void bs_set_checksum(void* handle, int enabled) {
  ((Server*)handle)->checksum.store(enabled != 0);
}

// mmap `path` and serve it under `token`. Returns 0 on success.
int bs_register_file(void* handle, uint32_t token, const char* path) {
  Server* s = (Server*)handle;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -1;
  }
  void* base = nullptr;
  if (st.st_size > 0) {
    base = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (base == MAP_FAILED) {
      close(fd);
      return -1;
    }
  }
  close(fd);
  std::lock_guard<std::mutex> lk(s->files_mu);
  auto it = s->files.find(token);
  if (it != s->files.end() && it->second.base)
    munmap(it->second.base, it->second.size);
  s->files[token] = MappedFile{base, (uint64_t)st.st_size};
  return 0;
}

int bs_unregister_file(void* handle, uint32_t token) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  auto it = s->files.find(token);
  if (it == s->files.end()) return -1;
  if (it->second.base) munmap(it->second.base, it->second.size);
  s->files.erase(it);
  return 0;
}

uint64_t bs_bytes_served(void* handle) {
  return ((Server*)handle)->bytes_served.load();
}

uint64_t bs_requests_served(void* handle) {
  return ((Server*)handle)->requests_served.load();
}

void bs_stop(void* handle) {
  Server* s = (Server*)handle;
  s->stop.store(true);
  uint64_t one = 1;
  (void)!write(s->accept_wake_fd, &one, 8);
  for (Worker& w : s->workers) (void)!write(w.wake_fd, &one, 8);
  if (s->accept_th.joinable()) s->accept_th.join();
  for (Worker& w : s->workers) {
    if (w.th.joinable()) w.th.join();
    for (auto& [fd, c] : w.conns) {
      close(c->fd);
      delete c;
    }
    w.conns.clear();
    // accepted but never registered (stop raced the wake)
    std::lock_guard<std::mutex> lk(w.pending_mu);
    for (int fd : w.pending) close(fd);
    w.pending.clear();
  }
  {
    std::lock_guard<std::mutex> lk(s->files_mu);
    for (auto& [tok, f] : s->files)
      if (f.base) munmap(f.base, f.size);
    s->files.clear();
  }
  destroy(s);
}

}  // extern "C"
