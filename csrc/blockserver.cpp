// Native block server: the executor's data-serving path in C++.
//
// In the reference the serving executor's CPU is NOT in the data path — the
// NIC serves registered memory directly (one-sided READ,
// scala/RdmaShuffleFetcherIterator.scala:171-180 against mmap'd files
// registered in java/RdmaMappedFile.java). On the DCN fallback path this
// framework serves blocks over TCP; this server removes Python from that
// path: an epoll loop in one native thread serves FetchBlocks requests
// straight out of mmap'd spill files (page cache -> socket), with the
// Python control plane only registering (token -> file) mappings.
//
// Wire protocol: byte-compatible with sparkrdma_tpu.parallel.rpc_msg /
// messages — frames of [total:4][type:4][payload], request type 9
// (FetchBlocksReq: req_id q, shuffle_id i, count I, blocks (I,Q,I)*),
// response type 10 (FetchBlocksResp: req_id q, status i, flags i, data).
// Responses always use flags=0 (no compression on the native path).
//
// Exposed as a C ABI for ctypes.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kReqType = 9;
constexpr uint32_t kRespType = 10;
constexpr int32_t kStatusOk = 0;
constexpr int32_t kStatusUnknown = 1;
constexpr int32_t kStatusBadRange = 3;
constexpr size_t kMaxFrame = 1u << 30;
// Hard cap on one response's payload: far above the client's grouped-fetch
// ceiling (shuffle_read_block_size), far below uint32 frame-length wrap and
// the client Reassembler's 1 GiB max_frame. Oversized requests get
// kStatusBadRange instead of a frame the client can't parse (or, past
// 4 GiB, a wrapped out_total that would heap-overflow the out buffer).
constexpr uint64_t kMaxRespPayload = 256ull << 20;
// Stop parsing new requests while this much response data is still
// unwritten: bounds per-connection memory under pipelined clients.
constexpr size_t kOutHighWater = 256u << 20;

struct MappedFile {
  void* base;
  uint64_t size;
};

struct Conn {
  int fd;
  std::vector<uint8_t> in;   // accumulated unparsed bytes
  std::vector<uint8_t> out;  // pending unwritten response bytes
  size_t out_off = 0;
};

struct Server {
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;
  uint16_t port = 0;
  std::thread loop;
  std::atomic<bool> stop{false};
  std::mutex files_mu;
  std::unordered_map<uint32_t, MappedFile> files;
  std::unordered_map<int, Conn*> conns;
  std::atomic<uint64_t> bytes_served{0};
  std::atomic<uint64_t> requests_served{0};
};

void set_nonblock(int fd) {
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

void close_conn(Server* s, Conn* c) {
  epoll_ctl(s->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  s->conns.erase(c->fd);
  delete c;
}

void arm(Server* s, Conn* c) {
  epoll_event ev{};
  ev.events = EPOLLIN | (c->out.size() > c->out_off ? EPOLLOUT : 0u);
  ev.data.ptr = c;
  epoll_ctl(s->epoll_fd, EPOLL_CTL_MOD, c->fd, &ev);
}

// Parse + serve every complete frame in c->in; append responses to c->out.
bool process_frames(Server* s, Conn* c) {
  size_t pos = 0;
  while (c->in.size() - pos >= 8) {
    if (c->out.size() - c->out_off > kOutHighWater) break;  // backpressure
    uint32_t total, type;
    memcpy(&total, c->in.data() + pos, 4);
    memcpy(&type, c->in.data() + pos + 4, 4);
    if (total < 8 || total > kMaxFrame) return false;  // protocol error
    if (c->in.size() - pos < total) break;             // incomplete
    const uint8_t* p = c->in.data() + pos + 8;
    size_t plen = total - 8;
    // this port speaks exactly one request type; anything else is a
    // protocol violation — drop the connection so the client fails fast
    // (a TransportError) instead of timing out on a silently-ignored frame
    if (type != kReqType || plen < 16) return false;
    {
      int64_t req_id;
      uint32_t count;
      memcpy(&req_id, p, 8);
      // p+8..12: shuffle_id (unused server-side: tokens are global)
      memcpy(&count, p + 12, 4);
      const uint8_t* blocks = p + 16;
      int32_t status = kStatusOk;
      uint64_t resp_len = 0;
      if (plen != 16 + (size_t)count * 16) {
        status = kStatusBadRange;
        count = 0;
      }
      std::lock_guard<std::mutex> lk(s->files_mu);
      // validate + size pass
      for (uint32_t i = 0; i < count && status == kStatusOk; ++i) {
        uint32_t token, length;
        uint64_t offset;
        memcpy(&token, blocks + i * 16, 4);
        memcpy(&offset, blocks + i * 16 + 4, 8);
        memcpy(&length, blocks + i * 16 + 12, 4);
        auto it = s->files.find(token);
        if (it == s->files.end()) {
          status = kStatusUnknown;
        } else if (offset > it->second.size ||
                   length > it->second.size - offset) {
          status = kStatusBadRange;
        } else {
          resp_len += length;
        }
      }
      if (resp_len > kMaxRespPayload && status == kStatusOk)
        status = kStatusBadRange;
      if (status != kStatusOk) resp_len = 0;
      // frame: [total][type][req_id q][status i][flags i][data]
      uint32_t out_total = (uint32_t)(8 + 16 + resp_len);
      size_t base = c->out.size();
      c->out.resize(base + out_total);
      uint8_t* o = c->out.data() + base;
      memcpy(o, &out_total, 4);
      memcpy(o + 4, &kRespType, 4);
      memcpy(o + 8, &req_id, 8);
      memcpy(o + 16, &status, 4);
      uint32_t flags = 0;
      memcpy(o + 20, &flags, 4);
      uint8_t* data = o + 24;
      if (status == kStatusOk) {
        for (uint32_t i = 0; i < count; ++i) {
          uint32_t token, length;
          uint64_t offset;
          memcpy(&token, blocks + i * 16, 4);
          memcpy(&offset, blocks + i * 16 + 4, 8);
          memcpy(&length, blocks + i * 16 + 12, 4);
          const MappedFile& f = s->files.at(token);
          memcpy(data, (const char*)f.base + offset, length);
          data += length;
        }
        s->bytes_served += resp_len;
        s->requests_served += 1;
      }
    }
    pos += total;
  }
  if (pos) c->in.erase(c->in.begin(), c->in.begin() + pos);
  return true;
}

void io_loop(Server* s) {
  epoll_event events[64];
  while (!s->stop.load()) {
    int n = epoll_wait(s->epoll_fd, events, 64, 200);
    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {  // wake eventfd
        uint64_t tmp;
        (void)!read(s->wake_fd, &tmp, 8);
        continue;
      }
      if (events[i].data.ptr == (void*)s) {  // listen socket
        while (true) {
          int fd = accept(s->listen_fd, nullptr, nullptr);
          if (fd < 0) break;
          set_nonblock(fd);
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn* c = new Conn{fd, {}, {}, 0};
          s->conns[fd] = c;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = c;
          epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, fd, &ev);
        }
        continue;
      }
      Conn* c = (Conn*)events[i].data.ptr;
      bool dead = false;
      if (events[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
      if (!dead && (events[i].events & EPOLLIN)) {
        char buf[1 << 16];
        while (true) {
          ssize_t r = recv(c->fd, buf, sizeof(buf), 0);
          if (r > 0) {
            c->in.insert(c->in.end(), buf, buf + r);
          } else if (r == 0) {
            dead = true;
            break;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) dead = true;
            break;
          }
        }
        if (!dead && !process_frames(s, c)) dead = true;
      }
      if (!dead && c->out.size() > c->out_off) {
        while (c->out.size() > c->out_off) {
          ssize_t w = send(c->fd, c->out.data() + c->out_off,
                           c->out.size() - c->out_off, MSG_NOSIGNAL);
          if (w > 0) {
            c->out_off += (size_t)w;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) dead = true;
            break;
          }
        }
        if (c->out_off == c->out.size()) {
          c->out.clear();
          c->out_off = 0;
          // backlog drained: serve any requests parked by the high-water
          // mark while we were blocked on the socket
          if (!c->in.empty() && !process_frames(s, c)) dead = true;
        }
      }
      if (dead) {
        close_conn(s, c);
      } else {
        arm(s, c);
      }
    }
  }
}

}  // namespace

extern "C" {

void* bs_create(uint16_t port) {
  Server* s = new Server();
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(s->listen_fd, 128) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  set_nonblock(s->listen_fd);

  s->epoll_fd = epoll_create1(0);
  s->wake_fd = eventfd(0, EFD_NONBLOCK);
  if (s->epoll_fd < 0 || s->wake_fd < 0) {
    if (s->epoll_fd >= 0) close(s->epoll_fd);
    if (s->wake_fd >= 0) close(s->wake_fd);
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = (void*)s;
  epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->listen_fd, &ev);
  epoll_event wev{};
  wev.events = EPOLLIN;
  wev.data.ptr = nullptr;
  epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->wake_fd, &wev);
  s->loop = std::thread(io_loop, s);
  return s;
}

uint16_t bs_port(void* handle) { return ((Server*)handle)->port; }

// mmap `path` and serve it under `token`. Returns 0 on success.
int bs_register_file(void* handle, uint32_t token, const char* path) {
  Server* s = (Server*)handle;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -1;
  }
  void* base = nullptr;
  if (st.st_size > 0) {
    base = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (base == MAP_FAILED) {
      close(fd);
      return -1;
    }
  }
  close(fd);
  std::lock_guard<std::mutex> lk(s->files_mu);
  auto it = s->files.find(token);
  if (it != s->files.end() && it->second.base)
    munmap(it->second.base, it->second.size);
  s->files[token] = MappedFile{base, (uint64_t)st.st_size};
  return 0;
}

int bs_unregister_file(void* handle, uint32_t token) {
  Server* s = (Server*)handle;
  std::lock_guard<std::mutex> lk(s->files_mu);
  auto it = s->files.find(token);
  if (it == s->files.end()) return -1;
  if (it->second.base) munmap(it->second.base, it->second.size);
  s->files.erase(it);
  return 0;
}

uint64_t bs_bytes_served(void* handle) {
  return ((Server*)handle)->bytes_served.load();
}

uint64_t bs_requests_served(void* handle) {
  return ((Server*)handle)->requests_served.load();
}

void bs_stop(void* handle) {
  Server* s = (Server*)handle;
  s->stop.store(true);
  uint64_t one = 1;
  (void)!write(s->wake_fd, &one, 8);
  if (s->loop.joinable()) s->loop.join();
  for (auto& [fd, c] : s->conns) {
    close(c->fd);
    delete c;
  }
  s->conns.clear();
  {
    std::lock_guard<std::mutex> lk(s->files_mu);
    for (auto& [tok, f] : s->files)
      if (f.base) munmap(f.base, f.size);
    s->files.clear();
  }
  close(s->listen_fd);
  close(s->epoll_fd);
  close(s->wake_fd);
  delete s;
}

}  // extern "C"
