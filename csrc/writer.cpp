// Map-side write dataplane: the streaming partition-scatter kernel.
//
// The reference gets its write path for free by wrapping Spark's sort/spill
// machinery (writer/wrapper/RdmaWrapperShuffleWriter.scala:83-99); we own
// that machinery, so the hot inner loop — turning one record batch
// (keys u64[n], payload u8[n, W]) into a partition-contiguous run of
// `key(8B LE) | payload(W B)` rows — is a native O(n) counting-sort scatter
// instead of numpy's close-time argsort. Two passes: count rows per
// destination, prefix offsets, then scatter each row to its partition's
// cursor. Stability (arrival order within a partition) is what makes the
// committed file byte-identical to the monolithic writer, so the parallel
// split is by contiguous row ranges with a two-level (thread x partition)
// prefix: thread t's rows land after thread t-1's rows in every partition.
//
// Exposed as a C ABI for ctypes (runtime/native.py). The numpy fallback in
// shuffle/writer.py produces the identical layout (lockstep-tested).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

void scatter_range(const uint64_t* keys, const uint8_t* payload,
                   uint64_t payload_bytes, const int64_t* dest, uint64_t lo,
                   uint64_t hi, uint8_t* out, uint64_t* cursor) {
  const uint64_t row_bytes = 8 + payload_bytes;
  for (uint64_t i = lo; i < hi; ++i) {
    uint8_t* row = out + cursor[dest[i]];
    cursor[dest[i]] += row_bytes;
    std::memcpy(row, &keys[i], 8);
    if (payload_bytes)
      std::memcpy(row + 8, payload + i * payload_bytes, payload_bytes);
  }
}

}  // namespace

extern "C" {

// Scatter one record batch into a partition-contiguous run buffer.
//   keys:       u64[n] record keys (little-endian in the row format)
//   payload:    u8[n * payload_bytes], row-major
//   dest:       i64[n] destination partition per row
//   out:        u8[n * (8 + payload_bytes)] run buffer (fully overwritten)
//   out_counts: u64[num_partitions], receives per-partition ROW counts
// Returns total bytes written, or -1 if any dest is out of range.
int64_t writer_scatter(const uint64_t* keys, const uint8_t* payload,
                       uint64_t n, uint64_t payload_bytes, const int64_t* dest,
                       uint32_t num_partitions, uint8_t* out,
                       uint64_t* out_counts, int nthreads) {
  const uint64_t row_bytes = 8 + payload_bytes;
  for (uint64_t i = 0; i < n; ++i)
    if (dest[i] < 0 || (uint64_t)dest[i] >= num_partitions) return -1;

  int t = std::max(1, nthreads);
  // below ~1 MiB the two-level prefix costs more than it saves; and the
  // per-thread cursor tables must stay small relative to the batch
  if (n * row_bytes < (1u << 20) || (uint64_t)t * num_partitions > n) t = 1;
  if ((uint64_t)t > n && n > 0) t = (int)n;

  // pass 1: per-thread, per-partition counts over contiguous row slices
  std::vector<std::vector<uint64_t>> counts(
      t, std::vector<uint64_t>(num_partitions, 0));
  const uint64_t per = t ? (n + t - 1) / t : 0;
  auto count_range = [&](int k) {
    const uint64_t lo = k * per, hi = std::min(n, (k + 1) * per);
    for (uint64_t i = lo; i < hi; ++i) counts[k][dest[i]]++;
  };
  if (t == 1) {
    count_range(0);
  } else {
    std::vector<std::thread> threads;
    for (int k = 0; k < t; ++k) threads.emplace_back(count_range, k);
    for (auto& th : threads) th.join();
  }

  // two-level exclusive prefix: partition-major, thread-minor — thread t's
  // rows of partition p start after every earlier thread's rows of p
  std::vector<std::vector<uint64_t>> cursor(
      t, std::vector<uint64_t>(num_partitions, 0));
  uint64_t off = 0;
  for (uint32_t p = 0; p < num_partitions; ++p) {
    uint64_t total_p = 0;
    for (int k = 0; k < t; ++k) {
      cursor[k][p] = off + total_p * row_bytes;
      total_p += counts[k][p];
    }
    out_counts[p] = total_p;
    off += total_p * row_bytes;
  }

  // pass 2: scatter, each thread over its own contiguous slice
  if (t == 1) {
    scatter_range(keys, payload, payload_bytes, dest, 0, n, out,
                  cursor[0].data());
  } else {
    std::vector<std::thread> threads;
    for (int k = 0; k < t; ++k)
      threads.emplace_back(scatter_range, keys, payload, payload_bytes, dest,
                           (uint64_t)k * per,
                           std::min(n, (uint64_t)(k + 1) * per), out,
                           cursor[k].data());
    for (auto& th : threads) th.join();
  }
  return (int64_t)(n * row_bytes);
}

}  // extern "C"
