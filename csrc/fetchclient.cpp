// Native client fetch engine: the receive half of the one-sided dataplane.
//
// blockserver.cpp made the SERVE side constant-time (zero-copy iovec
// windows out of the registered mmap); this file does the same for the
// CLIENT: vectored read requests are doorbell-batched (many frames, one
// writev per connection per flush) and their response payloads land
// DIRECTLY in caller-provided staging — a BufferPool lease's registered
// memory — with the CRC trailer verified here in C. No Python bytes
// object, no intermediate copy: the pointer handed to fc_submit is where
// the wire bytes end up, and the Python side only ever sees (token,
// offset, length) views over memory that is already DMA-able.
//
// One engine instance belongs to ONE thread (the fetcher's peer loop, a
// pusher, or the DCN cross-slice mover); there are no locks. All three
// bulk movers share this submission/completion loop: block fetches use
// fc_submit (typed: req_id-matched, CRC-checked, scattered into the
// lease), planned-push sends and other pre-framed RPCs use fc_submit_raw
// (FIFO-matched per connection, payload into a small reply buffer).
//
// Failure philosophy mirrors the server's: any malformed, truncated, or
// unmatched frame KILLS the connection and fails every in-flight request
// on it with a local (negative) status — the Python caller re-runs those
// requests down the ordinary retry/suspect/checksum envelope, so the
// native engine only ever completes the happy path and anomalies stay
// byte-identical with the pure-Python fetcher.
//
// Where liburing is present at build time the bulk payload read uses an
// io_uring submit-and-wait readv (the staging the payload lands in is
// the pool's registered arena, so a fixed-buffer registration maps 1:1
// onto the lease tokens); the portable fallback is plain readv on the
// same nonblocking fd — identical semantics, one extra syscall per
// wakeup.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

#if defined(__has_include)
#if __has_include(<liburing.h>)
#include <liburing.h>
#define FC_HAVE_IO_URING 1
#endif
#endif
#ifndef FC_HAVE_IO_URING
#define FC_HAVE_IO_URING 0
#endif

namespace {

// Wire constants — lockstep-checked against parallel/messages.py by
// analysis/wire.py (same frame the server parses: [total:4][type:4]
// includes the 8-byte header in total).
constexpr uint32_t kReqType = 9;        // messages.FetchBlocksReq
constexpr uint32_t kRespType = 10;      // messages.FetchBlocksResp
constexpr int32_t kStatusOk = 0;        // messages.STATUS_OK
constexpr uint32_t kFlagCrc32 = 4;      // messages.FLAG_CRC32
constexpr size_t kMaxReqFrame = 1u << 20;
constexpr uint64_t kMaxRespPayload = 256ull << 20;
constexpr uint32_t kReqFixedBytes = 24;   // hdr 8 + req_id 8 + shuffle 4 + n 4
constexpr uint32_t kRespFixedBytes = 24;  // hdr 8 + req_id 8 + status 4 + flags 4
constexpr uint32_t kBlockWireBytes = 16;  // (buf u32, offset u64, length u32)
// Client-side tuning, never on the wire: frames per writev doorbell and
// the in-flight request cap per connection (the server defers at its own
// kMaxPendingPerConn; staying at or below it means a doorbell burst can
// never trip the server's backpressure break).
constexpr int kMaxSendIov = 64;
constexpr uint32_t kMaxPendingPerConn = 4096;

// Local completion statuses (negative: disjoint from server statuses by
// construction). All of them mean "this connection died and every
// request on it must be re-run through the Python envelope".
constexpr int32_t kErrConn = -100;   // EOF / reset / connect failure
constexpr int32_t kErrProto = -101;  // malformed frame or unmatched req_id
constexpr int32_t kErrTrunc = -102;  // payload length != requested length

// -- CRC32 (IEEE, zlib-compatible) — same slice-by-8 idiom as the block
// server. The client verifies whole response payloads in one pass, so
// checksum speed is directly on the wire->device critical path: slice-
// by-8 folds eight bytes per step where the byte chain does one.

struct Crc32Table {
  uint32_t t[8][256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int j = 1; j < 8; ++j)
        t[j][i] = t[0][t[j - 1][i] & 0xFF] ^ (t[j - 1][i] >> 8);
  }
};

uint32_t crc32_ieee(const uint8_t* p, size_t n) {
  static const Crc32Table tbl;
  uint32_t c = 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo, hi;  // memcpy: alignment-safe (UBSan) and little-endian
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= c;
    c = tbl.t[7][lo & 0xFF] ^ tbl.t[6][(lo >> 8) & 0xFF] ^
        tbl.t[5][(lo >> 16) & 0xFF] ^ tbl.t[4][lo >> 24] ^
        tbl.t[3][hi & 0xFF] ^ tbl.t[2][(hi >> 8) & 0xFF] ^
        tbl.t[1][(hi >> 16) & 0xFF] ^ tbl.t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  for (size_t i = 0; i < n; ++i)
    c = tbl.t[0][(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// -- engine structures --------------------------------------------------

struct Pending {
  uint64_t req_id = 0;
  uint8_t* dst = nullptr;           // where the payload lands (lease memory)
  uint64_t cap = 0;
  uint64_t expect = 0;              // block mode: sum of block lengths
  std::vector<uint32_t> lens;       // block mode: CRC trailer delimiters
  bool raw = false;
};

enum Phase : uint8_t { PH_HDR, PH_DATA };

struct FcConn {
  int64_t id = 0;
  int fd = -1;
  bool raw = false;
  bool dead = false;
  bool want_write = false;
  // outbound: frames queued by fc_submit*, sent by fc_flush (the
  // doorbell) as ONE writev per connection per flush
  std::deque<std::string> outq;
  size_t out_off = 0;
  // inbound frame state machine
  Phase phase = PH_HDR;
  uint8_t hdr[kRespFixedBytes];
  uint32_t hdr_need = 8, hdr_got = 0;
  uint32_t ftotal = 0, ftype = 0, fflags = 0;
  int32_t fstatus = 0;
  uint64_t fdata = 0, data_got = 0;
  std::vector<uint8_t> trailer;
  uint64_t tr_got = 0;
  Pending* cur = nullptr;           // detached from the tables below
  std::unordered_map<uint64_t, Pending*> by_id;  // block mode
  std::deque<Pending*> fifo;        // raw mode (in-order replies)
};

}  // namespace

extern "C" {

// One completion record per finished request. ``status`` is the server's
// status for well-formed responses and a negative local code when the
// connection died under the request. ``crc_state``: 0 = no trailer on
// the response, 1 = every block's CRC verified, -1 = at least one block
// mismatched (the payload is in dst either way; the caller discards and
// refetches through the Python envelope, which re-raises ChecksumError
// with precise per-block blame).
struct FcCompletion {
  int64_t conn_id;
  uint64_t req_id;
  int64_t nbytes;
  int32_t status;
  uint32_t flags;
  int32_t crc_state;
  uint32_t frame_type;
};

}  // extern "C"

namespace {

struct FcEngine {
  int ep = -1;
  int64_t next_conn = 1;
  std::unordered_map<int64_t, FcConn*> conns;
  std::deque<FcCompletion> done;
  // doorbell stats: batching is observable (frames_sent / flush_calls
  // is the achieved batch factor; writevs counts actual syscalls)
  uint64_t flush_calls = 0;
  uint64_t writevs = 0;
  uint64_t frames_sent = 0;
  uint64_t conns_killed = 0;
#if FC_HAVE_IO_URING
  struct io_uring ring;
  bool ring_ok = false;
#endif
};

#if FC_HAVE_IO_URING
// Fixed-buffer receive where available: one inline submit-and-wait readv
// through the ring. The destination is the BufferPool arena (already
// long-lived, page-aligned registered staging), so a registered-buffer
// upgrade is a straight swap to io_uring_prep_read_fixed keyed by lease
// token. -EAGAIN maps onto the portable fallback's nonblocking contract.
ssize_t fc_readv(FcEngine* e, int fd, struct iovec* iov, int n) {
  if (!e->ring_ok) return readv(fd, iov, n);
  struct io_uring_sqe* sqe = io_uring_get_sqe(&e->ring);
  if (!sqe) return readv(fd, iov, n);
  io_uring_prep_readv(sqe, fd, iov, n, 0);
  struct io_uring_cqe* cqe = nullptr;
  if (io_uring_submit_and_wait(&e->ring, 1) < 0 ||
      io_uring_wait_cqe(&e->ring, &cqe) != 0)
    return readv(fd, iov, n);
  ssize_t res = cqe->res;
  io_uring_cqe_seen(&e->ring, cqe);
  if (res < 0) {
    errno = (int)-res;
    return -1;
  }
  return res;
}
#else
ssize_t fc_readv(FcEngine*, int fd, struct iovec* iov, int n) {
  return readv(fd, iov, n);
}
#endif

void push_completion(FcEngine* e, FcConn* c, Pending* p, int32_t status,
                     int32_t crc_state, uint64_t nbytes, uint32_t ftype) {
  FcCompletion fc;
  fc.conn_id = c->id;
  fc.req_id = p ? p->req_id : 0;
  fc.nbytes = (int64_t)nbytes;
  fc.status = status;
  fc.flags = c->fflags;
  fc.crc_state = crc_state;
  fc.frame_type = ftype;
  e->done.push_back(fc);
}

// Tear the connection down and fail every in-flight request on it with
// ``status`` — the client-side analogue of the server's "protocol error
// drops the connection so the peer fails fast instead of timing out".
void kill_conn(FcEngine* e, FcConn* c, int32_t status) {
  if (c->dead) return;
  c->dead = true;
  e->conns_killed += 1;
  if (c->fd >= 0) {
    epoll_ctl(e->ep, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
    c->fd = -1;
  }
  c->fflags = 0;
  if (c->cur) {
    push_completion(e, c, c->cur, status, 0, 0, c->ftype);
    delete c->cur;
    c->cur = nullptr;
    status = kErrConn;  // the rest never started arriving
  }
  for (auto& kv : c->by_id) {
    push_completion(e, c, kv.second, status, 0, 0, 0);
    delete kv.second;
  }
  c->by_id.clear();
  for (Pending* p : c->fifo) {
    push_completion(e, c, p, status, 0, 0, 0);
    delete p;
  }
  c->fifo.clear();
  c->outq.clear();
  c->out_off = 0;
}

// Finish the current frame: verify the CRC trailer against the
// request's own block layout (the lengths fc_submit recorded), emit the
// completion, reset the state machine for the next frame.
void finish_frame(FcEngine* e, FcConn* c) {
  Pending* p = c->cur;
  c->cur = nullptr;
  int32_t crc_state = 0;
  if (!p->raw && (c->fflags & kFlagCrc32) && !c->trailer.empty()) {
    crc_state = 1;
    uint64_t off = 0;
    for (size_t i = 0; i < p->lens.size(); ++i) {
      uint32_t want;
      memcpy(&want, c->trailer.data() + 4 * i, 4);
      if (crc32_ieee(p->dst + off, p->lens[i]) != want) {
        crc_state = -1;
        break;
      }
      off += p->lens[i];
    }
  }
  push_completion(e, c, p, p->raw ? kStatusOk : c->fstatus, crc_state,
                  c->fdata, c->ftype);
  delete p;
  c->phase = PH_HDR;
  c->hdr_need = 8;
  c->hdr_got = 0;
  c->fflags = 0;
  c->trailer.clear();
  c->tr_got = 0;
  c->data_got = 0;
  c->fdata = 0;
}

// Header(s) complete: match the frame to its pending request and size
// the payload read. Returns false when the connection must die.
bool dispatch_frame(FcEngine* e, FcConn* c) {
  memcpy(&c->ftotal, c->hdr, 4);
  memcpy(&c->ftype, c->hdr + 4, 4);
  if (c->ftotal < 8 || (uint64_t)c->ftotal > kRespFixedBytes + kMaxRespPayload)
    return false;
  if (c->raw) {
    // pre-framed RPCs reply in submit order on one connection
    if (c->fifo.empty()) return false;  // unsolicited frame
    c->cur = c->fifo.front();
    c->fifo.pop_front();
    c->fdata = c->ftotal - 8;
    c->fstatus = kStatusOk;
    if (c->fdata > c->cur->cap) return false;  // reply overflows its buffer
    return true;
  }
  if (c->ftype != kRespType || c->ftotal < kRespFixedBytes) return false;
  if (c->hdr_need < kRespFixedBytes) {
    // frame header parsed; now collect the fixed response head
    c->hdr_need = kRespFixedBytes;
    return true;
  }
  uint64_t req_id;
  memcpy(&req_id, c->hdr + 8, 8);
  memcpy(&c->fstatus, c->hdr + 16, 4);
  memcpy(&c->fflags, c->hdr + 20, 4);
  auto it = c->by_id.find(req_id);
  if (it == c->by_id.end()) return false;  // unknown req_id
  c->cur = it->second;
  c->by_id.erase(it);
  uint64_t trailer_len =
      (c->fflags & kFlagCrc32) ? 4ull * c->cur->lens.size() : 0;
  uint64_t payload = c->ftotal - kRespFixedBytes;
  if (payload < trailer_len) return false;
  c->fdata = payload - trailer_len;
  // a well-formed OK response carries EXACTLY the requested bytes; an
  // error response carries none — anything else is truncation/overflow
  if (c->fstatus == kStatusOk ? c->fdata != c->cur->expect : c->fdata != 0) {
    // fail just this request precisely, then drop the conn (resync
    // after a length lie is not worth trusting the stream)
    push_completion(e, c, c->cur, kErrTrunc, 0, 0, c->ftype);
    delete c->cur;
    c->cur = nullptr;
    return false;
  }
  c->trailer.resize(trailer_len);
  return true;
}

// Drain everything readable on the connection: headers via read(),
// payload + trailer via ONE vectored read straight into lease memory.
void on_readable(FcEngine* e, FcConn* c) {
  while (!c->dead) {
    if (c->phase == PH_HDR) {
      ssize_t n = read(c->fd, c->hdr + c->hdr_got, c->hdr_need - c->hdr_got);
      if (n == 0) return kill_conn(e, c, kErrConn);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        if (errno == EINTR) continue;
        return kill_conn(e, c, kErrConn);
      }
      c->hdr_got += (uint32_t)n;
      if (c->hdr_got < c->hdr_need) continue;
      if (!dispatch_frame(e, c)) return kill_conn(e, c, kErrProto);
      if (c->cur == nullptr) continue;  // block mode: fixed head pending
      c->phase = PH_DATA;
      if (c->fdata == 0 && c->trailer.empty()) finish_frame(e, c);
      continue;
    }
    // PH_DATA: payload into the pending's destination, CRC trailer into
    // the side buffer, both in one readv
    struct iovec iov[2];
    int niov = 0;
    if (c->data_got < c->fdata) {
      iov[niov].iov_base = c->cur->dst + c->data_got;
      iov[niov].iov_len = (size_t)(c->fdata - c->data_got);
      ++niov;
    }
    if (c->tr_got < c->trailer.size()) {
      iov[niov].iov_base = c->trailer.data() + c->tr_got;
      iov[niov].iov_len = c->trailer.size() - c->tr_got;
      ++niov;
    }
    ssize_t n = fc_readv(e, c->fd, iov, niov);
    if (n == 0) return kill_conn(e, c, kErrConn);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      if (errno == EINTR) continue;
      return kill_conn(e, c, kErrConn);
    }
    uint64_t got = (uint64_t)n;
    uint64_t into_data = c->fdata - c->data_got;
    if (into_data > got) into_data = got;
    c->data_got += into_data;
    c->tr_got += got - into_data;
    if (c->data_got == c->fdata && c->tr_got == c->trailer.size())
      finish_frame(e, c);
  }
}

// Send queued frames: up to kMaxSendIov frames per writev (the doorbell
// batch), partial writes resumed from out_off, EAGAIN arms EPOLLOUT.
void flush_conn(FcEngine* e, FcConn* c) {
  while (!c->dead && !c->outq.empty()) {
    struct iovec iov[kMaxSendIov];
    int niov = 0;
    size_t off = c->out_off;
    for (auto it = c->outq.begin();
         it != c->outq.end() && niov < kMaxSendIov; ++it) {
      iov[niov].iov_base = (void*)(it->data() + off);
      iov[niov].iov_len = it->size() - off;
      ++niov;
      off = 0;
    }
    ssize_t n = writev(c->fd, iov, niov);
    e->writevs += 1;
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (!c->want_write) {
          struct epoll_event ev;
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.ptr = c;
          epoll_ctl(e->ep, EPOLL_CTL_MOD, c->fd, &ev);
          c->want_write = true;
        }
        return;
      }
      return kill_conn(e, c, kErrConn);
    }
    size_t left = (size_t)n;
    while (left > 0 && !c->outq.empty()) {
      size_t front_left = c->outq.front().size() - c->out_off;
      if (left >= front_left) {
        left -= front_left;
        c->outq.pop_front();
        c->out_off = 0;
        e->frames_sent += 1;
      } else {
        c->out_off += left;
        left = 0;
      }
    }
  }
  if (!c->dead && c->want_write && c->outq.empty()) {
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.ptr = c;
    epoll_ctl(e->ep, EPOLL_CTL_MOD, c->fd, &ev);
    c->want_write = false;
  }
}

void pump_events(FcEngine* e, int timeout_ms) {
  struct epoll_event evs[64];
  int n = epoll_wait(e->ep, evs, 64, timeout_ms);
  for (int i = 0; i < n; ++i) {
    FcConn* c = (FcConn*)evs[i].data.ptr;
    if (c->dead) continue;
    if (evs[i].events & (EPOLLIN | EPOLLHUP | EPOLLERR)) on_readable(e, c);
    if (!c->dead && (evs[i].events & EPOLLOUT)) flush_conn(e, c);
  }
}

FcConn* get_conn(FcEngine* e, int64_t id) {
  auto it = e->conns.find(id);
  return it == e->conns.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

void* fc_create(void) {
  FcEngine* e = new FcEngine();
  e->ep = epoll_create1(EPOLL_CLOEXEC);
  if (e->ep < 0) {
    delete e;
    return nullptr;
  }
#if FC_HAVE_IO_URING
  e->ring_ok = io_uring_queue_init(64, &e->ring, 0) == 0;
#endif
  return e;
}

int fc_io_uring(void* eng) {
#if FC_HAVE_IO_URING
  return ((FcEngine*)eng)->ring_ok ? 1 : 0;
#else
  (void)eng;
  return 0;
#endif
}

// Connect (bounded by timeout_ms) and register with the event loop.
// raw_mode = 1 for pre-framed RPC connections (planned-push sends, the
// DCN movers), 0 for block-fetch connections. Returns a conn id > 0,
// or 0 on failure.
int64_t fc_connect(void* eng, const char* host, uint16_t port, int raw_mode,
                   int timeout_ms) {
  FcEngine* e = (FcEngine*)eng;
  char portbuf[16];
  snprintf(portbuf, sizeof(portbuf), "%u", (unsigned)port);
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || res == nullptr)
    return 0;
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype | SOCK_NONBLOCK | SOCK_CLOEXEC,
                ai->ai_protocol);
    if (fd < 0) continue;
    int rc = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc == 0) break;
    if (errno == EINPROGRESS) {
      struct pollfd pfd;
      pfd.fd = fd;
      pfd.events = POLLOUT;
      if (poll(&pfd, 1, timeout_ms) == 1) {
        int err = 0;
        socklen_t len = sizeof(err);
        if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) == 0 && err == 0)
          break;
      }
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return 0;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  FcConn* c = new FcConn();
  c->id = e->next_conn++;
  c->fd = fd;
  c->raw = raw_mode != 0;
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.ptr = c;
  if (epoll_ctl(e->ep, EPOLL_CTL_ADD, fd, &ev) != 0) {
    close(fd);
    delete c;
    return 0;
  }
  e->conns[c->id] = c;
  return c->id;
}

// Queue one vectored block-read request. ``blocks_wire`` is the already
// wire-packed (buf:u32, offset:u64, length:u32) * n_blocks range array
// (the exact bytes messages.FetchBlocksReq carries). The response
// payload lands at ``dst`` (must hold the sum of the lengths). Nothing
// goes on the wire until fc_flush — the doorbell.
int fc_submit(void* eng, int64_t conn, uint64_t req_id, uint32_t shuffle_id,
              const uint8_t* blocks_wire, uint32_t n_blocks, void* dst,
              uint64_t dst_cap) {
  FcEngine* e = (FcEngine*)eng;
  FcConn* c = get_conn(e, conn);
  if (c == nullptr || c->dead || c->raw) return -1;
  uint64_t total = (uint64_t)kReqFixedBytes + (uint64_t)n_blocks * kBlockWireBytes;
  if (total > kMaxReqFrame) return -2;
  if (c->by_id.size() >= kMaxPendingPerConn) return -3;
  if (c->by_id.count(req_id)) return -4;
  Pending* p = new Pending();
  p->req_id = req_id;
  p->dst = (uint8_t*)dst;
  p->cap = dst_cap;
  p->lens.resize(n_blocks);
  for (uint32_t i = 0; i < n_blocks; ++i) {
    uint32_t len;
    memcpy(&len, blocks_wire + i * kBlockWireBytes + 12, 4);
    p->lens[i] = len;
    p->expect += len;
  }
  if (p->expect > dst_cap) {
    delete p;
    return -5;
  }
  std::string frame;
  frame.resize(total);
  char* f = &frame[0];
  uint32_t total32 = (uint32_t)total;
  memcpy(f, &total32, 4);
  memcpy(f + 4, &kReqType, 4);
  memcpy(f + 8, &req_id, 8);
  memcpy(f + 16, &shuffle_id, 4);
  memcpy(f + 20, &n_blocks, 4);
  memcpy(f + 24, blocks_wire, (size_t)n_blocks * kBlockWireBytes);
  c->outq.push_back(std::move(frame));
  c->by_id[req_id] = p;
  return 0;
}

// Queue one pre-framed request (planned-push send, DCN mover, any
// messages.py frame) on a raw-mode connection. The reply frame's
// payload (everything past the 8-byte header) is copied into ``dst``;
// replies match pending requests FIFO per connection. ``req_id`` is
// only for the completion record — the wire already carries its own.
int fc_submit_raw(void* eng, int64_t conn, uint64_t req_id,
                  const uint8_t* frame, uint64_t frame_len, void* dst,
                  uint64_t dst_cap) {
  FcEngine* e = (FcEngine*)eng;
  FcConn* c = get_conn(e, conn);
  if (c == nullptr || c->dead || !c->raw) return -1;
  if (frame_len < 8) return -2;
  if (c->fifo.size() >= kMaxPendingPerConn) return -3;
  Pending* p = new Pending();
  p->req_id = req_id;
  p->dst = (uint8_t*)dst;
  p->cap = dst_cap;
  p->raw = true;
  c->outq.push_back(std::string((const char*)frame, (size_t)frame_len));
  c->fifo.push_back(p);
  return 0;
}

// The doorbell: push every queued frame on every connection — one
// writev per connection per call covers the whole batch.
int fc_flush(void* eng) {
  FcEngine* e = (FcEngine*)eng;
  e->flush_calls += 1;
  for (auto& kv : e->conns) {
    FcConn* c = kv.second;
    if (!c->dead && !c->outq.empty() && !c->want_write) flush_conn(e, c);
  }
  return 0;
}

// Collect completions: waits up to timeout_ms for I/O when none are
// queued, otherwise just makes nonblocking progress. Returns the number
// of completion records written to out (<= max_out).
int fc_poll(void* eng, int timeout_ms, struct FcCompletion* out,
            int max_out) {
  FcEngine* e = (FcEngine*)eng;
  if (max_out <= 0) return 0;
  pump_events(e, e->done.empty() ? timeout_ms : 0);
  int n = 0;
  while (n < max_out && !e->done.empty()) {
    out[n++] = e->done.front();
    e->done.pop_front();
  }
  return n;
}

// Outstanding (submitted, not yet completed) requests on one connection,
// or -1 for an unknown conn id. Dead connections report 0 — their
// pendings were already failed into the completion queue.
int64_t fc_pending(void* eng, int64_t conn) {
  FcConn* c = get_conn((FcEngine*)eng, conn);
  if (c == nullptr) return -1;
  return (int64_t)(c->by_id.size() + c->fifo.size());
}

int fc_conn_alive(void* eng, int64_t conn) {
  FcConn* c = get_conn((FcEngine*)eng, conn);
  return (c != nullptr && !c->dead) ? 1 : 0;
}

uint64_t fc_flush_count(void* eng) { return ((FcEngine*)eng)->flush_calls; }
uint64_t fc_writev_count(void* eng) { return ((FcEngine*)eng)->writevs; }
uint64_t fc_frames_sent(void* eng) { return ((FcEngine*)eng)->frames_sent; }
uint64_t fc_conns_killed(void* eng) { return ((FcEngine*)eng)->conns_killed; }

void fc_close(void* eng, int64_t conn) {
  FcEngine* e = (FcEngine*)eng;
  FcConn* c = get_conn(e, conn);
  if (c == nullptr) return;
  kill_conn(e, c, kErrConn);
  e->conns.erase(conn);
  delete c;
}

void fc_destroy(void* eng) {
  FcEngine* e = (FcEngine*)eng;
  for (auto& kv : e->conns) {
    kill_conn(e, kv.second, kErrConn);
    delete kv.second;
  }
  e->conns.clear();
#if FC_HAVE_IO_URING
  if (e->ring_ok) io_uring_queue_exit(&e->ring);
#endif
  if (e->ep >= 0) close(e->ep);
  delete e;
}

}  // extern "C"
