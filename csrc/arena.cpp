// Host-memory arena pool for the TPU shuffle runtime.
//
// Native equivalent of the reference's registered-memory layer
// (java/RdmaBufferManager.java + java/RdmaBuffer.java behind libdisni):
//  * power-of-two size bins with a configurable minimum block size
//    (RdmaBufferManager.java:93,147-161),
//  * preallocation that carves many buffers out of one large region
//    (RdmaBufferManager.java:124-135; <=2 GiB per region),
//  * LRU trim when idle bytes exceed 90% of the allocation budget,
//    freeing down to 65% (RdmaBufferManager.java:169-211),
//  * allocation statistics dumped at stop (RdmaBufferManager.java:217-231),
//  * zero-fill on hand-out so stale bytes never leak across leases
//    (RdmaBuffer.java:74-76).
//
// There is no NIC, so "registration" here means: page-aligned, madvise'd
// host memory suitable as a DMA staging source for host->HBM transfers.
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

#include <sys/mman.h>
#include <unistd.h>

namespace {

struct Buffer {
  void* ptr = nullptr;
  uint64_t size = 0;          // usable size (the bin size)
  int32_t bin = -1;
  bool carved = false;        // part of a preallocated region: not individually freeable
  bool in_use = false;
  uint64_t last_free_seq = 0; // LRU ordering for trim
};

struct Region {  // one big preallocated carve source
  void* ptr;
  uint64_t size;
};

struct BinStats {
  uint64_t gets = 0;
  uint64_t puts = 0;
  uint64_t fresh_allocs = 0;
  uint64_t trims = 0;
};

struct Arena {
  std::mutex mu;
  uint64_t max_alloc_bytes;
  uint64_t min_block;
  int zero_on_get;
  std::vector<Buffer> bufs;               // id -> buffer
  std::vector<std::vector<uint64_t>> free_stacks;  // bin -> ids (stack: hot reuse)
  std::vector<Region> regions;
  std::vector<BinStats> stats;
  uint64_t total_bytes = 0;   // all live allocations owned by the arena
  uint64_t idle_bytes = 0;    // bytes sitting in free stacks
  uint64_t free_seq = 0;
};

constexpr uint64_t kMaxRegion = 1ull << 31;  // 2 GiB per carve region, ref RdmaBufferManager.java:124-135

int bin_of(const Arena* a, uint64_t size) {
  uint64_t s = std::max(size, a->min_block);
  int bin = 0;
  uint64_t b = a->min_block;
  while (b < s) { b <<= 1; bin++; }
  return bin;
}

uint64_t bin_size(const Arena* a, int bin) { return a->min_block << bin; }

void ensure_bin(Arena* a, int bin) {
  if ((int)a->free_stacks.size() <= bin) {
    a->free_stacks.resize(bin + 1);
    a->stats.resize(bin + 1);
  }
}

void* alloc_aligned(uint64_t size) {
  const long page = sysconf(_SC_PAGESIZE);
  void* p = nullptr;
  if (posix_memalign(&p, (size_t)page, size) != 0) return nullptr;
#ifdef MADV_HUGEPAGE
  if (size >= (2u << 20)) madvise(p, size, MADV_HUGEPAGE);
#endif
  return p;
}

// Trim idle buffers, oldest-free first, until idle <= target. Caller holds mu.
// Reference policy: trigger >90% of budget idle, clean to 65%
// (RdmaBufferManager.java:169-211).
void trim_locked(Arena* a, uint64_t target_idle) {
  // Collect (seq, id) of non-carved idle buffers.
  std::vector<std::pair<uint64_t, uint64_t>> idle;
  for (uint64_t id = 0; id < a->bufs.size(); ++id) {
    Buffer& b = a->bufs[id];
    if (!b.in_use && b.ptr && !b.carved) idle.emplace_back(b.last_free_seq, id);
  }
  std::sort(idle.begin(), idle.end());
  for (auto& [seq, id] : idle) {
    if (a->idle_bytes <= target_idle) break;
    Buffer& b = a->bufs[id];
    auto& stack = a->free_stacks[b.bin];
    auto it = std::find(stack.begin(), stack.end(), id);
    if (it == stack.end()) continue;  // defensive; shouldn't happen
    stack.erase(it);
    a->idle_bytes -= b.size;
    a->total_bytes -= b.size;
    a->stats[b.bin].trims++;
    free(b.ptr);
    b.ptr = nullptr;
    b.bin = -1;
  }
}

}  // namespace

extern "C" {

void* arena_create(uint64_t max_alloc_bytes, uint64_t min_block, int zero_on_get) {
  Arena* a = new Arena();
  a->max_alloc_bytes = max_alloc_bytes ? max_alloc_bytes : (10ull << 30);
  uint64_t mb = min_block ? min_block : (16ull << 10);
  // round min block to a power of two
  uint64_t p = 256;
  while (p < mb) p <<= 1;
  a->min_block = p;
  a->zero_on_get = zero_on_get;
  return a;
}

// Returns buffer id (>=0) or -1 on allocation failure.
int64_t arena_get(void* handle, uint64_t size) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  int bin = bin_of(a, size);
  ensure_bin(a, bin);
  a->stats[bin].gets++;
  uint64_t id;
  if (!a->free_stacks[bin].empty()) {
    id = a->free_stacks[bin].back();
    a->free_stacks[bin].pop_back();
    a->idle_bytes -= a->bufs[id].size;
  } else {
    uint64_t sz = bin_size(a, bin);
    void* p = alloc_aligned(sz);
    if (!p) return -1;
    a->stats[bin].fresh_allocs++;
    a->total_bytes += sz;
    id = a->bufs.size();
    a->bufs.push_back(Buffer{p, sz, bin, /*carved=*/false, /*in_use=*/true, 0});
    if (a->zero_on_get) memset(p, 0, sz);
    return (int64_t)id;
  }
  Buffer& b = a->bufs[id];
  b.in_use = true;
  if (a->zero_on_get) memset(b.ptr, 0, b.size);
  return (int64_t)id;
}

// Return a buffer to its bin; may trigger the idle trim.
int arena_put(void* handle, int64_t id) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  if (id < 0 || (uint64_t)id >= a->bufs.size()) return -1;
  Buffer& b = a->bufs[id];
  if (!b.in_use || !b.ptr) return -2;  // double-put or trimmed
  b.in_use = false;
  b.last_free_seq = ++a->free_seq;
  a->free_stacks[b.bin].push_back((uint64_t)id);
  a->idle_bytes += b.size;
  a->stats[b.bin].puts++;
  if (a->idle_bytes > a->max_alloc_bytes * 9 / 10)
    trim_locked(a, a->max_alloc_bytes * 65 / 100);
  return 0;
}

// Carve `count` buffers of `size` (rounded up to a bin size) out of as few
// large regions as possible; push them all onto the free stack.
int arena_preallocate(void* handle, uint64_t size, uint64_t count) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  int bin = bin_of(a, size);
  ensure_bin(a, bin);
  uint64_t sz = bin_size(a, bin);
  uint64_t per_region = std::max<uint64_t>(1, kMaxRegion / sz);
  uint64_t remaining = count;
  while (remaining > 0) {
    uint64_t n = std::min(per_region, remaining);
    void* p = alloc_aligned(n * sz);
    if (!p) return -1;
    memset(p, 0, n * sz);
    a->regions.push_back(Region{p, n * sz});
    a->total_bytes += n * sz;
    for (uint64_t i = 0; i < n; ++i) {
      uint64_t id = a->bufs.size();
      a->bufs.push_back(Buffer{(char*)p + i * sz, sz, bin, /*carved=*/true,
                               /*in_use=*/false, ++a->free_seq});
      a->free_stacks[bin].push_back(id);
      a->idle_bytes += sz;
    }
    remaining -= n;
  }
  return 0;
}

void* arena_buf_ptr(void* handle, int64_t id) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  if (id < 0 || (uint64_t)id >= a->bufs.size()) return nullptr;
  return a->bufs[id].ptr;
}

uint64_t arena_buf_size(void* handle, int64_t id) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  if (id < 0 || (uint64_t)id >= a->bufs.size()) return 0;
  return a->bufs[id].size;
}

uint64_t arena_total_bytes(void* handle) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  return a->total_bytes;
}

uint64_t arena_idle_bytes(void* handle) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  return a->idle_bytes;
}

// Manual trim to `target_idle` idle bytes (0 = free everything idle).
void arena_trim(void* handle, uint64_t target_idle) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  trim_locked(a, target_idle);
}

// JSON stats into caller buffer; returns bytes written (excl. NUL), or the
// required size if cap is too small. Reference: alloc-stats dump at stop
// (RdmaBufferManager.java:217-231).
int arena_stats_json(void* handle, char* out, int cap) {
  Arena* a = (Arena*)handle;
  std::lock_guard<std::mutex> lk(a->mu);
  std::string s = "{\"total_bytes\":" + std::to_string(a->total_bytes) +
                  ",\"idle_bytes\":" + std::to_string(a->idle_bytes) + ",\"bins\":[";
  for (size_t bin = 0; bin < a->stats.size(); ++bin) {
    const BinStats& st = a->stats[bin];
    if (bin) s += ",";
    s += "{\"size\":" + std::to_string(bin_size(a, (int)bin)) +
         ",\"gets\":" + std::to_string(st.gets) +
         ",\"puts\":" + std::to_string(st.puts) +
         ",\"fresh\":" + std::to_string(st.fresh_allocs) +
         ",\"trimmed\":" + std::to_string(st.trims) + "}";
  }
  s += "]}";
  if ((int)s.size() + 1 <= cap) {
    memcpy(out, s.c_str(), s.size() + 1);
    return (int)s.size();
  }
  return (int)s.size() + 1;
}

void arena_destroy(void* handle) {
  Arena* a = (Arena*)handle;
  {
    std::lock_guard<std::mutex> lk(a->mu);
    for (Buffer& b : a->bufs)
      if (b.ptr && !b.carved) free(b.ptr);
    for (Region& r : a->regions) free(r.ptr);
  }
  delete a;
}

}  // extern "C"
