// Spill-file staging engine for the TPU shuffle runtime.
//
// Native equivalent of the reference's zero-copy file serving layer
// (java/RdmaMappedFile.java): the reference mmaps the committed shuffle data
// file in partition-aligned chunks and registers each mapping as an RDMA MR
// so remote NICs can READ partition bytes directly (RdmaMappedFile.java:
// 113-157, 163-189). A TPU has no NIC in the loop; the equivalent hot path
// is: mmap the spill file, then gather the selected (offset, length) block
// list into one contiguous, page-aligned staging buffer with a multithreaded
// memcpy — i.e. the scatter-READ of many blocks into one registered buffer
// (RdmaShuffleFetcherIterator.scala:119-180) performed by host cores at
// memory bandwidth, after which a single host->HBM DMA moves it on-device.
//
// Exposed as a C ABI for ctypes.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapped {
  void* base;
  uint64_t size;
};

// Shared gather core: pack n blocks (src_offsets[i], lengths[i]) from `base`
// back-to-back into dst, splitting the block list across threads at roughly
// equal byte counts. Caller has already bounds-checked the blocks.
int64_t gather_impl(const char* base, const uint64_t* src_offsets,
                    const uint64_t* lengths, uint64_t n, char* dst,
                    int nthreads) {
  std::vector<uint64_t> dst_off(n + 1, 0);
  for (uint64_t i = 0; i < n; ++i) dst_off[i + 1] = dst_off[i] + lengths[i];
  const uint64_t total = dst_off[n];

  int t = std::max(1, nthreads);
  if (total < (4u << 20)) t = 1;  // copy overhead dominates below ~4 MiB
  if ((uint64_t)t > n && n > 0) t = (int)n;

  auto copy_range = [&](uint64_t lo, uint64_t hi) {
    for (uint64_t i = lo; i < hi; ++i)
      if (lengths[i]) memcpy(dst + dst_off[i], base + src_offsets[i], lengths[i]);
  };

  if (t == 1) {
    copy_range(0, n);
  } else {
    std::vector<std::thread> threads;
    uint64_t per = (total + t - 1) / t;
    uint64_t lo = 0;
    for (int k = 0; k < t && lo < n; ++k) {
      uint64_t target = std::min(total, (uint64_t)(k + 1) * per);
      uint64_t hi = (uint64_t)(std::upper_bound(dst_off.begin() + lo + 1,
                                                dst_off.end(), target) -
                               dst_off.begin()) - 1;
      hi = std::max(hi, lo + 1);
      hi = std::min(hi, n);
      threads.emplace_back(copy_range, lo, hi);
      lo = hi;
    }
    for (auto& th : threads) th.join();
  }
  return (int64_t)total;
}

}  // namespace

extern "C" {

// mmap a file read-only. Returns handle or nullptr.
void* staging_map_file(const char* path, uint64_t* out_size) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  madvise(base, (size_t)st.st_size, MADV_SEQUENTIAL);
  if (out_size) *out_size = (uint64_t)st.st_size;
  Mapped* m = new Mapped{base, (uint64_t)st.st_size};
  return m;
}

void staging_unmap(void* handle) {
  Mapped* m = (Mapped*)handle;
  if (!m) return;
  munmap(m->base, m->size);
  delete m;
}

// Gather n blocks (src_offsets[i], lengths[i]) from the mapped file into dst,
// packed back-to-back in order. Parallelized across `nthreads` by splitting
// the block list at roughly equal byte counts. Returns total bytes copied,
// or -1 if any block is out of bounds.
int64_t staging_gather(void* handle, const uint64_t* src_offsets,
                       const uint64_t* lengths, uint64_t n, char* dst,
                       int nthreads) {
  Mapped* m = (Mapped*)handle;
  if (!m) return -1;
  // Overflow-safe bounds check: offset and length validated independently so
  // offset+length cannot wrap uint64.
  for (uint64_t i = 0; i < n; ++i)
    if (src_offsets[i] > m->size || lengths[i] > m->size - src_offsets[i])
      return -1;
  return gather_impl((const char*)m->base, src_offsets, lengths, n, dst,
                     nthreads);
}

// Plain memory gather: same as staging_gather but from an arbitrary base
// pointer (e.g. an arena buffer) instead of a mapped file. No bounds info is
// available, so the caller guarantees validity.
int64_t mem_gather(const char* base, const uint64_t* src_offsets,
                   const uint64_t* lengths, uint64_t n, char* dst,
                   int nthreads) {
  return gather_impl(base, src_offsets, lengths, n, dst, nthreads);
}

}  // extern "C"
