"""Receiver-driven serving flow control (java/RdmaChannel.java:61-64,
744-787 re-design): the server reserves each data response's logical size
from a per-connection credit window BEFORE building it, parks when the
window is exhausted, and the reader's receipt CreditReport replenishes.
A stalled consumer therefore BOUNDS server-held response bytes instead of
growing them — audited here via the endpoint's serve_stats."""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager

BLOCK = 64 << 10          # per-partition block ~64 KiB
WINDOW = 256 << 10        # tiny serving window: 4 blocks


def _cluster(tmp_path, **conf_kw):
    conf_kw.setdefault("connect_timeout_ms", 3000)
    conf = TpuShuffleConf(**conf_kw)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(2)]
    for ex in execs:
        ex.executor.wait_for_members(2)
    return driver, execs


def _write_shuffle(driver, execs, shuffle_id, num_partitions=16,
                   rows_per_map=None):
    """One map output on executor 0 with ~BLOCK bytes per partition."""
    payload_w = 96  # 8B key + 96B payload
    rows_per_part = BLOCK // (8 + payload_w)
    handle = driver.register_shuffle(shuffle_id, 1, num_partitions,
                                     PartitionerSpec("modulo"),
                                     row_payload_bytes=payload_w)
    rng = np.random.default_rng(shuffle_id)
    keys = np.repeat(np.arange(num_partitions, dtype=np.uint64),
                     rows_per_part)
    w = execs[0].get_writer(handle, 0)
    w.write_batch(keys, rng.integers(0, 255, (len(keys), payload_w),
                                     dtype=np.uint64).astype(np.uint8))
    w.close()
    return handle


def test_stalled_consumers_bound_server_memory(tmp_path):
    """Eight concurrent readers share the peer connection and all stall
    (their consumers never drain): server-held response bytes are bounded
    by the credit window — the ledger reserves BEFORE building, so
    peak_reserved <= window is the memory bound — serving demonstrably
    parks, and once consumers drain everything completes exactly."""
    driver, execs = _cluster(
        tmp_path, serve_credit_bytes=WINDOW,
        # small grouped reads so many requests are needed
        shuffle_read_block_size=BLOCK,
        # a huge client-side gate so the CLIENT does not throttle — the
        # server's own window must do the bounding
        max_bytes_in_flight=1 << 30,
        use_cpp_runtime=False)
    try:
        handle = _write_shuffle(driver, execs, 1, num_partitions=32)
        n_readers = 8
        iters, started = [], []
        for r in range(n_readers):
            reader = execs[1].get_reader(handle, 0, 32)
            it = iter(reader.read())
            iters.append(it)
            t = threading.Thread(target=lambda i=it: next(i), daemon=True)
            t.start()
            started.append(t)
        for t in started:
            t.join(timeout=10)
        time.sleep(1.0)  # all 8 stalled; their fetchers keep requesting
        stats = execs[0].executor.serve_stats()
        assert stats["peak_reserved"] <= WINDOW, stats  # THE memory bound
        assert stats["parked"] > 0, \
            f"window never exerted backpressure: {stats}"
        # drain everyone — credits replenish and every row arrives
        want = 32 * (BLOCK // (8 + 96))
        rows_per_part = BLOCK // (8 + 96)
        for it in iters:
            got = sum(len(k) for k, _ in it)
            assert got >= want - rows_per_part  # minus the batch next() ate
        stats = execs[0].executor.serve_stats()
        assert stats["credit_timeouts"] == 0
        assert stats["peak_reserved"] <= WINDOW
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_credit_starved_fetch_fails_not_hangs(tmp_path):
    """A consumer that NEVER replenishes (stall past the park timeout)
    gets STATUS_ERROR on its excess fetches instead of wedging the server;
    the failure surfaces as the ordinary retryable fetch error."""
    driver, execs = _cluster(
        tmp_path, serve_credit_bytes=BLOCK,  # window = ONE block
        shuffle_read_block_size=BLOCK, max_bytes_in_flight=1 << 30,
        connect_timeout_ms=1500, use_cpp_runtime=False)
    try:
        handle = _write_shuffle(driver, execs, 2, num_partitions=8)
        # raw pipelined requests with NO credit reports: grab locations,
        # then fire several block fetches through the wire layer directly
        peer = execs[1].executor.member_at(
            execs[0].executor.exec_index(timeout=2))
        locs = execs[1].executor.fetch_output_range(peer, 2, 0, 0, 8)
        conn = execs[1].executor._clients.get(peer.rpc_host, peer.rpc_port)
        futures = []
        from concurrent.futures import ThreadPoolExecutor

        def raw_fetch(loc):
            req = M.FetchBlocksReq(conn.next_req_id(), 2,
                                   [(loc.buf, loc.offset, loc.length)])
            return conn.request(req, timeout=10)

        with ThreadPoolExecutor(max_workers=8) as pool:
            futures = [pool.submit(raw_fetch, loc) for loc in locs]
            statuses = [f.result().status for f in futures]
        ok = statuses.count(M.STATUS_OK)
        errs = statuses.count(M.STATUS_ERROR)
        # exactly one window's worth can be served; the rest park until
        # the timeout and fail cleanly
        assert ok >= 1
        assert errs >= 1, f"no credit starvation surfaced: {statuses}"
        assert ok + errs == len(statuses)
        stats = execs[0].executor.serve_stats()
        assert stats["credit_timeouts"] >= errs
        assert stats["peak_reserved"] <= BLOCK
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_healthy_peer_unaffected_by_stalled_peer(tmp_path):
    """Credit windows are per connection: one stalled reader exhausting
    its window must not slow a healthy reader on another connection."""
    driver, execs = _cluster(
        tmp_path, serve_credit_bytes=WINDOW,
        shuffle_read_block_size=BLOCK, max_bytes_in_flight=1 << 30,
        use_cpp_runtime=False)
    try:
        handle = _write_shuffle(driver, execs, 3, num_partitions=32)
        stalled = execs[1].get_reader(handle, 0, 32)
        it = iter(stalled.read())
        next(it)  # start, then stall (don't drain)
        time.sleep(0.3)
        # the "healthy peer": executor 0 reading its own spills would be
        # local; instead re-read from executor 1 via a FRESH manager whose
        # connection (and window) is its own
        healthy = TpuShuffleManager(
            TpuShuffleConf(connect_timeout_ms=3000,
                           serve_credit_bytes=WINDOW,
                           shuffle_read_block_size=BLOCK,
                           use_cpp_runtime=False),
            driver_addr=driver.driver_addr, executor_id="h",
            spill_dir=str(tmp_path / "h"))
        healthy.executor.wait_for_members(3)
        try:
            t0 = time.monotonic()
            keys, _ = healthy.get_reader(handle, 0, 32).read_all()
            dt = time.monotonic() - t0
            assert len(keys) == 32 * (BLOCK // (8 + 96))
            assert dt < 5.0, f"healthy reader throttled by stalled peer ({dt:.1f}s)"
        finally:
            healthy.stop()
        # drain the stalled reader so teardown is clean
        for _ in it:
            pass
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_reserve_or_park_no_lost_wakeup():
    """The availability check and the park are ONE atomic operation: a
    release draining concurrently with a failed check can no longer
    strand a request (regression: a separate try_reserve-then-park pair
    had a window where the last outstanding release slipped between the
    two calls and nothing ever woke the parked queue). Hammered with
    4x-oversubscribed concurrent requests; every one must serve."""
    from sparkrdma_tpu.parallel.endpoints import ByteCredits

    credits = ByteCredits(1024)
    served = []
    lock = threading.Lock()
    n = 200

    def work(i):
        def resume():
            with lock:
                served.append(i)
            credits.release(256)  # consume + replenish immediately

        if credits.reserve_or_park(256, time.monotonic() + 30, resume,
                                   lambda: None):
            resume()

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with lock:
            if len(served) == n:
                break
        time.sleep(0.01)
    assert len(served) == n, f"lost wakeup: {len(served)}/{n} served"


def test_timed_out_fetch_reports_credit_via_orphan(tmp_path):
    """A fetch whose requester gives up waiting but whose response still
    arrives (slow server) must not leak the serving window: either the
    late response is returned by the request-race path, or it lands as an
    orphan and the unsolicited handler sends the CreditReport. Proven by
    a follow-up window-sized fetch succeeding (a leaked window would park
    it until STATUS_ERROR)."""
    driver, execs = _cluster(
        tmp_path, serve_credit_bytes=BLOCK,  # window = ONE block
        shuffle_read_block_size=BLOCK, max_bytes_in_flight=1 << 30,
        connect_timeout_ms=900, use_cpp_runtime=False)
    try:
        handle = _write_shuffle(driver, execs, 4, num_partitions=4)
        server_ep = execs[0].executor
        orig = server_ep._on_fetch_blocks
        slow_once = threading.Event()

        def slow(msg):
            if not slow_once.is_set():
                slow_once.set()
                time.sleep(2.0)  # outlive the client's 0.9s wait
            return orig(msg)

        server_ep._on_fetch_blocks = slow
        client = execs[1].executor
        peer = client.member_at(execs[0].executor.exec_index(timeout=2))
        locs = client.fetch_output_range(peer, 4, 0, 0, 4)
        conn = client._clients.get(peer.rpc_host, peer.rpc_port)
        req = M.FetchBlocksReq(
            conn.next_req_id(), 4,
            [(locs[0].buf, locs[0].offset, locs[0].length)])
        t0 = time.monotonic()
        try:
            client._credited_request(conn, req, credited=True)
        except TimeoutError:
            pass  # the expected outcome; a race-window return is also fine
        # wait for the late response to land and its credits to be
        # reported through whichever path won
        time.sleep(max(0.0, 2.6 - (time.monotonic() - t0)))
        req2 = M.FetchBlocksReq(
            conn.next_req_id(), 4,
            [(locs[1].buf, locs[1].offset, locs[1].length)])
        resp2 = client._credited_request(conn, req2, credited=True)
        assert resp2.status == M.STATUS_OK, \
            "window leaked by the timed-out fetch"
        assert server_ep.serve_stats()["credit_timeouts"] == 0
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
