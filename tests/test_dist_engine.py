"""Engine-driven DISTRIBUTED mesh data plane: executor processes form a
real 2-process jax.distributed group (4 CPU devices each), and the DAG
engine's reduce-side reads ride ONE global-mesh collective per parent
shuffle — the multi-node pipeline that is the reference's whole reason to
exist (README.md:11-31), driven end-to-end through the engine SPI."""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
from sparkrdma_tpu.shuffle.manager import PartitionerSpec
from sparkrdma_tpu.shuffle.spark_compat import (
    ShuffleDependency,
    SparkCompatShuffleManager,
)
from sparkrdma_tpu.tasks import remote_executors

# the two tests that run a REAL collective over the 2-process CPU mesh
# need a jax whose XLA:CPU implements multiprocess computations (0.5+);
# the failure-path tests never reach a successful collective and run
# anywhere
import jax  # noqa: E402

_requires_multiprocess_cpu = pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="jax<0.5 XLA:CPU cannot run multiprocess computations")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = f'''
import sys, time
sys.path.insert(0, {REPO_ROOT!r})
pid, coord, host, port, spill = (int(sys.argv[1]), sys.argv[2],
                                 sys.argv[3], int(sys.argv[4]), sys.argv[5])
from sparkrdma_tpu.parallel.multihost import init_multihost
init_multihost(coord, num_processes=2, process_id=pid,
               local_device_count=4, platform="cpu")
import jax
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4
from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager
from sparkrdma_tpu.tasks import install_task_server
mgr = SparkCompatShuffleManager(
    TpuShuffleConf(connect_timeout_ms=5000), driverAddr=(host, port),
    executorId=f"w{{pid}}", spill_dir=spill)
install_task_server(mgr)
print("WORKER_READY", pid, flush=True)
time.sleep(600)
'''

CONF = TpuShuffleConf(connect_timeout_ms=3000, max_connection_attempts=2,
                      task_timeout_ms=120_000)

P, MAPS, ROWS = 8, 4, 400


def _make_fns():
    """Task closures (NOT module-level: cloudpickle would ship them by
    reference to this test module, which worker processes can't import)."""
    rows = ROWS

    def map_fn(ctx, writer, task_id, _rows=rows):
        import numpy as np
        rng = np.random.default_rng(40 + task_id)
        keys = rng.integers(0, 10_000, _rows).astype(np.uint64)
        vals = rng.integers(0, 1000, _rows).astype("<u4")
        writer.write((keys, vals.view(np.uint8).reshape(_rows, 4)))

    def reduce_fn(ctx, task_id):
        import numpy as np
        from sparkrdma_tpu.shuffle import dist_cache

        handle = ctx._parents[0]
        from_collective = dist_cache.get(handle.shuffle_id,
                                         task_id) is not None
        total = 0
        for keys, payload in ctx.read(0).readBatches():
            vals = np.ascontiguousarray(payload).view("<u4")
            total += int(vals.astype(np.int64).sum())
        return total, from_collective, handle.shuffle_id

    return map_fn, reduce_fn


def _expected_partition_sums():
    sums = np.zeros(P, dtype=np.int64)
    for m in range(MAPS):
        rng = np.random.default_rng(40 + m)
        keys = rng.integers(0, 10_000, ROWS).astype(np.uint64)
        vals = rng.integers(0, 1000, ROWS).astype(np.int64)
        np.add.at(sums, (keys % P).astype(np.int64), vals)
    return sums


def test_dist_collective_retries_through_recovery(monkeypatch, tmp_path):
    """Driver-side orchestration in isolation (no jax group): a
    group-wide FetchFailed on the first collective round triggers ONE
    recovery, the group re-enters, ownership lands; coverage and
    duplicate-process validation raise clearly."""
    from sparkrdma_tpu.engine import DAGEngine
    from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
    from sparkrdma_tpu.shuffle.manager import ShuffleHandle

    class StubRemote:
        alive = True

        def __init__(self, pidx, nproc, parts, fail_rounds=0):
            self.pidx, self.nproc, self.parts = pidx, nproc, parts
            self.fail_rounds = fail_rounds
            self.calls = 0

        def run_result_task(self, fn, parents, task_id):
            self.calls += 1
            if self.calls <= self.fail_rounds:
                raise FetchFailedError(7, 1, 0, "spill disposed")
            return (self.pidx, self.nproc, self.parts), {}

    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    try:
        a = StubRemote(0, 2, [0, 2, 4, 6], fail_rounds=1)
        b = StubRemote(1, 2, [1, 3, 5, 7], fail_rounds=1)
        engine = DAGEngine.__new__(DAGEngine)  # orchestration state only
        engine.executors = [a, b]
        engine.dist_mesh_axis = "shuffle"
        engine.dist_rows_per_round = 0
        engine.mesh_impl = "auto"
        engine.max_stage_retries = 2
        engine.tracer = driver.native.tracer
        import threading
        engine._dist_lock = threading.RLock()
        engine._dist_owner = {}
        recoveries = []
        engine._recover_shuffle = lambda e: recoveries.append(e.shuffle_id)
        handle = ShuffleHandle(7, 4, 8, 4, PartitionerSpec("modulo"))
        engine._dist_mesh_reduce(handle)
        assert recoveries == [7]
        owner = engine._dist_owner[7]
        assert {p for p, ex in owner.items() if ex is a} == {0, 2, 4, 6}
        assert {p for p, ex in owner.items() if ex is b} == {1, 3, 5, 7}
        # duplicate process index -> loud config error
        engine._dist_owner.clear()
        engine.executors = [StubRemote(0, 2, [0]), StubRemote(0, 2, [1])]
        with pytest.raises(RuntimeError, match="two engine executors"):
            engine._dist_mesh_reduce(handle)
        # missing process -> loud coverage error
        engine._dist_owner.clear()
        engine.executors = [StubRemote(0, 2, [0])]
        with pytest.raises(RuntimeError, match="covered 1/2"):
            engine._dist_mesh_reduce(handle)
    finally:
        driver.stop()


@_requires_multiprocess_cpu
def test_rdd_over_distributed_mesh(tmp_path):
    """The RDD layer's pickled-blob shuffles ride the cross-process
    collective unchanged — including BOUNDED ROUNDS that split a map's
    multi-row blobs across collectives and interleave sources: the
    per-row (map, seq) tags make decoding order-independent."""
    from sparkrdma_tpu.rdd import EngineContext

    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    host, port = driver.driverAddr
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = "127.0.0.1:%d" % s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), coord, host, str(port),
         str(tmp_path / f"w{i}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    remotes = []
    try:
        remotes = remote_executors(driver, CONF, expect=2, timeout=60)
        # dist_rows_per_round forces multiple bounded collective rounds;
        # blob framing must survive the round slicing (a boundary splits
        # exactly one map, head/tail stay adjacent per destination)
        ctx = EngineContext(DAGEngine(driver, remotes,
                                      dist_mesh_axis="shuffle",
                                      dist_rows_per_round=2))
        # 3 KB values -> multi-row blobs; rows_per_round=2 forces many
        # rounds, so blobs genuinely split and interleave in transit
        pairs = [(i % 7, "v%d" % i + "x" * 3000) for i in range(42)]
        got = (ctx.parallelize(pairs, 4)
               .group_by_key(8)
               .map_values(len)
               .collect())
        assert dict(got) == {k: 6 for k in range(7)}
    finally:
        for p in procs:
            p.kill()
        for r in remotes:
            r.stop()
        driver.stop()


def test_kill_executor_mid_collective_fails_fast(tmp_path):
    """SIGKILL one executor process while ``run_multihost_mesh_reduce``
    is in flight (SURVEY §7 hard part 4: a failed participant stalls the
    whole mesh). The driver must surface a group-wide failure within the
    short fail grace — NOT block the full task budget on the wedged
    survivor — and must name the lost process, not the survivor
    (RdmaShuffleFetcherIterator.scala:376-381 is the reference's
    stage-retry precedent; a jax.distributed group can't re-form around
    a dead process, so the contract here is bounded-time fail-fast)."""
    import threading

    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    host, port = driver.driverAddr
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = "127.0.0.1:%d" % s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), coord, host, str(port),
         str(tmp_path / f"w{i}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    remotes = []
    try:
        remotes = remote_executors(driver, CONF, expect=2, timeout=60)
        # many bounded rounds stretch the collective so the kill lands
        # genuinely in flight (compile + rounds >> the 1s kill delay)
        engine = DAGEngine(driver, remotes, dist_mesh_axis="shuffle",
                           dist_rows_per_round=8, dist_fail_grace_s=3.0)
        map_fn, reduce_fn = _make_fns()
        stage = MapStage(MAPS, ShuffleDependency(
            P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)

        # instrument the victim's proxy so the kill fires only once the
        # collective dispatch is actually in flight on the workers.
        # remote_executors returns proxies in driver-REGISTRATION order —
        # a startup race — so map proxy->process by executor id ("w{i}"
        # is process i by construction in _WORKER)
        by_id = {r.manager_id.executor_id.executor: r for r in remotes}
        victim, survivor = by_id["w1"], by_id["w0"]
        dispatched = threading.Event()
        orig = victim.run_result_task

        def tapped(fn, parents, task_id):
            dispatched.set()
            return orig(fn, parents, task_id)

        victim.run_result_task = tapped

        outcome = {}

        def run_job():
            try:
                outcome["got"] = engine.run(
                    ResultStage(P, reduce_fn, parents=[stage]))
            except BaseException as e:
                outcome["err"] = e

        t = threading.Thread(target=run_job)
        t.start()
        assert dispatched.wait(90), "collective was never dispatched"
        time.sleep(1.0)  # let both processes enter the collective
        procs[1].kill()
        t_kill = time.monotonic()
        t.join(timeout=60)
        elapsed = time.monotonic() - t_kill
        assert not t.is_alive(), \
            "driver still blocked >60s after executor death"
        err = outcome.get("err")
        assert err is not None, f"job succeeded?! {outcome.get('got')}"
        assert "restart the process group" in str(err) or \
            "mid-collective" in str(err), f"unexpected failure: {err!r}"
        # bounded: grace (3s) + transport detection, nowhere near the
        # 120s task budget the survivor's RPC would otherwise hold
        assert elapsed < 45, f"fail-fast took {elapsed:.0f}s"
        # the SURVIVOR must not be blamed or written off as dead
        assert getattr(survivor, "alive", True), \
            "healthy survivor was marked dead"
    finally:
        for p in procs:
            p.kill()
        for r in remotes:
            r.stop()
        driver.stop()


@_requires_multiprocess_cpu
def test_engine_distributed_mesh_reduce(tmp_path):
    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    host, port = driver.driverAddr
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = "127.0.0.1:%d" % s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers pin their own 4-device split
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), coord, host, str(port),
         str(tmp_path / f"w{i}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    remotes = []
    try:
        remotes = remote_executors(driver, CONF, expect=2, timeout=60)
        engine = DAGEngine(driver, remotes, dist_mesh_axis="shuffle")
        map_fn, reduce_fn = _make_fns()
        stage = MapStage(MAPS, ShuffleDependency(
            P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
        got = engine.run(ResultStage(P, reduce_fn, parents=[stage]))
        sums = np.array([t for t, _, _ in got], dtype=np.int64)
        np.testing.assert_array_equal(sums, _expected_partition_sums())
        # owner-placement must have made every reduce read a local
        # collective-cache hit — rows moved over the mesh, not TCP
        assert all(flag for _, flag, _ in got), \
            f"reads fell back to TCP: {[f for _, f, _ in got]}"
        # job teardown drops the worker-side collective caches (the
        # unregister ship): stale rows must not survive the job
        sid = got[0][2]

        def probe(ctx, task_id, _sid=sid):
            from sparkrdma_tpu.shuffle import dist_cache
            return dist_cache.has_shuffle(_sid)

        for r in remotes:
            held, _ = r.run_result_task(probe, [], 0)
            assert held is False, "worker kept a torn-down shuffle's cache"
    finally:
        for p in procs:
            p.kill()
        for r in remotes:
            r.stop()
        driver.stop()
        for p in procs:
            try:
                out = p.stdout.read().decode(errors="replace")
                if out and "WORKER_READY" not in out:
                    print("worker output:", out[-2000:])
            except Exception:
                pass
