"""Test harness: run everything on an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; XLA's host platform can be
split into N virtual devices, which exercises the same SPMD partitioner and
collective lowering paths the TPU backend uses. This stands in for the
multi-node cluster runs the reference was only ever validated on
(reference: no src/test at all — see SURVEY.md §4).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
