"""Test harness: run everything on an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; XLA's host platform can be
split into N virtual devices, which exercises the same SPMD partitioner and
collective lowering paths the TPU backend uses. This stands in for the
multi-node cluster runs the reference was only ever validated on
(reference: no src/test at all — see SURVEY.md §4).

Note: the session's sitecustomize registers the real TPU backend and pins
``jax_platforms`` via jax config (env vars alone don't win), so we override
the config after import — backends initialize lazily, so this takes effect
as long as it runs before any ``jax.devices()`` call.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: longer than the tier-1 wall-clock budget on a CPU host; "
        "excluded by the default `-m 'not slow'` run, exercised "
        "explicitly and on hardware rounds")
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection scenario (parallel/faults.py); "
        "fast ones run in tier-1, the wide sweep is chaos+slow and "
        "driven by scripts/run_chaos.sh across CHAOS_SEED values")
    # ANALYSIS_LOCKGRAPH=1: run the whole session under the lock-order
    # shim (sparkrdma_tpu/analysis/lockgraph.py). Every lock the package
    # creates during the run is tracked; a lock-order cycle fails the
    # session at exit (scripts/run_analysis.sh --lockgraph drives this).
    global _lockgraph
    if os.environ.get("ANALYSIS_LOCKGRAPH", "0") not in ("0", "false", ""):
        from sparkrdma_tpu.analysis import lockgraph

        _lockgraph = lockgraph.install()


_lockgraph = None


def pytest_sessionfinish(session, exitstatus):
    if _lockgraph is None:
        return
    from sparkrdma_tpu.analysis import lockgraph

    lockgraph.uninstall()
    cycles = _lockgraph.cycles()
    if cycles:
        import sys

        print("\n" + _lockgraph.format_cycles(), file=sys.stderr)
        session.exitstatus = 3
    else:
        print(f"\nlockgraph: acyclic "
              f"({len(_lockgraph.edges())} distinct orderings)")
