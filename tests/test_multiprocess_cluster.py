"""Whole-framework integration across REAL process boundaries: the driver
runs in this process; two executor processes write, publish, and serve;
a reducer in a fourth process fetches across all of them. This is the
deployment shape of the reference's multi-node clusters (README.md:11-31)
at single-machine scale — every byte crosses a process boundary through
the control plane or the native block server."""

import os
import subprocess
import sys

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one shared definition of the shuffle geometry, prepended to both scripts
_COMMON = f'''
import sys, numpy as np
sys.path.insert(0, {REPO_ROOT!r})
from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import (
    TpuShuffleManager, ShuffleHandle, PartitionerSpec)
HANDLE = ShuffleHandle(1, 4, 4, 8, PartitionerSpec("modulo"))
'''

_WRITER = _COMMON + r'''
driver_host, driver_port, exec_id, spill_dir, maps = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4],
    [int(x) for x in sys.argv[5].split(",")])
conf = TpuShuffleConf(connect_timeout_ms=5000)
mgr = TpuShuffleManager(conf, driver_addr=(driver_host, driver_port),
                        executor_id=exec_id, spill_dir=spill_dir)
for m in maps:
    rng = np.random.default_rng(100 + m)
    w = mgr.get_writer(HANDLE, m)
    w.write_batch(rng.integers(0, 5000, 1000).astype(np.uint64),
                  rng.integers(0, 255, (1000, 8)).astype(np.uint8))
    w.close()
print("WRITER_DONE", exec_id, flush=True)
import time
time.sleep(float(sys.argv[6]))  # serve until the test's finally kills us
mgr.stop()
'''

_REDUCER = _COMMON + r'''
driver_host, driver_port = sys.argv[1], int(sys.argv[2])
conf = TpuShuffleConf(connect_timeout_ms=5000)
mgr = TpuShuffleManager(conf, driver_addr=(driver_host, driver_port),
                        executor_id="reducer", spill_dir=sys.argv[3])
reader = mgr.get_reader(HANDLE, 0, 4)
keys, payload = reader.read_all()
expect = np.sort(np.concatenate(
    [np.random.default_rng(100 + m).integers(0, 5000, 1000) for m in range(4)]
).astype(np.uint64))
assert np.array_equal(np.sort(keys), expect), "cross-process data mismatch"
m = reader.metrics
assert m.remote_bytes > 0 and m.local_bytes == 0  # everything is remote here
print("REDUCER_OK rows=%d remote_bytes=%d" % (len(keys), m.remote_bytes),
      flush=True)
mgr.stop()
'''


def test_cross_process_shuffle(tmp_path):
    conf = TpuShuffleConf(connect_timeout_ms=5000)
    driver = TpuShuffleManager(conf, is_driver=True)
    driver.register_shuffle(1, 4, 4, PartitionerSpec("modulo"),
                            row_payload_bytes=8)
    host, port = driver.driver_addr
    env = dict(os.environ)
    writers = [
        subprocess.Popen(
            [sys.executable, "-c", _WRITER, host, str(port), f"w{i}",
             str(tmp_path / f"w{i}"), ",".join(str(m) for m in maps), "600"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i, maps in enumerate([[0, 1], [2, 3]])
    ]
    try:
        # wait for both writers to commit+publish (driver table fills up)
        import time
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if driver.driver._tables[1].num_published == 4:
                break
            time.sleep(0.2)
        assert driver.driver._tables[1].num_published == 4, "publishes missing"

        reducer = subprocess.run(
            [sys.executable, "-c", _REDUCER, host, str(port),
             str(tmp_path / "r")],
            capture_output=True, timeout=90, env=env)
        out = reducer.stdout.decode()
        assert "REDUCER_OK rows=4000" in out, \
            f"reducer failed:\n{out[-2000:]}\n{reducer.stderr.decode()[-500:]}"
    finally:
        for w in writers:
            w.kill()
        driver.stop()
