"""Control-plane integration tests: driver + executors in one process,
real sockets on localhost.

Covers the reference's bootstrap/membership flow
(scala/RdmaShuffleManager.scala:73-134, 186-232), driver-table
publish/fetch (341-418), and peer location/block serving
(scala/RdmaShuffleFetcherIterator.scala:119-180, 293-315).
"""

import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.endpoints import DriverEndpoint, ExecutorEndpoint
from sparkrdma_tpu.parallel.transport import ConnectionCache, TransportError
from sparkrdma_tpu.shuffle.map_output import MAP_ENTRY_SIZE, MapTaskOutput

CONF = TpuShuffleConf(connect_timeout_ms=5000, max_connection_attempts=2)


class FakeSource:
    """In-memory ShuffleDataSource: buffers keyed by token."""

    def __init__(self):
        self.tables: Dict[Tuple[int, int], MapTaskOutput] = {}
        self.buffers: Dict[int, bytes] = {}

    def get_output_table(self, shuffle_id: int, map_id: int) -> Optional[MapTaskOutput]:
        return self.tables.get((shuffle_id, map_id))

    def read_block(self, shuffle_id: int, buf_token: int, offset: int,
                   length: int) -> Optional[bytes]:
        buf = self.buffers.get(buf_token)
        if buf is None or offset + length > len(buf):
            return None
        return buf[offset:offset + length]


@pytest.fixture
def cluster():
    driver = DriverEndpoint(CONF)
    execs, sources = [], []
    for i in range(3):
        src = FakeSource()
        ex = ExecutorEndpoint("127.0.0.1", str(i), driver.address,
                              data_source=src, conf=CONF)
        execs.append(ex)
        sources.append(src)
    for ex in execs:
        ex.start()
    for ex in execs:
        ex.wait_for_members(3)
    yield driver, execs, sources
    for ex in execs:
        ex.stop()
    driver.stop()


def test_membership_bootstrap(cluster):
    driver, execs, _ = cluster
    assert len(driver.members()) == 3
    # all executors converge on the same ordered list
    lists = [ex.members() for ex in execs]
    assert lists[0] == lists[1] == lists[2] == driver.members()
    # stable indices
    indices = sorted(ex.exec_index() for ex in execs)
    assert indices == [0, 1, 2]


def test_publish_and_fetch_driver_table(cluster):
    driver, execs, _ = cluster
    driver.register_shuffle(7, num_maps=6)
    # each executor publishes two map outputs
    for m in range(6):
        execs[m % 3].publish_map_output(7, m, table_token=1000 + m)
    table = execs[0].get_driver_table(7, expect_published=6, timeout=5)
    assert table.num_maps == 6
    for m in range(6):
        token, exec_idx = table.entry(m)
        assert token == 1000 + m
        assert exec_idx == execs[m % 3].exec_index()


def test_fetch_table_polls_until_published(cluster):
    driver, execs, _ = cluster
    driver.register_shuffle(8, num_maps=2)
    execs[0].publish_map_output(8, 0, table_token=1)

    def late_publish():
        time.sleep(0.2)
        execs[1].publish_map_output(8, 1, table_token=2)

    t = threading.Thread(target=late_publish)
    t.start()
    table = execs[2].get_driver_table(8, expect_published=2, timeout=5)
    t.join()
    assert table.entry(1)[0] == 2


def test_fetch_table_timeout(cluster):
    driver, execs, _ = cluster
    driver.register_shuffle(9, num_maps=4)
    with pytest.raises(TimeoutError):
        execs[0].get_driver_table(9, expect_published=4, timeout=0.3)


def test_fetch_output_range_and_blocks(cluster):
    driver, execs, sources = cluster
    # executor 1 stages a map output: 4 partitions in buffer 55
    payload = np.arange(400, dtype=np.uint8).tobytes()
    sources[1].buffers[55] = payload
    table = MapTaskOutput(4)
    for r in range(4):
        table.put(r, offset=r * 100, length=100, buf=55)
    sources[1].tables[(3, 0)] = table

    peer = execs[1].manager_id
    locs = execs[0].fetch_output_range(peer, 3, 0, 1, 3)
    assert len(locs) == 2
    assert locs[0].offset == 100 and locs[0].buf == 55

    data = execs[0].fetch_blocks(peer, 3, [(l.buf, l.offset, l.length) for l in locs])
    assert data == payload[100:300]


def test_fetch_errors(cluster):
    _, execs, _ = cluster
    peer = execs[1].manager_id
    with pytest.raises(TransportError):
        execs[0].fetch_output_range(peer, 999, 0, 0, 1)  # unknown map
    with pytest.raises(TransportError):
        execs[0].fetch_blocks(peer, 3, [(12345, 0, 10)])  # unknown buffer


def test_publish_unknown_shuffle_ignored(cluster):
    driver, execs, _ = cluster
    # publishing to an unregistered shuffle must not corrupt anything
    execs[0].publish_map_output(12345, 0, table_token=9)
    time.sleep(0.1)
    driver.register_shuffle(12345, num_maps=1)
    assert driver._tables[12345].num_published == 0


def test_connect_failure_budget():
    cache = ConnectionCache(TpuShuffleConf(connect_timeout_ms=200,
                                           max_connection_attempts=2))
    t0 = time.monotonic()
    with pytest.raises(TransportError):
        cache.get("127.0.0.1", 1)  # nothing listens on port 1
    assert time.monotonic() - t0 < 5


def test_request_after_peer_stop_fails_fast(cluster):
    driver, execs, _ = cluster
    driver.register_shuffle(1, num_maps=1)
    execs[0].publish_map_output(1, 0, table_token=5)
    execs[0].get_driver_table(1, expect_published=1, timeout=5)
    conn = execs[0].driver_conn()
    driver.server.stop()
    time.sleep(0.1)
    from sparkrdma_tpu.parallel import messages as M
    with pytest.raises((TransportError, Exception)):
        conn.request(M.FetchTableReq(conn.next_req_id(), 1), timeout=1)


def test_tombstone_keeps_indices_stable(cluster):
    driver, execs, _ = cluster
    from sparkrdma_tpu.parallel.endpoints import TOMBSTONE, DeadExecutorError
    idx_before = {ex.manager_id: ex.exec_index() for ex in execs}
    lost = execs[1].manager_id
    driver.remove_member(lost)
    time.sleep(0.3)  # let the tombstone announce propagate
    # surviving executors keep their indices
    for ex in (execs[0], execs[2]):
        assert ex.exec_index() == idx_before[ex.manager_id]
        assert ex.members()[idx_before[lost]] == TOMBSTONE
        with pytest.raises(DeadExecutorError):
            ex.member_at(idx_before[lost])


def test_negative_map_id_publish_ignored(cluster):
    driver, execs, _ = cluster
    from sparkrdma_tpu.parallel import messages as M
    from sparkrdma_tpu.shuffle.map_output import DriverTable
    driver.register_shuffle(77, num_maps=2)
    conn = execs[0].driver_conn()
    conn.send(M.PublishMsg(77, -1, DriverTable.pack_entry(9, 0)))
    conn.send(M.PublishMsg(77, 2, DriverTable.pack_entry(9, 0)))
    time.sleep(0.2)
    table = driver._tables[77]
    assert table.num_maps == 2 and table.num_published == 0
    assert len(table.to_bytes()) == 2 * MAP_ENTRY_SIZE


def test_partial_table_not_memoized(cluster):
    driver, execs, _ = cluster
    driver.register_shuffle(55, num_maps=3)
    execs[0].publish_map_output(55, 0, table_token=1)
    partial = execs[2].get_driver_table(55, expect_published=1, timeout=5)
    assert partial.num_published >= 1
    # a later, stricter expectation must NOT be served the partial snapshot
    execs[0].publish_map_output(55, 1, table_token=2)
    execs[1].publish_map_output(55, 2, table_token=3)
    full = execs[2].get_driver_table(55, expect_published=3, timeout=5)
    assert full.num_published == 3
    # complete table is memoized
    again = execs[2].get_driver_table(55, expect_published=3, timeout=5)
    assert again is full


def test_unreachable_executor_auto_tombstoned(cluster):
    """Failure detection: announce delivery failure marks the peer lost
    (scala/RdmaShuffleManager.scala:155-165 analogue)."""
    from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
    driver, execs, _ = cluster
    dead = execs[1]
    dead_idx = dead.exec_index()
    dead.stop()  # server gone; driver's cached conn will break
    # each new membership event triggers a broadcast; the dead peer's
    # connection fails on first real post-RST traffic, so detection
    # converges within a couple of events (TCP can't see a silent peer
    # death until a send bounces)
    fresh = []
    deadline = time.monotonic() + 10
    tombstoned = False
    while time.monotonic() < deadline and not tombstoned:
        ex = ExecutorEndpoint("127.0.0.1", f"f{len(fresh)}", driver.address,
                              conf=CONF)
        ex.start()
        fresh.append(ex)
        for _ in range(20):
            members = driver.members()
            if dead_idx < len(members) and members[dead_idx] == TOMBSTONE:
                tombstoned = True
                break
            time.sleep(0.05)
        if len(fresh) >= 3:
            break
    members = driver.members()
    assert members[dead_idx] == TOMBSTONE
    assert fresh[0].manager_id in members
    for ex in fresh:
        ex.stop()


def test_32_executor_bootstrap():
    """Control-plane scale: one coalescing broadcaster, not a thread per
    hello — 32 executors converge and publish/fetch still works
    (the reference pre-connects+caches for the same storm,
    java/RdmaNode.java:283-353)."""
    n = 32
    driver = DriverEndpoint(CONF)
    execs = []
    try:
        for i in range(n):
            ex = ExecutorEndpoint("127.0.0.1", f"x{i}", driver.address,
                                  conf=CONF)
            execs.append(ex)
            ex.start()
        for ex in execs:
            ex.wait_for_members(n, timeout=30)
        # announce order is identical everywhere
        order = [m.executor_id.executor for m in execs[0].members()]
        assert sorted(order) == sorted(f"x{i}" for i in range(n))
        assert all([m.executor_id.executor for m in ex.members()] == order
                   for ex in execs)
        # a publish/fetch round through the full membership
        driver.register_shuffle(9, num_maps=n)
        for ex in execs:
            ex.publish_map_output(9, ex.exec_index(timeout=5), table_token=1)
        table = execs[-1].get_driver_table(9, expect_published=n, timeout=20)
        assert table.num_published == n
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
