"""Metadata plane: epoch-versioned location tables, pushed invalidation,
sharded driver state (shuffle/location_plane.py).

Unit coverage of the plane's epoch-validity rules plus control-plane
integration: epoch allocation/bumps at the driver (repair publish,
tombstone, unregister), the EpochBumpMsg push reaching executors,
sharded table reads off shard-host replicas with driver fallback, and
the long-poll unregister race fix (a poll racing an unregister gets a
terminal answer now, not a burned deadline).
"""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.endpoints import DriverEndpoint, ExecutorEndpoint
from sparkrdma_tpu.shuffle.location_plane import (
    EPOCH_DEAD,
    LocationPlane,
    ShardMap,
    ShardStore,
)
from sparkrdma_tpu.shuffle.map_output import MAP_ENTRY_SIZE, DriverTable

CONF = TpuShuffleConf(connect_timeout_ms=5000, max_connection_attempts=2)


@pytest.fixture
def cluster():
    driver = DriverEndpoint(CONF)
    execs = []
    for i in range(3):
        ex = ExecutorEndpoint("127.0.0.1", str(i), driver.address,
                              conf=CONF)
        execs.append(ex)
    for ex in execs:
        ex.start()
    for ex in execs:
        ex.wait_for_members(3)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


# -- plane unit semantics -------------------------------------------------


def test_epoch_sentinels_agree():
    assert EPOCH_DEAD == M.EPOCH_DEAD


def test_plane_epoch_validity_rules():
    p = LocationPlane()
    t = DriverTable(2)
    t.publish(0, 5, 0)
    t.publish(1, 6, 1)
    p.put_table(7, t, 1)
    got = p.table(7)
    assert got is not None and got[0] is t and got[1] == 1
    # a newer observed epoch invalidates the cached view
    assert p.note_epoch(7, 2) is True
    assert p.table(7) is None
    # duplicate/stale observations are no-ops
    assert p.note_epoch(7, 2) is False
    assert p.note_epoch(7, 1) is False
    # a response stamped OLDER than the observed epoch never memoizes
    p.put_table(7, t, 1)
    assert p.table(7) is None
    p.put_table(7, t, 2)
    assert p.table(7) is not None
    # locations share the rules
    p.put_locations(7, 0, 0, 4, ["locs"], 2)
    assert p.locations(7, 0, 0, 4) == ["locs"]
    assert p.note_epoch(7, 3) is True
    assert p.locations(7, 0, 0, 4) is None


def test_dead_shuffle_stays_dead_against_late_responses():
    """The modelcheck ttl_vs_late_fetch fix: after the EPOCH_DEAD push
    is processed, a LATE response stamped with the pre-death epoch must
    not resurrect any cached view — the epoch record is gone, so only
    the dead marker can recognize the staleness. A pushed registration
    signal (note_registered) or a pushed positive bump re-arms the id
    for reuse; responses never do."""
    p = LocationPlane()
    t = DriverTable(1)
    t.publish(0, 5, 0)
    p.put_table(7, t, 1)
    p.note_epoch(7, EPOCH_DEAD)
    assert p.table(7) is None
    # late responses from before the death: all dropped as stale
    p.put_table(7, t, 1)
    assert p.table(7) is None
    p.put_locations(7, 0, 0, 1, ["locs"], 1)
    assert p.locations(7, 0, 0, 1) is None
    p.put_merged(7, object(), 1)
    assert p.merged(7) is None

    class _Plan:
        plan_epoch = 1
    assert p.put_plan(7, _Plan()) is False
    assert p.plan(7) is None
    assert p.snapshot()["dead"] == 1
    # a pushed registration signal re-arms the reused id
    p.note_registered(7)
    p.put_table(7, t, 1)
    assert p.table(7) is not None
    # ... and so does a pushed positive bump (FIFO: it postdates death)
    p.note_epoch(7, EPOCH_DEAD)
    assert p.note_epoch(7, 1) is False  # fresh incarnation, nothing cached
    p.put_table(7, t, 1)
    assert p.table(7) is not None
    # EPOCH_DEAD drops everything including the observation
    p.put_locations(7, 0, 0, 4, ["locs"], 3)
    p.note_epoch(7, EPOCH_DEAD)
    assert p.locations(7, 0, 0, 4) is None
    assert p.known_epoch(7) is None


def test_plane_partial_table_never_memoized():
    p = LocationPlane()
    t = DriverTable(3)
    t.publish(0, 5, 0)
    p.put_table(9, t, 1)
    assert p.table(9) is None


def test_plane_hard_invalidate_keeps_observation():
    p = LocationPlane()
    t = DriverTable(1)
    t.publish(0, 5, 0)
    p.put_table(3, t, 4)
    p.invalidate(3)
    assert p.table(3) is None
    # the observation survives: a racing response from epoch 3 (older
    # than what we've seen) must still be recognized as stale
    assert p.known_epoch(3) == 4
    p.put_table(3, t, 3)
    assert p.table(3) is None


def test_plane_disabled_is_passthrough():
    p = LocationPlane(enabled=False)
    t = DriverTable(1)
    t.publish(0, 5, 0)
    p.put_table(1, t, 1)
    assert p.table(1) is None
    p.put_locations(1, 0, 0, 1, ["x"], 1)
    assert p.locations(1, 0, 0, 1) is None


def test_plane_location_ranges_bounded():
    p = LocationPlane(max_ranges=4)
    for m in range(10):
        p.put_locations(1, m, 0, 2, [m], 1)
    assert p.snapshot()["ranges"] == 4
    # oldest evicted FIFO, newest kept
    assert p.locations(1, 9, 0, 2) == [9]
    assert p.locations(1, 0, 0, 2) is None


# -- shard map / shard store ---------------------------------------------


def test_shard_map_assignment_and_ranges():
    sm = ShardMap.assign(10, [0, 1, 2], 3)
    assert sm.num_shards == 3
    assert [sm.range_of(s) for s in range(3)] == [(0, 4), (4, 8), (8, 10)]
    assert sm.slot_of_map(0) == 0 and sm.slot_of_map(9) == 2
    # more shards than maps or hosts degrade gracefully
    assert ShardMap.assign(2, [0, 1, 2], 8).num_shards == 2
    assert ShardMap.assign(10, [5], 8).num_shards == 1
    assert ShardMap.assign(10, [], 8) is None
    assert ShardMap.assign(10, [0, 1], 0) is None
    # trailing shards whose range would be empty/inverted are dropped
    # (5 maps over 4 slots = span 2 = 3 REAL shards; an empty shard
    # would own no maps and fail every sharded sync into the fallback)
    sm5 = ShardMap.assign(5, [0, 1, 2, 3], 4)
    assert sm5.num_shards == 3
    assert [sm5.range_of(s) for s in range(3)] == [(0, 2), (2, 4), (4, 5)]
    assert all(lo < hi for lo, hi in
               (sm5.range_of(s) for s in range(sm5.num_shards)))
    sm9 = ShardMap(9, [0, 1, 2, 3])  # direct construction truncates too
    assert sm9.num_shards == 3 and sm9.range_of(2) == (6, 9)
    # truncation is wire-stable: reconstructing from the truncated slot
    # list derives identical ranges
    sm5b = ShardMap(sm5.num_maps, sm5.shard_slots)
    assert [sm5b.range_of(s) for s in range(sm5b.num_shards)] == \
        [sm5.range_of(s) for s in range(sm5.num_shards)]
    # wire round trip through ShardMapMsg
    msg = M.ShardMapMsg(1, 1, sm.num_maps, sm.shard_slots)
    back = M.ShardMapMsg.from_payload(msg.payload())
    sm2 = ShardMap(back.num_maps, back.shard_slots)
    assert [sm2.range_of(s) for s in range(3)] == \
        [sm.range_of(s) for s in range(3)]


def test_shard_store_apply_and_read():
    ss = ShardStore()
    assert ss.read_range(1, 0, 4) is None  # no replica
    e0 = DriverTable.pack_entry(100, 0)
    e2 = DriverTable.pack_entry(102, 1)
    ss.apply(1, 1, 0, 4, e0)
    ss.apply(1, 2, 2, 4, e2)  # repair forward carries a bumped epoch
    n, epoch, data = ss.read_range(1, 0, 4)
    assert n == 2 and epoch == 2
    assert len(data) == 4 * MAP_ENTRY_SIZE
    t = DriverTable.from_bytes(data)
    assert t.entry(0) == (100, 0) and t.entry(2) == (102, 1)
    assert t.entry(1) is None and t.entry(3) is None
    assert ss.count_in(1, 0, 2) == 1
    ss.drop(1)
    assert ss.read_range(1, 0, 4) is None


# -- driver epoch lifecycle ----------------------------------------------


def _wait(pred, timeout=5.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


def test_epoch_allocated_and_served_with_table(cluster):
    driver, execs = cluster
    driver.register_shuffle(1, num_maps=2)
    assert driver.epoch_of(1) == 1
    execs[0].publish_map_output(1, 0, table_token=10)
    execs[1].publish_map_output(1, 1, table_token=11)
    table, epoch = execs[2].get_driver_table_v(1, expect_published=2,
                                               timeout=5)
    assert epoch == 1 and table.num_published == 2
    # the complete table memoized under its epoch: a re-read is a cache
    # hit, no wire traffic
    before = execs[2].location_plane.snapshot()["hits"]
    t2, e2 = execs[2].get_driver_table_v(1, expect_published=2, timeout=5)
    assert t2 is table and e2 == 1
    assert execs[2].location_plane.snapshot()["hits"] == before + 1


def test_repair_publish_bumps_epoch_and_pushes(cluster):
    driver, execs = cluster
    driver.register_shuffle(2, num_maps=1)
    execs[0].publish_map_output(2, 0, table_token=10)
    table, epoch = execs[2].get_driver_table_v(2, 1, timeout=5)
    assert epoch == 1
    # identical republish: no state a cache could hold moved — no bump
    execs[0].publish_map_output(2, 0, table_token=10)
    time.sleep(0.2)
    assert driver.epoch_of(2) == 1
    # an overwrite (re-execution on another executor) IS a repair
    execs[1].publish_map_output(2, 0, table_token=20)
    assert _wait(lambda: driver.epoch_of(2) == 2)
    # the push invalidates every executor's cached view
    assert _wait(lambda: execs[2].location_plane.known_epoch(2) == 2)
    assert execs[2].location_plane.table(2) is None
    # the re-sync serves the repaired entry under the new epoch
    t2, e2 = execs[2].get_driver_table_v(2, 1, timeout=5)
    assert e2 == 2 and t2.entry(0)[0] == 20


def test_tombstone_bumps_shuffles_naming_the_dead_slot(cluster):
    driver, execs = cluster
    driver.register_shuffle(3, num_maps=1)
    driver.register_shuffle(4, num_maps=1)
    # shuffle 3's output lives on the victim; shuffle 4's does not
    execs[1].publish_map_output(3, 0, table_token=1)
    execs[0].publish_map_output(4, 0, table_token=2)
    execs[2].get_driver_table_v(3, 1, timeout=5)
    execs[2].get_driver_table_v(4, 1, timeout=5)
    driver.remove_member(execs[1].manager_id)
    assert _wait(lambda: driver.epoch_of(3) == 2)
    assert _wait(lambda: execs[2].location_plane.known_epoch(3) == 2)
    assert execs[2].location_plane.table(3) is None
    # a shuffle with nothing on the dead slot keeps its epoch AND its
    # caches — invalidating it would cold-restart reducers for nothing
    assert driver.epoch_of(4) == 1
    assert execs[2].location_plane.table(4) is not None


def test_unregister_pushes_terminal_epoch(cluster):
    driver, execs = cluster
    driver.register_shuffle(5, num_maps=1)
    execs[0].publish_map_output(5, 0, table_token=1)
    execs[2].get_driver_table_v(5, 1, timeout=5)
    assert execs[2].location_plane.snapshot()["tables"] >= 1
    driver.unregister_shuffle(5)
    assert driver.epoch_of(5) is None
    assert _wait(lambda: execs[2].location_plane.known_epoch(5) is None
                 and execs[2].location_plane.table(5) is None)


# -- long-poll unregister race (satellite fix) ----------------------------


class _HookLock:
    """Wraps a lock; fires ``hook`` once, from ``owner`` thread only,
    BEFORE the acquisition — forcing the exact interleaving where an
    unregister lands between the poll's table read and its waiter
    registration."""

    def __init__(self, real, hook, owner):
        self._real = real
        self._hook = hook
        self._owner = owner
        self.fired = False

    def __enter__(self):
        if not self.fired and threading.current_thread() is self._owner:
            self.fired = True
            self._hook()
        return self._real.__enter__()

    def __exit__(self, *a):
        return self._real.__exit__(*a)


class _FakeConn:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)


def test_long_poll_unregister_race_gets_terminal_answer(cluster):
    """The race: _on_fetch_table reads the table (registered), an
    unregister fully completes (waiter list popped — nothing to wake),
    THEN the poll registers its waiter. Pre-fix it sat parked until the
    deadline sweeper; now the re-check answers it terminally at once."""
    driver, _execs = cluster
    driver.register_shuffle(42, num_maps=2)
    real = driver._waiters_lock
    driver._waiters_lock = _HookLock(
        real, lambda: driver.unregister_shuffle(42),
        threading.current_thread())
    try:
        conn = _FakeConn()
        t0 = time.monotonic()
        resp = driver._on_fetch_table(
            conn, M.FetchTableReq(1, 42, min_published=2, timeout_ms=5000))
        dt = time.monotonic() - t0
    finally:
        driver._waiters_lock = real
    assert driver._waiters_lock is real
    # answered immediately (returned or sent), terminally, within ms —
    # NOT the 5 s deadline
    answers = ([resp] if resp is not None else []) + conn.sent
    assert len(answers) == 1, answers
    assert answers[0].num_published < 0
    assert dt < 1.0, f"poll burned {dt:.2f}s of its deadline"
    # and no orphan waiter is left behind for the sweeper
    assert 42 not in driver._waiters


def test_long_poll_unregister_while_parked_wakes(cluster):
    """The pre-existing path: a parked long-poll is woken terminally by
    unregister (no full-deadline burn) — the client surfaces it as the
    not-registered TimeoutError immediately."""
    driver, execs = cluster
    driver.register_shuffle(43, num_maps=4)
    execs[0].publish_map_output(43, 0, table_token=1)
    errs = []

    def poll():
        t0 = time.monotonic()
        try:
            execs[2].get_driver_table(43, expect_published=4, timeout=30)
            errs.append(("no-error", 0.0))
        except TimeoutError as e:
            errs.append((str(e), time.monotonic() - t0))

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.3)  # let the poll park at the driver
    driver.unregister_shuffle(43)
    t.join(timeout=5)
    assert not t.is_alive(), "poll never returned"
    msg, dt = errs[0]
    assert "not registered" in msg
    assert dt < 5.0, f"poll burned {dt:.1f}s instead of waking"


# -- sharded cold path ----------------------------------------------------


@pytest.fixture
def sharded_cluster():
    conf = TpuShuffleConf(connect_timeout_ms=5000,
                          max_connection_attempts=2, metadata_shards=2)
    driver = DriverEndpoint(conf)
    execs = []
    for i in range(3):
        ex = ExecutorEndpoint("127.0.0.1", str(i), driver.address,
                              conf=conf)
        execs.append(ex)
    for ex in execs:
        ex.start()
    for ex in execs:
        ex.wait_for_members(3)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


def test_sharded_table_read_serves_from_shard_hosts(sharded_cluster):
    driver, execs = sharded_cluster
    driver.register_shuffle(7, num_maps=6)
    # the shard map reaches every executor by push
    assert _wait(lambda: all(ex.location_plane.shard_map(7) is not None
                             for ex in execs))
    sm = execs[2].location_plane.shard_map(7)
    assert sm.num_shards == 2 and sm.num_maps == 6
    for m in range(6):
        execs[m % 3].publish_map_output(7, m, table_token=100 + m)
    # the driver's entry forwards to the shard replicas are async
    # one-attempt pushes: a cold sync that beats them finds no replica
    # (or a partial one) and legitimately falls back to the driver, so
    # wait for every replica to be COMPLETE before counting frames —
    # this test pins the steady-state serve path, not the forward race
    def _replicas_complete():
        for shard in range(sm.num_shards):
            lo, hi = sm.range_of(shard)
            host = next(ex for ex in execs if ex.manager_id ==
                        execs[0].member_at(sm.shard_slots[shard]))
            res = host.shard_store.read_range(7, lo, hi)
            if res is None or res[0] < hi - lo:
                return False
        return True
    assert _wait(_replicas_complete)
    # count frames at the driver vs shard hosts
    served = {"driver": 0, "shard": 0}
    orig_table = driver._on_fetch_table

    def count_table(conn, msg):
        served["driver"] += 1
        return orig_table(conn, msg)

    driver._on_fetch_table = count_table
    for ex in execs:
        orig_shard = ex._on_fetch_shard

        def count_shard(conn, msg, orig=orig_shard):
            served["shard"] += 1
            return orig(conn, msg)

        ex._on_fetch_shard = count_shard
    table = execs[2].get_driver_table(7, expect_published=6, timeout=5)
    assert table.num_published == 6
    for m in range(6):
        assert table.entry(m)[0] == 100 + m
    assert served["driver"] == 0, "cold sync still hit the driver"
    assert served["shard"] == 2, served


def test_sharded_read_long_polls_until_published(sharded_cluster):
    driver, execs = sharded_cluster
    driver.register_shuffle(8, num_maps=2)
    assert _wait(lambda: execs[2].location_plane.shard_map(8) is not None)
    execs[0].publish_map_output(8, 0, table_token=1)

    def late():
        time.sleep(0.3)
        execs[1].publish_map_output(8, 1, table_token=2)

    t = threading.Thread(target=late)
    t.start()
    table = execs[2].get_driver_table(8, expect_published=2, timeout=5)
    t.join()
    assert table.entry(1)[0] == 2


def test_sharded_read_falls_back_to_driver_on_dead_host(sharded_cluster):
    driver, execs = sharded_cluster
    driver.register_shuffle(9, num_maps=4)
    assert _wait(lambda: execs[2].location_plane.shard_map(9) is not None)
    for m in range(4):
        execs[m % 3].publish_map_output(9, m, table_token=m)
    sm = execs[2].location_plane.shard_map(9)
    # kill a shard host's server: the shard read fails, the driver
    # (authoritative) serves the sync instead
    victim_slot = sm.shard_slots[0]
    victim = next(ex for ex in execs
                  if ex.manager_id == execs[0].member_at(victim_slot))
    reader = next(ex for ex in execs if ex is not victim)
    victim.server.stop()
    time.sleep(0.1)
    table = reader.get_driver_table(9, expect_published=4, timeout=10)
    assert table.num_published == 4


def test_metadata_rpc_counting(cluster):
    """get_driver_table_v charges actual wire syncs to the passed
    metrics object; cache hits charge nothing."""
    from sparkrdma_tpu.shuffle.fetcher import ReadMetrics

    driver, execs = cluster
    driver.register_shuffle(11, num_maps=1)
    execs[0].publish_map_output(11, 0, table_token=1)
    m = ReadMetrics()
    execs[2].get_driver_table_v(11, 1, timeout=5, metrics=m)
    assert m.metadata_rpcs_per_stage == 1
    execs[2].get_driver_table_v(11, 1, timeout=5, metrics=m)
    assert m.metadata_rpcs_per_stage == 1  # warm: zero new RPCs
