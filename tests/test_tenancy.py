"""Multi-tenant shuffle service: per-tenant quotas, deficit-round-robin
fair-share serving, admission control, and shuffle TTL/GC
(``shuffle/tenancy.py`` + the tenant threading through
manager/resolver/pool/dist_cache/endpoints).

The load-bearing invariants:

* Quota exhaustion sheds exactly the offending tenant's work —
  co-hosted tenants' leases/commits/caches are untouched.
* Cache evictions are charged to the INSERTING tenant:
  ``cross_tenant_evictions`` stays 0 always (the regression gate for
  the dist_cache satellite fix).
* DRR with a single tenant is FIFO bit-for-bit (every pre-tenancy
  deployment is the degenerate case).
* Admission sheds load as queue-or-reject with a retry-after hint,
  never as an OOM; the TTL sweep + orphan reap bound disk.
* Fair-share serving changes ONLY request ordering: outputs stay
  byte-identical on both serve paths.
"""

import os
import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.runtime import native
from sparkrdma_tpu.runtime.pool import BufferPool
from sparkrdma_tpu.shuffle import dist_cache
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.tenancy import (
    DEFAULT_TENANT,
    AdmissionController,
    AdmissionRejected,
    DeficitRoundRobin,
    TenantLedger,
    TenantQuotaError,
    effective_hbm_budget,
)

CONF_KW = dict(connect_timeout_ms=5000, use_cpp_runtime=False,
               pre_warm_connections=False)


# -- TenantLedger --------------------------------------------------------


def test_ledger_charge_release_and_quota():
    led = TenantLedger("pool", quota=100)
    led.charge(1, 60)
    led.charge(2, 90)  # independent tenants, independent budgets
    with pytest.raises(TenantQuotaError) as ei:
        led.charge(1, 50)
    assert ei.value.tenant == 1 and ei.value.quota == 100
    assert led.rejections == 1
    assert led.usage(1) == 60  # failed charge left nothing behind
    led.release(1, 60)
    led.charge(1, 100)  # exactly at quota fits
    assert led.snapshot() == {1: 100, 2: 90}


def test_ledger_unbounded_and_double_release():
    led = TenantLedger("spill", quota=0)
    led.charge(7, 1 << 40)  # quota 0 = unbounded
    led.release(7, 1 << 41)  # double/over-release floors at zero...
    assert led.usage(7) == 0
    led.charge(7, 5)  # ...and cannot corrupt later admissions
    assert led.usage(7) == 5
    led.charge(7, 0)
    led.charge(7, -3)  # non-positive charges are no-ops
    assert led.usage(7) == 5


# -- DeficitRoundRobin ---------------------------------------------------


def test_drr_single_tenant_is_fifo():
    q = DeficitRoundRobin(quantum=1024)
    items = [(i, 10_000 * (i % 3)) for i in range(50)]  # mixed costs
    for i, cost in items:
        q.push(DEFAULT_TENANT, cost, i)
    assert q.drain() == [i for i, _ in items]
    assert q.reordered == 0  # the degenerate case IS arrival order


def test_drr_small_request_jumps_wide_backlog():
    # tenant 0 floods 32 wide reads; tenant 1 then queues ONE small
    # fetch. Under FIFO it would wait out the whole backlog; under DRR
    # it dispatches within the first round.
    q = DeficitRoundRobin(quantum=64 << 10)
    for i in range(32):
        q.push(0, 1 << 20, ("wide", i))
    q.push(1, 4 << 10, ("small", 0))
    order = q.drain()
    assert order.index(("small", 0)) <= 2
    assert q.reordered >= 1
    # per-tenant FIFO preserved: tenant 0's wide reads stay in order
    wides = [x for x in order if x[0] == "wide"]
    assert wides == [("wide", i) for i in range(32)]


def test_drr_interleaves_equal_load():
    q = DeficitRoundRobin(quantum=100)
    for i in range(10):
        q.push(0, 100, ("a", i))
        q.push(1, 100, ("b", i))
    order = q.drain()
    # each round grants one quantum = one item per tenant: strict
    # alternation (whichever tenant leads, neither ever runs 3 deep)
    for k in range(len(order) - 2):
        assert not (order[k][0] == order[k + 1][0] == order[k + 2][0])


def test_drr_len_and_empty_pop():
    q = DeficitRoundRobin()
    assert q.pop() is None and len(q) == 0
    q.push(0, 1, "x")
    assert len(q) == 1
    assert q.pop() == "x"
    assert q.pop() is None


# -- AdmissionController -------------------------------------------------


def test_admission_disabled_is_noop():
    adm = AdmissionController(max_inflight=0)
    for sid in range(100):
        adm.admit(0, sid)  # never blocks, never rejects
    assert adm.accepted == 0  # the gate isn't even counting


def test_admission_cap_queue_accept():
    adm = AdmissionController(max_inflight=1, queue_depth=4,
                              retry_after_ms=5000)
    adm.admit(0, 100)
    events = []
    done = threading.Event()

    def queued_register():
        adm.admit(0, 101, on_event=lambda k, t, w: events.append(k))
        done.set()

    t = threading.Thread(target=queued_register)
    t.start()
    time.sleep(0.1)
    assert not done.is_set()  # parked: tenant 0 is at its cap
    adm.on_unregister(0, 100)  # freeing the slot wakes the waiter
    assert done.wait(2.0)
    t.join()
    assert events == ["queue", "accept"]
    assert adm.inflight(0) == 1 and adm.queued_total == 1


def test_admission_queue_full_rejects_immediately():
    adm = AdmissionController(max_inflight=1, queue_depth=0,
                              retry_after_ms=60_000)
    adm.admit(3, 1)
    t0 = time.monotonic()
    with pytest.raises(AdmissionRejected) as ei:
        adm.admit(3, 2)  # queue_depth 0: reject without parking
    assert time.monotonic() - t0 < 1.0
    assert ei.value.retry_after_ms == 60_000
    assert adm.rejected == 1


def test_admission_park_deadline_rejects():
    adm = AdmissionController(max_inflight=1, queue_depth=4,
                              retry_after_ms=100)
    adm.admit(0, 1)
    with pytest.raises(AdmissionRejected):
        adm.admit(0, 2)  # parks, expires after ~100ms
    assert adm.rejected == 1
    # the expired waiter passed its FIFO turn: a later register admits
    adm.on_unregister(0, 1)
    adm.admit(0, 3)
    assert adm.inflight(0) == 1


def test_admission_tenants_do_not_queue_against_each_other():
    adm = AdmissionController(max_inflight=1, queue_depth=0)
    adm.admit(0, 1)
    adm.admit(1, 2)  # tenant 1 has its own cap
    with pytest.raises(AdmissionRejected):
        adm.admit(0, 3)
    assert adm.inflight(0) == 1 and adm.inflight(1) == 1


def test_admission_idempotent_reregister():
    adm = AdmissionController(max_inflight=1)
    adm.admit(0, 1)
    adm.admit(0, 1)  # same shuffle re-registering: no second slot
    assert adm.inflight(0) == 1


# -- effective_hbm_budget ------------------------------------------------


def test_hbm_budget_even_share_and_quota():
    conf = TpuShuffleConf(device_hbm_budget="64m")
    assert effective_hbm_budget(conf, 1) == 64 << 20
    assert effective_hbm_budget(conf, 2) == 32 << 20
    assert effective_hbm_budget(conf, 4) == 16 << 20
    conf2 = TpuShuffleConf(device_hbm_budget="64m",
                           tenant_hbm_quota="8m")
    assert effective_hbm_budget(conf2, 1) == 8 << 20  # quota pins
    assert effective_hbm_budget(conf2, 100) == 8 << 20


# -- BufferPool lease quotas ---------------------------------------------


@pytest.mark.parametrize("use_native", [False, True])
def test_pool_tenant_quota(use_native):
    if use_native and not native.available():
        pytest.skip("native runtime not built")
    conf = TpuShuffleConf(use_cpp_runtime=use_native,
                          min_block_size="16k",
                          tenant_pool_quota="64k")
    pool = BufferPool(conf)
    try:
        a = pool.get(40 << 10, tenant=1)  # bins to 64k = exactly quota
        assert pool.tenant_leased_bytes(1) == 64 << 10
        with pytest.raises(TenantQuotaError):
            pool.get(1, tenant=1)  # anything more is over
        b = pool.get(40 << 10, tenant=2)  # sibling tenant unaffected
        assert pool.tenant_leased_bytes(2) == 64 << 10
        stats_tenants = pool.stats()["tenant_leased_bytes"]
        assert stats_tenants == {1: 64 << 10, 2: 64 << 10}
        a.free()
        assert pool.tenant_leased_bytes(1) == 0
        c = pool.get(40 << 10, tenant=1)  # released bytes re-admit
        c.free()
        b.free()
    finally:
        pool.stop()


def test_pool_default_tenant_unbounded():
    conf = TpuShuffleConf(use_cpp_runtime=False, tenant_pool_quota=0)
    pool = BufferPool(conf)
    try:
        bufs = [pool.get(1 << 20) for _ in range(8)]  # no tenant, no cap
        for b in bufs:
            b.free()
        assert "tenant_leased_bytes" not in pool.stats()
    finally:
        pool.stop()


# -- dist_cache: per-tenant charging, zero cross-tenant eviction ---------


def _reset_cache(budget, tenant_quota=0):
    with dist_cache._lock:
        dist_cache._cache.clear()
        dist_cache._ranges.clear()
        dist_cache._bytes.clear()
        dist_cache._tenants.clear()
    dist_cache.configure(budget, tenant_quota=tenant_quota)


def _put(sid, nbytes, epoch=1):
    keys = np.zeros(nbytes // 8, dtype=np.uint64)
    payload = np.zeros((0, 0), dtype=np.uint8)
    return dist_cache.put_range(sid, epoch, 0, 4, keys, payload)


def test_cache_no_cross_tenant_eviction():
    # the satellite regression: tenant 1's warm iterative ranges must
    # survive tenant 2's cold bulk insert storm
    _reset_cache(64 << 10)
    before = dist_cache.cross_tenant_evictions
    dist_cache.set_tenant(1, 1)
    assert _put(1, 16 << 10)  # tenant 1's warm range: 16k of 64k
    for sid in range(100, 120):  # tenant 2 floods far past the budget
        dist_cache.set_tenant(sid, 2)
        _put(sid, 8 << 10)
    assert dist_cache.get_range(1, 1, 0, 4) is not None  # survived
    assert dist_cache.cross_tenant_evictions == before
    # tenant 2 evicted ITS OWN oldest entries instead
    s = dist_cache.stats()
    assert s["evicted"] > 0
    assert s["tenant_bytes"].get(2, 0) <= dist_cache._tenant_cap_locked(2)


def test_cache_evicts_own_lru_within_share():
    _reset_cache(64 << 10)
    dist_cache.set_tenant(10, 5)
    dist_cache.set_tenant(11, 5)
    dist_cache.set_tenant(12, 5)
    assert _put(10, 24 << 10)
    assert _put(11, 24 << 10)
    assert _put(12, 24 << 10)  # 72k > 64k: shuffle 10 (LRU) evicts
    assert dist_cache.get_range(10, 1, 0, 4) is None
    assert dist_cache.get_range(11, 1, 0, 4) is not None
    assert dist_cache.get_range(12, 1, 0, 4) is not None


def test_cache_insert_declined_when_budget_held_by_sibling():
    _reset_cache(64 << 10)
    dist_cache.set_tenant(1, 1)
    assert _put(1, 48 << 10)  # tenant 1 holds 48k (sole tenant: fits)
    dist_cache.set_tenant(2, 2)
    # tenant 2 needs 32k; global headroom is 16k and tenant 1's bytes
    # are not its to evict -> declined, tenant 1 untouched
    assert not _put(2, 32 << 10)
    assert dist_cache.get_range(1, 1, 0, 4) is not None
    assert dist_cache.cross_tenant_evictions == 0
    # a fit inside its own share succeeds (2 active tenants: 32k each)
    assert _put(2, 8 << 10)
    assert dist_cache.get_range(2, 1, 0, 4) is not None


def test_cache_explicit_quota_caps_single_tenant():
    _reset_cache(1 << 20, tenant_quota=16 << 10)
    dist_cache.set_tenant(1, 1)
    assert not _put(1, 32 << 10)  # over the explicit per-tenant cap
    assert _put(1, 8 << 10)


def test_cache_terminal_epoch_forgets_tenant():
    _reset_cache(1 << 20)
    dist_cache.set_tenant(1, 7)
    assert _put(1, 8 << 10)
    dist_cache.on_epoch(1, -1)  # EPOCH_DEAD
    with dist_cache._lock:
        assert 1 not in dist_cache._tenants


# -- e2e: tenant threading, disk quota, TTL/GC, fair-share serving -------


def _cluster(tmp_path, n=2, **kw):
    conf = TpuShuffleConf(**dict(CONF_KW, **kw))
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _write_shuffle(driver, execs, sid, tenant, num_maps=3, parts=4,
                   rows=512, seed=0, owner=None):
    handle = driver.register_shuffle(sid, num_maps, parts,
                                     PartitionerSpec("modulo"),
                                     row_payload_bytes=8, tenant=tenant)
    rng = np.random.default_rng(seed)
    for m in range(num_maps):
        w = execs[owner if owner is not None
                  else m % len(execs)].get_writer(handle, m)
        w.write_batch(rng.integers(0, 1000, rows).astype(np.uint64),
                      rng.integers(0, 255, (rows, 8)).astype(np.uint8))
        w.close()
    return handle


def _canon(k, p):
    rows = np.concatenate(
        [k[:, None].view(np.uint8).reshape(len(k), 8), p], axis=1)
    return rows[np.lexsort(rows.T[::-1])]


def test_tenant_minted_and_pushed(tmp_path):
    driver, execs = _cluster(tmp_path)
    try:
        handle = _write_shuffle(driver, execs, 1, tenant=7)
        assert handle.tenant == 7
        assert driver.driver.tenant_of(1) == 7
        # the one-sided TenantMapMsg push lands on every executor
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(ex.executor.tenant_of(1) == 7 for ex in execs):
                break
            time.sleep(0.02)
        assert all(ex.executor.tenant_of(1) == 7 for ex in execs)
        # the handle path taught the resolvers too (the lost-push
        # backstop), and the cache got the mapping
        assert all(ex.resolver.tenant_of(1) == 7 for ex in execs)
        with dist_cache._lock:
            assert dist_cache._tenants.get(1) == 7
    finally:
        _shutdown(driver, execs)


def test_default_tenant_no_wire_frames(tmp_path):
    # the degenerate case must put ZERO tenancy frames on the wire
    driver, execs = _cluster(tmp_path)
    try:
        seen = []
        orig = driver.driver._queue_push

        def spy(slot, msg):
            seen.append(type(msg).__name__)
            return orig(slot, msg)

        driver.driver._queue_push = spy
        _write_shuffle(driver, execs, 1, tenant=0)
        assert "TenantMapMsg" not in seen
    finally:
        _shutdown(driver, execs)


def test_spill_quota_fails_commit_cleanly(tmp_path):
    # tenant 1 has a 4k disk quota: its commit must fail with
    # TenantQuotaError (tmp reaped), while tenant 2 commits freely
    driver, execs = _cluster(tmp_path, tenant_spill_quota="4k")
    try:
        h1 = driver.register_shuffle(1, 1, 2, PartitionerSpec("modulo"),
                                     row_payload_bytes=8, tenant=1)
        w = execs[0].get_writer(h1, 0)
        rng = np.random.default_rng(0)
        w.write_batch(rng.integers(0, 100, 2048).astype(np.uint64),
                      rng.integers(0, 255, (2048, 8)).astype(np.uint8))
        with pytest.raises(TenantQuotaError):
            w.close()  # 2048 rows * 16B = 32k > 4k quota
        spill_dir = execs[0].resolver.spill_dir
        leftovers = [f for f in os.listdir(spill_dir)
                     if not f.startswith("merge")]
        assert leftovers == []  # every tmp/data file reaped
        assert execs[0].resolver.disk_ledger.usage(1) == 0
        # tenant 1's exhaustion does not bleed into tenant 2: a commit
        # within tenant 2's OWN quota on the same executor works
        h2 = _write_shuffle(driver, execs, 2, tenant=2, num_maps=1,
                            rows=128, owner=0)  # 128*16B = 2k < 4k
        k, p = execs[1].get_reader(h2, 0, 4).read_all()
        assert len(k) == 128
        assert execs[0].resolver.disk_ledger.usage(2) == 2048
    finally:
        _shutdown(driver, execs)


def test_ttl_gc_unregisters_and_reaps_disk(tmp_path):
    # a shuffle past its TTL is unregistered by the driver sweep and
    # its committed outputs disappear from executor disk (ROADMAP item
    # 1's shuffle TTL/GC); a young shuffle survives the same sweep
    driver, execs = _cluster(tmp_path, shuffle_ttl_ms=30_000)
    try:
        h_old = _write_shuffle(driver, execs, 1, tenant=1, owner=0)
        _write_shuffle(driver, execs, 2, tenant=1, owner=0, seed=1)
        spill_dir = execs[0].resolver.spill_dir

        def files_of(sid):
            return [f for f in os.listdir(spill_dir)
                    if f.startswith(f"shuffle_{sid}_")]

        assert files_of(1) and files_of(2)
        # deterministic sweep: pretend 31s passed for shuffle 1 only
        with driver.driver._tables_lock:
            driver.driver._register_times[1] -= 31.0
        expired = driver.driver.gc_sweep()
        assert expired == [1]
        assert driver.driver.gc_expired == 1
        assert driver.driver.live_shuffles() == [2]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and files_of(1):
            time.sleep(0.02)  # reap runs on the executor serve pool
        assert files_of(1) == []  # expired shuffle's outputs reaped
        assert files_of(2)  # young shuffle untouched
        # the admission slot freed: driver no longer tracks shuffle 1
        assert driver.driver.tenant_of(1) == 0
        # a fetch for the dead shuffle fails authoritatively, and the
        # old handle's reader can't resurrect it
        with pytest.raises(Exception):
            execs[1].get_reader(h_old, 0, 4).read_all()
    finally:
        _shutdown(driver, execs)


def test_gc_orphan_reap(tmp_path):
    # debris of a dead process (committed triplets + merge leftovers
    # no unregister push will ever name) is swept by gc_orphans; live
    # and locally-known shuffles are never touched
    driver, execs = _cluster(tmp_path, push_merge=True, merge_replicas=1)
    try:
        handle = _write_shuffle(driver, execs, 1, tenant=1, owner=0)
        spill_dir = execs[0].resolver.spill_dir
        # plant an orphan triplet under a shuffle id nobody registered
        orphan = os.path.join(spill_dir, "shuffle_999_0.data")
        with open(orphan, "wb") as f:
            f.write(b"x" * 128)
        with open(orphan + ".index", "wb") as f:
            np.array([128], dtype=np.uint64).tofile(f)
        merge_dir = os.path.join(spill_dir, "merge")
        os.makedirs(merge_dir, exist_ok=True)
        with open(os.path.join(merge_dir, "seg_999_3.seg"), "wb") as f:
            f.write(b"y" * 64)
        live = driver.driver.live_shuffles()
        assert live == [1]
        # freshly planted files are protected by the racing-commit age
        # guard; only past it do they become eligible
        assert execs[0].gc_orphans(live) == 0
        assert os.path.exists(orphan)
        reaped = execs[0].gc_orphans(live, min_age_s=0)
        assert reaped >= 1
        assert not os.path.exists(orphan)
        assert not os.path.exists(orphan + ".index")
        assert not os.path.exists(os.path.join(merge_dir, "seg_999_3.seg"))
        # the live shuffle's files survived and still serve
        k, _ = execs[1].get_reader(handle, 0, 4).read_all()
        assert len(k) > 0
    finally:
        _shutdown(driver, execs)


def test_admission_e2e_register_queue_or_reject(tmp_path):
    driver, execs = _cluster(tmp_path, admission_max_inflight=1,
                             admission_queue_depth=0,
                             admission_retry_after_ms=250)
    try:
        _write_shuffle(driver, execs, 1, tenant=1)
        # tenant 1 at its cap: next register rejects with the hint
        with pytest.raises(AdmissionRejected) as ei:
            driver.register_shuffle(2, 1, 2, PartitionerSpec("modulo"),
                                    tenant=1)
        assert ei.value.retry_after_ms == 250
        # tenant 2 is not gated by tenant 1's cap
        _write_shuffle(driver, execs, 3, tenant=2, seed=2)
        # unregister frees the slot; the retried register admits
        driver.unregister_shuffle(1)
        driver.register_shuffle(2, 1, 2, PartitionerSpec("modulo"),
                                tenant=1)
        snap = driver.driver.admission.snapshot()
        assert snap["rejected"] == 1
        assert snap["inflight"] == {1: 1, 2: 1}
    finally:
        _shutdown(driver, execs)


@pytest.mark.parametrize("fair", [False, True])
def test_fair_share_serving_byte_identical(tmp_path, fair):
    # fair share changes ONLY the serve order: two tenants' concurrent
    # reads return bytes identical to the FIFO path's
    driver, execs = _cluster(tmp_path, fair_share_serving=fair,
                             shuffle_read_block_size="4k")
    try:
        h1 = _write_shuffle(driver, execs, 1, tenant=1, rows=2000,
                            owner=0)
        h2 = _write_shuffle(driver, execs, 2, tenant=2, rows=2000,
                            seed=1, owner=0)
        results = {}

        def read(tag, handle):
            r = execs[1].get_reader(handle, 0, 4)
            results[tag] = r.read_all()

        ts = [threading.Thread(target=read, args=("t1", h1)),
              threading.Thread(target=read, args=("t2", h2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for tag, handle, seed in (("t1", h1, 0), ("t2", h2, 1)):
            k, p = results[tag]
            rng = np.random.default_rng(seed)
            exp_k, exp_p = [], []
            for _ in range(handle.num_maps):
                exp_k.append(rng.integers(0, 1000, 2000).astype(np.uint64))
                exp_p.append(rng.integers(0, 255, (2000, 8)).astype(np.uint8))
            np.testing.assert_array_equal(
                _canon(k, p),
                _canon(np.concatenate(exp_k), np.concatenate(exp_p)))
        if fair:
            # the serving executor dispatched through the DRR and
            # attributed serves to both tenants
            served = execs[0].executor.fair_served
            assert served.get(1, 0) > 0 and served.get(2, 0) > 0
    finally:
        _shutdown(driver, execs)


def test_merge_store_mixed_tenant_charges_release_exactly(tmp_path):
    # pushes landing BEFORE the TenantMapMsg teaches the resolver
    # charge DEFAULT_TENANT; later ones charge the real owner — the
    # drop must repay each ledger exactly, or tenant 0 retains phantom
    # bytes while the owner's quota erases
    from sparkrdma_tpu.shuffle.push_merge import MergeStore
    from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver

    conf = TpuShuffleConf(use_cpp_runtime=False, tenant_spill_quota="1m")
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"), conf=conf)
    store = MergeStore(resolver, conf)
    try:
        status, acc = store.push(1, 0, fence=1, start_partition=0,
                                 sizes=[100], data=b"x" * 100)
        assert acc == b"\x01"
        assert resolver.disk_ledger.usage(0) == 100  # untaught yet
        resolver.note_tenant(1, 9)  # the push arrives mid-stream
        status, acc = store.push(1, 1, fence=1, start_partition=0,
                                 sizes=[50], data=b"y" * 50)
        assert acc == b"\x01"
        assert resolver.disk_ledger.usage(9) == 50
        store.drop_shuffle(1)
        assert resolver.disk_ledger.usage(0) == 0
        assert resolver.disk_ledger.usage(9) == 0
    finally:
        store.stop()


def test_unregister_prunes_executor_tenant_map(tmp_path):
    # a long-running service churning TTL'd shuffles must not leak one
    # executor-side dict entry per dead shuffle
    driver, execs = _cluster(tmp_path)
    try:
        handle = _write_shuffle(driver, execs, 1, tenant=7)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                any(ex.executor.tenant_of(1) != 7 for ex in execs):
            time.sleep(0.02)
        driver.unregister_shuffle(handle.shuffle_id)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with execs[0].executor._tenant_lock:
                pruned = all(1 not in ex.executor._tenant_map
                             for ex in execs)
            if pruned:
                break
            time.sleep(0.02)
        for ex in execs:
            with ex.executor._tenant_lock:
                assert 1 not in ex.executor._tenant_map
    finally:
        _shutdown(driver, execs)


def test_duplicate_register_other_tenant_leaks_no_slot(tmp_path):
    # a duplicate register under the WRONG tenant id must not strand a
    # phantom entry in that tenant's admission inflight set
    driver, execs = _cluster(tmp_path, admission_max_inflight=2)
    try:
        _write_shuffle(driver, execs, 1, tenant=1)
        # duplicate registers: same tenant, then a different tenant
        driver.register_shuffle(1, 3, 4, PartitionerSpec("modulo"),
                                tenant=1)
        driver.register_shuffle(1, 3, 4, PartitionerSpec("modulo"),
                                tenant=2)
        snap = driver.driver.admission.snapshot()
        assert snap["inflight"] == {1: 1}, snap  # tenant 2 holds nothing
        assert driver.driver.tenant_of(1) == 1  # owner unchanged
    finally:
        _shutdown(driver, execs)


# -- microbench acceptance (the tenant_isolation_speedup secondary) ------

# scripts/run_tenant_bench.sh sweeps extra seeds through this module;
# a red seed replays with TENANT_SEED=<seed> pytest tests/test_tenancy.py
TENANT_SEED = int(os.environ.get("TENANT_SEED", "0"))


def test_tenant_isolation_acceptance(tmp_path):
    """The ISSUE's acceptance gate: under an antagonist tenant
    saturating the serve path, fair-share scheduling cuts the victim
    tenant's p99 >= 1.5x vs FIFO, every tenant's bytes identical to its
    solo run, zero cross-tenant cache evictions."""
    from sparkrdma_tpu.shuffle.tenant_bench import (
        ANTAGONIST, VICTIM, run_isolation_microbench)

    from sparkrdma_tpu.utils.benchgate import gated_best_of

    res = gated_best_of(
        lambda: run_isolation_microbench(str(tmp_path), victim_reads=25,
                                         seed=TENANT_SEED))
    assert res["identical"], res
    assert res["cross_tenant_evictions"] == 0, res
    assert res["speedup"] >= 1.5, res
    # both tenants were actually dispatched through the DRR, and the
    # victim's small reads did jump the antagonist's backlog
    assert res["fair_served"].get(VICTIM, 0) > 0, res
    assert res["fair_served"].get(ANTAGONIST, 0) > 0, res
    assert res["drr_reordered"] > 0, res


def test_sustained_traffic_acceptance(tmp_path):
    """The sustained-traffic driver: N tenants x terasort/pagerank/join
    at a target arrival rate through admission control — every
    completed job byte-identical to its input, load shed cleanly
    (accounting closed, nothing leaked), zero cross-tenant
    evictions."""
    from sparkrdma_tpu.shuffle.tenant_bench import run_sustained_bench

    res = run_sustained_bench(str(tmp_path), duration_s=2.0,
                              seed=TENANT_SEED)
    assert res["identical"], res
    assert res["cross_tenant_evictions"] == 0, res
    jobs = res["jobs"]
    assert jobs["completed"] > 0, res
    assert jobs["completed"] + jobs["shed"] == jobs["submitted"], res
    assert res["admission"]["inflight"] == {}, res  # nothing leaked
    assert all(v is not None for v in res["per_tenant_p99_ms"].values()), res
    assert res["aggregate_rows_per_s"] > 0, res


@pytest.mark.skipif(not native.available() or not native.has_fair_serving(),
                    reason="native fair-share serving not built")
def test_native_fair_pipelined_burst_past_pending_cap(tmp_path):
    """A client pipelining MORE requests than the per-connection
    deferred cap (csrc kMaxPendingPerConn = 4096) on one connection
    must get every response: frames read into the connection buffer
    but parked by the cap have no future epoll event to announce them,
    so the fair dispatch loop itself must re-parse them once slots
    free (the stranded-frame hang regression)."""
    import socket
    import struct

    from sparkrdma_tpu.runtime.blockserver import BlockServer

    srv = BlockServer(threads=1)
    data = os.urandom(1 << 16)
    path = tmp_path / "burst.bin"
    path.write_bytes(data)
    try:
        srv.register_file(7, str(path), tenant=3)
        srv.set_fair(True, 4096)
        n = 5000  # > kMaxPendingPerConn
        frames = []
        for r in range(n):
            off = (r * 131) % (len(data) - 16)
            frames.append(M.FetchBlocksReq(r, 1, [(7, off, 16)]).encode())
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=30)
        try:
            sender = threading.Thread(
                target=lambda: sock.sendall(b"".join(frames)),
                daemon=True)
            sender.start()
            got = 0
            sock.settimeout(30)
            for _ in range(n):
                hdr = b""
                while len(hdr) < 8:
                    chunk = sock.recv(8 - len(hdr))
                    assert chunk, f"server EOF after {got} responses"
                    hdr += chunk
                total, _ = struct.unpack("<II", hdr)
                body = b""
                while len(body) < total - 8:
                    chunk = sock.recv(total - 8 - len(body))
                    assert chunk, f"server EOF after {got} responses"
                    body += chunk
                resp = M.FetchBlocksResp.from_payload(body)
                assert resp.status == M.STATUS_OK, (got, resp.status)
                off = (resp.req_id * 131) % (len(data) - 16)
                assert resp.data == data[off:off + 16], got
                got += 1
            sender.join(timeout=10)
        finally:
            sock.close()
        assert got == n
        assert srv.fair_queued() >= n  # every request went through DRR
    finally:
        srv.stop()


@pytest.mark.skipif(not native.available() or not native.has_fair_serving(),
                    reason="native fair-share serving not built")
def test_native_fair_serving_byte_identical(tmp_path):
    # same property on the native serve path: bs_set_fair(1) defers
    # requests through the worker-local DRR queues, bytes unchanged
    driver, execs = _cluster(tmp_path, use_cpp_runtime=True,
                             fair_share_serving=True,
                             shuffle_read_block_size="4k")
    try:
        h1 = _write_shuffle(driver, execs, 1, tenant=1, rows=2000,
                            owner=0)
        h2 = _write_shuffle(driver, execs, 2, tenant=2, rows=2000,
                            seed=1, owner=0)
        out = {}

        def read(tag, handle):
            out[tag] = execs[1].get_reader(handle, 0, 4).read_all()

        ts = [threading.Thread(target=read, args=("t1", h1)),
              threading.Thread(target=read, args=("t2", h2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(out["t1"][0]) == 6000 and len(out["t2"][0]) == 6000
        srv = execs[0].resolver.block_server
        assert srv is not None and srv.fair_queued() > 0
    finally:
        _shutdown(driver, execs)
