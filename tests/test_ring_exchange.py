"""Pallas ring all-to-all tests: interpret-mode remote DMA on the 8-device
virtual mesh, checked against a numpy transpose oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.ring_exchange import make_ring_all_to_all

D = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


def _run(mesh, x):
    a2a = make_ring_all_to_all(mesh, "shuffle", interpret=True)
    sharding = NamedSharding(mesh, P("shuffle"))
    return np.asarray(jax.block_until_ready(a2a(jax.device_put(x, sharding))))


def test_ring_a2a_matches_transpose(mesh):
    """All-to-all of per-destination blocks == block transpose: the payload
    device i addressed to device j must end up as device j's block from i."""
    C, W = 16, 8
    rng = np.random.default_rng(0)
    x = rng.integers(0, 2**31, size=(D, D, C, W), dtype=np.uint32)
    out = _run(mesh, x)
    expect = np.swapaxes(x, 0, 1)  # out[j][i] = x[i][j]
    np.testing.assert_array_equal(out, expect)


def test_ring_a2a_identity_patterns(mesh):
    """Device-identifying payloads land on the right devices intact."""
    C, W = 4, 4
    x = np.zeros((D, D, C, W), dtype=np.uint32)
    for i in range(D):
        for j in range(D):
            x[i, j] = i * 100 + j  # "from i to j" stamp
    out = _run(mesh, x)
    for j in range(D):
        for i in range(D):
            assert (out[j, i] == i * 100 + j).all(), (i, j)


def test_ring_single_device():
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("shuffle",))
    x = np.arange(1 * 1 * 4 * 4, dtype=np.uint32).reshape(1, 1, 4, 4)
    a2a = make_ring_all_to_all(mesh1, "shuffle", interpret=True)
    out = np.asarray(a2a(jax.device_put(
        x, NamedSharding(mesh1, P("shuffle")))))
    np.testing.assert_array_equal(out, x)


def test_chunked_exchange_over_ring_transport(mesh):
    """The chunked multi-round exchange produces identical results whether it
    rides the XLA collective or the Pallas ring kernel."""
    from sparkrdma_tpu.parallel.exchange import chunked_exchange
    rng = np.random.default_rng(3)
    per_dev = 40
    rows = np.zeros((D * per_dev, 2), dtype=np.uint32)
    counts = np.zeros((D, D), dtype=np.int32)
    for d in range(D):
        dest = np.sort(rng.integers(0, D, size=per_dev))
        seg = np.stack([dest.astype(np.uint32),
                        rng.integers(0, 2**31, per_dev, dtype=np.uint32)], 1)
        rows[d * per_dev:(d + 1) * per_dev] = seg
        counts[d] = np.bincount(dest, minlength=D)
    via_collective, r1 = chunked_exchange(mesh, "shuffle", rows, counts,
                                          quota=8, impl="gather")
    via_ring, r2 = chunked_exchange(mesh, "shuffle", rows, counts,
                                    quota=8, impl="ring_interpret")
    assert r1 == r2
    for d in range(D):
        np.testing.assert_array_equal(via_ring[d], via_collective[d])


# -- shipped ring entry points: make_shuffle_exchange / make_terasort_step --

def _run_shuffle_impl(mesh, data, dest, out_factor, impl):
    from sparkrdma_tpu.parallel.exchange import make_shuffle_exchange
    exchange = make_shuffle_exchange(mesh, "shuffle", impl=impl,
                                     out_factor=out_factor)
    sharding = NamedSharding(mesh, P("shuffle"))
    received, counts, offsets, overflowed = jax.block_until_ready(
        exchange(jax.device_put(data, sharding),
                 jax.device_put(dest, sharding)))
    return (np.asarray(received), np.asarray(counts), np.asarray(offsets),
            np.asarray(overflowed))


def test_shuffle_exchange_ring_parity_no_overflow(mesh):
    """No pair over its slot: the ring transport's shuffle exchange is
    bit-identical to gather AND dense — same received rows, counts,
    offsets, and clear overflow flags."""
    capacity = 32
    rng = np.random.default_rng(11)
    data = rng.integers(0, 2**31, size=(D * capacity, 2), dtype=np.int32)
    dest = rng.integers(0, D, size=D * capacity).astype(np.int32)
    ring = _run_shuffle_impl(mesh, data, dest, 2, "ring_interpret")
    for other in ("gather", "dense"):
        ref = _run_shuffle_impl(mesh, data, dest, 2, other)
        np.testing.assert_array_equal(ring[1], ref[1])  # counts
        np.testing.assert_array_equal(ring[2], ref[2])  # offsets
        np.testing.assert_array_equal(ring[0], ref[0])  # received rows
        assert not ring[3].any() and not ref[3].any()


def test_shuffle_exchange_ring_overflow_flag_agreement(mesh):
    """Everyone floods device 5 past its pair slot: the ring transport
    must raise the same per-device overflow flags as dense (they share
    the slot layout), never silently truncate."""
    capacity = 32
    data = np.arange(D * capacity, dtype=np.int32)
    dest = np.full(D * capacity, 5, np.int32)
    ring = _run_shuffle_impl(mesh, data, dest, 2, "ring_interpret")
    dense = _run_shuffle_impl(mesh, data, dest, 2, "dense")
    np.testing.assert_array_equal(ring[3], dense[3])
    assert ring[3].any(), "flood past the pair slot must overflow"


def test_terasort_ring_parity(mesh):
    """make_terasort_step over the ring transport sorts bit-identically
    to the gather and dense transports on the same rows."""
    from sparkrdma_tpu.models.terasort import (
        TeraSortConfig, generate_rows, run_terasort)
    cfg = TeraSortConfig(rows_per_device=256, payload_words=2, out_factor=2)
    rows = generate_rows(cfg, D, seed=4)
    out_ring, counts_ring, _ = run_terasort(mesh, cfg, impl="ring_interpret",
                                            rows=rows)
    for other in ("gather", "dense"):
        out_ref, counts_ref, _ = run_terasort(mesh, cfg, impl=other,
                                              rows=rows)
        np.testing.assert_array_equal(counts_ring, counts_ref)
        np.testing.assert_array_equal(out_ring, out_ref)
