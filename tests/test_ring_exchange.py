"""Pallas ring all-to-all tests: interpret-mode remote DMA on the 8-device
virtual mesh, checked against a numpy transpose oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.ring_exchange import make_ring_all_to_all

D = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


def _run(mesh, x):
    a2a = make_ring_all_to_all(mesh, "shuffle", interpret=True)
    sharding = NamedSharding(mesh, P("shuffle"))
    return np.asarray(jax.block_until_ready(a2a(jax.device_put(x, sharding))))


def test_ring_a2a_matches_transpose(mesh):
    """All-to-all of per-destination blocks == block transpose: the payload
    device i addressed to device j must end up as device j's block from i."""
    C, W = 16, 8
    rng = np.random.default_rng(0)
    x = rng.integers(0, 2**31, size=(D, D, C, W), dtype=np.uint32)
    out = _run(mesh, x)
    expect = np.swapaxes(x, 0, 1)  # out[j][i] = x[i][j]
    np.testing.assert_array_equal(out, expect)


def test_ring_a2a_identity_patterns(mesh):
    """Device-identifying payloads land on the right devices intact."""
    C, W = 4, 4
    x = np.zeros((D, D, C, W), dtype=np.uint32)
    for i in range(D):
        for j in range(D):
            x[i, j] = i * 100 + j  # "from i to j" stamp
    out = _run(mesh, x)
    for j in range(D):
        for i in range(D):
            assert (out[j, i] == i * 100 + j).all(), (i, j)


def test_ring_single_device():
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("shuffle",))
    x = np.arange(1 * 1 * 4 * 4, dtype=np.uint32).reshape(1, 1, 4, 4)
    a2a = make_ring_all_to_all(mesh1, "shuffle", interpret=True)
    out = np.asarray(a2a(jax.device_put(
        x, NamedSharding(mesh1, P("shuffle")))))
    np.testing.assert_array_equal(out, x)


def test_chunked_exchange_over_ring_transport(mesh):
    """The chunked multi-round exchange produces identical results whether it
    rides the XLA collective or the Pallas ring kernel."""
    from sparkrdma_tpu.parallel.exchange import chunked_exchange
    rng = np.random.default_rng(3)
    per_dev = 40
    rows = np.zeros((D * per_dev, 2), dtype=np.uint32)
    counts = np.zeros((D, D), dtype=np.int32)
    for d in range(D):
        dest = np.sort(rng.integers(0, D, size=per_dev))
        seg = np.stack([dest.astype(np.uint32),
                        rng.integers(0, 2**31, per_dev, dtype=np.uint32)], 1)
        rows[d * per_dev:(d + 1) * per_dev] = seg
        counts[d] = np.bincount(dest, minlength=D)
    via_collective, r1 = chunked_exchange(mesh, "shuffle", rows, counts,
                                          quota=8, impl="gather")
    via_ring, r2 = chunked_exchange(mesh, "shuffle", rows, counts,
                                    quota=8, impl="ring_interpret")
    assert r1 == r2
    for d in range(D):
        np.testing.assert_array_equal(via_ring[d], via_collective[d])
