"""Device-side reduce-by-key ops vs numpy oracles."""

import jax.numpy as jnp
import numpy as np
import pytest

from sparkrdma_tpu.ops.aggregate import count_by_key, segment_reduce_by_key


def _padded_sorted(rng, n_valid, cap, key_space=20):
    keys = np.sort(rng.integers(0, key_space, n_valid)).astype(np.uint32)
    vals = rng.integers(1, 100, n_valid).astype(np.int32)
    pk = np.full(cap, np.iinfo(np.uint32).max, np.uint32)
    pv = np.zeros(cap, np.int32)
    pk[:n_valid] = keys
    pv[:n_valid] = vals
    valid = np.arange(cap) < n_valid
    return pk, pv, valid, keys, vals


@pytest.mark.parametrize("op,np_op", [("sum", np.sum), ("max", np.max),
                                      ("min", np.min)])
def test_reduce_by_key_matches_numpy(op, np_op):
    rng = np.random.default_rng(0)
    pk, pv, valid, keys, vals = _padded_sorted(rng, 150, 256)
    uniq, agg, n = segment_reduce_by_key(jnp.array(pk), jnp.array(pv),
                                         jnp.array(valid), 64, op=op)
    n = int(n)
    got = dict(zip(np.asarray(uniq)[:n].tolist(), np.asarray(agg)[:n].tolist()))
    expect = {int(k): int(np_op(vals[keys == k])) for k in np.unique(keys)}
    assert got == expect


def test_count_by_key():
    rng = np.random.default_rng(1)
    pk, pv, valid, keys, _ = _padded_sorted(rng, 90, 128, key_space=7)
    uniq, cnt, n = count_by_key(jnp.array(pk), jnp.array(valid), 16)
    n = int(n)
    got = dict(zip(np.asarray(uniq)[:n].tolist(), np.asarray(cnt)[:n].tolist()))
    expect = {int(k): int((keys == k).sum()) for k in np.unique(keys)}
    assert got == expect


def test_all_padding():
    pk = np.full(32, np.iinfo(np.uint32).max, np.uint32)
    valid = np.zeros(32, bool)
    uniq, agg, n = segment_reduce_by_key(jnp.array(pk),
                                         jnp.zeros(32, jnp.int32),
                                         jnp.array(valid), 8)
    assert int(n) == 0
    assert int(agg.sum()) == 0


def test_single_key():
    pk = np.full(16, 5, np.uint32)
    pv = np.ones(16, np.int32)
    valid = np.ones(16, bool)
    uniq, agg, n = segment_reduce_by_key(jnp.array(pk), jnp.array(pv),
                                         jnp.array(valid), 4, op="sum")
    assert int(n) == 1 and int(uniq[0]) == 5 and int(agg[0]) == 16


def test_exact_capacity_last_key_survives():
    """n_unique == max_unique exactly: the last unique key must not be
    clobbered by padding-filler scatter collisions."""
    pk = np.array([1, 2, 2, 3, 7, 7, 7], dtype=np.uint32)
    pv = np.ones(7, np.int32)
    valid = np.ones(7, bool)
    uniq, agg, n = segment_reduce_by_key(jnp.array(pk), jnp.array(pv),
                                         jnp.array(valid), 4, op="sum")
    assert int(n) == 4
    assert np.asarray(uniq).tolist() == [1, 2, 3, 7]
    assert np.asarray(agg).tolist() == [1, 2, 1, 3]
