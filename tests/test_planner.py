"""Adaptive skew-aware reduce planner (shuffle/planner.py).

Unit layer: plan determinism, exact (partition x map) tiling,
coalesce/split boundary cases, placement policy, re-plan orphan rules,
wire round-trips. Cluster layer: byte-identical output vs the static
plan on every dataplane combo, plan push/cache-first resolution, warm
read-cache invalidation on plan-epoch change, least-loaded re-placement,
and the skew microbench acceptance gates (``SKEW_SEED`` sweeps extra
seeds via scripts/run_skew_bench.sh).
"""

import os

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.shuffle import dist_cache
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.planner import (
    PlanTask,
    ReducePlan,
    ReducePlanner,
    SizeHistogram,
    identity_plan,
    reduce_balance,
)
from sparkrdma_tpu.shuffle.recovery import (
    run_map_stage,
    run_planned_reduce,
)

SEED = int(os.environ.get("SKEW_SEED", "0"))


def _conf(**kw):
    base = dict(coalesce_target_bytes=4096,
                split_threshold_bytes=16384,
                locality_placement=True)
    base.update(kw)
    return TpuShuffleConf(**base)


def _hist(num_maps, rows):
    h = SizeHistogram(num_maps, len(rows[0]))
    for m, row in enumerate(rows):
        h.add(m, row)
    return h


def _tiles(plan):
    """Every (partition, map) cell covered by the plan's tasks."""
    cells = []
    for t in plan.tasks:
        for p in range(t.start_partition, t.end_partition):
            for m in range(t.map_start, t.map_end):
                cells.append((p, m))
    return cells


# -- unit: plan construction ---------------------------------------------


def test_plan_deterministic_and_wire_stable():
    rng = np.random.default_rng(SEED)
    rows = [rng.integers(0, 60000, 16).tolist() for _ in range(5)]
    conf = _conf()
    owners = {m: m % 3 for m in range(5)}
    a = ReducePlanner(conf).plan(9, _hist(5, rows), owners, [0, 1, 2])
    b = ReducePlanner(conf).plan(9, _hist(5, rows), owners, [0, 1, 2])
    assert a == b
    assert ReducePlan.from_bytes(a.to_bytes()) == a


def test_plan_tiles_partition_map_space_exactly():
    """No duplicate and no lost cell, whatever the skew: the tiling is
    what makes re-plans row-exact."""
    rng = np.random.default_rng(SEED + 1)
    for _ in range(5):
        rows = [rng.integers(0, 80000, 12).tolist() for _ in range(4)]
        plan = ReducePlanner(_conf()).plan(
            9, _hist(4, rows), {m: 0 for m in range(4)}, [0, 1])
        cells = _tiles(plan)
        assert len(cells) == len(set(cells)) == 12 * 4, cells


def test_all_tiny_coalesces_into_runs():
    rows = [[10] * 12 for _ in range(4)]
    plan = ReducePlanner(_conf()).plan(9, _hist(4, rows),
                                       {m: 0 for m in range(4)}, [0])
    assert len(plan.tasks) < 12
    assert plan.counts()["coalesced_runs"] >= 1
    assert plan.counts()["split_partitions"] == 0
    assert sorted(_tiles(plan)) == [(p, m) for p in range(12)
                                    for m in range(4)]


def test_one_hot_partition_splits_by_map_range():
    rows = [[100, 100, 30000, 100] for _ in range(6)]
    plan = ReducePlanner(_conf(coalesce_target_bytes=1)).plan(
        9, _hist(6, rows), {m: m % 3 for m in range(6)}, [0, 1, 2])
    splits = [t for t in plan.tasks if t.is_split(6)]
    assert splits, plan
    assert all(t.start_partition == 2 and t.end_partition == 3
               for t in splits)
    # the split slices partition the map space in order, no overlap
    spans = sorted((t.map_start, t.map_end) for t in splits)
    assert spans[0][0] == 0 and spans[-1][1] == 6
    assert all(spans[i][1] == spans[i + 1][0]
               for i in range(len(spans) - 1))
    # near-equal bytes per slice (uniform per-map contribution here)
    sizes = [(hi - lo) for lo, hi in spans]
    assert max(sizes) - min(sizes) <= 1


def test_uniform_input_produces_identity_plan():
    """Sizes between the coalesce target and the split threshold: the
    plan must be exactly today's static plan (no regression for
    balanced workloads)."""
    rows = [[8000] * 8 for _ in range(4)]
    plan = ReducePlanner(_conf()).plan(9, _hist(4, rows),
                                       {m: 0 for m in range(4)}, [0, 1])
    assert plan.is_identity
    assert len(plan.tasks) == 8


def test_single_map_never_splits():
    rows = [[100, 10 ** 6, 100, 100]]
    plan = ReducePlanner(_conf(coalesce_target_bytes=1)).plan(
        9, _hist(1, rows), {0: 0}, [0])
    assert all(not t.is_split(1) for t in plan.tasks)


def test_split_bounds_forced_cuts_cover_scarce_maps():
    h = _hist(6, [[100] * 4 for _ in range(6)])
    assert h.split_bounds(1, 6) == [(m, m + 1) for m in range(6)]
    assert h.split_bounds(1, 4) == [(0, 2), (2, 4), (4, 5), (5, 6)]
    assert h.split_bounds(1, 1) == [(0, 6)]
    # more pieces than maps clamps
    assert h.split_bounds(1, 99) == [(m, m + 1) for m in range(6)]


def test_empty_histogram_plans_nothing_weird():
    h = SizeHistogram(4, 8)
    plan = ReducePlanner(_conf()).plan(9, h, {}, [0])
    assert sorted(_tiles(plan)) == [(p, m) for p in range(8)
                                    for m in range(4)]


# -- unit: placement + re-plan -------------------------------------------


def test_locality_placement_prefers_largest_owner():
    # slot 1 owns the maps carrying partition 0's bytes (sizes below
    # the split threshold so the partition stays one task)
    rows = [[8000, 100], [7000, 100], [100, 100]]
    owners = {0: 1, 1: 1, 2: 0}
    plan = ReducePlanner(_conf(coalesce_target_bytes=1)).plan(
        9, _hist(3, rows), owners, [0, 1, 2])
    assert plan.placement_of(0) == 1


def test_balance_cap_spreads_single_owner_stage():
    """Every byte owned by slot 0 must NOT pile every task onto slot 0
    — the cap re-creates the spread locality would destroy."""
    rows = [[20000] * 8 for _ in range(4)]
    owners = {m: 0 for m in range(4)}
    plan = ReducePlanner(_conf()).plan(9, _hist(4, rows), owners,
                                       [0, 1, 2])
    used = {t.placement for t in plan.tasks}
    assert len(used) >= 2, plan


def test_locality_placement_off_leaves_no_preference():
    rows = [[8000] * 4 for _ in range(2)]
    plan = ReducePlanner(_conf(locality_placement=False)).plan(
        9, _hist(2, rows), {0: 0, 1: 1}, [0, 1])
    assert all(t.placement == -1 for t in plan.tasks)


def test_replan_moves_only_orphans_and_bumps_epoch():
    rng = np.random.default_rng(SEED + 2)
    rows = [rng.integers(100, 60000, 10).tolist() for _ in range(4)]
    planner = ReducePlanner(_conf())
    owners = {m: m % 3 for m in range(4)}
    plan = planner.plan(9, _hist(4, rows), owners, [0, 1, 2])
    dead = 1
    completed = [t.task_id for t in plan.tasks[:2]]
    new = planner.replan(plan, _hist(4, rows), owners, [0, 2],
                         completed)
    assert new.plan_epoch == plan.plan_epoch + 1
    by_id = {t.task_id: t for t in new.tasks}
    for t in plan.tasks:
        n = by_id[t.task_id]
        # ranges NEVER change on a re-plan
        assert (n.start_partition, n.end_partition, n.map_start,
                n.map_end) == (t.start_partition, t.end_partition,
                               t.map_start, t.map_end)
        if t.task_id in completed or t.placement != dead:
            assert n.placement == t.placement  # kept
        else:
            assert n.placement in (0, 2)  # orphan moved off the dead slot


def test_reduce_balance_gauge():
    assert reduce_balance([]) == 0.0
    assert reduce_balance([10, 10, 10]) == pytest.approx(1.0)
    assert reduce_balance([10, 10, 80]) == pytest.approx(2.4)


# -- unit: wire messages --------------------------------------------------


def test_publish_msg_lengths_roundtrip():
    entry = b"\x01" * 12
    with_l = M.PublishMsg(3, 7, entry, fence=9, lengths=[1, 2, 3])
    back = M.PublishMsg.from_payload(with_l.payload())
    assert (back.shuffle_id, back.map_id, back.fence) == (3, 7, 9)
    assert back.entry == entry and back.lengths == [1, 2, 3]
    # a pre-planning publish (no lengths) decodes with lengths=None
    legacy = M.PublishMsg(3, 7, entry, fence=9)
    assert M.PublishMsg.from_payload(legacy.payload()).lengths is None
    # empty lengths survive too (an empty-partition map)
    empty = M.PublishMsg(3, 7, entry, fence=9, lengths=[])
    assert M.PublishMsg.from_payload(empty.payload()).lengths == []


def test_plan_wire_messages_roundtrip():
    plan = identity_plan(5, 3, 4, plan_epoch=7)
    push = M.ReducePlanMsg.from_payload(
        M.ReducePlanMsg(plan.to_bytes()).payload())
    assert ReducePlan.from_bytes(push.plan_bytes) == plan
    req = M.FetchPlanReq.from_payload(M.FetchPlanReq(11, 5).payload())
    assert (req.req_id, req.shuffle_id) == (11, 5)
    resp = M.FetchPlanResp.from_payload(
        M.FetchPlanResp(11, M.STATUS_OK, plan.to_bytes()).payload())
    assert resp.status == M.STATUS_OK
    assert ReducePlan.from_bytes(resp.plan_bytes) == plan


# -- cluster layer --------------------------------------------------------


def _cluster(tmp_path, n=3, **kw):
    base = dict(connect_timeout_ms=15000, use_cpp_runtime=False,
                pre_warm_connections=False, adaptive_plan=True,
                coalesce_target_bytes=4096, split_threshold_bytes=16384,
                collect_shuffle_reader_stats=True)
    base.update(kw)
    conf = TpuShuffleConf(**base)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(TpuShuffleConf(**base),
                               driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"p{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


PARTS = 8


def _skewed_map_fn(writer, m):
    rng = np.random.default_rng(7000 + SEED * 100 + m)
    keys = np.where(rng.random(2000) < 0.7, 3,
                    rng.integers(0, PARTS, 2000)).astype(np.uint64)
    writer.write_batch(keys, rng.integers(
        0, 255, (len(keys), 8), dtype=np.uint64).astype(np.uint8))


def _canonical(keys, payload):
    order = np.lexsort(tuple(payload[:, c] for c in
                             range(payload.shape[1] - 1, -1, -1))
                       + (keys,))
    return keys[order], payload[order]


@pytest.mark.parametrize("coalesce", [True, False])
@pytest.mark.parametrize("warm", [True, False])
def test_planned_reduce_matches_static_on_every_dataplane(tmp_path,
                                                          coalesce, warm):
    """Byte-identical output vs the static plan on all four dataplane
    combos (coalesced/per-map x epoch-cache on/off), with real splits
    and coalesced runs in the plan."""
    driver, execs = _cluster(tmp_path, coalesce_reads=coalesce,
                             location_epoch_cache=warm)
    try:
        handle = driver.register_shuffle(
            1, num_maps=6, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        run_map_stage(execs, handle, _skewed_map_fn)
        plan = driver.plan_reduce(handle)
        assert plan is not None and not plan.is_identity
        assert plan.counts()["split_partitions"] >= 1
        res = run_planned_reduce(execs, handle, _skewed_map_fn, driver)
        static_reader = execs[1].get_reader(handle, 0, PARTS)
        ks, ps = _canonical(*static_reader.read_all())
        ka, pa = _canonical(res.keys, res.payload)
        np.testing.assert_array_equal(ka, ks)
        np.testing.assert_array_equal(pa, ps)
        assert res.replans == 0 and res.tasks_rerun == 0
    finally:
        _shutdown(driver, execs)


def test_plan_pushed_and_resolved_cache_first(tmp_path):
    import time
    driver, execs = _cluster(tmp_path)
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        run_map_stage(execs, handle, _skewed_map_fn)
        plan = driver.plan_reduce(handle)
        # the push lands on the broadcast channel; executors resolve it
        # from their LocationPlane without a driver round trip
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(ex.executor.location_plane.plan(1) is not None
                   for ex in execs):
                break
            time.sleep(0.01)
        for ex in execs:
            cached = ex.executor.location_plane.plan(1)
            assert cached is not None and cached.plan_epoch == 1
            assert ex.executor.get_reduce_plan(1) == plan
        # an executor whose push was lost pulls it (drop + refetch)
        execs[0].executor.location_plane.invalidate(1)
        assert execs[0].executor.location_plane.plan(1) is None
        assert execs[0].executor.get_reduce_plan(1) == plan
    finally:
        _shutdown(driver, execs)


def test_no_plan_without_adaptive_conf(tmp_path):
    driver, execs = _cluster(tmp_path, adaptive_plan=False)
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        run_map_stage(execs, handle, _skewed_map_fn)
        assert driver.plan_reduce(handle) is None
        assert execs[0].executor.get_reduce_plan(1) is None
        # run_planned_reduce degrades to the identity plan
        res = run_planned_reduce(execs, handle, _skewed_map_fn, driver)
        assert res.plan.is_identity
        static_reader = execs[1].get_reader(handle, 0, PARTS)
        ks, ps = _canonical(*static_reader.read_all())
        ka, pa = _canonical(res.keys, res.payload)
        np.testing.assert_array_equal(ka, ks)
        np.testing.assert_array_equal(pa, ps)
    finally:
        _shutdown(driver, execs)


def test_replan_invalidates_warm_read_cache(tmp_path):
    """Satellite: warm dist_cache ranges are keyed by plan epoch — a
    re-plan push must drop them so a stale coalesced range never
    serves."""
    import time
    driver, execs = _cluster(tmp_path, warm_read_cache=True)
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        run_map_stage(execs, handle, _skewed_map_fn)
        plan = driver.plan_reduce(handle)
        time.sleep(0.2)  # let the plan push land (plan epoch observed)
        reader = execs[1].get_reader(handle, 0, 2)
        reader.read_all()
        ep = execs[1].executor.location_plane.known_epoch(1)
        assert dist_cache.get_range(1, ep, 0, 2) is not None
        before = dist_cache.stats()["plan_invalidations"]
        new = driver.driver.replan_reduce(1, completed_task_ids=set())
        assert new is not None and new.plan_epoch == plan.plan_epoch + 1
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if dist_cache.get_range(1, ep, 0, 2) is None:
                break
            time.sleep(0.01)
        assert dist_cache.get_range(1, ep, 0, 2) is None
        assert dist_cache.stats()["plan_invalidations"] == before + 1
    finally:
        _shutdown(driver, execs)


def test_stale_plan_push_keeps_plan_and_warm_state(tmp_path):
    """A delayed, reordered push of an OLDER plan epoch must neither
    roll the cached plan back nor wipe warm ranges cached under the
    newer plan (broadcast pushes may reorder)."""
    import time
    driver, execs = _cluster(tmp_path, warm_read_cache=True)
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        run_map_stage(execs, handle, _skewed_map_fn)
        plan1 = driver.plan_reduce(handle)
        plan2 = driver.driver.replan_reduce(1, completed_task_ids=set())
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            cached = execs[1].executor.location_plane.plan(1)
            if cached is not None and cached.plan_epoch == 2:
                break
            time.sleep(0.01)
        # warm a range under the current (epoch-2) plan regime
        execs[1].get_reader(handle, 0, 2).read_all()
        ep = execs[1].executor.location_plane.known_epoch(1)
        assert dist_cache.get_range(1, ep, 0, 2) is not None
        # the stale epoch-1 push re-delivers late
        execs[1].executor._handle(None, M.ReducePlanMsg(plan1.to_bytes()))
        assert execs[1].executor.location_plane.plan(1).plan_epoch == \
            plan2.plan_epoch
        assert dist_cache.get_range(1, ep, 0, 2) is not None, \
            "stale push wiped warm state"
    finally:
        _shutdown(driver, execs)


def test_split_map_range_reads_are_warm_keyed_separately(tmp_path):
    """A split task's (partition, map-slice) read must not alias the
    full-range warm entry for the same partitions."""
    driver, execs = _cluster(tmp_path, warm_read_cache=True)
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        run_map_stage(execs, handle, _skewed_map_fn)
        full = execs[1].get_reader(handle, 3, 4)
        kf, pf = full.read_all()
        half = execs[1].get_reader(handle, 3, 4, map_range=(0, 2))
        kh, ph = half.read_all()
        assert len(kh) < len(kf)
        # re-reads serve the right entry for each key shape
        kf2, _ = execs[1].get_reader(handle, 3, 4).read_all()
        kh2, _ = execs[1].get_reader(handle, 3, 4,
                                     map_range=(0, 2)).read_all()
        assert np.array_equal(np.sort(kf), np.sort(kf2))
        assert np.array_equal(np.sort(kh), np.sort(kh2))
    finally:
        _shutdown(driver, execs)


def test_bytes_per_reducer_histogram_and_balance(tmp_path):
    driver, execs = _cluster(tmp_path)
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        run_map_stage(execs, handle, _skewed_map_fn)
        for p in range(PARTS):
            execs[1].get_reader(handle, p, p + 1).read_all()
        snap = execs[1].reader_stats.snapshot()
        assert snap["bytes_per_reducer"]["count"] == PARTS
        # the zipf-ish hot partition makes the gauge read well over 1
        assert snap["reduce_balance"] > 2.0, snap
        assert execs[1].reader_stats.reduce_balance() == pytest.approx(
            snap["reduce_balance"], abs=0.001)
    finally:
        _shutdown(driver, execs)


def test_run_map_stage_replaces_on_least_loaded(tmp_path, monkeypatch):
    """Satellite: a write-failed map re-places on the LEAST-LOADED live
    executor per the caller's load view, not blindly the next slot."""
    from sparkrdma_tpu.shuffle.writer import WriteFailedError

    driver, execs = _cluster(tmp_path)
    try:
        handle = driver.register_shuffle(
            1, num_maps=1, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)

        class _FailingWriter:
            closed = True

            def write_batch(self, *a, **kw):
                raise WriteFailedError("injected disk failure")

            def close(self, success=True):
                return None

        monkeypatch.setattr(execs[0], "get_writer",
                            lambda *a, **kw: _FailingWriter())
        # slot 1 is heavily loaded, slot 2 idle: the re-place must pick 2
        ran = run_map_stage(execs, handle, _skewed_map_fn, [0],
                            placement={0: 0},
                            slot_loads={1: 10 ** 9, 2: 0})
        assert ran[0] == 2
    finally:
        _shutdown(driver, execs)


def test_recover_uses_planner_size_stats_for_replacement(tmp_path):
    """The recompute path feeds the planner's per-slot byte view into
    run_map_stage (the 'same stats the planner keeps' satellite)."""
    from sparkrdma_tpu.shuffle.recovery import _recovery_slot_loads

    driver, execs = _cluster(tmp_path)
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=PARTS,
            partitioner=PartitionerSpec("modulo"), row_payload_bytes=8)
        ran = run_map_stage(execs, handle, _skewed_map_fn)
        table = execs[0].executor.get_driver_table(1, 4, timeout=5)
        hist = driver.driver.size_histogram(1)
        assert hist is not None and hist.maps_recorded == 4
        loads = _recovery_slot_loads(table, 4, hist)
        # byte-weighted: each owning slot's load is its maps' real bytes
        for m, slot in ran.items():
            assert loads.get(slot, 0) > 0
        assert sum(loads.values()) == hist.total_bytes()
    finally:
        _shutdown(driver, execs)


# -- microbench acceptance (the skew_speedup secondary's gates) ----------


def test_skew_microbench_speedup_and_parity(tmp_path):
    from sparkrdma_tpu.shuffle.plan_bench import run_skew_microbench

    res = run_skew_microbench(str(tmp_path), workload="terasort",
                              seed=SEED)
    assert res["identical"], res
    assert not res["is_identity"], res
    assert res["skew_speedup"] >= 1.5, res
    # the plan visibly rebalances the stage
    assert res["reduce_balance"]["adaptive"] < \
        res["reduce_balance"]["static"], res


def test_skew_microbench_uniform_is_identity(tmp_path):
    from sparkrdma_tpu.shuffle.plan_bench import run_skew_microbench

    res = run_skew_microbench(str(tmp_path), uniform=True, seed=SEED,
                              reps=1)
    assert res["identical"] and res["is_identity"], res


@pytest.mark.slow
def test_skew_microbench_join_workload(tmp_path):
    from sparkrdma_tpu.shuffle.plan_bench import run_skew_microbench

    res = run_skew_microbench(str(tmp_path), workload="join", seed=SEED)
    assert res["identical"], res
    assert res["skew_speedup"] >= 1.5, res
