"""The unified exchange dataplane: fused device-plane parity against the
host dataplane across every exchange transport, cost-model selection,
the overflow -> host degrade, round auto-sizing/overlap traces, and the
two exchange satellites (topology-warning dedupe, chunked-quota pow2
bucketing). Seed swept by ``scripts/run_device_bench.sh`` via
``DEVICE_SEED``."""

import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from engine_helpers import make_cluster, u32_payload as _u32_payload
from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
from sparkrdma_tpu.parallel import exchange as exchange_mod
from sparkrdma_tpu.parallel.device_plane import (
    DeviceExchange,
    HostExchange,
    StageProfile,
    auto_rows_per_round,
    run_fused_exchange,
    select_dataplane,
)
from sparkrdma_tpu.shuffle.manager import PartitionerSpec
from sparkrdma_tpu.shuffle.spark_compat import ShuffleDependency
from sparkrdma_tpu.utils.trace import Tracer

SEED = int(os.environ.get("DEVICE_SEED", "0"))
D = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


@pytest.fixture
def cluster(tmp_path):
    driver, execs = make_cluster(tmp_path)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


def _canon(keys: np.ndarray, payload: np.ndarray) -> bytes:
    """Canonical partition bytes: rows sorted by (key, payload) so
    equal-key payload order (unspecified on both planes) can't fail an
    exact-bytes comparison."""
    rows = np.concatenate(
        [keys.view(np.uint8).reshape(len(keys), 8),
         np.ascontiguousarray(payload)], axis=1)
    return rows[np.lexsort(rows.T[::-1])].tobytes()


def _job(num_partitions, maps, rows, key_space, base_seed, skip_partition=None):
    """A MapStage writing deterministic tables + the canonical-bytes
    reduce; returns (stage, reduce_fn)."""

    def table(seed):
        rng = np.random.default_rng(seed)
        keys = rng.integers(0, key_space, size=rows).astype(np.uint64)
        if skip_partition is not None:
            keys = keys[keys % num_partitions != skip_partition]
        vals = rng.integers(0, 1000, size=len(keys)).astype(np.uint32)
        return keys, vals

    def map_fn(ctx, writer, task_id):
        keys, vals = table(base_seed + task_id)
        writer.write((keys, _u32_payload(vals)))

    def reduce_fn(ctx, task_id):
        keys, payload = ctx.read(0)._r.read_all()
        assert ((keys % num_partitions) == task_id).all()
        return _canon(keys, payload)

    stage = MapStage(maps, ShuffleDependency(
        num_partitions, PartitionerSpec("modulo"), row_payload_bytes=4),
        map_fn)
    return stage, reduce_fn


def _fetcher_spy(monkeypatch):
    from sparkrdma_tpu.shuffle import fetcher as fetcher_mod

    built = {"n": 0}
    orig = fetcher_mod.ShuffleFetcher.__init__

    def spy(self, *a, **kw):
        built["n"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(fetcher_mod.ShuffleFetcher, "__init__", spy)
    return built


# -- fused-step vs host-dataplane parity, all four transports ------------

@pytest.mark.parametrize("impl", ["native", "dense", "gather",
                                  "ring_interpret"])
@pytest.mark.parametrize("skip_partition", [None, 2])
def test_device_vs_host_dataplane_byte_parity(tmp_path, mesh, impl,
                                              skip_partition):
    """The same job through the fused device plane and the host
    dataplane must produce byte-identical partitions — including a
    stage with an entirely empty partition."""
    if impl == "native":
        resolved = exchange_mod.resolve_impl(mesh, "auto", "shuffle")
        if resolved != "native":
            pytest.skip("ragged-all-to-all opcode unavailable on this "
                        f"mesh (probe resolved {resolved!r})")
    P, maps, rows, key_space = 4, 5, 600, 4000
    outs = {}
    for plane in ("device", "host"):
        driver, execs = make_cluster(tmp_path / f"{impl}_{plane}")
        try:
            stage, reduce_fn = _job(P, maps, rows, key_space,
                                    1000 * SEED + 17,
                                    skip_partition=skip_partition)
            before = exchange_mod.DATA_PLANE["exchanges"]
            engine = DAGEngine(driver, execs, mesh=mesh, mesh_impl=impl,
                               dataplane=plane)
            outs[plane] = engine.run(
                ResultStage(P, reduce_fn, parents=[stage]))
            moved = exchange_mod.DATA_PLANE["exchanges"] - before
            if plane == "device":
                assert moved > 0, "device plane dispatched no collective"
            else:
                assert moved == 0, "host plane dispatched a collective"
        finally:
            for ex in execs:
                ex.stop()
            driver.stop()
    assert outs["device"] == outs["host"]


def test_empty_shuffle_on_device_plane(cluster, mesh):
    """Maps that write nothing: the fused plane serves every partition
    empty without tripping staging or the exchange."""
    driver, execs = cluster
    P = 4

    def map_fn(ctx, writer, task_id):
        writer.write((np.zeros(0, np.uint64), np.zeros((0, 4), np.uint8)))

    def reduce_fn(ctx, task_id):
        keys, payload = ctx.read(0)._r.read_all()
        return len(keys) + len(payload)

    stage = MapStage(3, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    engine = DAGEngine(driver, execs, mesh=mesh, dataplane="device")
    assert engine.run(ResultStage(P, reduce_fn, parents=[stage])) == [0] * P


# -- overflow -> host degrade --------------------------------------------

def test_overflow_degrades_stage_to_host_dataplane(cluster, mesh,
                                                   monkeypatch, caplog):
    """Every key lands in ONE partition: the receive overflows the
    out_factor headroom, and the stage — not the job — degrades to the
    host dataplane with byte-identical results."""
    import logging

    caplog.set_level(logging.WARNING, logger="sparkrdma_tpu.engine")
    driver, execs = cluster
    P, maps, rows = 4, 4, 500

    def map_fn(ctx, writer, task_id):
        rng = np.random.default_rng(300 + SEED + task_id)
        keys = (rng.integers(0, 1000, rows).astype(np.uint64) * P)  # all p0
        writer.write((keys, _u32_payload(
            rng.integers(0, 1000, rows).astype(np.uint32))))

    degraded = {}

    def reduce_fn(ctx, task_id):
        keys, payload = ctx.read(0)._r.read_all()
        # observe the degrade while the stage is alive (teardown pops
        # the memo when run() returns)
        degraded.update(holder["engine"]._mesh_degraded)
        return _canon(keys, payload)

    built = _fetcher_spy(monkeypatch)
    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    holder = {"engine": None}
    engine = holder["engine"] = DAGEngine(driver, execs, mesh=mesh,
                                          dataplane="device")
    out = engine.run(ResultStage(P, reduce_fn, parents=[stage]))

    assert list(degraded.values()) == ["receive overflow"]
    assert not engine._mesh_degraded, "teardown leaked the degrade memo"
    assert built["n"] > 0, "degrade never reached the host dataplane"
    assert any("host dataplane" in r.message for r in caplog.records)
    # truth: all rows in partition 0, others empty
    all_k, all_v = [], []
    for m in range(maps):
        rng = np.random.default_rng(300 + SEED + m)
        all_k.append(rng.integers(0, 1000, rows).astype(np.uint64) * P)
        all_v.append(rng.integers(0, 1000, rows).astype(np.uint32))
    want0 = _canon(np.concatenate(all_k),
                   _u32_payload(np.concatenate(all_v)))
    empty = _canon(np.zeros(0, np.uint64), np.zeros((0, 4), np.uint8))
    assert out == [want0, empty, empty, empty]


# -- cost model ----------------------------------------------------------

def test_cost_model_selection(mesh):
    profile = StageProfile(est_bytes=1 << 20, row_bytes=16, out_factor=2)
    # overrides win
    assert select_dataplane(mesh, "shuffle", profile,
                            override="host").plane == "host"
    forced = select_dataplane(mesh, "shuffle", profile, override="device",
                              hbm_budget=1)  # budget below one row
    assert forced.plane == "device" and forced.rows_per_round == 1
    # auto: fits one round -> one-shot device
    fits = select_dataplane(mesh, "shuffle", profile,
                            hbm_budget=64 << 20)
    assert fits.plane == "device" and fits.rows_per_round == 0
    assert fits.impl in ("native", "dense", "gather")
    # auto: bigger than a round -> chunked device with auto-sized rounds
    big = StageProfile(est_bytes=1 << 30, row_bytes=16, out_factor=2)
    chunked = select_dataplane(mesh, "shuffle", big, hbm_budget=1 << 20)
    assert chunked.plane == "device"
    assert chunked.rows_per_round == auto_rows_per_round(16, 1 << 20, 2)
    assert 0 < chunked.rows_per_round < (1 << 30) // 16 // D
    # auto: budget below one row -> host
    tiny = select_dataplane(mesh, "shuffle", profile, hbm_budget=1)
    assert tiny.plane == "host"
    # no mesh / non-resident stages can't ride the device plane
    assert select_dataplane(None, "shuffle", profile).plane == "host"
    off_mesh = StageProfile(est_bytes=1, row_bytes=16, resident=False)
    assert select_dataplane(mesh, "shuffle", off_mesh).plane == "host"
    # forcing the device plane where it declared itself unable is loud
    with pytest.raises(ValueError, match="no mesh configured"):
        select_dataplane(None, "shuffle", profile, override="device")
    with pytest.raises(ValueError, match="not resident"):
        select_dataplane(mesh, "shuffle", off_mesh, override="device")
    # the interface: both planes answer supports() honestly
    assert DeviceExchange().supports(mesh, "shuffle", profile) == (True, "")
    assert DeviceExchange().supports(None, "shuffle", profile)[0] is False
    assert HostExchange().supports(None, "shuffle", profile)[0] is True


def test_auto_rows_per_round_footprint():
    # budget / (row_bytes * (2 + 2*out_factor)): 1 MiB at 16B rows,
    # out_factor 2 -> 1 MiB / 96
    assert auto_rows_per_round(16, 1 << 20, 2) == (1 << 20) // 96
    assert auto_rows_per_round(16, 0, 2) == 0
    assert auto_rows_per_round(16, 95, 2) == 0


def test_engine_auto_budget_streams_rounds(tmp_path, mesh):
    """A tiny device_hbm_budget auto-sizes multi-round streaming (the
    mesh_rows_per_round replacement): several exchanges dispatch, exact
    results."""
    driver, execs = make_cluster(tmp_path)
    try:
        P, maps, rows, key_space = 4, 4, 400, 1000
        stage, reduce_fn = _job(P, maps, rows, key_space, 7000 + SEED)
        before = exchange_mod.DATA_PLANE["exchanges"]
        row_bytes = 4 * 3  # 2 key words + 1 payload word
        budget = row_bytes * (2 + 2 * 4) * 128  # 128 rows/round (of=4)
        engine = DAGEngine(driver, execs, mesh=mesh, dataplane="device",
                           device_hbm_budget=budget)
        out_dev = engine.run(ResultStage(P, reduce_fn, parents=[stage]))
        assert exchange_mod.DATA_PLANE["exchanges"] - before > 1, \
            "budget did not stream multiple rounds"

        stage2, reduce2 = _job(P, maps, rows, key_space, 7000 + SEED)
        engine2 = DAGEngine(driver, execs, mesh=mesh, dataplane="host")
        assert engine2.run(ResultStage(P, reduce2,
                                       parents=[stage2])) == out_dev
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_cost_model_rejects_unknown_override(mesh):
    """A typo'd device_plane escape hatch must fail loudly, not
    silently ride the cost model."""
    profile = StageProfile(est_bytes=1 << 20, row_bytes=16)
    with pytest.raises(ValueError, match="unknown dataplane override"):
        select_dataplane(mesh, "shuffle", profile, override="hsot")


@pytest.mark.parametrize("sort_mode", ["gather", "multisort", "colsort"])
def test_fused_u64_key_sort_modes_identical(mesh, sort_mode):
    """The packed-u64 (key_words=2) layout through every local-sort
    strategy: the multi-key operand sorts (gather/multisort) and the
    LSD stable passes (colsort) must order identically."""
    rng = np.random.default_rng(SEED + 9)
    N = 3000
    # low 32 bits collide often so multi-word ordering actually matters
    keys = (rng.integers(0, 2**31, N, dtype=np.uint64) << 32) \
        | rng.integers(0, 4, N, dtype=np.uint64)
    rows = np.zeros((N, 3), np.uint32)
    rows[:, :2] = keys.view(np.uint32).reshape(N, 2)
    rows[:, 2] = rng.integers(0, 2**32, N, dtype=np.uint32)
    dest = (keys % D).astype(np.int32)
    res, _ = run_fused_exchange(mesh, "shuffle", rows, dest, key_words=2,
                                impl="gather", out_factor=4,
                                sort_mode=sort_mode)
    got = []
    for d, r in enumerate(res):
        k = r[:, :2].copy().view(np.uint64).reshape(-1)
        assert (k % D == d).all()
        assert (k[:-1] <= k[1:]).all(), f"{sort_mode}: not u64-sorted"
        got.append(k)
    np.testing.assert_array_equal(np.sort(np.concatenate(got)),
                                  np.sort(keys))


# -- overlap traces ------------------------------------------------------

def test_round_overlap_traces(mesh):
    """Double-buffered rounds: round k+1's collective dispatches before
    round k is collected — one exchange.round span per round and an
    exchange.overlap instant per overlapped pair prove it."""
    rng = np.random.default_rng(SEED)
    N = 4000
    keys = rng.integers(0, 2**63, N, dtype=np.uint64)
    rows = np.zeros((N, 3), np.uint32)
    rows[:, :2] = keys.view(np.uint32).reshape(N, 2)
    rows[:, 2] = rng.integers(0, 2**32, N, dtype=np.uint32)
    dest = (keys % D).astype(np.int32)

    def run(pipeline):
        tracer = Tracer()
        res, rounds = run_fused_exchange(
            mesh, "shuffle", rows, dest, key_words=2, impl="gather",
            out_factor=4, rows_per_round=128, tracer=tracer,
            pipeline_rounds=pipeline)
        spans = [e for e in tracer._events if e["name"] == "exchange.round"]
        overlaps = [e for e in tracer._events
                    if e["name"] == "exchange.overlap"]
        return res, rounds, spans, overlaps

    res_p, rounds, spans, overlaps = run(True)
    assert rounds == -(-N // (128 * D)) and rounds >= 3
    assert len(spans) == rounds
    assert len(overlaps) == rounds - 1, \
        "rounds did not overlap (no double buffering)"
    # sequential mode: same bytes, zero overlap instants
    res_s, _, spans_s, overlaps_s = run(False)
    assert len(spans_s) == rounds and not overlaps_s
    for a, b in zip(res_p, res_s):
        np.testing.assert_array_equal(a, b)


# -- satellite: topology-warning dedupe ----------------------------------

def test_topology_warning_dedupes_per_mesh_axis(mesh, caplog):
    import logging

    caplog.set_level(logging.WARNING,
                     logger="sparkrdma_tpu.parallel.exchange")
    exchange_mod._topology_warned.discard((mesh, "shuffle"))
    for _ in range(3):
        exchange_mod._warn_topology_once(mesh, "shuffle", "probe says no")
    hits = [r for r in caplog.records if "rejects ragged" in r.message]
    assert len(hits) == 1, "warning not deduped per (mesh, axis)"


# -- satellite: chunked-quota pow2 bucketing -----------------------------

def test_bucket_quota_values():
    from sparkrdma_tpu.parallel.exchange import bucket_quota

    assert [bucket_quota(q) for q in (1, 2, 3, 5, 8, 9, 127, 128)] == \
        [1, 2, 4, 8, 8, 16, 128, 128]


def test_chunked_exchange_quota_bucketing_parity(mesh):
    """Drifting quotas bucket to one compiled round_fn; results are
    unchanged for every quota in the bucket."""
    from sparkrdma_tpu.parallel.exchange import (
        chunked_exchange,
        make_chunked_exchange,
    )

    assert make_chunked_exchange(mesh, "shuffle", 5) is \
        make_chunked_exchange(mesh, "shuffle", 8)
    assert make_chunked_exchange(mesh, "shuffle", 9) is not \
        make_chunked_exchange(mesh, "shuffle", 8)

    rng = np.random.default_rng(SEED + 4)
    per_dev = 48
    rows = np.zeros((D * per_dev, 2), dtype=np.uint32)
    counts = np.zeros((D, D), dtype=np.int32)
    for d in range(D):
        dest = np.sort(rng.integers(0, D, size=per_dev))
        rows[d * per_dev:(d + 1) * per_dev, 0] = dest
        rows[d * per_dev:(d + 1) * per_dev, 1] = rng.integers(
            0, 2**31, per_dev, dtype=np.uint32)
        counts[d] = np.bincount(dest, minlength=D)
    base, _ = chunked_exchange(mesh, "shuffle", rows, counts, quota=16)
    for quota in (7, 8, 13):  # 7/8 share a bucket; 13 buckets to 16
        got, _ = chunked_exchange(mesh, "shuffle", rows, counts,
                                  quota=quota)
        for d in range(D):
            np.testing.assert_array_equal(got[d], base[d])


# -- bench acceptance + round-JSON provenance ----------------------------

def test_fused_exchange_microbench_acceptance(tmp_path):
    """The ISSUE's acceptance gate: fused vs host-staged same-process
    A/B >= 1.5x, byte-identical."""
    from sparkrdma_tpu.shuffle.device_bench import run_device_microbench
    from sparkrdma_tpu.utils.benchgate import gated_best_of

    res = gated_best_of(lambda: run_device_microbench(str(tmp_path)))
    assert res["identical"], "dataplanes reduced different bytes"
    assert res["speedup"] >= 1.5, res


def test_bench_round_json_provenance():
    """Every bench round must record host_load_avg (the BENCH_r05
    host-contention lesson) and, on dense rounds, dense_exchange_guard;
    the fused secondary rides _secondary_workloads."""
    import inspect

    import bench as bench_mod

    detail = bench_mod._round_provenance({})
    assert len(detail["host_load_avg"]) == 3
    assert "captured_at" in detail
    main_src = inspect.getsource(bench_mod.main)
    assert "_round_provenance" in main_src
    assert "_bench_dense_guard" in main_src
    sec_src = inspect.getsource(bench_mod._secondary_workloads)
    assert "_bench_fused_exchange" in sec_src
