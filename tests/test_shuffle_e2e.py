"""End-to-end shuffle through the engine-facing API: a 3-executor in-process
cluster runs a full map/shuffle/reduce cycle with bytes verified against a
numpy oracle — the integration tier the reference never had (SURVEY.md §4).
"""

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
from sparkrdma_tpu.shuffle.manager import (
    PartitionerSpec,
    TpuShuffleManager,
)

N_EXEC = 3
CONF = TpuShuffleConf(connect_timeout_ms=5000,
                      shuffle_read_block_size="4k")  # small: forces grouping


@pytest.fixture
def cluster(tmp_path):
    driver = TpuShuffleManager(CONF, is_driver=True)
    execs = [
        TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                          executor_id=str(i),
                          spill_dir=str(tmp_path / f"exec{i}"))
        for i in range(N_EXEC)
    ]
    for ex in execs:
        ex.executor.wait_for_members(N_EXEC)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


def _run_shuffle(driver, execs, shuffle_id, num_maps, num_partitions,
                 rows_per_map=1000, payload_bytes=8, seed=0):
    handle = driver.register_shuffle(
        shuffle_id, num_maps, num_partitions,
        PartitionerSpec("modulo"), row_payload_bytes=payload_bytes)
    rng = np.random.default_rng(seed)
    all_keys, all_payloads = [], []
    for m in range(num_maps):
        keys = rng.integers(0, 10_000, size=rows_per_map).astype(np.uint64)
        payload = rng.integers(0, 255, size=(rows_per_map, payload_bytes)
                               ).astype(np.uint8)
        writer = execs[m % len(execs)].get_writer(handle, m)
        # two batches to exercise accumulation
        writer.write_batch(keys[:rows_per_map // 2], payload[:rows_per_map // 2])
        writer.write_batch(keys[rows_per_map // 2:], payload[rows_per_map // 2:])
        writer.close()
        all_keys.append(keys)
        all_payloads.append(payload)
    return handle, np.concatenate(all_keys), np.concatenate(all_payloads)


def test_full_shuffle_cycle(cluster):
    driver, execs = cluster
    handle, keys, payloads = _run_shuffle(driver, execs, 1, num_maps=6,
                                          num_partitions=9)
    # every executor reduces a slice of the partition space
    got_keys, got_payloads = [], []
    for i, ex in enumerate(execs):
        reader = ex.get_reader(handle, i * 3, (i + 1) * 3)
        k, p = reader.read_all()
        assert ((k % 9 >= i * 3) & (k % 9 < (i + 1) * 3)).all()
        got_keys.append(k)
        got_payloads.append(p)
        m = reader.metrics
        assert m.remote_fetches > 0 and m.local_fetches > 0  # both paths hit
    got_k = np.concatenate(got_keys)
    got_p = np.concatenate(got_payloads)
    assert len(got_k) == len(keys)
    # content equality irrespective of order: compare sorted (key, payload) rows
    def canon(k, p):
        rows = np.concatenate([k[:, None].view(np.uint8).reshape(len(k), 8), p],
                              axis=1)
        return rows[np.lexsort(rows.T[::-1])]
    np.testing.assert_array_equal(canon(got_k, got_p), canon(keys, payloads))


def test_read_sorted(cluster):
    driver, execs = cluster
    handle, keys, _ = _run_shuffle(driver, execs, 2, num_maps=3,
                                   num_partitions=4, payload_bytes=0)
    reader = execs[0].get_reader(handle, 0, 4)  # all partitions
    sk, _ = reader.read_sorted()
    np.testing.assert_array_equal(sk, np.sort(keys))


def test_empty_maps_and_partitions(cluster):
    driver, execs = cluster
    handle = driver.register_shuffle(3, num_maps=2, num_partitions=4,
                                     partitioner=PartitionerSpec("modulo"),
                                     row_payload_bytes=4)
    for m in range(2):
        w = execs[m].get_writer(handle, m)
        if m == 0:  # map 1 writes nothing at all
            w.write_batch(np.array([0, 1], dtype=np.uint64),
                          np.zeros((2, 4), dtype=np.uint8))
        w.close()
    k, p = execs[2].get_reader(handle, 0, 4).read_all()
    assert len(k) == 2
    k2, _ = execs[1].get_reader(handle, 2, 4).read_all()
    assert len(k2) == 0  # keys 0,1 land in partitions 0,1


def test_grouping_respects_read_block_size(cluster):
    driver, execs = cluster
    # rows land in many partitions; 4k read-block limit forces multiple
    # grouped fetches per map
    handle, keys, _ = _run_shuffle(driver, execs, 4, num_maps=2,
                                   num_partitions=8, rows_per_map=4000,
                                   payload_bytes=24)
    from sparkrdma_tpu.shuffle.reader import TpuShuffleReader

    # per-map dataplane: grouping granularity IS request granularity
    per_map = TpuShuffleReader(
        execs[2].executor, execs[2].resolver,
        TpuShuffleConf(connect_timeout_ms=5000,
                       shuffle_read_block_size="4k", coalesce_reads=False),
        handle.shuffle_id, 2, 0, 8, 24)
    k, _ = per_map.read_all()
    assert len(k) == len(keys)
    m = per_map.metrics
    # 2 maps x 4000 rows x 32B = 256KB total; with 4KB grouping there must be
    # far more than one fetch per remote map
    assert m.remote_fetches > 8
    # coalesced dataplane (cluster default): identical bytes, same 4KB
    # grouping underneath, but the groups merge into far fewer request
    # frames on the wire
    coalesced = execs[2].get_reader(handle, 0, 8)
    k2, _ = coalesced.read_all()
    assert len(k2) == len(keys)
    m2 = coalesced.metrics
    assert m2.remote_bytes == m.remote_bytes
    assert m2.requests_per_reduce < m.requests_per_reduce


def test_writer_abort_discards(cluster):
    driver, execs = cluster
    handle = driver.register_shuffle(5, num_maps=1, num_partitions=2,
                                     partitioner=PartitionerSpec("modulo"))
    w = execs[0].get_writer(handle, 0)
    w.write_batch(np.array([1, 2, 3], dtype=np.uint64))
    assert w.close(success=False) is None
    # nothing published: reader times out cleanly
    reader = execs[1].get_reader(handle, 0, 2)
    reader.fetcher.conf = CONF
    with pytest.raises((TimeoutError, FetchFailedError)):
        reader.fetcher.endpoint.get_driver_table(5, 1, timeout=0.3)


def test_fetch_failure_surfaces(cluster):
    driver, execs = cluster
    handle, _, _ = _run_shuffle(driver, execs, 6, num_maps=3, num_partitions=3)
    # kill executor 1's server after publish, then fetch from executor 0
    lost = execs[1].executor.manager_id
    execs[1].executor.server.stop()
    driver.driver.remove_member(lost)
    import time
    time.sleep(0.2)
    reader = execs[0].get_reader(handle, 0, 3)
    with pytest.raises(FetchFailedError):
        list(reader.read())


def test_unregister_cleans_up(cluster, tmp_path):
    import os
    driver, execs = cluster
    handle, _, _ = _run_shuffle(driver, execs, 7, num_maps=3, num_partitions=3)
    spill_dir = execs[0].resolver.spill_dir
    assert os.listdir(spill_dir)
    for node in execs + [driver]:
        node.unregister_shuffle(7)
    assert not os.listdir(spill_dir)


def test_read_to_device(cluster):
    """Pool-staged host->device on-ramp yields the same records."""
    driver, execs = cluster
    handle, keys, payloads = _run_shuffle(driver, execs, 8, num_maps=3,
                                          num_partitions=3)
    import numpy as np
    reader = execs[0].get_reader(handle, 0, 3)
    dk, dp = reader.read_to_device(execs[0].pool)
    # keys come back as u32 (lo, hi) word pairs
    got_k = np.asarray(dk).copy().view(np.uint64).reshape(-1)
    got_p = np.asarray(dp)
    assert got_k.shape == keys.shape

    def canon(k, p):
        rows = np.concatenate([k[:, None].view(np.uint8).reshape(len(k), 8), p],
                              axis=1)
        return rows[np.lexsort(rows.T[::-1])]
    np.testing.assert_array_equal(canon(got_k, got_p), canon(keys, payloads))


def test_reader_stats_collected(cluster, tmp_path):
    conf = TpuShuffleConf(collect_shuffle_reader_stats=True,
                          connect_timeout_ms=5000)
    driver2 = TpuShuffleManager(conf, is_driver=True)
    ex = [TpuShuffleManager(conf, driver_addr=driver2.driver_addr,
                            executor_id=f"s{i}",
                            spill_dir=str(tmp_path / f"s{i}"))
          for i in range(2)]
    for e in ex:
        e.executor.wait_for_members(2)
    try:
        import numpy as np
        handle = driver2.register_shuffle(1, 2, 2, PartitionerSpec("modulo"))
        for m in range(2):
            w = ex[m].get_writer(handle, m)
            w.write_batch(np.arange(100, dtype=np.uint64))
            w.close()
        r = ex[0].get_reader(handle, 0, 2)
        r.read_all()
        snap = ex[0].reader_stats.snapshot()
        assert snap["global"]["count"] >= 1
        assert len(snap["per_remote"]) >= 1
    finally:
        for e in ex:
            e.stop()
        driver2.stop()


def test_wire_compression_roundtrip(cluster, tmp_path):
    """DCN payload compression is transparent end-to-end."""
    conf = TpuShuffleConf(wire_compress=True, wire_compress_min="1k",
                          connect_timeout_ms=5000)
    driver2 = TpuShuffleManager(conf, is_driver=True)
    ex = [TpuShuffleManager(conf, driver_addr=driver2.driver_addr,
                            executor_id=f"c{i}",
                            spill_dir=str(tmp_path / f"c{i}"))
          for i in range(2)]
    for e in ex:
        e.executor.wait_for_members(2)
    try:
        handle = driver2.register_shuffle(1, 2, 2, PartitionerSpec("modulo"),
                                          row_payload_bytes=32)
        rng = np.random.default_rng(0)
        truth = []
        for m in range(2):
            # highly compressible payload
            keys = np.arange(3000, dtype=np.uint64)
            payload = np.zeros((3000, 32), dtype=np.uint8)
            w = ex[m].get_writer(handle, m)
            w.write_batch(keys, payload)
            w.close()
            truth.append(keys)
        reader = ex[0].get_reader(handle, 0, 2)
        k, p = reader.read_all()
        assert len(k) == 6000
        assert (p == 0).all()
        np.testing.assert_array_equal(np.sort(k),
                                      np.sort(np.concatenate(truth)))
        # wire counter sees COMPRESSED sizes: far below the raw remote
        # payload (map 1's 3000 rows x 40B); fails if compression stops
        assert 0 < ex[0].executor.wire_bytes_in < 3000 * 40 // 10
    finally:
        for e in ex:
            e.stop()
        driver2.stop()


def test_native_block_server_serves_fetches(cluster):
    """With the native runtime built, remote fetches ride the C++ epoll
    server — verified by its served-bytes counter."""
    from sparkrdma_tpu.runtime import native
    if not native.available():
        pytest.skip("native runtime not built")
    driver, execs = cluster
    assert all(e.block_server is not None for e in execs)
    handle, keys, payloads = _run_shuffle(driver, execs, 9, num_maps=3,
                                          num_partitions=3)
    reader = execs[0].get_reader(handle, 0, 3)
    k, p = reader.read_all()
    assert len(k) == len(keys)
    served = sum(e.block_server.stats()["bytes_served"] for e in execs)
    # maps 1 and 2 are remote to executor 0: 2000 rows x 16B rows
    assert served >= 2000 * 16
    reqs = sum(e.block_server.stats()["requests_served"] for e in execs)
    assert reqs > 0


def test_cli_selftest_and_config():
    """python -m sparkrdma_tpu surfaces work without touching accelerators."""
    import subprocess, sys, json, os
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-m", "sparkrdma_tpu", "selftest"],
                       capture_output=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr.decode()[-500:]
    line = [l for l in r.stdout.decode().splitlines() if l.startswith("{")]
    assert line and json.loads(line[0])["selftest"] == "ok", r.stdout.decode()[-500:]
    r2 = subprocess.run([sys.executable, "-m", "sparkrdma_tpu", "config"],
                        capture_output=True, timeout=60, env=env)
    assert r2.returncode == 0 and b"shuffle_read_block_size" in r2.stdout
    r3 = subprocess.run([sys.executable, "-m", "sparkrdma_tpu", "nope"],
                        capture_output=True, timeout=60, env=env)
    assert r3.returncode == 2


def test_hash_partitioner_host_device_identical():
    """The writer's numpy hash must match the device op bit-for-bit (rows
    partitioned on the host are fetched by device-side consumers that
    recompute the same partition ids)."""
    from sparkrdma_tpu.ops.partition import hash_partition
    keys = np.random.default_rng(3).integers(0, 2**64, 50_000, dtype=np.uint64)
    host = PartitionerSpec("hash").build(16)(keys)
    dev = np.asarray(hash_partition(keys.astype(np.uint32), 16))
    np.testing.assert_array_equal(host, dev)


def test_map_side_combine(cluster):
    """Writer-side combine collapses duplicate keys before bytes hit disk
    (the aggregator half of Spark's write path, which the reference
    inherits by wrapping Spark's writers)."""
    from sparkrdma_tpu.shuffle.writer import make_sum_combiner

    driver, execs = cluster[0], cluster[1]
    handle = driver.register_shuffle(77, num_maps=2, num_partitions=4,
                                     partitioner=PartitionerSpec("modulo"),
                                     row_payload_bytes=4)
    rng = np.random.default_rng(3)
    oracle: dict = {}
    for m in range(2):
        w = execs[m].get_writer(handle, m, combiner=make_sum_combiner("<u4"))
        keys = rng.integers(0, 20, 5000).astype(np.uint64)  # heavy dups
        vals = rng.integers(0, 1000, 5000).astype("<u4")
        for k, v in zip(keys.tolist(), vals.tolist()):
            oracle[(m, k)] = oracle.get((m, k), 0) + v
        w.write_batch(keys, vals.view(np.uint8).reshape(-1, 4))
        w.close()
        # at most one row per distinct key per map reached disk;
        # records_written counts post-combine rows (Spark recordsWritten)
        assert w.metrics["records_written"] <= 20
        assert w.metrics["bytes_written"] <= 20 * (8 + 4)

    reader = execs[0].get_reader(handle, 0, 4)
    keys, payload = reader.read_all()
    vals = np.ascontiguousarray(payload).view("<u4").ravel()
    got: dict = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        got[k] = got.get(k, 0) + int(v)
    want: dict = {}
    for (m, k), v in oracle.items():
        want[k] = want.get(k, 0) + (v & 0xFFFFFFFF)
    assert {k: v & 0xFFFFFFFF for k, v in want.items()} == \
        {k: v & 0xFFFFFFFF for k, v in got.items()}
