"""Wire codecs (the encryption/integrity stream-wrap hook): unit
round-trips, tamper detection, and an end-to-end encrypted shuffle."""

import numpy as np
import pytest

from sparkrdma_tpu.utils.codecs import (
    Codec,
    CodecError,
    get_codec,
    register_codec,
)

KEY = bytes(range(32))


AAD = b"req-context"


@pytest.mark.parametrize("name", ["hmac-sha256", "aes-gcm"])
def test_roundtrip_and_tamper(name):
    try:
        codec = get_codec(name)
    except CodecError:
        pytest.skip(f"{name} not registered (missing dependency)")
    payload = bytes(np.random.default_rng(0).integers(0, 256, 5000,
                                                      dtype=np.uint8))
    wire = codec.wrap(payload, KEY, AAD)
    assert codec.unwrap(wire, KEY, AAD) == payload
    if name == "aes-gcm":
        assert payload[:64] not in wire, "plaintext visible on the wire"
    # bit-flip anywhere must fail loudly
    flipped = bytearray(wire)
    flipped[len(flipped) // 2] ^= 1
    with pytest.raises(CodecError):
        codec.unwrap(bytes(flipped), KEY, AAD)
    # wrong key must fail loudly
    with pytest.raises(CodecError):
        codec.unwrap(wire, bytes(32), AAD)
    with pytest.raises(CodecError):
        codec.unwrap(wire[:8], KEY, AAD)  # truncation
    # replay onto a different request context must fail: an authentic
    # response for req A cannot be swapped in for req B
    with pytest.raises(CodecError):
        codec.unwrap(wire, KEY, b"other-request")


def test_unknown_codec_or_bad_key_fails_fast():
    from sparkrdma_tpu.config import TpuShuffleConf
    from sparkrdma_tpu.utils.codecs import resolve

    with pytest.raises(CodecError):
        resolve(TpuShuffleConf(wire_codec="rot13"))
    with pytest.raises(CodecError):
        resolve(TpuShuffleConf(wire_codec="hmac-sha256",
                               wire_codec_key="not-hex"))
    # empty/short keys defeat the integrity goal: rejected at resolve
    with pytest.raises(CodecError):
        resolve(TpuShuffleConf(wire_codec="hmac-sha256"))
    with pytest.raises(CodecError):
        resolve(TpuShuffleConf(wire_codec="aes-gcm",
                               wire_codec_key="ab" * 20))  # 20 bytes


def test_engine_registered_codec():
    register_codec(Codec("test-xor1",
                         lambda p, k, a: bytes(b ^ 1 for b in p),
                         lambda p, k, a: bytes(b ^ 1 for b in p)))
    c = get_codec("test-xor1")
    assert c.unwrap(c.wrap(b"abc", b"", b""), b"", b"") == b"abc"


def test_encrypted_shuffle_end_to_end(tmp_path):
    """Fetches ride aes-gcm: exact data through, and a key-mismatched
    reader fails the fetch instead of reading garbage."""
    from sparkrdma_tpu.config import TpuShuffleConf
    from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
    from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager

    try:
        get_codec("aes-gcm")
    except CodecError:
        pytest.skip("aes-gcm unavailable")
    conf = TpuShuffleConf(connect_timeout_ms=2000, max_connection_attempts=2,
                          wire_codec="aes-gcm", wire_codec_key=KEY.hex())
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(2)]
    bad_conf = TpuShuffleConf(connect_timeout_ms=2000,
                              max_connection_attempts=2,
                              wire_codec="aes-gcm",
                              wire_codec_key=bytes(32).hex())
    intruder = TpuShuffleManager(bad_conf, driver_addr=driver.driver_addr,
                                 executor_id="x",
                                 spill_dir=str(tmp_path / "x"))
    try:
        for ex in execs + [intruder]:
            ex.executor.wait_for_members(3)
        handle = driver.register_shuffle(1, num_maps=2, num_partitions=2,
                                         partitioner=PartitionerSpec("modulo"),
                                         row_payload_bytes=4)
        rng = np.random.default_rng(1)
        keys_all = []
        for m in range(2):
            w = execs[m].get_writer(handle, m)
            keys = rng.integers(0, 1000, 2000).astype(np.uint64)
            keys_all.append(keys)
            w.write_batch(keys, rng.integers(0, 255, (2000, 4), np.uint8))
            w.close()
        got, _ = execs[0].get_reader(handle, 0, 2).read_all()
        np.testing.assert_array_equal(
            np.sort(got), np.sort(np.concatenate(keys_all)))
        with pytest.raises(FetchFailedError):
            intruder.get_reader(handle, 0, 2).read_all()
    finally:
        for ex in execs + [intruder]:
            ex.stop()
        driver.stop()
