"""TPC-DS workloads: the generic star join plus the ACTUAL q64 and q95
plan shapes (models/tpcds_queries.py), each run on-mesh (chained
collective exchanges) and as an engine stage DAG, against numpy
oracles."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from sparkrdma_tpu.models.tpcds import (
    TpcdsConfig,
    build_tpcds_job,
    generate_star,
    numpy_tpcds,
    run_tpcds,
)

CFG = TpcdsConfig(fact_rows_per_device=512, dim1_size=200, dim2_size=300,
                  num_groups=64, out_factor=4)


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("shuffle",))


def test_on_mesh_matches_oracle(mesh):
    counts, sums = run_tpcds(mesh, CFG, seed=3)
    fact, dim1, dim2 = generate_star(CFG, 8, seed=3)
    want_c, want_s = numpy_tpcds(fact, dim1, dim2, CFG.num_groups)
    np.testing.assert_array_equal(counts, want_c)
    np.testing.assert_array_equal(sums, want_s)
    assert counts.sum() > 0, "degenerate query: nothing joined"


def test_heavy_skew_still_exact(mesh):
    """zipf_a -> 1.05 piles most fact rows on few keys; headroom + flags
    must keep results exact (BASELINE config #5-style skew stress)."""
    cfg = TpcdsConfig(fact_rows_per_device=256, dim1_size=50, dim2_size=80,
                      num_groups=32, zipf_a=1.05, out_factor=8)
    counts, sums = run_tpcds(mesh, cfg, seed=11)
    fact, dim1, dim2 = generate_star(cfg, 8, seed=11)
    want_c, want_s = numpy_tpcds(fact, dim1, dim2, cfg.num_groups)
    np.testing.assert_array_equal(counts, want_c)
    np.testing.assert_array_equal(sums, want_s)


def test_overflow_flag_on_insufficient_headroom(mesh):
    cfg = TpcdsConfig(fact_rows_per_device=256, dim1_size=8, dim2_size=50,
                      num_groups=16, zipf_a=1.01, out_factor=1)
    with pytest.raises(OverflowError):
        run_tpcds(mesh, cfg, seed=1)


def test_engine_plan_matches_oracle(tmp_path):
    from sparkrdma_tpu.config import TpuShuffleConf
    from sparkrdma_tpu.engine import DAGEngine
    from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager

    conf = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2)
    driver = SparkCompatShuffleManager(conf, isDriver=True)
    execs = [SparkCompatShuffleManager(
        conf, driverAddr=driver.driverAddr, executorId=str(i),
        spill_dir=str(tmp_path / f"e{i}")) for i in range(3)]
    try:
        for ex in execs:
            ex.native.executor.wait_for_members(3)
        cfg = TpcdsConfig(fact_rows_per_device=2048, dim1_size=150,
                          dim2_size=200, num_groups=48)
        job, finish = build_tpcds_job(cfg, num_maps=3, num_partitions=4,
                                      seed=5)
        counts, sums = finish(DAGEngine(driver, execs).run(job))
        fact, dim1, dim2 = generate_star(cfg, 1, seed=5)
        want_c, want_s = numpy_tpcds(fact, dim1, dim2, cfg.num_groups)
        np.testing.assert_array_equal(counts, want_c)
        np.testing.assert_array_equal(sums, want_s)
        assert counts.sum() > 0
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


# ===========================================================================
# actual q95 / q64 plan shapes (models/tpcds_queries.py)
# ===========================================================================

from sparkrdma_tpu.models.tpcds_queries import (  # noqa: E402
    Q64Config,
    Q95Config,
    build_q64_job,
    build_q95_job,
    generate_q64,
    generate_q95,
    numpy_q64,
    numpy_q95,
    run_q64,
    run_q95,
)

Q95_CFG = Q95Config(ws_rows_per_device=768, num_orders=600, out_factor=3)
Q64_CFG = Q64Config(ss_rows_per_device=640, cs_rows_per_device=512,
                    num_items=300, out_factor=4)


def test_q95_on_mesh_matches_oracle(mesh):
    got = run_q95(mesh, Q95_CFG, seed=9)
    want = numpy_q95(*generate_q95(Q95_CFG, 8, seed=9), Q95_CFG)
    assert got == want
    assert want[0] > 0, "degenerate q95: no qualifying orders"
    # the self-semi-join and returns semi-join must both bite: some rows
    # pass all dim filters yet fall to the order-level predicates
    ws, wr, date, addr, site = generate_q95(Q95_CFG, 8, seed=9)
    loose = numpy_q95(ws, np.arange(Q95_CFG.num_orders, dtype=np.uint32)
                      .reshape(-1, 1), date, addr, site, Q95_CFG)
    assert loose[0] > want[0], "returns semi-join filtered nothing"


def test_q95_dense_transport_matches(mesh):
    got = run_q95(mesh, Q95_CFG, seed=9, impl="dense")
    want = numpy_q95(*generate_q95(Q95_CFG, 8, seed=9), Q95_CFG)
    assert got == want


def test_q64_on_mesh_matches_oracle(mesh):
    got = run_q64(mesh, Q64_CFG, seed=13)
    want = numpy_q64(*generate_q64(Q64_CFG, 8, seed=13), Q64_CFG)
    assert got == want
    assert want[0] > 0, "degenerate q64: no qualifying items"


def test_q64_having_predicate_bites(mesh):
    """cs_ui's HAVING sum(sale) > 2*sum(refund) must exclude items (the
    returns-heavy items), not pass everything."""
    ss, sr, cs, cr, date = generate_q64(Q64_CFG, 8, seed=13)
    items_with_sales = len(set(cs[:, 0].tolist()))
    no_refunds = numpy_q64(ss, sr, cs, cr[:0], date, Q64_CFG)
    with_refunds = numpy_q64(ss, sr, cs, cr, date, Q64_CFG)
    assert with_refunds[0] < no_refunds[0], \
        f"HAVING filtered nothing ({items_with_sales} items)"


from engine_helpers import make_cluster as _cluster  # noqa: E402


def test_q95_engine_plan_matches_oracle(tmp_path):
    from sparkrdma_tpu.engine import DAGEngine

    driver, execs = _cluster(tmp_path)
    try:
        job, finish = build_q95_job(Q95_CFG, num_maps=3, num_partitions=4,
                                    seed=9, data_scale=8)
        got = finish(DAGEngine(driver, execs).run(job))
        want = numpy_q95(*generate_q95(Q95_CFG, 8, seed=9), Q95_CFG)
        assert got == want
        assert got[0] > 0
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_q64_engine_plan_matches_oracle(tmp_path):
    from sparkrdma_tpu.engine import DAGEngine

    driver, execs = _cluster(tmp_path)
    try:
        job, finish = build_q64_job(Q64_CFG, num_maps=3, num_partitions=4,
                                    seed=13, data_scale=8)
        got = finish(DAGEngine(driver, execs).run(job))
        want = numpy_q64(*generate_q64(Q64_CFG, 8, seed=13), Q64_CFG)
        assert got == want
        assert got[0] > 0
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
