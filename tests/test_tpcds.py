"""TPC-DS-shaped star join (q64/q95 class): on-mesh chained exchanges and
the engine-API plan, both against the numpy oracle."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from sparkrdma_tpu.models.tpcds import (
    TpcdsConfig,
    build_tpcds_job,
    generate_star,
    numpy_tpcds,
    run_tpcds,
)

CFG = TpcdsConfig(fact_rows_per_device=512, dim1_size=200, dim2_size=300,
                  num_groups=64, out_factor=4)


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("shuffle",))


def test_on_mesh_matches_oracle(mesh):
    counts, sums = run_tpcds(mesh, CFG, seed=3)
    fact, dim1, dim2 = generate_star(CFG, 8, seed=3)
    want_c, want_s = numpy_tpcds(fact, dim1, dim2, CFG.num_groups)
    np.testing.assert_array_equal(counts, want_c)
    np.testing.assert_array_equal(sums, want_s)
    assert counts.sum() > 0, "degenerate query: nothing joined"


def test_heavy_skew_still_exact(mesh):
    """zipf_a -> 1.05 piles most fact rows on few keys; headroom + flags
    must keep results exact (BASELINE config #5-style skew stress)."""
    cfg = TpcdsConfig(fact_rows_per_device=256, dim1_size=50, dim2_size=80,
                      num_groups=32, zipf_a=1.05, out_factor=8)
    counts, sums = run_tpcds(mesh, cfg, seed=11)
    fact, dim1, dim2 = generate_star(cfg, 8, seed=11)
    want_c, want_s = numpy_tpcds(fact, dim1, dim2, cfg.num_groups)
    np.testing.assert_array_equal(counts, want_c)
    np.testing.assert_array_equal(sums, want_s)


def test_overflow_flag_on_insufficient_headroom(mesh):
    cfg = TpcdsConfig(fact_rows_per_device=256, dim1_size=8, dim2_size=50,
                      num_groups=16, zipf_a=1.01, out_factor=1)
    with pytest.raises(OverflowError):
        run_tpcds(mesh, cfg, seed=1)


def test_engine_plan_matches_oracle(tmp_path):
    from sparkrdma_tpu.config import TpuShuffleConf
    from sparkrdma_tpu.engine import DAGEngine
    from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager

    conf = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2)
    driver = SparkCompatShuffleManager(conf, isDriver=True)
    execs = [SparkCompatShuffleManager(
        conf, driverAddr=driver.driverAddr, executorId=str(i),
        spill_dir=str(tmp_path / f"e{i}")) for i in range(3)]
    try:
        for ex in execs:
            ex.native.executor.wait_for_members(3)
        cfg = TpcdsConfig(fact_rows_per_device=2048, dim1_size=150,
                          dim2_size=200, num_groups=48)
        job, finish = build_tpcds_job(cfg, num_maps=3, num_partitions=4,
                                      seed=5)
        counts, sums = finish(DAGEngine(driver, execs).run(job))
        fact, dim1, dim2 = generate_star(cfg, 1, seed=5)
        want_c, want_s = numpy_tpcds(fact, dim1, dim2, cfg.num_groups)
        np.testing.assert_array_equal(counts, want_c)
        np.testing.assert_array_equal(sums, want_s)
        assert counts.sum() > 0
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
