"""Coalesced shuffle reads: one location fetch per peer + cross-map
vectored data reads.

The dataplane the RPC-count reduction rides on: parity against the
per-map paths (byte-identical across every dataplane/depth combination,
zero-length blocks and degenerate shapes included), the >=5x request
reduction on a many-small-maps shuffle (the acceptance gate), wire-
traffic shape (coalescing OFF must reproduce today's per-map traffic
exactly; ON must issue ONE batched location RPC per peer), CRC sub-block
isolation, the frame-cap derivation that keeps the Python planner in
lockstep with the C++ server limit, and the refcounted multi-view pool
lease every vectored response lands in.
"""

import os
import re

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.transport import ChecksumError
from sparkrdma_tpu.shuffle.fetch_bench import run_coalesce_microbench
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader

CONF_KW = dict(connect_timeout_ms=5000, use_cpp_runtime=False,
               pre_warm_connections=False)


def _cluster(tmp_path, n=3, **kw):
    conf = TpuShuffleConf(**dict(CONF_KW, **kw))
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _drain(reader):
    """All fetch results as a sorted multiset of attributable tuples."""
    results = []
    reader.fetcher.start()
    try:
        for r in reader.fetcher:
            results.append((r.map_id, r.start_partition, r.end_partition,
                            bytes(r.data)))
            r.free()
    finally:
        reader.fetcher.close()
    return sorted(results)


def _reader(execs, idx, handle, conf, start=None, end=None, **kw):
    return TpuShuffleReader(
        execs[idx].executor, execs[idx].resolver, conf, handle.shuffle_id,
        handle.num_maps, 0 if start is None else start,
        handle.num_partitions if end is None else end,
        handle.row_payload_bytes, **kw)


# -- parity: every dataplane fetches identical bytes ---------------------


@pytest.mark.parametrize("shape", ["mixed", "mostly_empty", "single_map"])
def test_dataplane_parity_byte_identical(tmp_path, shape):
    """Coalesced (sequential + windowed) vs per-map (sequential +
    pipelined) drain the same shuffle byte-identically, with per-map
    attribution (map_id, partition range) intact — including zero-length
    blocks, a mostly-empty partition range, and the single-map
    degenerate shuffle."""
    driver, execs = _cluster(tmp_path, shuffle_read_block_size=2048)
    try:
        num_maps = 1 if shape == "single_map" else 6
        num_partitions = 16
        handle = driver.register_shuffle(
            1, num_maps, num_partitions, PartitionerSpec("modulo"),
            row_payload_bytes=8)
        rng = np.random.default_rng(7)
        for m in range(num_maps):
            w = execs[m % 2].get_writer(handle, m)
            if shape == "mostly_empty":
                # everything lands in ONE partition: the other 15 are
                # zero-length blocks riding the same requests
                keys = np.full(64, 3, dtype=np.uint64)
            else:
                # skip odd partitions entirely -> zero-length blocks
                # interleave with data blocks in every group
                keys = (rng.integers(0, 8, size=200).astype(np.uint64) * 2)
            w.write_batch(keys, rng.integers(
                0, 255, (len(keys), 8), dtype=np.uint64).astype(np.uint8))
            w.close()

        combos = [
            ("coalesced_seq", dict(coalesce_reads=True, read_ahead_depth=1)),
            ("coalesced_win", dict(coalesce_reads=True, read_ahead_depth=8)),
            ("per_map_seq", dict(coalesce_reads=False, read_ahead_depth=1)),
            ("per_map_pipe", dict(coalesce_reads=False, read_ahead_depth=8)),
        ]
        drained = {}
        for name, kw in combos:
            conf = TpuShuffleConf(**dict(CONF_KW,
                                         shuffle_read_block_size=2048, **kw))
            drained[name] = _drain(_reader(execs, 2, handle, conf))
        baseline = drained["per_map_seq"]
        assert baseline, "shuffle drained nothing"
        for name, got in drained.items():
            assert got == baseline, f"{name} diverged from per_map_seq"
        # a partial range drains identically too (grouping offsets differ)
        conf_on = TpuShuffleConf(**dict(CONF_KW,
                                        shuffle_read_block_size=2048,
                                        coalesce_reads=True))
        conf_off = TpuShuffleConf(**dict(CONF_KW,
                                         shuffle_read_block_size=2048,
                                         coalesce_reads=False))
        lo, hi = 5, 11
        assert (_drain(_reader(execs, 2, handle, conf_on, lo, hi))
                == _drain(_reader(execs, 2, handle, conf_off, lo, hi)))
    finally:
        _shutdown(driver, execs)


def test_coalescing_disabled_reproduces_per_map_wire_traffic(tmp_path):
    """The escape hatch: with ``coalesce_reads`` off the serving peer
    sees exactly today's traffic — one FetchOutputReq per map, zero
    batched requests; with it on, ONE FetchOutputsReq covers the peer
    and no per-map location RPC is issued."""
    driver, execs = _cluster(tmp_path, n=2)
    try:
        num_maps = 5
        handle = driver.register_shuffle(1, num_maps, 4,
                                         PartitionerSpec("modulo"),
                                         row_payload_bytes=0)
        for m in range(num_maps):
            w = execs[0].get_writer(handle, m)
            w.write_batch(np.arange(16, dtype=np.uint64))
            w.close()
        ep = execs[0].executor
        served = {"per_map": 0, "batched": 0}
        orig_one, orig_many = ep._on_fetch_output, ep._on_fetch_outputs

        def count_one(msg):
            served["per_map"] += 1
            return orig_one(msg)

        def count_many(msg):
            served["batched"] += 1
            return orig_many(msg)

        ep._on_fetch_output, ep._on_fetch_outputs = count_one, count_many

        off = TpuShuffleConf(**dict(CONF_KW, coalesce_reads=False))
        assert _drain(_reader(execs, 1, handle, off))
        assert served == {"per_map": num_maps, "batched": 0}

        served.update(per_map=0, batched=0)
        # this test measures COLD wire traffic per dataplane: drop the
        # warm location views the first drain cached (the zero-RPC warm
        # path has its own wire-traffic test, test_warm_iterative.py)
        execs[1].executor.location_plane.invalidate(handle.shuffle_id)
        on = TpuShuffleConf(**dict(CONF_KW, coalesce_reads=True))
        assert _drain(_reader(execs, 1, handle, on))
        assert served == {"per_map": 0, "batched": 1}
    finally:
        _shutdown(driver, execs)


# -- the acceptance gate: >=5x fewer request frames at equal bytes -------


def test_rpc_reduction_many_small_maps(tmp_path):
    """64-map/8-partition loopback microbench: the coalesced path issues
    >=5x fewer request frames than per-map at equal total bytes,
    byte-identical — and ``ReadMetrics.requests_per_reduce`` is the
    counter that shows it (the CI guard for the RPC-count regression)."""
    res = run_coalesce_microbench(str(tmp_path), num_maps=64,
                                  num_partitions=8)
    assert res["identical"], "dataplanes fetched different bytes"
    assert res["bytes"] > 0
    per_map, coalesced = res["requests"]["per_map"], \
        res["requests"]["coalesced"]
    # per-map: 64 location RPCs + >=64 data reads; coalesced: one
    # batched location RPC + a handful of vectored reads
    assert per_map >= 2 * 64
    assert coalesced < per_map
    assert res["rpc_reduction"] >= 5.0, res


# -- CRC sub-block isolation ---------------------------------------------


def test_verify_block_crcs_names_bad_blocks():
    """The verifier checks EVERY block and reports the full bad set plus
    the stripped body — what lets a vectored fetch salvage clean
    sub-ranges and refetch only the corrupt ones."""
    from sparkrdma_tpu.parallel.endpoints import ExecutorEndpoint
    import struct
    import zlib

    ep = ExecutorEndpoint.__new__(ExecutorEndpoint)  # no sockets needed
    ep.checksum_failures = 0
    blocks = [(0, 0, 4), (0, 4, 6), (0, 10, 0), (0, 10, 5)]
    req = M.FetchBlocksReq(1, 1, blocks)
    parts = [b"aaaa", b"bbbbbb", b"", b"ccccc"]
    body = b"".join(parts)
    crcs = [zlib.crc32(p) for p in parts]
    crcs[1] ^= 0x1  # corrupt one mid-list block's checksum
    data = body + struct.pack("<4I", *crcs)
    with pytest.raises(ChecksumError) as ei:
        ep._verify_block_crcs(req, data)
    assert ei.value.bad_blocks == [1]
    assert ei.value.body == body
    assert ep.checksum_failures == 1
    # clean data passes and strips the trailer
    ok = body + struct.pack("<4I", *(zlib.crc32(p) for p in parts))
    assert ep._verify_block_crcs(req, ok) == body


# -- frame-cap derivation (satellite: no magic 8192) ---------------------


def test_max_fetch_blocks_derived_from_native_frame_cap():
    """The block-count bound is derived from the C++ server's inbound
    frame cap; the mirrored Python constant is greppped out of the .cpp
    so a drift fails here instead of at 2am in production."""
    cpp = open(os.path.join(os.path.dirname(__file__), "..", "csrc",
                            "blockserver.cpp")).read()
    m = re.search(r"kMaxReqFrame\s*=\s*(\d+)u?\s*<<\s*(\d+)", cpp)
    assert m, "kMaxReqFrame not found in csrc/blockserver.cpp"
    assert int(m.group(1)) << int(m.group(2)) == M.NATIVE_MAX_REQ_FRAME
    # auto mode: an 8x margin under the frame cap, in wire-block units
    expect = ((M.NATIVE_MAX_REQ_FRAME // 8 - M.BLOCKS_REQ_FIXED_BYTES)
              // M.BLOCK_WIRE_BYTES)
    assert TpuShuffleConf().resolved_max_fetch_blocks() == expect
    # an explicit value passes through; 0 means auto
    assert TpuShuffleConf(
        max_fetch_blocks=123).resolved_max_fetch_blocks() == 123
    # ...but never past what ONE native frame physically carries (the
    # C++ server drops the connection as a protocol error past it, which
    # no retry heals) — even when the config range allows more
    hard = ((M.NATIVE_MAX_REQ_FRAME - M.BLOCKS_REQ_FIXED_BYTES)
            // M.BLOCK_WIRE_BYTES)
    assert TpuShuffleConf(
        max_fetch_blocks=1 << 20).resolved_max_fetch_blocks() == hard
    assert hard * M.BLOCK_WIRE_BYTES + M.BLOCKS_REQ_FIXED_BYTES \
        <= M.NATIVE_MAX_REQ_FRAME
    # the derived bound actually bounds the planner: a request can never
    # exceed what one native frame carries
    assert (expect * M.BLOCK_WIRE_BYTES + M.BLOCKS_REQ_FIXED_BYTES
            <= M.NATIVE_MAX_REQ_FRAME)


def test_group_locations_honors_configured_block_cap(tmp_path):
    """A wide, mostly-empty partition range splits its groups at the
    configured block cap (zero-length blocks still count — they cost
    frame bytes, not payload bytes)."""
    from sparkrdma_tpu.shuffle.fetcher import ShuffleFetcher
    from sparkrdma_tpu.shuffle.map_output import BlockLocation

    conf = TpuShuffleConf(max_fetch_blocks=10)
    f = ShuffleFetcher.__new__(ShuffleFetcher)
    f.conf = conf
    f.start_partition = 0
    locs = [BlockLocation(0, 0, 1)] * 25  # 25 zero-ish blocks, cap 10
    groups = f._group_locations(0, 0, locs)
    assert [len(g.blocks) for g in groups] == [10, 10, 5]


# -- mixed-version fallback ----------------------------------------------


def test_batched_failure_falls_back_to_per_map(tmp_path):
    """A peer that fails the first batched location call (a
    mixed-version server tears the connection on the unknown frame type)
    is served by the per-map dataplane instead — same bytes, no error
    surfaced."""
    from sparkrdma_tpu.parallel.faults import DISCONNECT, FaultInjector

    driver, execs = _cluster(tmp_path, n=2)
    injector = FaultInjector(seed=0)
    try:
        handle = driver.register_shuffle(1, 4, 4, PartitionerSpec("modulo"),
                                         row_payload_bytes=0)
        for m in range(4):
            w = execs[0].get_writer(handle, m)
            w.write_batch(np.arange(32, dtype=np.uint64))
            w.close()
        ep = execs[0].executor
        served = {"per_map": 0, "batched": 0}
        orig_one, orig_many = ep._on_fetch_output, ep._on_fetch_outputs
        ep._on_fetch_output = lambda msg: (
            served.__setitem__("per_map", served["per_map"] + 1),
            orig_one(msg))[1]
        ep._on_fetch_outputs = lambda msg: (
            served.__setitem__("batched", served["batched"] + 1),
            orig_many(msg))[1]

        injector.install_endpoint(execs[1].executor)
        on = TpuShuffleConf(**dict(CONF_KW, coalesce_reads=True,
                                   retry_backoff_base_ms=5,
                                   retry_backoff_cap_ms=20))
        # ONE cut batched reply is a transient blip: the guarded retry
        # keeps the peer on the coalesced dataplane (no demotion)
        injector.add(DISCONNECT, msg_type=M.FetchOutputsResp, times=1)
        got = _drain(_reader(execs, 1, handle, on))
        assert got
        assert injector.fired_count(DISCONNECT) == 1
        assert served["batched"] == 2 and served["per_map"] == 0

        # BOTH attempts torn down (what an old server that drops the
        # unknown frame type does every time) -> per-map fallback.
        # Each phase measures COLD wire traffic: drop the warm location
        # views the previous drain cached (warm-path behavior has its
        # own wire-traffic test, test_warm_iterative.py)
        execs[1].executor.location_plane.invalidate(handle.shuffle_id)
        served.update(per_map=0, batched=0)
        injector.clear()
        injector.add(DISCONNECT, msg_type=M.FetchOutputsResp, times=2)
        got2 = _drain(_reader(execs, 1, handle, on))
        assert got2 == got
        # fired_count accumulates across clear(): 1 (phase one) + 2
        assert injector.fired_count(DISCONNECT) == 3
        assert served["batched"] >= 2  # both attempts reached the peer
        assert served["per_map"] == 4  # the fallback served every map
        execs[1].executor.location_plane.invalidate(handle.shuffle_id)
        off = TpuShuffleConf(**dict(CONF_KW, coalesce_reads=False))
        assert got == _drain(_reader(execs, 1, handle, off))
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


# -- pool lease landing --------------------------------------------------


def test_vectored_response_lands_in_shared_pool_lease(tmp_path):
    """With a pool, one vectored response lands in ONE refcounted
    multi-view RegisteredBuffer: every per-map result holds a view into
    the same lease, bytes are exact, and the buffer returns to the pool
    on the last ``free`` (java/RdmaRegisteredBuffer.java:28-87 made
    real)."""
    from sparkrdma_tpu.runtime.pool import BufferPool

    driver, execs = _cluster(tmp_path, n=2)
    try:
        handle = driver.register_shuffle(1, 6, 4, PartitionerSpec("modulo"),
                                         row_payload_bytes=8)
        rng = np.random.default_rng(3)
        for m in range(6):
            w = execs[0].get_writer(handle, m)
            keys = rng.integers(0, 4, size=100).astype(np.uint64)
            w.write_batch(keys, rng.integers(
                0, 255, (len(keys), 8), dtype=np.uint64).astype(np.uint8))
            w.close()
        pool = BufferPool(TpuShuffleConf(use_cpp_runtime=False))
        conf = TpuShuffleConf(**dict(CONF_KW, coalesce_reads=True))
        reader = _reader(execs, 1, handle, conf, pool=pool)
        baseline = _drain(_reader(execs, 1, handle, conf))  # bytes oracle

        results = []
        reader.fetcher.start()
        try:
            results.extend(reader.fetcher)
        finally:
            reader.fetcher.close()
        leased = [r for r in results if r.lease is not None]
        assert leased, "no vectored result landed in a pool lease"
        # 6 tiny maps coalesce into one request -> one shared lease
        assert len({id(r.lease) for r in leased}) < len(leased)
        got = sorted((r.map_id, r.start_partition, r.end_partition,
                      bytes(r.data)) for r in results)
        assert got == baseline
        assert pool.idle_bytes < pool.total_bytes  # leases still held
        for r in results:
            r.free()
        assert pool.total_bytes > 0
        assert pool.idle_bytes == pool.total_bytes  # all returned
        pool.stop()
    finally:
        _shutdown(driver, execs)


def test_close_frees_unconsumed_leases(tmp_path):
    """An abandoned iteration (failure/early-exit teardown) must return
    the pool buffers of results the consumer never took — a stage-retry
    loop would otherwise grow the executor pool without bound."""
    import time

    from sparkrdma_tpu.runtime.pool import BufferPool

    driver, execs = _cluster(tmp_path, n=2)
    try:
        handle = driver.register_shuffle(1, 6, 4, PartitionerSpec("modulo"),
                                         row_payload_bytes=8)
        rng = np.random.default_rng(9)
        for m in range(6):
            w = execs[0].get_writer(handle, m)
            keys = rng.integers(0, 4, size=100).astype(np.uint64)
            w.write_batch(keys, rng.integers(
                0, 255, (len(keys), 8), dtype=np.uint64).astype(np.uint8))
            w.close()
        pool = BufferPool(TpuShuffleConf(use_cpp_runtime=False))
        conf = TpuShuffleConf(**dict(CONF_KW, coalesce_reads=True))
        reader = _reader(execs, 1, handle, conf, pool=pool)
        it = iter(reader.fetcher.start())
        first = next(it)
        assert first.lease is not None  # the shared lease is live
        first.free()
        reader.fetcher.close()  # walk away with 5 siblings unconsumed
        deadline = time.monotonic() + 5
        while (pool.idle_bytes != pool.total_bytes
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert pool.total_bytes > 0
        assert pool.idle_bytes == pool.total_bytes, "leaked pool lease"
        pool.stop()
    finally:
        _shutdown(driver, execs)


def test_reader_frees_leases_end_to_end(tmp_path):
    """The manager-built reader (pool wired through get_reader) decodes
    lease-backed results and releases every lease: after read_all the
    reducer's pool holds no outstanding fetch buffers."""
    driver, execs = _cluster(tmp_path, n=2)
    try:
        handle = driver.register_shuffle(1, 8, 4, PartitionerSpec("modulo"),
                                         row_payload_bytes=8)
        rng = np.random.default_rng(5)
        expect_keys = []
        for m in range(8):
            w = execs[0].get_writer(handle, m)
            keys = rng.integers(0, 1000, size=200).astype(np.uint64)
            w.write_batch(keys, rng.integers(
                0, 255, (len(keys), 8), dtype=np.uint64).astype(np.uint8))
            w.close()
            expect_keys.append(keys)
        reader = execs[1].get_reader(handle, 0, 4)
        keys, _ = reader.read_all()
        expect = np.concatenate(expect_keys)
        expect = expect[expect % 4 < 4]  # all partitions in range
        assert sorted(keys.tolist()) == sorted(expect.tolist())
        pool = execs[1].pool
        assert pool.idle_bytes == pool.total_bytes
    finally:
        _shutdown(driver, execs)


# -- observability -------------------------------------------------------


def test_vectored_trace_and_request_histograms(tmp_path):
    """The coalesced dataplane proves its shape in telemetry:
    ``fetch.vectored`` spans carry maps/blocks/bytes, the existing
    issue->wire->complete contract is preserved, and the reader-stats
    snapshot grows a bytes-per-request histogram whose mass sits in the
    big buckets under coalescing."""
    from sparkrdma_tpu.utils.stats import ShuffleReaderStats
    from sparkrdma_tpu.utils.trace import Tracer

    driver, execs = _cluster(tmp_path, n=2)
    try:
        handle = driver.register_shuffle(1, 6, 8, PartitionerSpec("modulo"),
                                         row_payload_bytes=8)
        rng = np.random.default_rng(11)
        for m in range(6):
            w = execs[0].get_writer(handle, m)
            keys = rng.integers(0, 8, size=200).astype(np.uint64)
            w.write_batch(keys, rng.integers(
                0, 255, (len(keys), 8), dtype=np.uint64).astype(np.uint8))
            w.close()
        tracer = Tracer()
        stats = ShuffleReaderStats(TpuShuffleConf())
        conf = TpuShuffleConf(**dict(CONF_KW, coalesce_reads=True,
                                     read_ahead_depth=4))
        reader = _reader(execs, 1, handle, conf, tracer=tracer,
                         reader_stats=stats)
        assert _drain(reader)
        names = {e["name"] for e in tracer._events}
        assert {"fetch.locations", "fetch.vectored", "fetch.issue",
                "fetch.blocks", "fetch.complete"} <= names, names
        vec = [e for e in tracer._events if e["name"] == "fetch.vectored"]
        assert all(e["args"]["maps"] >= 1 and e["args"]["blocks"] >= 1
                   and e["dur"] >= 0 for e in vec)
        assert sum(e["args"]["maps"] for e in vec) == 6
        # batched location span names the whole peer batch
        locs = [e for e in tracer._events
                if e["name"] == "fetch.locations"]
        assert any(e["args"].get("batched") and e["args"]["maps"] == 6
                   for e in locs)
        snap = stats.snapshot()
        assert snap["request_bytes"]["count"] == len(vec)
        assert snap["request_bytes"]["total_bytes"] == \
            reader.metrics.remote_bytes
    finally:
        _shutdown(driver, execs)
