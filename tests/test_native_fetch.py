"""Native client fetch engine: wire->device zero-copy reads into leases.

The receiving side of the host dataplane rebuilt for constant client CPU
per byte (csrc/fetchclient.cpp): byte-identity between the native client
and the pure-Python fetcher across dataplane combos (zero-length blocks
riding every request), proof the native path actually engaged (traced
``fetch.vectored`` spans with ``native=True``), the doorbell batch
observable in engine counters (one writev carries N request frames),
lease refcount round-trips through the pool (including the concurrent
double-free race FetchResult.free hardens against), and the two
fallbacks that must stay bit-identical to today's fetcher:
``native_fetch=off`` and a .so without the client symbols.
"""

import os
import threading

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.runtime import native
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader

SEED = int(os.environ.get("NATIVE_FETCH_SEED", "0"))

needs_native = pytest.mark.skipif(
    not (native.available() and native.has_fetch_client()),
    reason="native fetch client not built")

CONF_KW = dict(connect_timeout_ms=5000, pre_warm_connections=False,
               use_cpp_runtime=True)


def _cluster(tmp_path, tag, n=3, **kw):
    conf = TpuShuffleConf(**dict(CONF_KW, **kw))
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=f"{tag}{i}",
                               spill_dir=str(tmp_path / f"{tag}{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _write_shuffle(driver, execs, num_maps=6, num_partitions=16,
                   payload_w=8, seed=SEED, shape="mixed"):
    handle = driver.register_shuffle(1, num_maps, num_partitions,
                                     PartitionerSpec("modulo"),
                                     row_payload_bytes=payload_w)
    rng = np.random.default_rng(seed)
    for m in range(num_maps):
        w = execs[m % 2].get_writer(handle, m)
        if shape == "mostly_empty":
            # everything lands in ONE partition: the other 15 arrive as
            # zero-length blocks inside the native vectored requests
            keys = np.full(64, 3, dtype=np.uint64)
        else:
            # skip odd partitions -> zero-length blocks interleave with
            # data blocks in every request frame
            keys = (rng.integers(0, num_partitions // 2,
                                 size=180).astype(np.uint64) * 2)
        w.write_batch(keys, rng.integers(
            0, 255, (len(keys), payload_w), dtype=np.uint64
        ).astype(np.uint8))
        w.close()
    return handle


def _drain(execs, idx, handle, conf, pool=None, tracer=None):
    reader = TpuShuffleReader(
        execs[idx].executor, execs[idx].resolver, conf, handle.shuffle_id,
        handle.num_maps, 0, handle.num_partitions, handle.row_payload_bytes,
        pool=pool, tracer=tracer)
    results = []
    reader.fetcher.start()
    try:
        for r in reader.fetcher:
            results.append((r.map_id, r.start_partition, r.end_partition,
                            bytes(r.data)))
            r.free()
    finally:
        reader.fetcher.close()
    return sorted(results)


def _native_spans(tracer):
    return [e for e in tracer._events if e["name"] == "fetch.vectored"
            and e["args"].get("native")]


# -- byte-identity: native client vs pure-Python fetcher -------------------


@needs_native
@pytest.mark.parametrize("shape", ["mixed", "mostly_empty"])
def test_native_vs_python_fetch_byte_identity(tmp_path, shape):
    """The same shuffle drains byte-identically (per-map attribution
    included) through the native client and through every pure-Python
    dataplane — and the native drain PROVES it took the native path via
    its traced spans. Zero-length blocks ride every request."""
    from sparkrdma_tpu.utils.trace import Tracer

    driver, execs = _cluster(tmp_path, "nf", fetch_checksum=True,
                             at_rest_checksum=True)
    try:
        handle = _write_shuffle(driver, execs, shape=shape)
        combos = [
            ("native_seq", dict(native_fetch=True, read_ahead_depth=1)),
            ("native_win", dict(native_fetch=True, read_ahead_depth=8)),
            ("python_seq", dict(native_fetch=False, read_ahead_depth=1)),
            ("python_win", dict(native_fetch=False, read_ahead_depth=8)),
            ("per_map", dict(native_fetch=True, coalesce_reads=False)),
        ]
        drained = {}
        for name, kw in combos:
            conf = TpuShuffleConf(**dict(CONF_KW, fetch_checksum=True,
                                         at_rest_checksum=True, **kw))
            tracer = Tracer()
            drained[name] = _drain(execs, 2, handle, conf,
                                   pool=execs[2].pool, tracer=tracer)
            native_engaged = bool(_native_spans(tracer))
            if name.startswith("native"):
                assert native_engaged, f"{name} never took the native path"
            else:
                assert not native_engaged, \
                    f"{name} must stay pure-Python, took the native path"
        baseline = drained["python_seq"]
        assert baseline, "shuffle drained nothing"
        for name, got in drained.items():
            assert got == baseline, f"{name} diverged from python_seq"
    finally:
        _shutdown(driver, execs)


@needs_native
def test_native_fetch_read_to_device_parity(tmp_path):
    """``read_to_device`` returns the same device arrays whether the
    bytes arrived through the native engine's lease-donation path or the
    staging-gather path — the wire->device hop the zero-copy receive
    exists for must not change a single row."""
    driver, execs = _cluster(tmp_path, "dv")
    try:
        handle = _write_shuffle(driver, execs, seed=SEED + 5)
        outs = {}
        for name, nat in (("native", True), ("python", False)):
            conf = TpuShuffleConf(**dict(CONF_KW, native_fetch=nat))
            reader = TpuShuffleReader(
                execs[2].executor, execs[2].resolver, conf,
                handle.shuffle_id, handle.num_maps, 0,
                handle.num_partitions, handle.row_payload_bytes,
                pool=execs[2].pool)
            keys, payload = reader.read_to_device(execs[2].pool)
            outs[name] = (np.asarray(keys), np.asarray(payload))
        nk, npay = outs["native"]
        pk, ppay = outs["python"]
        # arrival order differs between drains: compare as row multisets
        def rows(k, p):
            return sorted(map(bytes, np.concatenate(
                [k.reshape(len(k), -1).view(np.uint8), p], axis=1)))
        assert rows(nk, npay) == rows(pk, ppay)
        pool = execs[2].pool
        assert pool.idle_bytes == pool.total_bytes, "leaked pool lease"
    finally:
        _shutdown(driver, execs)


# -- the doorbell: one writev carries the whole batch ----------------------


@needs_native
def test_doorbell_batches_submits_into_one_writev(tmp_path):
    """N submits before one flush ring the doorbell ONCE: the engine's
    counters show a single writev carrying all N request frames, and
    every payload lands byte-exact in its lease slot."""
    import zlib

    from sparkrdma_tpu.runtime.blockserver import BlockServer
    from sparkrdma_tpu.runtime.pool import BufferPool
    from sparkrdma_tpu.shuffle.native_fetch import NativeFetchEngine

    data = bytes((i * 131 + 7) % 256 for i in range(1 << 16))
    path = tmp_path / "blk.data"
    path.write_bytes(data)
    srv = BlockServer(checksum=True)
    pool = BufferPool(TpuShuffleConf(use_cpp_runtime=False))
    try:
        srv.register_file(11, str(path))
        with NativeFetchEngine() as eng:
            conn = eng.connect("127.0.0.1", srv.port, timeout_ms=5000)
            assert conn > 0
            blocks = [(11, i * 4096, 1024 + i) for i in range(4)]
            leases = {}
            for rid, b in enumerate(blocks, start=1):
                lease = pool.get_registered(b[2])
                leases[rid] = (lease, b)
                rc = eng.submit(conn, rid, 0, [b],
                                lease._buf.view.ctypes.data, b[2])
                assert rc == 0
            wv = eng.writev_count
            eng.flush()
            assert eng.writev_count == wv + 1, \
                "doorbell flush must issue ONE writev for the batch"
            assert eng.frames_sent == 4
            done = []
            while len(done) < 4:
                done.extend(eng.poll(timeout_ms=100))
            for c in done:
                assert c.ok and c.crc_state == 1, c
                lease, (tok, off, ln) = leases[c.req_id]
                got = bytes(lease._buf.view[:ln])
                assert got == data[off:off + ln]
                assert zlib.crc32(got) == zlib.crc32(data[off:off + ln])
                lease.release()
        assert pool.idle_bytes == pool.total_bytes
    finally:
        pool.stop()
        srv.stop()


# -- lease refcount round-trip + the double-free race ----------------------


def test_fetch_result_free_is_idempotent_and_race_safe():
    """FetchResult.free from N racing threads releases the lease exactly
    once — the regression test for the refcount underflow a completion
    thread racing a consumer could hit (satellite of the native engine,
    which completes on a different thread than the consumer frees on)."""
    from sparkrdma_tpu.runtime.pool import BufferPool
    from sparkrdma_tpu.shuffle.fetcher import FetchResult

    pool = BufferPool(TpuShuffleConf(use_cpp_runtime=False))
    try:
        for _ in range(50):
            lease = pool.get_registered(4096)
            r = FetchResult(0, 0, 1, lease.slice(4096), lease=lease)
            lease.release()  # creator's ref; the result holds its own
            barrier = threading.Barrier(8)

            def free(r=r, barrier=barrier):
                barrier.wait()
                r.free()

            threads = [threading.Thread(target=free) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            r.free()  # late extra free: still a no-op
        assert pool.idle_bytes == pool.total_bytes, \
            "racing frees leaked or double-released a lease"
    finally:
        pool.stop()


def test_registered_buffer_over_release_asserts():
    """The pool refcount guard: releasing a RegisteredBuffer below zero
    is a programming error that must fail loudly (an underflowed lease
    silently recycles memory another result still views)."""
    from sparkrdma_tpu.runtime.pool import BufferPool

    pool = BufferPool(TpuShuffleConf(use_cpp_runtime=False))
    try:
        lease = pool.get_registered(1024)
        lease.release()
        with pytest.raises(AssertionError):
            lease.release()
    finally:
        pool.stop()


# -- fallbacks must stay bit-identical to today's fetcher ------------------


@needs_native
def test_native_fetch_off_and_missing_so_are_pure_python(tmp_path):
    """``native_fetch=off`` and a .so without the client symbols both
    drain byte-identically through today's Python dataplane — no native
    spans, no behavior drift. The second is what a version-skewed deploy
    (new Python, old .so) gets."""
    from sparkrdma_tpu.shuffle.native_fetch import NativeFetchEngine
    from sparkrdma_tpu.utils.trace import Tracer

    driver, execs = _cluster(tmp_path, "fb")
    try:
        handle = _write_shuffle(driver, execs, seed=SEED + 9)
        on = TpuShuffleConf(**dict(CONF_KW, native_fetch=True))
        tr = Tracer()
        want = _drain(execs, 2, handle, on, pool=execs[2].pool, tracer=tr)
        assert want and _native_spans(tr)

        off = TpuShuffleConf(**dict(CONF_KW, native_fetch=False))
        tr_off = Tracer()
        got = _drain(execs, 2, handle, off, pool=execs[2].pool,
                     tracer=tr_off)
        assert got == want and not _native_spans(tr_off)

        # simulate the old .so: the availability probe says no — the
        # fetcher must quietly keep the Python dataplane
        orig = NativeFetchEngine.available
        NativeFetchEngine.available = staticmethod(lambda: False)
        try:
            tr_miss = Tracer()
            got = _drain(execs, 2, handle, on, pool=execs[2].pool,
                         tracer=tr_miss)
            assert got == want and not _native_spans(tr_miss)
        finally:
            NativeFetchEngine.available = staticmethod(orig)
    finally:
        _shutdown(driver, execs)


@needs_native
def test_native_planned_push_parity(tmp_path):
    """Planned pushes ride the same engine's raw-mode connections: a
    push-merge cluster with the native sender on and off produces the
    same merged reduce inputs (the receive-side fence/epoch discipline
    is untouched — only the submission path changes)."""
    drained = {}
    for tag, nat in (("pn", True), ("pp", False)):
        driver, execs = _cluster(tmp_path, tag, push_merge=True,
                                 planned_push=True, adaptive_plan=True,
                                 native_fetch=nat)
        try:
            handle = _write_shuffle(driver, execs, seed=SEED + 3)
            conf = TpuShuffleConf(**dict(CONF_KW, push_merge=True,
                                         planned_push=True,
                                         adaptive_plan=True,
                                         native_fetch=nat))
            drained[tag] = _drain(execs, 2, handle, conf,
                                  pool=execs[2].pool)
        finally:
            _shutdown(driver, execs)
    assert drained["pn"], "push-merge shuffle drained nothing"
    assert drained["pn"] == drained["pp"], \
        "native planned-push sender changed the merged bytes"


# -- acceptance: client-side CPU per GB -----------------------------------


@needs_native
def test_client_cpu_per_gb_acceptance(tmp_path):
    """The tier-1 gate on the tentpole: the native fetch engine lands
    the same bytes with >= 1.5x less CLIENT CPU per GB than the
    pure-Python receive path (>= 2x is the bench-script target; CPU
    ratios are rusage-based and thus host-contention-robust),
    per-request digests byte-identical with CRC trailers on AND off,
    and the doorbell batching visible in the engine's own counters
    (strictly fewer writevs than frames sent)."""
    from sparkrdma_tpu.shuffle.client_bench import run_client_microbench

    for checksum in (False, True):
        res = run_client_microbench(str(tmp_path / f"c{checksum}"),
                                    file_mb=32, total_mb=128,
                                    checksum=checksum)
        assert res["identical"], res
        assert res["cpu_speedup"] >= 1.5, res
        db = res["doorbell"]
        assert 0 < db["writevs"] < db["frames"], res
        # wire->device must not regress: the donated lease upload has
        # one fewer host copy than bytes->ndarray->device staging
        w2d = res["wire_to_device_ms"]
        assert w2d["native"] <= 1.5 * w2d["python"], res
