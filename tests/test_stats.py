"""Observability tests (reference: scala/RdmaShuffleReaderStats.scala)."""

import logging

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.utils.stats import (
    FetchHistogram,
    MemStats,
    ShuffleReaderStats,
    process_stats,
)


def test_histogram_bucketing():
    h = FetchHistogram(bucket_ms=100, num_buckets=3)
    for ms in (10, 99, 150, 250, 950):
        h.add(ms / 1e3)
    s = h.summary()
    assert s["count"] == 5
    buckets = list(s["buckets"].values())
    assert buckets == [2, 1, 1, 1]  # <100, <200, <300, overflow
    assert s["mean_ms"] == round((10 + 99 + 150 + 250 + 950) / 5, 3)


def test_reader_stats_per_remote():
    stats = ShuffleReaderStats(TpuShuffleConf(fetch_time_bucket_size_ms=50,
                                              fetch_time_num_buckets=4))
    stats.update(0, 0.01)
    stats.update(0, 0.02)
    stats.update(3, 0.5)
    snap = stats.snapshot()
    assert snap["global"]["count"] == 3
    assert snap["per_remote"]["0"]["count"] == 2
    assert snap["per_remote"]["3"]["count"] == 1
    stats.log_summary(logging.getLogger("test"))  # must not raise


def test_mem_stats_diff_monotonic():
    m = MemStats()
    # touch some memory to cause faults
    blob = bytearray(4 << 20)
    blob[::4096] = b"x" * len(blob[::4096])
    d = m.diff()
    assert d["minor_faults"] >= 0
    assert d["peak_rss_kb"] > 0
    p = process_stats()
    assert p["pid"] > 0


def test_device_profile_captures_xla_trace(tmp_path):
    """utils.trace.device_profile wraps a jitted step and leaves an XLA
    profile on disk (the device-side half of the observability story)."""
    import glob

    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.utils.trace import device_profile

    with device_profile(str(tmp_path)):
        jax.block_until_ready(jax.jit(lambda x: x * 2 + 1)(jnp.ones(128)))
    found = glob.glob(str(tmp_path / "**" / "*.xplane.pb"), recursive=True)
    assert found, "no xplane profile written"
