"""Elastic recovery: an executor dies after committing map outputs; the
stage-retry loop recomputes its maps on survivors and the reduce completes
with exactly the right data (reference behavior: FetchFailed -> recompute,
scala/RdmaShuffleFetcherIterator.scala:376-381)."""

import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.recovery import run_map_stage, run_reduce_with_retry

CONF = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2)


def _map_fn(writer, map_id):
    """Deterministic map task: recompute yields identical records."""
    rng = np.random.default_rng(1000 + map_id)
    keys = rng.integers(0, 5000, size=500).astype(np.uint64)
    writer.write_batch(keys)


def _reduce_fn(mgr, handle):
    reader = mgr.get_reader(handle, 0, handle.num_partitions)
    keys, _ = reader.read_all()
    return np.sort(keys)


def test_reduce_survives_executor_loss(tmp_path):
    driver = TpuShuffleManager(CONF, is_driver=True)
    execs = [TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(3)]
    for ex in execs:
        ex.executor.wait_for_members(3)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        ran = run_map_stage(execs, handle, _map_fn)
        assert len(ran) == 6
        expect = np.sort(np.concatenate(
            [np.random.default_rng(1000 + m).integers(0, 5000, 500)
             for m in range(6)]).astype(np.uint64))

        # sanity: clean reduce works
        np.testing.assert_array_equal(_reduce_fn(execs[0], handle), expect)

        # kill executor 1 (it owns maps 1 and 4); tombstone it
        lost = execs[1].executor.manager_id
        lost_slot = execs[1].executor.exec_index()
        execs[1].executor.stop()
        driver.driver.remove_member(lost)
        time.sleep(0.3)
        execs[0].executor.invalidate_shuffle(1)

        # un-retried reduce fails...
        with pytest.raises(FetchFailedError):
            _reduce_fn(execs[0], handle)

        # ...the stage-retry loop repairs and completes with exact data
        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=0)
        np.testing.assert_array_equal(got, expect)

        # the repaired table no longer references the dead slot
        table = execs[0].executor.get_driver_table(1, 6, timeout=5)
        for m in range(6):
            assert table.entry(m)[1] != lost_slot
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
