"""Elastic recovery: an executor dies after committing map outputs; the
stage-retry loop recomputes its maps on survivors and the reduce completes
with exactly the right data (reference behavior: FetchFailed -> recompute,
scala/RdmaShuffleFetcherIterator.scala:376-381)."""

import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.recovery import run_map_stage, run_reduce_with_retry

CONF = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2)


def _map_fn(writer, map_id):
    """Deterministic map task: recompute yields identical records."""
    rng = np.random.default_rng(1000 + map_id)
    keys = rng.integers(0, 5000, size=500).astype(np.uint64)
    writer.write_batch(keys)


def _reduce_fn(mgr, handle):
    reader = mgr.get_reader(handle, 0, handle.num_partitions)
    keys, _ = reader.read_all()
    return np.sort(keys)


def test_reduce_survives_executor_loss(tmp_path):
    driver = TpuShuffleManager(CONF, is_driver=True)
    execs = [TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(3)]
    for ex in execs:
        ex.executor.wait_for_members(3)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        ran = run_map_stage(execs, handle, _map_fn)
        assert len(ran) == 6
        expect = np.sort(np.concatenate(
            [np.random.default_rng(1000 + m).integers(0, 5000, 500)
             for m in range(6)]).astype(np.uint64))

        # sanity: clean reduce works
        np.testing.assert_array_equal(_reduce_fn(execs[0], handle), expect)

        # kill executor 1 (it owns maps 1 and 4); tombstone it
        lost = execs[1].executor.manager_id
        lost_slot = execs[1].executor.exec_index()
        execs[1].executor.stop()
        driver.driver.remove_member(lost)
        time.sleep(0.3)
        execs[0].executor.invalidate_shuffle(1)

        # un-retried reduce fails...
        with pytest.raises(FetchFailedError):
            _reduce_fn(execs[0], handle)

        # ...the stage-retry loop repairs and completes with exact data
        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=0)
        np.testing.assert_array_equal(got, expect)

        # the repaired table no longer references the dead slot
        table = execs[0].executor.get_driver_table(1, 6, timeout=5)
        for m in range(6):
            assert table.entry(m)[1] != lost_slot
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def _expected(num_maps):
    return np.sort(np.concatenate(
        [np.random.default_rng(1000 + m).integers(0, 5000, 500)
         for m in range(num_maps)]).astype(np.uint64))


def _make_cluster(tmp_path, n, **conf_kw):
    conf = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2,
                          retry_backoff_base_ms=10, retry_backoff_cap_ms=50,
                          **conf_kw)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def test_two_successive_executor_losses(tmp_path):
    """Multi-failure recovery: TWO map-output owners die before the
    reduce. Each FetchFailed names one dead slot; the retry loop must
    repair twice within its budget WITHOUT placing the first repair's
    recomputes on the second (also-dead) executor."""
    driver, execs = _make_cluster(tmp_path, 4)
    try:
        handle = driver.register_shuffle(1, num_maps=8, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        expect = _expected(8)
        np.testing.assert_array_equal(_reduce_fn(execs[0], handle), expect)

        dead_slots = []
        for k in (1, 2):
            dead_slots.append(execs[k].executor.exec_index())
            execs[k].executor.stop()
        execs[0].executor.invalidate_shuffle(1)

        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=2,
                                    driver=driver)
        np.testing.assert_array_equal(got, expect)

        # both dead slots are repaired out of the table and tombstoned
        table = execs[0].executor.get_driver_table(1, 8, timeout=5)
        for m in range(8):
            assert table.entry(m)[1] not in dead_slots
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        members = driver.driver.members()
        for slot in dead_slots:
            assert members[slot] == TOMBSTONE
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_straggler_fetching_mid_repair(tmp_path):
    """recovery.py's "old or new owner" claim under actual concurrency:
    while one reducer's retry loop is repairing the dead slot's maps, a
    straggler reducer starts fetching. It must see either the old (dead)
    owner — failing into its own retry — or the new one, and both
    reducers must finish byte-identical."""
    driver, execs = _make_cluster(tmp_path, 3)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        expect = _expected(6)

        execs[1].executor.stop()
        for ex in (execs[0], execs[2]):
            ex.executor.invalidate_shuffle(1)

        results = {}
        errors = []

        def reduce_on(idx, delay_s):
            try:
                time.sleep(delay_s)
                results[idx] = run_reduce_with_retry(
                    execs, handle, _map_fn, _reduce_fn, reducer_index=idx,
                    max_stage_retries=3, driver=driver)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append((idx, e))

        threads = [threading.Thread(target=reduce_on, args=(0, 0.0)),
                   threading.Thread(target=reduce_on, args=(2, 0.15))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        np.testing.assert_array_equal(results[0], expect)
        np.testing.assert_array_equal(results[2], expect)
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
