"""AOT-compile the TPU data plane against a real v5e topology.

The centerpiece transport — ``lax.ragged_all_to_all`` over ICI
(parallel/exchange.py) — cannot execute on the CPU validation mesh
(XLA:CPU lacks the opcode) and single-chip hardware runs bypass the
exchange entirely. These tests close that gap as far as software can
without a multi-chip slice: the full XLA:TPU + Mosaic compiler stack runs
here against an ahead-of-time ``v5e:2x4`` topology, validating opcode
support, SPMD partitioning, layouts, and the Pallas ring kernel's
compiled-mode path (including the WAR-race neighbor barrier that
interpret mode cannot emulate, ops/ring_exchange.py:79). Execution parity
with the gather oracle is asserted wherever the running backend honors
the opcode (skipped until one does — the reference's analogous most-
tested path is its verbs engine, java/RdmaChannel.java).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.utils.compat import shard_map

AXIS = "shuffle"

# the opcode these tests compiler-validate arrived in jax 0.5.x; an older
# interpreter can only ever watch resolve_impl fall back to dense, so the
# native-path assertions are environment-gated (same spirit as the
# tpu_mesh fixture's topology skip)
requires_ragged = pytest.mark.skipif(
    not hasattr(jax.lax, "ragged_all_to_all"),
    reason="this jax lacks lax.ragged_all_to_all (the opcode under test)")


@functools.lru_cache(maxsize=1)
def _tpu_mesh():
    from jax.experimental import topologies

    try:
        topo = topologies.get_topology_desc("v5e:2x4")
    except Exception as e:  # noqa: BLE001 — no libtpu compiler in this env
        return None, str(e)
    return Mesh(np.array(topo.devices).reshape(8), (AXIS,)), ""


@pytest.fixture
def tpu_mesh():
    mesh, err = _tpu_mesh()
    if mesh is None:
        pytest.skip(f"TPU AOT topology unavailable: {err[:120]}")
    return mesh


def _lower_compile(jitted, *args):
    lowered = jitted.lower(*args)
    text = lowered.as_text()
    compiled = lowered.compile()
    assert compiled is not None
    return text, compiled


@requires_ragged
def test_native_exchange_compiles_with_ragged_opcode(tpu_mesh):
    """The full 8-device native exchange AOT-compiles for v5e and actually
    lowers to the ragged-all-to-all opcode (not a silent decomposition)."""
    from sparkrdma_tpu.parallel.exchange import make_shuffle_exchange

    exchange = make_shuffle_exchange(tpu_mesh, AXIS, impl="native",
                                     out_factor=2)
    sh = NamedSharding(tpu_mesh, P(AXIS))
    data = jax.ShapeDtypeStruct((8 * 128, 8), jnp.uint32, sharding=sh)
    dest = jax.ShapeDtypeStruct((8 * 128,), jnp.int32, sharding=sh)
    text, _ = _lower_compile(exchange, data, dest)
    assert "ragged_all_to_all" in text, "native path decomposed away"


@requires_ragged
def test_terasort_step_compiles_for_tpu(tpu_mesh):
    """The flagship multi-chip step (partition + native ragged exchange +
    sort) passes the real XLA:TPU compiler at v5e layouts."""
    from sparkrdma_tpu.models.terasort import TeraSortConfig, make_terasort_step

    cfg = TeraSortConfig(rows_per_device=256, payload_words=24, out_factor=2)
    step = make_terasort_step(tpu_mesh, AXIS, cfg)  # auto -> native on tpu
    rows = jax.ShapeDtypeStruct((8 * cfg.rows_per_device, 25), jnp.uint32,
                                sharding=NamedSharding(tpu_mesh, P(AXIS)))
    text, _ = _lower_compile(step, rows)
    assert "ragged_all_to_all" in text


def test_terasort_multisort_compiles_for_tpu(tpu_mesh):
    """The gather-free sort strategy also passes the v5e compiler (the
    hardware A/B in bench.py needs both variants compilable)."""
    from sparkrdma_tpu.models.terasort import TeraSortConfig, make_terasort_step

    cfg = TeraSortConfig(rows_per_device=256, payload_words=24, out_factor=2,
                         sort_mode="multisort")
    step = make_terasort_step(tpu_mesh, AXIS, cfg)
    rows = jax.ShapeDtypeStruct((8 * cfg.rows_per_device, 25), jnp.uint32,
                                sharding=NamedSharding(tpu_mesh, P(AXIS)))
    _lower_compile(step, rows)


def test_terasort_colsort_compiles_for_tpu(tpu_mesh):
    """The broadcast-key stable 2D sort strategy passes the v5e compiler.
    This is the mode built to dodge multisort's ~16s/operand compile
    blowup, so its own compile must stay cheap — asserted with a bound
    loose enough for CI noise but far under multisort's minutes."""
    import time

    from sparkrdma_tpu.models.terasort import TeraSortConfig, make_terasort_step

    cfg = TeraSortConfig(rows_per_device=256, payload_words=24, out_factor=2,
                         sort_mode="colsort")
    step = make_terasort_step(tpu_mesh, AXIS, cfg)
    rows = jax.ShapeDtypeStruct((8 * cfg.rows_per_device, 25), jnp.uint32,
                                sharding=NamedSharding(tpu_mesh, P(AXIS)))
    t0 = time.monotonic()
    _lower_compile(step, rows)
    assert time.monotonic() - t0 < 120, \
        "colsort compile no longer cheap — its reason to exist"


def test_ring_kernel_mosaic_compiles(tpu_mesh):
    """The hand-scheduled Pallas ring (remote DMAs + neighbor barrier)
    passes Mosaic in compiled mode — the barrier code interpret mode can't
    reach gets compiler-validated here."""
    from sparkrdma_tpu.ops.ring_exchange import make_ring_all_to_all

    a2a = make_ring_all_to_all(tpu_mesh, AXIS, interpret=False)
    x = jax.ShapeDtypeStruct((8, 8, 8, 128), jnp.uint32,
                             sharding=NamedSharding(tpu_mesh, P(AXIS)))
    _lower_compile(a2a, x)


def test_chunked_ring_round_compiles(tpu_mesh):
    """The production wrapper of the ring (chunked exchange, impl='ring')
    compiles end-to-end for v5e."""
    from sparkrdma_tpu.parallel.exchange import make_chunked_exchange

    round_fn = make_chunked_exchange(tpu_mesh, AXIS, quota=128, impl="ring")
    sh = NamedSharding(tpu_mesh, P(AXIS))
    grouped = jax.ShapeDtypeStruct((8 * 1024, 8), jnp.uint32, sharding=sh)
    counts = jax.ShapeDtypeStruct((8 * 8,), jnp.int32, sharding=sh)
    _lower_compile(round_fn, grouped, counts, 0)


@requires_ragged
def test_2d_mesh_exchange_compiles(tpu_mesh):
    """dp x shuffle composition (the embedding a host engine uses) compiles
    for v5e — collectives ride the inner mesh axis only."""
    from sparkrdma_tpu.parallel.exchange import shuffle_shard

    devs = np.array(tpu_mesh.devices).reshape(2, 4)
    mesh2 = Mesh(devs, ("dp", AXIS))

    @jax.jit
    @functools.partial(shard_map, mesh=mesh2,
                       in_specs=(P("dp", AXIS),) * 2,
                       out_specs=P("dp", AXIS))
    def exchange2d(data, dest):
        received, _, _, _ = shuffle_shard(data[0], dest[0], AXIS, 4,
                                          impl="native")
        return received[None]

    sh = NamedSharding(mesh2, P("dp", AXIS))
    data = jax.ShapeDtypeStruct((2, 4 * 64), jnp.int32, sharding=sh)
    dest = jax.ShapeDtypeStruct((2, 4 * 64), jnp.int32, sharding=sh)
    text, _ = _lower_compile(exchange2d, data, dest)
    assert "ragged_all_to_all" in text


@requires_ragged
def test_tpcds_step_compiles_for_tpu(tpu_mesh):
    """The 5-exchange star-join step (the TPC-DS-class plan) compiles for
    v5e with all exchanges on the native opcode."""
    from sparkrdma_tpu.models.tpcds import TpcdsConfig, make_tpcds_step

    cfg = TpcdsConfig(fact_rows_per_device=256, dim1_size=128, dim2_size=128,
                      num_groups=64)
    step = make_tpcds_step(tpu_mesh, AXIS, cfg)
    sh = NamedSharding(tpu_mesh, P(AXIS))
    fact = jax.ShapeDtypeStruct((8 * 256, 3), jnp.uint32, sharding=sh)
    dim = jax.ShapeDtypeStruct((8 * 16, 2), jnp.uint32, sharding=sh)
    text, _ = _lower_compile(step, fact, dim, dim)
    assert text.count("ragged_all_to_all") >= 5


@requires_ragged
def test_scale_up_topologies_resolve_and_compile():
    """The v5e compiler accepts ragged-all-to-all only up to 16 chips
    (32+ have limited ICI routing and reject the opcode — discovered by
    this AOT suite). resolve_impl probe-compiles per mesh, so the
    flagship step must pick native at 16 chips and degrade to the dense
    fixed-slot transport at 64 — compiling at BOTH scales."""
    from jax.experimental import topologies

    from sparkrdma_tpu.models.terasort import TeraSortConfig, make_terasort_step
    from sparkrdma_tpu.parallel.exchange import resolve_impl

    cfg = TeraSortConfig(rows_per_device=256, payload_words=24, out_factor=2)
    for name, n, native_ok in (("v5e:4x4", 16, True), ("v5e:8x8", 64, False)):
        try:
            topo = topologies.get_topology_desc(name)
        except Exception as e:  # noqa: BLE001
            pytest.skip(f"{name} AOT topology unavailable: {str(e)[:100]}")
        mesh = Mesh(np.array(topo.devices).reshape(n), (AXIS,))
        impl = resolve_impl(mesh, axis_name=AXIS)
        assert impl == ("native" if native_ok else "dense"), (name, impl)
        step = make_terasort_step(mesh, AXIS, cfg)
        rows = jax.ShapeDtypeStruct((n * cfg.rows_per_device, 25),
                                    jnp.uint32,
                                    sharding=NamedSharding(mesh, P(AXIS)))
        text, _ = _lower_compile(step, rows)
        assert ("ragged_all_to_all" in text) == native_ok, name
        if not native_ok:  # the dense transport's all-to-all must survive
            assert "all_to_all" in text, name


@requires_ragged
def test_native_parity_where_backend_executes():
    """Bit-identity of impl='native' vs the gather oracle, on any running
    backend that honors the opcode (today: real multi-chip TPU; XLA:CPU
    raises UNIMPLEMENTED and the test skips — the AOT tests above still
    compiler-validate the path)."""
    from sparkrdma_tpu.parallel.exchange import make_shuffle_exchange

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs >=4 devices")
    n = 4
    mesh = Mesh(np.array(devs[:n]), (AXIS,))
    sh = NamedSharding(mesh, P(AXIS))
    rng = np.random.default_rng(3)
    cap = 64
    data = rng.integers(0, 2**31, size=(n * cap, 8), dtype=np.int32)
    dest = rng.integers(0, n, size=(n * cap,)).astype(np.int32)
    data_d, dest_d = (jax.device_put(x, sh) for x in (data, dest))

    native = make_shuffle_exchange(mesh, AXIS, impl="native", out_factor=2)
    try:
        got = jax.block_until_ready(native(data_d, dest_d))
    except Exception as e:  # noqa: BLE001
        if "not supported" in str(e) or "UNIMPLEMENTED" in str(e):
            pytest.skip(f"backend lacks ragged-all-to-all: {str(e)[:100]}")
        raise
    oracle = make_shuffle_exchange(mesh, AXIS, impl="gather", out_factor=2)
    want = jax.block_until_ready(oracle(data_d, dest_d))
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
