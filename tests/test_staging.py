"""Spill staging tests (reference: java/RdmaMappedFile.java chunking/offset
math 113-157 and partition serving 231-235; scatter-gather analogue of
RdmaShuffleFetcherIterator.scala:119-180)."""

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.runtime.pool import BufferPool
from sparkrdma_tpu.runtime.staging import SpillFile


@pytest.fixture
def spill(tmp_path):
    """A spill file with 6 partitions of known content; partition p is filled
    with byte value p+1 (partition 3 is empty)."""
    lengths = [100, 5000, 0, 70000, 1, 300]
    data = b"".join(bytes([p + 1]) * n for p, n in enumerate(lengths))
    path = tmp_path / "shuffle_0_0.data"
    path.write_bytes(data)
    sf = SpillFile(str(path), lengths, file_token=99)
    yield sf, lengths
    sf.dispose()


def test_map_output_locations(spill):
    sf, lengths = spill
    loc = sf.map_output.get_block_location(3)
    assert loc.offset == 5100 and loc.length == 70000 and loc.buf == 99
    assert sf.map_output.total_bytes == sum(lengths)


def test_read_partition(spill):
    sf, lengths = spill
    for p, n in enumerate(lengths):
        data = sf.read_partition(p)
        assert len(data) == n
        assert data == bytes([p + 1]) * n


def test_gather_subset_multithreaded(spill):
    sf, lengths = spill
    ids = [1, 3, 5]
    offs = sf.partition_offsets[ids]
    lens = sf.partition_lengths[ids]
    dst = np.zeros(int(lens.sum()), dtype=np.uint8)
    n = sf.gather(offs, lens, dst, nthreads=4)
    assert n == int(lens.sum())
    expect = b"".join(bytes([p + 1]) * lengths[p] for p in ids)
    assert dst.tobytes() == expect


def test_gather_into_pool_buffer(spill):
    sf, lengths = spill
    pool = BufferPool(TpuShuffleConf(min_block_size="1k"))
    buf = sf.gather_partitions([0, 4, 5], pool)
    total = lengths[0] + lengths[4] + lengths[5]
    assert buf.view[:total].tobytes() == (b"\x01" * 100 + b"\x05" + b"\x06" * 300)
    buf.free()
    pool.stop()


def test_gather_bounds_checked(spill):
    sf, _ = spill
    dst = np.zeros(10, dtype=np.uint8)
    with pytest.raises((IndexError, ValueError)):
        sf.gather([10**9], [8], dst)


def test_short_file_rejected(tmp_path):
    path = tmp_path / "short.data"
    path.write_bytes(b"xy")
    with pytest.raises(ValueError):
        SpillFile(str(path), [100], file_token=1)


def test_dispose_deletes(tmp_path):
    import os
    path = tmp_path / "d.data"
    path.write_bytes(b"a" * 64)
    sf = SpillFile(str(path), [64], file_token=1)
    sf.dispose()
    assert not os.path.exists(str(path))


def test_empty_gather(spill):
    sf, _ = spill
    dst = np.zeros(1, dtype=np.uint8)
    assert sf.gather([], [], dst) == 0


def test_gather_overflow_offsets_rejected(spill):
    # offsets near 2^64 must not wrap the bounds check (native path)
    sf, _ = spill
    dst = np.zeros(0x2000, dtype=np.uint8)
    with pytest.raises((IndexError, ValueError, OverflowError)):
        sf.gather([0xFFFFFFFFFFFFF000], [0x2000], dst)


def test_partition_over_4gib_rejected(tmp_path):
    path = tmp_path / "big.data"
    path.write_bytes(b"x")
    with pytest.raises(ValueError, match="4 GiB"):
        SpillFile(str(path), [5 << 30], file_token=1)
