"""Partitioned metadata ownership (ROADMAP item 3): shard owners run
the control-plane write path.

Four layers, cheapest first:

* ``ShardOwnerStore`` / ``ShardStandbyBuffer`` unit semantics — the
  fence CAS, seal-then-replay handoff, forward-only generations.
* ``ShardMap.assign`` membership policy — a DRAINING slot is never
  handed a write-owner range.
* The control-plane scale-out gate — ``run_ctrl_microbench`` must show
  >= 1.5x publish throughput at 4 owners AND byte-identical resulting
  driver state (the ISSUE acceptance bar; measured headroom is ~4x).
* Live endpoints — publishes converge through owner batches, and
  killing an owner mid-stage fails over via the standby log with ZERO
  map re-executions (the driver table completes with the ORIGINAL
  tokens).
"""

import time

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.membership import MembershipPlane
from sparkrdma_tpu.shuffle import ha, shard_plane
from sparkrdma_tpu.shuffle.ctrl_bench import run_ctrl_microbench
from sparkrdma_tpu.shuffle.location_plane import ShardMap
from sparkrdma_tpu.shuffle.shard_plane import (APPLIED, FENCED, NOT_OWNER,
                                               SEALED, STALE_GEN,
                                               ShardOwnerStore,
                                               ShardStandbyBuffer)
from sparkrdma_tpu.utils.ids import ExecutorId, ShuffleManagerId

SID = 7


def _entry(token, exec_index=0):
    return shard_plane._ENTRY.pack(token, exec_index)


# ------------------------------------------------ owner store semantics

def test_owner_fence_cas_matches_driver_table():
    """The owner-side CAS is DriverTable.publish's: older fence for the
    same (map, exec) bounces, equal re-applies, per-exec floors are
    independent (the fence_loser shape)."""
    store = ShardOwnerStore()
    gen = ha.compose_epoch(0, 1)
    assert store.adopt(SID, 0, 0, 4, 4, gen)
    st, rec = store.publish(SID, 0, 1, _entry(100, 0), 2, gen)
    assert st == APPLIED and rec is not None
    # zombie: older fence, same exec
    st, _ = store.publish(SID, 0, 1, _entry(99, 0), 1, gen)
    assert st == FENCED
    # equal fence re-applies (at-least-once delivery)
    st, _ = store.publish(SID, 0, 1, _entry(100, 0), 2, gen)
    assert st == APPLIED
    # another exec's fence floor is independent
    st, _ = store.publish(SID, 0, 1, _entry(200, 1), 1, gen)
    assert st == APPLIED
    assert store.entries_of(SID, 0)[1] == _entry(200, 1)
    assert store.fenced == 1 and store.applied == 3


def test_owner_rejects_out_of_range_stale_gen_and_unowned():
    store = ShardOwnerStore()
    gen = ha.compose_epoch(0, 2)
    store.adopt(SID, 1, 4, 8, 16, gen)
    assert store.publish(SID, 1, 2, _entry(1), 1, gen)[0] == NOT_OWNER
    assert store.publish(SID, 0, 1, _entry(1), 1, gen)[0] == NOT_OWNER
    stale = ha.compose_epoch(0, 1)
    assert store.publish(SID, 1, 5, _entry(1), 1, stale)[0] == STALE_GEN
    assert store.rejected_stale == 1


def test_seal_then_replay_handoff_preserves_entries():
    """Seal-then-replay: the sealed owner bounces everything; the
    successor adopts at a newer generation, replays the sealed segment,
    and the entries survive under the new gen's log stamp."""
    old = ShardOwnerStore()
    gen1, gen2 = ha.compose_epoch(0, 1), ha.compose_epoch(0, 2)
    old.adopt(SID, 0, 0, 4, 4, gen1)
    old.publish(SID, 0, 0, _entry(500), 1, gen1)
    old.merged(SID, 0, gen1, b"merged-blob")
    segment = old.seal(SID, 0)
    assert [r.kind for r in segment] == [ha.SHARD_OP_PUBLISH,
                                         ha.SHARD_OP_MERGED]
    assert old.publish(SID, 0, 1, _entry(501), 1, gen1)[0] == SEALED
    assert not old.owns(SID, 0)

    new = ShardOwnerStore()
    assert new.adopt(SID, 0, 0, 4, 4, gen2,
                     replay=[(r.kind, r.payload) for r in segment])
    assert new.entries_of(SID, 0) == {0: _entry(500)}
    assert new.merged_of(SID, 0) == [b"merged-blob"]
    assert new.owns(SID, 0)
    # fence floors replayed too: the original fence still wins
    assert new.publish(SID, 0, 0, _entry(499), 0, gen2)[0] == FENCED


def test_adopt_is_forward_only():
    """A late replay of an OLD assignment must not resurrect a sealed
    shard — adoption at a generation <= the held one is a no-op."""
    store = ShardOwnerStore()
    gen1, gen2 = ha.compose_epoch(0, 1), ha.compose_epoch(0, 2)
    assert store.adopt(SID, 0, 0, 4, 4, gen2)
    assert not store.adopt(SID, 0, 0, 4, 4, gen1)
    assert not store.adopt(SID, 0, 0, 4, 4, gen2)
    assert store.gen_of(SID, 0) == gen2
    # a post-failover driver's composed gen dominates every
    # pre-failover one regardless of its seq half
    promoted = ha.compose_epoch(1, 1)
    assert promoted > gen2
    assert store.adopt(SID, 0, 0, 4, 4, promoted)


def test_standby_buffer_forward_only_and_take():
    sb = ShardStandbyBuffer()
    gen = ha.compose_epoch(0, 1)
    assert sb.ingest(SID, 0, gen, 1, ha.SHARD_OP_PUBLISH, b"a")
    assert sb.ingest(SID, 0, gen, 2, ha.SHARD_OP_MERGED, b"b")
    # duplicate / reordered stream entries are zombie-fenced
    assert not sb.ingest(SID, 0, gen, 2, ha.SHARD_OP_PUBLISH, b"dup")
    assert not sb.ingest(SID, 0, gen, 1, ha.SHARD_OP_PUBLISH, b"old")
    assert sb.dropped_stale == 2
    assert sb.last(SID, 0) == (gen, 2)
    assert sb.take(SID, 0) == [(ha.SHARD_OP_PUBLISH, b"a"),
                               (ha.SHARD_OP_MERGED, b"b")]
    assert sb.take(SID, 0) == []  # drained


# ------------------------------------------------ assignment policy

def _plane(n):
    plane = MembershipPlane(tombstone=ShuffleManagerId(
        ExecutorId("", "", 0), "", 0, 0))
    for i in range(n):
        plane.join(ShuffleManagerId(ExecutorId(str(i), "h", 0), "h",
                                    9000 + i, 0))
    return plane


def test_assign_never_picks_draining_slot():
    """The satellite: ``ShardMap.assign`` consults the membership plane
    directly, so a DRAINING slot — whose writes are being walked off the
    host — is never assigned as a write owner."""
    plane = _plane(4)
    assert plane.begin_drain(1) is not None
    smap = ShardMap.assign(num_maps=64, membership=plane, max_shards=4)
    assert smap is not None
    assert 1 not in smap.shard_slots
    assert set(smap.shard_slots) <= {0, 2, 3}
    # avoid= excludes the slot whose death triggered reassignment
    smap = ShardMap.assign(num_maps=64, membership=plane, max_shards=4,
                           avoid=(0,))
    assert set(smap.shard_slots) == {2, 3}
    # everyone draining/avoided -> sharding off, not a crash
    plane.begin_drain(0)
    plane.begin_drain(2)
    plane.begin_drain(3)
    assert ShardMap.assign(64, plane, 4) is None
    # raw slot lists still accepted (model checker / bench callers)
    assert ShardMap.assign(64, [0, 1], 2).shard_slots == [0, 1]


# ------------------------------------------------ the scale-out gate

def test_ctrl_plane_scaleout_gate():
    """ISSUE acceptance: >= 1.5x publish throughput at 4 owners vs the
    driver-serialized baseline, and the two modes' driver state is
    byte-identical (table bytes, fence floors, merged directory, and
    the SAME zombie publishes fenced). Best-of-2 rounds: the sleep-cost
    model is noisy on loaded CI hosts; the identity check must hold on
    EVERY round."""
    best = 0.0
    for _ in range(2):
        res = run_ctrl_microbench(shards=4, num_maps=512,
                                  op_cost_s=100e-6, batch_entries=16,
                                  registrations=8)
        assert res["identical"], "sharded driver state diverged"
        assert res["fenced"] > 0, "work script exercised no zombies"
        assert res["registrations_per_s"] > 0
        best = max(best, res["speedup"])
        if best >= 1.5:
            break
    assert best >= 1.5, f"control-plane scale-out only {best:.2f}x"


# ------------------------------------------------ live endpoints

def _cluster(n, **conf_kw):
    from sparkrdma_tpu.parallel.endpoints import (DriverEndpoint,
                                                  ExecutorEndpoint)
    conf = TpuShuffleConf(connect_timeout_ms=5000,
                          max_connection_attempts=2,
                          metadata_shards=2, shard_ownership=True,
                          **conf_kw)
    driver = DriverEndpoint(conf)
    execs = [ExecutorEndpoint("127.0.0.1", str(i), driver.address,
                              conf=conf) for i in range(n)]
    for ex in execs:
        ex.start()
    for ex in execs:
        ex.wait_for_members(n)
    return driver, execs


def _stop_all(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def test_endpoint_publishes_converge_through_owners():
    """End-to-end: publishes land at shard owners (one hop), converge
    into the driver table via owner batches, and stream to standbys."""
    driver, execs = _cluster(3, shard_batch_entries=2)
    try:
        driver.register_shuffle(SID, num_maps=6)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(ex.location_plane.shard_map_v(SID) is not None
                   for ex in execs):
                break
            time.sleep(0.05)
        smap, gen = execs[0].location_plane.shard_map_v(SID)
        assert smap.num_shards == 2 and gen > 0

        for m in range(6):
            execs[m % 3].publish_map_output(SID, m, table_token=1000 + m,
                                            fence=1)
        table = execs[0].get_driver_table(SID, expect_published=6,
                                          timeout=8)
        for m in range(6):
            token, _ = table.entry(m)
            assert token == 1000 + m
        assert driver.shard_batches > 0, \
            "publishes went driver-direct — owners never converged a batch"
        owned = [ex.shard_owner.owned_shards(SID) for ex in execs]
        assert sorted(s for shards in owned for s in shards) == [0, 1]
        assert sum(ex.shard_owner.applied for ex in execs) >= 6
        assert sum(ex.shard_standby.ingested for ex in execs) > 0, \
            "no op records streamed to any standby"
    finally:
        _stop_all(driver, execs)


def test_owner_death_fails_over_without_map_reexecution():
    """THE handoff acceptance: kill the owner of shard 0 mid-stage with
    unconverged applied publishes. Failover must be per-shard (standby
    log + republish backstop) and the driver table must complete with
    the ORIGINAL tokens — zero map re-executions."""
    # big batch: the victim is holding applied-but-unconverged writes
    driver, execs = _cluster(4, shard_batch_entries=64)
    try:
        driver.register_shuffle(SID, num_maps=8)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(ex.location_plane.shard_map_v(SID) is not None
                   for ex in execs):
                break
            time.sleep(0.05)
        smap, _gen = execs[0].location_plane.shard_map_v(SID)
        victim_slot = smap.shard_slots[0]
        victim = execs[victim_slot]
        others = [e for i, e in enumerate(execs) if i != victim_slot]

        for m in range(8):
            others[m % len(others)].publish_map_output(
                SID, m, table_token=1000 + m, fence=1)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and victim.shard_owner.applied == 0:
            time.sleep(0.05)
        assert victim.shard_owner.applied > 0, \
            "victim never owned any publish — handoff would prove nothing"

        victim.stop()  # abrupt: no batch flush, no goodbye
        driver.remove_member(victim.manager_id)
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and driver.shard_handoffs == 0:
            time.sleep(0.05)
        assert driver.shard_handoffs >= 1

        table = others[0].get_driver_table(SID, expect_published=8,
                                           timeout=10)
        for m in range(8):
            token, _ = table.entry(m)
            assert token == 1000 + m, \
                f"map {m} token {token}: output lost -> re-execution"
        smap2, gen2 = others[0].location_plane.shard_map_v(SID)
        assert victim_slot not in smap2.shard_slots
    finally:
        for ex in execs:
            try:
                ex.stop()  # idempotent for the already-stopped victim
            except Exception:
                pass
        driver.stop()
