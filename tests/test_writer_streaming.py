"""Streaming map-side write dataplane (shuffle/writer.py).

The contract under test: the streaming writer (incremental partition-
scatter, bounded-memory background spill, sequential merge commit) produces
committed files BYTE-IDENTICAL to the pre-streaming monolithic writer on
every input — randomized shapes, spill-forcing thresholds, combiners, empty
outputs — while keeping its bounded-memory and cleanliness promises
(peak buffered <= threshold + one batch; an aborted attempt leaves nothing
on disk). Plus the native scatter kernel's lockstep parity with the numpy
fallback, and e2e read-back through both fetch dataplanes.
"""

import os

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.runtime import native
from sparkrdma_tpu.runtime.pool import BufferPool
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver
from sparkrdma_tpu.shuffle.writer import (
    MonolithicShuffleWriter,
    TpuShuffleWriter,
    decode_rows,
    make_sum_combiner,
)

# run_write_bench.sh sweeps extra seeds through the randomized parity tests
_EXTRA_SEED = os.environ.get("WRITE_SEED")
_SEEDS = [0, 1, 7] + ([int(_EXTRA_SEED)] if _EXTRA_SEED else [])


def _mod_part(p):
    return lambda keys: (np.asarray(keys) % p).astype(np.int64)


def _gen_batches(rng, num_batches, max_rows, payload_bytes, key_space=997):
    out = []
    for _ in range(num_batches):
        n = int(rng.integers(0, max_rows))
        out.append((rng.integers(0, key_space, n).astype(np.uint64),
                    rng.integers(0, 255, (n, payload_bytes)).astype(np.uint8)))
    return out


def _commit(writer_cls, spill_dir, shuffle_id, map_id, num_partitions,
            payload_bytes, batches, combiner=None, **kw):
    """Write + close one map through `writer_cls`; returns
    (file bytes, partition_lengths, writer)."""
    resolver = TpuShuffleBlockResolver(spill_dir)
    w = writer_cls(resolver, shuffle_id, map_id, num_partitions,
                   _mod_part(num_partitions), payload_bytes,
                   combiner=combiner, **kw)
    for keys, payload in batches:
        w.write_batch(keys, payload)
    _, part_lengths = w.close()
    path = os.path.join(spill_dir, f"shuffle_{shuffle_id}_{map_id}.data")
    with open(path, "rb") as f:
        data = f.read()
    return data, part_lengths, w


@pytest.mark.parametrize("seed", _SEEDS)
@pytest.mark.parametrize("threshold", ["1g", "4k", 0])
def test_streaming_byte_identical_to_monolithic(tmp_path, seed, threshold):
    """Randomized parity: no-spill, spill-forcing and spill-every-batch
    streaming configs all commit the monolithic writer's exact bytes."""
    rng = np.random.default_rng(seed)
    payload_bytes = int(rng.integers(0, 40))
    num_partitions = int(rng.integers(1, 33))
    batches = _gen_batches(rng, int(rng.integers(1, 9)), 3000, payload_bytes)
    ref, ref_len, _ = _commit(
        MonolithicShuffleWriter, str(tmp_path / "mono"), 1, 0,
        num_partitions, payload_bytes, batches)
    got, got_len, w = _commit(
        TpuShuffleWriter, str(tmp_path / "stream"), 1, 0,
        num_partitions, payload_bytes, batches,
        conf=TpuShuffleConf(spill_threshold_bytes=threshold))
    assert got == ref
    assert (got_len == ref_len).all()
    if threshold == 0 and sum(len(k) for k, _ in batches):
        assert w.metrics.spills >= 1


@pytest.mark.parametrize("seed", _SEEDS)
@pytest.mark.parametrize("threshold", ["1g", "2k"])
def test_combiner_parity_spill_vs_global(tmp_path, seed, threshold):
    """Combine-per-run (+ re-combine at merge, under spilling) must equal
    the monolithic writer's single global combine, byte for byte — the
    per-partition-run sort replacing the old global argsort included."""
    rng = np.random.default_rng(seed)
    payload_bytes = 8  # two <u4 words
    num_partitions = int(rng.integers(1, 17))
    # small key space: heavy duplication, so the combiner really collapses
    batches = _gen_batches(rng, int(rng.integers(1, 7)), 2000, payload_bytes,
                           key_space=37)
    ref, ref_len, ref_w = _commit(
        MonolithicShuffleWriter, str(tmp_path / "mono"), 2, 0,
        num_partitions, payload_bytes, batches,
        combiner=make_sum_combiner("<u4"))
    got, got_len, w = _commit(
        TpuShuffleWriter, str(tmp_path / "stream"), 2, 0,
        num_partitions, payload_bytes, batches,
        combiner=make_sum_combiner("<u4"),
        conf=TpuShuffleConf(spill_threshold_bytes=threshold))
    assert got == ref
    assert (got_len == ref_len).all()
    assert w.records_written == ref_w.records_written


def test_spill_threshold_boundaries(tmp_path):
    """Spill triggers strictly past the budget: exact multiple stays in
    memory, one byte over spills, zero spills every batch."""
    payload_bytes = 8  # 16B rows
    batch_rows = 64  # 1024B per batch
    batch_bytes = batch_rows * 16
    keys = np.arange(batch_rows, dtype=np.uint64)
    payload = np.zeros((batch_rows, payload_bytes), dtype=np.uint8)

    def spills_with(threshold):
        resolver = TpuShuffleBlockResolver(str(tmp_path / f"t{threshold}"))
        w = TpuShuffleWriter(resolver, 3, 0, 4, _mod_part(4), payload_bytes,
                             conf=TpuShuffleConf(spill_threshold_bytes=threshold))
        for _ in range(6):
            w.write_batch(keys, payload)
        _, lengths = w.close()
        assert int(lengths.sum()) == 6 * batch_bytes
        return w.metrics.spills

    # budget of exactly 3 batches: buffered == threshold is within budget,
    # so the spill fires on the 4th batch only — one spill over 6 batches
    assert spills_with(3 * batch_bytes) == 1
    # one byte under: the 3rd batch tips it — two spills over 6 batches
    assert spills_with(3 * batch_bytes - 1) == 2
    # zero budget: every batch spills
    assert spills_with(0) == 6


def test_empty_map_output(tmp_path):
    got, lengths, w = _commit(TpuShuffleWriter, str(tmp_path / "s"), 4, 0, 8,
                              16, [], conf=TpuShuffleConf())
    ref, ref_len, _ = _commit(MonolithicShuffleWriter, str(tmp_path / "m"),
                              4, 0, 8, 16, [])
    assert got == ref == b""
    assert (lengths == 0).all() and (lengths == ref_len).all()
    assert w.metrics.spills == 0


def test_single_partition_shuffle(tmp_path):
    rng = np.random.default_rng(3)
    batches = _gen_batches(rng, 4, 500, 4)
    ref, _, _ = _commit(MonolithicShuffleWriter, str(tmp_path / "m"), 5, 0,
                        1, 4, batches)
    got, lengths, _ = _commit(
        TpuShuffleWriter, str(tmp_path / "s"), 5, 0, 1, 4, batches,
        conf=TpuShuffleConf(spill_threshold_bytes="1k"))
    assert got == ref
    assert len(lengths) == 1 and int(lengths[0]) == len(ref)


def test_peak_buffered_bounded_by_threshold_plus_batch(tmp_path):
    rng = np.random.default_rng(11)
    payload_bytes = 24
    batches = _gen_batches(rng, 12, 2000, payload_bytes)
    threshold = 32 << 10
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"))
    w = TpuShuffleWriter(resolver, 6, 0, 8, _mod_part(8), payload_bytes,
                         conf=TpuShuffleConf(spill_threshold_bytes=threshold))
    max_batch = max((len(k) * w.row_bytes for k, _ in batches), default=0)
    for keys, payload in batches:
        w.write_batch(keys, payload)
    w.close()
    assert w.metrics.spills >= 1
    assert w.metrics.peak_buffered_bytes <= threshold + max_batch


def test_abort_mid_write_leaves_shuffle_dir_clean(tmp_path):
    """close(success=False) after spill-forcing writes must unlink every
    artifact — spill files included — leaving other maps' committed
    outputs untouched."""
    spill_dir = str(tmp_path / "s")
    rng = np.random.default_rng(5)
    # a committed neighbor map that must survive the abort
    _commit(TpuShuffleWriter, spill_dir, 7, 1, 4, 8,
            _gen_batches(rng, 2, 200, 8), conf=TpuShuffleConf())
    resolver = TpuShuffleBlockResolver(spill_dir)
    w = TpuShuffleWriter(resolver, 7, 0, 4, _mod_part(4), 8,
                         conf=TpuShuffleConf(spill_threshold_bytes=0))
    for keys, payload in _gen_batches(rng, 4, 500, 8):
        w.write_batch(keys, payload)
    assert w.metrics.spills >= 1
    assert w.close(success=False) is None
    assert sorted(os.listdir(spill_dir)) == [
        "shuffle_7_1.data", "shuffle_7_1.data.index"]


def test_commit_failure_unlinks_tmp_and_spills(tmp_path):
    """An exception between data_tmp_path() and resolver.commit() (here:
    commit itself) must not leak the data tmp or any spill file."""
    spill_dir = str(tmp_path / "s")
    resolver = TpuShuffleBlockResolver(spill_dir)
    rng = np.random.default_rng(6)

    def boom(*a, **kw):
        raise RuntimeError("injected commit failure")

    resolver.commit = boom
    w = TpuShuffleWriter(resolver, 8, 0, 4, _mod_part(4), 8,
                         conf=TpuShuffleConf(spill_threshold_bytes="1k"))
    for keys, payload in _gen_batches(rng, 5, 400, 8):
        w.write_batch(keys, payload)
    with pytest.raises(RuntimeError, match="injected commit failure"):
        w.close()
    assert os.listdir(spill_dir) == []


def test_remove_shuffle_reaps_orphan_tmps(tmp_path):
    """Resolver teardown of a shuffle deletes uncommitted attempt files
    (crashed writers) alongside the committed pair."""
    spill_dir = str(tmp_path / "s")
    resolver = TpuShuffleBlockResolver(spill_dir)
    w = TpuShuffleWriter(resolver, 9, 0, 2, _mod_part(2), 0,
                         conf=TpuShuffleConf())
    w.write_batch(np.arange(10, dtype=np.uint64))
    w.close()
    # a crashed attempt's leftovers: data tmp + one spill file
    tmp = resolver.data_tmp_path(9, 1)
    open(tmp, "wb").write(b"x")
    open(tmp + ".s0.tmp", "wb").write(b"y")
    other = os.path.join(spill_dir, "shuffle_10_0.5.tmp")
    open(other, "wb").write(b"z")  # different shuffle: must survive
    resolver.remove_shuffle(9)
    assert sorted(os.listdir(spill_dir)) == ["shuffle_10_0.5.tmp"]


@pytest.mark.skipif(not native.has_writer_scatter(),
                    reason="native writer_scatter not built")
@pytest.mark.parametrize("rows", [100, 80_000])  # 80k * 16B > the kernel's
# 1 MiB multithreading floor: both the single- and multi-threaded paths
def test_native_and_numpy_scatter_lockstep(tmp_path, rows):
    """The native kernel and the numpy fallback must produce identical
    run layouts (bytes AND per-partition counts) — the property that
    makes `native_write_scatter` a pure speed knob."""
    rng = np.random.default_rng(13)
    payload_bytes = 8
    keys = rng.integers(0, 1 << 40, rows).astype(np.uint64)
    payload = rng.integers(0, 255, (rows, payload_bytes)).astype(np.uint8)
    runs = {}
    for name, native_on in (("native", True), ("numpy", False)):
        resolver = TpuShuffleBlockResolver(str(tmp_path / name))
        w = TpuShuffleWriter(
            resolver, 10, 0, 16, _mod_part(16), payload_bytes,
            conf=TpuShuffleConf(native_write_scatter=native_on,
                                spill_threshold_bytes="1g"))
        assert w.metrics.native_scatter is native_on
        w.write_batch(keys, payload)
        run = w._runs[0]
        runs[name] = (bytes(run.view), run.counts.tolist())
        w.close(success=False)
    assert runs["native"] == runs["numpy"]


def test_run_buffers_come_from_pool_and_return(tmp_path):
    """Zero-copy registered commit: run buffers are pool leases, and every
    lease is back in the pool after close (leased-bytes gauge hits zero)."""
    conf = TpuShuffleConf(spill_threshold_bytes="4k", use_cpp_runtime=False)
    pool = BufferPool(conf)
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"))
    rng = np.random.default_rng(17)
    w = TpuShuffleWriter(resolver, 11, 0, 8, _mod_part(8), 16,
                         conf=conf, pool=pool)
    for keys, payload in _gen_batches(rng, 6, 600, 16):
        w.write_batch(keys, payload)
    assert pool.peak_leased_bytes > 0
    w.close()
    assert pool.leased_bytes == 0
    assert w.metrics.spills >= 1
    pool.stop()


def test_write_trace_spans(tmp_path):
    from sparkrdma_tpu.utils.trace import Tracer

    tracer = Tracer()
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"))
    w = TpuShuffleWriter(resolver, 12, 0, 4, _mod_part(4), 8,
                         conf=TpuShuffleConf(spill_threshold_bytes=0),
                         tracer=tracer)
    rng = np.random.default_rng(19)
    for keys, payload in _gen_batches(rng, 3, 300, 8):
        w.write_batch(keys, payload)
    w.close()
    names = {e["name"] for e in tracer._events}
    assert {"write.scatter", "write.spill", "write.merge"} <= names


def test_combiner_contract_errors(tmp_path):
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"))

    def bad_dtype(keys, payload):
        return keys, payload.view("<u4").astype(np.int64)

    w = TpuShuffleWriter(resolver, 13, 0, 2, _mod_part(2), 8,
                         combiner=bad_dtype, conf=TpuShuffleConf())
    w.write_batch(np.arange(8, dtype=np.uint64),
                  np.ones((8, 8), dtype=np.uint8))
    with pytest.raises(ValueError, match="uint8 payload"):
        w.close()
    assert os.listdir(resolver.spill_dir) == []  # failed close leaks nothing


def test_decode_rows_single_materialization_and_zero_copy():
    rng = np.random.default_rng(23)
    rows = rng.integers(0, 255, (64, 20), dtype=np.uint8)
    data = rows.tobytes()
    keys_c, payload_c = decode_rows(data, 12, copy=True)
    keys_v, payload_v = decode_rows(data, 12, copy=False)
    assert keys_c.dtype == np.uint64 and payload_c.shape == (64, 12)
    assert (keys_c == keys_v).all()
    assert (np.asarray(payload_c) == np.asarray(payload_v)).all()
    # copy=True: ONE materialization — both outputs view the same copy
    assert payload_c.base is not None and keys_c.base is not None
    assert payload_c.base is keys_c.base.base or payload_c.base is keys_c.base
    # copy=False: zero-copy views over the caller's bytes (the base chain
    # bottoms out at the `data` object itself)
    base = payload_v
    while isinstance(base, np.ndarray):
        base = base.base
    assert base is data
    with pytest.raises(ValueError, match="not a multiple"):
        decode_rows(data[:-1], 12)


def test_e2e_readback_python_and_native_dataplanes(tmp_path):
    """Spill-forcing writers through the full manager/endpoint stack, read
    back over loopback on both fetch dataplanes (pure-Python and native
    block server) — content parity vs the input oracle."""
    from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager

    for label, use_cpp in (("py", False),
                           ("native", native.available())):
        if label == "native" and not use_cpp:
            pytest.skip("native runtime not built")
        conf = TpuShuffleConf(connect_timeout_ms=5000,
                              shuffle_read_block_size="4k",
                              spill_threshold_bytes="2k",
                              use_cpp_runtime=use_cpp)
        driver = TpuShuffleManager(conf, is_driver=True)
        execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                                   executor_id=str(i),
                                   spill_dir=str(tmp_path / f"{label}{i}"))
                 for i in range(2)]
        try:
            for ex in execs:
                ex.executor.wait_for_members(2)
            handle = driver.register_shuffle(1, 2, 4, PartitionerSpec("modulo"),
                                             row_payload_bytes=8)
            rng = np.random.default_rng(29)
            oracle = []
            for m in range(2):
                w = execs[m].get_writer(handle, m)
                for _ in range(3):
                    keys = rng.integers(0, 1000, 700).astype(np.uint64)
                    payload = rng.integers(0, 255, (700, 8)).astype(np.uint8)
                    w.write_batch(keys, payload)
                    oracle.append((keys, payload))
                w.close()
                assert w.write_metrics.spills >= 1
            keys = np.concatenate([k for k, _ in oracle])
            payloads = np.concatenate([p for _, p in oracle])
            got_k, got_p = [], []
            for i, ex in enumerate(execs):
                reader = ex.get_reader(handle, i * 2, (i + 1) * 2)
                k, p = reader.read_all()
                got_k.append(k)
                got_p.append(p)
            got_k, got_p = np.concatenate(got_k), np.concatenate(got_p)
            assert len(got_k) == len(keys)

            def canon(k, p):
                rows = np.concatenate(
                    [np.ascontiguousarray(k)[:, None].view(np.uint8)
                     .reshape(len(k), 8), np.ascontiguousarray(p)], axis=1)
                return rows[np.lexsort(rows.T[::-1])]

            assert (canon(got_k, got_p) == canon(keys, payloads)).all()
        finally:
            for ex in execs:
                ex.stop()
            driver.stop()


def test_write_microbench_speedup_and_bounds(tmp_path):
    """The acceptance gate: at a spill-forcing size (>=2 spills) the
    streaming writer is >=2x the monolithic one on this host, files are
    byte-identical, and peak buffered stays within threshold + one batch."""
    from sparkrdma_tpu.shuffle.write_bench import run_write_microbench

    res = run_write_microbench(str(tmp_path), reps=3, map_compute_s=0.004)
    assert res["identical"], "committed files differ between writers"
    assert res["spills"] >= 2
    assert res["peak_buffered_bytes"] <= (res["spill_threshold"]
                                          + res["batch_bytes"])
    assert res["speedup"] >= 2.0, res
