"""The full end-to-end slice (SURVEY.md §7): engine-facing writers commit
spills -> staged to the mesh -> ONE ICI ragged all-to-all redistributes ->
device-side reduce. Verified against both a host-side reader and the raw
input multiset."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.mesh_service import run_mesh_reduce

D = 8
CONF = TpuShuffleConf(connect_timeout_ms=5000)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


@pytest.fixture
def cluster(tmp_path):
    driver = TpuShuffleManager(CONF, is_driver=True)
    execs = [TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(2)]
    for ex in execs:
        ex.executor.wait_for_members(2)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


def test_manager_to_mesh_reduce(cluster, mesh):
    driver, execs = cluster
    num_partitions = 16
    handle = driver.register_shuffle(1, num_maps=4,
                                     num_partitions=num_partitions,
                                     partitioner=PartitionerSpec("modulo"),
                                     row_payload_bytes=8)
    rng = np.random.default_rng(0)
    truth_k, truth_p = [], []
    for m in range(4):
        keys = rng.integers(0, 100_000, 2500).astype(np.uint64)
        payload = rng.integers(0, 255, (2500, 8)).astype(np.uint8)
        w = execs[m % 2].get_writer(handle, m)
        w.write_batch(keys, payload)
        w.close()
        truth_k.append(keys)
        truth_p.append(payload)
    truth_k = np.concatenate(truth_k)
    truth_p = np.concatenate(truth_p)

    results = run_mesh_reduce(execs, handle, mesh)

    got_k, got_p = [], []
    for d, (k, p, parts) in enumerate(results):
        # placement: every row's partition owner must be this device
        np.testing.assert_array_equal(parts % D, np.full(len(parts), d))
        # sorted within device
        assert (np.diff(k.astype(np.int64)) >= 0).all()
        got_k.append(k)
        got_p.append(p)
    got_k = np.concatenate(got_k)
    got_p = np.concatenate(got_p)
    assert len(got_k) == len(truth_k)

    def canon(k, p):
        rows = np.concatenate([k[:, None].view(np.uint8).reshape(len(k), 8), p],
                              axis=1)
        return rows[np.lexsort(rows.T[::-1])]
    np.testing.assert_array_equal(canon(got_k, got_p), canon(truth_k, truth_p))

    # cross-check one device against the host-side DCN reader path
    d0_parts = [p for p in range(num_partitions) if p % D == 0]
    host_k = []
    for p in d0_parts:
        rk, _ = execs[0].get_reader(handle, p, p + 1).read_all()
        host_k.append(rk)
    np.testing.assert_array_equal(np.sort(np.concatenate(host_k)),
                                  np.sort(results[0][0]))


def test_mesh_reduce_empty_shuffle(cluster, mesh):
    driver, execs = cluster
    handle = driver.register_shuffle(2, num_maps=1, num_partitions=4,
                                     partitioner=PartitionerSpec("modulo"))
    w = execs[0].get_writer(handle, 0)
    w.close()  # empty map output
    results = run_mesh_reduce(execs, handle, mesh)
    assert all(len(k) == 0 for k, _, _ in results)


def test_spark_compat_surface(tmp_path):
    """Reference-shaped API: registerShuffle/getWriter/getReader/stop."""
    from sparkrdma_tpu.shuffle.spark_compat import (
        ShuffleDependency, SparkCompatShuffleManager)
    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    ex = [SparkCompatShuffleManager(CONF, driverAddr=driver.driverAddr,
                                    executorId=str(i),
                                    spill_dir=str(tmp_path / f"sc{i}"))
          for i in range(2)]
    for e in ex:
        e.native.executor.wait_for_members(2)
    try:
        dep = ShuffleDependency(num_partitions=4, row_payload_bytes=4)
        handle = driver.registerShuffle(9, 2, dep)
        for m in range(2):
            w = ex[m].getWriter(handle, m)
            w.write([(k, np.full(4, k % 256, dtype=np.uint8))
                     for k in range(m * 50, m * 50 + 50)])
            w.stop(True)
        records = list(ex[0].getReader(handle, 0, 4).read())
        assert len(records) == 100
        for k, v in records:
            assert (v == k % 256).all()
        assert driver.unregisterShuffle(9)
        assert ex[0].shuffleBlockResolver is not None
    finally:
        for e in ex:
            e.stop()
        driver.stop()


def test_mesh_reduce_overflow_detected(cluster, mesh):
    """All keys hit one partition: skew beyond out_factor must raise, not
    silently truncate."""
    driver, execs = cluster
    handle = driver.register_shuffle(3, num_maps=1, num_partitions=16,
                                     partitioner=PartitionerSpec("modulo"))
    w = execs[0].get_writer(handle, 0)
    w.write_batch(np.zeros(4096, dtype=np.uint64))  # all -> partition 0
    w.close()
    with pytest.raises(OverflowError):
        run_mesh_reduce(execs, handle, mesh, out_factor=2)


def test_compat_writer_two_record_iterable(tmp_path):
    """A 2-element tuple of records must not be mistaken for a batch."""
    from sparkrdma_tpu.shuffle.spark_compat import (
        ShuffleDependency, SparkCompatShuffleManager)
    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    ex = SparkCompatShuffleManager(CONF, driverAddr=driver.driverAddr,
                                   executorId="0",
                                   spill_dir=str(tmp_path / "t"))
    ex.native.executor.wait_for_members(1)
    try:
        handle = driver.registerShuffle(5, 1, ShuffleDependency(2, row_payload_bytes=2))
        w = ex.getWriter(handle, 0)
        w.write(((1, np.array([7, 7], dtype=np.uint8)),
                 (2, np.array([9, 9], dtype=np.uint8))))
        w.stop(True)
        records = dict(ex.getReader(handle, 0, 2).read())
        assert records[1].tolist() == [7, 7] and records[2].tolist() == [9, 9]
    finally:
        ex.stop()
        driver.stop()


def test_streamed_mesh_reduce_matches_one_shot(cluster, mesh):
    """Bounded-round staging produces the same per-device reduce as the
    one-shot path (same keys in order, same full-row multiset), with
    rounds small enough to force many exchanges."""
    from sparkrdma_tpu.shuffle.mesh_service import run_mesh_reduce_streamed

    driver, execs = cluster
    handle = driver.register_shuffle(31, num_maps=4, num_partitions=16,
                                     partitioner=PartitionerSpec("modulo"),
                                     row_payload_bytes=8)
    rng = np.random.default_rng(8)
    for m in range(4):
        w = execs[m % 2].get_writer(handle, m)
        w.write_batch(rng.integers(0, 3000, 1500).astype(np.uint64),
                      rng.integers(0, 255, (1500, 8)).astype(np.uint8))
        w.close()

    one_shot = run_mesh_reduce(execs, handle, mesh)
    streamed = run_mesh_reduce_streamed(execs, handle, mesh,
                                        rows_per_round=128)  # ~6 rounds
    for d in range(D):
        k1, p1, parts1 = one_shot[d]
        k2, p2, parts2 = streamed[d]
        np.testing.assert_array_equal(k1, k2)
        np.testing.assert_array_equal(parts1, parts2)
        # payload multiset per device (duplicate-key order may differ
        # between a global stable sort and a tournament merge)
        rows1 = np.concatenate([k1[:, None].astype(np.uint64),
                                p1.astype(np.uint64)], axis=1)
        rows2 = np.concatenate([k2[:, None].astype(np.uint64),
                                p2.astype(np.uint64)], axis=1)
        np.testing.assert_array_equal(rows1[np.lexsort(rows1.T[::-1])],
                                      rows2[np.lexsort(rows2.T[::-1])])


def test_streamed_mesh_reduce_pipelined_matches_sequential(cluster, mesh):
    """Double-buffered rounds (stage r+1 while r's exchange runs) must be
    byte-identical to strictly sequential rounds; the A/B times are logged
    as the overlap evidence this environment can produce."""
    import time

    from sparkrdma_tpu.shuffle.mesh_service import run_mesh_reduce_streamed

    driver, execs = cluster
    handle = driver.register_shuffle(41, num_maps=4, num_partitions=16,
                                     partitioner=PartitionerSpec("modulo"),
                                     row_payload_bytes=8)
    rng = np.random.default_rng(11)
    for m in range(4):
        w = execs[m % 2].get_writer(handle, m)
        w.write_batch(rng.integers(0, 1 << 30, 20_000).astype(np.uint64),
                      rng.integers(0, 255, (20_000, 8)).astype(np.uint8))
        w.close()

    kw = dict(rows_per_round=1024, expect_maps=4)  # ~10 rounds
    # warm the compile, then time both modes
    run_mesh_reduce_streamed(execs, handle, mesh, **kw)
    t0 = time.monotonic()
    piped = run_mesh_reduce_streamed(execs, handle, mesh,
                                     pipeline_rounds=True, **kw)
    t_piped = time.monotonic() - t0
    t0 = time.monotonic()
    seq = run_mesh_reduce_streamed(execs, handle, mesh,
                                   pipeline_rounds=False, **kw)
    t_seq = time.monotonic() - t0
    for d in range(D):
        np.testing.assert_array_equal(piped[d][0], seq[d][0])
        np.testing.assert_array_equal(piped[d][1], seq[d][1])
        np.testing.assert_array_equal(piped[d][2], seq[d][2])
    total = sum(len(k) for k, _, _ in piped)
    assert total == 4 * 20_000
    print(f"\nstreamed mesh reduce ~10 rounds: pipelined {t_piped:.3f}s "
          f"vs sequential {t_seq:.3f}s")
