"""Native block server: multi-worker serving, bind scope, response caps.

The serving plane the reference scales by round-robining channels across a
CPU vector (java/RdmaNode.java:222-279) — here connections shard across N
epoll workers; these tests drive the real wire protocol over localhost.
"""

import os
import threading

import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.transport import ConnectionCache
from sparkrdma_tpu.runtime import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native runtime not built")

CONF = TpuShuffleConf(connect_timeout_ms=5000, max_connection_attempts=2)


@pytest.fixture
def server(tmp_path):
    from sparkrdma_tpu.runtime.blockserver import BlockServer

    srv = BlockServer(threads=4)
    data = os.urandom(1 << 16)
    path = tmp_path / "spill.bin"
    path.write_bytes(data)
    srv.register_file(7, str(path))
    yield srv, data
    srv.stop()


def _fetch(cache, port, blocks, shuffle_id=1):
    conn = cache.get("127.0.0.1", port)
    resp = conn.request(M.FetchBlocksReq(conn.next_req_id(), shuffle_id,
                                         blocks))
    assert isinstance(resp, M.FetchBlocksResp)
    return resp


def test_many_clients_across_workers(server):
    """8 concurrent pipelined clients; every response byte-exact."""
    srv, data = server
    errors = []

    def client(i):
        cache = ConnectionCache(CONF)
        try:
            for r in range(50):
                off = (i * 997 + r * 131) % (len(data) - 256)
                resp = _fetch(cache, srv.port, [(7, off, 128), (7, 0, 64)])
                assert resp.status == M.STATUS_OK
                assert resp.data == data[off:off + 128] + data[:64]
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            cache.close_all()

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    stats = srv.stats()
    assert stats["requests_served"] == 8 * 50
    assert stats["bytes_served"] == 8 * 50 * (128 + 64)


def test_bind_defaults_to_loopback(server):
    """The unauthenticated data port must not listen wider than asked."""
    import socket

    srv, _ = server
    # loopback reachable
    with socket.create_connection(("127.0.0.1", srv.port), timeout=2):
        pass
    # loopback port actually held
    probe = socket.socket()
    with probe:
        with pytest.raises(OSError):
            probe.bind(("127.0.0.1", srv.port))
    # NOT bound on INADDR_ANY: a non-loopback local address on the same
    # port must still be bindable (it wouldn't be under a wildcard bind)
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as u:
        try:
            u.connect(("10.255.255.255", 1))  # no traffic; just routes
        except OSError:
            pytest.skip("no non-loopback route to probe")
        local_ip = u.getsockname()[0]
    if local_ip.startswith("127."):
        pytest.skip("no non-loopback interface to probe")
    probe = socket.socket()
    with probe:
        probe.bind((local_ip, srv.port))


def test_unknown_token_and_bad_range(server):
    srv, data = server
    cache = ConnectionCache(CONF)
    try:
        assert _fetch(cache, srv.port, [(99, 0, 16)]).status == M.STATUS_UNKNOWN_SHUFFLE
        assert _fetch(cache, srv.port, [(7, len(data), 1)]).status == M.STATUS_BAD_RANGE
        # over the 256 MiB response cap: rejected, connection stays usable
        big = [(7, 0, 1 << 16)] * 5000  # ~320 MiB requested
        assert _fetch(cache, srv.port, big).status == M.STATUS_BAD_RANGE
        assert _fetch(cache, srv.port, [(7, 0, 32)]).status == M.STATUS_OK
    finally:
        cache.close_all()


def test_checksum_trailer_matches_python_contract(server):
    """With bs_set_checksum(1) the native server appends the same
    per-block CRC32 trailer the Python path does (FLAG_CRC32, one u32
    per requested block — zero-length blocks included), over a VECTORED
    request spanning tokens; the client-side verifier accepts and strips
    it. Without the toggle, flags stay 0."""
    import struct
    import zlib

    srv, data = server
    # a second registered file: the vectored request spans tokens the
    # way a coalesced fetch spans maps' spill files
    import tempfile

    with tempfile.NamedTemporaryFile(delete=False) as f:
        data2 = bytes(range(256)) * 8
        f.write(data2)
        path2 = f.name
    srv.register_file(8, path2)
    cache = ConnectionCache(CONF)
    try:
        blocks = [(7, 11, 100), (8, 0, 64), (7, 0, 0), (8, 128, 32)]
        expect = data[11:111] + data2[:64] + b"" + data2[128:160]
        resp = _fetch(cache, srv.port, blocks)
        assert resp.status == M.STATUS_OK and resp.flags == 0
        assert resp.data == expect

        srv.set_checksum(True)
        resp = _fetch(cache, srv.port, blocks)
        assert resp.status == M.STATUS_OK
        assert resp.flags == M.FLAG_CRC32
        n = len(blocks)
        body, trailer = resp.data[:-4 * n], resp.data[-4 * n:]
        assert body == expect
        got_crcs = struct.unpack(f"<{n}I", trailer)
        pos = 0
        for (_t, _o, ln), crc in zip(blocks, got_crcs):
            assert zlib.crc32(body[pos:pos + ln]) == crc
            pos += ln

        srv.set_checksum(False)
        assert _fetch(cache, srv.port, [(7, 0, 16)]).flags == 0
    finally:
        cache.close_all()
        import os as _os

        _os.unlink(path2)


def test_worker_survives_client_disconnect(server):
    """A client vanishing mid-pipeline must not take the worker down."""
    import socket

    srv, data = server
    for _ in range(4):
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=2)
        req = M.FetchBlocksReq(1, 1, [(7, 0, 4096)])
        s.sendall(req.encode()[:10])  # truncated frame
        s.close()
    cache = ConnectionCache(CONF)
    try:
        resp = _fetch(cache, srv.port, [(7, 0, 64)])
        assert resp.status == M.STATUS_OK and resp.data == data[:64]
    finally:
        cache.close_all()
