"""Warm iterative reuse: superstep N>=1 over an unchanged shuffle puts
ZERO metadata RPCs on the wire (the acceptance gate of the one-sided
metadata plane), and — with ``warm_read_cache`` — zero data RPCs too.

Wire traffic is counted SERVER-side (handler invocations per received
frame at the driver and the serving peer), so the assertions hold at
the frame level, not just the client counters. Every dataplane
combination is covered; epoch bumps (re-execution overwrites) must
invalidate and force a fresh snapshot + fresh bytes.
"""

import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle import dist_cache
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader

CONF_KW = dict(connect_timeout_ms=5000, use_cpp_runtime=False,
               pre_warm_connections=False)


def _cluster(tmp_path, n=2, **kw):
    conf = TpuShuffleConf(**dict(CONF_KW, **kw))
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _write_maps(execs, handle, version=0, owner=0):
    for m in range(handle.num_maps):
        w = execs[owner].get_writer(handle, m)
        rng = np.random.default_rng(100 * version + m)
        w.write_batch(rng.integers(0, 64, 256).astype(np.uint64))
        w.close()


class _WireCounters:
    """Server-side frame counts: every received metadata/data request
    increments here, exactly once per frame on the wire."""

    def __init__(self, driver, serving_exec):
        self.counts = {"table": 0, "loc_per_map": 0, "loc_batched": 0,
                       "blocks": 0}
        drv = driver.driver
        ep = serving_exec.executor
        orig_table = drv._on_fetch_table
        orig_one, orig_many = ep._on_fetch_output, ep._on_fetch_outputs
        orig_blocks = ep._on_fetch_blocks

        def wrap(key, orig):
            def handler(*a):
                self.counts[key] += 1
                return orig(*a)
            return handler

        drv._on_fetch_table = wrap("table", orig_table)
        ep._on_fetch_output = wrap("loc_per_map", orig_one)
        ep._on_fetch_outputs = wrap("loc_batched", orig_many)
        ep._on_fetch_blocks = wrap("blocks", orig_blocks)

    @property
    def metadata(self):
        c = self.counts
        return c["table"] + c["loc_per_map"] + c["loc_batched"]

    def snapshot(self):
        return dict(self.counts)


def _superstep(execs, handle, conf):
    """One reducer pass over the whole partition range (a superstep's
    read of an unchanged parent shuffle). Returns (sorted keys, metrics)."""
    reader = TpuShuffleReader(execs[1].executor, execs[1].resolver, conf,
                              handle.shuffle_id, handle.num_maps, 0,
                              handle.num_partitions,
                              handle.row_payload_bytes)
    keys, _ = reader.read_all()
    return np.sort(keys), reader.metrics


def _native_available():
    from sparkrdma_tpu.runtime import native

    return native.available()


DATAPLANES = [
    ("coalesced_seq", dict(coalesce_reads=True, read_ahead_depth=1)),
    ("coalesced_win", dict(coalesce_reads=True, read_ahead_depth=8)),
    ("per_map_seq", dict(coalesce_reads=False, read_ahead_depth=1)),
    ("per_map_pipe", dict(coalesce_reads=False, read_ahead_depth=8)),
    # data bytes served by the native block server (metadata always
    # rides the control plane, so the zero-RPC warm contract must hold
    # identically there)
    ("native_blocks", dict(coalesce_reads=True, read_ahead_depth=8,
                           use_cpp_runtime=True)),
]


@pytest.mark.parametrize("name,kw", DATAPLANES)
def test_warm_superstep_issues_zero_location_rpcs(tmp_path, name, kw):
    """The acceptance gate: superstep N>=1 over unchanged inputs puts no
    FetchTableReq / FetchOutputReq / FetchOutputsReq frames on the wire
    — on every dataplane — and the reduce output is byte-identical to
    the cold path."""
    if kw.get("use_cpp_runtime") and not _native_available():
        pytest.skip("native runtime not built")
    driver, execs = _cluster(tmp_path, **kw)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        _write_maps(execs, handle)
        conf = TpuShuffleConf(**dict(CONF_KW, **kw))
        wire = _WireCounters(driver, execs[0])

        cold, m_cold = _superstep(execs, handle, conf)
        cold_meta = wire.metadata
        assert cold_meta > 0, "cold superstep issued no metadata RPCs?"
        assert m_cold.metadata_rpcs_per_stage == cold_meta

        for step in range(1, 4):
            warm, m_warm = _superstep(execs, handle, conf)
            np.testing.assert_array_equal(warm, cold,
                                          err_msg=f"{name} step {step}")
            assert wire.metadata == cold_meta, \
                f"{name} superstep {step} put metadata frames on the wire: " \
                f"{wire.snapshot()}"
            assert m_warm.metadata_rpcs_per_stage == 0
            assert m_warm.location_cache_hits == handle.num_maps
        # data frames still flow on the warm path (only metadata is
        # cached; warm_read_cache covers the bytes — separate test).
        # With a native block server the data reads land on ITS port,
        # invisible to the control-plane counter — which is the point.
        if not kw.get("use_cpp_runtime"):
            assert wire.counts["blocks"] > 0
    finally:
        _shutdown(driver, execs)


def test_repair_overwrite_invalidates_warm_path(tmp_path):
    """A re-execution overwrite bumps the epoch; the pushed invalidation
    forces the next superstep back to a fresh snapshot — which serves
    the NEW owner's bytes, never the cached dead location."""
    driver, execs = _cluster(tmp_path, n=3)
    try:
        handle = driver.register_shuffle(1, num_maps=4, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        _write_maps(execs, handle, version=0, owner=0)
        conf = TpuShuffleConf(**CONF_KW)
        cold, _ = _superstep(execs, handle, conf)
        warm, m = _superstep(execs, handle, conf)
        assert m.metadata_rpcs_per_stage == 0
        np.testing.assert_array_equal(warm, cold)

        # re-execute map 0 on a DIFFERENT executor with different rows
        # (version 1): the publish overwrites the entry -> epoch bump
        w = execs[2].get_writer(handle, 0)
        rng = np.random.default_rng(999)
        new_rows = rng.integers(64, 128, 256).astype(np.uint64)
        w.write_batch(new_rows)
        w.close()
        # the publish is one-sided: wait for the driver to apply + bump,
        # then for the push to land at the reducer
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and driver.driver.epoch_of(1) != 2:
            time.sleep(0.01)
        assert driver.driver.epoch_of(1) == 2
        plane = execs[1].executor.location_plane
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and plane.known_epoch(1) != 2:
            time.sleep(0.01)
        assert plane.known_epoch(1) == 2

        fresh, m2 = _superstep(execs, handle, conf)
        assert m2.metadata_rpcs_per_stage > 0, \
            "post-bump superstep served stale cached locations"
        expect = np.sort(np.concatenate(
            [new_rows] + [np.random.default_rng(100 * 0 + m2_)
                          .integers(0, 64, 256) for m2_ in range(1, 4)]
        ).astype(np.uint64))
        np.testing.assert_array_equal(fresh, expect)
    finally:
        _shutdown(driver, execs)


def test_warm_read_cache_serves_bytes_locally(tmp_path):
    """``warm_read_cache``: superstep N>=1 moves NOTHING on the wire —
    no metadata frames, no data frames — and returns identical bytes."""
    driver, execs = _cluster(tmp_path, warm_read_cache=True)
    try:
        handle = driver.register_shuffle(1, num_maps=4, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        _write_maps(execs, handle)
        conf = TpuShuffleConf(**dict(CONF_KW, warm_read_cache=True))
        wire = _WireCounters(driver, execs[0])
        cold, _ = _superstep(execs, handle, conf)
        snap = wire.snapshot()
        assert snap["blocks"] > 0
        warm, m = _superstep(execs, handle, conf)
        np.testing.assert_array_equal(warm, cold)
        assert wire.snapshot() == snap, \
            f"warm superstep touched the wire: {wire.snapshot()} != {snap}"
        assert m.warm_range_hits == 1
        assert m.metadata_rpcs_per_stage == 0
        # the returned batch is a private copy: mutation can't poison
        warm[:8] = 0
        again, _ = _superstep(execs, handle, conf)
        np.testing.assert_array_equal(again, cold)
    finally:
        _shutdown(driver, execs)


def test_warm_read_cache_epoch_bump_serves_fresh_bytes(tmp_path):
    driver, execs = _cluster(tmp_path, n=3, warm_read_cache=True)
    try:
        handle = driver.register_shuffle(1, num_maps=2, num_partitions=2,
                                         partitioner=PartitionerSpec("modulo"))
        _write_maps(execs, handle, version=0, owner=0)
        conf = TpuShuffleConf(**dict(CONF_KW, warm_read_cache=True))
        cold, _ = _superstep(execs, handle, conf)
        warm, m = _superstep(execs, handle, conf)
        assert m.warm_range_hits == 1
        # re-execute map 1 elsewhere with new rows -> epoch bump
        w = execs[2].get_writer(handle, 1)
        new_rows = np.arange(1000, 1256, dtype=np.uint64)
        w.write_batch(new_rows)
        w.close()
        plane = execs[1].executor.location_plane
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and plane.known_epoch(1) != 2:
            time.sleep(0.01)
        assert plane.known_epoch(1) == 2
        fresh, m2 = _superstep(execs, handle, conf)
        assert m2.warm_range_hits == 0
        expect = np.sort(np.concatenate(
            [np.random.default_rng(0).integers(0, 64, 256),
             new_rows]).astype(np.uint64))
        np.testing.assert_array_equal(fresh, expect)
    finally:
        _shutdown(driver, execs)


# -- the iterative bench (acceptance gate) -------------------------------


def test_iterative_warm_bench_acceptance(tmp_path):
    """The bench secondary's tier-1 assertion: over a PageRank-style
    10-superstep loop, warm supersteps issue ZERO metadata RPCs, the
    bytes are identical, and the per-superstep improvement vs per-stage
    cold metadata clears 1.5x (with the fixed metadata service delay
    standing in for control-plane RTT, see shuffle/iter_bench.py)."""
    from sparkrdma_tpu.shuffle.iter_bench import run_iterative_microbench

    from sparkrdma_tpu.utils.benchgate import gated_best_of

    res = gated_best_of(
        lambda: run_iterative_microbench(str(tmp_path), supersteps=10,
                                         delay_s=0.008))
    assert res["identical"], "cold and warm supersteps diverged"
    assert res["metadata_rpcs_per_superstep"]["warm"] == 0.0, res
    assert res["metadata_rpcs_per_superstep"]["cold"] >= 2.0, res
    assert res["speedup"] >= 1.5, res


def test_dense_exchange_bench_guard():
    """The dense-exchange regression guard (bench satellite): dense and
    gather step the same rows in the same process — the recorded ratio
    cancels host noise, so a dense-specific regression is attributable
    per bench round. At micro size the ratio just has to be sane and
    both transports must actually run."""
    import bench as bench_mod
    import jax
    from jax.sharding import Mesh

    from sparkrdma_tpu.models.terasort import TeraSortConfig, generate_rows

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("shuffle",))
    cfg = TeraSortConfig(rows_per_device=512, payload_words=24,
                         out_factor=1 if len(devs) == 1 else 2,
                         sort_mode="gather")
    rows = generate_rows(cfg, len(devs), seed=1)
    detail = {}
    bench_mod._bench_dense_guard(detail, mesh, "dense", cfg, rows)
    assert "dense_exchange_guard" in detail, detail
    g = detail["dense_exchange_guard"]
    assert g["dense_step_s"] > 0 and g["gather_step_s"] > 0
    assert 0 < g["dense_vs_gather"] < 100


# -- dist_cache bounds (satellite) ---------------------------------------


def test_dist_cache_byte_budget_evicts_lru():
    dist_cache.configure(0)  # flush residue from earlier tests (the
    # cache is process-global on purpose — co-hosted managers share it)
    dist_cache.configure(10_000)
    try:
        k = np.zeros(500, dtype=np.uint64)      # 4000 B
        p = np.zeros((500, 1), dtype=np.uint8)  # 500 B
        base = dist_cache.evicted
        assert dist_cache.put_range(101, 1, 0, 4, k, p)
        assert dist_cache.put_range(102, 1, 0, 4, k.copy(), p.copy())
        assert dist_cache.get_range(101, 1, 0, 4) is not None
        # a third shuffle exceeds the budget: the LRU one (102 — 101 was
        # touched by the get above) evicts
        assert dist_cache.put_range(103, 1, 0, 4, k.copy(), p.copy())
        assert dist_cache.evicted == base + 1
        assert dist_cache.get_range(102, 1, 0, 4) is None
        assert dist_cache.get_range(101, 1, 0, 4) is not None
        assert dist_cache.get_range(103, 1, 0, 4) is not None
        stats = dist_cache.stats()
        assert stats["bytes"] <= stats["budget"]
        assert stats["evicted"] == dist_cache.evicted
    finally:
        for sid in (101, 102, 103):
            dist_cache.drop(sid)
        dist_cache.configure(256 << 20)


def test_dist_cache_oversized_entry_never_thrashes():
    dist_cache.configure(1000)
    try:
        big_k = np.zeros(1000, dtype=np.uint64)  # 8000 B > budget
        small = np.zeros(10, dtype=np.uint64)
        pay = np.zeros((10, 1), dtype=np.uint8)
        assert dist_cache.put_range(201, 1, 0, 1, small, pay)
        before = dist_cache.evicted
        assert not dist_cache.put_range(202, 1, 0, 1, big_k,
                                        np.zeros((1000, 1), np.uint8))
        # the resident small entry survived; nothing was evicted for a
        # lost cause
        assert dist_cache.evicted == before
        assert dist_cache.get_range(201, 1, 0, 1) is not None
    finally:
        dist_cache.drop(201)
        dist_cache.drop(202)
        dist_cache.configure(256 << 20)


def test_dist_cache_mesh_store_budgeted_too():
    dist_cache.configure(10_000)
    try:
        keys = np.zeros(500, dtype=np.uint64)
        payload = np.zeros((500, 1), dtype=np.uint8)
        parts = np.zeros(500, dtype=np.int64)
        base = dist_cache.evicted
        assert dist_cache.store(301, [(keys, payload, parts)]) == [0]
        assert dist_cache.store(302, [(keys, payload, parts)]) == [0]
        assert dist_cache.store(303, [(keys, payload, parts)]) == [0]
        assert dist_cache.evicted > base
        assert dist_cache.get(303, 0) is not None
        stats = dist_cache.stats()
        assert stats["bytes"] <= stats["budget"]
    finally:
        for sid in (301, 302, 303):
            dist_cache.drop(sid)
        dist_cache.configure(256 << 20)


def test_dist_cache_epoch_bump_evicts_stale_ranges():
    dist_cache.configure(1 << 20)
    try:
        k = np.arange(10, dtype=np.uint64)
        p = np.zeros((10, 1), dtype=np.uint8)
        dist_cache.put_range(401, 1, 0, 4, k, p)
        dist_cache.on_epoch(401, 2)
        assert dist_cache.get_range(401, 1, 0, 4) is None
        assert dist_cache.stats()["warm_shuffles"] == 0
        # terminal bump drops both stores
        dist_cache.put_range(401, 2, 0, 4, k, p)
        dist_cache.on_epoch(401, -1)
        assert dist_cache.get_range(401, 2, 0, 4) is None
    finally:
        dist_cache.drop(401)
        dist_cache.configure(256 << 20)
