"""One-sided host serve path: zero-copy registered-region reads.

The serving side of the host dataplane rebuilt for constant server CPU
per request (csrc/blockserver.cpp): byte-identity between the native
fast path and the Python fallback server across the degenerate-shape
matrix (zero-length blocks, CRC trailers on/off, the exactly-
kMaxReqFrame request, merged-segment tokens) on both coalesce
dataplanes; the registration-on-demand pool (over-budget LRU remap then
re-serve, byte-identical, remap events traced); pin-safety of
unregister during an in-flight vectored serve; CRC-reuse parity against
zlib on both serving paths; and the serve-side CPU-per-GB acceptance
gate (>= 1.5x less CPU than the memcpy path at comparable throughput,
byte-identical with CRC on and off).
"""

import os
import struct
import threading
import zlib

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.transport import ConnectionCache
from sparkrdma_tpu.runtime import native
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader

SEED = int(os.environ.get("SERVE_SEED", "0"))

needs_native = pytest.mark.skipif(
    not (native.available() and native.has_serve_path()),
    reason="native serve path not built")

CONF_KW = dict(connect_timeout_ms=5000, pre_warm_connections=False)


# -- helpers ---------------------------------------------------------------


def _cluster(tmp_path, tag, n=3, **kw):
    conf = TpuShuffleConf(**dict(CONF_KW, **kw))
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=f"{tag}{i}",
                               spill_dir=str(tmp_path / f"{tag}{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _write_shuffle(driver, execs, num_maps=6, num_partitions=16,
                   payload_w=8, seed=SEED):
    handle = driver.register_shuffle(1, num_maps, num_partitions,
                                     PartitionerSpec("modulo"),
                                     row_payload_bytes=payload_w)
    rng = np.random.default_rng(seed)
    for m in range(num_maps):
        w = execs[m % 2].get_writer(handle, m)
        # skip odd partitions -> zero-length blocks ride every request
        keys = (rng.integers(0, num_partitions // 2,
                             size=180).astype(np.uint64) * 2)
        w.write_batch(keys, rng.integers(
            0, 255, (len(keys), payload_w), dtype=np.uint64
        ).astype(np.uint8))
        w.close()
    return handle


def _drain(execs, idx, handle, conf):
    reader = TpuShuffleReader(
        execs[idx].executor, execs[idx].resolver, conf, handle.shuffle_id,
        handle.num_maps, 0, handle.num_partitions, handle.row_payload_bytes)
    results = []
    reader.fetcher.start()
    try:
        for r in reader.fetcher:
            results.append((r.map_id, r.start_partition, r.end_partition,
                            bytes(r.data)))
            r.free()
    finally:
        reader.fetcher.close()
    return sorted(results), reader.metrics


def _fetch(cache, port, blocks, shuffle_id=1):
    conn = cache.get("127.0.0.1", port)
    resp = conn.request(M.FetchBlocksReq(conn.next_req_id(), shuffle_id,
                                         blocks))
    assert isinstance(resp, M.FetchBlocksResp)
    return resp


# -- fast path vs Python server: byte-identity matrix ---------------------


@needs_native
@pytest.mark.parametrize("checksum", [False, True])
def test_native_vs_python_serve_byte_identity(tmp_path, checksum):
    """The SAME shuffle, written identically into a native-serving and a
    Python-serving cluster, drains byte-identically (per-map attribution
    included) with CRC trailers on and off, on both coalesce dataplanes
    — zero-length blocks riding every request. The parity gate that
    keeps the Python serve loop an honest no-native fallback."""
    drained = {}
    for tag, native_on in (("n", True), ("p", False)):
        driver, execs = _cluster(
            tmp_path, tag, use_cpp_runtime=native_on,
            fetch_checksum=checksum, at_rest_checksum=True)
        try:
            if native_on:
                assert all(ex.block_server is not None for ex in execs), \
                    "native cluster must actually serve natively"
            handle = _write_shuffle(driver, execs)
            for coalesce in (True, False):
                conf = TpuShuffleConf(**dict(
                    CONF_KW, use_cpp_runtime=native_on,
                    fetch_checksum=checksum, at_rest_checksum=True,
                    coalesce_reads=coalesce))
                rows, _ = _drain(execs, 2, handle, conf)
                assert rows, "shuffle drained nothing"
                drained[(tag, coalesce)] = rows
        finally:
            _shutdown(driver, execs)
    for coalesce in (True, False):
        assert drained[("n", coalesce)] == drained[("p", coalesce)], \
            f"native and Python serving diverged (coalesce={coalesce})"


# -- registered-region pool: over-budget LRU remap then re-serve ----------


@needs_native
def test_over_budget_lru_remap_then_reserve(tmp_path):
    """With the region budget below one file, alternating serves evict
    and remap (counted, traced); every re-serve stays byte-exact and
    mapped bytes never exceed the budget once pins drain."""
    from sparkrdma_tpu.runtime.blockserver import BlockServer

    events = []

    class _Trace:
        def instant(self, name, cat, **kw):
            events.append((name, kw))

    rng = np.random.default_rng(SEED)
    datas, paths = {}, {}
    for t in (1, 2, 3):
        datas[t] = rng.integers(0, 255, 1 << 16, dtype=np.uint8).tobytes()
        p = tmp_path / f"f{t}.data"
        p.write_bytes(datas[t])
        paths[t] = str(p)
    srv = BlockServer(threads=2, tracer=_Trace())
    cache = ConnectionCache(TpuShuffleConf(**CONF_KW))
    try:
        for t in paths:
            srv.register_file(t, paths[t])
        srv.set_region_budget(len(datas[1]) + 512)
        for r in range(9):
            t = (r % 3) + 1
            resp = _fetch(cache, srv.port, [(t, 256, 8192), (t, 0, 0)])
            assert resp.status == M.STATUS_OK
            assert resp.data == datas[t][256:256 + 8192]
        stats = srv.trace_serve()
        assert stats["remaps"] >= 2, stats
        assert stats["mapped_bytes"] <= len(datas[1]) + 512
        assert stats["zero_copy_blocks"] >= 6
        names = [n for n, _ in events]
        assert "serve.remap" in names and "serve.pin" in names \
            and "serve.zero_copy" in names
        # after lifting the budget, the SAME tokens re-serve byte-exact
        srv.set_region_budget(0)
        for t in (1, 2, 3):
            resp = _fetch(cache, srv.port, [(t, 0, 1 << 16)])
            assert resp.data == datas[t]
    finally:
        cache.close_all()
        srv.stop()


# -- unregister during an in-flight vectored serve ------------------------


@needs_native
def test_unregister_during_inflight_vectored_serve(tmp_path):
    """A register/unregister storm against a token being served in
    vectored requests: every OK response is byte-exact (the refcount pin
    froze its region), misses answer UNKNOWN, nothing crashes. The ASan
    twin of this test lives in analysis/native_harness.py."""
    from sparkrdma_tpu.runtime.blockserver import BlockServer

    rng = np.random.default_rng(SEED + 1)
    data = rng.integers(0, 255, 1 << 18, dtype=np.uint8).tobytes()
    keep = tmp_path / "keep.data"
    keep.write_bytes(data)
    churn_path = tmp_path / "churn.data"
    churn_path.write_bytes(data)
    srv = BlockServer(threads=2)
    cache = ConnectionCache(TpuShuffleConf(**CONF_KW))
    stop = threading.Event()

    def churn():
        while not stop.is_set():
            srv.unregister_file(9)
            srv.register_file(9, str(churn_path))

    th = threading.Thread(target=churn)
    try:
        srv.register_file(1, str(keep))
        srv.register_file(9, str(churn_path))
        th.start()
        ok = unknown = 0
        for r in range(200):
            blocks = [(9, 0, 65536), (1, 4096, 4096), (9, 131072, 65536)]
            resp = _fetch(cache, srv.port, blocks)
            if resp.status == M.STATUS_OK:
                want = data[:65536] + data[4096:8192] + data[131072:196608]
                assert resp.data == want
                ok += 1
            else:
                assert resp.status == M.STATUS_UNKNOWN_SHUFFLE
                unknown += 1
        assert ok + unknown == 200
    finally:
        stop.set()
        th.join()
        cache.close_all()
        srv.stop()


# -- token = inode snapshot, not path -------------------------------------


@needs_native
def test_token_pins_inode_across_rename_over(tmp_path):
    """resolver.commit os.replace()s the SAME path on a speculative or
    retried re-commit BEFORE the old token unregisters — so a registered
    token must stay bound to the inode it validated. Never-mapped and
    LRU-evicted regions (re)map through the registration-time fd and
    serve the ORIGINAL bytes after the rename-over, on both dataplanes."""
    from sparkrdma_tpu.runtime.blockserver import BlockServer
    from sparkrdma_tpu.runtime.staging import SpillFile

    rng = np.random.default_rng(SEED + 5)
    old = rng.integers(0, 255, 1 << 14, dtype=np.uint8).tobytes()
    new = rng.integers(0, 255, 1 << 14, dtype=np.uint8).tobytes()
    p = tmp_path / "f.data"
    p.write_bytes(old)
    srv = BlockServer(threads=1)
    cache = ConnectionCache(TpuShuffleConf(**CONF_KW))
    try:
        srv.register_file(1, str(p))  # never served: mapping still deferred
        srv.register_file(2, str(p))
        srv.set_region_budget(1)      # evict the moment pins release
        assert _fetch(cache, srv.port, [(2, 0, 4096)]).status == M.STATUS_OK
        assert srv.stats()["mapped_bytes"] == 0  # token 2's region evicted
        nxt = tmp_path / "f.next"
        nxt.write_bytes(new)
        os.replace(nxt, p)            # the re-commit's rename-over
        first = _fetch(cache, srv.port, [(1, 0, 1 << 14)])  # first-ever map
        remap = _fetch(cache, srv.port, [(2, 0, 1 << 14)])  # post-evict remap
        assert first.status == M.STATUS_OK and first.data == old
        assert remap.status == M.STATUS_OK and remap.data == old
    finally:
        cache.close_all()
        srv.stop()
    # the Python fallback's half: SpillFile's deferred first map reads
    # through the construction-time fd, not the renamed-over path
    p2 = tmp_path / "g.data"
    p2.write_bytes(old)
    sf = SpillFile(str(p2), [len(old)], file_token=7,
                   delete_on_dispose=False)
    nxt2 = tmp_path / "g.next"
    nxt2.write_bytes(new)
    os.replace(nxt2, p2)
    out = np.empty(len(old), dtype=np.uint8)
    sf.gather([0], [len(old)], out)
    sf.dispose()
    assert out.tobytes() == old


# -- degenerate frames ----------------------------------------------------


@needs_native
def test_exactly_max_req_frame_and_zero_length(tmp_path):
    """The biggest request frame the server must parse — exactly under
    kMaxReqFrame, 65534 zero-length blocks — serves OK with a full CRC
    trailer of zeros; an all-zero-length vectored request is legal."""
    from sparkrdma_tpu.runtime.blockserver import BlockServer

    p = tmp_path / "f.data"
    p.write_bytes(b"x" * 1024)
    srv = BlockServer(threads=1, checksum=True)
    cache = ConnectionCache(TpuShuffleConf(**CONF_KW))
    try:
        srv.register_file(5, str(p))
        from sparkrdma_tpu.parallel.rpc_msg import HEADER
        nmax = (M.NATIVE_MAX_REQ_FRAME - M.BLOCKS_REQ_FIXED_BYTES
                - HEADER.size) // M.BLOCK_WIRE_BYTES
        resp = _fetch(cache, srv.port, [(5, 0, 0)] * nmax)
        assert resp.status == M.STATUS_OK
        assert resp.flags & M.FLAG_CRC32
        assert resp.data == b"\x00" * (4 * nmax)  # trailer of empty CRCs
    finally:
        cache.close_all()
        srv.stop()


# -- CRC reuse parity (both serving paths) --------------------------------


@needs_native
def test_native_crc_reuse_parity_with_zlib(tmp_path):
    """Attested-range CRC reuse on the native path: aligned blocks (one
    range, several combined ranges, the whole file) take table CRCs,
    unaligned blocks recompute — every trailer entry equals zlib.crc32
    of the served bytes either way."""
    from sparkrdma_tpu.runtime.blockserver import BlockServer

    rng = np.random.default_rng(SEED + 2)
    data = rng.integers(0, 255, 1 << 16, dtype=np.uint8).tobytes()
    p = tmp_path / "f.data"
    p.write_bytes(data)
    rlen = 1 << 13
    ranges = [(o, rlen, zlib.crc32(data[o:o + rlen]))
              for o in range(0, len(data), rlen)]
    srv = BlockServer(threads=1, checksum=True)
    cache = ConnectionCache(TpuShuffleConf(**CONF_KW))
    try:
        srv.register_file(4, str(p), crc_ranges=ranges)
        blocks = [(4, 0, rlen), (4, rlen, 3 * rlen), (4, 0, len(data)),
                  (4, 5, 1000), (4, 0, 0)]
        resp = _fetch(cache, srv.port, blocks)
        assert resp.status == M.STATUS_OK and resp.flags & M.FLAG_CRC32
        body_len = sum(ln for _, _, ln in blocks)
        body, trailer = resp.data[:body_len], resp.data[body_len:]
        assert body == b"".join(data[o:o + ln] for _, o, ln in blocks)
        crcs = struct.unpack(f"<{len(blocks)}I", trailer)
        pos = 0
        for (_, _, ln), crc in zip(blocks, crcs):
            assert crc == zlib.crc32(body[pos:pos + ln])
            pos += ln
        stats = srv.stats()
        # exactly the aligned non-empty blocks reused attested CRCs
        # (zero-length trailers are constant 0, not a table lookup)
        assert stats["crc_reused"] == 3
    finally:
        cache.close_all()
        srv.stop()


def test_python_block_crc_reuse_parity(tmp_path):
    """The Python serving path's half of the CRC-reuse contract:
    resolver.block_crc answers committed sidecar CRCs for partition-
    aligned ranges (combined across partitions) and None off-alignment;
    answers always equal zlib.crc32 of the served bytes."""
    from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver

    conf = TpuShuffleConf(use_cpp_runtime=False, at_rest_checksum=True)
    resolver = TpuShuffleBlockResolver(str(tmp_path / "spill"), conf=conf)
    try:
        rng = np.random.default_rng(SEED + 3)
        parts = [rng.integers(0, 255, ln, dtype=np.uint8).tobytes()
                 for ln in (700, 0, 1300, 512)]
        blob = b"".join(parts)
        tmp = resolver.data_tmp_path(1, 0)
        with open(tmp, "wb") as f:
            f.write(blob)
        _, token = resolver.commit(1, 0, tmp, [len(p) for p in parts])
        offs = np.cumsum([0] + [len(p) for p in parts]).tolist()
        # aligned: one partition, a run across the empty partition, all
        for lo, hi in ((0, 1), (0, 3), (2, 3), (0, 4)):
            off, ln = offs[lo], offs[hi] - offs[lo]
            got = resolver.block_crc(1, token, off, ln)
            assert got == zlib.crc32(blob[off:off + ln]), (lo, hi)
        assert resolver.block_crc(1, token, 0, 0) == 0
        # unaligned: recompute (None)
        assert resolver.block_crc(1, token, 1, 100) is None
        assert resolver.block_crc(1, token, 0, 699) is None
        # served bytes == what the CRCs attest
        assert resolver.read_block(1, token, 0, len(blob)) == blob
    finally:
        resolver.stop()


# -- merged-segment tokens -------------------------------------------------


@needs_native
def test_merged_segment_tokens_native_vs_python(tmp_path):
    """Merged segments (register_external tokens with ledger-attested
    ranges) serve byte-identically from the native fast path and the
    Python fallback, merged-first reads engaged on both; the native
    serve reuses the ledger CRCs for its trailers."""
    drained = {}
    merged_reads = {}
    for tag, native_on in (("mn", True), ("mp", False)):
        kw = dict(CONF_KW, use_cpp_runtime=native_on, push_merge=True,
                  merge_replicas=1, push_deadline_ms=8000,
                  fetch_checksum=True)
        driver, execs = _cluster(tmp_path, tag, **kw)
        reducer = None
        try:
            num_maps, num_parts = 8, 4
            handle = driver.register_shuffle(
                3, num_maps, num_parts, PartitionerSpec("modulo"),
                row_payload_bytes=24)
            rng = np.random.default_rng(SEED + 4)
            keys = np.repeat(np.arange(num_parts, dtype=np.uint64), 12)
            for m in range(num_maps):
                w = execs[0].get_writer(handle, m)
                w.write_batch(keys, rng.integers(
                    0, 255, (len(keys), 24), dtype=np.uint64
                ).astype(np.uint8))
                w.close()
            from sparkrdma_tpu.shuffle.push_merge import wait_for_coverage
            execs[0].pusher.drain(15)
            assert wait_for_coverage(driver.driver, handle.shuffle_id,
                                     num_maps, num_parts, timeout=15)
            reducer = TpuShuffleManager(
                TpuShuffleConf(**kw), driver_addr=driver.driver_addr,
                executor_id=f"{tag}r",
                spill_dir=str(tmp_path / f"{tag}r"))
            reducer.executor.wait_for_members(4)
            reader = TpuShuffleReader(
                reducer.executor, reducer.resolver, TpuShuffleConf(**kw),
                handle.shuffle_id, num_maps, 0, num_parts, 24)
            rows = []
            reader.fetcher.start()
            try:
                for r in reader.fetcher:
                    rows.append(bytes(r.data))
                    r.free()
            finally:
                reader.fetcher.close()
            blob = np.frombuffer(b"".join(rows), dtype=np.uint8)
            blob = blob.reshape(-1, 32)
            drained[tag] = blob[np.lexsort(blob.T[::-1])]
            merged_reads[tag] = reader.metrics.merged_reads
            if native_on:
                reused = sum(ex.block_server.stats()["crc_reused"]
                             for ex in execs if ex.block_server)
                assert reused > 0, \
                    "native merged serve reused no ledger CRCs"
        finally:
            if reducer is not None:
                reducer.stop()
            _shutdown(driver, execs)
    assert merged_reads["mn"] > 0 and merged_reads["mp"] > 0
    assert np.array_equal(drained["mn"], drained["mp"])


# -- full shuffle under a sub-working-set budget ---------------------------


@needs_native
def test_shuffle_completes_under_region_budget(tmp_path):
    """With registered_region_budget far below the committed working
    set, a full shuffle still drains byte-identically to an unbudgeted
    run — serves remap on demand (events traced via serve.remap) instead
    of growing the mapped set without bound."""
    drained = {}
    for tag, budget in (("b", 4096), ("u", 0)):
        driver, execs = _cluster(
            tmp_path, tag, use_cpp_runtime=True,
            registered_region_budget=budget,
            trace_file=str(tmp_path / f"{tag}.trace"))
        try:
            handle = _write_shuffle(driver, execs, num_maps=8)
            conf = TpuShuffleConf(**dict(CONF_KW, use_cpp_runtime=True))
            # two drains: the first maps every served file (evicting as
            # pins release), the second re-serves files the budget
            # already unmapped — the remap-on-demand path
            rows, _ = _drain(execs, 2, handle, conf)
            rows2, _ = _drain(execs, 2, handle, conf)
            assert rows and rows == rows2
            drained[tag] = rows
            if budget:
                stats = {}
                for ex in execs:
                    if ex.block_server is None:
                        continue
                    s = ex.block_server.trace_serve()
                    for k, v in s.items():
                        stats[k] = stats.get(k, 0) + v
                assert stats["remaps"] > 0, stats
                assert stats["mapped_bytes"] <= 2 * 4096, stats
                traced = [e["name"] for ex in execs
                          for e in ex.tracer._events]
                assert "serve.remap" in traced
        finally:
            _shutdown(driver, execs)
    assert drained["b"] == drained["u"]


# -- acceptance: serve-side CPU per GB ------------------------------------


@needs_native
def test_serve_cpu_per_gb_acceptance(tmp_path):
    """The tier-1 gate on the tentpole: the zero-copy path serves the
    same bytes with >= 1.5x less server CPU per GB than the memcpy path
    (>= 2x is the bench target; CPU ratios are rusage-based and thus
    host-contention-robust), byte-identical with CRC trailers on AND
    off, CRC reuse engaged in the checksum mode."""
    from sparkrdma_tpu.shuffle.serve_bench import run_serve_microbench

    for checksum in (False, True):
        res = run_serve_microbench(str(tmp_path / f"c{checksum}"),
                                   file_mb=32, total_mb=160,
                                   checksum=checksum)
        assert res["identical"], res
        assert res["trailer_ok"], res
        assert res["cpu_speedup"] >= 1.5, res
        if checksum:
            assert res["crc_reused"] > 0, res
        # throughput must not regress materially (equal-or-better is the
        # bench-script gate; tier-1 tolerates scheduler noise)
        thr = res["throughput_gb_s"]
        assert thr["zero_copy"] >= 0.7 * thr["memcpy"], res
