"""The DAG engine across REAL process boundaries: tasks ship by
cloudpickle to executor processes (the role Spark's task scheduler plays
for the reference) and run against each process's local manager; stage
retry spans processes — a killed executor's maps recompute on survivors."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
from sparkrdma_tpu.shuffle.manager import PartitionerSpec
from sparkrdma_tpu.shuffle.spark_compat import (
    ShuffleDependency,
    SparkCompatShuffleManager,
)
from sparkrdma_tpu.tasks import remote_executors

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = f'''
import sys, time
sys.path.insert(0, {REPO_ROOT!r})
from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager
from sparkrdma_tpu.tasks import install_task_server

host, port, exec_id, spill = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
mgr = SparkCompatShuffleManager(
    TpuShuffleConf(connect_timeout_ms=5000), driverAddr=(host, port),
    executorId=exec_id, spill_dir=spill)
install_task_server(mgr)
print("WORKER_READY", exec_id, flush=True)
time.sleep(600)
'''

CONF = TpuShuffleConf(connect_timeout_ms=2000, max_connection_attempts=2,
                      task_timeout_ms=60_000)


@pytest.fixture
def cluster(tmp_path):
    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    host, port = driver.driverAddr
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, host, str(port), f"w{i}",
         str(tmp_path / f"w{i}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        remotes = remote_executors(driver, CONF, expect=2, timeout=30)
        yield driver, remotes, procs
    finally:
        for p in procs:
            p.kill()
        for r in (locals().get("remotes") or []):
            r.stop()
        driver.stop()


def _job(P, maps, rows, seed):
    def map_fn(ctx, writer, task_id):
        rng = np.random.default_rng(seed + task_id)
        keys = rng.integers(0, 4000, rows).astype(np.uint64)
        vals = rng.integers(0, 1000, rows).astype("<u4")
        writer.write((keys, vals.view(np.uint8).reshape(rows, 4)))

    def reduce_fn(ctx, task_id):
        total = 0
        for keys, payload in ctx.read(0).readBatches():
            vals = np.ascontiguousarray(payload).view("<u4")
            total += int(vals.astype(np.int64).sum())
        return total

    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    want = 0
    for m in range(maps):
        rng = np.random.default_rng(seed + m)
        rng.integers(0, 4000, rows)  # keys draw, same stream as map_fn
        want += int(rng.integers(0, 1000, rows).astype(np.int64).sum())
    return ResultStage(P, reduce_fn, parents=[stage]), want


def test_remote_job_exact(cluster):
    """A shuffle job whose every task runs in an executor process."""
    driver, remotes, _ = cluster
    job, want = _job(P=4, maps=6, rows=800, seed=50)
    got = sum(DAGEngine(driver, remotes).run(job))
    assert got == want


def test_remote_executor_loss_recovers(cluster, tmp_path, caplog):
    """Kill one executor PROCESS mid-job: the remote FetchFailed re-raises
    driver-side, lost maps recompute on the surviving process, results
    are exact."""
    import logging

    caplog.set_level(logging.WARNING, logger="sparkrdma_tpu.engine")
    driver, remotes, procs = cluster
    sentinel = tmp_path / "task0-running"

    def map_fn(ctx, writer, task_id):
        rng = np.random.default_rng(70 + task_id)
        keys = rng.integers(0, 4000, 600).astype(np.uint64)
        vals = rng.integers(0, 1000, 600).astype("<u4")
        writer.write((keys, vals.view(np.uint8).reshape(600, 4)))

    spath = str(sentinel)

    def reduce_fn(ctx, task_id):
        if task_id == 0:
            open(spath, "w").write("x")
            time.sleep(2.0)  # window for the driver-side kill
        total = 0
        for keys, payload in ctx.read(0).readBatches():
            vals = np.ascontiguousarray(payload).view("<u4")
            total += int(vals.astype(np.int64).sum())
        return total

    # task 0 runs on remotes[0]; the victim is the OTHER worker, which
    # owns the odd map ids (round-robin placement). Hello order is
    # nondeterministic, so match the victim's process by executor id
    # (worker i was spawned as executorId f"w{i}").
    victim = remotes[1]
    victim_proc = procs[int(victim.manager_id.executor_id.executor[1:])]

    def killer():
        deadline = time.monotonic() + 30
        while not sentinel.exists() and time.monotonic() < deadline:
            time.sleep(0.02)
        victim_proc.kill()
        driver.native.driver.remove_member(victim.manager_id)

    k = threading.Thread(target=killer, daemon=True)
    k.start()

    stage = MapStage(6, ShuffleDependency(
        4, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    got = sum(DAGEngine(driver, remotes).run(
        ResultStage(4, reduce_fn, parents=[stage])))
    k.join(timeout=5)
    assert sentinel.exists(), "failure injection never armed"

    want = 0
    for m in range(6):
        rng = np.random.default_rng(70 + m)
        rng.integers(0, 4000, 600)  # keys draw, same stream as map_fn
        want += int(rng.integers(0, 1000, 600).astype(np.int64).sum())
    assert got == want
    assert any("recovering shuffle" in r.message for r in caplog.records)


def test_parallel_task_dispatch(cluster):
    """Tasks within a stage run concurrently across executor processes
    (and their task slots): 4 sleeping result tasks over 2 workers finish
    in ~1 sleep, not 4."""
    driver, remotes, _ = cluster
    job, want = _job(P=4, maps=2, rows=50, seed=90)

    def slow_reduce(ctx, task_id):
        t0 = time.monotonic()
        time.sleep(0.5)
        total = 0
        for keys, payload in ctx.read(0).readBatches():
            vals = np.ascontiguousarray(payload).view("<u4")
            total += int(vals.astype(np.int64).sum())
        return total, t0

    stage = job.parents[0]
    results = DAGEngine(driver, remotes, max_parallel_tasks=4).run(
        ResultStage(4, slow_reduce, parents=[stage]))
    assert sum(r[0] for r in results) == want
    # overlap, not wall time (load-tolerant): some pair of the 0.5s sleep
    # windows [t0, t0+0.5) must intersect — impossible if serialized
    starts = sorted(r[1] for r in results)
    gaps = [b - a for a, b in zip(starts, starts[1:])]
    assert min(gaps) < 0.5, f"tasks were serialized (gaps {gaps})"


def test_dependency_combiner_applies_in_shipped_tasks(cluster):
    """A combiner on ShuffleDependency rides the cloudpickled task to the
    worker: duplicate keys collapse before bytes hit the wire."""
    from sparkrdma_tpu.shuffle.writer import make_sum_combiner

    driver, remotes, _ = cluster

    def map_fn(ctx, writer, t):
        keys = np.full(1000, 7 + t, np.uint64)  # 1000 dups per map
        vals = np.ones(1000, "<u4")
        writer.write((keys, vals.view(np.uint8).reshape(1000, 4)))

    def red_fn(ctx, t):
        rows = 0
        total = 0
        for keys, payload in ctx.read(0).readBatches():
            rows += len(keys)
            total += int(np.ascontiguousarray(payload).view("<u4")
                         .astype(np.int64).sum())
        return rows, total

    stage = MapStage(2, ShuffleDependency(
        4, PartitionerSpec("modulo"), row_payload_bytes=4,
        combiner=make_sum_combiner()), map_fn)
    results = DAGEngine(driver, remotes).run(
        ResultStage(4, red_fn, parents=[stage]))
    assert sum(r[0] for r in results) == 2, "combine did not collapse rows"
    assert sum(r[1] for r in results) == 2000


def test_shared_vars_across_processes(cluster):
    """Broadcast fetched over the control plane by worker PROCESSES (the
    closure ships only the id) and accumulator deltas returned in the
    task-result envelope, merged exactly once driver-side."""
    driver, remotes, _ = cluster
    engine = DAGEngine(driver, remotes)
    lookup = engine.broadcast({k: 2 * k for k in range(100)})
    seen = engine.accumulator("seen")
    P, maps, rows = 4, 4, 200

    def map_fn(ctx, writer, task_id):
        keys = np.arange(rows, dtype=np.uint64) % 100
        vals = keys.astype("<u4")
        writer.write((keys, vals.view(np.uint8).reshape(rows, 4)))
        seen.add(rows)

    def reduce_fn(ctx, task_id):
        table = lookup.value  # triggers the once-per-process fetch
        total = 0
        for keys, _ in ctx.read(0).readBatches():
            total += sum(table[int(k)] for k in keys)
        return total

    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    got = sum(engine.run(ResultStage(P, reduce_fn, parents=[stage])))
    want = maps * int(sum(2 * (k % 100) for k in range(rows)))
    assert got == want
    assert seen.value == maps * rows
