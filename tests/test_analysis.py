"""Invariant analyzer suite (sparkrdma_tpu/analysis/): the tier-1 gate.

Three claims, each load-bearing:

1. the LIVE TREE is clean — every static pass (wire, concurrency,
   drift) runs over the real codebase and reports zero findings, so a
   drifted constant, an unguarded shared write, or a typo'd trace name
   fails the build here;
2. the analyzers actually DETECT — each seeded-violation fixture under
   tests/fixtures/analysis/ is caught by its pass with the right
   file:line (an analyzer that silently stopped seeing violations
   would otherwise "pass" forever);
3. the lockgraph shim records real acquisition orderings — a synthetic
   inversion is reported as a cycle, and a genuine multi-threaded
   shuffle (writers spilling, readers fetching over sockets) runs
   ACYCLIC under the shim with Condition semantics intact.

The sanitizer harness (pass 4) is exercised when RUN_SANITIZERS=1
(scripts/run_analysis.sh --sanitize); building instrumented .so's is
out of tier-1's budget.
"""

import importlib.util
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import sparkrdma_tpu.analysis as analysis
from sparkrdma_tpu.analysis import (concurrency, core, drift, lockgraph,
                                    modelcheck, resources, scheduler, wire)

ROOT = core.repo_root()
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "analysis")


def _load_fixture(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(FIXTURES, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # inspect needs it to resolve source files
    spec.loader.exec_module(mod)
    return mod


def _marker_line(path, marker="seeded-violation"):
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            if marker in line:
                return i
    raise AssertionError(f"no '{marker}' marker in {path}")


# ---------------------------------------------------------- the live gate

def test_live_tree_zero_findings():
    """THE gate: wire + concurrency + drift over the real tree."""
    findings = analysis.run_all()
    assert not findings, "\n" + core.format_report(findings)


def test_wire_registry_is_dense_and_unique():
    findings = wire.check_registry(wire.live_pairs())
    assert not findings, "\n" + core.format_report(findings)
    ids = [t for t, _ in wire.live_pairs()]
    assert len(ids) == len(set(ids))
    assert set(ids) | set(wire.rpc_msg.RESERVED_WIRE_IDS) == set(
        range(1, max(ids) + 1))


def test_wire_density_over_full_membership_range():
    """Msgs 51-53 (the cold tier's one-sided blob publish + directory
    pull) closed the id space at 53: the registry + reservations must
    tile 1..53 exactly, every membership message must carry
    _EXTRA_CASES domain corners (epoch 0, max-i64, DRAINING-only
    vectors), and the tiered frames must carry theirs (empty covered
    bitmap, max-u64 blob size, the EPOCH_DEAD directory answer) so the
    fuzzer exercises the pack boundaries the name-based generator
    avoids."""
    ids = [t for t, _ in wire.live_pairs()]
    assert max(ids) == 53
    assert set(ids) | set(wire.rpc_msg.RESERVED_WIRE_IDS) == set(
        range(1, 54))
    for name in ("JoinMsg", "MembershipBumpMsg", "DrainReq", "DrainResp"):
        assert name in wire._EXTRA_CASES, name
    corners = [c() for c in wire._EXTRA_CASES["MembershipBumpMsg"]]
    assert any(m.epoch == 0 for m in corners)
    assert any(m.epoch == (1 << 63) - 1 for m in corners)
    assert any(m.slot_states and all(s == 1 for s in m.slot_states)
               for m in corners)  # DRAINING-only fleet vector
    for name in ("TieredPublishMsg", "FetchTieredResp"):
        assert name in wire._EXTRA_CASES, name
    tiered = [c() for c in wire._EXTRA_CASES["TieredPublishMsg"]]
    assert any(m.covered == b"" for m in tiered)  # empty coverage
    assert any(m.nbytes == (1 << 64) - 1 for m in tiered)  # u64 edge
    dirs = [c() for c in wire._EXTRA_CASES["FetchTieredResp"]]
    assert any(m.epoch == wire.M.EPOCH_DEAD and m.data == b""
               for m in dirs)  # dead-shuffle directory answer


def test_wire_doc_table_matches_registry():
    assert not wire.check_doc_table()


def test_legacy_truncation_matrix():
    assert not wire.check_truncation()


def test_native_constant_lockstep():
    assert not wire.check_native_constants()


# ------------------------------------------------------- fixture detection

def test_fixture_duplicate_msg_id():
    mod = _load_fixture("fixture_dup_msg_id")
    findings = wire.check_registry(mod.FIXTURE_PAIRS,
                                   wire_ids=mod.FIXTURE_WIRE_IDS,
                                   reserved={})
    dups = [f for f in findings if "duplicate wire id 1" in f.message]
    assert dups, core.format_report(findings)
    path = os.path.join(FIXTURES, "fixture_dup_msg_id.py")
    assert dups[0].path.endswith("fixture_dup_msg_id.py")
    assert dups[0].line == _marker_line(path)


def test_fixture_asymmetric_roundtrip():
    mod = _load_fixture("fixture_asymmetric")
    findings = wire.fuzz_roundtrip(mod.FIXTURE_PAIRS)
    asym = [f for f in findings if "asymmetry" in f.message]
    assert asym, core.format_report(findings)
    path = os.path.join(FIXTURES, "fixture_asymmetric.py")
    assert asym[0].path.endswith("fixture_asymmetric.py")
    assert asym[0].line == _marker_line(path)


def test_fixture_unguarded_write():
    path = os.path.join(FIXTURES, "fixture_unguarded_write.py")
    with open(path) as f:
        findings = concurrency.scan_source(f.read(), path)
    hits = [f for f in findings if "_count" in f.message
            and "outside any 'with <lock>'" in f.message]
    assert hits, core.format_report(findings)
    assert hits[0].line == _marker_line(path)


def test_fixture_wait_without_loop_and_deadline():
    path = os.path.join(FIXTURES, "fixture_wait_no_loop.py")
    with open(path) as f:
        findings = concurrency.scan_source(f.read(), path)
    no_loop = [f for f in findings if "outside a 'while'" in f.message]
    no_deadline = [f for f in findings if "without a deadline" in f.message]
    assert no_loop and no_loop[0].line == _marker_line(path)
    assert no_deadline and no_deadline[0].line == _marker_line(
        path, "seeded-deadline")


def test_fixture_undocumented_and_ghost_key():
    py = os.path.join(FIXTURES, "fixture_undocumented_key.py")
    md = os.path.join(FIXTURES, "fixture_undocumented_key.md")
    with open(md) as f:
        doc_text = f.read()
    findings = drift.check_config_docs(
        drift._config_key_lines(py), py, doc_text, md)
    missing = [f for f in findings if "mystery_key" in f.message]
    stale = [f for f in findings if "ghost_key" in f.message]
    assert missing and missing[0].path == py
    assert missing[0].line == _marker_line(py)
    assert stale and stale[0].path == md
    assert stale[0].line == _marker_line(md)
    assert len(findings) == 2  # documented_key drifts neither way


# ----------------------------------------------------------- pragma rules

def test_bare_pragma_is_a_finding():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._x = 0\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._x = 1\n"
           "    def b(self):\n"
           "        self._x = 2  # analysis: unguarded-ok\n")
    findings = concurrency.scan_source(src, "<mem>")
    assert any(f.pass_name == "pragma" for f in findings)


def test_reasoned_pragma_suppresses():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._x = 0\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._x = 1\n"
           "    def b(self):\n"
           "        self._x = 2  # analysis: unguarded-ok(single-owner)\n")
    assert not concurrency.scan_source(src, "<mem>")


def test_locked_suffix_convention():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._x = 0\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._x = 1\n"
           "    def bump_locked(self):\n"
           "        self._x += 1\n")
    assert not concurrency.scan_source(src, "<mem>")


# -------------------------------------------------------------- lockgraph

def test_lockgraph_unit_cycle_detection():
    g = lockgraph.LockGraph()
    g._push("A", 1)
    g._note_acquire("B", 2)
    g._push("B", 2)
    g._pop("B", 2)
    g._pop("A", 1)
    g._push("B", 2)
    g._note_acquire("A", 1)  # inversion
    g._push("A", 1)
    cycles = g.cycles()
    assert len(cycles) == 1 and set(cycles[0]) == {"A", "B"}
    assert "A -> B" in g.format_cycles()


def test_lockgraph_same_site_pairs_excluded():
    g = lockgraph.LockGraph()
    g._push("A", 1)
    g._note_acquire("A", 2)  # second instance of the same role
    g._push("A", 2)
    assert not g.cycles() and not g.edges()


def test_lockgraph_reentrant_rlock_no_edge():
    g = lockgraph.LockGraph()
    g._push("A", 1)
    g._note_acquire("A", 1)  # reentrant re-acquire
    assert not g.edges()


def test_shuffle_e2e_under_lockgraph_is_acyclic(tmp_path):
    """The acceptance run: a real 2-executor shuffle — streaming
    writers with background spill, socket fetch, driver publishes —
    recorded by the shim, then checked for lock-order cycles. Also
    proves patched Condition/RLock semantics hold end to end (the
    shuffle byte-verifies its output)."""
    from sparkrdma_tpu.config import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import (PartitionerSpec,
                                               TpuShuffleManager)

    owned = lockgraph.current() is None
    graph = lockgraph.install()
    pre = {tuple(c) for c in graph.cycles()}  # session shim may own graph
    try:
        conf = TpuShuffleConf(connect_timeout_ms=5000,
                              shuffle_read_block_size="4k",
                              spill_threshold_bytes=4096)
        driver = TpuShuffleManager(conf, is_driver=True)
        execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                                   executor_id=str(i),
                                   spill_dir=str(tmp_path / f"e{i}"))
                 for i in range(2)]
        try:
            for ex in execs:
                ex.executor.wait_for_members(2)
            handle = driver.register_shuffle(
                91, 4, 6, PartitionerSpec("modulo"), row_payload_bytes=8)
            rng = np.random.default_rng(3)
            total_rows = 0
            for m in range(4):
                keys = rng.integers(0, 5000, size=800).astype(np.uint64)
                payload = rng.integers(0, 255, size=(800, 8)).astype(np.uint8)
                w = execs[m % 2].get_writer(handle, m)
                w.write_batch(keys, payload)
                w.close()
                total_rows += 800
            got = 0
            for i, ex in enumerate(execs):
                reader = ex.get_reader(handle, i * 3, (i + 1) * 3)
                k, _ = reader.read_all()
                got += len(k)
            assert got == total_rows
        finally:
            for ex in execs:
                ex.stop()
            driver.stop()
    finally:
        if owned:
            lockgraph.uninstall()
    assert graph.edges(), "shim recorded nothing — install() broken?"
    new = [c for c in graph.cycles() if tuple(c) not in pre]
    assert not new, graph.format_cycles()


# ------------------------------------------------- model checker (pass 5)

def test_modelcheck_catalog_clean_and_enumerates_500():
    """THE model-check gate: every scenario in the catalog, under the
    tier-1 default budgets, enumerates schedules with ZERO invariant
    violations on the live tree — and the catalog covers >= 500
    distinct DFS schedules, so the sweep is an enumeration, not a
    sample."""
    findings, stats = modelcheck.run_catalog()
    assert not findings, "\n" + core.format_report(findings)
    total = sum(s.dfs_schedules for s in stats)
    assert total >= 500, f"only {total} schedules enumerated: {stats}"
    assert {s.name for s in stats} >= {
        "pub_tomb_bump", "fence_loser", "finalize_vs_push",
        "drain_vs_kill", "ttl_vs_late_fetch",
        "driver_failover_mid_publish", "split_brain_two_leases",
        "zombie_primary_publish", "failover_vs_ttl_sweep",
        "handoff_vs_publish", "handoff_vs_driver_failover"}


def test_modelcheck_driver_death_scenarios_enumerate_500():
    """The driver-HA gate (ISSUE 17 acceptance): the four driver-death
    scenarios ALONE cover >= 500 distinct DFS schedules with zero
    invariant violations — lease CAS single-holder, epoch monotonicity
    across incarnations, zombie writes fenced, no resurrected shuffle,
    ledger conservation through replay."""
    driver_death = {"driver_failover_mid_publish",
                    "split_brain_two_leases", "zombie_primary_publish",
                    "failover_vs_ttl_sweep"}
    total = 0
    for scn in modelcheck.catalog():
        if scn.name not in driver_death:
            continue
        runs, st = modelcheck.run_scenario(scn)
        bad = [r for r in runs if r.violation]
        assert not bad, (f"{scn.name}: {bad[0].violation}; "
                         f"schedule: {' -> '.join(bad[0].trace)}")
        total += st.dfs_schedules
    assert total >= 500, f"only {total} driver-death schedules"


def test_scheduler_fifo_channels_and_por():
    """Scheduler semantics the checker's soundness rests on: same-chan
    steps deliver FIFO (never reordered), commuting steps collapse to
    one canonical schedule, conflicting steps explore both orders."""
    order = []

    def build_fifo(sched):
        sched.post("a1", lambda s: order.append("a1"), chan="a")
        sched.post("a2", lambda s: order.append("a2"), chan="a")
        sched.post("b1", lambda s: order.append("b1"), chan="b")
        return None

    runs = scheduler.explore_dfs(build_fifo, lambda st, sc: None)
    assert len(runs) == 3  # interleavings of [a1,a2] with [b1]
    for run in runs:
        assert run.trace.index("a1") < run.trace.index("a2")

    def build_commute(sched):
        sched.post("x", lambda s: None, touches={"x"})
        sched.post("y", lambda s: None, touches={"y"})
        return None

    assert len(scheduler.explore_dfs(build_commute,
                                     lambda st, sc: None)) == 1

    def build_conflict(sched):
        sched.post("x", lambda s: None, touches={"shared"})
        sched.post("y", lambda s: None, touches={"shared"})
        return None

    assert len(scheduler.explore_dfs(build_conflict,
                                     lambda st, sc: None)) == 2


def test_fixture_ledger_double_release():
    """The conservation invariant catches a double-release at the
    seeded step's exact file:line (the floor-at-zero ledger would
    otherwise silently erase ANOTHER tenant item's live bytes)."""
    mod = _load_fixture("fixture_ledger_double_release")
    runs = scheduler.explore_dfs(mod.build, modelcheck.check_invariants)
    bad = [r for r in runs if r.violation is not None]
    assert bad and "ledger-conserve" in bad[0].violation
    path = os.path.join(FIXTURES, "fixture_ledger_double_release.py")
    apath, aline = modelcheck._anchor_of(bad[0], mod.build)
    assert apath.endswith("fixture_ledger_double_release.py")
    assert aline == _marker_line(path)


def test_fixture_bad_trace_caught_and_replays_byte_identically():
    """An invariant-violating schedule is caught at the seeded step's
    file:line, and its recorded trace replays BYTE-IDENTICALLY with
    the same violation — the --replay contract."""
    mod = _load_fixture("fixture_bad_trace")
    runs = scheduler.explore_dfs(mod.build, modelcheck.check_invariants)
    bad = [r for r in runs if r.violation is not None]
    assert bad and "epoch-monotone" in bad[0].violation
    path = os.path.join(FIXTURES, "fixture_bad_trace.py")
    apath, aline = modelcheck._anchor_of(bad[0], mod.build)
    assert apath.endswith("fixture_bad_trace.py")
    assert aline == _marker_line(path)
    replayed = scheduler.replay(mod.build, modelcheck.check_invariants,
                                bad[0].trace)
    assert replayed.trace == bad[0].trace  # byte-identical reproduction
    assert replayed.violation == bad[0].violation


def test_modelcheck_trace_artifact_roundtrip(tmp_path, monkeypatch):
    """run_catalog dumps a violating trace artifact and replay_trace
    re-runs it: seed a violating scenario into the catalog, then
    replay the dumped JSON byte-identically."""
    mod = _load_fixture("fixture_bad_trace")
    scn = modelcheck.Scenario("fixture_bad_trace", mod.build)
    monkeypatch.setattr(modelcheck, "_CATALOG",
                        modelcheck._CATALOG + [scn])
    findings, _stats = modelcheck.run_catalog(trace_dir=str(tmp_path))
    assert findings and "fixture_bad_trace" in findings[-1].message
    artifact = tmp_path / "fixture_bad_trace.trace.json"
    assert artifact.exists()
    run = modelcheck.replay_trace(str(artifact))
    assert run.violation is not None and "epoch-monotone" in run.violation


# --------------------------------------------- resource contracts (pass 6)

def test_fixture_release_on_one_path_only():
    path = os.path.join(FIXTURES, "fixture_release_one_path.py")
    with open(path) as f:
        findings, _used = resources.scan_leaks(f.read(), path)
    leaks = [f for f in findings if "not released on every path"
             in f.message]
    assert leaks, core.format_report(findings)
    assert leaks[0].line == _marker_line(path)
    assert len(leaks) == 1  # the all-paths control stays quiet


def test_fixture_raw_epoch_equality():
    path = os.path.join(FIXTURES, "fixture_epoch_eq.py")
    with open(path) as f:
        findings, _used = resources.scan_epoch_compares(f.read(), path)
    hits = [f for f in findings if "raw ==/!=" in f.message]
    assert hits, core.format_report(findings)
    assert hits[0].line == _marker_line(path)
    # one-hop taint: `known = table.get_epoch()` makes `known` epoch-
    # typed, so the later != is flagged too — and nothing else is
    assert hits[1].line == _marker_line(path, "seeded-taint")
    assert len(hits) == 2


def test_fixture_stale_pragma():
    """A pragma the lint no longer needs is itself a finding at the
    pragma's own line (dead pragmas claim hazards that are gone)."""
    path = os.path.join(FIXTURES, "fixture_stale_pragma.py")
    with open(path) as f:
        findings = concurrency.scan_source(f.read(), path)
    stale = [f for f in findings if "stale pragma" in f.message]
    assert stale, core.format_report(findings)
    assert stale[0].line == _marker_line(path)
    assert len(findings) == 1  # the live pragma on hot() doesn't exist


def test_leak_lint_structural_coverage():
    """All-paths analysis unit corners: try/finally release is clean;
    release in only the except arm is a leak; release before every
    return/raise is clean."""
    clean_finally = (
        "class C:\n"
        "    def f(self, ledger, n):\n"
        "        ledger.charge(0, n)\n"
        "        try:\n"
        "            work()\n"
        "        finally:\n"
        "            ledger.release(0, n)\n")
    findings, _ = resources.scan_leaks(clean_finally, "<mem>")
    assert not findings, core.format_report(findings)

    leak_except_only = (
        "class C:\n"
        "    def f(self, ledger, n):\n"
        "        ledger.charge(0, n)\n"
        "        try:\n"
        "            return work()\n"
        "        except Exception:\n"
        "            ledger.release(0, n)\n"
        "            raise\n")
    findings, _ = resources.scan_leaks(leak_except_only, "<mem>")
    assert len(findings) == 1 and findings[0].line == 3

    clean_both_arms = (
        "class C:\n"
        "    def f(self, ledger, n, ok):\n"
        "        ledger.charge(0, n)\n"
        "        if ok:\n"
        "            ledger.release(0, n)\n"
        "            return True\n"
        "        ledger.release(0, n)\n"
        "        return False\n")
    findings, _ = resources.scan_leaks(clean_both_arms, "<mem>")
    assert not findings, core.format_report(findings)


def test_epoch_lint_monotone_and_sentinel_allowed():
    src = ("EPOCH_DEAD = -1\n"
           "def f(epoch, prev_epoch):\n"
           "    if epoch == EPOCH_DEAD:\n"
           "        return None\n"
           "    if epoch <= prev_epoch:\n"
           "        return False\n"
           "    return True\n")
    findings, _ = resources.scan_epoch_compares(src, "<mem>")
    assert not findings, core.format_report(findings)
    src_eq = ("class M:\n"
              "    def __eq__(self, other):\n"
              "        return self.epoch == other.epoch\n")
    findings, _ = resources.scan_epoch_compares(src_eq, "<mem>")
    assert not findings, core.format_report(findings)


# ------------------------------------------------------------ CLI + gated

def test_cli_exit_code_plumbing(monkeypatch, capsys):
    """The CLI's exit-code/format contract, in-process — the full
    passes already ran once this session in
    test_live_tree_zero_findings; re-running them in a subprocess
    would only re-pay the fuzz + AST walks."""
    from sparkrdma_tpu.analysis import __main__ as cli

    monkeypatch.setattr(cli, "run_all", lambda: [])
    assert cli.main([]) == 0
    assert "clean (0 findings)" in capsys.readouterr().out
    boom = core.Finding("wire", "x.py", 3, "boom")
    monkeypatch.setattr(cli, "run_all", lambda: [boom])
    assert cli.main([]) == 1
    assert "x.py:3: [wire] boom" in capsys.readouterr().out


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("RUN_SANITIZERS") != "1",
                    reason="RUN_SANITIZERS=1 builds + runs the "
                           "ASan/UBSan native harness")
def test_native_sanitizer_harness():
    subprocess.run(["make", "-C", os.path.join(ROOT, "csrc"),
                    "asan", "ubsan"], check=True, timeout=600)
    asan_so = os.path.join(ROOT, "sparkrdma_tpu", "runtime",
                           "libtpushuffle_asan.so")
    ubsan_so = os.path.join(ROOT, "sparkrdma_tpu", "runtime",
                            "libtpushuffle_ubsan.so")
    libasan = subprocess.run(
        [os.environ.get("CXX", "g++"), "-print-file-name=libasan.so"],
        capture_output=True, text=True, check=True).stdout.strip()
    for so, extra_env in ((asan_so, {"LD_PRELOAD": libasan,
                                     "ASAN_OPTIONS": "detect_leaks=0"}),
                          (ubsan_so, {})):
        proc = subprocess.run(
            [sys.executable, "-m",
             "sparkrdma_tpu.analysis.native_harness", so],
            cwd=ROOT, capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu", **extra_env})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "all exercises passed" in proc.stdout
