"""Invariant analyzer suite (sparkrdma_tpu/analysis/): the tier-1 gate.

Three claims, each load-bearing:

1. the LIVE TREE is clean — every static pass (wire, concurrency,
   drift) runs over the real codebase and reports zero findings, so a
   drifted constant, an unguarded shared write, or a typo'd trace name
   fails the build here;
2. the analyzers actually DETECT — each seeded-violation fixture under
   tests/fixtures/analysis/ is caught by its pass with the right
   file:line (an analyzer that silently stopped seeing violations
   would otherwise "pass" forever);
3. the lockgraph shim records real acquisition orderings — a synthetic
   inversion is reported as a cycle, and a genuine multi-threaded
   shuffle (writers spilling, readers fetching over sockets) runs
   ACYCLIC under the shim with Condition semantics intact.

The sanitizer harness (pass 4) is exercised when RUN_SANITIZERS=1
(scripts/run_analysis.sh --sanitize); building instrumented .so's is
out of tier-1's budget.
"""

import importlib.util
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import sparkrdma_tpu.analysis as analysis
from sparkrdma_tpu.analysis import concurrency, core, drift, lockgraph, wire

ROOT = core.repo_root()
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "analysis")


def _load_fixture(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(FIXTURES, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # inspect needs it to resolve source files
    spec.loader.exec_module(mod)
    return mod


def _marker_line(path, marker="seeded-violation"):
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            if marker in line:
                return i
    raise AssertionError(f"no '{marker}' marker in {path}")


# ---------------------------------------------------------- the live gate

def test_live_tree_zero_findings():
    """THE gate: wire + concurrency + drift over the real tree."""
    findings = analysis.run_all()
    assert not findings, "\n" + core.format_report(findings)


def test_wire_registry_is_dense_and_unique():
    findings = wire.check_registry(wire.live_pairs())
    assert not findings, "\n" + core.format_report(findings)
    ids = [t for t, _ in wire.live_pairs()]
    assert len(ids) == len(set(ids))
    assert set(ids) | set(wire.rpc_msg.RESERVED_WIRE_IDS) == set(
        range(1, max(ids) + 1))


def test_wire_doc_table_matches_registry():
    assert not wire.check_doc_table()


def test_legacy_truncation_matrix():
    assert not wire.check_truncation()


def test_native_constant_lockstep():
    assert not wire.check_native_constants()


# ------------------------------------------------------- fixture detection

def test_fixture_duplicate_msg_id():
    mod = _load_fixture("fixture_dup_msg_id")
    findings = wire.check_registry(mod.FIXTURE_PAIRS,
                                   wire_ids=mod.FIXTURE_WIRE_IDS,
                                   reserved={})
    dups = [f for f in findings if "duplicate wire id 1" in f.message]
    assert dups, core.format_report(findings)
    path = os.path.join(FIXTURES, "fixture_dup_msg_id.py")
    assert dups[0].path.endswith("fixture_dup_msg_id.py")
    assert dups[0].line == _marker_line(path)


def test_fixture_asymmetric_roundtrip():
    mod = _load_fixture("fixture_asymmetric")
    findings = wire.fuzz_roundtrip(mod.FIXTURE_PAIRS)
    asym = [f for f in findings if "asymmetry" in f.message]
    assert asym, core.format_report(findings)
    path = os.path.join(FIXTURES, "fixture_asymmetric.py")
    assert asym[0].path.endswith("fixture_asymmetric.py")
    assert asym[0].line == _marker_line(path)


def test_fixture_unguarded_write():
    path = os.path.join(FIXTURES, "fixture_unguarded_write.py")
    with open(path) as f:
        findings = concurrency.scan_source(f.read(), path)
    hits = [f for f in findings if "_count" in f.message
            and "outside any 'with <lock>'" in f.message]
    assert hits, core.format_report(findings)
    assert hits[0].line == _marker_line(path)


def test_fixture_wait_without_loop_and_deadline():
    path = os.path.join(FIXTURES, "fixture_wait_no_loop.py")
    with open(path) as f:
        findings = concurrency.scan_source(f.read(), path)
    no_loop = [f for f in findings if "outside a 'while'" in f.message]
    no_deadline = [f for f in findings if "without a deadline" in f.message]
    assert no_loop and no_loop[0].line == _marker_line(path)
    assert no_deadline and no_deadline[0].line == _marker_line(
        path, "seeded-deadline")


def test_fixture_undocumented_and_ghost_key():
    py = os.path.join(FIXTURES, "fixture_undocumented_key.py")
    md = os.path.join(FIXTURES, "fixture_undocumented_key.md")
    with open(md) as f:
        doc_text = f.read()
    findings = drift.check_config_docs(
        drift._config_key_lines(py), py, doc_text, md)
    missing = [f for f in findings if "mystery_key" in f.message]
    stale = [f for f in findings if "ghost_key" in f.message]
    assert missing and missing[0].path == py
    assert missing[0].line == _marker_line(py)
    assert stale and stale[0].path == md
    assert stale[0].line == _marker_line(md)
    assert len(findings) == 2  # documented_key drifts neither way


# ----------------------------------------------------------- pragma rules

def test_bare_pragma_is_a_finding():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._x = 0\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._x = 1\n"
           "    def b(self):\n"
           "        self._x = 2  # analysis: unguarded-ok\n")
    findings = concurrency.scan_source(src, "<mem>")
    assert any(f.pass_name == "pragma" for f in findings)


def test_reasoned_pragma_suppresses():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._x = 0\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._x = 1\n"
           "    def b(self):\n"
           "        self._x = 2  # analysis: unguarded-ok(single-owner)\n")
    assert not concurrency.scan_source(src, "<mem>")


def test_locked_suffix_convention():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._x = 0\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._x = 1\n"
           "    def bump_locked(self):\n"
           "        self._x += 1\n")
    assert not concurrency.scan_source(src, "<mem>")


# -------------------------------------------------------------- lockgraph

def test_lockgraph_unit_cycle_detection():
    g = lockgraph.LockGraph()
    g._push("A", 1)
    g._note_acquire("B", 2)
    g._push("B", 2)
    g._pop("B", 2)
    g._pop("A", 1)
    g._push("B", 2)
    g._note_acquire("A", 1)  # inversion
    g._push("A", 1)
    cycles = g.cycles()
    assert len(cycles) == 1 and set(cycles[0]) == {"A", "B"}
    assert "A -> B" in g.format_cycles()


def test_lockgraph_same_site_pairs_excluded():
    g = lockgraph.LockGraph()
    g._push("A", 1)
    g._note_acquire("A", 2)  # second instance of the same role
    g._push("A", 2)
    assert not g.cycles() and not g.edges()


def test_lockgraph_reentrant_rlock_no_edge():
    g = lockgraph.LockGraph()
    g._push("A", 1)
    g._note_acquire("A", 1)  # reentrant re-acquire
    assert not g.edges()


def test_shuffle_e2e_under_lockgraph_is_acyclic(tmp_path):
    """The acceptance run: a real 2-executor shuffle — streaming
    writers with background spill, socket fetch, driver publishes —
    recorded by the shim, then checked for lock-order cycles. Also
    proves patched Condition/RLock semantics hold end to end (the
    shuffle byte-verifies its output)."""
    from sparkrdma_tpu.config import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import (PartitionerSpec,
                                               TpuShuffleManager)

    owned = lockgraph.current() is None
    graph = lockgraph.install()
    pre = {tuple(c) for c in graph.cycles()}  # session shim may own graph
    try:
        conf = TpuShuffleConf(connect_timeout_ms=5000,
                              shuffle_read_block_size="4k",
                              spill_threshold_bytes=4096)
        driver = TpuShuffleManager(conf, is_driver=True)
        execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                                   executor_id=str(i),
                                   spill_dir=str(tmp_path / f"e{i}"))
                 for i in range(2)]
        try:
            for ex in execs:
                ex.executor.wait_for_members(2)
            handle = driver.register_shuffle(
                91, 4, 6, PartitionerSpec("modulo"), row_payload_bytes=8)
            rng = np.random.default_rng(3)
            total_rows = 0
            for m in range(4):
                keys = rng.integers(0, 5000, size=800).astype(np.uint64)
                payload = rng.integers(0, 255, size=(800, 8)).astype(np.uint8)
                w = execs[m % 2].get_writer(handle, m)
                w.write_batch(keys, payload)
                w.close()
                total_rows += 800
            got = 0
            for i, ex in enumerate(execs):
                reader = ex.get_reader(handle, i * 3, (i + 1) * 3)
                k, _ = reader.read_all()
                got += len(k)
            assert got == total_rows
        finally:
            for ex in execs:
                ex.stop()
            driver.stop()
    finally:
        if owned:
            lockgraph.uninstall()
    assert graph.edges(), "shim recorded nothing — install() broken?"
    new = [c for c in graph.cycles() if tuple(c) not in pre]
    assert not new, graph.format_cycles()


# ------------------------------------------------------------ CLI + gated

def test_cli_exit_code_plumbing(monkeypatch, capsys):
    """The CLI's exit-code/format contract, in-process — the full
    passes already ran once this session in
    test_live_tree_zero_findings; re-running them in a subprocess
    would only re-pay the fuzz + AST walks."""
    from sparkrdma_tpu.analysis import __main__ as cli

    monkeypatch.setattr(cli, "run_all", lambda: [])
    assert cli.main([]) == 0
    assert "clean (0 findings)" in capsys.readouterr().out
    boom = core.Finding("wire", "x.py", 3, "boom")
    monkeypatch.setattr(cli, "run_all", lambda: [boom])
    assert cli.main([]) == 1
    assert "x.py:3: [wire] boom" in capsys.readouterr().out


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("RUN_SANITIZERS") != "1",
                    reason="RUN_SANITIZERS=1 builds + runs the "
                           "ASan/UBSan native harness")
def test_native_sanitizer_harness():
    subprocess.run(["make", "-C", os.path.join(ROOT, "csrc"),
                    "asan", "ubsan"], check=True, timeout=600)
    asan_so = os.path.join(ROOT, "sparkrdma_tpu", "runtime",
                           "libtpushuffle_asan.so")
    ubsan_so = os.path.join(ROOT, "sparkrdma_tpu", "runtime",
                            "libtpushuffle_ubsan.so")
    libasan = subprocess.run(
        [os.environ.get("CXX", "g++"), "-print-file-name=libasan.so"],
        capture_output=True, text=True, check=True).stdout.strip()
    for so, extra_env in ((asan_so, {"LD_PRELOAD": libasan,
                                     "ASAN_OPTIONS": "detect_leaks=0"}),
                          (ubsan_so, {})):
        proc = subprocess.run(
            [sys.executable, "-m",
             "sparkrdma_tpu.analysis.native_harness", so],
            cwd=ROOT, capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu", **extra_env})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "all exercises passed" in proc.stdout
