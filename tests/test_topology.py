"""Two-level (ICI/DCN) topology: the Topology model and its detection,
the generalized cost model (hierarchical plan kind, single-slice
degenerate parity), the factored hierarchical exchange (byte parity vs
the flat device plan and a host reference across uniform / zipfian /
slice-affine inputs, empty slices, per-slice degrade), the link-cost-
aware partition layout and planner placement, the
``mesh_rows_per_round`` deprecation latch, bench provenance, and the
topo microbench acceptance gates. Seed swept by
``scripts/run_topo_bench.sh`` via ``TOPO_SEED``."""

import os
import warnings

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from engine_helpers import u32_payload as _u32_payload
from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
from sparkrdma_tpu.parallel import device_plane as device_plane_mod
from sparkrdma_tpu.parallel import exchange as exchange_mod
from sparkrdma_tpu.parallel import topology as topology_mod
from sparkrdma_tpu.parallel.device_plane import (
    StageProfile,
    run_fused_exchange,
    run_hierarchical_exchange,
    select_dataplane,
)
from sparkrdma_tpu.parallel.topology import Topology, detect_topology
from sparkrdma_tpu.shuffle.manager import PartitionerSpec
from sparkrdma_tpu.shuffle.planner import (
    ReducePlanner,
    SizeHistogram,
    slice_aligned_partition_map,
)
from sparkrdma_tpu.shuffle.spark_compat import (
    ShuffleDependency,
    SparkCompatShuffleManager,
)
from sparkrdma_tpu.utils.trace import Tracer

SEED = int(os.environ.get("TOPO_SEED", "0"))
D = 8
TOPO = Topology((4, 4))


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


def _canon(rows: np.ndarray) -> bytes:
    """Canonical multiset bytes of one device/partition's rows."""
    return (rows[np.lexsort(rows.T[::-1])] if len(rows) else rows).tobytes()


def _make_rows(n_rows: int, dist: str, rng) -> np.ndarray:
    """u32[N, 3] device rows with packed-u64 keys under the named key
    distribution (uniform / zipfian / affine handled by callers)."""
    if dist == "zipfian":
        ranks = rng.zipf(1.3, size=n_rows).astype(np.uint64)
        keys = ranks * 2_654_435_761 % (1 << 40)
    else:
        keys = rng.integers(0, 1 << 40, n_rows, dtype=np.uint64)
    rows = np.zeros((n_rows, 3), np.uint32)
    rows[:, :2] = keys.view(np.uint32).reshape(n_rows, 2)
    rows[:, 2] = rng.integers(0, 1 << 32, n_rows, dtype=np.uint32)
    return rows


def _host_reference(rows, dest, n):
    """The host-plane oracle: group by destination device, key-sort."""
    out = []
    for d in range(n):
        sub = rows[dest == d]
        keys = sub[:, :2].copy().view(np.uint64).reshape(-1)
        out.append(sub[np.argsort(keys, kind="stable")])
    return out


# -- the topology model --------------------------------------------------

def test_topology_model_units():
    t = Topology((2, 4, 2), ici_gbps=100.0, dcn_gbps=10.0)
    assert t.num_slices == 3 and t.num_devices == 8 and not t.is_flat
    assert [t.slice_of(i) for i in range(8)] == [0, 0, 1, 1, 1, 1, 2, 2]
    np.testing.assert_array_equal(t.device_slices(),
                                  [0, 0, 1, 1, 1, 1, 2, 2])
    assert t.slice_bounds(1) == (2, 6)
    with pytest.raises(IndexError):
        t.slice_of(8)
    # uniform inter fraction: 1 - sum((|s|/D)^2)
    assert Topology((4, 4)).uniform_inter_fraction() == pytest.approx(0.5)
    assert Topology((8,)).uniform_inter_fraction() == 0.0
    # link cost: intra rides ICI, inter rides DCN
    gb = 1 << 30
    assert t.link_seconds(gb, 0) == pytest.approx(1 / 100.0)
    assert t.link_seconds(0, gb) == pytest.approx(1 / 10.0)
    # refine returns a re-anchored copy, original untouched
    r = t.refine(dcn_gbps=25.0)
    assert r.dcn_gbps == 25.0 and r.ici_gbps == 100.0
    assert t.dcn_gbps == 10.0
    d = t.describe()
    assert d["slices"] == 3 and d["devices_per_slice"] == [2, 4, 2]
    # degenerate: single slice is flat; every slot homes there
    flat = Topology((8,))
    assert flat.is_flat
    assert all(flat.slice_of_slot(s, 3) == 0 for s in range(3))
    # slot -> slice proportional mapping on the multi-slice shape
    assert [Topology((4, 4)).slice_of_slot(s, 4) for s in range(4)] == \
        [0, 0, 1, 1]


def test_detect_topology_and_spec_parsing(mesh):
    # auto on a single-process CPU mesh: every device shares a
    # process_index -> ONE slice, the degenerate pre-topology case
    auto = detect_topology(mesh)
    assert auto.is_flat and auto.num_devices == D
    # conf-driven virtual slicing (CI/bench shape)
    two = detect_topology(mesh, conf=TpuShuffleConf(slice_topology="2"))
    assert two.slice_sizes == (4, 4)
    explicit = detect_topology(
        mesh, conf=TpuShuffleConf(slice_topology="2,6", ici_gbps=80.0,
                                  dcn_gbps=8.0))
    assert explicit.slice_sizes == (2, 6)
    assert explicit.ici_gbps == 80.0 and explicit.dcn_gbps == 8.0
    # invalid specs log-and-default to auto (config contract): a count
    # that doesn't divide, sizes that don't sum, junk text
    for bad in ("3", "5,5", "0,8", "x,y", "-2"):
        assert detect_topology(
            mesh, conf=TpuShuffleConf(slice_topology=bad)).is_flat, bad
    # no mesh at all: empty degenerate topology
    assert detect_topology(None).is_flat
    # host_topology (bench provenance) never raises and sees the devices
    host = topology_mod.host_topology()
    assert host.num_devices == len(jax.devices())


# -- the generalized cost model ------------------------------------------

def test_select_dataplane_single_slice_bit_identical(mesh):
    """The degenerate topology must reproduce the flat selector's plans
    exactly — same plane, impl, rounds, reason."""
    flat = Topology((D,))
    for profile, budget in (
            (StageProfile(est_bytes=1 << 20, row_bytes=16), 64 << 20),
            (StageProfile(est_bytes=1 << 30, row_bytes=16), 1 << 20),
            (StageProfile(est_bytes=1 << 20, row_bytes=16), 1),
            (StageProfile(est_bytes=1, row_bytes=16, resident=False),
             64 << 20)):
        base = select_dataplane(mesh, "shuffle", profile,
                                hbm_budget=budget)
        topo = select_dataplane(mesh, "shuffle", profile,
                                hbm_budget=budget, topology=flat)
        assert topo == base


def test_select_dataplane_hierarchical_scoring(mesh):
    profile = StageProfile(est_bytes=1 << 20, row_bytes=16)
    plan = select_dataplane(mesh, "shuffle", profile, topology=TOPO)
    assert plan.plane == "hierarchical"
    assert plan.topology is TOPO
    assert "two-level" in plan.reason
    # the plan carries the RAW transport ask: "auto" must re-probe per
    # sub-mesh (the opcode a cross-slice mesh rejects may compile per
    # slice), never the global mesh's resolution
    assert plan.impl == "auto" and plan.rows_per_round == 0
    # a CHUNKED device plan keeps its streamed staging discipline: the
    # hierarchical runner's whole-stage host staging is one-shot-only
    big = StageProfile(est_bytes=1 << 30, row_bytes=16)
    chunked = select_dataplane(mesh, "shuffle", big, hbm_budget=1 << 20,
                               topology=TOPO)
    assert chunked.plane == "device" and chunked.rows_per_round > 0
    # no ICI:DCN gap -> the hierarchical plan buys nothing -> flat device
    even = Topology((4, 4), ici_gbps=10.0, dcn_gbps=10.0)
    assert select_dataplane(mesh, "shuffle", profile,
                            topology=even).plane == "device"
    # an explicit per-link byte decomposition overrides the uniform
    # estimate: zero inter bytes still beats all-DCN flat pricing
    skewed = StageProfile(est_bytes=1 << 20, row_bytes=16,
                          intra_bytes=1 << 20, inter_bytes=0)
    assert select_dataplane(mesh, "shuffle", skewed,
                            topology=TOPO).plane == "hierarchical"
    # overrides and non-device outcomes are untouched by topology
    assert select_dataplane(mesh, "shuffle", profile, override="host",
                            topology=TOPO).plane == "host"
    assert select_dataplane(None, "shuffle", profile,
                            topology=TOPO).plane == "host"
    assert select_dataplane(mesh, "shuffle", profile, hbm_budget=1,
                            topology=TOPO).plane == "host"


# -- the factored hierarchical exchange ----------------------------------

@pytest.mark.parametrize("dist", ["uniform", "zipfian"])
@pytest.mark.parametrize("sizes", [(4, 4), (2, 6)])
def test_hierarchical_vs_flat_vs_host_byte_parity(mesh, dist, sizes):
    """The parity matrix: hierarchical, flat-device, and host plans must
    serve byte-identical per-device results across input shapes and
    slice layouts."""
    topo = Topology(sizes)
    rng = np.random.default_rng(1000 * SEED + hash((dist, sizes)) % 997)
    rows = _make_rows(4000, dist, rng)
    keys = rows[:, :2].copy().view(np.uint64).reshape(-1)
    dest = (keys % D).astype(np.int32)
    home = rng.integers(0, topo.num_slices, len(rows)).astype(np.int32)

    before = topology_mod.cross_slice_snapshot()["bytes"]
    hier, _ = run_hierarchical_exchange(
        mesh, "shuffle", topo, rows, dest, home, key_words=2,
        out_factor=8, impl="gather")
    moved = topology_mod.cross_slice_snapshot()["bytes"] - before
    dev_slice = topo.device_slices()
    want_cross = int((dev_slice[dest] != home).sum()) * rows.shape[1] * 4
    assert moved == want_cross, "cross-slice tally != actual residue"

    flat, _ = run_fused_exchange(mesh, "shuffle", rows, dest, key_words=2,
                                 out_factor=8, impl="gather")
    host = _host_reference(rows, dest, D)
    for d in range(D):
        assert _canon(hier[d]) == _canon(flat[d]) == _canon(host[d]), \
            f"device {d} diverged under {dist}/{sizes}"
        # the per-device sort contract holds on the hierarchical plan
        k = hier[d][:, :2].copy().view(np.uint64).reshape(-1)
        assert (k[:-1] <= k[1:]).all()


def test_hierarchical_empty_slice_and_empty_input(mesh):
    """A slice that produces and receives nothing is simply idle — and
    the degenerate empty stage returns empty devices."""
    rng = np.random.default_rng(SEED + 3)
    rows = _make_rows(800, "uniform", rng)
    keys = rows[:, :2].copy().view(np.uint64).reshape(-1)
    dest = (keys % 4).astype(np.int32)  # devices 0-3 only: slice 1 idle
    home = np.zeros(len(rows), np.int32)
    before = topology_mod.cross_slice_snapshot()
    hier, _ = run_hierarchical_exchange(
        mesh, "shuffle", TOPO, rows, dest, home, key_words=2,
        out_factor=8, impl="gather")
    after = topology_mod.cross_slice_snapshot()
    assert after["bytes"] == before["bytes"], \
        "slice-local stage moved bytes across the seam"
    host = _host_reference(rows, dest, D)
    for d in range(D):
        assert _canon(hier[d]) == _canon(host[d])
    assert all(len(hier[d]) == 0 for d in range(4, 8))
    # fully empty input
    empty, rounds = run_hierarchical_exchange(
        mesh, "shuffle", TOPO, np.zeros((0, 3), np.uint32),
        np.zeros(0, np.int32), np.zeros(0, np.int32), impl="gather")
    assert rounds == 0 and all(len(e) == 0 for e in empty)


def test_slice_overflow_degrades_only_that_slice(mesh):
    """Skew that overflows ONE slice's receive headroom degrades only
    that slice's rows to host serving — byte-identically — while the
    other slice stays on the ICI collective."""
    rng = np.random.default_rng(SEED + 11)
    # slice 0: balanced intra traffic; slice 1: every row lands on
    # device 4 (4x the balanced share — past out_factor=2 headroom)
    r0 = _make_rows(2000, "uniform", rng)
    k0 = r0[:, :2].copy().view(np.uint64).reshape(-1)
    d0 = (k0 % 4).astype(np.int32)
    r1 = _make_rows(2000, "uniform", rng)
    d1 = np.full(len(r1), 4, np.int32)
    rows = np.concatenate([r0, r1])
    dest = np.concatenate([d0, d1])
    home = np.concatenate([np.zeros(len(r0), np.int32),
                           np.ones(len(r1), np.int32)])
    tracer = Tracer()
    before = exchange_mod.DATA_PLANE["exchanges"]
    hier, _ = run_hierarchical_exchange(
        mesh, "shuffle", TOPO, rows, dest, home, key_words=2,
        out_factor=2, impl="gather", tracer=tracer)
    assert exchange_mod.DATA_PLANE["exchanges"] - before >= 1, \
        "the healthy slice left the ICI collective too"
    degrades = [e for e in tracer._events
                if e["name"] == "exchange.degrade"]
    assert [e["args"]["slice"] for e in degrades] == [1]
    assert all(e["args"]["scope"] == "slice" for e in degrades)
    host = _host_reference(rows, dest, D)
    for d in range(D):
        assert _canon(hier[d]) == _canon(host[d]), f"device {d} diverged"


def test_hierarchical_budget_rounds_parity(mesh):
    """``rows_per_round`` bounds the per-slice ICI rounds (the budget
    auto-sizing's knob) without changing a byte."""
    rng = np.random.default_rng(SEED + 21)
    rows = _make_rows(3000, "uniform", rng)
    keys = rows[:, :2].copy().view(np.uint64).reshape(-1)
    dest = (keys % D).astype(np.int32)
    home = rng.integers(0, 2, len(rows)).astype(np.int32)
    one_shot, r1 = run_hierarchical_exchange(
        mesh, "shuffle", TOPO, rows, dest, home, key_words=2,
        out_factor=8, impl="gather")
    rounds, rn = run_hierarchical_exchange(
        mesh, "shuffle", TOPO, rows, dest, home, key_words=2,
        out_factor=8, impl="gather", rows_per_round=128)
    assert rn > r1
    for d in range(D):
        assert _canon(one_shot[d]) == _canon(rounds[d])


# -- engine end-to-end: the three planes agree ---------------------------

def _topo_cluster(tmp_path, **conf_kw):
    conf = TpuShuffleConf(connect_timeout_ms=1000,
                          max_connection_attempts=2, **conf_kw)
    driver = SparkCompatShuffleManager(conf, isDriver=True)
    execs = [SparkCompatShuffleManager(
        conf, driverAddr=driver.driverAddr, executorId=str(i),
        spill_dir=str(tmp_path / f"e{i}")) for i in range(3)]
    for ex in execs:
        ex.native.executor.wait_for_members(3)
    return driver, execs


def _engine_job(num_partitions, maps, rows, base_seed):
    def table(seed):
        rng = np.random.default_rng(seed)
        keys = rng.integers(0, 40000, size=rows).astype(np.uint64)
        vals = rng.integers(0, 1000, size=rows).astype(np.uint32)
        return keys, vals

    def map_fn(ctx, writer, task_id):
        keys, vals = table(base_seed + task_id)
        writer.write((keys, _u32_payload(vals)))

    def reduce_fn(ctx, task_id):
        keys, payload = ctx.read(0)._r.read_all()
        assert ((keys % num_partitions) == task_id).all()
        rows8 = np.concatenate(
            [keys.view(np.uint8).reshape(len(keys), 8), payload], axis=1)
        return _canon(rows8)

    stage = MapStage(maps, ShuffleDependency(
        num_partitions, PartitionerSpec("modulo"), row_payload_bytes=4),
        map_fn)
    return stage, reduce_fn


def test_engine_hierarchical_plane_end_to_end(tmp_path, mesh):
    """With a multi-slice ``slice_topology`` conf the cost model selects
    the HIERARCHICAL plan; its results are byte-identical to the forced
    flat-device and host planes, and the run actually crossed the seam
    (cross_slice_bytes) and rode ICI (collective tally)."""
    P, maps, rows = 4, 4, 500
    outs = {}
    for label, conf_kw, engine_kw in (
            ("hier", dict(slice_topology="2"), dict(mesh_impl="gather")),
            ("device", dict(hierarchical_exchange=False),
             dict(dataplane="device", mesh_impl="gather")),
            ("host", {}, dict(dataplane="host"))):
        driver, execs = _topo_cluster(tmp_path / label, **conf_kw)
        try:
            stage, reduce_fn = _engine_job(P, maps, rows, 9000 + SEED)
            cross0 = topology_mod.cross_slice_snapshot()["bytes"]
            moved0 = exchange_mod.DATA_PLANE["exchanges"]
            engine = DAGEngine(driver, execs, mesh=mesh, **engine_kw)
            outs[label] = engine.run(
                ResultStage(P, reduce_fn, parents=[stage]))
            cross = topology_mod.cross_slice_snapshot()["bytes"] - cross0
            moved = exchange_mod.DATA_PLANE["exchanges"] - moved0
            if label == "hier":
                assert cross > 0, "hierarchical run crossed no seam"
                assert moved > 0, "hierarchical run rode no collective"
            else:
                assert cross == 0, f"{label} plane tallied cross-slice"
        finally:
            for ex in execs:
                ex.stop()
            driver.stop()
    assert outs["hier"] == outs["device"] == outs["host"]


# -- link-cost-aware layout ----------------------------------------------

def test_slice_aligned_partition_map():
    # flat topology reproduces p % D bit-for-bit
    flat = slice_aligned_partition_map(np.zeros((1, 6), np.int64),
                                       Topology((4,)), 4)
    np.testing.assert_array_equal(flat, np.arange(6) % 4)
    # slice-affine histogram: every partition lands in its producing
    # slice, devices balanced within it
    topo = Topology((4, 4))
    hist = np.zeros((2, 16), np.int64)
    hist[0, :8] = 100
    hist[1, 8:] = 100
    pmap = slice_aligned_partition_map(hist, topo, 8)
    assert (pmap[:8] < 4).all() and (pmap[8:] >= 4).all()
    assert np.bincount(pmap, minlength=8).max() == 2  # balanced
    # one slice produced EVERYTHING: the balance cap forces a spill so
    # neither slice is starved (locality never recreates the straggler)
    solo = np.zeros((2, 16), np.int64)
    solo[0] = 100
    smap = slice_aligned_partition_map(solo, topo, 8)
    assert (smap < 4).any() and (smap >= 4).any()
    # determinism
    np.testing.assert_array_equal(
        pmap, slice_aligned_partition_map(hist, topo, 8))


def test_planner_link_cost_placement():
    """Multi-slice slot topology: placement minimizes the two-level
    link bill (consolidating same-slice bytes beats raw locality); the
    flat spec reproduces the byte-locality placement."""
    kw = dict(adaptive_plan=True, coalesce_target_bytes=0,
              split_threshold_bytes=1 << 30, locality_placement=True)
    hist = SizeHistogram(num_maps=3, num_partitions=1)
    hist.add(0, [40])
    hist.add(1, [30])
    hist.add(2, [30])
    owners = {0: 0, 1: 2, 2: 3}  # 40B on slot 0; 30B each on slots 2, 3
    live = [0, 1, 2, 3]
    flat_plan = ReducePlanner(TpuShuffleConf(**kw)).plan(
        1, hist, owners, live)
    # byte locality: slot 0 holds the single largest share
    assert flat_plan.tasks[0].placement == 0
    topo_plan = ReducePlanner(TpuShuffleConf(
        slice_topology="2", ici_gbps=100.0, dcn_gbps=10.0, **kw)).plan(
        1, hist, owners, live)
    # link cost: slots 2+3 share a slice — 60B at ICI beats 40B at ICI
    # with 60B crossing DCN, so the task consolidates into slice 1
    assert topo_plan.tasks[0].placement == 2
    # replan of an orphaned task follows the same link-cost scoring
    lost = ReducePlanner(TpuShuffleConf(
        slice_topology="2", ici_gbps=100.0, dcn_gbps=10.0, **kw)).replan(
        topo_plan, hist, owners, [0, 1, 3], completed_task_ids=[])
    assert lost.tasks[0].placement == 3  # same slice, next-best link bill


# -- satellites ----------------------------------------------------------

def test_mesh_rows_per_round_deprecation_warns_once():
    device_plane_mod._rows_knob_warned = False
    with pytest.warns(DeprecationWarning, match="mesh_rows_per_round"):
        device_plane_mod.warn_mesh_rows_deprecated()
    with warnings.catch_warnings(record=True) as later:
        warnings.simplefilter("always")
        device_plane_mod.warn_mesh_rows_deprecated()
    assert not later, "deprecation warning not latched once per process"
    # the conf key parses (mixed-version configs stay loadable) and
    # defaults to auto-sizing
    assert TpuShuffleConf().mesh_rows_per_round == 0
    assert TpuShuffleConf(mesh_rows_per_round=256).mesh_rows_per_round \
        == 256


def test_bench_round_provenance_records_topology():
    import bench as bench_mod

    detail = bench_mod._round_provenance({})
    assert len(detail["host_load_avg"]) == 3
    topo = detail["topology"]
    assert topo["slices"] >= 1
    assert sum(topo["devices_per_slice"]) == len(jax.devices())
    assert topo["ici_gbps"] > topo["dcn_gbps"] > 0


def test_topo_microbench_acceptance(mesh):
    """The ISSUE's acceptance gate: >= 1.5x vs the flat plan on a
    2-slice virtual cluster under the 10:1 ICI:DCN cost shim, byte-
    identical output, strictly fewer cross-slice bytes."""
    from sparkrdma_tpu.shuffle.topo_bench import run_topo_microbench
    from sparkrdma_tpu.utils.benchgate import gated_best_of

    res = gated_best_of(lambda: run_topo_microbench(seed=SEED))
    assert res["identical"], "plans exchanged different bytes"
    assert res["slices"] == 2
    assert res["cross_slice_bytes"]["hier"] < \
        res["cross_slice_bytes"]["flat"]
    assert res["speedup"] >= 1.5, res
