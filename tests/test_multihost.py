"""Multi-host integration: a real 2-process jax.distributed cluster (4 CPU
devices each) runs the TeraSort exchange over the 8-device GLOBAL mesh —
the process-boundary behaviors (global array assembly, cross-process
collectives over the Gloo/DCN path) that single-process tests can't reach.
"""

import os
import socket
import subprocess
import sys

_WORKER = r'''
import sys, numpy as np
pid, port = int(sys.argv[1]), sys.argv[2]
from sparkrdma_tpu.parallel.multihost import (
    init_multihost, global_mesh, run_multihost_terasort)
init_multihost(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
               local_device_count=4, platform="cpu")
import jax
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4
mesh = global_mesh("shuffle")
rows_per_device = 64
local_out, local_counts = run_multihost_terasort(
    mesh, "shuffle", rows_per_device, payload_words=2, seed=5)
# each local device shard must be internally sorted with the right count
per_dev = local_out.reshape(4, -1, 3)
cnts = local_counts.reshape(4, -1)
for d in range(4):
    total = int(cnts[d].sum())
    keys = per_dev[d][:total, 0].astype(np.int64)
    assert (np.diff(keys) >= 0).all(), f"proc {pid} dev {d} unsorted"
# global row conservation across both processes
total_here = int(cnts.sum())
print(f"MULTIHOST_OK {pid} rows={total_here}", flush=True)
'''


def test_two_process_global_mesh_terasort(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, "-c", _WORKER, str(i), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         env=env, cwd=str(tmp_path))
        for i in range(2)
    ]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outputs.append(out.decode())
    for i, out in enumerate(outputs):
        assert f"MULTIHOST_OK {i}" in out, f"proc {i} failed:\n{out[-2000:]}"
    # global conservation: the two processes' rows sum to the full dataset
    rows = sum(int(out.split("rows=")[1].split()[0]) for out in outputs)
    assert rows == 8 * 64
