"""Multi-host integration: a real 2-process jax.distributed cluster (4 CPU
devices each) runs the TeraSort exchange over the 8-device GLOBAL mesh —
the process-boundary behaviors (global array assembly, cross-process
collectives over the Gloo/DCN path) that single-process tests can't reach.
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

# every test here runs a real two-process jax.distributed CPU mesh;
# XLA:CPU only learned multiprocess computations in jax 0.5
pytestmark = pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="jax<0.5 XLA:CPU cannot run multiprocess computations")

_WORKER = r'''
import sys, numpy as np
pid, port = int(sys.argv[1]), sys.argv[2]
from sparkrdma_tpu.parallel.multihost import (
    init_multihost, global_mesh, run_multihost_terasort)
init_multihost(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
               local_device_count=4, platform="cpu")
import jax
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4
mesh = global_mesh("shuffle")
rows_per_device = 64
local_out, local_counts = run_multihost_terasort(
    mesh, "shuffle", rows_per_device, payload_words=2, seed=5)
# each local device shard must be internally sorted with the right count
per_dev = local_out.reshape(4, -1, 3)
cnts = local_counts.reshape(4, -1)
for d in range(4):
    total = int(cnts[d].sum())
    keys = per_dev[d][:total, 0].astype(np.int64)
    assert (np.diff(keys) >= 0).all(), f"proc {pid} dev {d} unsorted"
# global row conservation across both processes
total_here = int(cnts.sum())
print(f"MULTIHOST_OK {pid} rows={total_here}", flush=True)
'''


_REDUCE_WORKER = r'''
import pathlib, sys, tempfile, time
import numpy as np

pid, port = int(sys.argv[1]), sys.argv[2]
from sparkrdma_tpu.parallel.multihost import (
    global_mesh, init_multihost, run_multihost_mesh_reduce)
init_multihost(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
               local_device_count=4, platform="cpu")
import jax
from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import (
    PartitionerSpec, ShuffleHandle, TpuShuffleManager)

conf = TpuShuffleConf(connect_timeout_ms=5000)
PARTS, MAPS, ROWS, W = 16, 4, 2000, 8
addr_file = pathlib.Path("driver_addr.txt")
driver = None
if pid == 0:
    driver = TpuShuffleManager(conf, is_driver=True)
    handle = driver.register_shuffle(7, MAPS, PARTS,
                                     PartitionerSpec("modulo"),
                                     row_payload_bytes=W)
    # atomic publish: write-then-rename so the poller never reads a
    # half-written address
    tmp = addr_file.with_suffix(".tmp")
    tmp.write_text("%s:%d" % driver.driver_addr)
    tmp.replace(addr_file)
    driver_addr = driver.driver_addr
else:
    # the handle is a value object; both processes construct it identically
    handle = ShuffleHandle(7, MAPS, PARTS, W, PartitionerSpec("modulo"))
    deadline = time.monotonic() + 30
    while not addr_file.exists():
        assert time.monotonic() < deadline, "driver address never appeared"
        time.sleep(0.05)
    h, p = addr_file.read_text().split(":")
    driver_addr = (h, int(p))

mgr = TpuShuffleManager(conf, driver_addr=driver_addr,
                        executor_id=f"h{pid}",
                        spill_dir=tempfile.mkdtemp())
mgr.executor.wait_for_members(2)

def table(m):
    rng = np.random.default_rng(1000 + m)
    return (rng.integers(0, 100000, ROWS).astype(np.uint64),
            rng.integers(0, 255, (ROWS, W)).astype(np.uint8))

# SPI writes: maps 0,1 on host 0; maps 2,3 on host 1
for m in ((0, 1) if pid == 0 else (2, 3)):
    w = mgr.get_writer(handle, m)
    w.write_batch(*table(m))
    w.close()

mesh = global_mesh("shuffle")
results = run_multihost_mesh_reduce([mgr], handle, mesh)

# the 2-process cluster IS a 2-slice topology (per-host seams): the
# reduce must have tallied its cross-host bytes on the DCN metric
from sparkrdma_tpu.parallel import topology as topo_mod
assert not topo_mod.detect_topology(mesh).is_flat, "seams undetected"
assert topo_mod.CROSS_SLICE["bytes"] > 0, "per-host seam traffic untallied"

# verify OUR devices against the deterministic global truth
tk = np.concatenate([table(m)[0] for m in range(MAPS)])
tp = np.concatenate([table(m)[1] for m in range(MAPS)])
owner_dev = (tk % PARTS % 8).astype(np.int64)

def canon(k, p):
    rows = np.concatenate(
        [np.ascontiguousarray(k)[:, None].view(np.uint8).reshape(len(k), 8),
         p], axis=1)
    return rows[np.lexsort(rows.T[::-1])]

local_devs = [i for i, d in enumerate(mesh.devices.flat)
              if d.process_index == jax.process_index()]
got_rows = 0
for (k, p, parts), dev in zip(results, local_devs):
    assert (parts % 8 == dev).all()
    assert (np.diff(k.astype(np.int64)) >= 0).all(), "not key-sorted"
    mask = owner_dev == dev
    assert np.array_equal(canon(k, p), canon(tk[mask], tp[mask])), \
        f"device {dev} mismatch"
    got_rows += len(k)

# streamed rounds (rows_per_round bounds device memory; cap=1000 here, so
# 64/round = 16 collective rounds) must produce identical results
streamed = run_multihost_mesh_reduce([mgr], handle, mesh, rows_per_round=64)
for (k1, p1, pa1), (k2, p2, pa2) in zip(results, streamed):
    assert np.array_equal(canon(k1, p1), canon(k2, p2)), "streamed mismatch"
    assert np.array_equal(np.sort(pa1), np.sort(pa2))

from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("done")  # driver outlives readers
print(f"MESHREDUCE_OK {pid} rows={got_rows}", flush=True)
mgr.stop()
if driver is not None:
    driver.stop()
'''


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_two_process(worker: str, tmp_path, ok_marker: str):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, "-c", worker, str(i), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         env=env, cwd=str(tmp_path))
        for i in range(2)
    ]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outputs.append(out.decode())
    for i, out in enumerate(outputs):
        assert f"{ok_marker} {i}" in out, f"proc {i} failed:\n{out[-2000:]}"
    return outputs


def test_two_process_spi_mesh_reduce(tmp_path):
    """The reference's multi-node pipeline end-to-end (README.md:11-31):
    spills committed through the SPI on TWO processes feed ONE global-mesh
    exchange; every device's reduce output is exact vs. the global truth."""
    outputs = _run_two_process(_REDUCE_WORKER, tmp_path, "MESHREDUCE_OK")
    rows = sum(int(out.split("rows=")[1].split()[0]) for out in outputs)
    assert rows == 4 * 2000  # global conservation: every written row landed


def test_two_process_global_mesh_terasort(tmp_path):
    outputs = _run_two_process(_WORKER, tmp_path, "MULTIHOST_OK")
    # global conservation: the two processes' rows sum to the full dataset
    rows = sum(int(out.split("rows=")[1].split()[0]) for out in outputs)
    assert rows == 8 * 64
