"""Buffer-pool semantics tests (reference: java/RdmaBufferManager.java
size-rounding 147-161, preallocation 124-135, LRU trim 169-211, stats
217-231; java/RdmaRegisteredBuffer.java refcounting 28-87).

Every test runs against both backends: the C++ arena and the pure-Python
fallback.
"""

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.runtime import native
from sparkrdma_tpu.runtime.pool import BufferPool

BACKENDS = ["python"] + (["native"] if native.available() else [])


@pytest.fixture(params=BACKENDS)
def pool(request):
    conf = TpuShuffleConf(use_cpp_runtime=(request.param == "native"),
                          min_block_size="1k", max_buffer_allocation_size="1m")
    p = BufferPool(conf)
    assert p.is_native == (request.param == "native")
    yield p
    p.stop()


def test_native_lib_builds():
    assert native.available(), "C++ shim should be built (make -C csrc)"


def test_size_rounding(pool):
    b = pool.get(100)
    assert b.size == 1024  # rounds up to min block
    b2 = pool.get(1500)
    assert b2.size == 2048  # next pow2 bin
    b.free(), b2.free()


def test_reuse_same_bin(pool):
    b = pool.get(4000)
    tok = b.token
    b.view[:10] = 7
    b.free()
    b2 = pool.get(3000)  # same 4k bin -> recycled buffer
    assert b2.token == tok
    b2.free()


def test_write_through_view(pool):
    b = pool.get(1024)
    b.view[:] = np.arange(b.size, dtype=np.uint8) % 251
    assert b.view[250] == 250 % 251
    b.free()


def test_double_free_safe(pool):
    b = pool.get(64)
    b.free()
    b.free()  # idempotent


def test_preallocate_counts(pool):
    before = pool.total_bytes
    pool.preallocate(2048, 8)
    assert pool.total_bytes == before + 8 * 2048
    assert pool.idle_bytes >= 8 * 2048
    # gets should consume preallocated buffers without fresh allocs
    bufs = [pool.get(2048) for _ in range(8)]
    stats = pool.stats()
    bin2k = next(b for b in stats["bins"] if b["size"] == 2048)
    assert bin2k["fresh"] == 0
    for b in bufs:
        b.free()


def test_lru_trim_on_idle_watermark(pool):
    # budget is 1m; idle > 90% triggers trim down to 65%
    bufs = [pool.get(128 * 1024) for _ in range(8)]  # 1 MiB live
    for b in bufs:
        b.free()
    assert pool.idle_bytes <= (1 << 20) * 65 // 100
    stats = pool.stats()
    assert any(b["trimmed"] > 0 for b in stats["bins"])


def test_explicit_trim(pool):
    b = pool.get(64 * 1024)
    b.free()
    assert pool.idle_bytes > 0
    pool.trim(0)
    assert pool.idle_bytes == 0


def test_registered_buffer_refcount(pool):
    reg = pool.get_registered(8192)
    v1 = reg.slice(100)
    v2 = reg.slice(200)
    v1[:] = 1
    v2[:] = 2
    # distinct, adjacent views
    assert v1.sum() == 100 and v2.sum() == 400
    tok = reg.token
    reg.release()  # creator ref
    # still held by the two slices
    reg.release()
    reg.release()
    # after last release, the bin should hand the same token back
    b = pool.get(8192)
    assert b.token == tok
    b.free()


def test_registered_buffer_exhaustion(pool):
    reg = pool.get_registered(1024)
    reg.slice(1000)
    with pytest.raises(ValueError):
        reg.slice(500)
    reg.release()
    reg.release()


def test_stats_shape(pool):
    b = pool.get(512)
    b.free()
    s = pool.stats()
    assert {"total_bytes", "idle_bytes", "bins"} <= set(s)
    assert s["bins"][0]["gets"] >= 1


def test_prealloc_from_conf():
    conf = TpuShuffleConf(min_block_size="1k", prealloc_buffers="1k:4,2k:2")
    p = BufferPool(conf)
    assert p.idle_bytes == 4 * 1024 + 2 * 2048
    p.stop()


def test_free_after_stop_is_inert():
    conf = TpuShuffleConf(min_block_size="1k")
    p = BufferPool(conf)
    b = p.get(1024)
    p.stop()
    b.free()  # must not raise even though the arena is gone
    p.stop()  # double-stop inert too
