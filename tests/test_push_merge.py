"""Push-merge dataplane tests (shuffle/push_merge.py).

Units (target assignment, ledger fencing, directory round-trips), the
end-to-end merged-vs-scattered byte-parity matrix (full and PARTIAL
coverage, split-task bypass, warm directory caching), the tiered-spill
ENOSPC overflow, and the merged-read microbench acceptance gates.
``MERGE_SEED`` varies the generated data for scripts/run_merge_bench.sh
seed sweeps.
"""

import os
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.faults import ENOSPC, StorageFaultInjector
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.push_merge import (
    MergedDirectory,
    MergedEntry,
    MergeStore,
    bitmap_get,
    bitmap_members,
    bitmap_new,
    bitmap_set,
    merge_targets,
    wait_for_coverage,
)
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver

SEED = int(os.environ.get("MERGE_SEED", "0"))


# -- units ----------------------------------------------------------------


def test_merge_targets_contiguous_deterministic_and_self_excluding():
    targets = merge_targets(8, [0, 1, 2], my_slot=0, replicas=2)
    assert targets == merge_targets(8, [0, 1, 2], 0, 2)  # deterministic
    assert 0 not in targets  # never targets the pusher itself
    # each replica index covers every partition exactly once
    for r in range(2):
        covered = []
        for slot, ranges in targets.items():
            for lo, hi in ranges:
                covered.extend(range(lo, hi))
        # both replicas together cover each partition exactly twice
    counts = np.zeros(8, dtype=int)
    for ranges in targets.values():
        for lo, hi in ranges:
            counts[lo:hi] += 1
    assert (counts == 2).all(), counts
    # ranges are contiguous and sorted per slot
    for ranges in targets.values():
        assert all(lo < hi for lo, hi in ranges)
    # K clamps to the candidate count; replicas=0 disables
    assert not merge_targets(8, [0, 1], 0, 0)
    t1 = merge_targets(4, [0, 1], 0, 5)
    assert set(t1) == {1}
    # single-executor degenerate case still pushes somewhere
    assert merge_targets(4, [0], 0, 1) == {0: [(0, 4)]}


def test_bitmap_roundtrip():
    b = bitmap_new(12)
    for m in (0, 3, 11):
        bitmap_set(b, m)
    assert bitmap_members(bytes(b), 12) == [0, 3, 11]
    assert bitmap_get(bytes(b), 3) and not bitmap_get(bytes(b), 4)
    assert not bitmap_get(b"", 5)  # short bitmap reads as uncovered


def test_merged_directory_roundtrip_and_pruning():
    d = MergedDirectory()
    cov_a = bitmap_new(6)
    bitmap_set(cov_a, 1)
    bitmap_set(cov_a, 2)
    cov_b = bitmap_new(6)
    bitmap_set(cov_b, 1)
    d.apply(MergedEntry(0, 1, 10, 100, 0xAB, bytes(cov_a), [(0, 100)]))
    d.apply(MergedEntry(0, 2, 11, 50, 0xCD, bytes(cov_b), [(0, 50)]))
    d.apply(MergedEntry(3, 2, 12, 70, 0xEF, bytes(cov_a), [(0, 40),
                                                           (50, 30)]))
    # widest coverage first, slot tie-break
    assert [e.slot for e in d.entries(0)] == [1, 2]
    # wire round trip
    d2 = MergedDirectory.from_bytes(d.to_bytes())
    assert len(d2) == 3
    e = d2.entries(3)[0]
    assert (e.slot, e.token, e.nbytes, e.crc32) == (2, 12, 70, 0xEF)
    assert e.ranges == ((0, 40), (50, 30))
    assert e.covered_maps(6) == [1, 2]
    # repair publish for map 2 drops every entry covering it
    assert d.drop_map(2) == 2
    assert [e.slot for e in d.entries(0)] == [2]
    # tombstone drops the slot's entries
    assert d.drop_slot(2) == 1
    assert d.entries(0) == [] and d.partitions() == []
    assert MergedDirectory.from_bytes(b"").partitions() == []


def test_merge_store_ledger_fencing_and_finalize(tmp_path):
    conf = TpuShuffleConf(use_cpp_runtime=False)
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"), conf=conf)
    store = MergeStore(resolver, conf)
    try:
        status, acc = store.push(1, 0, fence=5, start_partition=0,
                                 sizes=[3, 2], data=b"abcde")
        assert (status, acc) == (0, b"\x01\x01")
        # duplicate / stale-fence pushes are rejected per partition
        status, acc = store.push(1, 0, fence=4, start_partition=0,
                                 sizes=[3, 2], data=b"XXXYY")
        assert acc == b"\x00\x00"
        # a NEWER fence supersedes: old bytes excluded from the final
        # ranges, the newest attempt's bytes serve
        status, acc = store.push(1, 0, fence=7, start_partition=0,
                                 sizes=[3, 2], data=b"ABCDE")
        assert acc == b"\x01\x01"
        # second map rides partition 1 only
        status, acc = store.push(1, 1, fence=2, start_partition=1,
                                 sizes=[4], data=b"wxyz")
        assert acc == b"\x01"
        published = []
        count = store.finalize(1, exec_index=2, publish=published.append)
        assert count == 2 and len(published) == 2
        by_part = {m.partition_id: m for m in published}
        p0 = by_part[0]
        assert p0.exec_index == 2
        assert bitmap_members(p0.covered, 6) == [0]
        # ledger file holds "abc" + "ABC"; only the fence-7 range serves
        assert p0.ranges == [(3, 3)] and p0.nbytes == 3
        import zlib
        assert p0.crc32 == zlib.crc32(b"ABC")
        assert resolver.read_block(1, p0.token, 3, 3) == b"ABC"
        p1 = by_part[1]
        assert sorted(bitmap_members(p1.covered, 6)) == [0, 1]
        # partition 1 ledger: "de" (fence 5, superseded) + "DE" (fence
        # 7) + "wxyz" — the adjacent surviving rows coalesce into ONE
        # range and the superseded prefix is excluded
        assert p1.ranges == [(2, 6)] and p1.nbytes == 6
        assert resolver.read_block(1, p1.token, 2, 6) == b"DEwxyz"
        # finalize is idempotent; later pushes answer FINALIZED
        assert store.finalize(1, 2, published.append) == 0
        from sparkrdma_tpu.parallel import messages as M
        status, acc = store.push(1, 3, fence=1, start_partition=0,
                                 sizes=[1], data=b"z")
        assert status == M.STATUS_FINALIZED and acc == b"\x00"
        # segment cap: a push that would grow a PER-PARTITION segment
        # past the cap is rejected for exactly that partition
        store.max_segment = 4
        status, acc = store.push(2, 0, fence=1, start_partition=0,
                                 sizes=[3, 3], data=b"aaabbb")
        assert acc == b"\x01\x01"  # both segments fit 3 <= 4
        status, acc = store.push(2, 1, fence=1, start_partition=0,
                                 sizes=[3, 1], data=b"cccd")
        assert acc == b"\x00\x01"  # p0 would hit 6 > 4; p1 fits 4 <= 4
        store.drop_shuffle(1)
        store.drop_shuffle(2)
        assert not list((tmp_path / "s" / "merge").glob("seg_*"))
        # the modelcheck finalize_vs_push fix: a push racing the
        # unregister broadcast lands AFTER drop_shuffle — it must be
        # refused (FINALIZED), not re-create state and charge disk
        # bytes nothing will ever release
        status, acc = store.push(1, 4, fence=9, start_partition=0,
                                 sizes=[2], data=b"zz")
        assert status == M.STATUS_FINALIZED and acc == b"\x00"
        status, token = store.push_overflow(1, 4, 9, b"blob")
        assert status == M.STATUS_FINALIZED and token == 0
        assert resolver.disk_ledger.usage(0) == 0
        assert not list((tmp_path / "s" / "merge").glob("seg_1_*"))
        # a pushed registration signal re-arms the reused id
        store.note_registered(1)
        status, acc = store.push(1, 0, fence=1, start_partition=0,
                                 sizes=[2], data=b"ok")
        assert (status, acc) == (M.STATUS_OK, b"\x01")
        store.drop_shuffle(1)
        # push_overflow's drop window: the unregister lands BETWEEN the
        # entry check and the final record (blob written + registered
        # outside the lock) — the call must unwind its charge, its
        # external registration, and the blob, not park zombie bytes
        orig_register = resolver.register_external

        def register_then_drop(sid, path, length, **kw):
            token = orig_register(sid, path, length, **kw)
            store.drop_shuffle(sid)  # the broadcast wins the window
            return token
        resolver.register_external = register_then_drop
        try:
            status, token = store.push_overflow(5, 0, 1, b"blob")
        finally:
            resolver.register_external = orig_register
        assert status == M.STATUS_FINALIZED and token == 0
        assert resolver.disk_ledger.usage(0) == 0
        assert not list((tmp_path / "s" / "merge").glob("ovf_5_*"))
    finally:
        store.stop()
        resolver.stop()


# -- e2e cluster matrix ---------------------------------------------------


def _cluster(tmp_path, n=3, **kw):
    base = dict(connect_timeout_ms=10000, use_cpp_runtime=False,
                retry_backoff_base_ms=10, retry_backoff_cap_ms=80,
                push_merge=True, merge_replicas=1, push_deadline_ms=8000)
    base.update(kw)
    conf = TpuShuffleConf(**base)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs, conf


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _write_maps(driver, execs, num_maps=6, num_partitions=4, rows=400,
                payload_w=0, shuffle_id=1):
    handle = driver.register_shuffle(
        shuffle_id, num_maps, num_partitions, PartitionerSpec("modulo"),
        row_payload_bytes=payload_w)
    for m in range(num_maps):
        w = execs[m % len(execs)].get_writer(handle, m)
        rng = np.random.default_rng(SEED * 1000 + m)
        keys = rng.integers(0, 5000, rows).astype(np.uint64)
        payload = (rng.integers(0, 255, (rows, payload_w), dtype=np.uint64)
                   .astype(np.uint8) if payload_w else None)
        w.write_batch(keys, payload)
        w.close()
    return handle


def _ready(driver, execs, handle, timeout=15):
    for ex in execs:
        assert ex.pusher.drain(timeout)
    assert wait_for_coverage(driver.driver, handle.shuffle_id,
                             handle.num_maps, handle.num_partitions,
                             timeout=timeout)


def _sorted_keys(reader):
    keys, _ = reader.read_all()
    return np.sort(keys)


def test_e2e_merged_read_byte_parity_and_accounting(tmp_path):
    driver, execs, conf = _cluster(tmp_path, merge_replicas=2)
    try:
        handle = _write_maps(driver, execs)
        _ready(driver, execs, handle)
        # merged-first read
        merged_reader = execs[0].get_reader(handle, 0, 4)
        merged = _sorted_keys(merged_reader)
        m = merged_reader.metrics
        assert m.merged_reads == 4, m  # ONE wide read per partition
        assert m.merged_fallbacks == 0 and m.failed_fetches == 0, m
        # scattered (per-map) read of the same shuffle, same executor
        scat_reader = TpuShuffleReader(
            execs[0].executor, execs[0].resolver,
            TpuShuffleConf(**dict(conf.to_dict(), push_merge=False)),
            handle.shuffle_id, handle.num_maps, 0, 4, 0)
        scattered = _sorted_keys(scat_reader)
        np.testing.assert_array_equal(merged, scattered,
                                      err_msg=f"seed={SEED}")
        assert scat_reader.metrics.merged_reads == 0
        # every (map, partition) served exactly once: the byte totals
        # agree (merged bytes ALSO count as local/remote per hosting
        # slot, so the comparable total is local + remote)
        assert (m.remote_bytes + m.local_bytes
                == scat_reader.metrics.remote_bytes
                + scat_reader.metrics.local_bytes)
    finally:
        _shutdown(driver, execs)


@pytest.mark.parametrize("coalesce", [True, False])
def test_e2e_partial_coverage_mixes_merged_and_per_map(tmp_path, coalesce):
    """A tiny merge_segment_max_bytes rejects part of the push stream:
    partitions end up PARTIALLY covered and the reducer mixes merged
    reads with per-map fetches of the stragglers (skip-set sealing on
    both dataplanes) — byte-identical either way."""
    driver, execs, conf = _cluster(
        tmp_path, merge_replicas=1, coalesce_reads=coalesce,
        merge_segment_max_bytes=1 << 16)
    try:
        # 64B rows, 500 rows/map over 4 partitions = ~8000B per (map,
        # partition); 16 maps want ~128 KiB per partition — only ~half
        # fit the 64 KiB segment cap, the rest are rejected
        handle = _write_maps(driver, execs, num_maps=16, rows=500,
                             payload_w=56)
        for ex in execs:
            assert ex.pusher.drain(20)
        driver.driver.finalize_merge(handle.shuffle_id)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            d = driver.driver.merged_directory(handle.shuffle_id)
            if d is not None and len(d.partitions()) == 4:
                break
            time.sleep(0.02)
        store_snaps = [ex.executor.merge_store.snapshot() for ex in execs]
        assert any(s["pushes_rejected"] for s in store_snaps), store_snaps
        reader = execs[0].get_reader(handle, 0, 4)
        merged = _sorted_keys(reader)
        m = reader.metrics
        assert m.merged_reads >= 1, m
        # stragglers went per-map (remote or local short-circuit runs)
        assert m.remote_fetches + m.local_fetches >= 1, m
        scat = TpuShuffleReader(
            execs[0].executor, execs[0].resolver,
            TpuShuffleConf(**dict(conf.to_dict(), push_merge=False)),
            handle.shuffle_id, handle.num_maps, 0, 4, 56)
        np.testing.assert_array_equal(merged, _sorted_keys(scat),
                                      err_msg=f"seed={SEED}")
    finally:
        _shutdown(driver, execs)


def test_e2e_split_map_range_bypasses_merged(tmp_path):
    """A map-range-SPLIT reader (adaptive planner's split tasks) cannot
    slice a merged segment to its map subset — it bypasses merged
    resolution entirely and stays byte-correct."""
    driver, execs, _conf = _cluster(tmp_path)
    try:
        handle = _write_maps(driver, execs)
        _ready(driver, execs, handle)
        lo, hi = 1, 4
        reader = execs[0].get_reader(handle, 0, 4, map_range=(lo, hi))
        keys = _sorted_keys(reader)
        assert reader.metrics.merged_reads == 0
        expected = np.sort(np.concatenate(
            [np.random.default_rng(SEED * 1000 + m).integers(0, 5000, 400)
             for m in range(lo, hi)]).astype(np.uint64))
        np.testing.assert_array_equal(keys, expected,
                                      err_msg=f"seed={SEED}")
    finally:
        _shutdown(driver, execs)


def test_e2e_warm_directory_serves_second_read_with_zero_metadata_rpcs(
        tmp_path):
    driver, execs, _conf = _cluster(tmp_path)
    try:
        handle = _write_maps(driver, execs)
        _ready(driver, execs, handle)
        r1 = execs[0].get_reader(handle, 0, 4)
        first = _sorted_keys(r1)
        assert r1.metrics.metadata_rpcs_per_stage >= 1
        r2 = execs[0].get_reader(handle, 0, 4)
        second = _sorted_keys(r2)
        np.testing.assert_array_equal(first, second)
        # table AND merged directory served from the epoch-validated
        # cache: the warm stage touches the wire only for data
        assert r2.metrics.metadata_rpcs_per_stage == 0, r2.metrics
        assert r2.metrics.merged_reads == 4
    finally:
        _shutdown(driver, execs)


def test_e2e_epoch_bump_invalidates_cached_directory(tmp_path):
    from sparkrdma_tpu.parallel import messages as M

    driver, execs, _conf = _cluster(tmp_path)
    try:
        handle = _write_maps(driver, execs)
        _ready(driver, execs, handle)
        r1 = execs[0].get_reader(handle, 0, 4)
        _sorted_keys(r1)
        plane = execs[0].executor.location_plane
        assert plane.snapshot()["merged"] == 1
        epoch = driver.driver.epoch_of(handle.shuffle_id)
        plane.note_epoch(handle.shuffle_id, epoch + 1)
        assert plane.merged(handle.shuffle_id) is None
        plane.note_epoch(handle.shuffle_id, M.EPOCH_DEAD)
        assert plane.snapshot()["merged"] == 0
    finally:
        _shutdown(driver, execs)


# -- tiered-spill ENOSPC overflow -----------------------------------------


def test_overflow_spill_survives_total_enospc(tmp_path):
    """Every local spill write fails with ENOSPC past the retry budget:
    the spill overflows to a merge peer, the attempt COMMITS (merge
    fetches the blob back), and the output is byte-identical to a
    fault-free run — the failure that used to cost a WriteFailedError
    now costs a round trip."""
    driver, execs, _conf = _cluster(
        tmp_path, n=2, spill_threshold_bytes=0, spill_retry_budget=1,
        merge_replicas=1)
    injector = StorageFaultInjector(seed=SEED)
    injector.install()
    try:
        handle = driver.register_shuffle(5, 1, 4,
                                         PartitionerSpec("modulo"))
        injector.add(ENOSPC, op="spill_write",
                     path_substr=str(tmp_path / "e0") + "/")
        w = execs[0].get_writer(handle, 0)
        rng = np.random.default_rng(SEED)
        keys = rng.integers(0, 5000, 600).astype(np.uint64)
        w.write_batch(keys[:300])
        w.write_batch(keys[300:])
        result = w.close()  # would raise WriteFailedError without overflow
        assert result is not None
        assert injector.fired_count(ENOSPC) >= 2
        wm = w.write_metrics.snapshot()
        assert wm["remote_spills"] >= 1, wm
        assert execs[0].merge_client.overflow_spills >= 1
        reader = execs[1].get_reader(handle, 0, 4)
        got = _sorted_keys(reader)
        np.testing.assert_array_equal(got, np.sort(keys),
                                      err_msg=f"seed={SEED}")
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


# -- microbench acceptance (the merged_read_speedup secondary's gates) ----


def test_merged_read_microbench_acceptance(tmp_path):
    """The ISSUE's acceptance gate: merged-vs-scattered same-process A/B
    on a many-small-maps shuffle under the per-range seek shim — >= 2x
    per-partition fetch, requests_per_reduce ~ 1 per partition,
    byte-identical output."""
    from sparkrdma_tpu.shuffle.merge_bench import run_merge_microbench

    res = run_merge_microbench(str(tmp_path), num_maps=24,
                               num_partitions=8, seek_delay_s=0.002)
    assert res["coverage_complete"], res
    assert res["identical"], res
    assert res["speedup"] >= 2.0, res
    assert res["merged_reads"] == res["partitions"], res
    assert res["requests"]["merged"] <= res["partitions"] + 2, res
    # the seek-shape win itself: served ranges collapse M x P -> P
    assert res["blocks_served"]["merged"] == res["partitions"], res
    assert (res["blocks_served"]["scattered"]
            >= res["maps"] * res["partitions"]), res
