"""Elastic executor membership (parallel/membership.py): the
epoch-versioned membership plane, mid-job join, graceful drain with
zero re-executions, the autoscaler policy, admission capacity scaling,
and the mixed-version degrade to static membership."""

import os
import threading
import time

import numpy as np
import pytest

# scripts/run_elastic_bench.sh sweeps this: it varies every map task's
# data so drain/replication/coverage exercise across payloads
SEED = int(os.environ.get("ELASTIC_SEED", "0"))

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.membership import (
    SLOT_DEAD,
    SLOT_DRAINING,
    SLOT_LIVE,
    Autoscaler,
    MembershipPlane,
)
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.recovery import run_map_stage, run_reduce_with_retry
from sparkrdma_tpu.shuffle.tenancy import AdmissionController

CONF = dict(connect_timeout_ms=2000, max_connection_attempts=2,
            pre_warm_connections=False)

# CHAOS_LOCKGRAPH=1: run the elastic-churn suite under the lock-order
# shim (sparkrdma_tpu/analysis/lockgraph.py), mirroring the
# tests/test_chaos.py hook — join/drain/retire/autoscale drive the
# membership plane's rare teardown paths, exactly where lock-order
# inversions hide. Any cycle fails the module.
LOCKGRAPH = os.environ.get("CHAOS_LOCKGRAPH", "0") not in ("0", "false")


@pytest.fixture(scope="module", autouse=True)
def _membership_lockgraph():
    if not LOCKGRAPH:
        yield
        return
    from engine_helpers import lockgraph_module_guard
    yield from lockgraph_module_guard()


def _mk_conf(**kw):
    base = dict(CONF)
    base.update(kw)
    return TpuShuffleConf(**base)


def _cluster(tmp_path, n=3, tag="e", **kw):
    conf = _mk_conf(**kw)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=f"{tag}{i}",
                               spill_dir=str(tmp_path / f"{tag}{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return conf, driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _map_fn_for(counter):
    def map_fn(writer, map_id):
        counter[map_id] = counter.get(map_id, 0) + 1
        rng = np.random.default_rng(4000 + SEED * 10007 + map_id)
        writer.write_batch(rng.integers(0, 7000, 400).astype(np.uint64))
    return map_fn


def _expected(num_maps):
    return np.sort(np.concatenate(
        [np.random.default_rng(4000 + SEED * 10007 + m)
         .integers(0, 7000, 400)
         for m in range(num_maps)]).astype(np.uint64))


def _reduce_fn(mgr, handle):
    keys, _ = mgr.get_reader(handle, 0, handle.num_partitions).read_all()
    return np.sort(keys)


# -- the membership plane (unit) ------------------------------------------

def test_membership_plane_state_machine():
    from sparkrdma_tpu.utils.ids import ExecutorId, ShuffleManagerId
    plane = MembershipPlane()
    mids = [ShuffleManagerId(ExecutorId(str(i), "h", 0), "h", 9000 + i, 0)
            for i in range(3)]
    epochs = []
    for mid in mids:
        *_, epoch, is_new = plane.join(mid)
        assert is_new
        epochs.append(epoch)
    assert epochs == sorted(epochs) and len(set(epochs)) == 3
    assert plane.live_slots() == [0, 1, 2]

    # re-hello bumps the epoch but appends nothing
    *_, e2, is_new = plane.join(mids[1])
    assert not is_new and e2 > epochs[-1]
    assert plane.live_slots() == [0, 1, 2]

    # drain: live set shrinks, include_draining view doesn't
    assert plane.begin_drain(1) is not None
    assert plane.begin_drain(1) is None  # not LIVE anymore
    assert plane.live_slots() == [0, 2]
    assert plane.live_slots(include_draining=True) == [0, 1, 2]
    assert plane.draining_slots() == {1}
    assert plane.state_of(1) == SLOT_DRAINING

    # abort returns it; retire kills it
    assert plane.abort_drain(1) is not None
    assert plane.state_of(1) == SLOT_LIVE
    assert plane.begin_drain(1) is not None
    members, states, _ = plane.retire(1)
    assert states[1] == SLOT_DEAD
    from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
    assert members[1] == TOMBSTONE
    assert plane.retire(1) is None  # idempotent
    # tombstone by identity converges too
    assert plane.tombstone(mids[1]) is None
    res = plane.tombstone(mids[0])
    assert res is not None and res[3] == 0
    assert plane.live_slots() == [2]
    assert plane.state_of(99) == SLOT_DEAD  # unknown slot = dead


def test_membership_plane_baseline_freezes_once():
    from sparkrdma_tpu.utils.ids import ExecutorId, ShuffleManagerId
    plane = MembershipPlane()
    for i in range(4):
        plane.join(ShuffleManagerId(ExecutorId(str(i), "h", 0), "h",
                                    9100 + i, 0))
    assert plane.baseline() == 4  # unfrozen: tracks live
    assert plane.freeze_baseline() == 4
    plane.join(ShuffleManagerId(ExecutorId("j", "h", 0), "h", 9200, 0))
    assert plane.baseline() == 4  # frozen: joins don't move it
    assert plane.joins == 1      # post-baseline join counted


# -- admission capacity from live membership (satellite) ------------------

def test_admission_scales_with_live_membership():
    adm = AdmissionController(max_inflight=4, queue_depth=0,
                              retry_after_ms=1000)
    assert adm.effective_max_inflight() == 4
    # a drained fleet sheds honestly: cap halves, hint doubles
    adm.set_fleet(live=2, baseline=4)
    assert adm.effective_max_inflight() == 2
    assert adm.effective_retry_after_ms() == 2000
    # a grown fleet admits more; the hint never shrinks below configured
    adm.set_fleet(live=8, baseline=4)
    assert adm.effective_max_inflight() == 8
    assert adm.effective_retry_after_ms() == 1000

    adm.set_fleet(live=1, baseline=4)
    for sid in range(1):
        adm.admit(7, sid)
    from sparkrdma_tpu.shuffle.tenancy import AdmissionRejected
    with pytest.raises(AdmissionRejected) as ei:
        adm.admit(7, 99)
    assert ei.value.retry_after_ms == 4000
    assert adm.snapshot()["effective_cap"] == 1
    # disabled admission stays disabled under any fleet
    off = AdmissionController(max_inflight=0)
    off.set_fleet(1, 8)
    assert off.effective_max_inflight() == 0
    off.admit(1, 1)  # no-op, no raise


# -- autoscaler policy (unit, injected gauges) ----------------------------

class _StubDriver:
    def __init__(self, conf, live=4):
        from sparkrdma_tpu.utils.ids import ExecutorId, ShuffleManagerId
        from sparkrdma_tpu.utils import trace as trace_mod
        self.conf = conf
        self.membership = MembershipPlane()
        for i in range(live):
            self.membership.join(ShuffleManagerId(
                ExecutorId(str(i), "h", 0), "h", 9300 + i, 0))
        self.admission = AdmissionController()
        self.tracer = trace_mod.NULL
        self.actions = []

    def live_shuffles(self):
        return []

    def decommission_slot(self, slot, deadline_ms=None):
        self.actions.append(("drain", slot))
        self.membership.retire(slot)
        return {"status": "drained", "slot": slot}


def test_autoscaler_policy_up_down_clamped():
    conf = _mk_conf(min_executors=2, max_executors=6)
    drv = _StubDriver(conf, live=4)
    gauges = {"admission_backlog": 0, "queue_depth": 0.0,
              "reduce_balance": 1.0}
    spawned = []

    def spawn(n):  # the harness's hook: really grow the fleet
        from sparkrdma_tpu.utils.ids import ExecutorId, ShuffleManagerId
        spawned.append(n)
        for k in range(n):
            drv.membership.join(ShuffleManagerId(
                ExecutorId(f"s{len(spawned)}-{k}", "h", 0), "h", 9400, 0))

    scaler = Autoscaler(drv, conf, scale_up=spawn, load_fn=lambda: gauges)

    # backlog-driven scale-up, clamped at max_executors
    gauges["admission_backlog"] = 5
    assert scaler.tick() == ("up", 2)  # 4 + 5 clamped to 6 => +2
    assert spawned == [2]

    # busy (deep queue) holds steady
    gauges["admission_backlog"] = 0
    gauges["queue_depth"] = 10.0
    assert scaler.tick() is None

    # idle needs TWO consecutive ticks before the first drain
    gauges["queue_depth"] = 0.0
    assert scaler.tick() is None
    assert scaler.tick() == ("down", 5)  # highest live slot drains first
    assert drv.actions == [("drain", 5)]

    # skew (reduce_balance) is a scale-up signal
    gauges["reduce_balance"] = 3.0
    assert scaler.tick() == ("up", 1)
    gauges["reduce_balance"] = 1.0

    # the floor holds: drain down to min_executors, never below
    for _ in range(10):
        scaler.tick()
        scaler.tick()
    assert len(drv.membership.live_slots()) >= conf.min_executors


def test_autoscaler_unbounded_ceiling_scales_up():
    """max_executors=0 means UNBOUNDED (the config contract): a backlog
    on the default config must still grow the fleet — the ceiling must
    not collapse to the current live count."""
    conf = _mk_conf()  # min_executors=0, max_executors=0 (defaults)
    drv = _StubDriver(conf, live=3)
    spawned = []
    scaler = Autoscaler(drv, conf, scale_up=lambda n: spawned.append(n),
                        load_fn=lambda: {"admission_backlog": 4})
    assert scaler.tick() == ("up", 4)
    assert spawned == [4]


# -- wire messages (satellite: fuzz conventions + legacy decode) ----------

def test_membership_wire_roundtrip_and_legacy():
    m = M.MembershipBumpMsg(9, [SLOT_LIVE, SLOT_DRAINING, SLOT_DEAD])
    m2 = M.MembershipBumpMsg.from_payload(m.payload())
    assert (m2.epoch, m2.slot_states) == (9, [0, 1, 2])
    # epoch-only legacy payload (pre-elastic peer): empty vector
    import struct
    legacy = M.MembershipBumpMsg.from_payload(struct.pack("<q", 9))
    assert legacy.epoch == 9 and legacy.slot_states == []

    d = M.DrainReq(5, 2, 1234)
    d2 = M.DrainReq.from_payload(d.payload())
    assert (d2.req_id, d2.slot, d2.deadline_ms) == (5, 2, 1234)
    assert M.DrainReq.from_payload(
        struct.pack("<qi", 5, 2)).deadline_ms == 0

    r = M.DrainResp(5, M.STATUS_OK, 7, 4096)
    r2 = M.DrainResp.from_payload(r.payload())
    assert (r2.maps_pushed, r2.bytes_pushed) == (7, 4096)

    from sparkrdma_tpu.utils.ids import ExecutorId, ShuffleManagerId
    mid = ShuffleManagerId(ExecutorId("x", "h", 1), "h", 9999, 7)
    j = M.JoinMsg(mid, 0)
    j2 = M.JoinMsg.from_payload(j.payload())
    assert j2.manager_id == mid and j2.flags == 0
    # the hello-shaped (flag-less) prefix decodes too
    assert M.JoinMsg.from_payload(j.payload()[:-4]).manager_id == mid


# -- mid-job join (e2e) ---------------------------------------------------

def test_join_mid_job_bump_states_and_health_watch(tmp_path):
    """A joiner announced mid-job: the membership bump teaches every
    peer the slot-state vector AND registers the joiner with the
    heartbeat monitor (satellite: previously a joiner was watched only
    once a fetch took interest, so its silent death surfaced only as a
    failed fetch)."""
    conf, driver, execs = _cluster(tmp_path, n=2,
                                   heartbeat_interval_ms=100)
    joiner = None
    try:
        handle = driver.register_shuffle(
            1, num_maps=2, num_partitions=2,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        run_map_stage(execs, handle, _map_fn_for(counter))

        joiner = TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                                   executor_id="j",
                                   spill_dir=str(tmp_path / "j"))
        joiner.join_cluster()
        joiner.executor.wait_for_members(3)
        assert len(driver.driver.members()) == 3
        assert driver.driver.membership.live_slots() == [0, 1, 2]
        assert driver.driver.membership.joins >= 0

        # the bump reaches existing peers: state vector cached, joiner
        # slot registered with the health monitor
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            epoch, states = execs[0].executor.location_plane.membership()
            snap = execs[0].executor.health_snapshot()
            if len(states) == 3 and 2 in snap["watched"]:
                break
            time.sleep(0.02)
        epoch, states = execs[0].executor.location_plane.membership()
        assert list(states) == [SLOT_LIVE] * 3
        assert 2 in execs[0].executor.health_snapshot()["watched"]

        # the joiner serves reads (stage completes across 3 members)
        got = _reduce_fn(execs[0], handle)
        np.testing.assert_array_equal(got, _expected(2))
    finally:
        if joiner is not None:
            joiner.stop()
        _shutdown(driver, execs)


# -- graceful drain (e2e) -------------------------------------------------

def test_drain_zero_reexecutions(tmp_path):
    """Decommission an executor that owns committed maps: push-merge
    replication + re-point means the reduce completes byte-identically
    with ZERO map re-executions, and the drain result says 'drained'."""
    conf, driver, execs = _cluster(tmp_path, n=3, push_merge=True,
                                   merge_replicas=1)
    try:
        handle = driver.register_shuffle(
            2, num_maps=6, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        map_fn = _map_fn_for(counter)
        ran = run_map_stage(execs, handle, map_fn)
        assert sum(counter.values()) == 6
        # background pushes land before the drain begins (determinism)
        for ex in execs:
            assert ex.pusher.drain(timeout=10)

        victim_slot = execs[2].executor.exec_index(timeout=2)
        res = driver.decommission_slot(victim_slot)
        assert res["status"] == "drained", res
        assert res["unservable"] == []
        assert driver.driver.drains_completed == 1
        assert driver.driver.drain_fallbacks == 0
        # the drainee owned maps; they re-point, not re-execute
        owned = [m for m, s in ran.items() if s == 2]
        assert res["repointed"] >= len(owned) > 0
        # membership: slot dead, announce converged
        assert driver.driver.membership.state_of(victim_slot) == SLOT_DEAD

        # the drainee may now be stopped entirely; reads stay complete
        execs[2].stop()
        got = run_reduce_with_retry(execs[:2], handle, map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=2,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(6))
        assert sum(counter.values()) == 6, \
            f"re-executions after a clean drain: {counter}"
    finally:
        _shutdown(driver, execs[:2])


def test_drain_dead_drainee_falls_back_to_tombstone(tmp_path):
    """The drainee dies before the drain: the decommission FALLS BACK
    to ordinary tombstone recovery — the slot still retires, reducers
    re-execute the lost maps, output stays byte-identical."""
    conf, driver, execs = _cluster(tmp_path, n=3)  # push_merge OFF
    try:
        handle = driver.register_shuffle(
            3, num_maps=6, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        map_fn = _map_fn_for(counter)
        ran = run_map_stage(execs, handle, map_fn)
        owned = [m for m, s in ran.items() if s == 2]
        assert owned

        victim_slot = execs[2].executor.exec_index(timeout=2)
        execs[2].stop()  # dies mid-drain (before the DrainReq lands)
        res = driver.decommission_slot(victim_slot, deadline_ms=1500)
        assert res["status"] == "fallback", res
        assert driver.driver.drain_fallbacks == 1
        assert driver.driver.membership.state_of(victim_slot) == SLOT_DEAD

        got = run_reduce_with_retry(execs[:2], handle, map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=2,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(6))
        # the fallback path re-executed exactly the drainee's maps
        assert sum(counter.values()) == 6 + len(owned)
    finally:
        _shutdown(driver, execs[:2])


def test_abort_drain_rebroadcasts_live_state(tmp_path):
    """The operator-facing abort: DRAINING -> LIVE is BROADCAST (a
    silent revert would leave peers treating the slot as draining
    forever) and admission capacity is restored."""
    conf, driver, execs = _cluster(tmp_path, n=3)
    try:
        drv = driver.driver
        drv.membership.freeze_baseline()
        assert drv.membership.begin_drain(2) is not None
        drv.publish_membership(*drv.membership.snapshot())
        assert drv.abort_drain(2)
        assert not drv.abort_drain(2)  # not DRAINING anymore: no-op
        assert drv.membership.live_slots() == [0, 1, 2]
        assert drv.admission.snapshot()["fleet"] == (3, 3)
        # peers converge back to an all-LIVE state vector
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            _, states = execs[0].executor.location_plane.membership()
            if list(states) == [SLOT_LIVE] * 3:
                break
            time.sleep(0.02)
        assert list(states) == [SLOT_LIVE] * 3
        assert not execs[0].executor.slot_draining(2)
    finally:
        _shutdown(driver, execs)


def test_draining_slot_takes_no_new_maps(tmp_path):
    """While a slot is DRAINING, run_map_stage steers new maps away
    from it (the membership-aware exclude), and the driver's planner
    inputs mark it avoided."""
    conf, driver, execs = _cluster(tmp_path, n=3)
    try:
        assert driver.driver.membership.begin_drain(2) is not None
        handle = driver.register_shuffle(
            4, num_maps=6, num_partitions=3,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        ran = run_map_stage(execs, handle, _map_fn_for(counter),
                            exclude_slots=driver.driver.membership
                            .draining_slots())
        assert all(slot != 2 for slot in ran.values()), ran
        got = _reduce_fn(execs[0], handle)
        np.testing.assert_array_equal(got, _expected(6))
    finally:
        _shutdown(driver, execs)


# -- bench acceptance -----------------------------------------------------

def test_elastic_microbench_acceptance(tmp_path):
    """The drain-vs-kill A/B's tier-1 gates (bench.py's
    ``drain_zero_reexec`` secondary): byte-identical both arms, ZERO
    re-executions on the planned drain, and a real re-execution bill on
    the unplanned kill of the same slot."""
    from sparkrdma_tpu.shuffle.elastic_bench import run_elastic_microbench

    res = run_elastic_microbench(str(tmp_path), seed=SEED)
    assert res["identical"]
    assert res["drain_status"] == "drained", res
    assert res["reexec_drain"] == 0, res
    assert res["reexec_kill"] == res["victim_owned_maps"] > 0, res


# -- mixed-version degrade ------------------------------------------------

def test_old_peer_ignoring_elastic_frames_degrades_static(tmp_path):
    """A pre-elastic peer drops the membership-bump/drain frames it
    doesn't know (its transport would tear the connection; dropping is
    the conservative stand-in). It keeps the announce-only static view
    — no state vector, every slot LIVE — and jobs still complete:
    elastic frames are strictly additive."""
    conf, driver, execs = _cluster(tmp_path, n=2)
    joiner = None
    try:
        old = execs[1].executor
        orig_handle = old._handle

        def dropping_handle(conn, msg):
            if isinstance(msg, (M.MembershipBumpMsg, M.DrainReq)):
                return None  # "unknown frame" on a pre-elastic peer
            return orig_handle(conn, msg)

        old._handle = dropping_handle
        # re-point the live server dispatch at the wrapper — including
        # connections the driver ALREADY accepted (the broadcast channel
        # the bump rides was dialed at cluster start)
        old.server._handler = dropping_handle
        with old.server._conns_lock:
            for c in old.server._conns:
                c._on_message = dropping_handle
        # forget any bump that raced in before the patch: a genuinely
        # pre-elastic peer never held a state vector at all
        with old.location_plane._lock:
            old.location_plane._member_epoch = -1
            old.location_plane._member_states = ()

        handle = driver.register_shuffle(
            5, num_maps=4, num_partitions=2,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        run_map_stage(execs, handle, _map_fn_for(counter))

        joiner = TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                                   executor_id="j2",
                                   spill_dir=str(tmp_path / "j2"))
        joiner.join_cluster()
        joiner.executor.wait_for_members(3)
        time.sleep(0.3)  # let the (dropped) bump traffic settle

        # the old peer saw the ANNOUNCE (members grew) but no states
        assert len(old.members()) == 3
        _, states = old.location_plane.membership()
        assert states == ()  # static view: everything reads LIVE
        assert not old.slot_draining(0)

        got = _reduce_fn(execs[1], handle)  # reads through the old peer
        np.testing.assert_array_equal(got, _expected(4))
    finally:
        if joiner is not None:
            joiner.stop()
        _shutdown(driver, execs)
