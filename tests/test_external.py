"""Bounded-memory sort/merge (the ExternalSorter role): vectorized merges,
spill-to-disk k-way merge, and a genuine address-space-capped run."""

import os
import subprocess
import sys

import numpy as np
import pytest

from sparkrdma_tpu.shuffle.external import ExternalMerger, merge_runs, merge_two


def test_merge_two_stable():
    rng = np.random.default_rng(0)
    ak = np.sort(rng.integers(0, 50, 200).astype(np.uint64))
    bk = np.sort(rng.integers(0, 50, 300).astype(np.uint64))
    ar = np.zeros((200, 2), np.uint8)   # tag rows by side
    br = np.ones((300, 2), np.uint8)
    keys, rows = merge_two(ak, ar, bk, br)
    assert (np.diff(keys.astype(np.int64)) >= 0).all()
    np.testing.assert_array_equal(np.sort(keys),
                                  np.sort(np.concatenate([ak, bk])))
    # stability: within one key, all a-rows precede all b-rows
    for k in np.unique(keys):
        tags = rows[keys == k, 0]
        assert (np.diff(tags.astype(np.int8)) >= 0).all()


def test_merge_runs_matches_full_sort():
    rng = np.random.default_rng(1)
    runs = []
    for _ in range(7):  # odd count exercises the bye
        rows = rng.integers(0, 2**32, size=(rng.integers(0, 500), 5),
                            dtype=np.uint32)
        rows = rows[np.argsort(rows[:, 0], kind="stable")]
        runs.append((rows[:, 0], rows))
    _, merged = merge_runs(runs)
    everything = np.concatenate([r for _, r in runs])
    want = everything[np.argsort(everything[:, 0], kind="stable")]
    np.testing.assert_array_equal(merged[:, 0], want[:, 0])


def test_external_merger_exact_and_bounded(tmp_path):
    rng = np.random.default_rng(2)
    W = 24
    budget = 1 << 20  # 1 MiB forces many spills for 8 MiB of rows
    all_keys = []
    with ExternalMerger(W, spill_dir=str(tmp_path), run_buffer_rows=1024,
                        memory_budget_bytes=budget) as m:
        for _ in range(32):
            keys = rng.integers(0, 2**63, size=8192).astype(np.uint64)
            m.add_batch(keys, rng.integers(0, 256, size=(8192, W),
                                           dtype=np.uint8))
            all_keys.append(keys)
        assert m.num_runs >= 8, "budget never triggered spilling"
        assert m.peak_buffer_bytes <= budget + 8192 * (8 + W)
        got_keys, got_payload = [], 0
        for keys, payload in m.sorted_batches():
            got_keys.append(keys)
            got_payload += len(payload)
        got = np.concatenate(got_keys)
    assert (np.diff(got.astype(np.float64)) >= 0).all()
    np.testing.assert_array_equal(np.sort(got),
                                  np.sort(np.concatenate(all_keys)))
    assert got_payload == 32 * 8192
    assert not os.listdir(tmp_path), "spill files not cleaned up"


def test_merge_runs_all_empty_preserves_shape():
    """A device whose runs are all empty must get an empty array of the
    INPUT row shape/dtype, not (0, 0) u8 — concatenation depends on it."""
    empty = np.zeros((0, 5), np.uint32)
    keys, rows = merge_runs([(empty[:, 0], empty), (empty[:, 0], empty)])
    assert rows.shape == (0, 5) and rows.dtype == np.uint32
    assert keys.dtype == np.uint32


def test_under_budget_skips_disk(tmp_path):
    """Data fitting the budget never touches disk."""
    with ExternalMerger(4, spill_dir=str(tmp_path),
                        memory_budget_bytes=1 << 20) as m:
        m.add_batch(np.array([5, 1], np.uint64), np.zeros((2, 4), np.uint8))
        m.add_batch(np.array([3], np.uint64), np.zeros((1, 4), np.uint8))
        k, _ = m.sorted_all()
        np.testing.assert_array_equal(k, [1, 3, 5])
        assert m.spilled_bytes == 0
        assert not os.listdir(tmp_path)


def test_empty_and_single_batch(tmp_path):
    with ExternalMerger(4, spill_dir=str(tmp_path)) as m:
        k, p = m.sorted_all()
        assert len(k) == 0 and p.shape == (0, 4)
    with ExternalMerger(4, spill_dir=str(tmp_path)) as m:
        m.add_batch(np.array([3, 1, 2], np.uint64),
                    np.arange(12, dtype=np.uint8).reshape(3, 4))
        k, p = m.sorted_all()
        np.testing.assert_array_equal(k, [1, 2, 3])
        np.testing.assert_array_equal(p[0], [4, 5, 6, 7])


_RLIMIT_SCRIPT = r"""
import resource, sys
import numpy as np
sys.path.insert(0, {repo!r})
from sparkrdma_tpu.shuffle.external import ExternalMerger

W = 56   # 64-byte rows
rows_total = {rows_total}
batch = 1 << 15
rng = np.random.default_rng(0)
m = ExternalMerger(W, spill_dir={spill!r}, memory_budget_bytes=4 << 20,
                   run_buffer_rows=4096)
checksum = np.uint64(0)
for start in range(0, rows_total, batch):
    keys = rng.integers(0, 2**63, size=batch).astype(np.uint64)
    checksum ^= np.bitwise_xor.reduce(keys)
    m.add_batch(keys, np.zeros((batch, W), np.uint8))

# cap the address space JUST above current usage: the ~{mb} MiB dataset can
# no longer be materialized, so only a bounded merge can finish
with open("/proc/self/status") as f:
    vm_kb = next(int(l.split()[1]) for l in f if l.startswith("VmSize"))
cap = (vm_kb << 10) + (64 << 20)
resource.setrlimit(resource.RLIMIT_AS, (cap, cap))
try:
    np.zeros(rows_total * (8 + W), np.uint8)  # the old read_sorted way
    print("CAP-NOT-EFFECTIVE")
except MemoryError:
    pass

count = 0
prev = -1
out_checksum = np.uint64(0)
for keys, payload in m.sorted_batches():
    assert int(keys[0]) >= prev
    assert (np.diff(keys.astype(np.float64)) >= 0).all()
    prev = int(keys[-1])
    count += len(keys)
    out_checksum ^= np.bitwise_xor.reduce(keys)
m.close()
assert count == rows_total, count
assert out_checksum == checksum
print("RLIMIT-MERGE-OK")
"""


def test_merge_completes_under_address_space_cap(tmp_path):
    """A reduce larger than the allowed address space completes: the spill
    merge is the only way through (materializing provably MemoryErrors)."""
    rows_total = 1 << 21  # 2M rows x 64B = 128 MiB
    script = _RLIMIT_SCRIPT.format(repo=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), rows_total=rows_total,
        spill=str(tmp_path), mb=128)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=300)
    if "CAP-NOT-EFFECTIVE" in proc.stdout:
        pytest.skip("RLIMIT_AS not enforceable on this platform")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "RLIMIT-MERGE-OK" in proc.stdout


def test_terasort_streamed_uses_merge(tmp_path):
    """The streamed TeraSort host merge is the tournament merge and its
    output is unchanged (exact multiset + sorted per device)."""
    import os as _os
    _os.environ.setdefault("XLA_FLAGS",
                           "--xla_force_host_platform_device_count=8")
    import jax
    from jax.sharding import Mesh

    from sparkrdma_tpu.models.terasort import (
        TeraSortConfig, generate_rows, run_terasort_streamed)

    mesh = Mesh(np.array(jax.devices()[:8]), ("shuffle",))
    cfg = TeraSortConfig(rows_per_device=512, payload_words=4, out_factor=2)
    big = TeraSortConfig(rows_per_device=512 * 3, payload_words=4)
    rows = generate_rows(big, 8, seed=5)[: 8 * 512 * 3 - 700]  # ragged tail
    merged, rounds = run_terasort_streamed(mesh, cfg, rows)
    assert rounds == 3
    got = np.concatenate(merged)
    assert len(got) == len(rows)
    prev = -1
    for d, part in enumerate(merged):
        keys = part[:, 0].astype(np.int64)
        assert (np.diff(keys) >= 0).all(), f"device {d} unsorted"
        if len(keys):
            assert keys[0] >= prev
            prev = keys[-1]
    np.testing.assert_array_equal(
        np.sort(got[:, 0]), np.sort(rows[:, 0]))
