"""RPC framing round-trip tests (reference: scala/RdmaRpcMsg.scala:42-78)."""

import pytest

from sparkrdma_tpu.parallel.rpc_msg import (
    AnnounceMsg,
    HelloMsg,
    Reassembler,
    decode_message,
    segments,
)
from sparkrdma_tpu.utils.ids import BlockId, ExecutorId, ShuffleManagerId


def _mid(i: int) -> ShuffleManagerId:
    return ShuffleManagerId(ExecutorId(str(i), f"host{i}", 7000 + i), f"host{i}", 9000 + i)


def test_ids_roundtrip():
    e = ExecutorId("3", "worker-a.example", 41234)
    decoded, off = ExecutorId.deserialize(e.serialize())
    assert decoded == e and off == len(e.serialize())
    m = _mid(5)
    decoded2, _ = ShuffleManagerId.deserialize(m.serialize())
    assert decoded2 == m
    b = BlockId(1, 2, 3)
    assert BlockId.deserialize(b.serialize())[0] == b


def test_id_interning():
    m = _mid(1)
    a, _ = ShuffleManagerId.deserialize(m.serialize())
    b, _ = ShuffleManagerId.deserialize(m.serialize())
    assert a is b  # interning cache (scala/RdmaUtils.scala:136-142)


def test_hello_roundtrip():
    msg = HelloMsg(_mid(2))
    assert decode_message(msg.encode()) == msg


def test_announce_roundtrip():
    msg = AnnounceMsg([_mid(i) for i in range(5)])
    assert decode_message(msg.encode()) == msg
    assert decode_message(AnnounceMsg([]).encode()) == AnnounceMsg([])


def test_segmentation_and_reassembly():
    msg = AnnounceMsg([_mid(i) for i in range(100)])
    frame = msg.encode()
    segs = segments(frame, 64)
    assert all(len(s) <= 64 for s in segs)
    assert b"".join(segs) == frame
    r = Reassembler()
    out = []
    for s in segs:
        out.extend(r.feed(s))
    assert out == [msg]


def test_reassembler_multiple_messages_one_chunk():
    m1, m2 = HelloMsg(_mid(1)), AnnounceMsg([_mid(2)])
    r = Reassembler()
    out = list(r.feed(m1.encode() + m2.encode()))
    assert out == [m1, m2]


def test_reassembler_byte_at_a_time():
    msg = HelloMsg(_mid(9))
    r = Reassembler()
    out = []
    for i in range(len(msg.encode())):
        out.extend(r.feed(msg.encode()[i:i + 1]))
    assert out == [msg]


def test_bad_frames():
    with pytest.raises(ValueError):
        decode_message(b"\x10\x00\x00\x00\x63\x00\x00\x00" + b"x" * 8)  # unknown type 99
    msg = HelloMsg(_mid(1)).encode()
    with pytest.raises(ValueError):
        decode_message(msg + b"extra")


def test_announce_epoch_roundtrip():
    msg = AnnounceMsg([_mid(1)], epoch=42)
    decoded = decode_message(msg.encode())
    assert decoded.epoch == 42 and decoded == msg


def test_stale_epoch_announce_ignored():
    from sparkrdma_tpu.config import TpuShuffleConf
    from sparkrdma_tpu.parallel.endpoints import ExecutorEndpoint, DriverEndpoint
    conf = TpuShuffleConf()
    driver = DriverEndpoint(conf)
    ex = ExecutorEndpoint("127.0.0.1", "0", driver.address, conf=conf)
    try:
        fresh = AnnounceMsg([_mid(1), _mid(2)], epoch=5)
        stale = AnnounceMsg([_mid(9)], epoch=3)
        ex._handle(None, fresh)
        ex._handle(None, stale)  # must not overwrite
        assert ex.members() == fresh.manager_ids
    finally:
        ex.stop()
        driver.stop()
