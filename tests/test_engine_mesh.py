"""Engine + ICI data plane unification: with a mesh configured, a DAG job's
shuffle bytes move over the collective exchange — the engine SPI and the
accelerated path are the SAME code path, matching the reference where the
reader Spark gets back does the one-sided RDMA fetch itself
(scala/RdmaShuffleManager.scala:234-261,
scala/RdmaShuffleFetcherIterator.scala:119-180). Asserted three ways:
exchange dispatch counters tick, zero TCP fetchers are constructed, and
results are exact — including across an executor loss (stage retry)."""

import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from engine_helpers import (
    make_cluster,
    make_table as _table,
    payload_u32 as _payload_u32,
    u32_payload as _u32_payload,
)
from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
from sparkrdma_tpu.parallel import exchange as exchange_mod
from sparkrdma_tpu.shuffle.manager import PartitionerSpec
from sparkrdma_tpu.shuffle.spark_compat import ShuffleDependency

D = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


@pytest.fixture
def cluster(tmp_path):
    driver, execs = make_cluster(tmp_path)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


def _no_tcp_fetchers(monkeypatch):
    """Arm a counter that ticks if ANY TCP fetcher gets built."""
    from sparkrdma_tpu.shuffle import fetcher as fetcher_mod

    built = {"n": 0}
    orig = fetcher_mod.ShuffleFetcher.__init__

    def spy(self, *a, **kw):
        built["n"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(fetcher_mod.ShuffleFetcher, "__init__", spy)
    return built


@pytest.mark.parametrize("rows_per_round", [0, 256])
def test_engine_job_rides_mesh(cluster, mesh, monkeypatch, rows_per_round):
    """Sum-by-partition job: exact results, exchanges dispatched, zero TCP
    fetchers built (one-shot and streamed-round mesh reduces)."""
    driver, execs = cluster
    P, maps, rows, key_space = 4, 6, 700, 5000

    def map_fn(ctx, writer, task_id):
        keys, vals = _table(100 + task_id, rows, key_space)
        writer.write((keys, _u32_payload(vals)))

    def reduce_fn(ctx, task_id):
        reader = ctx.read(0)
        total = 0
        n = 0
        for keys, payload in reader.readBatches():
            total += int(_payload_u32(payload).astype(np.int64).sum())
            n += len(keys)
        assert reader.metrics.remote_bytes == 0  # nothing crossed TCP
        return total, n

    built = _no_tcp_fetchers(monkeypatch)
    before = exchange_mod.DATA_PLANE["exchanges"]
    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    engine = DAGEngine(driver, execs, mesh=mesh,
                       mesh_rows_per_round=rows_per_round)
    out = engine.run(ResultStage(P, reduce_fn, parents=[stage]))

    # exact per-partition sums vs. host truth
    want = [0] * P
    seen = 0
    for m in range(maps):
        keys, vals = _table(100 + m, rows, key_space)
        for p in range(P):
            want[p] += int(vals[keys % P == p].astype(np.int64).sum())
        seen += rows
    assert [t for t, _ in out] == want
    assert sum(n for _, n in out) == seen
    assert exchange_mod.DATA_PLANE["exchanges"] > before, \
        "no collective exchange dispatched — bytes did not ride the mesh"
    assert built["n"] == 0, "TCP fetcher constructed in mesh mode"
    if rows_per_round:  # streamed mode must have taken multiple rounds
        assert exchange_mod.DATA_PLANE["exchanges"] - before > 1


def test_engine_mesh_survives_executor_loss(cluster, mesh, caplog):
    """Executor dies after the map stage: mesh staging surfaces the missing
    map as FetchFailed, the ordinary retry recomputes on survivors, the
    re-reduce is exact (scala/RdmaShuffleFetcherIterator.scala:376-381)."""
    import logging

    caplog.set_level(logging.WARNING, logger="sparkrdma_tpu.engine")
    driver, execs = cluster
    P, maps, rows, key_space = 4, 6, 500, 5000

    def map_fn(ctx, writer, task_id):
        keys, vals = _table(9100 + task_id, rows, key_space)
        writer.write((keys, _u32_payload(vals)))

    killed = {"done": False}

    def reduce_fn(ctx, task_id):
        if task_id == 0 and not killed["done"]:
            killed["done"] = True
            victim = execs[1].native
            mid = victim.executor.manager_id
            victim.executor.stop()
            driver.native.driver.remove_member(mid)
            time.sleep(0.3)
        total = 0
        for keys, payload in ctx.read(0).readBatches():
            total += int(_payload_u32(payload).astype(np.int64).sum())
        return total

    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    # sequential: the injection relies on task 0 killing BEFORE any other
    # task's read triggers the mesh reduce (a concurrent sibling would
    # legitimately cache the pre-kill reduce and no recovery would fire)
    engine = DAGEngine(driver, execs, mesh=mesh, max_parallel_tasks=1)
    got = sum(engine.run(ResultStage(P, reduce_fn, parents=[stage])))
    assert killed["done"], "failure injection never ran"

    want = sum(int(_table(9100 + m, rows, key_space)[1].astype(np.int64).sum())
               for m in range(maps))
    assert got == want
    assert any("recovering shuffle" in r.message for r in caplog.records)


def test_engine_mesh_two_table_join(cluster, mesh, monkeypatch):
    """Multi-parent read (equi-join) over the mesh plane: two shuffles,
    both served by collective reduces, zero TCP fetchers."""
    driver, execs = cluster
    P, maps, rows, key_space = 4, 3, 400, 64

    def writer_fn(base_seed):
        def fn(ctx, writer, task_id):
            keys, vals = _table(base_seed + task_id, rows, key_space)
            writer.write((keys, _u32_payload(vals)))
        return fn

    def join_fn(ctx, task_id):
        lk, lp = ctx.read(0)._r.read_all()
        rk, rp = ctx.read(1)._r.read_all()
        lv, rv = _payload_u32(lp), _payload_u32(rp)
        total = 0
        for k in np.unique(lk):
            total += int(lv[lk == k].astype(np.int64).sum()
                         * rv[rk == k].astype(np.int64).sum())
        return total

    built = _no_tcp_fetchers(monkeypatch)
    left = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), writer_fn(7000))
    right = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), writer_fn(8000))
    engine = DAGEngine(driver, execs, mesh=mesh)
    got = sum(engine.run(ResultStage(P, join_fn, parents=[left, right])))

    # truth: sum over keys of (sum of left vals) * (sum of right vals)
    lk = np.concatenate([_table(7000 + m, rows, key_space)[0]
                         for m in range(maps)])
    lv = np.concatenate([_table(7000 + m, rows, key_space)[1]
                         for m in range(maps)]).astype(np.int64)
    rk = np.concatenate([_table(8000 + m, rows, key_space)[0]
                         for m in range(maps)])
    rv = np.concatenate([_table(8000 + m, rows, key_space)[1]
                         for m in range(maps)]).astype(np.int64)
    want = sum(int(lv[lk == k].sum() * rv[rk == k].sum())
               for k in np.unique(lk))
    assert got == want
    assert built["n"] == 0


def test_engine_mesh_rejects_remote_executors(cluster, mesh):
    from sparkrdma_tpu.tasks import RemoteExecutor

    driver, execs = cluster
    fake = RemoteExecutor.__new__(RemoteExecutor)
    with pytest.raises(ValueError, match="in-process"):
        DAGEngine(driver, [*execs, fake], mesh=mesh)
