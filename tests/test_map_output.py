"""Table layout math tests, mirroring the offset arithmetic the reference
relies on (scala/RdmaMapTaskOutput.scala:25-83,
scala/RdmaShuffleManager.scala:410-412)."""

import numpy as np
import pytest

from sparkrdma_tpu.shuffle.map_output import (
    ENTRY_SIZE,
    MAP_ENTRY_SIZE,
    BlockLocation,
    DriverTable,
    MapTaskOutput,
)


def test_entry_sizes_match_reference():
    assert ENTRY_SIZE == 16  # (offset:8, length:4, buf:4) ~ (addr:8, len:4, mkey:4)
    assert MAP_ENTRY_SIZE == 12  # (token:8, exec:4) ~ (addr:8, lkey:4)


def test_put_get_roundtrip():
    out = MapTaskOutput(8)
    out.put(3, offset=4096, length=1234, buf=7)
    assert out.get_block_location(3) == BlockLocation(4096, 1234, 7)
    assert out.get_block_location(0) == BlockLocation(0, 0, 0)
    assert out.total_bytes == 1234


def test_put_all_vectorized():
    lengths = np.array([10, 0, 30, 5], dtype=np.uint32)
    offsets = np.array([0, 10, 10, 40], dtype=np.uint64)
    out = MapTaskOutput(4)
    out.put_all(offsets, lengths, buf=42)
    assert out.get_block_location(2) == BlockLocation(10, 30, 42)
    assert out.total_bytes == 45


def test_range_wire_format():
    out = MapTaskOutput(16)
    for r in range(16):
        out.put(r, offset=r * 100, length=r, buf=1)
    payload = out.get_range(4, 9)
    assert len(payload) == 5 * ENTRY_SIZE
    locs = MapTaskOutput.locations_from_range(payload)
    assert locs[0] == BlockLocation(400, 4, 1)
    assert locs[-1] == BlockLocation(800, 8, 1)


def test_serialize_roundtrip():
    out = MapTaskOutput(5)
    out.put(4, 999, 7, 3)
    clone = MapTaskOutput.from_bytes(out.to_bytes())
    assert clone.num_partitions == 5
    assert clone.get_block_location(4) == BlockLocation(999, 7, 3)


def test_driver_table_publish_and_offsets():
    t = DriverTable(10)
    assert t.num_published == 0
    assert t.entry(5) is None
    t.publish(5, table_token=0xDEADBEEF, exec_index=2)
    assert t.entry(5) == (0xDEADBEEF, 2)
    assert t.num_published == 1
    # one-sided positional write at map_id * MAP_ENTRY_SIZE
    t.write_raw(7 * MAP_ENTRY_SIZE, DriverTable.pack_entry(123, 0))
    assert t.entry(7) == (123, 0)
    with pytest.raises(ValueError):
        t.write_raw(5, b"x" * MAP_ENTRY_SIZE)  # unaligned
    with pytest.raises(IndexError):
        t.write_raw(10 * MAP_ENTRY_SIZE, DriverTable.pack_entry(1, 1))


def test_driver_table_roundtrip():
    t = DriverTable(4)
    t.publish(0, 11, 1)
    t.publish(3, 22, 0)
    clone = DriverTable.from_bytes(t.to_bytes())
    assert clone.num_maps == 4
    assert clone.entry(0) == (11, 1)
    assert clone.entry(1) is None
    assert clone.entry(3) == (22, 0)
    assert len(t.to_bytes()) == 4 * MAP_ENTRY_SIZE


def test_driver_table_negative_offset_rejected():
    t = DriverTable(4)
    with pytest.raises(IndexError):
        t.write_raw(-MAP_ENTRY_SIZE, DriverTable.pack_entry(1, 1))
    assert len(t.to_bytes()) == 4 * MAP_ENTRY_SIZE
