"""Shared variables (broadcast + accumulators) through the DAG engine —
the Spark-core features (sc.broadcast / longAccumulator) the reference's
jobs rely on, provided in-tree by shared_vars.py: broadcast delivery once
per executor process over the control plane, accumulator deltas merged
driver-side exactly once per task across attempts."""

import threading

import numpy as np
import pytest

from engine_helpers import make_cluster, payload_u32, u32_payload
from sparkrdma_tpu import shared_vars
from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
from sparkrdma_tpu.shuffle.manager import PartitionerSpec
from sparkrdma_tpu.shuffle.spark_compat import ShuffleDependency


@pytest.fixture
def cluster(tmp_path):
    driver, execs = make_cluster(tmp_path)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


def test_broadcast_and_accumulator_in_process(cluster):
    """An engine job joins against a broadcast lookup table and counts
    matched rows in an accumulator; both exact."""
    driver, execs = cluster
    P, maps, rows = 4, 3, 300
    engine = DAGEngine(driver, execs)
    lookup = engine.broadcast({k: k * 10 for k in range(32)})
    matched = engine.accumulator("matched")
    row_count = engine.accumulator("rows")

    def map_fn(ctx, writer, task_id):
        rng = np.random.default_rng(task_id)
        keys = rng.integers(0, 64, rows).astype(np.uint64)
        writer.write((keys, u32_payload(keys.astype(np.uint32))))
        row_count.add(len(keys))

    def reduce_fn(ctx, task_id):
        total = 0
        table = lookup.value
        for keys, payload in ctx.read(0).readBatches():
            for k in keys:
                if int(k) in table:
                    matched.add(1)
                    total += table[int(k)]
        return total

    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    got = sum(engine.run(ResultStage(P, reduce_fn, parents=[stage])))

    all_keys = np.concatenate([
        np.random.default_rng(t).integers(0, 64, rows) for t in range(maps)])
    want_matched = int((all_keys < 32).sum())
    assert row_count.value == maps * rows
    assert matched.value == want_matched
    assert got == int(sum(k * 10 for k in all_keys if k < 32))


def test_accumulator_first_success_dedupe(cluster):
    """Duplicate successful attempts of the same task (speculation's
    normal outcome) merge their deltas exactly once, and a straggler
    whose job generation has closed is dropped entirely."""
    driver, execs = cluster
    engine = DAGEngine(driver, execs)
    acc = engine.accumulator("a")
    engine._active_gens.add(1)
    engine._gen_of_stage[7] = 1
    engine._apply_acc_deltas(7, 3, {acc.acc_id: 5}, job_gen=1)
    engine._apply_acc_deltas(7, 3, {acc.acc_id: 5}, job_gen=1)  # losing twin
    engine._apply_acc_deltas(7, 4, {acc.acc_id: 2}, job_gen=1)
    assert acc.value == 7
    # job closes; an abandoned straggler carrying gen 1 lands late
    engine._active_gens.discard(1)
    engine._acc_applied.clear()
    engine._apply_acc_deltas(7, 5, {acc.acc_id: 100}, job_gen=1)
    assert acc.value == 7, "closed-generation straggler double-counted"


def test_ledger_cleared_between_jobs_with_reused_stage_ids(cluster):
    """Two sequential jobs reusing the same stage ids must both count:
    the first-success ledger is per job, not per engine lifetime."""
    driver, execs = cluster
    P, maps, rows = 2, 2, 50
    engine = DAGEngine(driver, execs)
    acc = engine.accumulator("n")

    def make_job():
        # fresh stage objects each run, SAME default stage ids
        def map_fn(ctx, writer, task_id):
            keys = np.arange(rows, dtype=np.uint64)
            writer.write((keys, u32_payload(keys.astype(np.uint32))))

        def reduce_fn(ctx, task_id):
            for keys, _ in ctx.read(0).readBatches():
                acc.add(len(keys))
            return None

        stage = MapStage(maps, ShuffleDependency(
            P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn,
            stage_id=900)
        return ResultStage(P, reduce_fn, parents=[stage], stage_id=901)

    engine.run(make_job())
    engine.run(make_job())
    assert acc.value == 2 * maps * rows


def test_accumulator_outside_task_adds_directly(cluster):
    driver, execs = cluster
    engine = DAGEngine(driver, execs)
    acc = engine.accumulator("direct")
    acc.add(4)
    acc.add(1)
    assert acc.value == 5


def test_broadcast_pickles_as_id_only():
    """The handle must ship tiny — a closure capturing a broadcast of a
    large value serializes without the value's bytes."""
    import cloudpickle

    class _FakeEp:
        def register_broadcast(self, *a):
            pass

        def unregister_broadcast(self, *a):
            pass

    big = np.arange(1 << 20, dtype=np.uint8)
    b = shared_vars.create_broadcast(big, _FakeEp())
    try:
        blob = cloudpickle.dumps(lambda: b.value.sum())
        assert len(blob) < 4096, len(blob)
        # local round trip resolves to the original, no fetch needed
        restored = cloudpickle.loads(blob)
        assert restored() == big.sum()
    finally:
        b.unpersist()


def test_broadcast_unpersist_then_unpickle_elsewhere_errors():
    """After unpersist, a foreign process' proxy (no local original, no
    task fetch channel) fails loudly, not with a silent None."""
    proxy = shared_vars._BroadcastProxy(999_999)
    with pytest.raises(RuntimeError, match="outside a task"):
        _ = proxy.value


def test_accumulator_proxy_value_is_driver_only():
    proxy = shared_vars._AccumulatorProxy(1, "x")
    with pytest.raises(RuntimeError, match="driver-only"):
        _ = proxy.value
