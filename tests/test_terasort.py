"""TeraSort model tests on the 8-device virtual mesh (BASELINE.json
configs #1/#2 at test scale)."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from sparkrdma_tpu.models.terasort import (
    TeraSortConfig,
    generate_rows,
    numpy_terasort,
    run_terasort,
    verify_terasort,
)

D = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


def test_terasort_8dev_verified(mesh):
    cfg = TeraSortConfig(rows_per_device=2048, payload_words=4, out_factor=2)
    rows = generate_rows(cfg, D, seed=0)
    sorted_rows, counts, _ = run_terasort(mesh, cfg, rows=rows)
    verify_terasort(sorted_rows, counts, rows, D)


def test_terasort_payload_rides_with_keys(mesh):
    """Payload words must stay attached to their key through the full
    partition/exchange/sort cycle."""
    cfg = TeraSortConfig(rows_per_device=512, payload_words=2, out_factor=2)
    rows = generate_rows(cfg, D, seed=1)
    # make payload a function of the key so attachment is checkable
    rows[:, 1] = rows[:, 0] ^ 0xA5A5A5A5
    rows[:, 2] = rows[:, 0] + 1
    sorted_rows, counts, _ = run_terasort(mesh, cfg, rows=rows)
    per_dev = sorted_rows.reshape(D, -1, 3)
    for d in range(D):
        total = int(counts[d].sum())
        seg = per_dev[d][:total]
        np.testing.assert_array_equal(seg[:, 1], seg[:, 0] ^ 0xA5A5A5A5)
        np.testing.assert_array_equal(seg[:, 2], seg[:, 0] + 1)


def test_numpy_baseline_is_a_true_sort():
    cfg = TeraSortConfig(rows_per_device=1000, payload_words=1)
    rows = generate_rows(cfg, 2, seed=2)
    out = numpy_terasort(rows, 8)
    assert (np.diff(out[:, 0].astype(np.int64)) >= 0).all()
    np.testing.assert_array_equal(np.sort(out[:, 0]), np.sort(rows[:, 0]))


def test_graft_entry_contract():
    """entry() and dryrun_multichip() must work as the driver expects."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out, counts, overflowed = jax.block_until_ready(fn(*args))
    assert out.shape[0] == args[0].shape[0]
    assert not bool(np.asarray(overflowed).any())
    mod.dryrun_multichip(8)


def test_streamed_terasort_multi_round(mesh):
    """Dataset 3.5x one round's capacity: bounded rounds, exact global sort."""
    from sparkrdma_tpu.models.terasort import run_terasort_streamed
    cfg = TeraSortConfig(rows_per_device=512, payload_words=2, out_factor=2)
    rng = np.random.default_rng(0)
    n_rows = int(3.5 * D * cfg.rows_per_device)  # non-divisible tail round
    rows = rng.integers(0, 2**32, size=(n_rows, 3), dtype=np.uint32)
    merged, rounds = run_terasort_streamed(mesh, cfg, rows)
    assert rounds == 4
    got = np.concatenate(merged)
    assert len(got) == n_rows
    prev_max = -1
    for d in range(D):
        keys = merged[d][:, 0].astype(np.int64)
        if len(keys):
            assert (np.diff(keys) >= 0).all()
            assert keys[0] >= prev_max
            prev_max = keys[-1]
    np.testing.assert_array_equal(np.sort(got[:, 0]), np.sort(rows[:, 0]))


def test_streamed_terasort_sentinel_keys_survive(mesh):
    """Real 0xFFFFFFFF keys must not be confused with tail padding."""
    from sparkrdma_tpu.models.terasort import run_terasort_streamed
    cfg = TeraSortConfig(rows_per_device=64, payload_words=1, out_factor=2)
    n_rows = D * 64 + 13  # forces a padded tail round
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 2**32, size=(n_rows, 2), dtype=np.uint32)
    rows[::100, 0] = 0xFFFFFFFF  # sprinkle genuine max keys
    n_max = int((rows[:, 0] == 0xFFFFFFFF).sum())
    merged, _ = run_terasort_streamed(mesh, cfg, rows)
    got = np.concatenate(merged)
    assert len(got) == n_rows
    assert int((got[:, 0] == 0xFFFFFFFF).sum()) == n_max


def test_sort_modes_match_gather(mesh):
    """sort_mode='multisort' (payload through the sort network as rank-1
    operands) and 'colsort' (one stable 2D sort with broadcast keys) are
    bit-identical to the gather path — the stable per-column permutation
    argument colsort relies on is proven here, duplicate keys included
    (payload_words=6, 4096 rows over a 2^32 key space has collisions
    across devices; seed 9 also collides within)."""
    from sparkrdma_tpu.models.terasort import (TeraSortConfig, generate_rows,
                                               run_terasort, verify_terasort)

    rows = generate_rows(TeraSortConfig(rows_per_device=512, payload_words=6),
                         8, seed=9)
    # force key duplicates so tie-handling differences would surface
    # (quantize to the top 12 bits: ~4k distinct keys over 4k rows, still
    # uniform across the device ranges)
    rows[:, 0] &= 0xFFF00000
    outs = {}
    for mode in ("gather", "multisort", "colsort"):
        cfg = TeraSortConfig(rows_per_device=512, payload_words=6,
                             out_factor=2, sort_mode=mode)
        out, counts, _ = run_terasort(mesh, cfg, rows=rows)
        verify_terasort(out, counts, rows, 8)
        outs[mode] = out
    np.testing.assert_array_equal(outs["gather"], outs["multisort"])
    np.testing.assert_array_equal(outs["gather"], outs["colsort"])
