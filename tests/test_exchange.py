"""Data-plane tests on the 8-device virtual CPU mesh.

This is the multi-device integration tier the reference never had
(SURVEY.md §4): the ragged all-to-all exchange is checked against a numpy
oracle for balanced, ragged, skewed, and empty traffic patterns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from sparkrdma_tpu.ops.partition import (
    hash_partition,
    partition_and_count,
    range_partition,
    sample_splitters,
    uniform_splitters,
)
from sparkrdma_tpu.ops.sort import sort_kv, sort_segments
from sparkrdma_tpu.parallel.exchange import make_shuffle_exchange

D = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= D, "conftest must provide 8 virtual devices"
    return Mesh(np.array(devs[:D]), ("shuffle",))


def _numpy_oracle(data: np.ndarray, dest: np.ndarray, capacity: int):
    """Expected per-device received rows, grouped by source device, in local
    row order — the exchange's contract."""
    n_dev = D
    per_dev = data.reshape(n_dev, capacity, *data.shape[1:])
    per_dest = dest.reshape(n_dev, capacity)
    out = []
    for i in range(n_dev):
        rows = [per_dev[j][per_dest[j] == i] for j in range(n_dev)]
        out.append(np.concatenate(rows) if rows else np.zeros((0,)))
    return out


def _run_exchange(mesh, data, dest, capacity, out_factor=1):
    exchange = make_shuffle_exchange(mesh, "shuffle", out_factor=out_factor)
    sharding = jax.NamedSharding(mesh, P("shuffle"))
    data_d = jax.device_put(data, sharding)
    dest_d = jax.device_put(dest, sharding)
    received, counts, offsets, overflowed = jax.block_until_ready(
        exchange(data_d, dest_d))
    return (np.asarray(received).reshape(D, capacity * out_factor, *data.shape[1:]),
            np.asarray(counts), np.asarray(offsets), np.asarray(overflowed))


def _check(mesh, data, dest, capacity, out_factor=1):
    received, counts, offsets, overflowed = _run_exchange(
        mesh, data, dest, capacity, out_factor)
    assert not overflowed.any(), "unexpected overflow flag"
    expect = _numpy_oracle(data, dest, capacity)
    for i in range(D):
        total = counts[i].sum()
        assert total == len(expect[i]), f"device {i}: count mismatch"
        np.testing.assert_array_equal(received[i][:total], expect[i])
        np.testing.assert_array_equal(offsets[i], np.cumsum(counts[i]) - counts[i])
    return received, counts


def test_balanced_exchange(mesh):
    capacity = 64
    rng = np.random.default_rng(0)
    data = rng.integers(0, 2**31, size=D * capacity, dtype=np.int32)
    dest = np.tile(np.repeat(np.arange(D, dtype=np.int32), capacity // D), D)
    _check(mesh, data, dest, capacity)


def test_ragged_random_exchange(mesh):
    capacity = 128
    rng = np.random.default_rng(1)
    data = rng.integers(0, 2**31, size=D * capacity, dtype=np.int32)
    dest = rng.integers(0, D, size=D * capacity).astype(np.int32)
    # random loads can exceed send capacity on some receiver -> 2x headroom
    _check(mesh, data, dest, capacity, out_factor=2)


def test_skewed_exchange(mesh):
    """ALS-style skew: ~90% of all rows target device 3 (receiver needs
    8x headroom — the pattern that motivates multi-round chunking)."""
    capacity = 64
    rng = np.random.default_rng(2)
    data = rng.integers(0, 2**31, size=D * capacity, dtype=np.int32)
    dest = np.where(rng.random(D * capacity) < 0.9, 3,
                    rng.integers(0, D, size=D * capacity)).astype(np.int32)
    _check(mesh, data, dest, capacity, out_factor=D)


def test_empty_senders(mesh):
    """Devices 1..7 send nothing; device 0 broadcasts evenly."""
    capacity = 32
    data = np.arange(D * capacity, dtype=np.int32)
    dest = np.full(D * capacity, -1, dtype=np.int32)  # -1 = padding
    dest[:capacity] = np.repeat(np.arange(D, dtype=np.int32), capacity // D)
    received, counts, _, _ = _run_exchange(mesh, data, dest, capacity)
    for i in range(D):
        assert counts[i].sum() == capacity // D
        # all received rows come from device 0
        assert counts[i][0] == capacity // D
        np.testing.assert_array_equal(
            received[i][:capacity // D],
            np.arange(i * (capacity // D), (i + 1) * (capacity // D)))


def test_all_traffic_to_one_device(mesh):
    """Every device sends capacity//D rows, all to device 0 (fits exactly)."""
    capacity = 16
    data = np.arange(D * capacity, dtype=np.int32)
    dest = np.full(D * capacity, -1, dtype=np.int32)
    for j in range(D):
        dest[j * capacity: j * capacity + capacity // D] = 0
    received, counts, _, _ = _run_exchange(mesh, data, dest, capacity)
    assert counts[0].sum() == capacity  # exactly fills device 0's buffer
    for i in range(1, D):
        assert counts[i].sum() == 0
    expect = np.concatenate([np.arange(j * capacity, j * capacity + capacity // D)
                             for j in range(D)])
    np.testing.assert_array_equal(received[0], expect)


def test_multicolumn_rows(mesh):
    """Rows with payload columns ride along."""
    capacity = 32
    rng = np.random.default_rng(3)
    data = rng.integers(0, 255, size=(D * capacity, 4), dtype=np.int32)
    dest = rng.integers(0, D, size=D * capacity).astype(np.int32)
    _check(mesh, data, dest, capacity, out_factor=2)


# ---- partition/sort op tests (single device) ----

def test_hash_partition_range_and_determinism():
    keys = jnp.arange(10_000, dtype=jnp.uint32)
    p1 = hash_partition(keys, 16)
    p2 = hash_partition(keys, 16)
    assert p1.min() >= 0 and p1.max() < 16
    np.testing.assert_array_equal(p1, p2)
    # roughly balanced
    counts = np.bincount(np.asarray(p1), minlength=16)
    assert counts.min() > 10_000 / 16 * 0.7


def test_range_partition_matches_numpy():
    rng = np.random.default_rng(4)
    keys = rng.integers(0, 2**32, size=5000, dtype=np.uint32)
    splitters = sample_splitters(keys[:500], 8)
    dest = np.asarray(range_partition(jnp.array(keys), jnp.array(splitters)))
    expect = np.searchsorted(splitters, keys, side="right")
    np.testing.assert_array_equal(dest, expect)
    assert dest.max() < 8


def test_uniform_splitters_balanced():
    keys = jnp.array(np.random.default_rng(5).integers(
        0, 2**32, size=50_000, dtype=np.uint32))
    spl = uniform_splitters(8, jnp.uint32)
    dest, counts = partition_and_count(keys, spl, 8)
    c = np.asarray(counts)
    assert c.sum() == 50_000
    assert c.min() > 50_000 / 8 * 0.8


def test_sort_kv():
    rng = np.random.default_rng(6)
    keys = jnp.array(rng.integers(0, 2**31, 1000, dtype=np.int32))
    vals = jnp.arange(1000, dtype=jnp.int32)
    sk, sv = sort_kv(keys, vals)
    np.testing.assert_array_equal(np.asarray(sk), np.sort(np.asarray(keys)))
    # values follow their keys
    np.testing.assert_array_equal(np.asarray(keys)[np.asarray(sv)], np.asarray(sk))


def test_sort_kv_multicolumn():
    rng = np.random.default_rng(7)
    keys = jnp.array(rng.integers(0, 1000, 256, dtype=np.int32))
    vals = jnp.array(rng.integers(0, 255, size=(256, 3), dtype=np.int32))
    sk, sv = sort_kv(keys, vals)
    order = np.argsort(np.asarray(keys), kind="stable")
    np.testing.assert_array_equal(np.asarray(sv), np.asarray(vals)[order])


def test_sort_segments_padding():
    keys = jnp.array([5, 3, 9, 7, 0, 0], dtype=jnp.uint32)
    valid = jnp.array([True, True, True, True, False, False])
    sk, _ = sort_segments(keys, valid)
    np.testing.assert_array_equal(np.asarray(sk)[:4], [3, 5, 7, 9])
    assert (np.asarray(sk)[4:] == np.iinfo(np.uint32).max).all()


# -- dense fixed-slot transport (the 32+ chip fallback; executable on CPU) --


def _run_impl(mesh, data, dest, capacity, out_factor, impl):
    exchange = make_shuffle_exchange(mesh, "shuffle", impl=impl,
                                     out_factor=out_factor)
    sharding = jax.NamedSharding(mesh, P("shuffle"))
    received, counts, _, overflowed = jax.block_until_ready(
        exchange(jax.device_put(data, sharding),
                 jax.device_put(dest, sharding)))
    return (np.asarray(received).reshape(D, capacity * out_factor,
                                         *data.shape[1:]),
            np.asarray(counts), np.asarray(overflowed))


def test_dense_bit_identical_to_gather(mesh):
    """No pair over its slot: dense == gather == oracle, bit for bit."""
    capacity = 64
    rng = np.random.default_rng(7)
    data = rng.integers(0, 2**31, size=(D * capacity, 3), dtype=np.int32)
    dest = rng.integers(0, D, size=D * capacity).astype(np.int32)
    dr, dc, dof = _run_impl(mesh, data, dest, capacity, 2, "dense")
    gr, gc, gof = _run_impl(mesh, data, dest, capacity, 2, "gather")
    np.testing.assert_array_equal(dc, gc)
    np.testing.assert_array_equal(dr, gr)
    assert not dof.any() and not gof.any()
    expect = _numpy_oracle(data, dest, capacity)
    for i in range(D):
        np.testing.assert_array_equal(dr[i][:dc[i].sum()], expect[i])


def test_dense_empty_and_one_hot(mesh):
    capacity = 32
    data = np.arange(D * capacity, dtype=np.int32)
    # nobody sends anything
    dest = np.full(D * capacity, -1, np.int32)
    dr, dc, dof = _run_impl(mesh, data, dest, capacity, 2, "dense")
    assert dc.sum() == 0 and not dof.any()
    # everyone sends everything to device 5; per-pair cap rows need
    # out_factor >= D for the slots to fit
    dest = np.full(D * capacity, 5, np.int32)
    dr, dc, dof = _run_impl(mesh, data, dest, capacity, D, "dense")
    assert dc[5].sum() == D * capacity
    assert not dof.any()
    np.testing.assert_array_equal(
        np.sort(dr[5][:D * capacity].ravel()), data)
    assert all(dc[i].sum() == 0 for i in range(D) if i != 5)


def test_dense_pair_overflow_sets_flag_counts_stay_true(mesh):
    """A single (src, dst) pair exceeding its slot must set the explicit
    overflow flag for the receiver ONLY, while reported counts stay the
    TRUE per-source counts (no poisoning — offsets derived from them
    remain meaningful)."""
    capacity, out_factor = 64, 2
    q = capacity * out_factor // D  # 16 per pair
    data = np.arange(D * capacity, dtype=np.int32)
    dest = np.full(D * capacity, -1, np.int32)
    # device 3 sends q+4 rows to device 0 (pair overflow); total to 0 is
    # far under out_cap
    dest[3 * capacity: 3 * capacity + q + 4] = 0
    dr, dc, dof = _run_impl(mesh, data, dest, capacity, out_factor, "dense")
    assert dof[0], "pair overflow flag not set on receiver"
    assert not dof[1:].any(), "overflow flag leaked to clean receivers"
    # counts are the true sent totals, not a poisoned sentinel
    assert dc[0].sum() == q + 4
    assert dc[0][3] == q + 4
    # unaffected devices stay exact (nothing was sent to them)
    assert all(dc[i].sum() == 0 for i in range(1, D))


def test_capacity_overflow_sets_flag(mesh):
    """Aggregate receive past out_capacity sets the flag on native/gather
    paths too (here gather on CPU): every device sends its full buffer to
    device 0 with out_factor 1."""
    capacity = 16
    data = np.arange(D * capacity, dtype=np.int32)
    dest = np.zeros(D * capacity, np.int32)
    _r, dc, dof = _run_impl(mesh, data, dest, capacity, 1, "gather")
    assert dof[0], "capacity overflow flag not set"
    assert not dof[1:].any()
    assert dc[0].sum() == D * capacity  # true counts still reported


# -- ring transport in the oracle matrix (ADVICE r5) ---------------------
#
# test_ring_exchange.py proves ring == gather/dense; these check the
# ring transport against the NUMPY ORACLE directly, through the same
# traffic-pattern matrix the other impls face, so a regression that
# broke ring and dense in lockstep would still be caught.


def _check_impl(mesh, data, dest, capacity, impl, out_factor=1):
    exchange = make_shuffle_exchange(mesh, "shuffle", impl=impl,
                                     out_factor=out_factor)
    sharding = jax.NamedSharding(mesh, P("shuffle"))
    received, counts, offsets, overflowed = jax.block_until_ready(
        exchange(jax.device_put(data, sharding),
                 jax.device_put(dest, sharding)))
    received = np.asarray(received).reshape(D, capacity * out_factor,
                                            *data.shape[1:])
    counts, offsets = np.asarray(counts), np.asarray(offsets)
    assert not np.asarray(overflowed).any(), "unexpected overflow flag"
    expect = _numpy_oracle(data, dest, capacity)
    for i in range(D):
        total = counts[i].sum()
        assert total == len(expect[i]), f"device {i}: count mismatch"
        np.testing.assert_array_equal(received[i][:total], expect[i])
        np.testing.assert_array_equal(offsets[i],
                                      np.cumsum(counts[i]) - counts[i])


@pytest.mark.parametrize("impl", ["ring_interpret", "dense", "gather"])
def test_impl_matrix_balanced_vs_oracle(mesh, impl):
    capacity = 32
    rng = np.random.default_rng(21)
    data = rng.integers(0, 2**31, size=D * capacity, dtype=np.int32)
    dest = np.tile(np.repeat(np.arange(D, dtype=np.int32),
                             capacity // D), D)
    _check_impl(mesh, data, dest, capacity, impl)


@pytest.mark.parametrize("impl", ["ring_interpret", "dense", "gather"])
def test_impl_matrix_ragged_vs_oracle(mesh, impl):
    capacity = 32
    rng = np.random.default_rng(22)
    data = rng.integers(0, 2**31, size=(D * capacity, 2), dtype=np.int32)
    dest = rng.integers(0, D, size=D * capacity).astype(np.int32)
    # out_factor 4: the fixed-slot transports (dense/ring) cap each
    # (src, dst) PAIR at capacity*out_factor/D rows — random raggedness
    # needs pair headroom, not just aggregate headroom
    _check_impl(mesh, data, dest, capacity, impl, out_factor=4)


@pytest.mark.parametrize("impl", ["ring_interpret", "dense", "gather"])
def test_impl_matrix_empty_senders_vs_oracle(mesh, impl):
    capacity = 16
    data = np.arange(D * capacity, dtype=np.int32)
    dest = np.full(D * capacity, -1, dtype=np.int32)  # -1 = padding
    dest[:capacity] = np.repeat(np.arange(D, dtype=np.int32),
                                capacity // D)
    _check_impl(mesh, data, dest, capacity, impl)


def test_terasort_ring_interpret_matches_numpy_baseline(mesh):
    """End-to-end terasort over the ring transport against the NUMPY
    baseline (test_ring_exchange.py checks ring == gather; this pins
    the ring path to the ground-truth sort itself: full verification
    plus the exact per-partition key sequence — payload order under
    equal keys is the one legitimate divergence from the stable CPU
    sort, so keys compare exactly and rows verify structurally)."""
    from sparkrdma_tpu.models.terasort import (
        TeraSortConfig, generate_rows, numpy_terasort, run_terasort,
        verify_terasort)
    cfg = TeraSortConfig(rows_per_device=128, payload_words=2,
                         out_factor=2)
    rows = generate_rows(cfg, D, seed=23)
    out, counts, _ = run_terasort(mesh, cfg, impl="ring_interpret",
                                  rows=rows)
    verify_terasort(out, counts, rows, D)
    want = numpy_terasort(rows, D)
    per_dev = out.reshape(D, -1, out.shape[-1])
    got = np.concatenate([per_dev[i][:counts[i].sum()] for i in range(D)])
    np.testing.assert_array_equal(got[:, 0], want[:, 0])
