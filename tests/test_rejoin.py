"""Elastic rejoin: an executor process dies AFTER committing map outputs;
a replacement starts over the same spill directory, recovers the committed
files from their sidecar indexes, re-publishes under its new slot, and
reducers complete without recomputation — durability the reference
delegates to Spark's index files + stage retry."""

import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager

CONF = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2)


def test_executor_rejoin_recovers_outputs(tmp_path):
    driver = TpuShuffleManager(CONF, is_driver=True)
    spill_dir1 = str(tmp_path / "e1")
    execs = [TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(2)]
    for ex in execs:
        ex.executor.wait_for_members(2)
    try:
        handle = driver.register_shuffle(1, num_maps=4, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        rng = np.random.default_rng(0)
        truth = []
        for m in range(4):
            keys = rng.integers(0, 9999, 300).astype(np.uint64)
            w = execs[m % 2].get_writer(handle, m)
            w.write_batch(keys)
            w.close()
            truth.append(keys)
        expect = np.sort(np.concatenate(truth))

        # executor 1 "crashes": endpoint dies, disk survives
        lost = execs[1].executor.manager_id
        execs[1].executor.stop()
        if execs[1].block_server is not None:
            execs[1].block_server.stop()
        driver.driver.remove_member(lost)
        time.sleep(0.3)
        execs[0].executor.invalidate_shuffle(1)
        with pytest.raises(FetchFailedError):
            execs[0].get_reader(handle, 0, 4).read_all()

        # replacement executor over the SAME spill dir: recover + republish
        rejoined = TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                                     executor_id="1b",
                                     spill_dir=str(tmp_path / "e1"))
        rejoined.executor.wait_for_members(3)
        recovered = rejoined.recover_and_republish()
        assert sorted(m for m, _ in recovered[1]) == [1, 3]  # executor 1's maps
        time.sleep(0.2)

        execs[0].executor.invalidate_shuffle(1)
        keys, _ = execs[0].get_reader(handle, 0, 4).read_all()
        np.testing.assert_array_equal(np.sort(keys), expect)
        rejoined.stop()
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_recover_ignores_uncommitted(tmp_path):
    """Data files without an index (crash mid-commit) are not recovered."""
    from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver
    d = tmp_path / "r"
    d.mkdir()
    (d / "shuffle_1_0.data").write_bytes(b"x" * 64)  # no index
    (d / "shuffle_1_1.data").write_bytes(b"y" * 32)
    np.array([32], dtype=np.uint64).tofile(str(d / "shuffle_1_1.data.index"))
    (d / "shuffle_2_0.data").write_bytes(b"")  # empty data, stale index
    np.array([64], dtype=np.uint64).tofile(str(d / "shuffle_2_0.data.index"))
    r = TpuShuffleBlockResolver(str(d))
    recovered = r.recover()
    assert [m for m, _ in recovered[1]] == [1] and list(recovered) == [1]
    assert r.get_output_table(1, 1) is not None
    r.stop()


def test_standalone_shuffle_service_process(tmp_path):
    """The ``shuffle-service`` CLI as a real PROCESS: it adopts a dead
    executor's spill directory, re-publishes the committed outputs, and
    reducers complete without recomputation (the external-shuffle-service
    role the reference cannot play — its MR registrations die with the
    executor)."""
    import os
    import subprocess
    import sys

    driver = TpuShuffleManager(CONF, is_driver=True)
    execs = [TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(2)]
    for ex in execs:
        ex.executor.wait_for_members(2)
    svc = None
    try:
        handle = driver.register_shuffle(5, num_maps=4, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        rng = np.random.default_rng(3)
        truth = []
        for m in range(4):
            keys = rng.integers(0, 9999, 200).astype(np.uint64)
            w = execs[m % 2].get_writer(handle, m)
            w.write_batch(keys)
            w.close()
            truth.append(keys)
        expect = np.sort(np.concatenate(truth))

        lost = execs[1].executor.manager_id
        execs[1].executor.stop()
        if execs[1].block_server is not None:
            execs[1].block_server.stop()
        driver.driver.remove_member(lost)
        time.sleep(0.3)

        host, port = driver.driver_addr
        env = dict(os.environ, PYTHONPATH=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        svc = subprocess.Popen(
            [sys.executable, "-m", "sparkrdma_tpu", "shuffle-service",
             f"{host}:{port}", str(tmp_path / "e1"), "svc1"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        # banner read with a deadline: a wedged service must FAIL the
        # test, not hang the suite on a blocking readline
        import queue
        import threading

        banner: "queue.Queue[str]" = queue.Queue()
        threading.Thread(target=lambda: banner.put(svc.stdout.readline()),
                         daemon=True).start()
        line = banner.get(timeout=30)
        assert "serving 2 recovered map outputs" in line, line

        execs[0].executor.invalidate_shuffle(5)
        keys, _ = execs[0].get_reader(handle, 0, 4).read_all()
        np.testing.assert_array_equal(np.sort(keys), expect)
    finally:
        if svc is not None:
            svc.terminate()
            svc.wait(timeout=10)
        for ex in execs:
            ex.stop()
        driver.stop()
