"""Concurrency stress: many shuffles in flight at once through one cluster
— overlapping writers, readers, publishes, and native-server fetches from
competing threads. The reference's thread-safety is 'by construction'
(SURVEY.md §5, j.u.c. everywhere, never tested); here it's exercised.
"""

import threading

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.utils.trace import Tracer

CONF = TpuShuffleConf(connect_timeout_ms=5000,
                      shuffle_read_block_size="8k")


def test_concurrent_shuffles(tmp_path):
    driver = TpuShuffleManager(CONF, is_driver=True)
    execs = [TpuShuffleManager(CONF, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(3)]
    for ex in execs:
        ex.executor.wait_for_members(3)
    n_shuffles, n_maps, n_parts = 6, 4, 6
    errors = []

    def run_one(shuffle_id):
        try:
            handle = driver.register_shuffle(
                shuffle_id, n_maps, n_parts, PartitionerSpec("modulo"),
                row_payload_bytes=4)
            rng = np.random.default_rng(shuffle_id)
            total = 0
            for m in range(n_maps):
                keys = rng.integers(0, 10_000, 800).astype(np.uint64)
                pay = np.full((800, 4), shuffle_id % 256, dtype=np.uint8)
                w = execs[(shuffle_id + m) % 3].get_writer(handle, m)
                w.write_batch(keys, pay)
                w.close()
                total += len(keys)
            # two concurrent readers per shuffle, disjoint ranges
            got = []

            def read(lo, hi):
                r = execs[(shuffle_id + lo) % 3].get_reader(handle, lo, hi)
                k, p = r.read_all()
                assert (p == shuffle_id % 256).all(), "cross-shuffle bleed!"
                got.append(len(k))

            t1 = threading.Thread(target=read, args=(0, 3))
            t2 = threading.Thread(target=read, args=(3, 6))
            t1.start(); t2.start(); t1.join(); t2.join()
            assert sum(got) == total, f"shuffle {shuffle_id}: {sum(got)} != {total}"
        except Exception as e:  # noqa: BLE001
            errors.append((shuffle_id, repr(e)))

    threads = [threading.Thread(target=run_one, args=(s,))
               for s in range(1, n_shuffles + 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not errors, errors
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_tracer_records_spans(tmp_path):
    trace_path = str(tmp_path / "trace.json")
    conf = TpuShuffleConf(trace_file=trace_path, connect_timeout_ms=5000)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=f"t{i}",
                               spill_dir=str(tmp_path / f"t{i}"))
             for i in range(2)]
    for ex in execs:
        ex.executor.wait_for_members(2)
    try:
        handle = driver.register_shuffle(1, 2, 2, PartitionerSpec("modulo"))
        for m in range(2):
            w = execs[m].get_writer(handle, m)
            w.write_batch(np.arange(100, dtype=np.uint64))
            w.close()
        execs[0].get_reader(handle, 0, 2).read_all()
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
    import json
    trace = json.load(open(trace_path + ".t0.json"))  # exec 0's dump
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"writer.commit", "writer.publish", "fetch.driver_table"} <= names
    # the dataplane span: "fetch.blocks" per request on the Python
    # receive path, "fetch.vectored" when the native fetch engine lands
    # the payloads (the default where the .so is built)
    assert {"fetch.blocks", "fetch.vectored"} & names
    # chrome trace format essentials
    span = next(e for e in trace["traceEvents"]
                if e["name"] in ("fetch.blocks", "fetch.vectored"))
    assert span["ph"] == "X" and span["dur"] >= 0


def test_null_tracer_is_free():
    from sparkrdma_tpu.utils import trace
    with trace.NULL.span("x"):
        pass
    trace.NULL.instant("y")
    assert trace.NULL._events == []
