"""Model workload tests on the 8-device virtual mesh: PageRank (iterative),
ALS (zipf skew + chunked exchange), shuffle join — BASELINE.md configs
#3/#4/#5 at test scale, all oracle-verified."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from sparkrdma_tpu.models.als import (
    ALSConfig,
    als_half_step,
    generate_ratings,
    numpy_als_half_step,
)
from sparkrdma_tpu.models.join import (
    JoinConfig,
    generate_tables,
    numpy_join,
    run_join,
)
from sparkrdma_tpu.models.pagerank import (
    PageRankConfig,
    numpy_pagerank,
    random_graph,
    run_pagerank,
)
from sparkrdma_tpu.parallel.exchange import chunked_exchange

D = 8


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:D]), ("shuffle",))


# ---- chunked exchange (the skew machinery) ----

def test_chunked_exchange_extreme_skew(mesh):
    """All rows from all devices target device 0; quota bounds each round."""
    per_dev = 64
    rows = np.arange(D * per_dev, dtype=np.uint32).reshape(-1, 1)
    counts = np.zeros((D, D), dtype=np.int32)
    counts[:, 0] = per_dev  # everything -> device 0, already "grouped"
    received, rounds = chunked_exchange(mesh, "shuffle", rows, counts, quota=16)
    assert rounds == 4  # 64 / 16
    assert len(received[0]) == D * per_dev
    for d in range(1, D):
        assert len(received[d]) == 0
    # every row arrives exactly once
    np.testing.assert_array_equal(np.sort(received[0].ravel()),
                                  np.arange(D * per_dev, dtype=np.uint32))


def test_chunked_exchange_mixed_traffic(mesh):
    rng = np.random.default_rng(0)
    per_dev = 50
    rows = np.zeros((D * per_dev, 2), dtype=np.uint32)
    counts = np.zeros((D, D), dtype=np.int32)
    expect = [[] for _ in range(D)]
    for d in range(D):
        dest = np.sort(rng.integers(0, D, size=per_dev))
        seg = np.stack([dest.astype(np.uint32),
                        rng.integers(0, 2**31, per_dev, dtype=np.uint32)], 1)
        rows[d * per_dev:(d + 1) * per_dev] = seg
        counts[d] = np.bincount(dest, minlength=D)
        for i in range(D):
            expect[i].append(seg[dest == i])
    received, rounds = chunked_exchange(mesh, "shuffle", rows, counts, quota=7)
    assert rounds > 1
    for i in range(D):
        # exact source-grouped order: same contract as the one-shot exchange
        np.testing.assert_array_equal(received[i], np.concatenate(expect[i]))


# ---- PageRank ----

def test_pagerank_matches_oracle(mesh):
    cfg = PageRankConfig(num_vertices=64, edges_per_device=96, out_factor=D)
    edges, _, _ = random_graph(cfg, D, seed=3)
    ranks = run_pagerank(mesh, cfg, iterations=5, seed=3)
    expect = numpy_pagerank(edges, cfg.num_vertices, cfg.damping, 5)
    np.testing.assert_allclose(ranks, expect, rtol=1e-4)
    assert abs(ranks.sum() - 1.0) < 0.2  # probability-ish mass


def test_pagerank_converges(mesh):
    cfg = PageRankConfig(num_vertices=32, edges_per_device=64, out_factor=D)
    r5 = run_pagerank(mesh, cfg, iterations=5, seed=1)
    r20 = run_pagerank(mesh, cfg, iterations=20, seed=1)
    r21 = run_pagerank(mesh, cfg, iterations=21, seed=1)
    assert np.abs(r21 - r20).max() < np.abs(r5 - r20).max()


# ---- ALS ----

def test_als_skewed_half_step_matches_oracle(mesh):
    cfg = ALSConfig(num_users=64, num_items=16, rank=4, zipf_a=1.3)
    ratings = generate_ratings(cfg, D, per_device=80, seed=5)
    rng = np.random.default_rng(5)
    user_factors = rng.normal(size=(cfg.num_users, cfg.rank)).astype(np.float32)
    item_factors, rounds = als_half_step(mesh, cfg, ratings, user_factors,
                                         quota=16)
    assert rounds > 1  # zipf skew must force multiple rounds
    expect = numpy_als_half_step(ratings, user_factors, cfg)
    np.testing.assert_allclose(item_factors, expect, rtol=2e-2, atol=1e-3)


def test_als_user_half_step_matches_oracle(mesh):
    """The user-side half-step is the same math with columns swapped —
    validated against the item-side oracle on a column-swapped copy."""
    cfg = ALSConfig(num_users=64, num_items=16, rank=4, zipf_a=1.3)
    ratings = generate_ratings(cfg, D, per_device=80, seed=6)
    rng = np.random.default_rng(6)
    item_factors = rng.normal(size=(cfg.num_items, cfg.rank)).astype(np.float32)
    user_factors, _ = als_half_step(mesh, cfg, ratings, item_factors,
                                    quota=16, key_col=1)
    from dataclasses import replace
    swapped_cfg = replace(cfg, num_users=cfg.num_items,
                          num_items=cfg.num_users)
    expect = numpy_als_half_step(ratings[:, [1, 0, 2]], item_factors,
                                 swapped_cfg)
    np.testing.assert_allclose(user_factors, expect, rtol=2e-2, atol=1e-3)


def test_als_full_alternating_loop_converges(mesh):
    """The full users⇄items loop must actually FIT the ratings: RMSE
    drops hard from the random init and keeps improving (config #5's
    workload semantics, not just its shuffle shape)."""
    from sparkrdma_tpu.models.als import run_als

    cfg = ALSConfig(num_users=96, num_items=24, rank=6, zipf_a=1.3)
    ratings = generate_ratings(cfg, D, per_device=160, seed=8)
    _uf, _if, history, rounds = run_als(mesh, cfg, ratings, quota=32,
                                        iterations=4, seed=8)
    assert rounds >= 8  # two skewed shuffles per sweep, multiple rounds
    assert history[1] < history[0] * 0.5, history
    # monotone improvement every sweep; unstructured uniform ratings
    # floor near their intrinsic noise, so the bound is relative
    assert all(b <= a for a, b in zip(history[1:], history[2:])), history
    assert history[-1] < history[0] * 0.3, f"did not fit: {history}"


# ---- join ----

def test_join_matches_oracle(mesh):
    cfg = JoinConfig(rows_per_device_left=128, rows_per_device_right=96,
                     key_space=256, out_factor=4)
    left, right = generate_tables(cfg, D, seed=7)
    matches, pair_sum = run_join(mesh, cfg, seed=7)
    exp_matches, exp_sum = numpy_join(left, right)
    assert matches == exp_matches
    assert pair_sum == exp_sum


def test_join_no_matches(mesh):
    cfg = JoinConfig(rows_per_device_left=32, rows_per_device_right=32,
                     key_space=4, out_factor=D)
    left, right = generate_tables(cfg, D, seed=9)
    left[:, 0] = 0
    right[:, 0] = 1

    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparkrdma_tpu.models.join import make_join_step
    step = make_join_step(mesh, "shuffle", cfg)
    shard = NamedSharding(mesh, P("shuffle"))
    counts, sums, _ = step(jax.device_put(left, shard),
                           jax.device_put(right, shard))
    assert int(np.asarray(counts).sum()) == 0
    assert int(np.asarray(sums).sum()) == 0


def test_chunked_exchange_device_resident_at_als_scale(mesh):
    """VERDICT r2 item 3: >=64 rounds on the 8-device mesh with the round
    loop doing no per-round host data work — outputs accumulate in device
    buffers and cross to the host once. Asserts exactness, bounded host
    allocations during the loop, and logs the legacy-hostloop A/B time."""
    import time
    import tracemalloc

    from sparkrdma_tpu.parallel.exchange import (
        NamedSharding,
        P,
        jax as jax_mod,
        make_chunked_exchange,
        make_chunked_exchange_acc,
    )

    quota = 32
    heavy = 64 * quota  # pair (s, 0) traffic -> exactly 64 rounds
    light = 40
    width = 8
    rng = np.random.default_rng(5)
    counts = np.full((D, D), light, dtype=np.int32)
    counts[:, 0] = heavy
    total = int(counts.sum())
    rows = np.zeros((D, heavy + (D - 1) * light, width), dtype=np.uint32)
    expect = [[] for _ in range(D)]
    for s in range(D):
        segs = []
        for d in range(D):  # destination-grouped layout per source
            seg = rng.integers(0, 2**31, (counts[s, d], width),
                               dtype=np.uint32)
            segs.append(seg)
            expect[d].append(seg)
        rows[s] = np.concatenate(segs)
    rows = rows.reshape(D * rows.shape[1], width)

    chunked_exchange(mesh, "shuffle", rows, counts, quota=quota)  # warm

    tracemalloc.start()
    t0 = time.monotonic()
    received, rounds = chunked_exchange(mesh, "shuffle", rows, counts,
                                        quota=quota)
    new_time = time.monotonic() - t0
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()

    assert rounds == 64
    for d in range(D):
        # exact source-major contract straight out of the device buffer
        np.testing.assert_array_equal(received[d], np.concatenate(expect[d]))
    # the loop must not have staged the dataset on the host per round:
    # peak python/numpy allocations stay near the ONE final transfer of
    # the padded device buffer (D*cap_out rows; skew pads it), far under
    # 64 rounds x per-round staging
    final_bytes = total * width * 4
    cap_out = int(counts.sum(axis=0).max())
    padded_bytes = D * cap_out * width * 4
    assert peak < padded_bytes + 2 * final_bytes + (1 << 20), \
        f"host peak {peak} suggests per-round host staging"

    # legacy host-loop A/B (the pre-rework driver, reconstructed): pulls
    # every round's full mesh output to the host and slices O(D^2) segments
    round_fn = make_chunked_exchange(mesh, "shuffle", quota)
    sharding = NamedSharding(mesh, P("shuffle"))
    grouped_d = jax_mod.device_put(rows, sharding)
    counts_d = jax_mod.device_put(counts.reshape(-1), sharding)
    round_fn(grouped_d, counts_d, 0)  # warm (compile) before timing
    t0 = time.monotonic()
    per_source = [[[] for _ in range(D)] for _ in range(D)]
    for r in range(rounds):
        out, rc = round_fn(grouped_d, counts_d, r)
        out = np.asarray(out).reshape(D, quota * D, width)
        rc = np.asarray(rc)
        for d in range(D):
            start = 0
            for j in range(D):
                c = int(rc[d][j])
                if c:
                    per_source[d][j].append(out[d][start:start + c])
                start += c
    legacy = [np.concatenate([seg for j in range(D)
                              for seg in per_source[d][j]])
              for d in range(D)]
    legacy_time = time.monotonic() - t0
    for d in range(D):
        np.testing.assert_array_equal(received[d], legacy[d])
    print(f"\nchunked 64 rounds: device-resident {new_time:.3f}s vs "
          f"legacy host-loop {legacy_time:.3f}s "
          f"(host peak {peak / 1e6:.1f} MB, moved {final_bytes / 1e6:.1f} MB)")
