"""Disaggregated cold-tier tests (shuffle/cold_tier.py).

Units (the blob-store contract, the tiered directory wire, the tiering
service's upload/retry/tombstone/ledger discipline, orphan reap), the
blob fault matrix, and the e2e cluster suite: resolve-order precedence,
upload/restore byte parity across both coalesce dataplanes, the
FULL-FLEET-RESTART acceptance (every executor dies after map finalize;
a fresh fleet reduces byte-identically from the cold tier with ZERO map
re-executions), CRC-bad-blob degradation, drain-to-cold vs
drain-to-peer, and HA failover preserving the TieredDirectory.
``COLD_SEED`` varies the generated data for scripts/run_chaos.sh
CHAOS_COLD sweeps.
"""

import errno
import os
import time
import zlib

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.faults import (
    BLOB_CORRUPT,
    BLOB_SLOW,
    BLOB_UNAVAILABLE,
    QUOTA_EXHAUSTED,
    TORN_UPLOAD,
    BlobFaultInjector,
)
from sparkrdma_tpu.shuffle.cold_tier import (
    FSBlobStore,
    TieredDirectory,
    TieredEntry,
    TieringService,
    open_store,
    wait_for_tiered_coverage,
)
from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.push_merge import (
    bitmap_new,
    bitmap_set,
    wait_for_coverage,
)
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader
from sparkrdma_tpu.shuffle.recovery import run_map_stage, run_reduce_with_retry

SEED = int(os.environ.get("COLD_SEED", "0"))


def _cov(num_maps, *maps):
    b = bitmap_new(num_maps)
    for m in maps:
        bitmap_set(b, m)
    return bytes(b)


# -- units: directory + entry wire ----------------------------------------


def test_tiered_entry_and_directory_roundtrip():
    e = TieredEntry(3, "7/p3/seg_42", 128, 0xDEADBEEF, _cov(6, 1, 4))
    back, off = TieredEntry.from_bytes(e.to_bytes())
    assert off == len(e.to_bytes())
    assert (back.partition_id, back.blob_key, back.nbytes,
            back.crc32) == (3, "7/p3/seg_42", 128, 0xDEADBEEF)
    assert back.covered_maps(6) == [1, 4]
    assert back.covers(4) and not back.covers(0)

    d = TieredDirectory()
    d.apply(TieredEntry(0, "1/p0/seg_10", 100, 1, _cov(6, 0, 1, 2)))
    d.apply(TieredEntry(0, "1/p0/drain_m5_1", 10, 2, _cov(6, 5)))
    d.apply(TieredEntry(2, "1/p2/seg_11", 50, 3, _cov(6, 0, 1)))
    # widest coverage first, key tie-break; union coverage per partition
    assert [e.blob_key for e in d.entries(0)] == ["1/p0/seg_10",
                                                  "1/p0/drain_m5_1"]
    assert d.partitions() == [0, 2] and len(d) == 3
    assert [e.blob_key for e in d.covering(5, 0)] == ["1/p0/drain_m5_1"]
    assert d.covering(5, 2) == []
    # re-publish of the same key overwrites (newest upload wins)
    d.apply(TieredEntry(0, "1/p0/drain_m5_1", 11, 9, _cov(6, 5)))
    assert len(d) == 3
    assert d.covering(5, 0)[0].nbytes == 11
    # wire round trip
    d2 = TieredDirectory.from_bytes(d.to_bytes())
    assert d2.to_bytes() == d.to_bytes() and len(d2) == 3
    # a repair publish for map 1 drops every entry covering it
    assert d.drop_map(1) == 2
    assert d.partitions() == [0]
    assert TieredDirectory.from_bytes(b"").partitions() == []


# -- units: the blob-store contract ---------------------------------------


def test_fs_blob_store_contract(tmp_path):
    store = FSBlobStore(str(tmp_path / "cold"))
    etag = store.put("1/p0/seg_1", b"hello")
    # etags are content-derived: a re-put of identical bytes is stable
    assert store.put("1/p0/seg_1", b"hello") == etag
    assert store.put("1/p0/seg_2", b"other") != etag
    assert store.get("1/p0/seg_1") == b"hello"
    with pytest.raises(KeyError):
        store.get("1/p0/absent")
    # list is prefix-scoped, sorted, with sizes + mtimes
    store.put("2/p0/seg_1", b"x" * 7)
    metas = store.list("1/")
    assert [m.key for m in metas] == ["1/p0/seg_1", "1/p0/seg_2"]
    assert metas[0].size == 5 and metas[0].etag == etag
    assert metas[0].mtime > 0
    assert [m.key for m in store.list()] == ["1/p0/seg_1", "1/p0/seg_2",
                                             "2/p0/seg_1"]
    # delete is idempotent
    assert store.delete("1/p0/seg_2")
    assert not store.delete("1/p0/seg_2")
    assert [m.key for m in store.list("1/")] == ["1/p0/seg_1"]
    # the key grammar rejects escapes
    for bad in ("", "/abs", "a/../b"):
        with pytest.raises(ValueError):
            store.put(bad, b"")


def test_open_store_gating(tmp_path):
    assert open_store(TpuShuffleConf(cold_tier=False)) is None
    store = open_store(TpuShuffleConf(
        cold_tier=True, cold_tier_path=str(tmp_path / "c")))
    assert isinstance(store, FSBlobStore)
    assert store.root == str(tmp_path / "c")


# -- units: the blob fault matrix -----------------------------------------


def test_blob_fault_matrix_unit(tmp_path):
    store = FSBlobStore(str(tmp_path / "cold"))
    inj = BlobFaultInjector(seed=SEED)
    inj.install()
    try:
        # unavailable: the op raises OSError (store down)
        inj.add(BLOB_UNAVAILABLE, op="put", times=1)
        with pytest.raises(OSError):
            store.put("1/a", b"data")
        assert inj.fired_count(BLOB_UNAVAILABLE) == 1
        store.put("1/a", b"data")  # times=1: the window closed

        # quota: EDQUOT, distinguishable from a generic outage
        inj.add(QUOTA_EXHAUSTED, op="put", key_substr="1/q", times=1)
        with pytest.raises(OSError) as ei:
            store.put("1/q", b"data")
        assert ei.value.errno == errno.EDQUOT

        # torn upload: some bytes land, then the put errors — and the
        # torn middle is NEVER visible (atomicity half of the contract)
        inj.add(TORN_UPLOAD, op="put", key_substr="1/t", times=1,
                torn_bytes=2)
        with pytest.raises(OSError):
            store.put("1/t", b"full-payload")
        with pytest.raises(KeyError):
            store.get("1/t")
        assert all("1/t" not in m.key for m in store.list())

        # corrupt at rest: the put commits, rot lands after — the
        # published CRC covers the CLEAN bytes, restore-side
        # verification owns detection
        clean = b"z" * 64
        inj.add(BLOB_CORRUPT, op="put", key_substr="1/r", times=1)
        store.put("1/r", clean)
        rotten = store.get("1/r")
        assert len(rotten) == len(clean)
        assert zlib.crc32(rotten) != zlib.crc32(clean)

        # slow: the op stalls on the caller
        inj.add(BLOB_SLOW, op="get", key_substr="1/a", times=1,
                delay_s=0.05)
        t0 = time.monotonic()
        assert store.get("1/a") == b"data"
        assert time.monotonic() - t0 >= 0.05
    finally:
        inj.uninstall()


# -- units: the tiering service -------------------------------------------


class _Ledger:
    def __init__(self):
        self.balance = {}

    def charge(self, tenant, n):
        self.balance[tenant] = self.balance.get(tenant, 0) + n

    def release(self, tenant, n):
        self.balance[tenant] = self.balance.get(tenant, 0) - n


class _FakeResolver:
    """Just enough resolver for TieringService: token-addressed segment
    bytes + the tenant disk ledger."""

    def __init__(self, blocks=None):
        self.blocks = dict(blocks or {})
        self.disk_ledger = _Ledger()

    def read_block(self, sid, token, off, ln):
        seg = self.blocks.get((sid, token))
        return None if seg is None else seg[off:off + ln]

    def tenant_of(self, sid):
        return 0


def _svc(tmp_path, resolver, published, **conf_kw):
    base = dict(cold_tier=True, cold_tier_path=str(tmp_path / "cold"),
                retry_backoff_base_ms=1, retry_backoff_cap_ms=5,
                tier_retry_budget=2)
    base.update(conf_kw)
    conf = TpuShuffleConf(**base)
    store = open_store(conf)
    return TieringService(store, resolver, conf, publish=published.append)


def _merged_msg(sid, partition, token, seg, ranges, num_maps=4, maps=(0,)):
    served = b"".join(seg[off:off + ln] for off, ln in ranges)
    return M.MergedPublishMsg(sid, partition, 0, token, len(served),
                              zlib.crc32(served), _cov(num_maps, *maps),
                              list(ranges))


def test_tiering_service_uploads_surviving_ranges_only(tmp_path):
    # the ledger file holds superseded bytes too; only the published
    # ranges (fence supersession already resolved) may tier
    seg = b"DEADbeefSURVIVES"
    resolver = _FakeResolver({(7, 42): seg})
    published = []
    svc = _svc(tmp_path, resolver, published)
    try:
        msg = _merged_msg(7, 2, 42, seg, [(0, 4), (8, 8)], maps=(0, 3))
        assert svc.submit(msg)
        assert svc.drain(5)
        assert svc.uploads_done == 1 and not svc.uploads_failed
        (out,) = published
        assert isinstance(out, M.TieredPublishMsg)
        assert (out.shuffle_id, out.partition_id) == (7, 2)
        assert out.blob_key == "7/p2/seg_0_42"
        blob = svc.store.get(out.blob_key)
        assert blob == b"DEADSURVIVES"
        assert out.nbytes == len(blob)
        assert zlib.crc32(blob) == out.crc32 & 0xFFFFFFFF
        # the cold bytes were charged to the owning tenant
        assert resolver.disk_ledger.balance[0] == len(blob)
        # a locally-rotten segment never tiers (CRC mismatch pre-upload)
        bad = M.MergedPublishMsg(7, 3, 0, 42, 4, 12345,
                                 _cov(4, 1), [(0, 4)])
        assert svc.submit(bad)
        assert svc.drain(5)
        assert svc.uploads_failed == 1 and len(published) == 1
    finally:
        svc.stop()


def test_tiering_service_retry_and_permanent_failure(tmp_path):
    seg = b"retry-me"
    resolver = _FakeResolver({(1, 5): seg})
    published = []
    svc = _svc(tmp_path, resolver, published)
    inj = BlobFaultInjector(seed=SEED)
    inj.install()
    try:
        # one transient outage: the retry budget absorbs it
        inj.add(BLOB_UNAVAILABLE, op="put", times=1)
        assert svc.submit(_merged_msg(1, 0, 5, seg, [(0, 8)]))
        assert svc.drain(5)
        assert svc.uploads_done == 1 and len(published) == 1
        assert inj.fired_count(BLOB_UNAVAILABLE) == 1
        # a persistent outage exhausts the budget: the segment stays
        # hot-only, nothing publishes, nothing raises (graceful degrade)
        inj.add(BLOB_UNAVAILABLE, op="put")
        assert svc.submit(_merged_msg(1, 1, 5, seg, [(0, 8)]))
        assert svc.drain(5)
        assert svc.uploads_failed == 1 and len(published) == 1
        assert inj.fired_count(BLOB_UNAVAILABLE) == 1 + 1 + svc.retry_budget
    finally:
        inj.uninstall()
        svc.stop()


def test_tiering_service_budget_sheds_not_blocks(tmp_path):
    seg = b"s" * 64
    resolver = _FakeResolver({(1, 1): seg})
    published = []
    svc = _svc(tmp_path, resolver, published)
    svc.max_inflight_bytes = 80
    inj = BlobFaultInjector(seed=SEED)
    inj.install()
    try:
        # hold the first upload in flight; the second would breach the
        # in-flight byte budget and must SHED (never queue unboundedly)
        inj.add(BLOB_SLOW, op="put", times=1, delay_s=0.3)
        assert svc.submit(_merged_msg(1, 0, 1, seg, [(0, 64)]))
        deadline = time.monotonic() + 2
        while svc._inflight_bytes < 64 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert not svc.submit(_merged_msg(1, 1, 1, seg, [(0, 64)]))
        assert svc.uploads_shed == 1
        assert svc.drain(5)
        assert svc.uploads_done == 1
    finally:
        inj.uninstall()
        svc.stop()


def test_tiering_service_tombstone_ledger_and_drain_rows(tmp_path):
    resolver = _FakeResolver()
    published = []
    svc = _svc(tmp_path, resolver, published)
    try:
        # drain rows: synchronous, one blob per only-copy row
        assert svc.tier_row(9, 1, 3, fence=2, data=b"row-bytes",
                            num_maps=4)
        assert svc.rows_tiered == 1
        (out,) = published
        assert out.blob_key == "9/p1/drain_m3_2"
        entry, _ = (TieredEntry(out.partition_id, out.blob_key, out.nbytes,
                                out.crc32, out.covered), 0)
        assert entry.covered_maps(4) == [3]
        assert resolver.disk_ledger.balance[0] == len(b"row-bytes")
        # drop: tombstone the id, reap its blobs, repay the ledger
        svc.drop_shuffle(9)
        assert resolver.disk_ledger.balance[0] == 0
        assert svc.store.list("9/") == []
        # dead shuffle: submits and rows are refused
        assert not svc.tier_row(9, 0, 0, 1, b"x", 1)
        assert not svc.submit(_merged_msg(9, 0, 1, b"abcd", [(0, 4)]))
        # authoritative registration evidence re-arms the id
        svc.note_registered(9)
        assert svc.tier_row(9, 0, 0, 1, b"x", 1)
    finally:
        svc.stop()


def test_tiering_service_upload_races_unregister_reaps_blob(tmp_path):
    # the tombstone lands while the upload is mid-put: the worker must
    # reap its own blob and skip the publish (modelcheck
    # tier_vs_unregister's real-code twin)
    seg = b"zombie-segment"
    resolver = _FakeResolver({(3, 8): seg})
    published = []
    svc = _svc(tmp_path, resolver, published)

    real_put = svc.store.put

    def put_then_drop(key, data):
        etag = real_put(key, data)
        svc.drop_shuffle(3)  # the unregister broadcast wins the window
        return etag

    svc.store.put = put_then_drop
    try:
        assert svc.submit(_merged_msg(3, 0, 8, seg, [(0, len(seg))]))
        assert svc.drain(5)
        assert svc.uploads_reaped == 1 and svc.uploads_done == 0
        assert published == []
        assert svc.store.list("3/") == []
        assert resolver.disk_ledger.balance.get(0, 0) == 0
    finally:
        svc.store.put = real_put
        svc.stop()


def test_reap_orphans(tmp_path):
    resolver = _FakeResolver()
    svc = _svc(tmp_path, resolver, [])
    try:
        svc.store.put("1/p0/seg_1", b"live")
        svc.store.put("2/p0/seg_1", b"dead")
        svc.store.put("2/p1/drain_m0_1", b"dead")
        svc.store.put("notanid/x", b"foreign")
        # fresh blobs are protected (an upload racing the snapshot)
        assert svc.reap_orphans([1], min_age_s=3600) == 0
        assert svc.reap_orphans([1], min_age_s=0.0) == 2
        assert [m.key for m in svc.store.list()] == ["1/p0/seg_1",
                                                     "notanid/x"]
    finally:
        svc.stop()


def test_wait_for_tiered_coverage_reports_absence():
    class _Drv:
        def tiered_directory(self, sid):
            return None

    assert not wait_for_tiered_coverage(_Drv(), 1, 1, 1, timeout=0.1)


# -- e2e cluster ----------------------------------------------------------


def _cluster(tmp_path, n=3, **kw):
    base = dict(connect_timeout_ms=10000, use_cpp_runtime=False,
                retry_backoff_base_ms=10, retry_backoff_cap_ms=80,
                push_merge=True, merge_replicas=1, push_deadline_ms=8000,
                cold_tier=True, cold_tier_path=str(tmp_path / "cold"))
    base.update(kw)
    conf = TpuShuffleConf(**base)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs, conf


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _map_fn_for(counter, rows=400, payload_w=0):
    def map_fn(writer, map_id):
        counter[map_id] = counter.get(map_id, 0) + 1
        rng = np.random.default_rng(SEED * 1000 + map_id)
        keys = rng.integers(0, 5000, rows).astype(np.uint64)
        payload = (rng.integers(0, 255, (rows, payload_w), dtype=np.uint64)
                   .astype(np.uint8) if payload_w else None)
        writer.write_batch(keys, payload)
    return map_fn


def _expected(num_maps, rows=400):
    return np.sort(np.concatenate(
        [np.random.default_rng(SEED * 1000 + m).integers(0, 5000, rows)
         for m in range(num_maps)]).astype(np.uint64))


def _reduce_fn(mgr, handle):
    keys, _ = mgr.get_reader(handle, 0, handle.num_partitions).read_all()
    return np.sort(keys)


def _tier_ready(driver, execs, handle, timeout=15):
    for ex in execs:
        assert ex.pusher.drain(timeout)
    assert wait_for_coverage(driver.driver, handle.shuffle_id,
                             handle.num_maps, handle.num_partitions,
                             timeout=timeout)
    for ex in execs:
        if ex.executor.tiering is not None:
            assert ex.executor.tiering.drain(timeout)
    assert wait_for_tiered_coverage(driver.driver, handle.shuffle_id,
                                    handle.num_maps,
                                    handle.num_partitions, timeout=timeout)


def _tombstone_all(driver, execs):
    mids = [ex.executor.manager_id for ex in execs]
    for ex in execs:
        ex.stop()
    for mid in mids:
        driver.driver.remove_member(mid)


def _fresh_fleet(tmp_path, driver, conf, n, dead_n, tag="f"):
    fresh = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=f"{tag}{i}",
                               spill_dir=str(tmp_path / f"{tag}{i}"))
             for i in range(n)]
    from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
    for ex in fresh:
        members = ex.executor.wait_for_members(dead_n + n)
        # the tombstones of the dead fleet must be visible before a
        # read, or the fetcher would dial dead peers first
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            members = ex.executor.members()
            if all(members[s] == TOMBSTONE for s in range(dead_n)):
                break
            time.sleep(0.02)
        assert all(members[s] == TOMBSTONE for s in range(dead_n))
    return fresh


def test_e2e_upload_coverage_and_resolve_precedence(tmp_path):
    """With the whole fleet healthy, a reduce must serve from merged
    segments and touch the cold tier ZERO times — TIERED is the LAST
    location class, strictly after pushed/merged/per-map."""
    driver, execs, conf = _cluster(tmp_path)
    try:
        handle = driver.register_shuffle(
            1, num_maps=6, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        run_map_stage(execs, handle, _map_fn_for(counter))
        _tier_ready(driver, execs, handle)
        # uploads happened and the directory covers everything...
        directory = driver.driver.tiered_directory(1)
        assert directory is not None and len(directory) >= 4
        assert driver.driver.tiered_publishes >= 4
        # ...but a healthy reduce never touches the cold store
        reader = execs[0].get_reader(handle, 0, 4)
        got = np.sort(reader.read_all()[0])
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        m = reader.metrics
        assert m.merged_reads > 0, m
        assert m.tiered_reads == 0 and m.tiered_bytes == 0, m
    finally:
        _shutdown(driver, execs)


@pytest.mark.parametrize("coalesce", [True, False])
def test_e2e_full_fleet_restart_restores_from_cold(tmp_path, coalesce):
    """THE acceptance: every executor dies after map finalize + tier
    upload; a FRESH fleet reduces byte-identically entirely from the
    cold tier with ZERO map re-executions — on both coalesce
    dataplanes."""
    driver, execs, conf = _cluster(tmp_path, coalesce_reads=coalesce)
    fresh = []
    try:
        handle = driver.register_shuffle(
            1, num_maps=6, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        run_map_stage(execs, handle, _map_fn_for(counter))
        _tier_ready(driver, execs, handle)

        # the spot-market event: the ENTIRE fleet is gone
        _tombstone_all(driver, execs)
        fresh = _fresh_fleet(tmp_path, driver, conf, 3, dead_n=3)

        got = run_reduce_with_retry(
            fresh, handle, _map_fn_for(counter), _reduce_fn,
            reducer_index=0, max_stage_retries=2, driver=driver)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        # ZERO re-executions: every map ran exactly once, ever
        assert all(n == 1 for n in counter.values()), counter
        assert sum(counter.values()) == 6

        # a direct reader confirms the bytes came off the cold tier
        reader = fresh[1].get_reader(handle, 0, 4)
        np.testing.assert_array_equal(np.sort(reader.read_all()[0]),
                                      _expected(6))
        m = reader.metrics
        # >= one blob restore per partition (a partition may compose
        # several targets' segment blobs)
        assert m.tiered_reads >= 4, m
        assert m.tiered_bytes > 0 and m.failed_fetches == 0, m
        assert m.merged_reads == 0, m  # merged replicas died with the fleet
    finally:
        _shutdown(driver, fresh if fresh else execs)


def test_e2e_crc_bad_blob_degrades_exactly_that_partition(tmp_path):
    """Rot one blob at rest AFTER the fleet dies: the restore of exactly
    that partition degrades (CRC verify catches it; verdict
    cold_unusable), recovery re-executes, the repair publish drops the
    stale cold entries, and the reduce still completes
    byte-identically. Healthy partitions keep serving from cold."""
    driver, execs, conf = _cluster(tmp_path)
    fresh = []
    try:
        handle = driver.register_shuffle(
            1, num_maps=4, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        run_map_stage(execs, handle, _map_fn_for(counter))
        _tier_ready(driver, execs, handle)
        _tombstone_all(driver, execs)

        # rot every blob of partition 0 in place (flip one byte each);
        # partitions 1-3 stay clean
        store = FSBlobStore(str(tmp_path / "cold"))
        rotted = 0
        for meta in store.list("1/p0/"):
            path = store._path(meta.key)
            with open(path, "r+b") as f:
                b = f.read(1)
                f.seek(0)
                f.write(bytes([b[0] ^ 0xFF]))
            rotted += 1
        assert rotted >= 1

        fresh = _fresh_fleet(tmp_path, driver, conf, 3, dead_n=3)
        # the un-retried read fails with the cold_unusable verdict —
        # the CRC caught the rot, nothing corrupt ever decoded
        reader = fresh[0].get_reader(handle, 0, 4)
        with pytest.raises(FetchFailedError) as ei:
            reader.read_all()
        assert ei.value.verdict == "cold_unusable"
        assert reader.metrics.tiered_fallbacks >= 1

        got = run_reduce_with_retry(
            fresh, handle, _map_fn_for(counter), _reduce_fn,
            reducer_index=0, max_stage_retries=4, driver=driver)
        np.testing.assert_array_equal(got, _expected(4),
                                      err_msg=f"seed={SEED}")
        # degradation re-executed SOME maps (never zero — the rotten
        # partition cannot be served cold) but the job completed
        assert sum(counter.values()) > 4, counter
    finally:
        _shutdown(driver, fresh if fresh else execs)


def test_e2e_drain_to_cold_zero_reexecutions(tmp_path):
    """Decommission with the cold tier up: the drain tiers the
    drainee's only-copy rows into blobs (no peer involved), the reduce
    completes byte-identically with ZERO re-executions after the
    drainee is gone — and the safety invariant credits cold coverage."""
    driver, execs, conf = _cluster(tmp_path)
    try:
        handle = driver.register_shuffle(
            2, num_maps=6, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        map_fn = _map_fn_for(counter)
        ran = run_map_stage(execs, handle, map_fn)
        for ex in execs:
            assert ex.pusher.drain(10)

        victim = execs[2]
        victim_slot = victim.executor.exec_index(timeout=2)
        res = driver.decommission_slot(victim_slot)
        assert res["status"] == "drained", res
        assert res["unservable"] == []
        assert driver.driver.drain_fallbacks == 0
        # only-copy rows went COLD, not to a peer
        assert victim.executor.tiering is not None
        assert victim.executor.tiering.rows_tiered > 0
        directory = driver.driver.tiered_directory(2)
        assert directory is not None and len(directory) > 0
        assert any("drain_m" in e.blob_key
                   for p in directory.partitions()
                   for e in directory.entries(p))

        victim.stop()
        got = run_reduce_with_retry(execs[:2], handle, map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=2,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert sum(counter.values()) == 6, \
            f"re-executions after a drain-to-cold: {counter}"
    finally:
        _shutdown(driver, execs[:2])


def test_e2e_drain_falls_back_to_peer_when_store_down(tmp_path):
    """The store is DOWN during the drain: tier_row declines, the drain
    falls back to the ordinary peer push — the decommission never gets
    weaker guarantees than it had before the cold tier existed."""
    driver, execs, conf = _cluster(tmp_path)
    inj = BlobFaultInjector(seed=SEED)
    inj.install()
    try:
        handle = driver.register_shuffle(
            3, num_maps=6, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        map_fn = _map_fn_for(counter)
        run_map_stage(execs, handle, map_fn)
        for ex in execs:
            assert ex.pusher.drain(10)

        inj.add(BLOB_UNAVAILABLE, op="put")  # every put: store down
        victim = execs[2]
        victim_slot = victim.executor.exec_index(timeout=2)
        res = driver.decommission_slot(victim_slot)
        assert res["status"] == "drained", res
        assert victim.executor.tiering.rows_tiered == 0
        assert inj.fired_count(BLOB_UNAVAILABLE) >= 1
        inj.uninstall()

        victim.stop()
        got = run_reduce_with_retry(execs[:2], handle, map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=2,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert sum(counter.values()) == 6, counter
    finally:
        inj.uninstall()
        _shutdown(driver, execs[:2])


# -- HA: the tiered directory survives failover ---------------------------


def test_ha_failover_preserves_tiered_directory():
    from sparkrdma_tpu.parallel.endpoints import DriverEndpoint
    from sparkrdma_tpu.shuffle import ha

    conf = TpuShuffleConf(connect_timeout_ms=2000, ha_standbys=1,
                          push_merge=True, cold_tier=True,
                          pre_warm_connections=False)
    ep = DriverEndpoint(conf, host="127.0.0.1")
    try:
        ep.register_shuffle(7, num_maps=4, num_partitions=2)
        msg = M.TieredPublishMsg(7, 1, "7/p1/seg_9", 256, 0xABCD,
                                 _cov(4, 0, 2))
        ep._handle(None, msg)
        ep._handle(None, M.TieredPublishMsg(7, 0, "7/p0/seg_8", 128,
                                            0xBEEF, _cov(4, 0, 2)))
        before = ep.tiered_directory(7).to_bytes()
        # replay idempotency: the op log re-applies frames verbatim
        ep._handle(None, msg)
        assert ep.tiered_directory(7).to_bytes() == before
        assert ep.tiered_publishes == 3  # counted, but state unchanged

        blob, tail = ep.oplog.restore_point()
        if blob is None:
            blob = ha.encode_snapshot(ep.snapshot_state())
        ep2 = DriverEndpoint(conf, host="127.0.0.1", incarnation=1,
                             restore=(blob, tail))
        try:
            restored = ep2.tiered_directory(7)
            assert restored is not None
            assert restored.to_bytes() == before
            (entry,) = restored.entries(1)
            assert entry.blob_key == "7/p1/seg_9"
            assert entry.covered_maps(4) == [0, 2]
            assert ep2.tiered_covering(7, [0, 2]) == {0, 2}
        finally:
            ep2.stop()
    finally:
        ep.stop()


def test_driver_drops_tiered_entries_on_repair_publish():
    """A repair publish for map m supersedes m's cold copies: the
    driver drops every tiered entry covering m AND tombstones (sid, m)
    so a publish mid-flight from a dead fleet cannot re-enter stale
    coverage."""
    from sparkrdma_tpu.parallel.endpoints import DriverEndpoint
    from sparkrdma_tpu.shuffle.map_output import _MAP_ENTRY

    conf = TpuShuffleConf(connect_timeout_ms=2000, push_merge=True,
                          cold_tier=True, pre_warm_connections=False)
    ep = DriverEndpoint(conf, host="127.0.0.1")
    try:
        ep.register_shuffle(5, num_maps=2, num_partitions=1)
        ep._handle(None, M.PublishMsg(5, 0, _MAP_ENTRY.pack(10, 0),
                                      fence=1))
        ep._handle(None, M.TieredPublishMsg(5, 0, "5/p0/seg_1", 8, 1,
                                            _cov(2, 0)))
        assert ep.tiered_covering(5, [0]) == {0}
        # the repair: map 0 re-published under a higher fence
        ep._handle(None, M.PublishMsg(5, 0, _MAP_ENTRY.pack(11, 1),
                                      fence=2))
        assert ep.tiered_covering(5, [0]) == set()
        # the mid-upload race: a stale cold publish arrives AFTER the
        # repair — it must be dropped, not re-enter coverage
        ep._handle(None, M.TieredPublishMsg(5, 0, "5/p0/seg_1", 8, 1,
                                            _cov(2, 0)))
        assert ep.tiered_covering(5, [0]) == set()
        assert ep.tiered_stale_drops == 1
    finally:
        ep.stop()


# -- the microbench acceptance gate (the cold_restore_speedup secondary) --

def test_cold_restore_microbench_acceptance(tmp_path):
    """The ISSUE's acceptance gate, exactly as the bench secondary
    records it: full fleet dead after map finalize, fresh-fleet
    makespan cold-restore vs full re-execution >= 1.5x, both phases
    byte-identical, the restore re-executing ZERO maps and the
    baseline re-executing ALL of them."""
    from sparkrdma_tpu.shuffle.cold_bench import run_cold_microbench
    from sparkrdma_tpu.utils.benchgate import gated_best_of

    res = gated_best_of(lambda: run_cold_microbench(str(tmp_path)))
    assert res["identical"], res
    assert res["reexec"]["cold"] == 0, res
    assert res["reexec"]["baseline"] == res["maps"], res
    assert res["speedup"] >= 1.5, res


def test_bench_secondary_rides_cold_restore():
    """bench.py wiring: the cold-restore A/B rides
    _secondary_workloads (so every bench round records
    cold_restore_speedup) and rounds carry the host_load_avg
    provenance the deflake gate keys on."""
    import inspect

    import bench as bench_mod

    detail = bench_mod._round_provenance({})
    assert len(detail["host_load_avg"]) == 3
    sec_src = inspect.getsource(bench_mod._secondary_workloads)
    assert "_bench_cold_restore" in sec_src
    cold_src = inspect.getsource(bench_mod._bench_cold_restore)
    assert "gated_best_of" in cold_src
