"""Driver HA (shuffle/ha.py + the DriverEndpoint op log): lease-store
CAS semantics on both backends, epoch composition, the snapshot codec,
op-log compaction, replay idempotency over the driver-bound wire
frames, DriverClient failover re-pointing, and an end-to-end in-process
lease failover with live executors (the SIGKILL variant lives in
tests/test_chaos.py)."""

import struct
import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.driver_client import (DriverClient,
                                                  DriverUnreachableError)
from sparkrdma_tpu.parallel.endpoints import DriverEndpoint
from sparkrdma_tpu.parallel.rpc_msg import HelloMsg
from sparkrdma_tpu.parallel.transport import ConnectionCache
from sparkrdma_tpu.shuffle import ha
from sparkrdma_tpu.shuffle.ha import (
    DriverStandby,
    FileLeaseStore,
    InMemoryLeaseStore,
    OpLog,
    compose_epoch,
    epoch_seq,
    incarnation_of,
    rebase_epoch,
)
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.ids import ExecutorId, ShuffleManagerId

CONF = dict(connect_timeout_ms=2000, max_connection_attempts=2,
            pre_warm_connections=False)


def _mk_conf(**kw):
    base = dict(CONF)
    base.update(kw)
    return TpuShuffleConf(**base)


def _mid(i, port=9000):
    return ShuffleManagerId(ExecutorId(str(i), "127.0.0.1", 0),
                            "127.0.0.1", port + i, 0)


# -- epoch composition ------------------------------------------------------

def test_epoch_composition():
    # incarnation 0 is the identity: pre-HA epochs are unchanged
    assert compose_epoch(0, 17) == 17
    assert incarnation_of(17) == 0 and epoch_seq(17) == 17
    e = compose_epoch(3, 42)
    assert incarnation_of(e) == 3 and epoch_seq(e) == 42
    # any incarnation-N epoch strictly dominates every incarnation-<N
    # one under the plain comparison receivers already do
    assert compose_epoch(1, 0) > compose_epoch(0, ha.EPOCH_SEQ_MASK)
    # rebase: one past the restored seq, under the new leading component
    r = rebase_epoch(17, 2)
    assert incarnation_of(r) == 2 and epoch_seq(r) == 18
    assert r > compose_epoch(1, 10 ** 6)
    # sentinels stay the caller's problem but never crash
    assert incarnation_of(-1) == 0 and epoch_seq(-1) == 0
    with pytest.raises(ValueError):
        compose_epoch(-1, 0)


# -- lease store (both backends) --------------------------------------------

def _stores(tmp_path):
    return [InMemoryLeaseStore(),
            FileLeaseStore(str(tmp_path / "lease.json"))]


@pytest.mark.parametrize("backend", ["memory", "file"])
def test_lease_cas_rules(tmp_path, backend):
    store = _stores(tmp_path)[backend == "file"]
    t0 = store.now()
    # the world starts at term 0; term 1 first is refused
    assert not store.try_acquire("a", 1, 10.0, now=t0)
    assert store.try_acquire("a", 0, 10.0, now=t0)
    lease = store.read()
    assert lease.holder == "a" and lease.term == 0
    # a live lease held by someone else refuses the next term
    assert not store.try_acquire("b", 1, 10.0, now=t0 + 1)
    # term must be exactly current+1, even for the holder
    assert not store.try_acquire("a", 2, 10.0, now=t0 + 1)
    # renew: holder+term must match exactly
    assert store.renew("a", 0, 10.0, now=t0 + 2)
    assert not store.renew("b", 0, 10.0, now=t0 + 2)
    assert not store.renew("a", 1, 10.0, now=t0 + 2)
    # expiry: the next term opens to anyone
    assert store.try_acquire("b", 1, 10.0, now=t0 + 30)
    # ... and the deposed holder's renew now fails — the zombie signal
    assert not store.renew("a", 0, 10.0, now=t0 + 31)
    assert store.read().holder == "b"


@pytest.mark.parametrize("backend", ["memory", "file"])
def test_lease_single_winner_per_term(tmp_path, backend):
    store = _stores(tmp_path)[backend == "file"]
    t0 = store.now()
    wins = []
    barrier = threading.Barrier(4)

    def racer(name):
        barrier.wait()
        if store.try_acquire(name, 0, 10.0, now=t0):
            wins.append(name)

    threads = [threading.Thread(target=racer, args=(f"s{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert store.read().holder == wins[0]


# -- op log -----------------------------------------------------------------

def test_oplog_compaction_and_restore_point():
    log = OpLog(incarnation=0, snapshot_every=4)
    for i in range(3):
        rec = log.append(ha.OP_BUMP, ha.op_sid(i))
        assert rec.seq == i + 1 and rec.incarnation == 0
    assert not log.snapshot_due()
    log.append(ha.OP_BUMP, ha.op_sid(3))
    assert log.snapshot_due()
    # snapshot at the current seq truncates the covered tail
    log.install_snapshot(log.last_seq(), b"snap@4")
    assert not log.snapshot_due()
    blob, tail = log.restore_point()
    assert blob == b"snap@4" and tail == []
    # ops after the snapshot survive and stream from entries_since
    r5 = log.append(ha.OP_UNREGISTER, ha.op_sid(9))
    blob, tail = log.restore_point()
    assert blob == b"snap@4" and [r.seq for r in tail] == [5]
    assert log.entries_since(4) == [r5]
    assert log.entries_since(5) == []
    # records round-trip bytes
    back = ha.OpRecord.from_bytes(r5.to_bytes())
    assert back == r5


def test_snapshot_codec_roundtrip():
    state = {
        "shuffles": {"7": {"num_maps": 3, "table": b"\x00\x01\xff",
                           "plan": None, "nested": [b"a", {"k": b"b"}],
                           "reg_unix": 1234.5}},
        "membership": {"members": [b"m0", b"m1"], "states": [0, 1],
                       "epoch": 12},
    }
    blob = ha.encode_snapshot(state)
    assert ha.decode_snapshot(blob) == state
    # versioning is enforced, not advisory
    bad = blob.replace(b'"version":1', b'"version":99')
    with pytest.raises(ValueError):
        ha.decode_snapshot(bad)


def test_op_payload_codecs():
    sid, nm, np_, ten, reg = ha.unpack_register(
        ha.op_register(7, 4, 8, 2, 1234.25))
    assert (sid, nm, np_, ten, reg) == (7, 4, 8, 2, 1234.25)
    assert ha.unpack_sid(ha.op_sid(11)) == 11
    assert ha.unpack_drain(ha.op_drain(3, ha.DRAIN_RETIRE)) == (3, 2)


# -- DriverClient -----------------------------------------------------------

def test_driver_client_forward_only_repoint():
    conf = _mk_conf()
    client = DriverClient(conf, ConnectionCache(conf), ("127.0.0.1", 1))
    assert client.incarnation == 0
    assert client.note_takeover(2, "127.0.0.1", 2)
    assert client.addr == ("127.0.0.1", 2) and client.incarnation == 2
    # a zombie's stale broadcast (equal or lower incarnation) never
    # re-points backwards
    assert not client.note_takeover(2, "127.0.0.1", 9)
    assert not client.note_takeover(1, "127.0.0.1", 9)
    assert client.addr == ("127.0.0.1", 2)
    assert client.failovers_observed == 1


def test_driver_client_unreachable_is_retryable_and_bounded():
    conf = _mk_conf(request_deadline_ms=300, max_connection_attempts=1,
                    connect_timeout_ms=200, retry_backoff_base_ms=10,
                    retry_backoff_cap_ms=20)
    client = DriverClient(conf, ConnectionCache(conf), ("127.0.0.1", 1))
    t0 = time.monotonic()
    with pytest.raises(DriverUnreachableError) as ei:
        client.send(M.PingMsg(1))
    # bounded by request_deadline_ms (plus one attempt's connect), and
    # classified retryable — the fetch layers must never tombstone a
    # live PEER over a driver blink
    assert time.monotonic() - t0 < 5.0
    assert ei.value.retryable
    assert client.retried_sends >= 1


# -- replay idempotency over the driver-bound wire frames -------------------

def _armed_driver(**kw):
    conf = _mk_conf(ha_standbys=1, push_merge=True, **kw)
    return conf, DriverEndpoint(conf, host="127.0.0.1")


def _entry(token, exec_index):
    from sparkrdma_tpu.shuffle.map_output import _MAP_ENTRY
    return _MAP_ENTRY.pack(token, exec_index)


def _driver_fingerprint(ep):
    """Everything a second application of the same frame must not move:
    table bytes, location epochs, tenants, merged directories, plans,
    membership members+states. (The membership EPOCH is excluded: a
    re-hello legitimately bumps it — epoch movement without member
    movement is exactly what receivers tolerate.)"""
    with ep._tables_lock:
        tables = {sid: t.to_bytes() for sid, t in ep._tables.items()}
        epochs = dict(ep._epochs)
        tenants = dict(ep._tenants)
        merged = {sid: d.to_bytes() for sid, d in ep._merged.items()}
        plans = {sid: p.to_bytes() for sid, p in ep._plans.items()}
    members, states, _ = ep.membership.snapshot()
    return (tables, epochs, tenants, merged, plans,
            [m.serialize() for m in members], list(states))


def _driver_bound_frames():
    """The WIRE_IDS subset the op log records verbatim (OP_WIRE): every
    one must be idempotent under re-application, because a failover
    replays the tail against a snapshot that may already contain it."""
    mid = _mid(0)
    return {
        "hello": HelloMsg(mid),
        "join": M.JoinMsg(_mid(1)),
        "publish": M.PublishMsg(7, 1, _entry(1234, 0), fence=3),
        "merged_publish": M.MergedPublishMsg(
            7, 0, 0, 99, 64, 0xDEAD, b"\x07", [(0, 64)]),
    }


@pytest.mark.parametrize("kind", sorted(_driver_bound_frames()))
def test_wire_replay_idempotent(kind):
    conf, ep = _armed_driver()
    try:
        ep.register_shuffle(7, num_maps=3, num_partitions=4, tenant=0)
        # a base population so the frame lands on real state
        ep._handle(None, HelloMsg(_mid(0)))
        ep._handle(None, M.PublishMsg(7, 0, _entry(111, 0), fence=1))
        msg = _driver_bound_frames()[kind]
        ep._handle(None, msg)
        before = _driver_fingerprint(ep)
        ep._handle(None, msg)  # the replayed duplicate
        assert _driver_fingerprint(ep) == before
    finally:
        ep.stop()


def test_restore_replays_tail_and_snapshot_to_same_state():
    """A cold standby's view (snapshot + tail) restored into a fresh
    endpoint reproduces the primary's tables — and replaying the SAME
    tail against a snapshot that already contains it is a no-op."""
    conf, ep = _armed_driver()
    try:
        ep.register_shuffle(7, num_maps=2, num_partitions=2, tenant=1)
        ep._handle(None, HelloMsg(_mid(0)))
        ep._handle(None, M.PublishMsg(7, 0, _entry(100, 0), fence=1))
        ep._handle(None, M.PublishMsg(7, 1, _entry(101, 0), fence=1))
        blob, tail = ep.oplog.restore_point()
        if blob is None:
            blob = ha.encode_snapshot(ep.snapshot_state())
            # a snapshot taken NOW already contains the whole tail:
            # replaying it on top must change nothing
        ep2 = DriverEndpoint(conf, host="127.0.0.1", incarnation=1,
                             restore=(blob, tail))
        try:
            with ep._tables_lock:
                src = {s: t.to_bytes() for s, t in ep._tables.items()}
            with ep2._tables_lock:
                dst = {s: t.to_bytes() for s, t in ep2._tables.items()}
                tenants = dict(ep2._tenants)
            assert dst == src and tenants == {7: 1}
            # every restored epoch was rebased under the new incarnation
            assert incarnation_of(ep2.epoch_of(7)) == 1
            assert ep2.epoch_of(7) > ep.epoch_of(7)
        finally:
            ep2.stop()
    finally:
        ep.stop()


def test_register_unregister_replay_keeps_ledger_balanced():
    conf, ep = _armed_driver()
    try:
        for sid in (1, 2, 3):
            ep.register_shuffle(sid, num_maps=1, num_partitions=1)
        ep.unregister_shuffle(2)
        blob, tail = ep.oplog.restore_point()
        ep2 = DriverEndpoint(conf, host="127.0.0.1", incarnation=1,
                             restore=(blob, tail))
        try:
            assert ep2.live_shuffles() == [1, 3]
        finally:
            ep2.stop()
    finally:
        ep.stop()


# -- end-to-end in-process failover -----------------------------------------

@pytest.mark.slow
def test_failover_mid_job_zero_reexecutions(tmp_path):
    """Kill the primary (in-process: stop renewing + stop serving)
    after the map stage; the standby CAS-takes the lease within
    driver_lease_ms, replays, re-points executors via TakeoverMsg, and
    the reduce completes byte-identically with ZERO map re-executions."""
    conf = _mk_conf(ha_standbys=1, driver_lease_ms=600,
                    request_deadline_ms=10_000,
                    retry_backoff_base_ms=20, retry_backoff_cap_ms=100)
    store = InMemoryLeaseStore()
    primary = DriverEndpoint(conf, host="127.0.0.1", lease_store=store,
                             lease_holder="primary")
    standby = DriverStandby(conf, store, "standby-1",
                            primary.address).start()
    execs = []
    try:
        execs = [TpuShuffleManager(conf, driver_addr=primary.address,
                                   executor_id=f"ha{i}",
                                   spill_dir=str(tmp_path / f"ha{i}"))
                 for i in range(2)]
        for ex in execs:
            ex.executor.wait_for_members(2)
        from sparkrdma_tpu.shuffle.manager import (PartitionerSpec,
                                                   ShuffleHandle)
        handle = ShuffleHandle(7, 4, 4, 0, PartitionerSpec("modulo"))
        primary.register_shuffle(7, num_maps=4, num_partitions=4)
        map_runs = {}

        def map_fn(writer, m):
            map_runs[m] = map_runs.get(m, 0) + 1
            rng = np.random.default_rng(500 + m)
            writer.write_batch(rng.integers(0, 5000, 300)
                               .astype(np.uint64))

        from sparkrdma_tpu.shuffle.recovery import run_map_stage
        run_map_stage(execs, handle, map_fn)
        # map outputs published; kill the primary (stops lease renewal,
        # mutes pushes, closes the server socket)
        primary.stop()
        deadline = time.monotonic() + 10.0
        while standby.endpoint is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert standby.endpoint is not None, "standby never promoted"
        new_primary = standby.endpoint
        assert new_primary.incarnation >= 1
        # restored registry: all four publishes survived the failover
        with new_primary._tables_lock:
            table = new_primary._tables[7]
        assert table.num_published == 4
        # executors observe the takeover and the reduce drains
        # byte-identically through the NEW primary
        deadline = time.monotonic() + 10.0
        while (any(ex.executor.driver.incarnation
                   < new_primary.incarnation for ex in execs)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        for ex in execs:
            assert ex.executor.driver.incarnation == \
                new_primary.incarnation
            assert ex.executor.driver.addr == new_primary.address
        keys, _ = execs[1].get_reader(handle, 0, 4).read_all()
        expect = np.sort(np.concatenate(
            [np.random.default_rng(500 + m).integers(0, 5000, 300)
             for m in range(4)]).astype(np.uint64))
        assert np.array_equal(np.sort(keys), expect)
        # the HA acceptance: failover cost ZERO map re-executions
        assert all(n == 1 for n in map_runs.values()), map_runs
        # epochs the new primary serves dominate the old incarnation's
        assert incarnation_of(new_primary.epoch_of(7)) == \
            new_primary.incarnation
    finally:
        for ex in execs:
            ex.stop()
        standby.stop()


@pytest.mark.slow
def test_zombie_primary_is_fenced_and_mutes(tmp_path):
    """A deposed primary notices within one lease period (renew fails)
    and every epoch it could still mint is dominated by the new
    incarnation's."""
    conf = _mk_conf(ha_standbys=1, driver_lease_ms=400)
    store = InMemoryLeaseStore()
    primary = DriverEndpoint(conf, host="127.0.0.1", lease_store=store,
                             lease_holder="primary")
    try:
        primary.register_shuffle(3, num_maps=1, num_partitions=1)
        old_epoch = primary.epoch_of(3)
        # a standby steals the lease out from under a LIVE primary by
        # CAS-ing term+1 after expiry; simulate the expiry directly
        far = store.now() + 1000.0
        assert store.try_acquire("usurper", 1, 10.0, now=far)
        deadline = time.monotonic() + 5.0
        while not primary.deposed() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert primary.deposed()
        # fencing arithmetic: anything the usurper publishes dominates
        assert rebase_epoch(old_epoch, 1) > old_epoch
    finally:
        primary.stop()


# -- the driver-down window in the recovery loop ----------------------------
#
# A reduce sync that dies because the DRIVER is electing must come back
# as a retryable driver-unreachable verdict: the data plane is fine, so
# the loop retries the sync without recomputing a map, tombstoning a
# peer, or burning the stage retry budget — and it stays bounded when
# the driver never comes back.

def test_recovery_driver_down_window_retries_without_recompute():
    from sparkrdma_tpu.shuffle.recovery import run_reduce_with_retry

    calls = {"reduce": 0, "map": 0}

    def reduce_fn(mgr, handle):
        calls["reduce"] += 1
        if calls["reduce"] <= 2:
            raise DriverUnreachableError("electing")
        return "done"

    def map_fn(writer, map_id):  # pragma: no cover - must never run
        calls["map"] += 1

    out = run_reduce_with_retry([object()], handle=None, map_fn=map_fn,
                                reduce_fn=reduce_fn, reducer_index=0,
                                max_stage_retries=2)
    assert out == "done"
    assert calls["reduce"] == 3  # two waits, then the healed sync
    assert calls["map"] == 0  # a driver blink never recomputes a map


def test_recovery_driver_down_window_is_bounded():
    from sparkrdma_tpu.shuffle.recovery import run_reduce_with_retry

    calls = {"reduce": 0}

    def reduce_fn(mgr, handle):
        calls["reduce"] += 1
        raise DriverUnreachableError("never coming back")

    with pytest.raises(DriverUnreachableError):
        run_reduce_with_retry([object()], handle=None, map_fn=None,
                              reduce_fn=reduce_fn, reducer_index=0,
                              max_stage_retries=1)
    # max_stage_retries + 1 waits, then the verdict surfaces
    assert calls["reduce"] == 3
