"""Fault-injection shim + hardened failure-path unit tests.

Covers the chaos shim itself (parallel/faults.py), the backoff helper
and the connect-retry wall-clock fix, the ControlServer connection reap,
the ``await_response`` claim-back race and ``_fail_pending`` vs
caller-cancel interleavings (pinned deterministically), checksummed
fetches healing a bit-flip via bounded refetch, and heartbeat-based
suspicion failing outstanding fetches long before any TCP-scale
timeout. The full scenario matrix lives in tests/test_chaos.py.
"""

import socket
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.endpoints import DriverEndpoint, ExecutorEndpoint
from sparkrdma_tpu.parallel.faults import (
    BLACKHOLE,
    CORRUPT,
    DELAY,
    DISCONNECT,
    REFUSE_CONNECT,
    FaultInjector,
)
from sparkrdma_tpu.parallel.transport import (
    Backoff,
    ChecksumError,
    Connection,
    ConnectionCache,
    ControlServer,
    TransportError,
    await_response,
)
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput


class FakeSource:
    """In-memory ShuffleDataSource keyed by token (test_control_plane's)."""

    def __init__(self):
        self.tables: Dict[Tuple[int, int], MapTaskOutput] = {}
        self.buffers: Dict[int, bytes] = {}

    def get_output_table(self, shuffle_id, map_id) -> Optional[MapTaskOutput]:
        return self.tables.get((shuffle_id, map_id))

    def read_block(self, shuffle_id, buf_token, offset, length):
        buf = self.buffers.get(buf_token)
        if buf is None or offset + length > len(buf):
            return None
        return buf[offset:offset + length]


# -- backoff helper ------------------------------------------------------


def test_backoff_bounds_and_determinism():
    import random

    b1 = Backoff(0.1, 0.4, rng=random.Random(42))
    b2 = Backoff(0.1, 0.4, rng=random.Random(42))
    d1 = [b1.delay(k) for k in range(6)]
    d2 = [b2.delay(k) for k in range(6)]
    assert d1 == d2, "same seed must replay the same sleep schedule"
    for k, d in enumerate(d1):
        span = min(0.4, 0.1 * (1 << k))
        # equal jitter: never below half the span (the wall-clock floor),
        # never above the capped span
        assert span / 2 <= d <= span, (k, d)


def test_backoff_sleep_interruptible():
    b = Backoff(5.0, 5.0)
    ev = threading.Event()
    ev.set()
    t0 = time.monotonic()
    assert b.sleep(0, interrupt=ev) is True
    assert time.monotonic() - t0 < 1.0


def test_connect_retry_backoff_spans_wall_clock():
    """The satellite fix: re-dials must sleep between attempts — the
    budget has to span real time, not burn out in microseconds."""
    conf = TpuShuffleConf(connect_timeout_ms=2000,
                          max_connection_attempts=3,
                          retry_backoff_base_ms=80,
                          retry_backoff_cap_ms=200)
    cache = ConnectionCache(conf)
    t0 = time.monotonic()
    with pytest.raises(TransportError):
        cache.get("127.0.0.1", 1)  # nothing listens on port 1
    dt = time.monotonic() - t0
    # two inter-attempt sleeps floored at span/2: >= 40ms + 80ms
    assert dt >= 0.12, f"retry loop still hot-spins ({dt:.4f}s)"
    assert dt < 5, dt


# -- chaos shim: connect faults -----------------------------------------


def test_refuse_connect_burst_absorbed_by_retry_budget():
    conf = TpuShuffleConf(connect_timeout_ms=2000,
                          max_connection_attempts=4,
                          retry_backoff_base_ms=5, retry_backoff_cap_ms=20)
    server = ControlServer("127.0.0.1", 0, conf, handler=lambda c, m: None)
    cache = ConnectionCache(conf)
    injector = FaultInjector(seed=7)
    injector.install(cache)
    try:
        injector.add(REFUSE_CONNECT, times=2)
        conn = cache.get(server.host, server.port)
        assert not conn.closed
        assert injector.fired_count(REFUSE_CONNECT) == 2
    finally:
        injector.uninstall()
        cache.close_all()
        server.stop()


def test_refuse_connect_exhausts_budget():
    conf = TpuShuffleConf(connect_timeout_ms=2000,
                          max_connection_attempts=2,
                          retry_backoff_base_ms=5, retry_backoff_cap_ms=20)
    server = ControlServer("127.0.0.1", 0, conf, handler=lambda c, m: None)
    cache = ConnectionCache(conf)
    injector = FaultInjector(seed=7)
    injector.install(cache)
    try:
        injector.add(REFUSE_CONNECT, times=None)  # every dial refused
        with pytest.raises(TransportError):
            cache.get(server.host, server.port)
        # uninstall restores the real dial path
        injector.uninstall()
        assert not cache.get(server.host, server.port).closed
    finally:
        injector.uninstall()
        cache.close_all()
        server.stop()


# -- ControlServer connection reap (satellite) ---------------------------


def test_control_server_reaps_dead_connections():
    conf = TpuShuffleConf(connect_timeout_ms=2000)
    server = ControlServer("127.0.0.1", 0, conf, handler=lambda c, m: None)
    try:
        socks = [socket.create_connection((server.host, server.port))
                 for _ in range(5)]
        deadline = time.monotonic() + 5
        while server.live_connections() < 5 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert server.live_connections() == 5
        for s in socks[:4]:
            s.close()
        while server.live_connections() > 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert server.live_connections() == 1, \
            "closed peers must be reaped, not accumulated forever"
        socks[4].close()
    finally:
        server.stop()


# -- claim-back race + teardown/cancel interleavings (satellite) ---------


def test_await_response_claim_back_race():
    """The reader completes the future in the window between the wait
    timing out and the caller's cancel(): the landed response must be
    returned, not dropped (a credited fetch would leak server window)."""
    marker = object()
    fut = Future()
    orig_cancel = fut.cancel

    def racing_cancel():
        fut.set_result(marker)  # the reader wins the race window
        return orig_cancel()

    fut.cancel = racing_cancel
    assert await_response(fut, timeout=0.01) is marker


def test_await_response_timeout_poisons_future():
    fut = Future()
    with pytest.raises(TimeoutError):
        await_response(fut, timeout=0.01)
    assert fut.cancelled()


def _socketpair_conn(conf=None, on_message=None):
    a, b = socket.socketpair()
    conn = Connection(a, conf or TpuShuffleConf(), on_message=on_message,
                      name="race-test")
    return conn, b


def test_fail_pending_vs_caller_cancel_interleaving():
    """Teardown's _fail_pending loses the race to a caller cancel between
    its done() check and set_exception — pinned by a future whose done()
    cancels itself. Must not raise, and the budget slot must recycle."""
    conn, raw = _socketpair_conn()
    try:
        class RacingFuture(Future):
            def done(self):
                r = super().done()
                if not r:
                    super().cancel()  # the caller's cancel lands HERE
                return r

        fut = RacingFuture()
        with conn._pending_lock:
            conn._pending[99] = fut
        conn._fail_pending(TransportError("teardown"))  # must not raise
        assert fut.cancelled()
        # normal ordering still fails pending futures
        fut2 = conn.request_async(M.FetchTableReq(conn.next_req_id(), 1))
        conn._fail_pending(TransportError("teardown"))
        with pytest.raises(TransportError):
            fut2.result(timeout=1)
    finally:
        conn.close()
        raw.close()


def test_cancelled_request_reroutes_late_response_to_orphan_path():
    """A response landing on a poisoned (cancelled) future must reach
    the unsolicited-message path, not vanish — that path owns credit
    healing for orphaned fetches."""
    orphans = []
    conn, raw = _socketpair_conn(
        on_message=lambda c, m: orphans.append(m) or None)
    try:
        req = M.FetchTableReq(conn.next_req_id(), 7)
        fut = conn.request_async(req)
        raw.recv(1 << 16)  # drain the request off the socketpair
        assert fut.cancel()
        raw.sendall(M.FetchTableResp(req.req_id, 3, b"").encode())
        deadline = time.monotonic() + 5
        while not orphans and time.monotonic() < deadline:
            time.sleep(0.01)
        assert orphans and isinstance(orphans[0], M.FetchTableResp)
        assert orphans[0].req_id == req.req_id
    finally:
        conn.close()
        raw.close()


# -- endpoint clusters for checksum / heartbeat / orphan credits ---------


@pytest.fixture
def pair():
    """driver + two executors; exec[1] serves a 400-byte buffer 55."""
    conf = TpuShuffleConf(connect_timeout_ms=20000,
                          heartbeat_interval_ms=0,  # per-test override
                          retry_backoff_base_ms=5, retry_backoff_cap_ms=20)
    yield from _make_pair(conf)


def _make_pair(conf):
    driver = DriverEndpoint(conf)
    src = FakeSource()
    src.buffers[55] = np.arange(400, dtype=np.uint8).tobytes()
    table = MapTaskOutput(4)
    for r in range(4):
        table.put(r, offset=r * 100, length=100, buf=55)
    src.tables[(3, 0)] = table
    execs = [ExecutorEndpoint("127.0.0.1", str(i), driver.address,
                              data_source=src, conf=conf)
             for i in range(2)]
    for ex in execs:
        ex.start()
    for ex in execs:
        ex.wait_for_members(2)
    yield driver, execs, src
    for ex in execs:
        ex.stop()
    driver.stop()


def test_fetch_blocks_carries_and_verifies_crc32(pair):
    _, execs, src = pair
    peer = execs[1].manager_id
    data = execs[0].fetch_blocks(peer, 3, [(55, 0, 100), (55, 300, 100)])
    assert data == src.buffers[55][0:100] + src.buffers[55][300:400]


def test_crc32_composes_with_compression_and_codec():
    """The trailer rides INSIDE the compressed/wrapped bytes: every flag
    must survive the compression branch (a dropped FLAG_CRC32 leaves the
    trailer embedded in the payload — 4 extra bytes per block corrupting
    every downstream row decode)."""
    for extra in ({"wire_compress": True, "wire_compress_min": 16},
                  {"wire_codec": "hmac-sha256", "wire_codec_key": "ab" * 16},
                  {"wire_compress": True, "wire_compress_min": 16,
                   "wire_codec": "hmac-sha256", "wire_codec_key": "ab" * 16}):
        conf = TpuShuffleConf(connect_timeout_ms=20000, **extra)
        gen = _make_pair(conf)
        _driver, execs, src = next(gen)
        try:
            peer = execs[1].manager_id
            # a compressible payload (arange bytes repeat mod 256)
            data = execs[0].fetch_blocks(peer, 3,
                                         [(55, 0, 200), (55, 200, 200)])
            assert data == src.buffers[55], extra
        finally:
            for _ in gen:
                pass


def test_corrupted_payload_raises_checksum_error(pair):
    _, execs, _ = pair
    peer = execs[1].manager_id
    injector = FaultInjector(seed=11)
    injector.install_endpoint(execs[0])
    try:
        injector.add(CORRUPT, msg_type=M.FetchBlocksResp, times=1)
        with pytest.raises(ChecksumError):
            execs[0].fetch_blocks(peer, 3, [(55, 0, 100)])
        assert execs[0].checksum_failures >= 1
        # the next (clean) fetch succeeds on the same connection
        assert execs[0].fetch_blocks(peer, 3, [(55, 0, 100)]) \
            == bytes(range(100))
    finally:
        injector.uninstall()


def test_late_response_after_deadline_heals_credits():
    """Per-request deadline + the orphan path end to end: the response
    lands after the deadline, the claim-back fails, and the orphaned
    response still reports its credits so the server window heals."""
    conf = TpuShuffleConf(connect_timeout_ms=20000, request_deadline_ms=120,
                          retry_backoff_base_ms=5, retry_backoff_cap_ms=20)
    gen = _make_pair(conf)
    driver, execs, src = next(gen)
    injector = FaultInjector(seed=5)
    injector.install_endpoint(execs[0])
    try:
        peer = execs[1].manager_id
        injector.add(DELAY, msg_type=M.FetchBlocksResp, delay_s=0.5, times=1)
        with pytest.raises(TimeoutError):
            execs[0].fetch_blocks(peer, 3, [(55, 0, 100)])
        # the delayed response lands orphaned; its pending credit entry
        # must drain via the orphan report
        conn = execs[0]._clients.get(peer.rpc_host, peer.rpc_port)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with execs[0]._fetch_credit_lock:
                if not execs[0]._fetch_credit_pending.get(conn):
                    break
            time.sleep(0.02)
        with execs[0]._fetch_credit_lock:
            assert not execs[0]._fetch_credit_pending.get(conn)
        # window healed: a clean fetch still goes through
        assert execs[0].fetch_blocks(peer, 3, [(55, 0, 100)]) \
            == bytes(range(100))
    finally:
        injector.uninstall()
        for _ in gen:
            pass


def test_heartbeat_declares_silent_peer_suspect():
    """A blackholed (partitioned) peer is detected by missed beats in
    ~2 x interval x misses — not the 20 s connect/request deadline — and
    its outstanding fetch fails the moment suspicion lands."""
    interval_ms = 150
    conf = TpuShuffleConf(connect_timeout_ms=20000,
                          heartbeat_interval_ms=interval_ms,
                          heartbeat_misses=2,
                          retry_backoff_base_ms=5, retry_backoff_cap_ms=20)
    gen = _make_pair(conf)
    driver, execs, src = next(gen)
    injector = FaultInjector(seed=13)
    injector.install_endpoint(execs[0])
    try:
        idx1 = execs[1].exec_index()
        peer = execs[0].member_at(idx1)
        # partition: everything the peer sends back vanishes
        injector.add(BLACKHOLE, peer=(peer.rpc_host, peer.rpc_port))
        handle = execs[0].fetch_blocks_async(peer, 3, [(55, 0, 100)])
        t0 = time.monotonic()
        execs[0].watch_peer(idx1, peer)
        with pytest.raises(TransportError):
            handle.result(timeout=15)
        detect_s = time.monotonic() - t0
        assert execs[0].peer_suspect(idx1)
        assert execs[0].suspect_events == 1
        bound = 2 * (conf.heartbeat_misses + 1) * interval_ms / 1000 + 1.5
        assert detect_s < bound, \
            f"detection took {detect_s:.2f}s (heartbeat should beat TCP)"
        execs[0].unwatch_peer(idx1)
        snap = execs[0].health_snapshot()
        assert snap["suspects"] == [idx1]
    finally:
        injector.uninstall()
        for _ in gen:
            pass


def test_transient_disconnect_is_transparent_to_endpoint_retry():
    """A mid-stream disconnect fails the in-flight request with a
    retryable TransportError; a fresh call re-dials and succeeds."""
    conf = TpuShuffleConf(connect_timeout_ms=20000,
                          retry_backoff_base_ms=5, retry_backoff_cap_ms=20)
    gen = _make_pair(conf)
    driver, execs, src = next(gen)
    injector = FaultInjector(seed=17)
    injector.install_endpoint(execs[0])
    try:
        peer = execs[1].manager_id
        injector.add(DISCONNECT, msg_type=M.FetchBlocksResp, times=1)
        with pytest.raises(TransportError) as ei:
            execs[0].fetch_blocks(peer, 3, [(55, 0, 100)])
        assert getattr(ei.value, "retryable", True)
        assert execs[0].fetch_blocks(peer, 3, [(55, 0, 100)]) \
            == bytes(range(100))
    finally:
        injector.uninstall()
        for _ in gen:
            pass
