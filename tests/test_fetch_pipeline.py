"""Pipelined-fetch tier-1 tests: the read-ahead window's win, measured
deterministically on CPU loopback.

The reference's speedup comes from keeping ``sendQueueDepth / cores``
one-sided READs in flight per channel
(RdmaShuffleFetcherIterator.scala:82-83); these tests drive the same
structure through the Python dataplane with a fixed service delay
standing in for wire latency (shuffle/fetch_bench.py), so the pipelining
win is asserted — not just eyeballed — without TPU hardware, and depth 1
is pinned to today's fully sequential behavior as the regression escape
hatch.
"""

import os
import time

import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.fetch_bench import run_fetch_microbench


def test_read_ahead_depth_resolution():
    """0 = auto (sendQueueDepth / cores, the reference's division),
    explicit values pass through, floor at 1."""
    auto = TpuShuffleConf(send_queue_depth=4096, read_ahead_depth=0)
    assert auto.resolved_read_ahead_depth() == \
        max(1, 4096 // max(1, os.cpu_count() or 1))
    assert TpuShuffleConf(read_ahead_depth=1).resolved_read_ahead_depth() == 1
    assert TpuShuffleConf(read_ahead_depth=7).resolved_read_ahead_depth() == 7
    # auto can never resolve to 0, however many cores the host has
    tiny = TpuShuffleConf(send_queue_depth=16, read_ahead_depth=0)
    assert tiny.resolved_read_ahead_depth() >= 1


def test_pipelined_fetch_faster_and_byte_identical(tmp_path):
    """The acceptance gate: depth >= 4 beats depth 1 by >= 1.5x on a
    latency-injected loopback cluster, fetching byte-identical data.

    96 grouped fetches x 6 ms service delay ~= 1.4 s serialized; a
    window of 8 overlaps the delays on the serving pool (observed ~2.8x
    here), so the margin over the asserted 1.5x is wide and
    deterministic."""
    from sparkrdma_tpu.utils.benchgate import gated_best_of

    res = gated_best_of(
        lambda: run_fetch_microbench(str(tmp_path), depths=(1, 8),
                                     delay_s=0.006, num_partitions=48,
                                     num_maps=2, serve_threads=8, reps=2))
    assert res["identical"], "read-ahead changed the fetched bytes"
    assert res["fetches"] > 0
    assert res["speedup"] >= 1.5, res
    # the deep run must actually have run deep: the per-peer depth
    # histogram (utils/stats.py) saw the window above 1
    per_peer = res["pipeline"]["per_peer"]
    assert any(p["depth"]["max"] >= 2 for p in per_peer.values()), res


def test_pipelined_fetch_emits_phase_spans(tmp_path):
    """With tracing on, a pipelined fetch emits separate
    issue -> wire -> complete spans (utils/trace.py complete_span) so a
    profile can tell queue wait from wire time from decode. The wire
    phase keeps the sequential path's "fetch.blocks" name — one trace
    contract either way."""
    import numpy as np

    from sparkrdma_tpu.shuffle.manager import (
        PartitionerSpec, TpuShuffleManager)
    from sparkrdma_tpu.shuffle.reader import TpuShuffleReader
    from sparkrdma_tpu.utils.trace import Tracer

    conf_kw = dict(connect_timeout_ms=20000, use_cpp_runtime=False)
    driver = TpuShuffleManager(TpuShuffleConf(**conf_kw), is_driver=True)
    execs = [TpuShuffleManager(TpuShuffleConf(**conf_kw),
                               driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(2)]
    try:
        for ex in execs:
            ex.executor.wait_for_members(2)
        handle = driver.register_shuffle(7, 1, 8, PartitionerSpec("modulo"),
                                         row_payload_bytes=8)
        w = execs[0].get_writer(handle, 0)
        keys = np.arange(64, dtype=np.uint64) % 8
        w.write_batch(keys, np.ones((64, 8), dtype=np.uint8))
        w.close()
        tracer = Tracer()
        reader = TpuShuffleReader(
            execs[1].executor, execs[1].resolver,
            TpuShuffleConf(**dict(conf_kw, read_ahead_depth=4)),
            handle.shuffle_id, 1, 0, 8, 8, tracer=tracer)
        reader.read_all()
        names = {e["name"] for e in tracer._events}
        assert {"fetch.locations", "fetch.issue", "fetch.blocks",
                "fetch.complete"} <= names, names
        # spans carry sane non-negative durations
        for e in tracer._events:
            if e["name"].startswith("fetch."):
                assert e["dur"] >= 0.0
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


@pytest.mark.parametrize("enabled", [True, False])
def test_connection_pre_warming(tmp_path, enabled):
    """With pre_warm_connections on, an executor dials its peers the
    moment the announce names them — before any fetch — so the first
    fetch pays no handshake. With it off, no ahead-of-fetch dials
    happen (the lazy path stays intact)."""
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    conf = TpuShuffleConf(connect_timeout_ms=20000, use_cpp_runtime=False,
                          pre_warm_connections=enabled)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(2)]
    try:
        for ex in execs:
            ex.executor.wait_for_members(2)
        if enabled:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if all(ex.executor.prewarm_dials >= 1 for ex in execs):
                    break
                time.sleep(0.02)
            for ex in execs:
                assert ex.executor.prewarm_dials >= 1
                # the dialed connection is in the client cache, live
                assert any(not c.closed for c in
                           ex.executor._clients._conns.values())
        else:
            time.sleep(0.3)  # give a buggy eager dial time to show up
            for ex in execs:
                assert ex.executor.prewarm_dials == 0
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
