"""RDD API end-to-end: the Spark-facing front half compiled onto the DAG
engine. Every action here drives the real SPI sequence (register ->
getWriter per map -> getReader per reduce -> unregister) underneath —
including through executor processes and the mesh data plane."""

import numpy as np
import pytest

from engine_helpers import make_cluster
from sparkrdma_tpu.engine import DAGEngine
from sparkrdma_tpu.rdd import EngineContext, portable_hash, _encode_blob, \
    _decode_blobs


@pytest.fixture
def ctx(tmp_path):
    driver, execs = make_cluster(tmp_path)
    engine = DAGEngine(driver, execs)
    yield EngineContext(engine)
    for ex in execs:
        ex.stop()
    driver.stop()


def test_blob_roundtrip_various_sizes():
    for size in (0, 1, 7, 1016, 1017, 5000):
        obj = list(range(size))
        keys, rows = _encode_blob(obj, part=3, width=1024, map_id=9)
        assert rows.shape[1] == 1024 and (keys == 3).all()
        [back] = list(_decode_blobs([(keys, rows)]))
        assert back == obj


def test_blob_decode_order_independent():
    """Rows from several maps, split across batches, in a SHUFFLED
    order, must reassemble exactly — transports may interleave maps and
    rounds arbitrarily (mesh sorts by key; bounded rounds split maps)."""
    import random
    objs = {m: {"m": m, "data": list(range(1500 * (m + 1)))}
            for m in range(4)}
    all_rows = []
    for m, obj in objs.items():
        keys, rows = _encode_blob(obj, part=0, width=256, map_id=m)
        all_rows += [rows[i] for i in range(len(rows))]
    rng = random.Random(3)
    rng.shuffle(all_rows)
    # deliver as 3 odd-sized batches of interleaved rows
    n = len(all_rows)
    cuts = [0, n // 3, 2 * n // 3, n]
    batches = [(np.zeros(cuts[i + 1] - cuts[i], np.uint64),
                np.stack(all_rows[cuts[i]:cuts[i + 1]]))
               for i in range(3)]
    back = list(_decode_blobs(batches))
    assert sorted(b["m"] for b in back) == [0, 1, 2, 3]
    for b in back:
        assert b == objs[b["m"]]


def test_blob_decode_rejects_corrupt_stream():
    keys, rows = _encode_blob(list(range(400)), part=0, width=128, map_id=0)
    assert len(rows) > 1
    with pytest.raises(ValueError, match="corrupt|truncated"):
        list(_decode_blobs([(keys[:1], rows[:1])]))  # truncated


def test_portable_hash_stability_and_spread():
    # documented-stable values guard cross-process routing compatibility
    assert portable_hash("a") == portable_hash("a")
    assert portable_hash(7) == portable_hash(np.int64(7))
    assert portable_hash((1, "x")) == portable_hash((1, "x"))
    buckets = {portable_hash(i) % 8 for i in range(100)}
    assert len(buckets) == 8  # dense ints spread, not collapse
    # numeric cross-type equality routes to the same partition (True ==
    # 1 == 1.0 must merge under reduce_by_key, like builtin hash)
    assert portable_hash(True) == portable_hash(1) == portable_hash(1.0)
    assert portable_hash(2.5) == portable_hash(np.float64(2.5))

def test_map_filter_collect_count(ctx):
    rdd = ctx.parallelize(range(100), 4)
    assert rdd.map(lambda x: x * 2).filter(lambda x: x % 10 == 0).count() == 20
    assert sorted(rdd.filter(lambda x: x < 5).collect()) == [0, 1, 2, 3, 4]
    assert rdd.count() == 100


def test_flat_map_glom_take_first_reduce(ctx):
    rdd = ctx.parallelize(range(10), 3)
    assert sorted(rdd.flat_map(lambda x: [x, -x]).collect())[:3] == [-9, -8, -7]
    assert sum(len(p) for p in rdd.glom().collect()) == 10
    assert rdd.take(4) == [0, 1, 2, 3]
    assert rdd.first() == 0
    assert rdd.reduce(lambda a, b: a + b) == 45
    with pytest.raises(ValueError, match="empty"):
        ctx.parallelize([], 2).reduce(lambda a, b: a + b)


def test_reduce_by_key_word_count(ctx):
    words = ("the quick brown fox jumps over the lazy dog the end".split())
    counts = dict(ctx.parallelize(words, 3)
                  .map(lambda w: (w, 1))
                  .reduce_by_key(lambda a, b: a + b, 4)
                  .collect())
    assert counts["the"] == 3 and counts["fox"] == 1
    assert sum(counts.values()) == len(words)


def test_salted_reduce_by_key_skewed(ctx):
    """One dominant key (ALS-style power law): the salted two-stage tree
    gives the same totals, with the hot key's partials spread first."""
    pairs = [("hot", 1)] * 500 + [(f"k{i}", 1) for i in range(20)]
    plain = dict(ctx.parallelize(pairs, 4)
                 .reduce_by_key(lambda a, b: a + b, 4).collect())
    salted = dict(ctx.parallelize(pairs, 4)
                  .reduce_by_key(lambda a, b: a + b, 4, salt=8).collect())
    assert salted == plain
    assert salted["hot"] == 500 and salted["k3"] == 1


def test_group_by_key_and_partitioning(ctx):
    pairs = [(i % 5, i) for i in range(50)]
    grouped = ctx.parallelize(pairs, 4).group_by_key(5).collect()
    as_dict = {k: sorted(vs) for k, vs in grouped}
    assert set(as_dict) == set(range(5))
    assert as_dict[2] == list(range(2, 50, 5))


def test_partition_by_places_equal_keys_together(ctx):
    pairs = [(f"k{i % 7}", i) for i in range(70)]
    parts = (ctx.parallelize(pairs, 5).partition_by(4).glom().collect())
    assert sum(len(p) for p in parts) == 70
    seen = {}
    for pid, part in enumerate(parts):
        for k, _v in part:
            assert seen.setdefault(k, pid) == pid, \
                f"key {k} split across partitions"


def test_join(ctx):
    left = ctx.parallelize([(i % 4, f"L{i}") for i in range(8)], 3)
    right = ctx.parallelize([(i % 4, f"R{i}") for i in range(4)], 2)
    joined = left.join(right, 4).collect()
    # every left record matches exactly one right record per key
    assert len(joined) == 8
    for k, (lv, rv) in joined:
        assert lv.startswith("L") and rv.startswith("R")
        assert int(lv[1:]) % 4 == k and int(rv[1:]) % 4 == k


def test_cogroup_keeps_unmatched_keys(ctx):
    left = ctx.parallelize([(1, "a"), (2, "b")], 2)
    right = ctx.parallelize([(2, "x"), (3, "y")], 2)
    got = {k: (sorted(ls), sorted(rs))
           for k, (ls, rs) in left.cogroup(right, 3).collect()}
    assert got == {1: (["a"], []), 2: (["b"], ["x"]), 3: ([], ["y"])}


def test_sort_by_key_global_order(ctx):
    import random
    rng = random.Random(7)
    pairs = [(rng.randint(0, 10_000), i) for i in range(500)]
    out = ctx.parallelize(pairs, 4).sort_by_key(4).collect()
    keys = [k for k, _ in out]
    assert keys == sorted(k for k, _ in pairs)
    parts = (ctx.parallelize(pairs, 4).sort_by_key(4).glom().collect())
    # partition ranges must not overlap (TeraSort's output contract)
    prev_max = None
    for part in parts:
        if not part:
            continue
        if prev_max is not None:
            assert part[0][0] >= prev_max
        prev_max = part[-1][0]


def test_sort_by_key_descending_balanced(ctx):
    """Descending sort must both order globally and keep range
    partitioning balanced (splitters stay ascending; the partition index
    flips — a descending splitter list would break bisect)."""
    pairs = [(i, i) for i in range(400)]
    rdd = ctx.parallelize(pairs, 4).sort_by_key(4, ascending=False)
    keys = [k for k, _ in rdd.collect()]
    assert keys == sorted((k for k, _ in pairs), reverse=True)
    sizes = [len(p) for p in
             ctx.parallelize(pairs, 4).sort_by_key(4, ascending=False)
             .glom().collect()]
    assert len([s for s in sizes if s > 0]) >= 3, \
        f"descending sort degenerated to {sizes}"


def test_sortByKey_pyspark_signature(ctx):
    """``sortByKey(False)`` is pyspark's ascending flag, not a partition
    count — a plain alias would absorb it as num_partitions=False and
    silently sort ascending."""
    pairs = [(i, i) for i in range(100)]
    keys = [k for k, _ in
            ctx.parallelize(pairs, 4).sortByKey(False).collect()]
    assert keys == sorted(range(100), reverse=True)
    keys = [k for k, _ in
            ctx.parallelize(pairs, 4)
            .sortByKey(True, numPartitions=3).collect()]
    assert keys == list(range(100))


def test_num_partitions_validated(ctx):
    rdd = ctx.parallelize([(1, 1)], 2)
    for bad in (0, -1, False, True, 2.0):
        with pytest.raises(ValueError, match="num_partitions"):
            rdd.sort_by_key(bad)


def test_save_as_text_file_missing_part_blocks_success(ctx, tmp_path,
                                                       monkeypatch):
    """_SUCCESS must not commit when a task's part file is absent on the
    driver's filesystem (the unshared-mount failure mode)."""
    import os
    out = tmp_path / "out"
    real_replace = os.replace

    def drop_part_2(src, dst, _r=real_replace):
        _r(src, dst)
        if dst.endswith("part-00002"):
            os.remove(dst)

    monkeypatch.setattr(os, "replace", drop_part_2)
    with pytest.raises(IOError, match="unshared"):
        ctx.parallelize(list(range(40)), 4).save_as_text_file(str(out))
    assert not (out / "_SUCCESS").exists()


def test_first_on_empty_rdd_raises_value_error(ctx):
    with pytest.raises(ValueError, match="empty"):
        ctx.parallelize([], 2).first()


def test_distinct_and_chained_wide_ops(ctx):
    data = [i % 10 for i in range(100)]
    assert sorted(ctx.parallelize(data, 4).distinct(3).collect()) == \
        list(range(10))
    # two shuffles back to back: reduce_by_key then sort_by_key
    out = (ctx.parallelize([(i % 6, 1) for i in range(60)], 4)
           .reduce_by_key(lambda a, b: a + b, 3)
           .sort_by_key(2)
           .collect())
    assert out == [(k, 10) for k in range(6)]


def test_text_file_split_boundaries_exact(ctx, tmp_path):
    """Byte-range splits at line granularity: every line exactly once,
    whatever the split points land on (the Hadoop input-split rule)."""
    lines = [f"line-{i:04d}-{'x' * (i % 23)}" for i in range(500)]
    p = tmp_path / "in.txt"
    p.write_text("\n".join(lines) + "\n")
    for slices in (1, 3, 7, 16):
        got = ctx.text_file(str(p), slices).collect()
        assert sorted(got) == sorted(lines), f"slices={slices}"
    assert ctx.text_file(str(p), 4).count() == 500


def test_text_file_glob_and_empty(ctx, tmp_path):
    (tmp_path / "a.txt").write_text("alpha\nbeta\n")
    (tmp_path / "b.txt").write_text("gamma\n")
    (tmp_path / "c.txt").write_text("")  # empty file contributes nothing
    got = sorted(ctx.text_file(str(tmp_path / "*.txt"), 4).collect())
    assert got == ["alpha", "beta", "gamma"]
    with pytest.raises(FileNotFoundError):
        ctx.text_file(str(tmp_path / "missing.txt"), 2).count()


def test_save_as_text_file_roundtrip(ctx, tmp_path):
    out = tmp_path / "out"
    (ctx.parallelize(range(100), 4)
     .map(lambda x: (x % 10, x))
     .reduce_by_key(lambda a, b: a + b, 3)
     .sort_by_key(3)
     .map(lambda kv: f"{kv[0]}\t{kv[1]}")
     .save_as_text_file(str(out)))
    assert (out / "_SUCCESS").exists()
    parts = sorted(out.glob("part-*"))
    assert len(parts) == 3
    back = [ln for p in parts for ln in p.read_text().splitlines()]
    assert back == [f"{k}\t{sum(range(k, 100, 10))}" for k in range(10)]


def test_save_as_text_file_clears_stale_parts(ctx, tmp_path):
    """A re-run with fewer partitions must not leave a previous run's
    extra part files under a fresh _SUCCESS."""
    out = tmp_path / "out"
    ctx.parallelize(range(8), 4).save_as_text_file(str(out))
    assert len(list(out.glob("part-*"))) == 4
    ctx.parallelize(range(4), 2).save_as_text_file(str(out))
    parts = sorted(out.glob("part-*"))
    assert len(parts) == 2
    got = sorted(int(x) for p in parts for x in p.read_text().split())
    assert got == [0, 1, 2, 3]


def test_text_file_crlf_terminators(ctx, tmp_path):
    p = tmp_path / "crlf.txt"
    p.write_bytes(b"alpha\r\nbeta\r\ngamma\n")
    assert sorted(ctx.text_file(str(p), 2).collect()) == \
        ["alpha", "beta", "gamma"]


def test_accumulator_and_broadcast_through_rdd(ctx):
    factor = ctx.broadcast(10)
    acc = ctx.accumulator("rows")

    def bump(x, _a=acc, _f=factor):
        _a.add(1)
        return x * _f.value

    got = sorted(ctx.parallelize(range(20), 4).map(bump).collect())
    assert got == [i * 10 for i in range(20)]
    assert acc.value == 20


from sparkrdma_tpu.shuffle.writer import make_sum_combiner

_sum_combiner = make_sum_combiner("<u4")  # the shipped per-key-sum combiner


@pytest.fixture
def batch_data():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 50, 4000).astype(np.uint64)
    vals = rng.integers(0, 1000, 4000).astype(np.uint32)
    return keys, vals


def test_batch_rdd_repartition_exact(ctx, batch_data):
    keys, vals = batch_data
    parts = (ctx.from_arrays(keys, vals[:, None], 4)
             .repartition(6).collect_batches())
    assert len(parts) == 6
    got_k = np.concatenate([k for k, _ in parts])
    got_v = np.concatenate([p.view(np.uint32)[:, 0] for _, p in parts])
    # same multiset of records, and every key lives in one partition
    assert sorted(zip(got_k.tolist(), got_v.tolist())) == \
        sorted(zip(keys.tolist(), vals.tolist()))
    owner = {}
    for pid, (k, _p) in enumerate(parts):
        for key in np.unique(k):
            assert owner.setdefault(int(key), pid) == pid


def test_batch_rdd_reduce_by_key_sum(ctx, batch_data):
    keys, vals = batch_data
    parts = (ctx.from_arrays(keys, vals[:, None], 5)
             .reduce_by_key(_sum_combiner, 3).collect_batches())
    got = {}
    for k, p in parts:
        for key, s in zip(k, p.view(np.uint32)[:, 0]):
            assert int(key) not in got, "key combined in two partitions"
            got[int(key)] = int(s)
    want = {int(k): int(vals[keys == k].sum()) for k in np.unique(keys)}
    assert got == want


def test_batch_rdd_sort_by_key_global(ctx, batch_data):
    keys, vals = batch_data
    parts = (ctx.from_arrays(keys, vals[:, None], 4)
             .sort_by_key(4).collect_batches())
    prev_max = -1
    total = 0
    for k, _p in parts:
        total += len(k)
        if len(k):
            assert (np.diff(k.astype(np.int64)) >= 0).all()
            assert int(k[0]) >= prev_max
            prev_max = int(k[-1])
    assert total == len(keys)


def test_batch_rdd_map_batches_width_change(ctx, batch_data):
    keys, vals = batch_data

    def widen(k, p):
        v = p.view(np.uint32)[:, 0].astype(np.uint64)
        return k, (v * 2)[:, None].view(np.uint8)

    parts = (ctx.from_arrays(keys, vals[:, None], 3)
             .map_batches(widen, payload_bytes=8)
             .repartition(2).collect_batches())
    got = np.concatenate([p.view(np.uint64)[:, 0] for _, p in parts])
    assert sorted(got.tolist()) == sorted((vals * 2).tolist())


def test_batch_rdd_combiner_empty_partitions(ctx):
    """More partitions than distinct keys: empty reduce partitions must
    not feed the combiner zero rows (the writer-side contract)."""
    keys = np.array([1, 1, 2, 2, 3], np.uint64)
    vals = np.arange(5, dtype=np.uint32)
    parts = (ctx.from_arrays(keys, vals[:, None], 2)
             .reduce_by_key(_sum_combiner, 8).collect_batches())
    got = {int(k): int(s) for kk, p in parts
           for k, s in zip(kk, p.view(np.uint32)[:, 0])}
    assert got == {1: 1, 2: 5, 3: 4}


def test_batch_rdd_sort_keys_near_u64_max(ctx):
    """Range splitters must come from the integer sample — float64
    quantiles round keys near 2**64 out of the uint64 range."""
    keys = np.array([2**64 - 1, 2**64 - 2, 5, 2**63, 2**64 - 3, 1],
                    np.uint64)
    vals = np.arange(6, dtype=np.uint32)
    parts = (ctx.from_arrays(keys, vals[:, None], 2)
             .sort_by_key(3).collect_batches())
    allk = np.concatenate([k for k, _ in parts])
    assert allk.tolist() == sorted(keys.tolist())


def test_batch_rdd_1d_payload(ctx):
    """A natural 1-D value array is a supported payload: rows are its
    itemsize-wide bytes (regression: the u8 view must not multiply the
    row count)."""
    keys = np.arange(40, dtype=np.uint64)
    vals = (keys * 3).astype(np.uint32)
    parts = ctx.from_arrays(keys, vals, 3).repartition(2).collect_batches()
    got = sorted((int(k), int(v)) for kk, p in parts
                 for k, v in zip(kk, p.view(np.uint32)[:, 0]))
    assert got == [(i, 3 * i) for i in range(40)]


def test_batch_rdd_empty_and_single_row(ctx):
    e = ctx.from_arrays(np.zeros(0, np.uint64), np.zeros((0, 4), np.uint8), 2)
    assert e.repartition(3).count() == 0
    one = ctx.from_arrays(np.array([7], np.uint64),
                          np.array([[1, 2, 3, 4]], np.uint8), 2)
    [(k, p)] = [b for b in one.repartition(2).collect_batches() if len(b[0])]
    assert k.tolist() == [7] and p.tolist() == [[1, 2, 3, 4]]


def test_batch_rdd_on_mesh(tmp_path, batch_data):
    """Batch shuffles ride the ICI plane under a mesh engine; aggregates
    stay exact."""
    import jax
    from jax.sharding import Mesh

    keys, vals = batch_data
    driver, execs = make_cluster(tmp_path)
    try:
        mesh = Mesh(np.array(jax.devices()[:4]), ("shuffle",))
        ctx = EngineContext(DAGEngine(driver, execs, mesh=mesh))
        parts = (ctx.from_arrays(keys, vals[:, None], 4)
                 .reduce_by_key(_sum_combiner, 4).collect_batches())
        got = {int(k): int(s) for kk, p in parts
               for k, s in zip(kk, p.view(np.uint32)[:, 0])}
        want = {int(k): int(vals[keys == k].sum()) for k in np.unique(keys)}
        assert got == want
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()


def test_rdd_through_remote_executors(tmp_path):
    """The same plans run when tasks ship to executor PROCESSES —
    closures, broadcast source partitions, and blob shuffles all cross
    the process boundary."""
    import subprocess
    import sys

    from test_remote_engine import _WORKER, CONF
    from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager
    from sparkrdma_tpu.tasks import remote_executors

    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    host, port = driver.driverAddr
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, host, str(port), f"w{i}",
         str(tmp_path / f"w{i}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    remotes = []
    try:
        remotes = remote_executors(driver, CONF, expect=2, timeout=30)
        ctx = EngineContext(DAGEngine(driver, remotes))
        counts = dict(ctx.parallelize([(i % 3, 1) for i in range(30)], 3)
                      .reduce_by_key(lambda a, b: a + b, 3)
                      .collect())
        assert counts == {0: 10, 1: 10, 2: 10}
    finally:
        for p in procs:
            p.kill()
        for r in remotes:
            r.stop()
        driver.stop()


def test_materialize_caches_lineage(ctx):
    """materialize() evaluates once; downstream actions replay the
    cached partitions, not the upstream lineage."""
    evals = ctx.accumulator("evals")

    def counting(x, _a=evals):
        _a.add(1)
        return (x % 4, x)

    cached = ctx.parallelize(range(40), 4).map(counting).materialize()
    assert evals.value == 40
    assert cached.num_partitions == 4
    assert sorted(cached.values().collect()) == list(range(40))
    assert cached.reduce_by_key(lambda a, b: a + b, 2).count() == 4
    assert evals.value == 40  # lineage never re-ran


def test_union_narrow_and_wide(ctx):
    """union() of source RDDs is narrow (no shuffle stage); union with
    a shuffled side routes through identity exchanges — same records
    either way, partitions in argument order."""
    a = ctx.parallelize(range(10), 3)
    b = ctx.parallelize(range(100, 106), 2)
    u = a.union(b)
    assert u.num_partitions == 5
    assert sorted(u.collect()) == sorted(list(range(10))
                                         + list(range(100, 106)))
    # chained unions flatten, order preserved
    c = ctx.parallelize([999], 1)
    assert a.union(b).union(c).collect()[-1] == 999
    # wide side: a reduce_by_key result unioned with a plain source
    pairs = ctx.parallelize([(i % 3, 1) for i in range(30)], 3) \
        .reduce_by_key(lambda x, y: x + y, 3)
    extra = ctx.parallelize([(9, 99)], 1)
    got = sorted(pairs.union(extra).collect())
    assert got == [(0, 10), (1, 10), (2, 10), (9, 99)]


def test_coalesce_narrow_contiguous_and_shuffle_grow(ctx):
    rdd = ctx.parallelize(range(12), 6)
    small = rdd.coalesce(2)
    assert small.num_partitions == 2
    parts = small.glom().collect()
    # narrow fan-in: each new partition is a contiguous range of old ones
    assert [sorted(p) for p in parts] == [[0, 1, 2, 3, 4, 5],
                                          [6, 7, 8, 9, 10, 11]]
    # coalesce never grows without shuffle=True
    assert rdd.coalesce(64).num_partitions == 6
    grown = rdd.coalesce(9, shuffle=True)
    assert grown.num_partitions == 9
    assert sorted(grown.collect()) == list(range(12))
    # repartition balances a skewed layout
    skewed = ctx.parallelize(range(100), 1).repartition(4)
    sizes = [len(p) for p in skewed.glom().collect()]
    assert sorted(skewed.collect()) == list(range(100))
    assert max(sizes) - min(sizes) <= 1


def test_wide_union_composes_downstream(ctx):
    """A wide union is a real chain boundary: coalescing it, unioning it
    again, or shuffling above it must compile correctly (regression —
    the wide-union build once claimed to be boundary-free and broke
    every downstream narrow-vs-shuffle decision)."""
    pairs = ctx.parallelize([(i % 3, 1) for i in range(30)], 3) \
        .reduce_by_key(lambda x, y: x + y, 3)
    extra = ctx.parallelize([(9, 99)], 1)
    u = pairs.union(extra)
    assert sorted(u.coalesce(2).collect()) == \
        [(0, 10), (1, 10), (2, 10), (9, 99)]
    more = ctx.parallelize([(7, 7)], 1)
    assert sorted(u.map(lambda kv: kv).union(more).collect()) == \
        [(0, 10), (1, 10), (2, 10), (7, 7), (9, 99)]
    assert dict(u.reduce_by_key(lambda a, b: a + b, 2).collect()) == \
        {0: 10, 1: 10, 2: 10, 9: 99}


def test_wide_union_shuffles_each_side_once(ctx):
    """A wide union consumed twice in one job compiles ONE identity
    exchange per side: the per-side _Shuffled wrappers are memoized on
    the _Union node (like _Coalesce._shuffled), so the _shuffle_stage
    memo can dedupe across consumptions instead of shuffling each
    side's data twice."""
    from sparkrdma_tpu.rdd import _chain

    pairs = ctx.parallelize([(i % 3, 1) for i in range(30)], 3) \
        .reduce_by_key(lambda x, y: x + y, 3)
    extra = ctx.parallelize([(9, 99)], 1)
    u = pairs.union(extra)
    memo: dict = {}
    _, stages1 = _chain(u._node, memo, u._ctx)
    _, stages2 = _chain(u._node, memo, u._ctx)
    assert stages1 and [id(s) for s in stages1] == [id(s) for s in stages2]
    # and end-to-end: a self-join (the union consumed on both cogroup
    # sides of one job) still produces the right records
    got = sorted(u.join(u).collect())
    assert got == [(0, (10, 10)), (1, (10, 10)), (2, (10, 10)),
                   (9, (99, 99))]


def test_coalesce_below_shuffle_boundary(ctx):
    """coalesce after a wide op compiles to an identity-routed exchange
    (tasks here read only their own partition) — records survive and
    land in the right fan-in partition."""
    counts = (ctx.parallelize([(i % 6, 1) for i in range(60)], 4)
              .reduce_by_key(lambda a, b: a + b, 6)
              .coalesce(2))
    assert counts.num_partitions == 2
    assert sorted(counts.collect()) == [(k, 10) for k in range(6)]


def test_coalesce_shuffle_fallback_layout_matches_narrow_ranges(ctx):
    """When P is not a multiple of n, the shuffle fallback's routing
    must be the EXACT inverse of the narrow path's [i*P//n, (i+1)*P//n)
    ranges (bisect over those boundaries): parent partition 2 of P=5,
    n=2 belongs to output partition 1 on BOTH paths (the old t*n//P
    routing put it in 0)."""
    P, n = 5, 2
    parent = (ctx.parallelize([(i % P, 1) for i in range(50)], 4)
              .reduce_by_key(lambda a, b: a + b, P))
    parent_parts = parent.glom().collect()
    parts = [sorted(p) for p in parent.coalesce(n).glom().collect()]
    # narrow-path contract: output i covers parents [i*P//n, (i+1)*P//n)
    expect = [sorted(kv for j in range(i * P // n, (i + 1) * P // n)
                     for kv in parent_parts[j])
              for i in range(n)]
    assert parts == expect, parts
    # narrow path on the same shape agrees (the documented contiguity)
    narrow = ctx.parallelize(range(P), P).coalesce(n)
    assert [sorted(p) for p in narrow.glom().collect()] == \
        [list(range(i * P // n, (i + 1) * P // n)) for i in range(n)]


def test_aggregate_by_key_mutable_zero(ctx):
    """aggregateByKey with a mutable zero ([]): each key must get its
    own accumulator (deep-copied), and value/combiner types differ."""
    pairs = [(i % 3, i) for i in range(12)]
    got = dict(ctx.parallelize(pairs, 4)
               .aggregate_by_key([], lambda acc, v: acc + [v],
                                 lambda a, b: a + b, 2)
               .map_values(sorted)
               .collect())
    assert got == {k: sorted(v for i, v in pairs if i == k)
                   for k in range(3)}


def test_combine_by_key_mean(ctx):
    """The classic combineByKey use: per-key mean via (sum, count)
    combiners — a shape reduceByKey cannot express."""
    pairs = [("a", 2.0), ("b", 4.0), ("a", 4.0), ("b", 6.0), ("a", 6.0)]
    sums = dict(ctx.parallelize(pairs, 3)
                .combine_by_key(lambda v: (v, 1),
                                lambda c, v: (c[0] + v, c[1] + 1),
                                lambda c1, c2: (c1[0] + c2[0],
                                                c1[1] + c2[1]), 2)
                .map_values(lambda c: c[0] / c[1])
                .collect())
    assert sums == {"a": 4.0, "b": 5.0}
    folded = dict(ctx.parallelize([(1, 2), (1, 3), (2, 5)], 2)
                  .fold_by_key(0, lambda a, b: a + b, 2).collect())
    assert folded == {1: 5, 2: 5}


def test_persist_skips_upstream_stages(ctx):
    """persist(): the first action materializes the pinned shuffle; later
    actions SKIP the whole upstream DAG (accumulator proves the map fn
    never re-runs — Spark's skipped-stages semantics); unpersist()
    releases it and lineage runs again."""
    evals = ctx.accumulator("evals")

    def counting(x, _a=evals):
        _a.add(1)
        return (x % 4, x)

    cached = ctx.parallelize(range(40), 4).map(counting).persist()
    assert cached.is_cached
    assert sorted(cached.values().collect()) == list(range(40))
    assert evals.value == 40
    # second + third actions: upstream skipped entirely
    assert cached.count() == 40
    assert cached.reduce_by_key(lambda a, b: a + b, 2).count() == 4
    assert evals.value == 40
    # engine retains exactly the pinned stage's shuffle
    assert len(ctx.engine._handles) == 1
    cached.unpersist()
    assert not cached.is_cached
    assert len(ctx.engine._handles) == 0
    assert cached.count() == 40
    assert evals.value == 80  # lineage re-ran after unpersist


def test_persist_recovery_through_cached_rdd(tmp_path):
    """Kill the executor PROCESS holding part of a cached RDD between
    actions: the next action's read hits FetchFailed and stage retry
    recomputes ONLY the lost partitions from the pinned stage's captured
    lineage — true lineage recovery through a cached RDD."""
    import subprocess
    import sys
    import time

    from test_remote_engine import _WORKER, CONF
    from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager
    from sparkrdma_tpu.tasks import remote_executors

    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    host, port = driver.driverAddr
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, host, str(port), f"w{i}",
         str(tmp_path / f"w{i}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    remotes = []
    try:
        remotes = remote_executors(driver, CONF, expect=2, timeout=30)
        ctx = EngineContext(DAGEngine(driver, remotes))
        cached = (ctx.parallelize([(i % 5, 1) for i in range(200)], 4)
                  .reduce_by_key(lambda a, b: a + b, 4)
                  .persist())
        assert dict(cached.collect()) == {k: 40 for k in range(5)}

        victim = remotes[1]
        victim_proc = procs[int(victim.manager_id.executor_id.executor[1:])]
        victim_proc.kill()
        victim_proc.wait()
        driver.native.driver.remove_member(victim.manager_id)
        time.sleep(0.2)

        # both a plain replay and a downstream wide op must survive
        assert dict(cached.collect()) == {k: 40 for k in range(5)}
        assert dict(cached.map_values(lambda v: v * 2)
                    .reduce_by_key(lambda a, b: a + b, 2)
                    .collect()) == {k: 80 for k in range(5)}
    finally:
        for p in procs:
            p.kill()
        for r in remotes:
            r.stop()
        driver.stop()


def test_rdd_pagerank_matches_oracle(ctx):
    """PageRank written in ~15 lines of RDD code (the classic Spark
    program, and BASELINE config #3's shape) agrees with the in-tree
    dense numpy oracle."""
    from sparkrdma_tpu.models.pagerank import numpy_pagerank

    rng = np.random.default_rng(5)
    V, E, iters, damping = 64, 400, 5, 0.85
    edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)],
                     axis=1).astype(np.int32)
    want = numpy_pagerank(edges, V, damping, iters)

    links = (ctx.parallelize([(int(s), int(d)) for s, d in edges], 4)
             .group_by_key(4))  # (src, [dsts]) — stays partitioned
    ranks = {v: 1.0 / V for v in range(V)}
    for _ in range(iters):
        rb = ctx.broadcast(ranks)
        contribs = links.flat_map(
            lambda kv, _r=rb: [(d, _r.value[kv[0]] / len(kv[1]))
                               for d in kv[1]])
        sums = dict(contribs.reduce_by_key(lambda a, b: a + b, 4).collect())
        ranks = {v: (1 - damping) / V + damping * sums.get(v, 0.0)
                 for v in range(V)}
    got = np.array([ranks[v] for v in range(V)], dtype=np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_rdd_recovers_from_executor_process_loss(tmp_path):
    """Kill an executor PROCESS mid-RDD-job: lineage recomputation must
    rebuild the lost map outputs and the word counts stay exact — the
    Spark recompute story driven from the RDD surface."""
    import subprocess
    import sys
    import threading
    import time

    from test_remote_engine import _WORKER, CONF
    from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager
    from sparkrdma_tpu.tasks import remote_executors

    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    host, port = driver.driverAddr
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, host, str(port), f"w{i}",
         str(tmp_path / f"w{i}")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)]
    remotes = []
    try:
        remotes = remote_executors(driver, CONF, expect=2, timeout=30)
        sentinel = tmp_path / "reduce-running"
        spath = str(sentinel)

        def slow_identity(it, _s=spath):
            got = list(it)
            open(_s, "a").write("x")
            time.sleep(1.5)  # window for the kill
            return iter(got)

        victim = remotes[1]
        victim_proc = procs[int(victim.manager_id.executor_id.executor[1:])]

        def killer():
            deadline = time.monotonic() + 30
            while not sentinel.exists() and time.monotonic() < deadline:
                time.sleep(0.02)
            victim_proc.kill()
            driver.native.driver.remove_member(victim.manager_id)

        k = threading.Thread(target=killer, daemon=True)
        k.start()
        ctx = EngineContext(DAGEngine(driver, remotes))
        counts = dict(ctx.parallelize([(i % 5, 1) for i in range(200)], 4)
                      .reduce_by_key(lambda a, b: a + b, 3)
                      .map_partitions(slow_identity)
                      .collect())
        k.join(timeout=10)
        assert sentinel.exists(), "failure injection never armed"
        assert counts == {k: 40 for k in range(5)}
    finally:
        for p in procs:
            p.kill()
        for r in remotes:
            r.stop()
        driver.stop()


def test_rdd_on_mesh_data_plane(tmp_path):
    """RDD shuffles ride the ICI collective plane when the engine has a
    mesh: same results, blob framing intact through the device exchange."""
    import jax
    from jax.sharding import Mesh

    driver, execs = make_cluster(tmp_path)
    try:
        mesh = Mesh(np.array(jax.devices()[:4]), ("shuffle",))
        engine = DAGEngine(driver, execs, mesh=mesh)
        ctx = EngineContext(engine)
        counts = dict(ctx.parallelize([(i % 4, 1) for i in range(40)], 4)
                      .reduce_by_key(lambda a, b: a + b, 4)
                      .collect())
        assert counts == {k: 10 for k in range(4)}
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
