"""Shared helpers for the DAG-engine test suites (test_engine.py,
test_engine_mesh.py): row codecs, deterministic tables, and the 3-executor
in-process compat cluster."""

import numpy as np

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.shuffle.spark_compat import SparkCompatShuffleManager

CONF = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2)


def u32_payload(values) -> np.ndarray:
    return np.ascontiguousarray(values, dtype="<u4").view(np.uint8).reshape(-1, 4)


def payload_u32(payload: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(payload).view("<u4").ravel()


def make_table(seed: int, rows: int, key_space: int):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, key_space, size=rows).astype(np.uint64)
    vals = rng.integers(0, 1000, size=rows).astype(np.uint32)
    return keys, vals


def make_cluster(tmp_path, n: int = 3):
    """(driver, executors) with membership settled; caller stops them."""
    driver = SparkCompatShuffleManager(CONF, isDriver=True)
    execs = [SparkCompatShuffleManager(
        CONF, driverAddr=driver.driverAddr, executorId=str(i),
        spill_dir=str(tmp_path / f"e{i}")) for i in range(n)]
    for ex in execs:
        ex.native.executor.wait_for_members(n)
    return driver, execs


def lockgraph_module_guard():
    """Shared body of the CHAOS_LOCKGRAPH module fixtures
    (tests/test_chaos.py, tests/test_membership.py): install the
    lock-order shim, snapshot pre-existing cycles (a session-wide
    ANALYSIS_LOCKGRAPH shim shares the graph — blame only cycles that
    appear DURING the module), and on teardown fail on any new cycle.
    Generator: fixtures drive it with ``yield from``."""
    from sparkrdma_tpu.analysis import lockgraph

    owned = lockgraph.current() is None
    graph = lockgraph.install()
    pre = {tuple(c) for c in graph.cycles()}
    yield
    if owned:
        lockgraph.uninstall()
    new = [c for c in graph.cycles() if tuple(c) not in pre]
    assert not new, graph.format_cycles()
