"""BASELINE config #2 dress rehearsal at environment scale.

The reference's headline run is TeraSort-320GB across 7 workers
(reference README.md:11-17). This environment has one host and a virtual
8-device CPU mesh, so the rehearsal scales the *shape* of that run, not
its size: a dataset many times one round's device capacity, streamed
through R >= 32 bounded rounds, with the host's address space capped so
any per-round memory leak (e.g. the out_factor-sized round buffers
surviving past their round) aborts the run instead of silently paging.

Runs in a subprocess: RLIMIT_AS must not poison the shared test process,
and jax must initialize fresh under the cap-free generation phase.
Size is env-tunable (REHEARSAL_MB, default 512 — "GB-class" for a CPU
mesh; real hardware rehearsals raise it).
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import json, os, resource, sys, time
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import Mesh
from sparkrdma_tpu.models.terasort import (
    TeraSortConfig, run_terasort_streamed)

D = 8
size_mb = {size_mb}
row_words = 25  # 100-byte classic TeraSort rows
rows_total = (size_mb << 20) // (4 * row_words)
# >= 32 rounds: per-round capacity is ceil(total / 32) rows over D devices
rows_per_device = -(-rows_total // (32 * D))
cfg = TeraSortConfig(rows_per_device=rows_per_device, payload_words=24,
                     out_factor=2)
rows = np.random.default_rng(7).integers(
    0, 2**32, size=(rows_total, row_words), dtype=np.uint32)
data_bytes = rows.nbytes

# Warm/compile the step BEFORE the cap: XLA compilation transiently maps
# large address ranges that have nothing to do with the streaming path
# under test.
mesh = Mesh(np.array(jax.devices()[:D]), ("shuffle",))
warm = {{}}
run_terasort_streamed(mesh, cfg, rows[: D * cfg.rows_per_device],
                      phase_times=warm)

# Cap the address space: current usage + the streaming path's legitimate
# needs (per-device runs ~= dataset, merged output ~= dataset, two
# pipelined rounds of out_factor-sized buffers) + slack. A leak that
# retains per-round buffers across rounds costs ~2x dataset extra and
# blows the cap.
with open("/proc/self/status") as f:
    vm_kb = next(int(l.split()[1]) for l in f if l.startswith("VmSize"))
headroom = int(2.4 * data_bytes) + (512 << 20)
cap = (vm_kb << 10) + headroom
resource.setrlimit(resource.RLIMIT_AS, (cap, cap))
try:
    np.zeros(headroom + (64 << 20), np.uint8)
    print("CAP-NOT-EFFECTIVE")
except MemoryError:
    pass

phases = {{}}
t0 = time.perf_counter()
merged, rounds = run_terasort_streamed(mesh, cfg, rows, phase_times=phases)
wall = time.perf_counter() - t0
assert rounds >= 32, rounds

# exact global sort: per-device sorted, ranges non-overlapping in device
# order, multiset of keys preserved
prev_max = -1
got = []
for d, out in enumerate(merged):
    keys = out[:, 0].astype(np.int64)
    if len(keys):
        assert (np.diff(keys) >= 0).all(), f"device {{d}} unsorted"
        assert keys[0] >= prev_max, f"device {{d}} overlaps previous"
        prev_max = int(keys[-1])
    got.append(keys)
got = np.concatenate(got)
assert len(got) == rows_total, (len(got), rows_total)
np.testing.assert_array_equal(np.sort(got),
                              np.sort(rows[:, 0].astype(np.int64)))

print("PHASES=" + json.dumps({{
    "data_mb": size_mb, "rounds": rounds, "wall_s": round(wall, 2),
    "stage_s": round(phases["stage_s"], 2),
    "collect_s": round(phases["collect_s"], 2),
    "merge_s": round(phases["merge_s"], 2),
    "throughput_mb_s": round(size_mb / wall, 1)}}))
print("REHEARSAL-OK")
"""


def test_streamed_terasort_gb_class_rehearsal():
    size_mb = int(os.environ.get("REHEARSAL_MB", "512"))
    script = _SCRIPT.format(repo=_REPO, size_mb=size_mb)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # script pins cpu itself
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=880,
                          env=env)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-3000:])
    if "CAP-NOT-EFFECTIVE" in proc.stdout:
        pytest.skip("RLIMIT_AS not enforceable on this platform")
    assert "REHEARSAL-OK" in proc.stdout
    phases = json.loads(next(
        ln for ln in proc.stdout.splitlines()
        if ln.startswith("PHASES=")).split("=", 1)[1])
    # the per-phase log IS the rehearsal evidence — surface it in the
    # test report even on success
    print("\nrehearsal phases:", json.dumps(phases))
    assert phases["rounds"] >= 32


_ALS_SCRIPT = r"""
import json, os, resource, sys, time
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import Mesh
from sparkrdma_tpu.models.als import (
    ALSConfig, als_half_step, generate_ratings, rmse)

D = 8
size_mb = {size_mb}
rows_total = (size_mb << 20) // 12          # (item, user, rating) u32 rows
per_device = rows_total // D
num_items = max(1 << 14, rows_total // 64)
num_users = max(D, (rows_total // 10) // D * D)
cfg = ALSConfig(num_users=num_users, num_items=num_items, rank=8,
                zipf_a=1.3)
ratings = generate_ratings(cfg, D, per_device, seed=11)
data_bytes = ratings.nbytes

# pick the quota so the SKEWED (item) side streams in MANY bounded
# rounds (rounds = ceil(max pair count / quota)). Small rounds are also
# what keeps the 8 virtual devices' collective rendezvous tight on a
# low-core host: participants arrive within the per-round work spread,
# and XLA:CPU aborts a collective whose participants stagger > 40s.
pair_max = 0
for d in range(D):
    seg = ratings[d * per_device:(d + 1) * per_device]
    pair_max = max(pair_max, int(np.bincount(
        (seg[:, 0] % D).astype(np.int64), minlength=D).max()))
quota = max(1024, -(-pair_max // 400))

mesh = Mesh(np.array(jax.devices()[:D]), ("shuffle",))
rng = np.random.default_rng(11)
user_factors = (rng.standard_normal((cfg.num_users, cfg.rank))
                .astype(np.float32) / np.sqrt(cfg.rank))

# warm/compile both chunked-exchange directions on a small slice BEFORE
# the cap (XLA compilation transiently maps large address ranges)
warm_rows = ratings[: D * 4096].copy()
als_half_step(mesh, cfg, warm_rows, user_factors, quota, key_col=0)
als_half_step(mesh, cfg, warm_rows, user_factors, quota, key_col=1)

with open("/proc/self/status") as f:
    vm_kb = next(int(l.split()[1]) for l in f if l.startswith("VmSize"))
# legitimate peaks: grouped copy (~1x data), device-resident accumulator
# + host view (~2.5x with skew), per-device received copies (~1x),
# solve transients + fresh shape compiles (slack)
headroom = int(5.0 * data_bytes) + (1536 << 20)
cap = (vm_kb << 10) + headroom
resource.setrlimit(resource.RLIMIT_AS, (cap, cap))
try:
    np.zeros(headroom + (64 << 20), np.uint8)
    print("CAP-NOT-EFFECTIVE")
except MemoryError:
    pass

t0 = time.perf_counter()
item_factors, rounds_i = als_half_step(mesh, cfg, ratings, user_factors,
                                       quota, key_col=0)
user_factors2, rounds_u = als_half_step(mesh, cfg, ratings, item_factors,
                                        quota, key_col=1)
wall = time.perf_counter() - t0
assert rounds_i >= 32, rounds_i

e0 = rmse(ratings, user_factors, np.zeros_like(item_factors), 100_000)
e1 = rmse(ratings, user_factors2, item_factors, 100_000)
assert e1 < e0 * 0.6, (e0, e1)

print("ALS=" + json.dumps({{
    "data_mb": size_mb, "ratings": rows_total,
    "rounds_item": rounds_i, "rounds_user": rounds_u,
    "wall_s": round(wall, 2),
    "ratings_per_s": round(rows_total * 2 / wall, 0),
    "rmse_init": round(e0, 4), "rmse_after_sweep": round(e1, 4)}}))
print("ALS-REHEARSAL-OK")
"""


@pytest.mark.slow
def test_als_zipf_rehearsal_memory_bounded():
    """Config #5 at environment scale: >=512 MB of zipf-skewed ratings
    through one full alternating sweep (two skewed shuffles) with the
    address space capped — the bounded-round exchange must hold its
    memory contract at data sizes where a leak aborts the run.

    Marked slow: the sweep's ~800 bounded exchange rounds take longer
    than the entire tier-1 wall-clock budget on a CPU host, which
    starved every alphabetically-later test file out of the tier-1 run
    entirely. The default `-m 'not slow'` filter skips it; run it
    explicitly (or at reduced REHEARSAL_ALS_MB) when touching the
    exchange or ALS paths."""
    size_mb = int(os.environ.get("REHEARSAL_ALS_MB", "512"))
    script = _ALS_SCRIPT.format(repo=_REPO, size_mb=size_mb)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=880,
                          env=env)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-3000:])
    if "CAP-NOT-EFFECTIVE" in proc.stdout:
        pytest.skip("RLIMIT_AS not enforceable on this platform")
    assert "ALS-REHEARSAL-OK" in proc.stdout
    stats = json.loads(next(
        ln for ln in proc.stdout.splitlines()
        if ln.startswith("ALS=")).split("=", 1)[1])
    assert stats["rounds_item"] >= 32
    assert stats["rmse_after_sweep"] < stats["rmse_init"]
