"""Seeded chaos scenarios: end-to-end shuffle reduces under injected
faults, asserting byte-identical results via refetch/recompute.

Every scenario builds a real driver + multi-executor cluster over
loopback, scripts faults through the :mod:`sparkrdma_tpu.parallel.faults`
shim (seeded — a failing run replays exactly from the seed printed in
the assertion message), runs a reduce through the hardened path, and
checks the result against the fault-free ground truth.

Fast scenarios run in tier-1 (marked ``chaos``); the wide sweep is
``chaos + slow`` and driven by ``scripts/run_chaos.sh``, which iterates
seeds via ``CHAOS_SEED``.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.parallel.faults import (
    BLACKHOLE,
    CORRUPT,
    CORRUPT_AT_REST,
    DELAY,
    DISCONNECT,
    EIO,
    ENOSPC,
    REFUSE_CONNECT,
    SLOW_DISK,
    TORN_WRITE,
    FaultInjector,
    StorageFaultInjector,
)
from sparkrdma_tpu.shuffle.ha import (DriverStandby, FileLeaseStore,
                                      InMemoryLeaseStore)
from sparkrdma_tpu.shuffle.manager import (PartitionerSpec, ShuffleHandle,
                                           TpuShuffleManager)
from sparkrdma_tpu.shuffle.recovery import run_map_stage, run_reduce_with_retry

pytestmark = pytest.mark.chaos

SEED = int(os.environ.get("CHAOS_SEED", "0"))
# dataplane under chaos: 1 = coalesced vectored reads (the default), 0 =
# the per-map fallback; scripts/run_chaos.sh sweeps both
COALESCE = os.environ.get("CHAOS_COALESCE", "1") not in ("0", "false")
# storage-fault sweep gate (CHAOS_DISK=0 runs the network-only matrix)
DISK = os.environ.get("CHAOS_DISK", "1") not in ("0", "false")
# metadata plane under chaos: 1 = epoch-validated location caches (the
# default), 0 = the cold pre-plane path; run_chaos.sh sweeps both —
# the failure paths differ (a warm reducer holds locations a loss just
# invalidated; a cold one re-syncs every time)
WARM = os.environ.get("CHAOS_WARM", "1") not in ("0", "false")
# adaptive reduce planning under chaos: 1 runs the whole matrix with
# adaptive_plan on (publishes carry size vectors into the driver's
# histogram, plans build on demand) so the planner's publish/plan paths
# see every injected fault; run_chaos.sh sweeps both. The mid-stage
# re-plan scenario below forces it on regardless.
SKEW = os.environ.get("CHAOS_SKEW", "0") not in ("0", "false")
# push-merge dataplane under chaos: 1 runs the whole byte-identity
# matrix with background pushes, merge targets, and merged-segment-first
# reads active (partial finalize mid-reduce included) so every injected
# fault also crosses the push/merge/serve path; run_chaos.sh sweeps
# both. Scenarios asserting exact wire counts or recompute semantics pin
# push_merge=False — the dedicated merge scenarios below own those
# assertions with deterministic coverage.
MERGE = os.environ.get("CHAOS_MERGE", "0") not in ("0", "false")
# planned push under chaos: 1 runs the whole byte-identity matrix with
# sender-driven planned pushes active in the BACKGROUND of the faulted
# reduce (adaptive_plan forced on, the driver publishes a ReducePlan
# right after the map stage, pushers race the reducer, staged ranges
# resolve first at their planned slots) so the pushed dataplane and its
# fences cross every injected fault; run_chaos.sh sweeps both. The
# dedicated kill-the-planned-reducer scenario below runs regardless.
PUSHPLAN = os.environ.get("CHAOS_PUSHPLAN", "0") not in ("0", "false")
# tenancy under chaos: 1 runs the whole matrix with every shuffle
# registered under a real tenant id (TenantMapMsg pushes, serve-path
# DRR queueing, disk-ledger charging, admission gating with a
# generous cap, and a live TTL sweeper that must expire NOTHING
# mid-test) so the tenancy plumbing sees every injected fault;
# run_chaos.sh sweeps both. The dedicated cross-tenant isolation
# scenarios below assert the blast-radius invariants regardless.
TENANT = os.environ.get("CHAOS_TENANT", "0") not in ("0", "false")
# elastic membership under chaos: 1 runs the wide byte-identity
# matrices with random join/drain CHURN in the background — a fresh
# executor joins mid-reduce (announce + membership bump + health-watch
# registration cross every injected fault) and is then gracefully
# decommissioned — so the elastic control plane sees the whole fault
# matrix; run_chaos.sh sweeps both. The dedicated scale-up/drain-down
# acceptance scenarios below run regardless.
ELASTIC = os.environ.get("CHAOS_ELASTIC", "0") not in ("0", "false")
# driver HA under chaos: 1 runs the wide byte-identity matrices with a
# lease-armed primary, a warm standby shadowing its op log, and a
# primary CRASH at a seeded random point inside the reduce window — the
# standby CAS-takes the next lease term, replays, and re-points the
# executors via TakeoverMsg, so reducer syncs ride the DriverClient
# retry envelope across a real failover under every injected fault;
# run_chaos.sh sweeps both. The dedicated SIGKILL acceptance scenario
# (separate primary process, kill -9, zero map re-executions) runs
# regardless.
DRIVER = os.environ.get("CHAOS_DRIVER", "0") not in ("0", "false")
# native client fetch engine under chaos: 1 runs the whole matrix on
# the native dataplane — the C++ block server serves and the C client
# engine (csrc/fetchclient.cpp) fetches into pool leases — so every
# injected control-plane fault, disk fault, and membership event crosses
# the native engine's fallback-to-Python envelope (conn death mid-batch,
# leases released on unwind, suspect re-resolution). Data-frame faults
# inject at the Python transport layer and so don't reach the C
# dataplane; the byte-identity assertions are the point here.
# run_chaos.sh sweeps both; requires the native .so (silently degrades
# to the Python dataplane where it isn't built).
NATIVE_FETCH = os.environ.get("CHAOS_NATIVE_FETCH",
                              "0") not in ("0", "false")
# partitioned metadata ownership under chaos: 1 runs the whole matrix
# with metadata_shards=2 + shard_ownership=True — executors publish
# map outputs DIRECTLY to per-shard write owners (fence CAS on the
# owner, batch convergence into the driver, per-shard standby streams)
# so every injected fault also crosses the sharded control-plane write
# path and its driver-direct fallback; run_chaos.sh sweeps both. The
# dedicated kill-a-shard-owner scenario below runs whenever sharding
# is on and asserts the per-shard failover costs ZERO re-executions.
SHARD = os.environ.get("CHAOS_SHARD", "0") not in ("0", "false")
# cold tier under chaos: 1 runs the whole matrix with the
# disaggregated cold tier active (push_merge forced on, finalized
# segments tiering to a blob store in the BACKGROUND of every faulted
# scenario — uploads, publishes, and tombstone reaps cross the whole
# fault matrix), plus the dedicated cold scenarios below: the
# full-fleet-loss restore under a seeded blob-fault matrix, and the
# store-outage degrade-to-hot-only acceptance. run_chaos.sh sweeps
# both. Scenarios that pin push_merge=False keep their pin (the cold
# tier rides the merge plane, so it is inert there).
COLD = os.environ.get("CHAOS_COLD", "0") not in ("0", "false")
# CHAOS_LOCKGRAPH=1: run every scenario under the lock-order shim
# (sparkrdma_tpu/analysis/lockgraph.py) so the chaos matrix doubles as
# race detection — faults drive the rare teardown/retry/suspect paths
# where lock-order inversions hide. Any cycle fails the module.
LOCKGRAPH = os.environ.get("CHAOS_LOCKGRAPH", "0") not in ("0", "false")


@pytest.fixture(scope="module", autouse=True)
def _chaos_lockgraph():
    if not LOCKGRAPH:
        yield
        return
    from engine_helpers import lockgraph_module_guard
    yield from lockgraph_module_guard()


# Faults that cut or corrupt DATA frames inject at the Python transport
# layer, which the native dataplane bypasses entirely — scenarios that
# assert those faults FIRED pin the Python dataplane (the native
# engine's own anomaly coverage lives in tests/test_native_fetch.py and
# the sanitizer harness; the byte-identity matrix still sweeps it).
PY_DATAPLANE = dict(use_cpp_runtime=False, native_fetch=False)


def _conf(**kw):
    base = dict(connect_timeout_ms=3000, max_connection_attempts=2,
                retry_backoff_base_ms=10, retry_backoff_cap_ms=80,
                fetch_retry_budget=3, use_cpp_runtime=NATIVE_FETCH,
                native_fetch=NATIVE_FETCH,
                pre_warm_connections=False,
                coalesce_reads=COALESCE,
                location_epoch_cache=WARM,
                adaptive_plan=SKEW or PUSHPLAN,
                planned_push=PUSHPLAN,
                push_merge=MERGE,
                collect_shuffle_reader_stats=True)
    if TENANT:
        # the tenancy sweep dimension: a generous admission cap (the
        # gate runs, nothing sheds) and a live TTL sweeper whose TTL no
        # scenario can reach — expiry mid-fault would be its own bug
        base.update(admission_max_inflight=16, shuffle_ttl_ms=120_000)
    if DRIVER:
        # the driver-HA sweep dimension: a lease short enough that the
        # failover lands inside the scenario, and a request deadline
        # generous enough that executor retries ride through the
        # no-primary window instead of surfacing it
        base.update(ha_standbys=1, driver_lease_ms=900,
                    request_deadline_ms=20_000)
    if SHARD:
        # the partitioned-ownership sweep dimension: two write owners,
        # a small batch so convergence happens repeatedly inside every
        # scenario's publish window
        base.update(metadata_shards=2, shard_ownership=True,
                    shard_batch_entries=4)
    base.update(kw)
    return TpuShuffleConf(**base)


def _cluster(tmp_path, n=3, **kw):
    if COLD:
        # the cold-tier sweep dimension: finalized segments tier to a
        # per-test blob store in the background of every scenario
        # (explicit pins — push_merge=False wire-count scenarios — win)
        kw.setdefault("cold_tier", True)
        kw.setdefault("cold_tier_path", str(tmp_path / "cold"))
        kw.setdefault("push_merge", True)
    conf = _conf(**kw)
    if DRIVER:
        driver = TpuShuffleManager(conf, is_driver=True,
                                   lease_store=InMemoryLeaseStore(),
                                   lease_holder="primary")
    else:
        driver = TpuShuffleManager(conf, is_driver=True)
    if TENANT:
        # every scenario's shuffles register under a real tenant id so
        # TenantMapMsg pushes, DRR serve queues, and ledger charging
        # cross every injected fault (explicit tenant= kwargs win)
        orig_register = driver.register_shuffle

        def register_with_tenant(*args, **kwargs):
            kwargs.setdefault("tenant", 1)
            return orig_register(*args, **kwargs)

        driver.register_shuffle = register_with_tenant
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _map_fn(writer, map_id):
    rng = np.random.default_rng(1000 + map_id)
    keys = rng.integers(0, 5000, size=500).astype(np.uint64)
    writer.write_batch(keys)


def _reduce_fn(mgr, handle):
    reader = mgr.get_reader(handle, 0, handle.num_partitions)
    keys, _ = reader.read_all()
    return np.sort(keys)


def _expected(num_maps):
    return np.sort(np.concatenate(
        [np.random.default_rng(1000 + m).integers(0, 5000, 500)
         for m in range(num_maps)]).astype(np.uint64))


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


class _ElasticChurn:
    """CHAOS_ELASTIC background churn: one executor JOINS mid-scenario
    (announce, membership bump, health-watch registration, placement
    recompute) and is then gracefully DECOMMISSIONED — so every fault
    in the matrix also crosses the elastic control plane. The churner
    owns no shuffle data, so the drain is coverage-trivial and the
    scenario's byte-identity assertions are untouched."""

    def __init__(self, conf, driver, tmp_path):
        self._conf = conf
        self._driver = driver
        self._dir = str(tmp_path / "churn")
        self._joiner = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="elastic-churn")
        self._thread.start()

    def _run(self):
        try:
            self._joiner = TpuShuffleManager(
                self._conf, driver_addr=self._driver.driver_addr,
                executor_id="churn", spill_dir=self._dir)
            self._joiner.join_cluster()
            slot = self._joiner.executor.exec_index(timeout=5)
            time.sleep(0.15)  # let the scenario's reduce overlap the join
            self._driver.driver.decommission_slot(slot, deadline_ms=5000)
        except Exception:  # noqa: BLE001 — churn must never fail the
            # scenario; its own assertions live in the dedicated tests
            pass

    def stop(self):
        self._thread.join(timeout=10)
        if self._joiner is not None:
            self._joiner.stop()


class _DriverFailover:
    """CHAOS_DRIVER=1 background churn: a warm standby shadows the
    primary's op log; at a seeded random point inside the reduce window
    the primary CRASHES (server down, lease renewals stop — the
    in-process stand-in for SIGKILL; the real kill -9 acceptance is the
    dedicated scenario at the bottom of this file). The standby
    CAS-takes the next lease term, replays, and re-points the executors
    via TakeoverMsg, so the scenario's byte-identity assertions hold
    unchanged: reducer syncs ride the DriverClient retry envelope
    across the outage."""

    def __init__(self, driver):
        self._driver = driver
        ep = driver.driver
        self.standby = DriverStandby(driver.conf, ep.lease_store,
                                     "chaos-standby",
                                     primary_addr=ep.address).start()
        # seeded kill point: varies across the sweep, replays exactly
        rng = np.random.default_rng(SEED + 7700)
        self._delay = 0.05 + rng.random() * 0.3
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="driver-failover-churn")
        self._thread.start()

    def _run(self):
        time.sleep(self._delay)
        try:
            self._driver.driver.stop()
        except Exception:  # noqa: BLE001 — the crash itself must never
            # fail the scenario; the assertions live in the test body
            pass

    def stop(self):
        self._thread.join(timeout=10)
        self.standby.stop()


# -- tier-1 chaos scenarios (fast, deterministic counts) -----------------


def test_chaos_corruption_healed_by_refetch(tmp_path):
    """Bit-flipped fetch payloads are caught by the CRC32 trailer and
    refetched within the budget; the reduce is byte-identical and the
    failure counters show the retries that absorbed it."""
    driver, execs = _cluster(tmp_path, push_merge=False, **PY_DATAPLANE)
    injector = FaultInjector(seed=SEED)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        injector.install_endpoint(execs[0].executor)
        injector.add(CORRUPT, msg_type=M.FetchBlocksResp, times=3)

        reader = execs[0].get_reader(handle, 0, handle.num_partitions)
        keys, _ = reader.read_all()
        np.testing.assert_array_equal(np.sort(keys), _expected(6),
                                      err_msg=f"seed={SEED}")
        assert injector.fired_count(CORRUPT) == 3, f"seed={SEED}"
        assert reader.metrics.checksum_failures >= 3, f"seed={SEED}"
        assert reader.metrics.retries >= 3, f"seed={SEED}"
        assert reader.metrics.failed_fetches == 0, f"seed={SEED}"
        snap = execs[0].reader_stats.snapshot()
        assert snap["failures"]["checksum_mismatches"] >= 3, snap
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def test_chaos_connect_refusal_burst(tmp_path):
    """A refusal burst at fetch time is absorbed by connect retries with
    backoff plus the fetch retry envelope — no stage retry needed.
    push_merge pinned off: merged resolution can satisfy the reduce
    without any fresh dial, so the refusal count would depend on
    finalize timing."""
    driver, execs = _cluster(tmp_path, push_merge=False)
    injector = FaultInjector(seed=SEED)
    map_runs = []
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        injector.install_endpoint(execs[0].executor)
        injector.add(REFUSE_CONNECT, times=3)

        def counting_map_fn(writer, map_id):
            map_runs.append(map_id)
            _map_fn(writer, map_id)

        got = run_reduce_with_retry(execs, handle, counting_map_fn,
                                    _reduce_fn, reducer_index=0,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert injector.fired_count(REFUSE_CONNECT) == 3, f"seed={SEED}"
        assert map_runs == [], \
            f"seed={SEED}: transient refusals must not escalate to recompute"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def test_chaos_transient_disconnect_absorbed(tmp_path):
    """One mid-stream disconnect (response cut on the wire) fails the
    whole in-flight window, but the retry envelope re-dials and refetches
    — byte-identical, no recompute."""
    driver, execs = _cluster(tmp_path, read_ahead_depth=4, **PY_DATAPLANE)
    injector = FaultInjector(seed=SEED)
    map_runs = []
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        injector.install_endpoint(execs[0].executor)
        injector.add(DISCONNECT, msg_type=M.FetchBlocksResp, times=1)

        def counting_map_fn(writer, map_id):
            map_runs.append(map_id)
            _map_fn(writer, map_id)

        got = run_reduce_with_retry(execs, handle, counting_map_fn,
                                    _reduce_fn, reducer_index=0,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert injector.fired_count(DISCONNECT) == 1, f"seed={SEED}"
        assert map_runs == [], f"seed={SEED}"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def test_chaos_peer_kill_mid_fetch_recompute(tmp_path):
    """A map-output owner dies while the reducer's window is in flight:
    location reads from the victim succeed, then every data response
    disconnects mid-stream and every re-dial is refused (a peer that
    died between STEP 2 and STEP 3). The failure exhausts the retry
    budget, escalates to FetchFailed, the stage retry recomputes on
    survivors — never on the dead slot — and the reduce completes
    byte-identical."""
    driver, execs = _cluster(tmp_path, read_ahead_depth=4,
                             fetch_retry_budget=1, push_merge=False,
                             **PY_DATAPLANE)
    injector = FaultInjector(seed=SEED)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        victim_slot = execs[2].executor.exec_index()
        victim_addr = (execs[2].executor.manager_id.rpc_host,
                       execs[2].executor.manager_id.rpc_port)
        injector.install_endpoint(execs[0].executor)
        injector.add(DISCONNECT, peer=victim_addr,
                     msg_type=M.FetchBlocksResp)
        # after=1: the first dial (location reads) succeeds — the peer
        # "dies" between STEP 2 and STEP 3; every re-dial then bounces
        injector.add(REFUSE_CONNECT, peer=victim_addr, after=1)

        # the REAL server dies the instant the injected disconnect fires,
        # so the recovery loop's reachability probe (which uses a raw
        # socket, not the shimmed cache) also sees a dead peer and the
        # tombstone gate opens
        done = threading.Event()

        def kill_on_disconnect():
            while (injector.fired_count(DISCONNECT) == 0
                   and not done.wait(0.005)):
                pass
            execs[2].executor.server.stop()

        killer = threading.Thread(target=kill_on_disconnect)
        killer.start()
        try:
            got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                        reducer_index=0, driver=driver)
        finally:
            done.set()
            killer.join()
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert injector.fired_count(DISCONNECT) >= 1, f"seed={SEED}"
        table = execs[0].executor.get_driver_table(1, 6, timeout=5)
        for m in range(6):
            assert table.entry(m)[1] != victim_slot, f"seed={SEED}"
        # the driver handle fed the tombstone path
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        assert driver.driver.members()[victim_slot] == TOMBSTONE, \
            f"seed={SEED}"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def test_chaos_blackhole_partition_heartbeat_escalates(tmp_path):
    """A silently partitioned peer (requests vanish, nothing bounces) is
    detected by the heartbeat monitor well before the 10 s request
    deadline; the suspect verdict fails the fetch into the recompute
    loop and the reduce still completes."""
    interval_ms = 200
    driver, execs = _cluster(tmp_path, request_deadline_ms=10000,
                             heartbeat_interval_ms=interval_ms,
                             heartbeat_misses=2, fetch_retry_budget=2,
                             push_merge=False)
    injector = FaultInjector(seed=SEED)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        victim = execs[1].executor.manager_id
        injector.install_endpoint(execs[0].executor)
        # partition: everything the victim sends back is dropped
        injector.add(BLACKHOLE, peer=(victim.rpc_host, victim.rpc_port))

        t0 = time.monotonic()
        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=0, driver=driver)
        wall = time.monotonic() - t0
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        ep = execs[0].executor
        assert ep.suspect_events >= 1, f"seed={SEED}: heartbeat never fired"
        # detection + recompute must ride the heartbeat, not the 10 s
        # request deadline (let alone a TCP-scale timeout)
        assert wall < 8.0, \
            f"seed={SEED}: {wall:.1f}s — waited out deadlines instead of " \
            f"heartbeat (2x interval = {2 * interval_ms / 1000:.1f}s)"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def test_chaos_vectored_corruption_refetches_only_affected_ranges(tmp_path):
    """A corrupt sub-block inside a coalesced (cross-map) vectored
    response is isolated by the per-block CRC trailer: ONLY the affected
    map's ranges refetch (not the whole vectored request), and the
    retry/trace attribution names that map."""
    if not COALESCE:
        pytest.skip("per-map dataplane sweep: vectored path disabled")
    from sparkrdma_tpu.shuffle.reader import TpuShuffleReader
    from sparkrdma_tpu.utils.trace import Tracer

    driver, execs = _cluster(tmp_path, n=2, push_merge=False)
    injector = FaultInjector(seed=SEED)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        # every map on ONE peer: the reducer coalesces all 6 maps into a
        # single vectored request (6 segments, 24 blocks)
        run_map_stage(execs, handle, _map_fn,
                      placement={m: 1 for m in range(6)})
        injector.install_endpoint(execs[0].executor)
        injector.add(CORRUPT, msg_type=M.FetchBlocksResp, times=1)

        tracer = Tracer()
        reader = TpuShuffleReader(execs[0].executor, execs[0].resolver,
                                  _conf(), handle.shuffle_id, 6, 0, 4, 0,
                                  tracer=tracer)
        keys, _ = reader.read_all()
        np.testing.assert_array_equal(np.sort(keys), _expected(6),
                                      err_msg=f"seed={SEED}")
        m = reader.metrics
        assert injector.fired_count(CORRUPT) == 1, f"seed={SEED}"
        assert m.checksum_failures >= 1, f"seed={SEED}"
        assert m.failed_fetches == 0, f"seed={SEED}"
        # exactly one vectored request covered all 6 maps...
        vec = [e for e in tracer._events if e["name"] == "fetch.vectored"]
        assert len(vec) == 1 and vec[0]["args"]["maps"] == 6, f"seed={SEED}"
        # ...and the heal refetched ONE map's ranges, not the request:
        # a single bit flip lands in one block (or its trailer word), so
        # one segment of 4 blocks goes back on the wire
        refetches = [e for e in tracer._events
                     if e["name"] == "fetch.refetch_range"]
        assert len(refetches) == 1, f"seed={SEED}: {refetches}"
        blamed = refetches[0]["args"]["map"]
        assert 0 <= blamed < 6, f"seed={SEED}"
        assert refetches[0]["args"]["blocks"] < vec[0]["args"]["blocks"], \
            f"seed={SEED}: refetch was not narrower than the request"
        # the retry instant attributes the SAME map the refetch named
        retries = [e for e in tracer._events if e["name"] == "fetch.retry"]
        assert retries and all(e["args"]["map"] == blamed
                               for e in retries), f"seed={SEED}"
        # wire accounting: 1 batched location RPC + 1 vectored read + 1
        # range refetch — nothing else
        assert m.requests_per_reduce == 3, f"seed={SEED}: {m}"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def _wait_merge_ready(driver, execs, handle):
    """Deterministic point past the asynchronous push+finalize pipeline:
    every pusher drained, every (map, partition) covered at the driver."""
    from sparkrdma_tpu.shuffle.push_merge import wait_for_coverage
    for ex in execs:
        assert ex.pusher is not None and ex.pusher.drain(15), \
            f"seed={SEED}: pusher did not drain"
    assert wait_for_coverage(driver.driver, handle.shuffle_id,
                             handle.num_maps, handle.num_partitions,
                             timeout=15), \
        f"seed={SEED}: merged coverage never completed"


def test_chaos_merge_repoint_zero_reexecutions(tmp_path):
    """The push-merge recovery acceptance: an executor owning map
    outputs dies MID-REDUCE with merge_replicas >= 1 and full replica
    coverage on survivors — the stage completes with ZERO map
    re-executions (a location-table flip to the replicas), the dead
    slot is tombstoned, and the retry serves every lost map from merged
    segments, byte-identical to the fault-free run."""
    driver, execs = _cluster(tmp_path, fetch_retry_budget=1,
                             push_merge=True, merge_replicas=2,
                             push_deadline_ms=8000, **PY_DATAPLANE)
    injector = FaultInjector(seed=SEED)
    map_runs = []
    merged_metrics = []
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        _wait_merge_ready(driver, execs, handle)
        victim_slot = execs[2].executor.exec_index()
        victim_addr = (execs[2].executor.manager_id.rpc_host,
                       execs[2].executor.manager_id.rpc_port)
        injector.install_endpoint(execs[0].executor)
        # the victim dies between the reducer's location reads and its
        # data reads (the peer_kill choreography): the first in-flight
        # response disconnects, every re-dial bounces, and the REAL
        # server dies so the tombstone probe agrees
        injector.add(DISCONNECT, peer=victim_addr,
                     msg_type=M.FetchBlocksResp)
        injector.add(REFUSE_CONNECT, peer=victim_addr, after=1)
        done = threading.Event()

        def kill_on_disconnect():
            while (injector.fired_count(DISCONNECT) == 0
                   and not done.wait(0.005)):
                pass
            execs[2].executor.server.stop()

        def counting_map_fn(writer, map_id):
            map_runs.append(map_id)
            _map_fn(writer, map_id)

        def reduce_fn(mgr, h, state={"attempt": 0}):
            # attempt 1 fetches per-map (a reducer that had not learned
            # the merged directory yet) so the kill lands mid-reduce;
            # the RETRY resolves merged-segment-first — the re-point
            state["attempt"] += 1
            if state["attempt"] == 1:
                from sparkrdma_tpu.shuffle.reader import TpuShuffleReader
                reader = TpuShuffleReader(
                    mgr.executor, mgr.resolver, _conf(push_merge=False),
                    h.shuffle_id, h.num_maps, 0, h.num_partitions, 0)
            else:
                reader = mgr.get_reader(h, 0, h.num_partitions)
            keys, _ = reader.read_all()
            merged_metrics.append(reader.metrics)
            return np.sort(keys)

        killer = threading.Thread(target=kill_on_disconnect)
        killer.start()
        try:
            got = run_reduce_with_retry(execs, handle, counting_map_fn,
                                        reduce_fn, reducer_index=0,
                                        driver=driver)
        finally:
            done.set()
            killer.join()
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        # ZERO map re-executions: recovery re-pointed every lost map to
        # a surviving merged replica instead of recomputing
        assert map_runs == [], \
            f"seed={SEED}: maps {map_runs} re-executed despite replicas"
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        assert driver.driver.members()[victim_slot] == TOMBSTONE, \
            f"seed={SEED}"
        # the dead slot's segments left the directory; survivors' stayed
        d = driver.driver.merged_directory(1)
        assert d is not None and all(
            e.slot != victim_slot
            for p in d.partitions() for e in d.entries(p)), f"seed={SEED}"
        # the retry actually served merged segments
        assert merged_metrics[-1].merged_reads >= 1, f"seed={SEED}"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def test_chaos_merge_corrupt_segment_degrades_per_map(tmp_path):
    """At-rest rot on a merged segment: the reducer-side entry CRC
    catches it and that partition DEGRADES to the per-map dataplane —
    byte-identical output, merged_fallbacks counted, no stage retry."""
    import glob

    driver, execs = _cluster(tmp_path, push_merge=True, merge_replicas=1,
                             push_deadline_ms=8000)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        _wait_merge_ready(driver, execs, handle)
        # rot the segment the reducer WILL choose for partition 0 (the
        # directory's widest-coverage entry — the fetcher's own policy),
        # on disk on its hosting executor: the serve path carries the
        # rotted bytes and the wire CRC trailer is computed over them,
        # so only the published entry CRC can tell
        d = driver.driver.merged_directory(1)
        chosen = d.entries(0)[0]
        slot_dirs = {execs[i].executor.exec_index():
                     str(tmp_path / f"e{i}") for i in range(len(execs))}
        seg = os.path.join(slot_dirs[chosen.slot], "merge", "seg_1_0.bin")
        assert glob.glob(seg), f"seed={SEED}: {seg} missing"
        with open(seg, "r+b") as f:
            f.seek(0)
            first = f.read(1)
            f.seek(0)
            f.write(bytes([first[0] ^ 0xFF]))

        reader = execs[0].get_reader(handle, 0, handle.num_partitions)
        keys, _ = reader.read_all()
        np.testing.assert_array_equal(np.sort(keys), _expected(6),
                                      err_msg=f"seed={SEED}")
        m = reader.metrics
        assert m.merged_fallbacks >= 1, f"seed={SEED}: {m}"
        assert m.checksum_failures >= 1, f"seed={SEED}: {m}"
        assert m.failed_fetches == 0, f"seed={SEED}: {m}"
        assert m.merged_reads >= 1, \
            f"seed={SEED}: clean partitions should still serve merged"
    finally:
        _shutdown(driver, execs)


def test_chaos_pushplan_reducer_kill_mid_push(tmp_path):
    """The planned reducer for partition 0 dies MID-PUSH — after
    accepting its first pushed range, while the senders' replay is
    still streaming toward it. Staged inputs die with it; the reduce on
    a survivor serves its OWN staged partitions pushed-first,
    pull-fills every hole, recovery recomputes the dead slot's maps,
    and the output is an EXACT multiset of the fault-free ground truth
    — zero duplicate rows, zero lost rows."""
    driver, execs = _cluster(tmp_path, adaptive_plan=True,
                             planned_push=True, push_merge=False,
                             coalesce_target_bytes=2048,
                             fetch_retry_budget=1)
    holder = {"victim_slot": None}
    killed = threading.Event()

    def arm(ep, orig):
        def handler(conn, msg):
            orig(conn, msg)
            if (holder["victim_slot"] is not None
                    and ep.exec_index() == holder["victim_slot"]
                    and not killed.is_set()):
                killed.set()
                # stop from a fresh thread: the handler runs on a serve
                # worker the stop would otherwise wait on
                threading.Thread(target=ep.server.stop,
                                 daemon=True).start()
        return handler

    for ex in execs:
        ep = ex.executor
        ep._on_push_planned = arm(ep, ep._on_push_planned)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=8,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        plan = driver.driver.build_reduce_plan(1)
        assert plan is not None, f"seed={SEED}"
        holder["victim_slot"] = plan.placement_of(0)
        assert killed.wait(10), \
            f"seed={SEED}: no push ever reached the planned reducer"
        victim_idx = next(
            i for i, ex in enumerate(execs)
            if ex.executor.exec_index() == holder["victim_slot"])
        reducer_idx = next(i for i in range(len(execs))
                           if i != victim_idx)
        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=reducer_idx,
                                    max_stage_retries=3, driver=driver)
        # zero duplicate rows, zero lost rows: exact multiset equality
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        assert driver.driver.members()[holder["victim_slot"]] \
            == TOMBSTONE, f"seed={SEED}"
        # the senders saw the death, not an error: failed planned pushes
        # are shed (the ranges stay pull-fetched), never worker-fatal
        snaps = [ex.executor.pushed_store.snapshot()
                 for i, ex in enumerate(execs) if i != victim_idx]
        assert all(s is not None for s in snaps), f"seed={SEED}"
    finally:
        _shutdown(driver, execs)


# -- cross-tenant isolation (the CHAOS_TENANT satellite) -----------------


def _map_fn_t2(writer, map_id):
    rng = np.random.default_rng(3000 + map_id)
    writer.write_batch(rng.integers(0, 5000, size=500).astype(np.uint64))


def _expected_t2(num_maps):
    return np.sort(np.concatenate(
        [np.random.default_rng(3000 + m).integers(0, 5000, 500)
         for m in range(num_maps)]).astype(np.uint64))


def test_chaos_tenant_executor_loss_isolated(tmp_path):
    """An executor loss inside tenant 1's shuffle must not perturb
    tenant 2: tenant 1 heals by recompute-on-survivors (its maps
    re-execute), tenant 2's shuffle — whose outputs never touched the
    dead slot — reads byte-identical with ZERO re-executions, zero
    failed fetches, and its location epoch UNBUMPED (the tombstone
    invalidates only shuffles naming the dead slot)."""
    driver, execs = _cluster(tmp_path, read_ahead_depth=4,
                             fetch_retry_budget=1, push_merge=False,
                             **PY_DATAPLANE)
    injector = FaultInjector(seed=SEED)
    t1_reruns = []
    try:
        h1 = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                     partitioner=PartitionerSpec("modulo"),
                                     tenant=1)
        run_map_stage(execs, h1, _map_fn)  # tenant 1 spans every slot
        # tenant 2's maps live ONLY on the survivors (execs 0 and 1)
        h2 = driver.register_shuffle(2, num_maps=4, num_partitions=4,
                                     partitioner=PartitionerSpec("modulo"),
                                     tenant=2)
        for m in range(4):
            w = execs[m % 2].get_writer(h2, m)
            _map_fn_t2(w, m)
            w.close()
        epoch2_before = driver.driver.epoch_of(2)

        victim_addr = (execs[2].executor.manager_id.rpc_host,
                       execs[2].executor.manager_id.rpc_port)
        victim_slot = execs[2].executor.exec_index()
        injector.install_endpoint(execs[0].executor)
        injector.add(DISCONNECT, peer=victim_addr,
                     msg_type=M.FetchBlocksResp)
        injector.add(REFUSE_CONNECT, peer=victim_addr, after=1)
        done = threading.Event()

        def kill_on_disconnect():
            while (injector.fired_count(DISCONNECT) == 0
                   and not done.wait(0.005)):
                pass
            execs[2].executor.server.stop()

        def counting_map_fn(writer, map_id):
            t1_reruns.append(map_id)
            _map_fn(writer, map_id)

        killer = threading.Thread(target=kill_on_disconnect)
        killer.start()
        try:
            got1 = run_reduce_with_retry(execs, h1, counting_map_fn,
                                         _reduce_fn, reducer_index=0,
                                         driver=driver)
        finally:
            done.set()
            killer.join()
        np.testing.assert_array_equal(got1, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert t1_reruns, f"seed={SEED}: the fault never landed"
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        assert driver.driver.members()[victim_slot] == TOMBSTONE, \
            f"seed={SEED}"

        # tenant 2: byte-identical, no retries, no re-executions (its
        # read succeeding outside any retry loop IS the proof), and the
        # tombstone did not bump its epoch — its warm caches survive
        reader2 = execs[0].get_reader(h2, 0, 4)
        keys2, _ = reader2.read_all()
        np.testing.assert_array_equal(np.sort(keys2), _expected_t2(4),
                                      err_msg=f"seed={SEED}")
        m2 = reader2.metrics
        assert m2.failed_fetches == 0, f"seed={SEED}: {m2}"
        assert m2.retries == 0, f"seed={SEED}: {m2}"
        assert driver.driver.epoch_of(2) == epoch2_before, \
            f"seed={SEED}: tenant 2's epoch bumped by tenant 1's loss"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def test_chaos_tenant_corrupt_segment_isolated(tmp_path):
    """At-rest rot on tenant 1's merged segment: tenant 1's read
    degrades that partition per-map (byte-identical, fallback counted);
    tenant 2's shuffle on the same cluster still serves MERGED with
    zero fallbacks, zero checksum failures, zero re-executions — the
    corruption's blast radius is one tenant's one partition."""
    import glob

    driver, execs = _cluster(tmp_path, push_merge=True, merge_replicas=1,
                             push_deadline_ms=8000)
    try:
        h1 = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                     partitioner=PartitionerSpec("modulo"),
                                     tenant=1)
        run_map_stage(execs, h1, _map_fn)
        _wait_merge_ready(driver, execs, h1)
        h2 = driver.register_shuffle(2, num_maps=6, num_partitions=4,
                                     partitioner=PartitionerSpec("modulo"),
                                     tenant=2)
        run_map_stage(execs, h2, _map_fn_t2)
        _wait_merge_ready(driver, execs, h2)

        # rot the segment tenant 1's reducer WILL choose for partition 0
        d = driver.driver.merged_directory(1)
        chosen = d.entries(0)[0]
        slot_dirs = {execs[i].executor.exec_index():
                     str(tmp_path / f"e{i}") for i in range(len(execs))}
        seg = os.path.join(slot_dirs[chosen.slot], "merge", "seg_1_0.bin")
        assert glob.glob(seg), f"seed={SEED}: {seg} missing"
        with open(seg, "r+b") as f:
            first = f.read(1)
            f.seek(0)
            f.write(bytes([first[0] ^ 0xFF]))

        reader1 = execs[0].get_reader(h1, 0, 4)
        keys1, _ = reader1.read_all()
        np.testing.assert_array_equal(np.sort(keys1), _expected(6),
                                      err_msg=f"seed={SEED}")
        m1 = reader1.metrics
        assert m1.merged_fallbacks >= 1, f"seed={SEED}: {m1}"

        # tenant 2 is untouched: all-merged serving, clean counters
        reader2 = execs[0].get_reader(h2, 0, 4)
        keys2, _ = reader2.read_all()
        np.testing.assert_array_equal(np.sort(keys2), _expected_t2(6),
                                      err_msg=f"seed={SEED}")
        m2 = reader2.metrics
        assert m2.merged_reads >= 1, f"seed={SEED}: {m2}"
        assert m2.merged_fallbacks == 0, f"seed={SEED}: {m2}"
        assert m2.checksum_failures == 0, f"seed={SEED}: {m2}"
        assert m2.failed_fetches == 0, f"seed={SEED}: {m2}"
        assert driver.driver.epoch_of(2) == 1, \
            f"seed={SEED}: tenant 2 re-executed under tenant 1's rot"
    finally:
        _shutdown(driver, execs)


def test_chaos_stale_cache_never_serves_dead_peer(tmp_path):
    """Executor loss mid-iteration: the reducer's warm location cache
    points at the dead peer. The fetch fails, recovery tombstones +
    recomputes, the loss BUMPS the epoch, and the re-synced snapshot
    never names the tombstoned slot — byte-identical output, no stale
    location served after invalidation."""
    if not WARM:
        pytest.skip("cold sweep: no cache to go stale")
    driver, execs = _cluster(tmp_path, fetch_retry_budget=1,
                             push_merge=False, **PY_DATAPLANE)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        # superstep 1 (cold): warms the reducer's location cache
        got1 = _reduce_fn(execs[0], handle)
        np.testing.assert_array_equal(got1, _expected(6),
                                      err_msg=f"seed={SEED}")
        plane = execs[0].executor.location_plane
        assert plane.snapshot()["tables"] >= 1, f"seed={SEED}"
        assert driver.driver.epoch_of(1) == 1, f"seed={SEED}"
        # the victim dies between supersteps; the warm cache still names
        # its slot
        victim_slot = execs[2].executor.exec_index()
        execs[2].executor.server.stop()
        # superstep 2: the stale cache leads to a failed fetch — NEVER a
        # wrong result — and recovery repairs + invalidates
        got2 = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                     reducer_index=0, driver=driver)
        np.testing.assert_array_equal(got2, _expected(6),
                                      err_msg=f"seed={SEED}")
        # the loss bumped the epoch (pushed invalidation)
        assert driver.driver.epoch_of(1) > 1, f"seed={SEED}"
        # the re-synced view never names the tombstoned slot
        table = execs[0].executor.get_driver_table(1, 6, timeout=5)
        for m in range(6):
            assert table.entry(m)[1] != victim_slot, f"seed={SEED}"
        # superstep 3 over the repaired state: clean, still identical
        got3 = _reduce_fn(execs[0], handle)
        np.testing.assert_array_equal(got3, _expected(6),
                                      err_msg=f"seed={SEED}")
    finally:
        _shutdown(driver, execs)


def test_chaos_corrupt_reexecution_bumps_epoch_mid_iteration(tmp_path):
    """Corrupt-output healing mid-iteration: at-rest rot caught at serve
    time re-executes exactly the rotten map; the repair publish BUMPS
    the epoch so every reducer's warm cache refreshes — the next
    superstep reads the healed output under the new epoch,
    byte-identical."""
    if not WARM:
        pytest.skip("cold sweep: no cache to invalidate")
    driver, execs = _cluster(tmp_path, at_rest_checksum=True,
                             push_merge=False)
    injector = StorageFaultInjector(seed=SEED)
    injector.install()
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        # one committed output rots right after its commit attested it
        injector.add(CORRUPT_AT_REST, op="commit", times=1)
        run_map_stage(execs, handle, _map_fn)
        assert injector.fired_count(CORRUPT_AT_REST) == 1, f"seed={SEED}"
        # superstep 1 trips the serve-time check -> corrupt_output
        # verdict -> re-execution of exactly that map -> repair publish
        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=0, driver=driver)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert driver.driver.epoch_of(1) > 1, \
            f"seed={SEED}: corrupt re-execution did not bump the epoch"
        # superstep 2: warm under the NEW epoch, clean and identical
        got2 = _reduce_fn(execs[0], handle)
        np.testing.assert_array_equal(got2, _expected(6),
                                      err_msg=f"seed={SEED}")
        r = execs[0].get_reader(handle, 0, handle.num_partitions)
        keys, _ = r.read_all()
        assert r.metrics.failed_fetches == 0, f"seed={SEED}"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


def _skew_map_fn(writer, map_id):
    rng = np.random.default_rng(4000 + map_id)
    keys = np.where(rng.random(1500) < 0.7, 3,
                    rng.integers(0, 8, 1500)).astype(np.uint64)
    writer.write_batch(keys)


def _skew_expected(num_maps):
    parts = []
    for m in range(num_maps):
        rng = np.random.default_rng(4000 + m)
        parts.append(np.where(rng.random(1500) < 0.7, 3,
                              rng.integers(0, 8, 1500)).astype(np.uint64))
    return np.sort(np.concatenate(parts))


def test_chaos_replan_mid_stage_after_executor_loss(tmp_path):
    """The adaptive planner's mid-stage re-plan: a skewed shuffle plans
    into coalesced + split tasks placed across executors; one executor
    dies AFTER the first task completes. The lost maps recompute on
    survivors, the driver re-plans under a bumped plan epoch — completed
    tasks keep their ranges and results, only orphaned tasks re-assign —
    and the stage finishes with ZERO duplicate and ZERO lost rows
    (exact multiset equality against the fault-free ground truth)."""
    from sparkrdma_tpu.shuffle.recovery import run_planned_reduce

    driver, execs = _cluster(tmp_path, adaptive_plan=True,
                             push_merge=False,
                             coalesce_target_bytes=2048,
                             split_threshold_bytes=4096)
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=8,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _skew_map_fn)
        plan = driver.plan_reduce(handle)
        assert plan is not None and len(plan.tasks) >= 3, f"seed={SEED}"
        assert plan.counts()["split_partitions"] >= 1, f"seed={SEED}"

        victim_slot = execs[2].executor.exec_index()
        state = {"killed": False}

        def kill_after_first(task, slot):
            if not state["killed"]:
                state["killed"] = True
                execs[2].executor.server.stop()

        res = run_planned_reduce(execs, handle, _skew_map_fn, driver,
                                 on_task_done=kill_after_first)
        # zero lost, zero duplicate rows: exact multiset equality
        np.testing.assert_array_equal(np.sort(res.keys),
                                      _skew_expected(6),
                                      err_msg=f"seed={SEED}")
        assert state["killed"], f"seed={SEED}"
        # the loss forced at least one re-plan under a bumped epoch...
        assert res.plan.plan_epoch > plan.plan_epoch, f"seed={SEED}"
        assert driver.driver.plan_replans >= 1, f"seed={SEED}"
        # ...that kept every task's exact ranges (only placement moved)
        by_id = {t.task_id: t for t in res.plan.tasks}
        for t in plan.tasks:
            n = by_id[t.task_id]
            assert (n.start_partition, n.end_partition, n.map_start,
                    n.map_end) == (t.start_partition, t.end_partition,
                                   t.map_start, t.map_end), f"seed={SEED}"
        # completed ranges were never re-executed
        assert res.tasks_rerun == 0, f"seed={SEED}"
        # the repaired table no longer names the dead slot
        table = execs[0].executor.get_driver_table(1, 6, timeout=5)
        for m in range(6):
            assert table.entry(m)[1] != victim_slot, f"seed={SEED}"
    finally:
        _shutdown(driver, execs)


def test_chaos_device_plane_loss_degrades_to_host(tmp_path, monkeypatch):
    """Device-dataplane loss scenario: the cost model picks the fused
    ICI plane for an on-mesh stage, an executor dies MID-STAGE (its
    committed outputs vanish while staging is in flight), and the stage
    degrades onto the host dataplane — recovery recomputes the lost
    maps on survivors, the retry serves the stage through the fetcher,
    and the output is byte-identical to a fault-free run."""
    import jax
    from jax.sharding import Mesh

    from engine_helpers import make_cluster, u32_payload
    from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
    from sparkrdma_tpu.shuffle import fetcher as fetcher_mod
    from sparkrdma_tpu.shuffle import mesh_service
    from sparkrdma_tpu.shuffle.spark_compat import ShuffleDependency

    mesh = Mesh(np.array(jax.devices()[:8]), ("shuffle",))
    P, maps, rows, key_space = 4, 6, 400, 3000

    def map_fn(ctx, writer, task_id):
        rng = np.random.default_rng(5000 + SEED * 100 + task_id)
        keys = rng.integers(0, key_space, rows).astype(np.uint64)
        writer.write((keys, u32_payload(
            rng.integers(0, 1000, rows).astype(np.uint32))))

    holder = {"engine": None, "degraded": {}}

    def reduce_fn(ctx, task_id):
        keys, payload = ctx.read(0)._r.read_all()
        # observe the degrade while the stage is alive (teardown pops
        # the memo when run() returns)
        holder["degraded"].update(holder["engine"]._mesh_degraded)
        rowsb = np.concatenate(
            [keys.view(np.uint8).reshape(len(keys), 8),
             np.ascontiguousarray(payload)], axis=1)
        return rowsb[np.lexsort(rowsb.T[::-1])].tobytes()

    fetchers = {"n": 0}
    orig_init = fetcher_mod.ShuffleFetcher.__init__

    def spy(self, *a, **kw):
        fetchers["n"] += 1
        return orig_init(self, *a, **kw)

    monkeypatch.setattr(fetcher_mod.ShuffleFetcher, "__init__", spy)

    def run(label, chaos):
        driver, execs = make_cluster(tmp_path / label)
        try:
            # sequential tasks: the injection relies on the FIRST read
            # triggering the one mesh staging pass
            engine = holder["engine"] = DAGEngine(driver, execs,
                                                  mesh=mesh,
                                                  max_parallel_tasks=1)
            holder["degraded"] = {}
            state = {"fired": False}
            if chaos:
                # the INDEXED iterator is the one staging hook every
                # mesh reduce driver (one-shot, fused, hierarchical)
                # flows through — injecting here covers them all
                orig_iter = mesh_service._iter_committed_batches_indexed

                def chaos_iter(managers, handle, delivered=None):
                    for batch in orig_iter(managers, handle, delivered):
                        yield batch
                        if not state["fired"]:
                            # mid-staging: the victim dies and its
                            # committed outputs die with it
                            state["fired"] = True
                            victim = execs[1].native
                            mid = victim.executor.manager_id
                            victim.executor.stop()
                            driver.native.driver.remove_member(mid)
                            victim.resolver.remove_shuffle(
                                handle.shuffle_id)

                monkeypatch.setattr(
                    mesh_service, "_iter_committed_batches_indexed",
                    chaos_iter)
            stage = MapStage(maps, ShuffleDependency(
                P, PartitionerSpec("modulo"), row_payload_bytes=4),
                map_fn)
            out = engine.run(ResultStage(P, reduce_fn, parents=[stage]))
            if chaos:
                monkeypatch.setattr(
                    mesh_service, "_iter_committed_batches_indexed",
                    orig_iter)
            return out, engine, state
        finally:
            for ex in execs:
                ex.stop()
            driver.stop()

    clean_out, clean_engine, _ = run("clean", chaos=False)
    assert not holder["degraded"], f"seed={SEED}"
    before_fetchers = fetchers["n"]

    chaos_out, chaos_engine, state = run("kill", chaos=True)
    assert state["fired"], f"seed={SEED}: injection never ran"
    # the device plane was selected (staging ran), then the stage
    # degraded onto the host dataplane...
    assert list(holder["degraded"].values()) == \
        ["mid-stage executor loss"], f"seed={SEED}"
    assert not chaos_engine._mesh_degraded, \
        f"seed={SEED}: teardown leaked the degrade memo"
    assert fetchers["n"] > before_fetchers, \
        f"seed={SEED}: degrade never reached the host dataplane"
    # ...byte-identically
    assert chaos_out == clean_out, f"seed={SEED}"


# -- the wide sweep (chaos + slow; scripts/run_chaos.sh) -----------------


def _scenario_faults(name, injector, victim_addr):
    if name == "corrupt_1pct":
        injector.add(CORRUPT, msg_type=M.FetchBlocksResp, prob=0.01)
    elif name == "refuse_burst":
        injector.add(REFUSE_CONNECT, times=3)
        injector.add(REFUSE_CONNECT, after=10, times=2)
    elif name == "delay_storm":
        injector.add(DELAY, msg_type=M.FetchBlocksResp, delay_s=0.05,
                     prob=0.2)
    elif name == "flaky_victim":
        injector.add(DISCONNECT, peer=victim_addr,
                     msg_type=M.FetchBlocksResp, times=2)
        injector.add(DELAY, peer=victim_addr, msg_type=M.FetchOutputResp,
                     delay_s=0.03, prob=0.5)
    elif name == "mixed":
        injector.add(CORRUPT, msg_type=M.FetchBlocksResp, prob=0.02)
        injector.add(DELAY, msg_type=M.FetchBlocksResp, delay_s=0.02,
                     prob=0.1)
        injector.add(REFUSE_CONNECT, times=2)
    else:  # pragma: no cover - scenario list and matrix stay in sync
        raise AssertionError(name)


def _map_fn_big(writer, map_id):
    rng = np.random.default_rng(1000 + map_id)
    keys = rng.integers(0, 50_000, size=3000).astype(np.uint64)
    writer.write_batch(keys)


def _expected_big(num_maps):
    return np.sort(np.concatenate(
        [np.random.default_rng(1000 + m).integers(0, 50_000, 3000)
         for m in range(num_maps)]).astype(np.uint64))


@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["corrupt_1pct", "refuse_burst",
                                      "delay_storm", "flaky_victim",
                                      "mixed"])
def test_chaos_matrix(tmp_path, scenario):
    """The sweep: ~a hundred small grouped fetches (tiny read block size,
    3000 rows per map) under probabilistic faults drawn from the seeded
    injector RNG. Replay a failure with
    ``CHAOS_SEED=<seed> pytest tests/test_chaos.py -m chaos``
    (the seed is in the assertion message)."""
    driver, execs = _cluster(tmp_path, shuffle_read_block_size=1024,
                             read_ahead_depth=4)
    injector = FaultInjector(seed=SEED)
    churn = None
    failover = None
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=8,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn_big)
        if PUSHPLAN:
            # background planned pushes: the plan publishes now, so the
            # pushers race the faulted reduce below and staged ranges
            # resolve first at their planned slots
            assert driver.driver.build_reduce_plan(1) is not None, \
                f"seed={SEED}: PUSHPLAN sweep built no plan"
        victim_addr = (execs[2].executor.manager_id.rpc_host,
                       execs[2].executor.manager_id.rpc_port)
        injector.install_endpoint(execs[0].executor)
        _scenario_faults(scenario, injector, victim_addr)
        if ELASTIC:
            churn = _ElasticChurn(driver.conf, driver, tmp_path)
        if DRIVER:
            failover = _DriverFailover(driver)

        got = run_reduce_with_retry(execs, handle, _map_fn_big, _reduce_fn,
                                    reducer_index=0, max_stage_retries=3,
                                    driver=driver)
        np.testing.assert_array_equal(
            got, _expected_big(6),
            err_msg=f"scenario={scenario} seed={SEED}")
    finally:
        injector.uninstall()
        if churn is not None:
            churn.stop()
        if failover is not None:
            failover.stop()
        _shutdown(driver, execs)


# -- the storage-fault matrix (CHAOS_DISK sweep) --------------------------
#
# Every injected ENOSPC/EIO/torn-write/slow-disk/corrupt-at-rest scenario
# must end with byte-identical job output — via spill retry, fallback
# dir, or map re-execution — or a clean, fully-reaped task failure:
# never a hang, never a served torn/corrupt block.


def _disk_faults(name, injector):
    deterministic = True
    if name == "enospc_spill":
        # two failures, absorbed by retries (budget 2 = 3 attempts)
        injector.add(ENOSPC, op="spill_write", times=2)
    elif name == "eio_spill":
        injector.add(EIO, op="spill_write", prob=0.2)
        deterministic = False
    elif name == "torn_spill":
        injector.add(TORN_WRITE, op="spill_write", torn_bytes=32, times=2)
    elif name == "slow_disk":
        injector.add(SLOW_DISK, delay_s=0.01, prob=0.3)
        deterministic = False
    elif name == "corrupt_at_rest":
        injector.add(CORRUPT_AT_REST, op="commit", times=1)
    elif name == "mixed_disk":
        injector.add(ENOSPC, op="spill_write", times=1)
        injector.add(SLOW_DISK, op="spill_write", delay_s=0.005, prob=0.2)
        injector.add(CORRUPT_AT_REST, op="commit", times=1)
    else:  # pragma: no cover - scenario list and matrix stay in sync
        raise AssertionError(name)
    return deterministic


@pytest.mark.skipif(not DISK, reason="CHAOS_DISK=0: network-only sweep")
@pytest.mark.parametrize("scenario", ["enospc_spill", "eio_spill",
                                      "torn_spill", "slow_disk",
                                      "corrupt_at_rest", "mixed_disk"])
def test_chaos_disk_matrix(tmp_path, scenario):
    """Seeded storage faults under a real multi-executor job: small spill
    threshold (every map spills), a fallback spill dir, at-rest
    checksums on. Replay a failure with
    ``CHAOS_SEED=<seed> CHAOS_COALESCE=<0|1> pytest tests/test_chaos.py
    -m chaos -k disk``."""
    driver, execs = _cluster(
        tmp_path, spill_threshold_bytes="1k",
        spill_dirs=str(tmp_path / "fallback"),
        spill_retry_budget=2, at_rest_checksum=True)
    injector = StorageFaultInjector(seed=SEED)
    injector.install()
    churn = None
    failover = None
    try:
        deterministic = _disk_faults(scenario, injector)
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        # the map stage runs UNDER the faults: spill retries, fallback
        # dirs, and WriteFailedError re-placement all exercise here
        run_map_stage(execs, handle, _map_fn)
        if PUSHPLAN:
            # background planned pushes under storage faults: staging
            # spills cross the same injected EIO/ENOSPC/slow-disk shims
            assert driver.driver.build_reduce_plan(1) is not None, \
                f"seed={SEED}: PUSHPLAN sweep built no plan"
        if ELASTIC:
            churn = _ElasticChurn(driver.conf, driver, tmp_path)
        if DRIVER:
            failover = _DriverFailover(driver)
        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=3,
                                    driver=driver)
        np.testing.assert_array_equal(
            got, _expected(6),
            err_msg=f"scenario={scenario} seed={SEED}")
        if deterministic:
            assert injector.fired_count() > 0, \
                f"scenario={scenario} seed={SEED}: no fault fired"
        # no attempt artifacts may outlive the job in ANY spill dir
        # (fallback dirs are namespaced per executor — walk recursively)
        leftovers = [str(p) for p in tmp_path.rglob("*.tmp")]
        assert leftovers == [], \
            f"scenario={scenario} seed={SEED}: leaked {leftovers}"
    finally:
        injector.uninstall()
        if churn is not None:
            churn.stop()
        if failover is not None:
            failover.stop()
        _shutdown(driver, execs)


@pytest.mark.skipif(not DISK, reason="CHAOS_DISK=0: network-only sweep")
def test_chaos_disk_total_failure_is_clean(tmp_path):
    """When every spill dir fails persistently, the job FAILS CLEANLY:
    WriteFailedError after re-placement on every live executor, no hang,
    and not one ``.tmp`` left anywhere. push_merge pinned off on
    purpose: its overflow rung would RESCUE the attempt by parking the
    spill on a peer (that behavior has its own test,
    test_push_merge.py::test_overflow_spill_survives_total_enospc) —
    this scenario exists to prove the failure is clean when nothing
    can rescue."""
    from sparkrdma_tpu.shuffle.writer import WriteFailedError

    driver, execs = _cluster(tmp_path, spill_threshold_bytes="1k",
                             spill_retry_budget=1, push_merge=False)
    injector = StorageFaultInjector(seed=SEED)
    injector.install()
    try:
        injector.add(EIO, op="spill_write")  # every attempt, every dir
        handle = driver.register_shuffle(1, num_maps=2, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        with pytest.raises(WriteFailedError):
            run_map_stage(execs, handle, _map_fn)
        leftovers = [str(p) for p in tmp_path.rglob("*.tmp")]
        assert leftovers == [], f"seed={SEED}: leaked {leftovers}"
    finally:
        injector.uninstall()
        _shutdown(driver, execs)


# -- elastic membership: the ROADMAP item 2 acceptance scenarios ----------
#
# A job starts on 4 executors, SCALES TO 8 mid-job (the planner places
# new maps on the joiners), DRAINS BACK TO 4 mid-reduce-stage, and the
# final output is byte-identical to the static-membership run with ZERO
# map re-executions on the planned drains (recovery.repoint-style
# accounting: the drained maps serve from merged replicas). A drainee
# dying mid-drain falls back to ordinary tombstone recovery and still
# completes byte-identically.


def _elastic_map_fn(counter):
    def map_fn(writer, map_id):
        counter[map_id] = counter.get(map_id, 0) + 1
        rng = np.random.default_rng(6000 + map_id)
        writer.write_batch(rng.integers(0, 9000, 300).astype(np.uint64))
    return map_fn


def _elastic_expected(num_maps):
    return np.sort(np.concatenate(
        [np.random.default_rng(6000 + m).integers(0, 9000, 300)
         for m in range(num_maps)]).astype(np.uint64))


def test_chaos_elastic_scale_up_drain_down_byte_identical(tmp_path):
    """4 -> 8 -> 4 with zero re-executions on the planned drains."""
    conf = _conf(push_merge=True, merge_replicas=2,
                 drain_deadline_ms=15000)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(4)]
    for ex in execs:
        ex.executor.wait_for_members(4)
    joiners = []
    try:
        num_maps, num_parts = 8, 6
        handle = driver.register_shuffle(
            1, num_maps=num_maps, num_partitions=num_parts,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        map_fn = _elastic_map_fn(counter)

        # SCALE UP: 4 joiners announce mid-job; the map stage then
        # places work across all 8 (joiners included)
        for j in range(4):
            joiner = TpuShuffleManager(
                conf, driver_addr=driver.driver_addr,
                executor_id=f"j{j}", spill_dir=str(tmp_path / f"j{j}"))
            joiner.join_cluster()
            joiners.append(joiner)
        all_execs = execs + joiners
        for ex in all_execs:
            ex.executor.wait_for_members(8)
        assert len(driver.driver.membership.live_slots()) == 8
        ran = run_map_stage(all_execs, handle, map_fn)
        joiner_slots = sorted(
            j.executor.exec_index(timeout=2) for j in joiners)
        placed_on_joiners = [m for m, i in ran.items() if i >= 4]
        assert placed_on_joiners, "planner never placed on the joiners"
        for ex in all_execs:
            assert ex.pusher.drain(timeout=15)

        # mid-reduce-stage: read HALF the partitions on the full fleet
        first = _reduce_keys(all_execs[0], handle, 0, num_parts // 2)

        # DRAIN DOWN: gracefully decommission all 4 joiners — planned
        # retires, ZERO re-executions (the repoint accounting)
        for slot in sorted(joiner_slots, reverse=True):
            res = driver.driver.decommission_slot(slot)
            assert res["status"] == "drained", \
                f"seed={SEED} drain of slot {slot}: {res}"
        assert driver.driver.drains_completed == 4
        assert driver.driver.drain_fallbacks == 0
        for j in joiners:
            j.stop()
        joiners_alive = []

        # finish the stage on the shrunk fleet; retry envelope covers
        # any straggler still holding pre-drain cached locations
        def rest_fn(mgr, h):
            return _reduce_keys(mgr, h, num_parts // 2, num_parts)

        rest = run_reduce_with_retry(execs, handle, map_fn, rest_fn,
                                     reducer_index=0,
                                     max_stage_retries=3, driver=driver)
        got = np.sort(np.concatenate([first, rest]))
        np.testing.assert_array_equal(
            got, _elastic_expected(num_maps),
            err_msg=f"seed={SEED}: elastic run diverged from the "
                    "static-membership ground truth")
        assert sum(counter.values()) == num_maps, \
            (f"seed={SEED}: planned drains re-executed maps: {counter} "
             f"(joiner-placed: {placed_on_joiners})")
        joiners = joiners_alive
    finally:
        for j in joiners:
            j.stop()
        _shutdown(driver, execs)


def _reduce_keys(mgr, handle, start, end):
    keys, _ = mgr.get_reader(handle, start, end).read_all()
    return keys


def test_chaos_elastic_drainee_death_mid_drain_falls_back(tmp_path):
    """The drainee dies MID-drain (after DrainReq lands, before its
    replication pass answers): the decommission falls back to ordinary
    tombstone recovery, the reduce re-executes the lost maps, and the
    output stays byte-identical."""
    conf = _conf(push_merge=False)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(3)]
    for ex in execs:
        ex.executor.wait_for_members(3)
    try:
        num_maps = 6
        handle = driver.register_shuffle(
            1, num_maps=num_maps, num_partitions=4,
            partitioner=PartitionerSpec("modulo"))
        counter = {}
        map_fn = _elastic_map_fn(counter)
        ran = run_map_stage(execs, handle, map_fn)
        victim = execs[2]
        victim_slot = victim.executor.exec_index(timeout=2)
        owned = [m for m, i in ran.items() if i == 2]
        assert owned

        # die mid-drain: the DrainReq handler kills the executor's
        # servers instead of replicating, so no DrainResp ever arrives
        orig = victim.executor._drain_replicate

        def die_mid_drain(deadline):
            victim.executor.stop()
            if victim.block_server is not None:
                victim.block_server.stop()
            raise RuntimeError("drainee died mid-drain")

        victim.executor._drain_replicate = die_mid_drain
        res = driver.driver.decommission_slot(victim_slot,
                                              deadline_ms=3000)
        assert res["status"] == "fallback", f"seed={SEED}: {res}"
        assert driver.driver.drain_fallbacks == 1
        from sparkrdma_tpu.parallel.membership import SLOT_DEAD
        assert driver.driver.membership.state_of(victim_slot) == SLOT_DEAD

        got = run_reduce_with_retry(execs[:2], handle, map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=3,
                                    driver=driver)
        np.testing.assert_array_equal(
            got, _elastic_expected(num_maps),
            err_msg=f"seed={SEED}: fallback run diverged")
        # tombstone recovery re-executed exactly the drainee's maps
        assert sum(counter.values()) == num_maps + len(owned), \
            f"seed={SEED}: {counter}"
    finally:
        _shutdown(driver, execs)


# -- driver HA: the kill -9 acceptance scenario ---------------------------
#
# The primary driver runs in its OWN PROCESS holding a file-backed lease
# and gets SIGKILLed at a seeded random point after the map outputs have
# replicated to a warm in-test standby. The standby must CAS-take the
# next lease term within the lease TTL, replay its shadowed op log, and
# re-point the executors — and the job must complete byte-identically
# with ZERO map re-executions: the map outputs live on the executors,
# so losing the driver may cost a wait, never a recompute.

_PRIMARY_CHILD = r"""
import json, os, sys, time
from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel.endpoints import DriverEndpoint
from sparkrdma_tpu.shuffle.ha import FileLeaseStore

conf = TpuShuffleConf(**json.loads(sys.argv[1]))
ep = DriverEndpoint(conf, host="127.0.0.1",
                    lease_store=FileLeaseStore(sys.argv[2]),
                    lease_holder="primary")
ep.register_shuffle(7, num_maps=4, num_partitions=4)
with open(sys.argv[3] + ".tmp", "w") as f:
    json.dump({"host": ep.server.host, "port": ep.server.port,
               "pid": os.getpid()}, f)
os.replace(sys.argv[3] + ".tmp", sys.argv[3])
while True:  # hold the lease until SIGKILL
    time.sleep(0.5)
"""


def test_chaos_driver_sigkill_failover_zero_reexecutions(tmp_path):
    conf_kw = dict(connect_timeout_ms=2000, max_connection_attempts=1,
                   retry_backoff_base_ms=20, retry_backoff_cap_ms=150,
                   pre_warm_connections=False, use_cpp_runtime=False,
                   ha_standbys=1, driver_lease_ms=800,
                   request_deadline_ms=20_000)
    conf = TpuShuffleConf(**conf_kw)
    lease_path = str(tmp_path / "lease.json")
    addr_path = str(tmp_path / "driver_addr.json")
    child_src = tmp_path / "primary_child.py"
    child_src.write_text(_PRIMARY_CHILD)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, str(child_src), json.dumps(conf_kw), lease_path,
         addr_path], env=env, cwd=repo_root)
    standby = None
    execs = []
    try:
        deadline = time.monotonic() + 60
        while not os.path.exists(addr_path):
            assert proc.poll() is None, \
                f"seed={SEED}: primary child died at startup"
            assert time.monotonic() < deadline, \
                f"seed={SEED}: primary child never published its address"
            time.sleep(0.05)
        with open(addr_path) as f:
            info = json.load(f)
        addr = (info["host"], info["port"])

        standby = DriverStandby(conf, FileLeaseStore(lease_path),
                                "standby-1", primary_addr=addr).start()
        execs = [TpuShuffleManager(conf, driver_addr=addr,
                                   executor_id=str(i),
                                   spill_dir=str(tmp_path / f"e{i}"))
                 for i in range(2)]
        for ex in execs:
            ex.executor.wait_for_members(2)

        handle = ShuffleHandle(7, 4, 4, 0, PartitionerSpec("modulo"))
        map_runs = []
        runs_lock = threading.Lock()

        def map_fn(writer, map_id):
            with runs_lock:
                map_runs.append(map_id)
            rng = np.random.default_rng(1000 + map_id)
            writer.write_batch(
                rng.integers(0, 5000, size=500).astype(np.uint64))

        run_map_stage(execs, handle, map_fn)
        # all four publishes are on the primary; wait until the standby's
        # shadowed op log has gone QUIET having heard them — nothing
        # mutates driver state after the map stage, so a stable ingest
        # seq means the async replication stream has fully drained and a
        # kill at any later instant loses no op
        table, _ = execs[0].executor.get_driver_table_v(
            7, expect_published=4, timeout=10)
        assert table.num_published == 4, f"seed={SEED}"
        stable_since, last_seen = time.monotonic(), standby._last
        while time.monotonic() - stable_since < 0.5:
            assert time.monotonic() < deadline, \
                f"seed={SEED}: standby never caught up"
            time.sleep(0.05)
            if standby._last != last_seen:
                stable_since, last_seen = time.monotonic(), standby._last
        assert last_seen[1] > 0, f"seed={SEED}: standby heard no ops"

        # reducers launch, then the primary dies at a seeded random
        # point inside the reduce window: reducers that already synced
        # never notice; the rest ride the DriverClient retry envelope
        # into the promoted standby
        results = {}

        def reduce_one(i):
            reader = execs[i].get_reader(handle, 0, 4)
            keys, _ = reader.read_all()
            results[i] = np.sort(keys)

        threads = [threading.Thread(target=reduce_one, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        time.sleep(np.random.default_rng(SEED + 990).random() * 0.2)
        os.kill(proc.pid, signal.SIGKILL)
        t_kill = time.monotonic()
        proc.wait(timeout=10)

        # takeover within the lease TTL (the remaining TTL at kill time
        # is at most one driver_lease_ms; the watcher polls at TTL/4,
        # promotion itself is bounded by replay) + scheduling grace
        while standby.endpoint is None:
            assert time.monotonic() - t_kill < \
                conf.driver_lease_ms / 1000 + 1.0, \
                f"seed={SEED}: standby never took the lease"
            time.sleep(0.02)
        new_primary = standby.endpoint
        assert new_primary.incarnation >= 1, f"seed={SEED}"

        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), \
            f"seed={SEED}: a reducer hung across the failover"
        expected = _expected(4)
        for i in range(2):
            np.testing.assert_array_equal(
                results[i], expected,
                err_msg=f"seed={SEED}: reducer {i} diverged after kill -9")
        # ZERO re-executions: losing the driver costs a wait, never a
        # recompute — every map ran exactly once
        assert sorted(map_runs) == [0, 1, 2, 3], \
            f"seed={SEED}: map re-executions after failover: {map_runs}"
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        for ex in execs:
            ex.stop()
        if standby is not None:
            standby.stop()


# -- partitioned metadata ownership: the shard-owner kill acceptance ------
#
# A shard OWNER is metadata-only infrastructure: killing it mid-stage
# must cost a per-shard handoff (standby log replay + republish
# backstop), never a map re-execution. The victim here owns shard 0's
# fence CAS but holds ZERO map outputs (placement pins the data on the
# other executors), so any re-execution in this scenario would be the
# control plane LOSING a publish — exactly the bug class the handoff
# protocol exists to rule out.


def test_chaos_shard_owner_kill_mid_publish_zero_reexecutions(tmp_path):
    """Kill the owner of shard 0 while the map stage's publishes are
    streaming at it (a seeded point after its first applied write). The
    stragglers bounce to the driver-direct path, the driver hands the
    shard to a successor, and the reduce completes byte-identical with
    ZERO map re-executions — the driver table never lost a publish."""
    driver, execs = _cluster(tmp_path, n=4, metadata_shards=2,
                             shard_ownership=True,
                             shard_batch_entries=64,  # unconverged tail
                             push_merge=False)
    map_runs = []
    killer = None
    done = threading.Event()
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        smap = None
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and smap is None:
            smv = execs[0].executor.location_plane.shard_map_v(1)
            smap = smv[0] if smv is not None else None
            time.sleep(0.02)
        assert smap is not None, f"seed={SEED}: no shard map pushed"
        victim_slot = smap.shard_slots[0]
        victim_idx = next(i for i, ex in enumerate(execs)
                          if ex.executor.exec_index() == victim_slot)
        survivors = [i for i in range(len(execs)) if i != victim_idx]

        def kill_on_first_applied():
            victim_ep = execs[victim_idx].executor
            while (victim_ep.shard_owner.applied == 0
                   and not done.wait(0.002)):
                pass
            if done.is_set():
                return
            victim_ep.stop()  # abrupt: applied writes left unconverged
            driver.driver.remove_member(victim_ep.manager_id)

        killer = threading.Thread(target=kill_on_first_applied)
        killer.start()
        # the victim hosts METADATA only: every map output lives on the
        # survivors, so the owner kill can never justify a recompute
        run_map_stage(execs, handle, _map_fn,
                      placement={m: survivors[m % len(survivors)]
                                 for m in range(6)})
        killer.join(timeout=10)
        assert not killer.is_alive(), f"seed={SEED}: killer hung"
        assert execs[victim_idx].executor.shard_owner.applied > 0, \
            f"seed={SEED}: the victim never owned a publish"
        deadline = time.monotonic() + 8
        while (time.monotonic() < deadline
               and driver.driver.shard_handoffs == 0):
            time.sleep(0.05)
        assert driver.driver.shard_handoffs >= 1, f"seed={SEED}"

        def counting_map_fn(writer, map_id):
            map_runs.append(map_id)
            _map_fn(writer, map_id)

        live = [execs[i] for i in survivors]
        got = run_reduce_with_retry(live, handle, counting_map_fn,
                                    _reduce_fn, reducer_index=0,
                                    max_stage_retries=3, driver=driver)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        assert map_runs == [], \
            (f"seed={SEED}: shard-owner death re-executed maps "
             f"{map_runs} — a publish was lost in the handoff")
        smv2 = execs[survivors[0]].executor.location_plane.shard_map_v(1)
        assert smv2 is not None and victim_slot not in smv2[0].shard_slots, \
            f"seed={SEED}: the dead owner still holds a shard"
    finally:
        done.set()
        if killer is not None:
            killer.join(timeout=10)
        _shutdown(driver, execs)


# -- the cold tier: full-fleet loss under the blob-fault matrix -----------
#
# The disaggregated tier's acceptance scenario class (CHAOS_COLD=1): the
# ENTIRE fleet dies after map finalize + tier upload, and a fresh fleet
# must reduce byte-identically from the blob store — under a SEEDED
# matrix of blob faults on both the upload path (outages, torn uploads,
# at-rest rot — segments degrade to hot-only or publish rotten blobs
# the restore CRC must catch) and the restore path (outages, slow
# store). Whatever the faults ate, the answer is byte-identical: cold
# restore where coverage survived, re-execution where it didn't.


@pytest.mark.skipif(not COLD, reason="CHAOS_COLD=0: cold tier inert")
def test_chaos_cold_full_fleet_loss_under_blob_faults(tmp_path):
    from sparkrdma_tpu.parallel.faults import (BLOB_CORRUPT, BLOB_SLOW,
                                               BLOB_UNAVAILABLE,
                                               TORN_UPLOAD,
                                               BlobFaultInjector)
    from sparkrdma_tpu.shuffle.cold_tier import wait_for_tiered_coverage
    from sparkrdma_tpu.shuffle.push_merge import wait_for_coverage

    driver, execs = _cluster(tmp_path, n=3, **PY_DATAPLANE)
    inj = BlobFaultInjector(seed=SEED)
    inj.install()
    fresh = []
    counter = {}

    def map_fn(writer, map_id):
        counter[map_id] = counter.get(map_id, 0) + 1
        _map_fn(writer, map_id)

    try:
        # upload-side faults: some puts fail outright, some land short
        # (must never become visible), some commit then rot at rest
        inj.add(BLOB_UNAVAILABLE, op="put", prob=0.15)
        inj.add(TORN_UPLOAD, op="put", prob=0.1, torn_bytes=32)
        inj.add(BLOB_CORRUPT, op="put", prob=0.15, flip_bits=3)

        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec(
                                             "modulo"))
        run_map_stage(execs, handle, map_fn)
        for ex in execs:
            assert ex.pusher.drain(15), f"seed={SEED}"
        assert wait_for_coverage(driver.driver, 1, 6, 4, timeout=15), \
            f"seed={SEED}"
        for ex in execs:
            if ex.executor.tiering is not None:
                assert ex.executor.tiering.drain(20), f"seed={SEED}"
        # coverage is best-effort under upload faults — whatever tiered,
        # tiered; the job must not care either way
        wait_for_tiered_coverage(driver.driver, 1, 6, 4, timeout=2)

        # the spot-market event: the ENTIRE fleet is gone
        mids = [ex.executor.manager_id for ex in execs]
        for ex in execs:
            ex.stop()
        for mid in mids:
            driver.driver.remove_member(mid)

        # restore-side faults: a blinking, slow store
        inj.add(BLOB_UNAVAILABLE, op="get", prob=0.15)
        inj.add(BLOB_SLOW, op="get", prob=0.3, delay_s=0.01)

        conf = _conf(cold_tier=True,
                     cold_tier_path=str(tmp_path / "cold"),
                     push_merge=True, **PY_DATAPLANE)
        fresh = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                                   executor_id=f"f{i}",
                                   spill_dir=str(tmp_path / f"f{i}"))
                 for i in range(3)]
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        for ex in fresh:
            ex.executor.wait_for_members(6)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                members = ex.executor.members()
                if all(members[s] == TOMBSTONE for s in range(3)):
                    break
                time.sleep(0.02)

        got = run_reduce_with_retry(fresh, handle, map_fn, _reduce_fn,
                                    reducer_index=0, max_stage_retries=8,
                                    driver=driver)
        np.testing.assert_array_equal(
            got, _expected(6),
            err_msg=f"seed={SEED}: cold restore diverged under blob "
                    f"faults (fired: {dict(inj.fired)})")
        # every map ran at least once (the original stage) and only
        # AS re-executions where the fault matrix destroyed coverage
        assert all(n >= 1 for n in counter.values()), \
            f"seed={SEED}: {counter}"
    finally:
        inj.uninstall()
        _shutdown(driver, fresh if fresh else execs)


@pytest.mark.skipif(not COLD, reason="CHAOS_COLD=0: cold tier inert")
def test_chaos_cold_store_outage_degrades_to_hot_only(tmp_path):
    """The blob store is DOWN for the entire job: every upload fails
    its whole retry budget, nothing tiers, and the job must not notice
    — tiering never fails a job (the graceful-degradation half of the
    acceptance)."""
    from sparkrdma_tpu.parallel.faults import (BLOB_UNAVAILABLE,
                                               BlobFaultInjector)

    driver, execs = _cluster(tmp_path, n=3, **PY_DATAPLANE)
    inj = BlobFaultInjector(seed=SEED)
    inj.install()
    try:
        inj.add(BLOB_UNAVAILABLE)  # every op, every time: store DOWN
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec(
                                             "modulo"))
        run_map_stage(execs, handle, _map_fn)
        for ex in execs:
            assert ex.pusher.drain(15), f"seed={SEED}"
        from sparkrdma_tpu.shuffle.push_merge import wait_for_coverage
        assert wait_for_coverage(driver.driver, 1, 6, 4, timeout=15), \
            f"seed={SEED}"
        for ex in execs:
            if ex.executor.tiering is not None:
                assert ex.executor.tiering.drain(20), f"seed={SEED}"
        got = _reduce_fn(execs[0], handle)
        np.testing.assert_array_equal(got, _expected(6),
                                      err_msg=f"seed={SEED}")
        snaps = [ex.executor.tiering.snapshot() for ex in execs
                 if ex.executor.tiering is not None]
        assert snaps, f"seed={SEED}: no tiering service installed"
        assert all(s["uploads_done"] == 0 for s in snaps), \
            f"seed={SEED}: {snaps}"
        assert sum(s["uploads_failed"] for s in snaps) > 0, \
            f"seed={SEED}: {snaps}"
        directory = driver.driver.tiered_directory(1)
        assert directory is None or len(directory) == 0, f"seed={SEED}"
    finally:
        inj.uninstall()
        _shutdown(driver, execs)
