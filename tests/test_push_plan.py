"""Planned-push (sender-driven shuffle) tests.

Units on the ``PushedInputStore`` double-fence discipline (attempt
fences, plan epochs, tombstones, budget spill, repay-exactly
accounting), the e2e push-vs-pull byte-parity matrix across every
dataplane combo (coalesced / sequential / pipelined x merged on/off),
the zero-RPC gate for fully-pushed partitions (frames counted
SERVER-side across the whole cluster), hole fallback, mid-stage
re-plan supersession, and the microbench acceptance gate
(shuffle/pushplan_bench.py). ``PUSHPLAN_SEED`` varies the generated
data for seed sweeps.
"""

import os
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import messages as M
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.push_merge import wait_for_coverage
from sparkrdma_tpu.shuffle.pushed_store import PushedInputStore
from sparkrdma_tpu.shuffle.reader import TpuShuffleReader
from sparkrdma_tpu.shuffle.resolver import TpuShuffleBlockResolver

SEED = int(os.environ.get("PUSHPLAN_SEED", "0"))


# -- units: PushedInputStore ----------------------------------------------


def test_pushed_store_fence_epoch_and_tombstone_discipline(tmp_path):
    conf = TpuShuffleConf(use_cpp_runtime=False)
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"), conf=conf)
    store = PushedInputStore(resolver, conf)
    try:
        status, acc = store.push(1, 0, fence=5, plan_epoch=1,
                                 start_partition=0, sizes=[3, 2],
                                 data=b"abcde")
        assert (status, acc) == (M.STATUS_OK, b"\x01\x01")
        assert store.take(1, 0, plan_epoch=1) == {0: b"abc"}
        assert store.take(1, 1, plan_epoch=1) == {0: b"de"}
        # ranges stay staged after a take (warm re-reads)
        assert store.take(1, 0, plan_epoch=1) == {0: b"abc"}
        # stale ATTEMPT fence: rejected per partition, bytes unchanged
        _, acc = store.push(1, 0, fence=4, plan_epoch=1,
                            start_partition=0, sizes=[3, 2], data=b"XXXYY")
        assert acc == b"\x00\x00"
        assert store.take(1, 0, plan_epoch=1) == {0: b"abc"}
        # newer fence supersedes; the old charge is released in-place
        _, acc = store.push(1, 0, fence=7, plan_epoch=1,
                            start_partition=0, sizes=[3, 2], data=b"ABCDE")
        assert acc == b"\x01\x01"
        assert store.take(1, 0, plan_epoch=1) == {0: b"ABC"}
        assert store.pushes_superseded == 2
        # stale PLAN epoch: shed wholesale
        _, acc = store.push(1, 1, fence=1, plan_epoch=0,
                            start_partition=0, sizes=[2], data=b"zz")
        assert acc == b"\x00"
        # a NEWER epoch adopts first (push beat the plan broadcast) and
        # releases every older-epoch range — exactly, not approximately
        _, acc = store.push(1, 1, fence=1, plan_epoch=2,
                            start_partition=0, sizes=[2], data=b"qq")
        assert acc == b"\x01"
        assert store.take(1, 0, plan_epoch=1) == {}  # stale never served
        assert store.take(1, 0, plan_epoch=2) == {1: b"qq"}
        assert store.maps_staged(1, 0, plan_epoch=2) == [1]
        snap = store.snapshot()
        assert snap["staged_ranges"] == 1 and snap["mem_bytes"] == 2, snap
        # on_plan: same adoption path as a push-carried epoch
        store.on_plan(1, 3)
        assert store.take(1, 0, plan_epoch=2) == {}
        assert store.snapshot()["staged_ranges"] == 0
        # drop -> tombstone: a racing push is FINALIZED (stops the
        # pusher); a registration event re-arms the id for reuse
        store.drop_shuffle(1)
        status, _ = store.push(1, 0, fence=9, plan_epoch=3,
                               start_partition=0, sizes=[1], data=b"a")
        assert status == M.STATUS_FINALIZED
        store.note_registered(1)
        status, acc = store.push(1, 0, fence=9, plan_epoch=3,
                                 start_partition=0, sizes=[1], data=b"a")
        assert (status, acc) == (M.STATUS_OK, b"\x01")
        store.drop_shuffle(1)
        assert store.snapshot()["mem_bytes"] == 0
        assert resolver.disk_ledger.usage(0) == 0
    finally:
        store.stop()
        resolver.stop()


def test_pushed_store_budget_spill_and_repay(tmp_path):
    """``push_staging_budget=0`` sends every range to disk: files land
    under ``<spill_dir>/pushed/``, the tenant's disk ledger is charged,
    takes read back the exact bytes, and drop repays + unlinks."""
    conf = TpuShuffleConf(use_cpp_runtime=False, push_staging_budget=0)
    resolver = TpuShuffleBlockResolver(str(tmp_path / "s"), conf=conf)
    store = PushedInputStore(resolver, conf)
    try:
        status, acc = store.push(7, 2, fence=1, plan_epoch=1,
                                 start_partition=0, sizes=[4, 4],
                                 data=b"aaaabbbb")
        assert (status, acc) == (M.STATUS_OK, b"\x01\x01")
        snap = store.snapshot()
        assert snap["mem_bytes"] == 0 and snap["spilled_bytes"] == 8, snap
        assert resolver.disk_ledger.usage(0) == 8
        assert list((tmp_path / "s" / "pushed").glob("push_7_*"))
        assert store.take(7, 0, plan_epoch=1) == {2: b"aaaa"}
        assert store.take(7, 1, plan_epoch=1) == {2: b"bbbb"}
        # location-epoch ADVANCE (recovery): conservatively drop rows,
        # repaying the spill charge; the plan epoch is kept
        store.on_location_epoch(7, 2)
        assert store.take(7, 0, plan_epoch=1) == {}
        assert resolver.disk_ledger.usage(0) == 0
        assert not list((tmp_path / "s" / "pushed").glob("push_7_*"))
    finally:
        store.stop()
        resolver.stop()


# -- e2e cluster ----------------------------------------------------------


def _cluster(tmp_path, n=3, **kw):
    base = dict(connect_timeout_ms=10000, use_cpp_runtime=False,
                retry_backoff_base_ms=10, retry_backoff_cap_ms=80,
                adaptive_plan=True, planned_push=True,
                push_deadline_ms=8000)
    base.update(kw)
    conf = TpuShuffleConf(**base)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs, conf


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def _write_maps(driver, execs, num_maps=6, num_partitions=4, rows=400,
                payload_w=0, shuffle_id=1):
    handle = driver.register_shuffle(
        shuffle_id, num_maps, num_partitions, PartitionerSpec("modulo"),
        row_payload_bytes=payload_w)
    for m in range(num_maps):
        w = execs[m % len(execs)].get_writer(handle, m)
        rng = np.random.default_rng(SEED * 1000 + m)
        keys = rng.integers(0, 5000, rows).astype(np.uint64)
        payload = (rng.integers(0, 255, (rows, payload_w), dtype=np.uint64)
                   .astype(np.uint8) if payload_w else None)
        w.write_batch(keys, payload)
        w.close()
    return handle


def _plan_and_stage(driver, execs, handle, timeout=15):
    """Publish the plan, then wait until EVERY (map, partition) is
    staged at its planned slot — the plan broadcast races the drain
    call, so coverage is polled, not assumed."""
    plan = driver.driver.build_reduce_plan(handle.shuffle_id)
    assert plan is not None, "no size rows reached the planner?"
    by_slot = {ex.executor.exec_index(timeout=5): ex for ex in execs}
    sid = handle.shuffle_id
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for ex in execs:
            ex.pusher.drain(timeout)
        if all(len(by_slot[plan.placement_of(p)].executor.pushed_store
                   .maps_staged(sid, p, plan.plan_epoch))
               == handle.num_maps
               for p in range(handle.num_partitions)):
            return plan, by_slot
        time.sleep(0.02)
    raise AssertionError("planned pushes never fully staged: %s" % [
        (p, by_slot[plan.placement_of(p)].executor.pushed_store
         .maps_staged(sid, p, plan.plan_epoch))
        for p in range(handle.num_partitions)])


def _rows_multiset(reader):
    """Sorted (key || payload) byte rows — the framing-independent
    byte-parity check (equal multisets, duplicates preserved)."""
    keys, payload = reader.read_all()
    if payload is None or payload.size == 0:
        return sorted(keys.tobytes()[i * 8:i * 8 + 8]
                      for i in range(len(keys)))
    return sorted(keys[i].tobytes() + payload[i].tobytes()
                  for i in range(len(keys)))


def _read_partition(ex, conf, handle, p, payload_w=0):
    return TpuShuffleReader(ex.executor, ex.resolver, conf,
                            handle.shuffle_id, handle.num_maps, p, p + 1,
                            payload_w)


class _WireCounters:
    """Server-side frame counts across the WHOLE cluster — the honest
    zero-RPC gate: a fully-pushed reducer must cause no metadata or
    data frames to arrive anywhere (driver table/plan serves included),
    not merely report zeros in its own client metrics."""

    def __init__(self, driver, execs):
        self.meta = 0
        self.data = 0

        def wrap(kind, orig):
            def handler(*a):
                setattr(self, kind, getattr(self, kind) + 1)
                return orig(*a)
            return handler

        drv = driver.driver
        drv._on_fetch_table = wrap("meta", drv._on_fetch_table)
        drv._on_fetch_plan = wrap("meta", drv._on_fetch_plan)
        for ex in execs:
            ep = ex.executor
            ep._on_fetch_output = wrap("meta", ep._on_fetch_output)
            ep._on_fetch_outputs = wrap("meta", ep._on_fetch_outputs)
            ep._on_fetch_blocks = wrap("data", ep._on_fetch_blocks)


_DATAPLANES = {
    "coalesced": dict(coalesce_reads=True),
    "sequential": dict(coalesce_reads=False, read_ahead_depth=1),
    "pipelined": dict(coalesce_reads=False, read_ahead_depth=8),
}


@pytest.mark.parametrize("dataplane", sorted(_DATAPLANES))
@pytest.mark.parametrize("merged", [False, True])
def test_e2e_push_vs_pull_byte_parity(tmp_path, dataplane, merged):
    """The parity matrix: a fully-pushed read must be byte-identical to
    a pull over EVERY dataplane combo — coalesced / sequential /
    pipelined, merged segments on and off."""
    kw = dict(_DATAPLANES[dataplane])
    if merged:
        kw.update(push_merge=True, merge_replicas=1)
    driver, execs, conf = _cluster(tmp_path, **kw)
    try:
        handle = _write_maps(driver, execs, payload_w=24)
        if merged:
            for ex in execs:
                assert ex.pusher.drain(15)
            assert wait_for_coverage(driver.driver, handle.shuffle_id,
                                     handle.num_maps,
                                     handle.num_partitions, timeout=15)
        plan, by_slot = _plan_and_stage(driver, execs, handle)
        pull_conf = TpuShuffleConf(**dict(conf.to_dict(),
                                          planned_push=False))
        for p in range(handle.num_partitions):
            ex = by_slot[plan.placement_of(p)]
            push_reader = _read_partition(ex, conf, handle, p, 24)
            pushed = _rows_multiset(push_reader)
            assert push_reader.metrics.pushed_reads == handle.num_maps, \
                push_reader.metrics
            assert push_reader.metrics.failed_fetches == 0
            pull_reader = _read_partition(ex, pull_conf, handle, p, 24)
            assert pushed == _rows_multiset(pull_reader), \
                f"partition {p} seed={SEED} {dataplane} merged={merged}"
            assert pull_reader.metrics.pushed_reads == 0
    finally:
        _shutdown(driver, execs)


def test_e2e_fully_pushed_read_is_zero_rpc(tmp_path):
    """The tentpole's headline: a reducer whose inputs were all pushed
    starts with ZERO metadata RPCs and ZERO data RPCs — counted
    server-side across the driver and every executor."""
    driver, execs, conf = _cluster(tmp_path)
    try:
        handle = _write_maps(driver, execs, payload_w=24)
        plan, by_slot = _plan_and_stage(driver, execs, handle)
        wire = _WireCounters(driver, execs)
        rows = []
        for p in range(handle.num_partitions):
            ex = by_slot[plan.placement_of(p)]
            reader = _read_partition(ex, conf, handle, p, 24)
            rows.extend(_rows_multiset(reader))
            m = reader.metrics
            assert m.pushed_reads == handle.num_maps, m
            assert m.metadata_rpcs_per_stage == 0, m
            assert m.requests_per_reduce == 0, m
            assert m.remote_fetches == 0 and m.local_fetches == 0, m
        assert (wire.meta, wire.data) == (0, 0), (wire.meta, wire.data)
        # sanity: the counters DO count — the same read pulling hits
        # the wire, and fetches the same bytes
        pull_conf = TpuShuffleConf(**dict(conf.to_dict(),
                                          planned_push=False))
        pulled = []
        for p in range(handle.num_partitions):
            ex = by_slot[plan.placement_of(p)]
            pulled.extend(_rows_multiset(
                _read_partition(ex, pull_conf, handle, p, 24)))
        assert wire.meta > 0 and wire.data > 0
        assert sorted(rows) == sorted(pulled)
    finally:
        _shutdown(driver, execs)


def test_e2e_hole_falls_back_per_map_byte_identical(tmp_path):
    """Evict one staged range at the planned slot: the reducer serves
    the other maps from staging and pull-fills ONLY the hole — no
    duplicate rows, no missing rows, failed_fetches == 0."""
    driver, execs, conf = _cluster(tmp_path)
    try:
        handle = _write_maps(driver, execs)
        plan, by_slot = _plan_and_stage(driver, execs, handle)
        ex = by_slot[plan.placement_of(0)]
        store = ex.executor.pushed_store
        with store._lock:
            state = store._shuffles[handle.shuffle_id]
            store._free_row_locked(state.rows.pop((0, 0)))
        reader = _read_partition(ex, conf, handle, 0)
        rows = _rows_multiset(reader)
        m = reader.metrics
        assert m.pushed_reads == handle.num_maps - 1, m
        assert m.remote_fetches + m.local_fetches == 1, m
        assert m.failed_fetches == 0, m
        pull_conf = TpuShuffleConf(**dict(conf.to_dict(),
                                          planned_push=False))
        assert rows == _rows_multiset(
            _read_partition(ex, pull_conf, handle, 0)), f"seed={SEED}"
    finally:
        _shutdown(driver, execs)


def test_e2e_replan_supersedes_staged_pushes_exactly(tmp_path):
    """A mid-stage re-plan (bumped epoch) supersedes every stale staged
    range, the senders' replay re-stages under the new epoch, and reads
    serve ONLY new-epoch rows — staged-range counts prove the
    supersession was exact (released, not duplicated)."""
    driver, execs, conf = _cluster(tmp_path)
    try:
        handle = _write_maps(driver, execs)
        plan1, by_slot = _plan_and_stage(driver, execs, handle)
        assert plan1.plan_epoch == 1
        n_ranges = handle.num_maps * handle.num_partitions
        assert sum(ex.executor.pushed_store.snapshot()["staged_ranges"]
                   for ex in execs) == n_ranges
        # rebuild: same histogram, bumped epoch, broadcast like the
        # original; stores adopt + shed, pushers replay
        plan2, by_slot = _plan_and_stage(driver, execs, handle)
        assert plan2.plan_epoch == 2
        # exactness: every stale range released, every range re-staged
        # once — the store holds exactly one epoch's worth of rows
        assert sum(ex.executor.pushed_store.snapshot()["staged_ranges"]
                   for ex in execs) == n_ranges
        assert any(ex.executor.pushed_store.pushes_superseded
                   for ex in execs)
        for p in range(handle.num_partitions):
            store = by_slot[plan2.placement_of(p)].executor.pushed_store
            # the stale epoch is never consumable, the new one is full
            assert store.take(handle.shuffle_id, p, plan1.plan_epoch) \
                == {}
            assert len(store.maps_staged(handle.shuffle_id, p,
                                         plan2.plan_epoch)) \
                == handle.num_maps
        # and the read at the new epoch is byte-identical to pull
        pull_conf = TpuShuffleConf(**dict(conf.to_dict(),
                                          planned_push=False))
        for p in range(handle.num_partitions):
            ex = by_slot[plan2.placement_of(p)]
            reader = _read_partition(ex, conf, handle, p)
            rows = _rows_multiset(reader)
            assert reader.metrics.pushed_reads == handle.num_maps
            assert rows == _rows_multiset(
                _read_partition(ex, pull_conf, handle, p)), f"seed={SEED}"
    finally:
        _shutdown(driver, execs)


def test_e2e_unregister_drops_staging_and_stops_pusher(tmp_path):
    """Shuffle TTL: unregister releases every staged range (leases
    freed, files gone) and tombstones the id so a racing push gets
    FINALIZED instead of parking zombie bytes."""
    driver, execs, _ = _cluster(tmp_path)
    try:
        handle = _write_maps(driver, execs)
        plan, by_slot = _plan_and_stage(driver, execs, handle)
        driver.unregister_shuffle(handle.shuffle_id)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snaps = [ex.executor.pushed_store.snapshot() for ex in execs]
            if all(s["staged_ranges"] == 0 and s["mem_bytes"] == 0
                   for s in snaps):
                break
            time.sleep(0.02)
        else:
            raise AssertionError(snaps)
        store = by_slot[plan.placement_of(0)].executor.pushed_store
        status, _ = store.push(handle.shuffle_id, 0, fence=99,
                               plan_epoch=plan.plan_epoch,
                               start_partition=0, sizes=[1], data=b"x")
        assert status == M.STATUS_FINALIZED
    finally:
        _shutdown(driver, execs)


# -- microbench acceptance gate -------------------------------------------


def test_pushplan_microbench_acceptance(tmp_path):
    """The PR's acceptance gate, exactly as the bench secondary records
    it: reduce-stage start-to-first-row >= 1.5x push vs pull under the
    wire-latency shim, byte-identical output, and 0 metadata + 0 data
    RPCs for the fully-pushed read."""
    from sparkrdma_tpu.shuffle.pushplan_bench import run_pushplan_microbench

    from sparkrdma_tpu.utils.benchgate import gated_best_of

    res = gated_best_of(lambda: run_pushplan_microbench(str(tmp_path)),
                        key="pushplan_speedup")
    assert res["identical"], res
    assert res["rpcs"]["push"] == {"meta": 0, "data": 0}, res
    assert res["rpcs"]["pull"]["meta"] > 0, res
    assert res["pushplan_speedup"] >= 1.5, res
    assert res["pushed_reads"] == res["maps"] * res["partitions"], res
