"""Storage-fault harness and the hardened write/serve path.

Covers the disk half of the chaos story (the network half lives in
``test_faults.py``/``test_chaos.py``): seeded storage fault injection
(ENOSPC / EIO / torn writes / slow disk / at-rest corruption), spill
retries into fallback dirs with quarantine, clean attempt failure with
full reaping, spill-worker-death detection, counted cleanup swallows,
crash-restart recovery windows, commit fencing (resolver CAS + driver
publish rejection), and at-rest CRC verification end to end on both the
Python and native dataplanes.
"""

import os
import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.parallel import faults as fault_mod
from sparkrdma_tpu.parallel.faults import (
    CORRUPT_AT_REST,
    EIO,
    ENOSPC,
    SLOW_DISK,
    TORN_WRITE,
    StorageFaultInjector,
)
from sparkrdma_tpu.runtime import native
from sparkrdma_tpu.shuffle.manager import PartitionerSpec, TpuShuffleManager
from sparkrdma_tpu.shuffle.map_output import DriverTable
from sparkrdma_tpu.shuffle.recovery import run_map_stage, run_reduce_with_retry
from sparkrdma_tpu.shuffle.resolver import (
    StaleAttemptError,
    TpuShuffleBlockResolver,
)
from sparkrdma_tpu.shuffle.writer import (
    TpuShuffleWriter,
    WriteFailedError,
    decode_rows,
)
from sparkrdma_tpu.utils import integrity


def _mod_part(n):
    return lambda keys: (np.asarray(keys) % n).astype(np.int64)


def _write_map(writer, seed=0, batches=3, rows=400):
    rng = np.random.default_rng(seed)
    for _ in range(batches):
        writer.write_batch(rng.integers(0, 4096, rows).astype(np.uint64))


def _tmp_leftovers(*dirs):
    out = []
    for d in dirs:
        for root, _dirs, names in os.walk(d):
            out += [n for n in names if n.endswith(".tmp")]
    return out


@pytest.fixture
def injector():
    inj = StorageFaultInjector(seed=0)
    inj.install()
    yield inj
    inj.uninstall()


# -- injector unit behavior ----------------------------------------------


def test_storage_injector_matching_windows(tmp_path, injector):
    injector.add(ENOSPC, op="spill_write", path_substr="alpha", after=1,
                 times=2)
    # wrong op / wrong path: no fire
    fault_mod.storage_check("merge_write", "/x/alpha/f")
    fault_mod.storage_check("spill_write", "/x/beta/f")
    # first match is skipped (after=1), next two fire, then exhausted
    fault_mod.storage_check("spill_write", "/x/alpha/f")
    with pytest.raises(OSError):
        fault_mod.storage_check("spill_write", "/x/alpha/f")
    with pytest.raises(OSError):
        fault_mod.storage_check("spill_write", "/x/alpha/f")
    fault_mod.storage_check("spill_write", "/x/alpha/f")
    assert injector.fired_count(ENOSPC) == 2


def test_storage_injector_uninstalled_is_noop(tmp_path):
    inj = StorageFaultInjector()
    inj.add(EIO)
    # never installed: hooks must stay no-ops
    fault_mod.storage_check("spill_write", "/anything")
    assert fault_mod.storage_write_cap("spill_write", "/anything", 10) is None


def test_torn_write_cap_and_slow_disk(tmp_path, injector):
    injector.add(TORN_WRITE, op="spill_write", torn_bytes=7, times=1)
    assert fault_mod.storage_write_cap("spill_write", "/f", 100) == 7
    assert fault_mod.storage_write_cap("spill_write", "/f", 100) is None
    injector.add(SLOW_DISK, op="serve_read", delay_s=0.05, times=1)
    t0 = time.monotonic()
    fault_mod.storage_check("serve_read", "/f")
    assert time.monotonic() - t0 >= 0.04


def test_corrupt_at_rest_flips_bits(tmp_path, injector):
    p = str(tmp_path / "f")
    with open(p, "wb") as f:
        f.write(b"\x00" * 128)
    injector.add(CORRUPT_AT_REST, op="commit", flip_bits=3, times=1)
    fault_mod.storage_corrupt("commit", p)
    data = open(p, "rb").read()
    assert data != b"\x00" * 128 and len(data) == 128


# -- integrity primitives -------------------------------------------------


def test_sidecar_roundtrip(tmp_path):
    import zlib
    data_path = str(tmp_path / "shuffle_1_0.data")
    parts = [b"abc" * 100, b"", b"zzz" * 57]
    with open(data_path, "wb") as f:
        for p in parts:
            f.write(p)
    crcs = [zlib.crc32(p) for p in parts]
    lens = [len(p) for p in parts]
    integrity.write_sidecar(data_path, fence=42, partition_crcs=crcs,
                            partition_lengths=lens)
    fence, got_crcs, file_crc = integrity.read_sidecar(data_path)
    assert fence == 42 and got_crcs == crcs
    assert file_crc == integrity.file_crc32(data_path)
    assert integrity.combine_parts(crcs, lens) == file_crc
    assert integrity.partition_crcs_of_file(data_path, lens) == crcs
    assert integrity.read_sidecar(str(tmp_path / "nope.data")) is None


# -- hardened spill path --------------------------------------------------


def _writer(resolver, conf, sid=1, mid=0, parts=4):
    return TpuShuffleWriter(resolver, sid, mid, parts, _mod_part(parts), 0,
                            conf=conf)


def test_spill_enospc_retries_into_fallback_dir(tmp_path, injector):
    primary, fb = str(tmp_path / "s"), str(tmp_path / "fb")
    conf = TpuShuffleConf(spill_threshold_bytes=0, spill_dirs=fb,
                          spill_retry_budget=2, retry_backoff_base_ms=1,
                          retry_backoff_cap_ms=5)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    injector.add(ENOSPC, op="spill_write", path_substr=primary + "/",
                 times=1)
    w = _writer(resolver, conf)
    _write_map(w, seed=1)
    token, lengths = w.close()
    assert injector.fired_count(ENOSPC) == 1
    assert w.metrics.spill_retries >= 1
    assert w.metrics.spill_dir_failures >= 1
    # byte-identical to a fault-free run of the same input
    r2 = TpuShuffleBlockResolver(str(tmp_path / "clean"), conf=conf)
    w2 = _writer(r2, conf)
    _write_map(w2, seed=1)
    w2.close()
    got = open(resolver._shuffles[1][0].path, "rb").read()
    want = open(r2._shuffles[1][0].path, "rb").read()
    assert got == want and len(got) > 0
    # nothing left behind in either dir
    assert _tmp_leftovers(primary, fb) == []
    resolver.stop()
    r2.stop()


def test_spill_dir_quarantined_after_max_failures(tmp_path, injector):
    primary, fb = str(tmp_path / "s"), str(tmp_path / "fb")
    conf = TpuShuffleConf(spill_threshold_bytes=0, spill_dirs=fb,
                          spill_dir_max_failures=1, spill_retry_budget=3,
                          retry_backoff_base_ms=1, retry_backoff_cap_ms=5)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    injector.add(ENOSPC, op="spill_write", path_substr=primary + "/")
    w = _writer(resolver, conf)
    _write_map(w, seed=2)
    w.close()
    assert resolver.spill_dir_health()["quarantined"] == [primary]
    # a NEW writer never even tries the quarantined dir
    before = injector.fired_count(ENOSPC)
    w2 = _writer(resolver, conf, mid=1)
    _write_map(w2, seed=3)
    w2.close()
    assert injector.fired_count(ENOSPC) == before
    assert w2.metrics.spill_retries == 0
    resolver.stop()


def test_torn_spill_write_retried_clean(tmp_path, injector):
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes=0, spill_retry_budget=2,
                          retry_backoff_base_ms=1, retry_backoff_cap_ms=5)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    injector.add(TORN_WRITE, op="spill_write", torn_bytes=16, times=1)
    w = _writer(resolver, conf)
    _write_map(w, seed=4)
    w.close()
    assert injector.fired_count(TORN_WRITE) == 1
    assert w.metrics.spill_retries >= 1
    r2 = TpuShuffleBlockResolver(str(tmp_path / "clean"), conf=conf)
    w2 = _writer(r2, conf)
    _write_map(w2, seed=4)
    w2.close()
    assert (open(resolver._shuffles[1][0].path, "rb").read()
            == open(r2._shuffles[1][0].path, "rb").read())
    assert _tmp_leftovers(primary) == []
    resolver.stop()
    r2.stop()


def test_enospc_shrinks_spill_threshold(tmp_path, injector):
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes="8k", spill_retry_budget=2,
                          retry_backoff_base_ms=1, retry_backoff_cap_ms=5)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    injector.add(ENOSPC, op="spill_write", times=1)
    w = _writer(resolver, conf)
    assert w.spill_threshold == 8 << 10
    _write_map(w, seed=5, batches=8, rows=500)
    w.close()
    assert w.metrics.spill_shrinks == 1
    assert w.spill_threshold <= 4 << 10
    resolver.stop()


def test_spill_failure_exhausted_fails_attempt_cleanly(tmp_path, injector):
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes=0, spill_retry_budget=1,
                          retry_backoff_base_ms=1, retry_backoff_cap_ms=5)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    injector.add(EIO, op="spill_write")  # every attempt, every dir
    w = _writer(resolver, conf)
    with pytest.raises(WriteFailedError):
        _write_map(w, seed=6, batches=10)
        w.close()
    if not w.closed:
        w.close(success=False)
    assert os.listdir(primary) == []  # clean failure: everything reaped
    resolver.stop()


def test_fatal_disk_error_fails_without_retry(tmp_path, injector):
    """A non-transient errno (EACCES here) must not burn the retry
    budget — the attempt fails immediately and cleanly."""
    import errno as _errno

    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes=0, spill_retry_budget=5,
                          retry_backoff_base_ms=1, retry_backoff_cap_ms=5)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    w = _writer(resolver, conf)

    real_open = open

    def denied(path, *a, **kw):
        if str(path).endswith(".s0.tmp"):
            raise OSError(_errno.EACCES, "injected permission denial", path)
        return real_open(path, *a, **kw)

    import builtins
    orig = builtins.open
    builtins.open = denied
    try:
        with pytest.raises(WriteFailedError):
            _write_map(w, seed=7, batches=10)
            w.close()
    finally:
        builtins.open = orig
    if not w.closed:
        w.close(success=False)
    assert w.metrics.spill_retries == 0
    assert os.listdir(primary) == []
    resolver.stop()


def test_spill_rotation_reaches_every_healthy_dir(tmp_path, injector):
    """With primary and the first fallback persistently failing, the
    SECOND fallback must get its attempt inside the retry budget."""
    primary = str(tmp_path / "s")
    fb1, fb2 = str(tmp_path / "fb1"), str(tmp_path / "fb2")
    conf = TpuShuffleConf(spill_threshold_bytes=0,
                          spill_dirs=f"{fb1},{fb2}",
                          spill_retry_budget=2, spill_dir_max_failures=10,
                          retry_backoff_base_ms=1, retry_backoff_cap_ms=5)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    injector.add(EIO, op="spill_write", path_substr=primary + "/")
    injector.add(EIO, op="spill_write", path_substr=fb1 + "/")
    w = _writer(resolver, conf)
    _write_map(w, seed=11)
    w.close()  # budget 2 = 3 attempts: primary, fb1, fb2 — fb2 heals it
    assert w.metrics.spill_retries >= 2
    assert _tmp_leftovers(primary, fb1, fb2) == []
    resolver.stop()


def test_commit_failure_after_rename_leaves_no_orphan_data(tmp_path,
                                                           injector):
    """A failed index/sidecar write AFTER the data rename must UN-commit:
    an index-less .data file would otherwise survive every sweep (the
    writer's cleanup only knows .tmp names) and leak a full-size file on
    an already-failing disk."""
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(at_rest_checksum=True)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    injector.add(ENOSPC, op="index_write")
    w = _writer(resolver, conf)
    _write_map(w, seed=12)
    with pytest.raises(WriteFailedError):
        w.close()
    assert os.listdir(primary) == [], \
        "a failed commit must leave nothing on disk"
    # the next attempt (no fault left) commits normally
    injector.clear()
    w2 = _writer(resolver, conf)
    _write_map(w2, seed=12)
    w2.close()
    assert resolver.get_output_table(1, 0) is not None
    resolver.stop()


# -- satellite: spill-worker death must wake blocked writers --------------


def test_spill_worker_death_wakes_writer(tmp_path):
    """Regression: a KILLED spill worker (thread gone, accounting stuck)
    must wake a ``write_batch`` blocked on the backpressure wait and
    raise, not hang the map task forever."""
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes=0, write_spill_threads=1)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    w = _writer(resolver, conf)
    # kill switch: the worker thread exits the moment it starts, leaving
    # the queued spill permanently in flight
    w._spill_worker = lambda: None
    w.write_batch(np.arange(100, dtype=np.uint64))  # enqueues the spill
    t0 = time.monotonic()
    with pytest.raises(WriteFailedError, match="spill"):
        for _ in range(50):
            w.write_batch(np.arange(100, dtype=np.uint64))
    assert time.monotonic() - t0 < 10, "detection must not wait out a hang"
    w.close(success=False)
    assert _tmp_leftovers(primary) == []
    resolver.stop()


def test_spill_worker_death_wakes_close(tmp_path):
    """Same detection on the close()/drain path."""
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes=0, write_spill_threads=2)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    w = _writer(resolver, conf)
    w._spill_worker = lambda: None
    w.write_batch(np.arange(100, dtype=np.uint64))
    time.sleep(0.05)  # let the doomed worker exit
    with pytest.raises(WriteFailedError, match="spill"):
        w.close()
    assert _tmp_leftovers(primary) == []
    resolver.stop()


# -- satellite: cleanup swallows are counted ------------------------------


def test_cleanup_swallows_are_counted(tmp_path, monkeypatch):
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes=0)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    w = _writer(resolver, conf)
    _write_map(w, seed=8, batches=2)

    real_unlink = os.unlink
    blocked = []

    def flaky_unlink(path, *a, **kw):
        if str(path).endswith(".s0.tmp"):
            blocked.append(str(path))
            raise PermissionError(13, "injected unlink denial", path)
        return real_unlink(path, *a, **kw)

    monkeypatch.setattr(os, "unlink", flaky_unlink)
    w.close()  # commit succeeds; spill cleanup swallow is counted
    monkeypatch.undo()
    assert w.metrics.cleanup_errors >= 1
    assert blocked, "the injected unlink failure never triggered"
    for path in blocked:
        if os.path.exists(path):
            real_unlink(path)
    resolver.stop()


# -- satellite: crash-restart recovery windows ----------------------------


def test_recover_crash_windows_and_orphan_sweep(tmp_path):
    """Death between data-rename and index-write, and death mid-spill:
    recover() serves ONLY fully-committed attempts and sweeps every
    orphan ``.tmp``/``.s<seq>.tmp`` — fallback spill dirs included."""
    primary, fb = str(tmp_path / "s"), str(tmp_path / "fb")
    conf = TpuShuffleConf(spill_threshold_bytes=0, spill_dirs=fb,
                          at_rest_checksum=True)
    r1 = TpuShuffleBlockResolver(primary, conf=conf)
    w = _writer(r1, conf, mid=0)
    _write_map(w, seed=9)
    w.close()
    committed_bytes = open(r1._shuffles[1][0].path, "rb").read()

    # crash window (a): data renamed, index never written (map 1)
    with open(os.path.join(primary, "shuffle_1_1.data"), "wb") as f:
        f.write(b"\x07" * 64)
    # crash window (b): mid-spill death (map 2) — tmp + spills, one of
    # them in the crashed resolver's (namespaced) fallback dir
    for name, d in [("shuffle_1_2.99.tmp", primary),
                    ("shuffle_1_2.99.tmp.s0.tmp", primary),
                    ("shuffle_1_2.99.tmp.s1.tmp",
                     r1.fallback_spill_dirs[0])]:
        with open(os.path.join(d, name), "wb") as f:
            f.write(b"junk")

    r2 = TpuShuffleBlockResolver(primary, conf=conf)
    recovered = r2.recover()
    assert [m for m, _ in recovered[1]] == [0] and list(recovered) == [1]
    assert r2.committed_fence(1, 0) == w.fence
    assert _tmp_leftovers(primary, fb) == []
    # the committed map still serves, byte-identical
    assert r2.local_blocks(1, 0, 0, 4) == committed_bytes
    # the half-committed data file is NOT served (recompute owns it)
    assert r2.get_output_table(1, 1) is None
    r2.stop()


def test_recover_drops_corrupt_and_unattested_files(tmp_path):
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(at_rest_checksum=True)
    r1 = TpuShuffleBlockResolver(primary, conf=conf)
    w = _writer(r1, conf, mid=0)
    _write_map(w, seed=10)
    w.close()
    data_path = r1._shuffles[1][0].path

    # map 1: committed pair WITHOUT a sidecar (checksum-off commit):
    # unattested under at_rest_checksum — treated as lost
    p1 = os.path.join(primary, "shuffle_1_1.data")
    with open(p1, "wb") as f:
        f.write(b"\x01" * 32)
    np.array([32], dtype=np.uint64).tofile(p1 + ".index")

    # rot map 0's committed bytes
    with open(data_path, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))

    r2 = TpuShuffleBlockResolver(primary, conf=conf)
    recovered = r2.recover()
    assert recovered == {}
    assert r2.corrupt_outputs == 1
    # both the corrupt set and the unattested pair were deleted so the
    # recompute starts clean and nothing full-size leaks across restarts
    assert not os.path.exists(data_path)
    assert not os.path.exists(data_path + ".index")
    assert not os.path.exists(integrity.sidecar_path(data_path))
    assert not os.path.exists(p1) and not os.path.exists(p1 + ".index")
    r2.stop()


def test_recovered_fence_does_not_fence_new_attempts(tmp_path):
    """Regression: after a restart, the attempt allocator restarts at 1
    while recover() restores higher committed fences from sidecars — a
    re-execution of a recovered map on the SAME executor must still
    out-fence its pre-crash commit, not lose the CAS forever."""
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(at_rest_checksum=True)
    r1 = TpuShuffleBlockResolver(primary, conf=conf)
    # burn a few attempts so the committed fence is well above 1
    for _ in range(3):
        r1.begin_attempt(1, 0)
    w = _writer(r1, conf)
    _write_map(w, seed=13)
    w.close()
    assert w.fence >= 4

    r2 = TpuShuffleBlockResolver(primary, conf=conf)
    recovered = r2.recover()
    assert [m for m, _ in recovered[1]] == [0]
    assert r2.committed_fence(1, 0) == w.fence
    # the re-execution (e.g. corrupt-output healing) commits fine
    w2 = _writer(r2, conf)
    assert w2.fence > w.fence
    _write_map(w2, seed=14)
    w2.close()
    r2.stop()


# -- satellite: commit fencing --------------------------------------------


def test_commit_fencing_loser_rejected_and_reaped(tmp_path):
    """Two concurrent speculative attempts of one map; the loser (older
    fence) commits AFTER the winner: the winner's bytes stay served, the
    loser raises StaleAttemptError, and the loser's files are reaped."""
    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes=0)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    loser = _writer(resolver, conf)     # fence f
    winner = _writer(resolver, conf)    # fence f+1
    assert winner.fence > loser.fence
    loser.write_batch(np.full(64, 3, dtype=np.uint64))
    winner.write_batch(np.full(64, 7, dtype=np.uint64))
    winner.close()
    with pytest.raises(StaleAttemptError):
        loser.close()
    assert resolver.fenced_commits == 1
    keys, _ = decode_rows(resolver.local_blocks(1, 0, 0, 4), 0)
    assert set(keys.tolist()) == {7}, "winner's bytes must be served"
    assert _tmp_leftovers(primary) == []
    resolver.stop()


def test_driver_table_publish_fencing_unit():
    t = DriverTable(4)
    assert t.publish(0, 10, exec_index=1, fence=5)
    assert not t.publish(0, 11, 1, fence=4)  # stale same-exec: rejected
    assert t.entry(0) == (10, 1)
    assert t.publish(0, 12, 1, fence=5)      # idempotent re-publish
    assert t.publish(0, 13, 2, fence=1)      # cross-exec always applies
    assert t.entry(0) == (13, 2)
    assert not t.publish(0, 14, 2, fence=0)  # now fenced on exec 2
    assert t.entry(0) == (13, 2)


def _cluster(tmp_path, n=2, **kw):
    base = dict(connect_timeout_ms=3000, max_connection_attempts=2,
                retry_backoff_base_ms=10, retry_backoff_cap_ms=80,
                fetch_retry_budget=1, use_cpp_runtime=False,
                pre_warm_connections=False)
    base.update(kw)
    conf = TpuShuffleConf(**base)
    driver = TpuShuffleManager(conf, is_driver=True)
    execs = [TpuShuffleManager(conf, driver_addr=driver.driver_addr,
                               executor_id=str(i),
                               spill_dir=str(tmp_path / f"e{i}"))
             for i in range(n)]
    for ex in execs:
        ex.executor.wait_for_members(n)
    return driver, execs


def _shutdown(driver, execs):
    for ex in execs:
        ex.stop()
    driver.stop()


def test_publish_fencing_rejects_stale_e2e(tmp_path):
    driver, execs = _cluster(tmp_path)
    try:
        handle = driver.register_shuffle(1, num_maps=1, num_partitions=2,
                                         partitioner=PartitionerSpec("modulo"))
        w = execs[0].get_writer(handle, 0)
        w.write_batch(np.arange(32, dtype=np.uint64))
        token, _ = w.close()
        time.sleep(0.1)
        entry = driver.driver.map_entry(1, 0)
        assert entry == (token, execs[0].executor.exec_index())
        # a zombie's late publish: same executor, older fence
        execs[0].executor.publish_map_output(1, 0, 4242, fence=0)
        time.sleep(0.2)
        assert driver.driver.map_entry(1, 0) == entry, \
            "stale publish must not clobber the committed winner"
        assert driver.driver.fenced_publishes == 1
    finally:
        _shutdown(driver, execs)


@pytest.mark.parametrize("native_dataplane", [
    False,
    pytest.param(True, marks=pytest.mark.skipif(
        not native.available(), reason="native runtime not built")),
])
def test_speculative_loser_fenced_winner_served(tmp_path, native_dataplane):
    """Acceptance: the stale attempt's late commit/publish is rejected
    and the committed winner's bytes are the ones a reducer receives —
    on the Python AND native dataplanes."""
    driver, execs = _cluster(tmp_path, use_cpp_runtime=native_dataplane)
    try:
        handle = driver.register_shuffle(1, num_maps=1, num_partitions=2,
                                         partitioner=PartitionerSpec("modulo"))
        loser = execs[0].get_writer(handle, 0)
        winner = execs[0].get_writer(handle, 0)
        loser.write_batch(np.full(64, 4, dtype=np.uint64))
        winner.write_batch(np.full(64, 8, dtype=np.uint64))
        winner.close()
        with pytest.raises(StaleAttemptError):
            loser.close()
        keys, _ = execs[1].get_reader(handle, 0, 2).read_all()
        assert set(keys.tolist()) == {8}, "winner's bytes must be served"
        assert execs[0].resolver.fenced_commits == 1
        assert _tmp_leftovers(str(tmp_path / "e0")) == []
    finally:
        _shutdown(driver, execs)


# -- at-rest corruption: detection and re-execution -----------------------


def _flip_mid_byte(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


def _map_fn(writer, map_id):
    rng = np.random.default_rng(1000 + map_id)
    writer.write_batch(rng.integers(0, 5000, 500).astype(np.uint64))


def _reduce_fn(mgr, handle):
    keys, _ = mgr.get_reader(handle, 0, handle.num_partitions).read_all()
    return np.sort(keys)


def _expected(num_maps):
    return np.sort(np.concatenate(
        [np.random.default_rng(1000 + m).integers(0, 5000, 500)
         for m in range(num_maps)]).astype(np.uint64))


@pytest.mark.parametrize("native_dataplane", [
    False,
    pytest.param(True, marks=pytest.mark.skipif(
        not native.available(), reason="native runtime not built")),
])
def test_at_rest_corruption_reexecutes_only_that_map(tmp_path,
                                                     native_dataplane):
    """Bit-rot in ONE committed output after commit: the serve-time CRC
    check demotes it to STATUS_CORRUPT, the reducer escalates with a
    corrupt_output verdict, and recovery re-executes exactly that map —
    no tombstone, no recompute of the owner's healthy outputs — ending
    byte-identical. On the native dataplane the detection rides the
    location serve (the only Python touchpoint there)."""
    driver, execs = _cluster(tmp_path, at_rest_checksum=True,
                             use_cpp_runtime=native_dataplane)
    map_runs = []
    try:
        handle = driver.register_shuffle(1, num_maps=6, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn,
                      placement={m: 1 for m in range(6)})
        victim_map = 3
        _flip_mid_byte(execs[1].resolver._shuffles[1][victim_map].path)

        def counting_map_fn(writer, map_id):
            map_runs.append(map_id)
            _map_fn(writer, map_id)

        got = run_reduce_with_retry(execs, handle, counting_map_fn,
                                    _reduce_fn, reducer_index=0,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(6))
        assert map_runs == [victim_map], \
            f"exactly the corrupt map must re-execute, got {map_runs}"
        assert execs[1].resolver.corrupt_outputs >= 1
        from sparkrdma_tpu.parallel.endpoints import TOMBSTONE
        assert all(m != TOMBSTONE for m in driver.driver.members()), \
            "bit-rot must never tombstone a live executor"
    finally:
        _shutdown(driver, execs)


def test_local_at_rest_corruption_reexecutes(tmp_path):
    """The reducer's OWN committed output rotted: the local short-circuit
    detects it the same way and the map re-executes."""
    driver, execs = _cluster(tmp_path, at_rest_checksum=True)
    try:
        handle = driver.register_shuffle(1, num_maps=2, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn)
        # map 0 ran on exec 0 == the reducer: rot it
        _flip_mid_byte(execs[0].resolver._shuffles[1][0].path)
        got = run_reduce_with_retry(execs, handle, _map_fn, _reduce_fn,
                                    reducer_index=0, driver=driver)
        np.testing.assert_array_equal(got, _expected(2))
        assert execs[0].resolver.corrupt_outputs >= 1
    finally:
        _shutdown(driver, execs)


def test_rot_after_recover_detected_and_healed(tmp_path):
    """Regression: recover()'s mmap-open verification must not exempt a
    recovered output from serve-time spot checks — rot landing BETWEEN
    restart-recovery and first serve was previously served silently (the
    fetch CRC trailer is computed over the rotted bytes, so it
    matches). The rejoined owner's re-execution must also out-fence its
    own pre-crash commit (the allocator-bump fix)."""
    driver, execs = _cluster(tmp_path, at_rest_checksum=True)
    rejoined = None
    try:
        handle = driver.register_shuffle(1, num_maps=4, num_partitions=4,
                                         partitioner=PartitionerSpec("modulo"))
        run_map_stage(execs, handle, _map_fn,
                      placement={m: 1 for m in range(4)})
        lost = execs[1].executor.manager_id
        execs[1].executor.stop()
        if execs[1].block_server is not None:
            execs[1].block_server.stop()
        driver.driver.remove_member(lost)
        time.sleep(0.3)
        rejoined = TpuShuffleManager(
            execs[0].conf, driver_addr=driver.driver_addr,
            executor_id="1b", spill_dir=str(tmp_path / "e1"))
        rejoined.executor.wait_for_members(2)
        rec = rejoined.recover_and_republish()
        assert sorted(m for m, _ in rec[1]) == [0, 1, 2, 3]
        time.sleep(0.2)
        # rot AFTER recovery verified the files
        _flip_mid_byte(rejoined.resolver._shuffles[1][2].path)
        execs[0].executor.invalidate_shuffle(1)
        got = run_reduce_with_retry([execs[0], rejoined], handle, _map_fn,
                                    _reduce_fn, reducer_index=0,
                                    driver=driver)
        np.testing.assert_array_equal(got, _expected(4))
        assert rejoined.resolver.corrupt_outputs >= 1
    finally:
        if rejoined is not None:
            rejoined.stop()
        _shutdown(driver, execs)


def test_at_rest_writer_streams_crcs_no_extra_read(tmp_path):
    """The streaming writer's sidecar CRCs (spill-time + merge-time
    streaming, crc32_combine for sendfile'd segments) must equal a
    from-scratch read of the committed file — spills, fallback dirs and
    combiners included."""
    from sparkrdma_tpu.shuffle.writer import make_sum_combiner

    primary = str(tmp_path / "s")
    conf = TpuShuffleConf(spill_threshold_bytes="2k", at_rest_checksum=True)
    resolver = TpuShuffleBlockResolver(primary, conf=conf)
    for mid, combiner in ((0, None), (1, make_sum_combiner("<u4"))):
        w = TpuShuffleWriter(resolver, 1, mid, 4, _mod_part(4), 4,
                             combiner=combiner, conf=conf)
        rng = np.random.default_rng(20 + mid)
        for _ in range(6):
            keys = rng.integers(0, 64, 300).astype(np.uint64)
            payload = rng.integers(0, 255, (300, 4)).astype(np.uint8)
            w.write_batch(keys, payload)
        w.close()
        assert w.metrics.spills >= 2
        spill = resolver._shuffles[1][mid].path
        fence, crcs, file_crc = integrity.read_sidecar(spill)
        lengths = np.fromfile(spill + ".index", dtype=np.uint64).tolist()
        assert crcs == integrity.partition_crcs_of_file(spill, lengths)
        assert file_crc == integrity.file_crc32(spill)
        assert fence == w.fence
    resolver.stop()
