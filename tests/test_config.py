"""Config parsing/validation tests (reference behavior:
scala/RdmaShuffleConf.scala:36-47 — invalid values fall back to defaults)."""

import pytest

from sparkrdma_tpu.config import TpuShuffleConf, parse_bytes, format_bytes


def test_parse_bytes():
    assert parse_bytes("8m") == 8 << 20
    assert parse_bytes("256k") == 256 << 10
    assert parse_bytes("10g") == 10 << 30
    assert parse_bytes("4K") == 4096
    assert parse_bytes(1234) == 1234
    assert parse_bytes("1.5k") == 1536
    with pytest.raises(ValueError):
        parse_bytes("abc")


def test_format_bytes_roundtrip():
    for s in ("8m", "256k", "10g", "16k"):
        assert format_bytes(parse_bytes(s)) == s


def test_defaults():
    c = TpuShuffleConf()
    assert c.shuffle_write_block_size == 8 << 20
    assert c.shuffle_read_block_size == 256 << 10
    assert c.max_bytes_in_flight == 48 << 20
    assert c.send_queue_depth == 4096
    assert c.recv_queue_depth == 256
    assert c.rpc_msg_size == 4096
    assert c.max_buffer_allocation_size == 10 << 30
    assert c.port_max_retries == 16
    assert c.max_connection_attempts == 5
    assert c.fetch_time_bucket_size_ms == 300
    assert c.fetch_time_num_buckets == 5
    assert c.sw_flow_control is True
    assert c.collect_shuffle_reader_stats is False


def test_prefixed_and_override_keys():
    c = TpuShuffleConf({"spark.shuffle.tpu.shuffle_read_block_size": "1m"},
                       max_bytes_in_flight="96m")
    assert c.shuffle_read_block_size == 1 << 20
    assert c.max_bytes_in_flight == 96 << 20
    # dotted key form also accepted
    c2 = TpuShuffleConf({"spark.shuffle.tpu.shuffle.read.block.size": "2m"})
    assert c2.shuffle_read_block_size == 2 << 20


def test_invalid_falls_back_to_default():
    c = TpuShuffleConf(shuffle_read_block_size="not-a-size",
                       send_queue_depth=-5,
                       max_connection_attempts=10**9)
    assert c.shuffle_read_block_size == 256 << 10
    assert c.send_queue_depth == 4096
    assert c.max_connection_attempts == 5


def test_unknown_key_raises():
    c = TpuShuffleConf()
    with pytest.raises(AttributeError):
        _ = c.no_such_key


def test_prealloc_spec():
    c = TpuShuffleConf(prealloc_buffers="4k:128,1m:16,4k:2")
    assert c.prealloc_spec() == {4096: 130, 1 << 20: 16}
    assert TpuShuffleConf().prealloc_spec() == {}
    # malformed entries skipped
    c2 = TpuShuffleConf(prealloc_buffers="4k:xx,oops,1m:4")
    assert c2.prealloc_spec() == {1 << 20: 4}


def test_bool_parsing():
    assert TpuShuffleConf(sw_flow_control="false").sw_flow_control is False
    assert TpuShuffleConf(sw_flow_control="1").sw_flow_control is True
    assert TpuShuffleConf(collect_shuffle_reader_stats="True").collect_shuffle_reader_stats is True
