"""Seeded violation for the resources leak lint: a ledger charge whose
release exists — but only on ONE path. The quota-rejection branch
repays; the success path returns with the charge held and nothing
recorded to repay it later (no ownership transfer, no pragma)."""


class LeakyStore:
    def __init__(self, ledger):
        self.disk_ledger = ledger
        self.size = 0

    def keep(self, tenant: int, nbytes: int) -> bool:
        self.disk_ledger.charge(tenant, nbytes)  # seeded-violation
        if nbytes > 4096:
            # oversize: shed and repay — the ONLY path that releases
            self.disk_ledger.release(tenant, nbytes)
            return False
        self.size += nbytes
        return True

    def paired(self, tenant: int, nbytes: int) -> None:
        """Control: all-paths release — the lint must stay quiet."""
        self.disk_ledger.charge(tenant, nbytes)
        try:
            self.size += nbytes
        finally:
            self.disk_ledger.release(tenant, nbytes)
