"""Seeded violation for the concurrency pass: ``Condition.wait`` under
an ``if`` instead of a ``while`` predicate loop (and with no deadline —
the in-loop deadline rule has its own seeded line below).
"""

import threading


class Waiter:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def wait_ready(self):
        with self._cv:
            if not self._ready:
                self._cv.wait()  # seeded-violation: no predicate loop

    def wait_ready_forever(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()  # seeded-deadline: loop but no timeout
