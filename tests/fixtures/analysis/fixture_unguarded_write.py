"""Seeded violation for the concurrency pass: ``_count`` is mutated
under ``self._lock`` on the hot path but clobbered without it in
``reset`` — the classic teardown race the unguarded-write lint flags.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def incr(self):
        with self._lock:
            self._count += 1

    def reset(self):
        self._count = 0  # seeded-violation: write outside the lock
