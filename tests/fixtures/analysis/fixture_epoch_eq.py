"""Seeded violation for the resources epoch-comparison lint: a raw
``==`` between epoch-typed values (staleness decided by equality where
only a monotone guard can tell newer from older). The sentinel check
and the monotone guard below are the allowed forms — the lint must
flag exactly the marker line."""

EPOCH_DEAD = -1


def serve(cached_epoch: int, epoch: int) -> bool:
    if epoch == EPOCH_DEAD:          # allowed: declared sentinel
        return False
    if cached_epoch < epoch:         # allowed: monotone guard
        return False
    if cached_epoch == epoch - 1:    # seeded-violation
        return False
    return True


def tainted(table) -> bool:
    known = table.get_epoch()
    return known != table.newest  # seeded-taint
