"""Seeded violation for the wire pass: two classes claim wire id 1.

Never imported by production code — tests/test_analysis.py feeds
``FIXTURE_PAIRS`` to ``wire.check_registry`` and asserts the duplicate
is caught with this file and the second class's line.
"""

import struct


class PingA:
    MSG_TYPE = 1

    def __init__(self, req_id=0):
        self.req_id = req_id

    def payload(self):
        return struct.pack("<q", self.req_id)

    @classmethod
    def from_payload(cls, payload):
        return cls(*struct.unpack_from("<q", payload, 0))


class PingB(PingA):  # seeded-violation: same wire id as PingA
    MSG_TYPE = 1


FIXTURE_PAIRS = [(1, PingA), (1, PingB)]
FIXTURE_WIRE_IDS = {"PingA": 1, "PingB": 1}
