"""Seeded violation: a schedule step that regresses an observer's
observed location epoch (modeling a buggy cache that re-applies a
stale observation instead of dropping it).

The model checker's epoch-monotone invariant must catch it, anchored
at the regressing step's exact line (the marker comment below), and
the recorded violating trace must replay byte-identically — this
fixture doubles as the ``--replay`` contract test.
"""

from sparkrdma_tpu.analysis.modelcheck import World


def build(sched):
    world = World(num_observers=1)
    sid = world.sid
    world.observers[0].note_epoch(sid, 5)
    # the seeded bug: a response handler that writes its stale observed
    # epoch back instead of keeping the monotone maximum
    sched.post("resp.stale_overwrite",
               lambda s: world.observers[0]._epochs.__setitem__(sid, 2),  # seeded-violation
               chan="obs0.resp", touches={"obs0"})
    sched.post("bump.e6->obs0",
               lambda s: world.observers[0].note_epoch(sid, 6),
               chan="obs0.push", touches={"obs0"})
    return world
