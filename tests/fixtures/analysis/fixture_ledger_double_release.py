"""Seeded violation: a TenantLedger double-release.

Two racing teardown paths repay the SAME bytes — the classic
drop-vs-failure-cleanup race. Because ``TenantLedger.release`` floors
at zero, the double repayment silently erases ANOTHER tenant item's
live charge (tenant B's 50 bytes below), which the model checker's
ledger-conserve invariant catches: usage != live charges.

The model checker must report the violation anchored at the
dup-release step's exact line (the marker comment below).
"""

from sparkrdma_tpu.analysis.modelcheck import World


def build(sched):
    world = World(num_observers=1)
    world.charge(0, 100)   # item A
    world.charge(0, 50)    # item B (stays live throughout)
    sched.post("teardown.release_a",
               lambda s: world.release(0, 100), touches={"ledger"})
    # the raced second teardown path repays item A AGAIN, straight at
    # the ledger (no bookkeeping — that is the bug being seeded)
    sched.post("teardown.dup_release_a",
               lambda s: world.ledger.release(0, 100), touches={"ledger"})  # seeded-violation
    return world
