"""Seeded violation for the drift pass: ``mystery_key`` is declared but
has no row in the paired fixture doc (fixture_undocumented_key.md),
which in turn documents a ``ghost_key`` no declaration backs.
"""


def _Key(name, default, kind):
    return (name, default, kind)


_KEYS = [
    _Key("documented_key", 1, "int"),
    _Key("mystery_key", 2, "int"),  # seeded-violation: no doc row
]
