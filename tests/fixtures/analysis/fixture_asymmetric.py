"""Seeded violation for the wire pass: pack/unpack asymmetry.

``payload()`` writes (req_id, flags) but ``from_payload`` drops flags,
so a decoded message re-encodes differently — exactly the
field-written-but-never-read drift the fuzzer exists to catch.
"""

import struct


class LossyMsg:  # seeded-violation: from_payload drops the flags field
    MSG_TYPE = 1

    def __init__(self, req_id=0, flags=0):
        self.req_id = req_id
        self.flags = flags

    def payload(self):
        return struct.pack("<qi", self.req_id, self.flags)

    @classmethod
    def from_payload(cls, payload):
        (req_id,) = struct.unpack_from("<q", payload, 0)
        return cls(req_id)  # flags lost: decodes as 0


FIXTURE_PAIRS = [(1, LossyMsg)]
FIXTURE_WIRE_IDS = {"LossyMsg": 1}
