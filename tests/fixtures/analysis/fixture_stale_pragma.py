"""Seeded violation for the stale-pragma audit: an ``unguarded-ok``
pragma on a write the concurrency lint would no longer flag (the
attribute is never shared under the class lock), left behind by an
imaginary refactor. The audit must report the pragma's own line."""

import threading


class Refactored:
    def __init__(self):
        self._lock = threading.Lock()
        self._shared = 0
        self._private = 0

    def hot(self):
        with self._lock:
            self._shared += 1

    def cold(self):
        self._private = 2  # analysis: unguarded-ok(left behind by refactor)  # seeded-violation
