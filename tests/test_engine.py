"""DAG engine end-to-end: multi-stage jobs through the exact compat SPI
sequence Spark issues (register -> getWriter/map -> getReader/reduce ->
unregister, scala/RdmaShuffleManager.scala:143-310), including stage retry
on executor loss — the engine half the reference delegates to Spark."""

import time

import numpy as np
import pytest

from engine_helpers import (
    make_cluster,
    make_table as _table,
    payload_u32 as _payload_u32,
    u32_payload as _u32_payload,
)
from sparkrdma_tpu.config import TpuShuffleConf
from sparkrdma_tpu.engine import DAGEngine, MapStage, ResultStage
from sparkrdma_tpu.shuffle.manager import PartitionerSpec
from sparkrdma_tpu.shuffle.spark_compat import (
    ShuffleDependency,
    SparkCompatShuffleManager,
)


@pytest.fixture
def cluster(tmp_path):
    driver, execs = make_cluster(tmp_path)
    yield driver, execs
    for ex in execs:
        ex.stop()
    driver.stop()


def test_two_table_join(cluster):
    """Equi-join via two shuffles + one result stage (multi-parent read)."""
    driver, execs = cluster
    P, maps, rows, key_space = 4, 3, 400, 64

    def writer_fn(base_seed):
        def fn(ctx, writer, task_id):
            keys, vals = _table(base_seed + task_id, rows, key_space)
            writer.write((keys, _u32_payload(vals)))
        return fn

    dep = ShuffleDependency(P, PartitionerSpec("modulo"), row_payload_bytes=4)
    left = MapStage(maps, dep, writer_fn(100))
    right = MapStage(maps, ShuffleDependency(P, PartitionerSpec("modulo"),
                                             row_payload_bytes=4),
                     writer_fn(200))

    def join_fn(ctx, task_id):
        lsum: dict = {}
        for keys, payload in ctx.read(0).readBatches():
            for k, v in zip(keys, _payload_u32(payload)):
                lsum.setdefault(int(k), []).append(int(v))
        total = 0
        for keys, payload in ctx.read(1).readBatches():
            for k, v in zip(keys, _payload_u32(payload)):
                for lv in lsum.get(int(k), ()):
                    total += lv * int(v)
        return total

    engine = DAGEngine(driver, execs)
    got = sum(engine.run(ResultStage(P, join_fn, parents=[left, right])))

    # job teardown must free executor-side shuffle data, not just the
    # driver table — long-lived clusters otherwise leak every dataset
    assert all(not ex.native.resolver._shuffles for ex in execs)

    # numpy oracle over the same deterministic tables
    lk = np.concatenate([_table(100 + m, rows, key_space)[0] for m in range(maps)])
    lv = np.concatenate([_table(100 + m, rows, key_space)[1] for m in range(maps)])
    rk = np.concatenate([_table(200 + m, rows, key_space)[0] for m in range(maps)])
    rv = np.concatenate([_table(200 + m, rows, key_space)[1] for m in range(maps)])
    want = 0
    for k in range(key_space):
        want += int(lv[lk == k].astype(np.int64).sum()) * \
            int(rv[rk == k].astype(np.int64).sum())
    assert got == want


def test_pagerank_iterations(cluster):
    """Two PageRank iterations, each a shuffle job through the engine."""
    driver, execs = cluster
    V, P, maps, epd = 64, 4, 3, 300  # vertices, partitions, maps, edges/map
    engine = DAGEngine(driver, execs)

    def edges_of(m):
        rng = np.random.default_rng(7000 + m)
        return (rng.integers(0, V, size=epd).astype(np.int64),
                rng.integers(0, V, size=epd).astype(np.int64))

    src_all = np.concatenate([edges_of(m)[0] for m in range(maps)])
    deg = np.maximum(np.bincount(src_all, minlength=V), 1)

    ranks = np.full(V, 1.0 / V, dtype=np.float64)
    for _ in range(2):
        snapshot = ranks.copy()

        def contrib_fn(ctx, writer, task_id):
            src, dst = edges_of(task_id)
            contrib = (snapshot[src] / deg[src]).astype("<f4")
            writer.write((dst.astype(np.uint64),
                          contrib.view(np.uint8).reshape(-1, 4)))

        def agg_fn(ctx, task_id):
            acc: dict = {}
            for keys, payload in ctx.read(0).readBatches():
                vals = np.ascontiguousarray(payload).view("<f4").ravel()
                for k, v in zip(keys, vals):
                    acc[int(k)] = acc.get(int(k), 0.0) + float(v)
            return acc

        stage = MapStage(maps, ShuffleDependency(
            P, PartitionerSpec("modulo"), row_payload_bytes=4), contrib_fn)
        parts = engine.run(ResultStage(P, agg_fn, parents=[stage]))
        ranks = np.full(V, 0.15 / V)
        for part in parts:
            for v, s in part.items():
                ranks[v] += 0.85 * s

    # dense numpy oracle, identical float32 contributions
    want = np.full(V, 1.0 / V, dtype=np.float64)
    for _ in range(2):
        acc = np.zeros(V)
        for m in range(maps):
            src, dst = edges_of(m)
            np.add.at(acc, dst, (want[src] / deg[src]).astype(np.float32)
                      .astype(np.float64))
        want = 0.15 / V + 0.85 * acc
    np.testing.assert_allclose(ranks, want, rtol=1e-6)


def test_mid_job_executor_loss_recovers(cluster, caplog):
    """An executor dies between the map stage and the reduce: the engine's
    own retry recomputes its maps on survivors and the job completes with
    exact results (scala/RdmaShuffleFetcherIterator.scala:376-381 story)."""
    import logging

    caplog.set_level(logging.WARNING, logger="sparkrdma_tpu.engine")
    driver, execs = cluster
    P, maps, rows, key_space = 4, 6, 500, 5000

    def map_fn(ctx, writer, task_id):
        keys, vals = _table(9000 + task_id, rows, key_space)
        writer.write((keys, _u32_payload(vals)))

    killed = {"done": False}

    def reduce_fn(ctx, task_id):
        if task_id == 0 and not killed["done"]:
            killed["done"] = True
            victim = execs[1].native
            mid = victim.executor.manager_id
            victim.executor.stop()
            driver.native.driver.remove_member(mid)
            time.sleep(0.3)
        total = 0
        for keys, payload in ctx.read(0).readBatches():
            total += int(_payload_u32(payload).astype(np.int64).sum())
        return total

    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    engine = DAGEngine(driver, execs)
    got = sum(engine.run(ResultStage(P, reduce_fn, parents=[stage])))
    assert killed["done"], "failure injection never ran"

    want = sum(int(_table(9000 + m, rows, key_space)[1].astype(np.int64).sum())
               for m in range(maps))
    assert got == want
    # the engine's recovery path must actually have fired
    assert any("recovering shuffle" in r.message for r in caplog.records)


def test_engine_emits_trace_spans(tmp_path):
    """Stage/task spans land in the driver's chrome trace."""
    import json

    conf = TpuShuffleConf(connect_timeout_ms=1000, max_connection_attempts=2,
                          trace_file=str(tmp_path / "trace"))
    driver = SparkCompatShuffleManager(conf, isDriver=True)
    execs = [SparkCompatShuffleManager(
        conf, driverAddr=driver.driverAddr, executorId=str(i),
        spill_dir=str(tmp_path / f"e{i}")) for i in range(2)]
    try:
        for ex in execs:
            ex.native.executor.wait_for_members(2)

        def map_fn(ctx, writer, t):
            writer.write((np.arange(10, dtype=np.uint64),
                          np.zeros((10, 4), np.uint8)))

        def red_fn(ctx, t):
            return sum(len(k) for k, _ in ctx.read(0).readBatches())

        stage = MapStage(2, ShuffleDependency(
            2, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
        total = sum(DAGEngine(driver, execs).run(
            ResultStage(2, red_fn, parents=[stage])))
        assert total == 20
    finally:
        for ex in execs:
            ex.stop()
        driver.stop()
    trace = json.loads((tmp_path / "trace.driver.json").read_text())
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"engine.stage", "engine.task"} <= names, names


def test_speculative_execution_beats_straggler(cluster):
    """A straggling task gets a backup on another executor; the backup's
    result completes the stage long before the straggler would have."""
    import threading

    driver, execs = cluster
    P, maps = 4, 3
    calls: dict = {}
    lock = threading.Lock()

    def map_fn(ctx, writer, t):
        writer.write((np.arange(100, dtype=np.uint64) + t,
                      np.zeros((100, 4), np.uint8)))

    def reduce_fn(ctx, t):
        with lock:
            attempt = calls[t] = calls.get(t, 0) + 1
        if t == 2 and attempt == 1:
            time.sleep(2.0)  # the straggler's first attempt
        return sum(len(k) for k, _ in ctx.read(0).readBatches())

    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    engine = DAGEngine(driver, execs, max_parallel_tasks=4,
                       speculation=True)
    t0 = time.monotonic()
    results = engine.run(ResultStage(P, reduce_fn, parents=[stage]))
    wall = time.monotonic() - t0
    assert sum(results) == maps * 100
    assert calls.get(2, 0) >= 2, "no speculative copy launched"
    # the stage must finish before the straggler's 2.0s sleep could have
    # (load-tolerant: anything under the sleep proves the backup won)
    assert wall < 2.0, f"speculation did not beat the straggler ({wall:.2f}s)"


def test_parallel_dispatch_is_default(cluster):
    """Concurrency is the contract (Spark's running-tasks model): the
    default bound is one in-flight task per executor, and a stage's tasks
    really do overlap."""
    import threading

    driver, execs = cluster
    engine = DAGEngine(driver, execs)
    assert engine.max_parallel_tasks == len(execs)

    barrier = threading.Barrier(len(execs), timeout=10)

    def map_fn(ctx, writer, t):
        barrier.wait()  # passes only if all tasks are in flight at once
        writer.write((np.arange(10, dtype=np.uint64),
                      np.zeros((10, 4), np.uint8)))

    def reduce_fn(ctx, t):
        return sum(len(k) for k, _ in ctx.read(0).readBatches())

    stage = MapStage(len(execs), ShuffleDependency(
        2, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    assert sum(engine.run(ResultStage(2, reduce_fn, parents=[stage]))) \
        == len(execs) * 10


def test_abandoned_attempt_exits_cleanly_after_teardown(cluster):
    """An attempt still running when run() tears the job down (speculative
    loser / cancelled sibling) must exit via the torn-down signal, not die
    on a KeyError over popped handles or republish to an unregistered
    shuffle."""
    driver, execs = cluster
    P, maps = 4, 3

    def map_fn(ctx, writer, t):
        writer.write((np.arange(50, dtype=np.uint64) + t,
                      np.zeros((50, 4), np.uint8)))

    def reduce_fn(ctx, t):
        return sum(len(k) for k, _ in ctx.read(0).readBatches())

    stage = MapStage(maps, ShuffleDependency(
        P, PartitionerSpec("modulo"), row_payload_bytes=4), map_fn)
    final = ResultStage(P, reduce_fn, parents=[stage])
    engine = DAGEngine(driver, execs)
    assert sum(engine.run(final)) == maps * 50
    # handles/owners are popped now; a late attempt of either stage kind
    # must return quietly (the engine logs at debug and moves on)
    assert engine._run_task(final, 0) is None
    assert engine._run_task(stage, 0) is None
    # and a late FetchFailed (abandoned attempt mid-fetch at teardown)
    # must surface the torn-down signal, not KeyError or retry burn
    from sparkrdma_tpu.engine import _JobTornDownError
    from sparkrdma_tpu.shuffle.fetcher import FetchFailedError
    with pytest.raises(_JobTornDownError):
        engine._recover_shuffle(FetchFailedError(999, 0, 0, "late fetch"))
